"""Benchmark: orchestrated mnist training throughput vs plain jax-on-TPU.

BASELINE.md metric: "mnist steps/sec/chip submitted via the ClusterSubmitter
-equivalent, target >= 90% of plain jax-on-TPU step throughput"
(BASELINE.json north star). This script measures

  1. plain JAX: the mnist train loop of tony_tpu/examples/mnist_jax.py run
     directly in this process on the local accelerator(s)
  2. orchestrated: the SAME script submitted as a 1-worker job through
     TonyClient -> driver -> executor (the ClusterSubmitter path)

and reports orchestrated steps/sec with vs_baseline = orchestrated / plain.
Orchestration happens off the training path (heartbeats + metrics RPC only),
so the ratio should be ~1.0; it also prints job-launch-to-first-step latency
as a secondary line on stderr.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import json
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent
STEPS = 300
BATCH = 512


def run_plain(tmp: Path) -> dict:
    out = tmp / "plain.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tony_tpu.examples.mnist_jax",
         "--steps", str(STEPS), "--batch-size", str(BATCH),
         "--metrics-out", str(out)],
        cwd=REPO, capture_output=True, text=True, timeout=900,
    )
    if proc.returncode != 0:
        print(proc.stdout, proc.stderr, file=sys.stderr)
        raise RuntimeError("plain jax run failed")
    return json.loads(out.read_text())


def run_orchestrated(tmp: Path) -> tuple[dict, float]:
    sys.path.insert(0, str(REPO))
    from tony_tpu.client import TonyClient
    from tony_tpu.conf import TonyConf

    out = tmp / "orch.json"
    conf = TonyConf({
        "tony.staging.dir": str(tmp / "staging"),
        "tony.history.intermediate": str(tmp / "hist/intermediate"),
        "tony.worker.instances": 1,
        "tony.worker.command": (
            f"{sys.executable} -m tony_tpu.examples.mnist_jax "
            f"--steps {STEPS} --batch-size {BATCH} --metrics-out {out}"
        ),
        "tony.am.monitor-interval-ms": 100,
    })
    client = TonyClient(conf, poll_interval_s=0.1)
    t_submit = time.time()
    client.submit()
    status = client.monitor()
    if status.value != "SUCCEEDED":
        log_dir = Path(client.job_dir)
        for p in sorted(log_dir.rglob("*.std*")) + sorted(log_dir.rglob("*.log")):
            print(f"==== {p} ====\n{p.read_text()[-2000:]}", file=sys.stderr)
        raise RuntimeError(f"orchestrated job finished {status}")
    metrics = json.loads(out.read_text())
    launch_latency = metrics["time_to_first_step_s"] + 0.0
    # end-to-end: submit -> first step = executor spawn + script start + compile
    e2e_first_step = launch_latency  # in-process portion; add client-side below
    return metrics, time.time() - t_submit


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="tony-bench-") as td:
        tmp = Path(td)
        plain = run_plain(tmp)
        orch, wall = run_orchestrated(tmp)

    plain_sps = plain["steps_per_sec"]
    orch_sps = orch["steps_per_sec"]
    print(
        f"# plain: {plain_sps:.1f} steps/s | orchestrated: {orch_sps:.1f} steps/s | "
        f"launch-to-first-step: {orch['time_to_first_step_s']:.2f}s | "
        f"job wall: {wall:.1f}s | devices: {orch['num_devices']} | "
        f"acc: {orch['accuracy']:.3f}",
        file=sys.stderr,
    )
    print(json.dumps({
        "metric": "mnist_steps_per_sec_per_chip_orchestrated",
        "value": round(orch_sps, 2),
        "unit": "steps/s",
        "vs_baseline": round(orch_sps / plain_sps, 4),
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
