"""Benchmark: orchestrated mnist training throughput vs plain jax-on-TPU.

BASELINE.md metric: "mnist steps/sec/chip submitted via the ClusterSubmitter
-equivalent, target >= 90% of plain jax-on-TPU step throughput"
(BASELINE.json north star). This script measures

  1. plain JAX: the mnist train loop of tony_tpu/examples/mnist_jax.py run
     directly as a subprocess on the local accelerator(s)
  2. orchestrated: the SAME script submitted as a 1-worker job through
     TonyClient -> driver -> executor (the ClusterSubmitter path)

and reports orchestrated steps/sec with vs_baseline = orchestrated / plain.
Orchestration happens off the training path (heartbeats + metrics RPC only),
so the ratio should be ~1.0.

Noise control: the accelerator may be reached over a network tunnel whose
latency/load varies run to run, so (a) the workload itself times scan-batched
on-device steps and reports a median-window rate (see mnist_jax.py), and
(b) this script interleaves plain/orchestrated runs (A/B pairs) and scores
the MEDIAN of the paired ratios: within a pair the two runs are adjacent in
time, so the ratio cancels tunnel/device drift, and the median keeps one
stalled (or lucky) pair in either direction from moving the gate. Every
arm's number and every pair ratio are persisted in the JSON.

BASELINE.md metric 2 (launch-to-first-step) is reported as a breakdown:
orchestration (submit -> user-process exec) vs in-process phases (import,
backend/tunnel init + data staging, first-block compile), once cold and
once warm — a persistent XLA compilation cache shared by both arms makes
relaunches skip most of the compile phase, which is the path users iterate
on. r02's undiagnosed 28->47s drift was entirely the in-process share
(backend init ~25s + 1000-step-scan compile ~20-29s, both tunnel-sensitive
and variable); orchestration's share is ~1s.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...breakdown}
"""

from __future__ import annotations

import json
import statistics
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent
STEPS = 6000
STEPS_PER_CALL = 1000
BATCH = 512
# 5 pairs: with 3, one noisy pair put the median at the mercy of a single
# run (r03 spread was 29%); two more pairs cost ~4 min and make the median
# robust to two bad pairs
PAIRS = 5


def _workload_args(out: Path, cache: Path) -> list[str]:
    return [
        "--steps", str(STEPS), "--steps-per-call", str(STEPS_PER_CALL),
        "--batch-size", str(BATCH), "--metrics-out", str(out),
        # persistent XLA cache shared by BOTH arms: pair 0 compiles cold,
        # later pairs measure the warm relaunch path users actually iterate on
        "--compile-cache", str(cache),
    ]


def run_plain(tmp: Path, rep: int) -> dict:
    out = tmp / f"plain{rep}.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tony_tpu.examples.mnist_jax",
         *_workload_args(out, tmp / "xla-cache")],
        cwd=REPO, capture_output=True, text=True, timeout=900,
    )
    if proc.returncode != 0:
        print(proc.stdout, proc.stderr, file=sys.stderr)
        raise RuntimeError("plain jax run failed")
    return json.loads(out.read_text())


def run_orchestrated(tmp: Path, rep: int) -> tuple[dict, float, float]:
    sys.path.insert(0, str(REPO))
    from tony_tpu.client import TonyClient
    from tony_tpu.conf import TonyConf

    out = tmp / f"orch{rep}.json"
    conf = TonyConf({
        "tony.staging.dir": str(tmp / f"staging{rep}"),
        "tony.history.intermediate": str(tmp / "hist/intermediate"),
        "tony.worker.instances": 1,
        "tony.worker.command": (
            f"{sys.executable} -m tony_tpu.examples.mnist_jax "
            + " ".join(_workload_args(out, tmp / "xla-cache"))
        ),
        "tony.am.monitor-interval-ms": 100,
    })
    client = TonyClient(conf, poll_interval_s=0.1)
    t_submit = time.time()
    client.submit()
    status = client.monitor()
    if status.value != "SUCCEEDED":
        log_dir = Path(client.job_dir)
        for p in sorted(log_dir.rglob("*.std*")) + sorted(log_dir.rglob("*.log")):
            print(f"==== {p} ====\n{p.read_text()[-2000:]}", file=sys.stderr)
        raise RuntimeError(f"orchestrated job finished {status}")
    return json.loads(out.read_text()), time.time() - t_submit, t_submit


def _launch_breakdown(m: dict, t_submit: float) -> dict:
    """Split launch-to-first-step into the orchestration share (submit ->
    user process exec, the part BASELINE.md metric 2 is really about) and
    the in-process phases the workload reports."""
    return {
        "orchestration_submit_to_exec_s": round(m["t_start_epoch"] - t_submit, 2),
        "import_s": round(m["import_s"], 2),
        "backend_and_data_s": round(m["backend_and_data_s"], 2),
        "compile_first_block_s": round(m["compile_first_block_s"], 2),
        "total_submit_to_first_step_s": round(
            m["t_start_epoch"] - t_submit + m["time_to_first_step_s"], 2
        ),
    }


def main() -> int:
    plain_runs, orch_runs, submits = [], [], []
    wall = 0.0
    with tempfile.TemporaryDirectory(prefix="tony-bench-") as td:
        tmp = Path(td)
        for rep in range(PAIRS):
            # orchestrated first so rep 0's launch breakdown is genuinely
            # COLD — a preceding plain run would warm the shared compile
            # cache and fake the number this breakdown exists to diagnose.
            # (Throughput is unaffected: compile is excluded from it.)
            orch, wall, t_submit = run_orchestrated(tmp, rep)
            orch_runs.append(orch)
            submits.append(t_submit)
            plain_runs.append(run_plain(tmp, rep))

    plain_all = [round(r["steps_per_sec"], 2) for r in plain_runs]
    orch_all = [round(r["steps_per_sec"], 2) for r in orch_runs]
    plain_sps = max(plain_all)
    orch_sps = max(orch_all)
    # score the MEDIAN of paired ratios: each pair's runs are adjacent in
    # time, so the ratio cancels tunnel/device drift that max(orch)/
    # max(plain) does not — one outlier run in a single arm (observed: a
    # plain arm 17% above its own siblings) would otherwise swing the gate
    # by ~10 points; the median is robust to one bad pair in EITHER
    # direction (max would inherit the mirror-image bias)
    paired = [
        round(o["steps_per_sec"] / p["steps_per_sec"], 4)
        for o, p in zip(orch_runs, plain_runs)
    ]
    vs_baseline = round(statistics.median(paired), 4)
    best_orch = max(orch_runs, key=lambda r: r["steps_per_sec"])
    launch_cold = _launch_breakdown(orch_runs[0], submits[0])
    warm_i = min(range(1, PAIRS),
                 key=lambda i: orch_runs[i]["time_to_first_step_s"],
                 default=0)
    launch_warm = _launch_breakdown(orch_runs[warm_i], submits[warm_i])
    print(
        f"# plain: {plain_sps:.1f} steps/s {plain_all} | "
        f"orchestrated: {orch_sps:.1f} steps/s {orch_all} | "
        f"launch cold: {launch_cold['total_submit_to_first_step_s']:.1f}s "
        f"(orchestration {launch_cold['orchestration_submit_to_exec_s']:.1f}s) | "
        f"warm: {launch_warm['total_submit_to_first_step_s']:.1f}s | "
        f"last job wall: {wall:.1f}s | devices: {best_orch['num_devices']} | "
        f"acc: {best_orch['accuracy']:.3f}",
        file=sys.stderr,
    )
    print(json.dumps({
        "metric": "mnist_steps_per_sec_per_chip_orchestrated",
        "value": round(orch_sps, 2),
        "unit": "steps/s",
        "vs_baseline": vs_baseline,
        "vs_baseline_paired_all": paired,
        "vs_baseline_max_over_max": round(orch_sps / plain_sps, 4),
        "plain_steps_per_sec_all": plain_all,
        "orchestrated_steps_per_sec_all": orch_all,
        "launch_cold": launch_cold,
        "launch_warm": launch_warm,
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
