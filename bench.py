"""Benchmark: orchestrated mnist training throughput vs plain jax-on-TPU.

BASELINE.md metric: "mnist steps/sec/chip submitted via the ClusterSubmitter
-equivalent, target >= 90% of plain jax-on-TPU step throughput"
(BASELINE.json north star). This script measures

  1. plain JAX: the mnist train loop of tony_tpu/examples/mnist_jax.py run
     directly as a subprocess on the local accelerator(s)
  2. orchestrated: the SAME script submitted as a 1-worker job through
     TonyClient -> driver -> executor (the ClusterSubmitter path)

and reports orchestrated steps/sec with vs_baseline = orchestrated / plain.
Orchestration happens off the training path (heartbeats + metrics RPC only),
so the ratio should be ~1.0.

Noise control (the round-4 regression forensics, docs/performance.md):
  - The workload reports a TWO-POINT device rate: scan blocks of N and N/2
    steps, interleaved; the step delta over the median-time delta cancels the
    fixed per-call cost. On the tunneled chip that fixed cost (~110ms RTT +
    dispatch) was ~90% of a 1000-step call's wall time, so the old wall-rate
    ratio compared RTT jitter, not training speed — the whole r04 "5pp
    regression" lived in that jitter. The wall-rate ratio is still recorded.
  - A/B pairs run adjacent in time and the MEDIAN of paired ratios is
    scored; one stalled (or lucky) pair cannot move the gate.
  - Pair ORDER alternates (pair 0 orchestrated-first for the cold-launch
    breakdown, then flipping): any systematic within-pair drift — link
    warming, page cache — hits each arm first equally often instead of
    always favoring the second runner.
  - Host telemetry per arm: loadavg + /proc/stat busy fraction, persisted so
    a deficit can be attributed to host contention instead of guessed at.

BASELINE.md metric 2 (launch-to-first-step) is reported as a breakdown:
orchestration (submit -> user-process exec) vs in-process phases (import,
backend/tunnel init + data staging, first-block compile), once cold and
once warm — a persistent XLA compilation cache shared by both arms makes
relaunches skip most of the compile phase, which is the path users iterate
on. r02's undiagnosed 28->47s drift was entirely the in-process share
(backend init ~25s + 1000-step-scan compile ~20-29s, both tunnel-sensitive
and variable); orchestration's share is ~1s.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...breakdown}

`python bench.py --serving` instead benchmarks the continuous-batching
SlotServer (models/serving.py): tokens/sec with batched multi-slot
admission vs the serial per-slot path (same completions, fewer host
dispatches per admission burst — both counts reported), and, when >= 2
devices are visible, the mesh-sharded (tensor-parallel) server with a
parity check against the single-device completions. On CPU run it under
`XLA_FLAGS=--xla_force_host_platform_device_count=4`. Results land in
PERF.json under `continuous_batching_tp`, and the timed pass's
p50/p90/p99 TTFT/TPOT/queue-wait/e2e (from the observability
histograms, docs/observability.md) under `serving_latency` — the
latency baseline future perf PRs regress against — plus a `device_time`
section (dispatch→ready quantiles per program kind from the
DispatchTracker, measured device lag behind host observation, and the
XLA compile count/time for the whole bench process). An `open_loop`
arm rides along: the same workload offered as seeded Poisson arrivals
at the measured burst capacity (byte-identity vs the burst asserted;
the latency block there is the open-loop shape, not the burst's
deep-backlog artifact).

`python bench.py --serving --shared-prefix` benchmarks the chunk-aligned
prefix KV cache on the workload it exists for: N requests sharing one
long template + short unique suffixes (the system-prompt/few-shot shape).
A cold server (prefix cache off) and a warm one (`prefix_cache_blocks`)
serve the identical submission order; the bench asserts byte-identical
completions and reports the reused-token fraction, prefill/copy/insert
dispatch counts, and tokens/sec for both paths. Results land in PERF.json
under `prefix_cache`.

`python bench.py --serving --fleet` benchmarks driver-orchestrated
fleet serving (docs/serving.md "Fleet serving"): 2-3 real serve
processes (one pinned per core, prefix caches on) behind the
prefix-aware FleetRouter — fleet-vs-single CAPACITY (closed-loop,
concurrency-matched, best-of-trials; asserted > 1.5x), open-loop
CAPACITY arms (Poisson arrivals at each arm's own measured capacity,
best-of-trials; asserted > 1.3x), Poisson open-loop collapse passes
at 1.2x measured fleet capacity, and prefix-affinity
vs random routing on the fleet-wide trie reuse fraction (asserted
affinity > random) and merged p99 TTFT. Results land in PERF.json
under `serving_fleet`.

`python bench.py --serving --paged-kv` gates the paged KV allocator
(docs/serving.md "Paged KV & admission tiers") on TINY shapes: (1)
byte-identical greedy completions vs the ring engine with peak
concurrency strictly above the ring's `slots` bound at EQUAL device
memory (same pool bytes, more slots, admission gated on free blocks);
(2) an admission storm of long prompts against in-flight decodes,
chaos-paced (20ms/turn) so the comparison is deterministic — TPOT p99
with chunked-prefill interleaving ON must stay ≤ 1.2x the quiescent
baseline while the interleave-OFF arm's single-turn stall is reported
(and must exceed the interleaved arm's); (3) admission tiers under
queue pressure — queued batch requests shed (finish_reason "shed")
before any interactive arrival is refused, zero failed requests, and
the 429s carry engine-derived Retry-After. Results land in PERF.json
under `paged_kv`.

`python bench.py --serving --disagg` gates disaggregated prefill/
decode serving (docs/serving.md "Disaggregated serving"): (1) a mixed
workload (long-prompt prefill storm dropped on in-flight interactive
decodes) on 1 prefill specialist + 1 decode replica vs 2 role="both"
replicas at EQUAL hardware — the decode tier's TPOT p99 must be ≥
1.2x better because prefill chunks never ride its scheduling turns —
with byte-identity vs solo greedy and zero failed requests enforced;
(2) a fleet leg with a mid-transfer SIGKILL of the prefill specialist:
completed handoffs before the kill, journal-replay fallback after it
(the router re-prefills from the prompt on the decode replica), zero
failed requests, byte-identical. Results land in PERF.json under
`disaggregated_serving`.

`python bench.py --serving --streaming` gates the streaming subsystem
(docs/serving.md "Streaming & OpenAI compatibility"): an open-loop
Poisson arrival process streamed per-token through the FleetRouter
against 2 TINY serve processes with a mid-stream replica SIGKILL —
ENFORCES zero failed requests and per-request byte-identity of the
concatenated client-side stream vs non-streamed greedy (stream
failovers included, resume prefix harvested from the stream), and
reports client-observed inter-token-latency quantiles from per-token
arrival timestamps. Results land in PERF.json under
`streaming_serving`.

`python bench.py --launch-path` measures the warm-executor-pool launch
story (docs/performance.md "Launch path"): the same 1-worker mnist job
submitted three ways in one run — cold (first-ever: cold XLA disk
cache, cold child), warm (resubmit, pool off), adopted (resubmit,
`tony.warmpool.size=1`: the task adopts a pre-warmed standby that
prepaid jax import + backend init + the warmup hook's staging and
train-block compile). Asserts the adopted arm adopted, the others did
not, and training results are identical across arms; results land in
PERF.json under `launch_path` with value = cold/adopted speedup (the
>=3x acceptance gate).

`python bench.py --elastic` exercises the TRAINING failure model
(docs/training-robustness.md): a real 2-worker local job running the
elastic_train drill under the driver's seeded chaos harness
(TONY_TEST_DRIVER_{KILL_RATE,PREEMPT_AT_STEP,CHAOS_SEED}) — random
container SIGKILLs plus one relayed preemption drain, with elasticity
on. The bench asserts ZERO failed jobs, ≤ save_interval steps recomputed
per recovery with no silent step skips (from the per-step StepTimer
JSONLs), and reports each loss→running recovery wall time from
tasks.trace.jsonl. Results land in PERF.json under
`training_robustness`.

`python bench.py --serving --overload --chaos` exercises the failure
model (docs/serving.md): a burst far exceeding slots + max_queue hits a
ServeApp whose SlotServer runs with seeded fault injection
(TONY_TEST_SERVING_DISPATCH_FAIL_RATE, constants.py). The bench asserts
the invariants the robustness tests pin — every submitted request
terminates with a completion, a shed (429-equivalent QueueFullError), or
an explicit error; zero hung waiters; the loop recovers within its
restart budget — and reports goodput, shed/cancelled/expired counts,
recovery counters, and the p50 latency of admitted requests. Results
land in PERF.json under `serving_robustness` (`--overload` alone runs
the same burst with injection off).

`python bench.py --serving --replay` gates the request-durability layer
(docs/serving.md "Request durability & replay"): a deterministic
mid-decode loop crash (TONY_TEST_SERVING_CRASH_AT_BLOCKS) and a replica
SIGKILL mid-burst behind the FleetRouter must both finish with ZERO
failed requests and byte-identical completions vs an uninterrupted run
(replay recompute bounded by one prompt+emitted-prefix re-prefill per
replay; the journal-off path must preserve today's fail-fast
behavior), and the SIGKILLed replica restarted against the same
--trace-dir must recover its file journal and finish the orphaned
requests. Results land in PERF.json under `serving_replay`.

`python bench.py --serving --router-ha` gates the shared-nothing router
tier (docs/serving.md "Router tier HA"): a real driver launches 2 serve
replicas behind 2 `router`-framework front doors, SIGKILLs door 0 on
its Nth request mid-burst, and ENFORCES zero failed requests (clients
re-POST the same request_id on the survivor), byte-identical buffered
AND streamed responses for every rerouted request, live cross-door
affinity agreement after the driver relaunches the dead door (restart
budget: router:0 restarts == 1, no collateral), reporting the p50
latency cost of losing a front door. Results land in PERF.json under
`router_ha`.

`python bench.py --serving --tracing` gates END-TO-END DISTRIBUTED
TRACING (docs/observability.md "Distributed tracing"): a disaggregated
fleet (1 prefill + 1 decode replica, --paged-kv) behind 2 router front
doors, every tier writing --trace-dir JSONL; door 0 is SIGKILLed upon
receiving its Nth front-door request mid-burst and the clients re-POST
the same request_id at door 1. The bench merges every tier's trace
file with TraceCollector and ENFORCES: every completed request yields
exactly ONE merged trace (the deterministic for_request_id trace_id
each response header echoed), ZERO orphan spans, >= 1 failover trace
carrying spans from BOTH router nonces under one trace_id (the dead
door contributes its unsealed write-ahead record), >= 1 trace whose
serve spans come from both the prefill and the decode replica (the
disagg handoff is one trace), and the span-union coverage accounts for
each client-observed e2e within a bounded gap. Results land in
PERF.json under `distributed_tracing`.

`python bench.py --serving --spec` gates speculative decoding inside
continuous batching (docs/serving.md "Speculative decoding &
multi-model serving"): a target and a 12x-smaller draft trained on the
same Markov corpus (real acceptance, the bench_transformer speculative
methodology) serve the identical burst spec-off and spec-on — the
bench asserts byte-identical completions and >= 1.3x tokens/s, reports
the measured acceptance + autotuned gamma + the acceptance-0 floor
(random draft), and a multi-model arm rolls a two-model serve process
mid-burst (SIGTERM drain -> relaunch with one checkpoint swapped under
the same name + journal dir) asserting zero failed requests. Results
land in PERF.json under `speculative_serving`.

`python bench.py --driver-failover` gates the CONTROL-PLANE recovery
layer (docs/training-robustness.md "Control-plane recovery") with two
arms. Training: a real 2-worker elastic_train job whose driver SIGKILLs
itself mid-job (TONY_TEST_DRIVER_SIGKILL_AT_STEP); the bench relaunches
`tony-tpu driver --recover`, which replays driver.journal.jsonl and
re-adopts both live workers — the job must SUCCEED with ZERO
outage-attributable worker restarts and ZERO recomputed steps (the
children never stopped stepping), and each worker's recovery→first
re-attached heartbeat is read off its `readopted` trace and bounded.
Fleet: a driver-orchestrated 2-replica serving fleet behind the
FleetRouter answers a paced burst while the driver is SIGKILLed and
recovered mid-burst — the router must serve the whole burst from its
last-known fleet (router_discovery_stale observed high, then clear)
with ZERO failed requests and zero replica restarts. Results land in
PERF.json under `control_plane_robustness`.

`python bench.py --autoscale` gates the CLOSED LOOP (docs/
autoscaling.md): one driver schedules a serving role (2 replica slots,
1 parked) and a batch elastic_train role over a 3-slot shared pool. A
seeded Poisson traffic ramp through the FleetRouter floods the single
replica past the queue SLO; the driver-resident autoscaler preempt-
drains the batch worker (donation, checkpoint at the step boundary),
scales the fleet up on the freed slot, and the measured client TTFT p99
recovers — no manual resize. The driver is SIGKILLed once the second
replica is live and relaunched with `--recover`: the journaled scale
ledger resumes mid-cooldown, so the final journal carries EXACTLY one
"up" and one "down" decision (no duplicates, no flapping). On
ramp-down the fleet scales back, the batch tier RECLAIMS the donated
slot (relaunched with the checkpoint prestaged), and the training job
runs to SUCCEEDED with ≤ save_interval recomputed steps per recovery
and ZERO failed serving requests. Results land in PERF.json under
`autoscaling`.

`python bench.py --serving --slo` gates the FLEET METRICS PIPELINE +
SLO ALERTING (docs/observability.md "Metrics pipeline & SLO
alerting"): one driver runs 2 replicas behind a `router`-framework
front door with a declared availability SLO; the driver-resident
metrics hub scrapes every tier. A healthy open-loop warm-up must fire
ZERO alerts; a replica SIGKILL under a Poisson overload burst sheds on
the survivor and the fast burn-rate pair must fire inside its window
(journaled); the driver is then SIGKILLed MID-INCIDENT and relaunched
with `--recover` — the replayed metrics.tsdb.jsonl + journal-seeded
alert state must RESUME the alert with exactly one firing transition
in the final journal (no duplicate); the alert clears after the
replica relaunch, and the engine's budget accounting must equal
(failed+shed)/total computed from the router's own /metrics counters
EXACTLY. Results land in PERF.json under `slo_alerting`.
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent
STEPS = 120000          # total long-block steps timed (short blocks add half)
STEPS_PER_CALL = 12000  # long block; short is half -> diff ~0.125s of device
                        # time per round vs per-call RTT jitter of a few ms;
                        # 10 rounds tighten each median to ~1-2ms (the first
                        # r05 trial at 5 rounds x 6k steps still showed +-7%
                        # pair noise, all of it from the PLAIN arm's medians)
BATCH = 512
# 5 pairs: with 3, one noisy pair put the median at the mercy of a single
# run (r03 spread was 29%); two more pairs cost ~4 min and make the median
# robust to two bad pairs
PAIRS = 5


def _workload_args(out: Path, cache: Path) -> list[str]:
    return [
        "--steps", str(STEPS), "--steps-per-call", str(STEPS_PER_CALL),
        "--batch-size", str(BATCH), "--metrics-out", str(out),
        # persistent XLA cache shared by BOTH arms: pair 0 compiles cold,
        # later pairs measure the warm relaunch path users actually iterate on
        "--compile-cache", str(cache),
    ]


def _cpu_busy() -> tuple[float, float]:
    """(busy_jiffies, total_jiffies) from /proc/stat line 1."""
    with open("/proc/stat") as f:
        parts = f.readline().split()[1:]
    nums = [float(p) for p in parts]
    idle = nums[3] + (nums[4] if len(nums) > 4 else 0.0)  # idle + iowait
    return sum(nums) - idle, sum(nums)


class _HostLoad:
    """Samples host contention around one arm's run."""

    def __enter__(self):
        self._busy0, self._total0 = _cpu_busy()
        self.load_start = os.getloadavg()[0]
        return self

    def __exit__(self, *exc):
        busy1, total1 = _cpu_busy()
        self.load_end = os.getloadavg()[0]
        dt = total1 - self._total0
        self.cpu_busy_frac = (busy1 - self._busy0) / dt if dt > 0 else 0.0

    def as_dict(self) -> dict:
        return {
            "loadavg_start": round(self.load_start, 2),
            "loadavg_end": round(self.load_end, 2),
            "cpu_busy_frac": round(self.cpu_busy_frac, 4),
        }


def run_plain(tmp: Path, rep: int) -> tuple[dict, dict]:
    out = tmp / f"plain{rep}.json"
    with _HostLoad() as hl:
        proc = subprocess.run(
            [sys.executable, "-m", "tony_tpu.examples.mnist_jax",
             *_workload_args(out, tmp / "xla-cache")],
            cwd=REPO, capture_output=True, text=True, timeout=900,
        )
    if proc.returncode != 0:
        print(proc.stdout, proc.stderr, file=sys.stderr)
        raise RuntimeError("plain jax run failed")
    return json.loads(out.read_text()), hl.as_dict()


def run_orchestrated(tmp: Path, rep: int) -> tuple[dict, float, float, dict]:
    sys.path.insert(0, str(REPO))
    from tony_tpu.client import TonyClient
    from tony_tpu.conf import TonyConf

    out = tmp / f"orch{rep}.json"
    conf = TonyConf({
        "tony.staging.dir": str(tmp / f"staging{rep}"),
        "tony.history.intermediate": str(tmp / "hist/intermediate"),
        "tony.worker.instances": 1,
        "tony.worker.command": (
            f"{sys.executable} -m tony_tpu.examples.mnist_jax "
            + " ".join(_workload_args(out, tmp / "xla-cache"))
        ),
        "tony.am.monitor-interval-ms": 100,
    })
    client = TonyClient(conf, poll_interval_s=0.1)
    with _HostLoad() as hl:
        t_submit = time.time()
        client.submit()
        status = client.monitor()
    if status.value != "SUCCEEDED":
        log_dir = Path(client.job_dir)
        for p in sorted(log_dir.rglob("*.std*")) + sorted(log_dir.rglob("*.log")):
            print(f"==== {p} ====\n{p.read_text()[-2000:]}", file=sys.stderr)
        raise RuntimeError(f"orchestrated job finished {status}")
    return json.loads(out.read_text()), time.time() - t_submit, t_submit, hl.as_dict()


def _launch_breakdown(m: dict, t_submit: float) -> dict:
    """Split launch-to-first-step into the orchestration share (submit ->
    user process exec, the part BASELINE.md metric 2 is really about) and
    the in-process phases the workload reports."""
    return {
        "orchestration_submit_to_exec_s": round(m["t_start_epoch"] - t_submit, 2),
        "import_s": round(m["import_s"], 2),
        "backend_and_data_s": round(m["backend_and_data_s"], 2),
        "compile_first_block_s": round(m["compile_first_block_s"], 2),
        "total_submit_to_first_step_s": round(
            m["t_start_epoch"] - t_submit + m["time_to_first_step_s"], 2
        ),
    }


def run_serving_bench() -> int:
    """Continuous-batching serving benchmark (in-process, one JSON line).

    One warm-up pass compiles every program variant; the timed pass then
    measures pure serving throughput. The admission-burst comparison is
    the tentpole number: all requests submitted up front, so the first
    _admit() sees a full burst of free slots — the batched path collapses
    its sum-of-chunks dispatches into max-chunks rounds."""
    import time as _time

    sys.path.insert(0, str(REPO))
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tony_tpu.models import transformer
    from tony_tpu.models.serving import Request, SlotServer
    from tony_tpu.observability import install_compile_telemetry

    # compile-time attribution rides the same run: installed BEFORE any
    # program compiles so the warm-up pass's compiles are counted
    compile_telemetry = install_compile_telemetry()

    cfg = transformer.TransformerConfig(
        vocab_size=2048, d_model=256, n_layers=4, n_heads=8, n_kv_heads=4,
        d_ff=1024, max_seq_len=512,
        dtype=jnp.bfloat16 if jax.default_backend() == "tpu"
        else jnp.float32,
    )
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    slots, max_len = 8, 512
    prompt_lens = [16, 48, 96, 160]
    budgets = [32, 96, 48, 64, 16, 80, 56, 40]
    n_requests = 24
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(0, cfg.vocab_size, size=prompt_lens[i % len(prompt_lens)],
                     dtype=np.int32)
        for i in range(n_requests)
    ]

    def serve(server_params, *, batched, mesh=None):
        srv = SlotServer(
            server_params, cfg, slots=slots, max_len=max_len,
            block_size=16, prefill_chunk=64, batched_admission=batched,
            mesh=mesh)
        reqs = [Request(prompt=p, max_new_tokens=budgets[i % len(budgets)])
                for i, p in enumerate(prompts)]
        for r in reqs:
            srv.submit(r)
        t0 = _time.time()
        done = srv.run_until_drained()
        wall = _time.time() - t0
        # key by submission index: Request.id is a process-global counter,
        # so ids differ between server instances serving the same workload
        toks = {i: done[r.id].tokens for i, r in enumerate(reqs)}
        n_tokens = sum(len(t) for t in toks.values())
        srv.dispatch_tracker.drain(timeout=10.0)    # reaper catches up
        out = {
            "wall_s": round(wall, 3),
            "tokens_per_sec": round(n_tokens / wall, 1),
            "useful_tokens": n_tokens,
            "admission_dispatches": srv.admission_dispatches,
            "latency": srv.telemetry.snapshot(),
            "device": srv.dispatch_tracker.snapshot(),
        }
        srv.shutdown()      # bench builds many servers: no thread pile-up
        return out, toks

    serve(params, batched=True)                       # compile warm-up
    # warmup line: compiles past here are RECOMPILES — the timed pass
    # replays warm shapes, so a healthy run reads ~0 post-warm
    compile_telemetry.mark_warm()
    batched, toks_b = serve(params, batched=True)
    # snapshot BEFORE the per-slot/TP passes, which legitimately compile
    # new program shapes (serial admission, sharded programs) and would
    # drown the timed pass's recompile signal
    compile_snap = compile_telemetry.snapshot()
    serve(params, batched=False)                      # warm per-slot too
    perslot, toks_p = serve(params, batched=False)
    assert toks_b == toks_p, "admission policy changed completions"

    # open-loop Poisson arrivals (ROADMAP leftover, ISSUE 16): the same
    # workload offered the way real traffic arrives — seeded
    # interarrivals at the measured burst capacity — instead of all up
    # front. Capacity is whatever the engine sustains under that
    # arrival process; byte-identity is asserted (arrival timing is
    # scheduling, never numerics), and the latency shape is the
    # open-loop one rather than the burst's deep-backlog artifact.
    def serve_open_loop(offered_tok_s):
        srv = SlotServer(params, cfg, slots=slots, max_len=max_len,
                         block_size=16, prefill_chunk=64,
                         batched_admission=True)
        mean_new = sum(budgets) / len(budgets)
        interarrival = mean_new / offered_tok_s
        sched = np.cumsum(np.random.default_rng(16).exponential(
            scale=interarrival, size=n_requests))
        reqs = [Request(prompt=p, max_new_tokens=budgets[i % len(budgets)])
                for i, p in enumerate(prompts)]
        done: dict = {}
        nxt = 0
        t0 = _time.time()
        while nxt < len(reqs) or not srv.idle:
            now = _time.time() - t0
            while nxt < len(reqs) and sched[nxt] <= now:
                srv.submit(reqs[nxt])
                nxt += 1
            if srv.idle and nxt < len(reqs):
                _time.sleep(min(0.002, max(0.0, sched[nxt] - now)))
                continue
            srv.step()
            # host-observe per turn (the ServeApp journal cadence):
            # predictive processing is lazy, and without this the
            # first_token/finished marks collapse into end-of-run
            # bursts and the latency block below is fiction
            srv.checkpoint_progress()
            if srv._done:
                done.update(srv.drain_completed())
        done.update(srv.drain_completed())
        wall = _time.time() - t0
        toks = {i: done[r.id].tokens for i, r in enumerate(reqs)}
        n_tokens = sum(len(t) for t in toks.values())
        lat = srv.telemetry.snapshot()
        srv.dispatch_tracker.drain(timeout=10.0)
        out = {
            "offered_tokens_per_sec": round(offered_tok_s, 1),
            "poisson_interarrival_s": round(interarrival, 4),
            "wall_s": round(wall, 3),
            "tokens_per_sec": round(n_tokens / wall, 1),
            "useful_tokens": n_tokens,
            "latency": {k: v for k, v in lat.items()
                        if k in ("ttft_s", "tpot_s", "queue_wait_s",
                                 "e2e_s")},
        }
        srv.shutdown()
        return out, toks
    serve_open_loop(batched["tokens_per_sec"])        # warm the pacer
    open_loop, toks_ol = serve_open_loop(batched["tokens_per_sec"])
    assert toks_ol == toks_b, "arrival process changed completions"

    # latency baseline (ISSUE 4): p50/p90/p99 TTFT / TPOT / queue wait /
    # e2e of the timed batched pass, from the observability histograms —
    # the PERF.json `serving_latency` section future perf PRs regress
    # against. Host-monotonic spans; the whole burst is submitted up
    # front, so queue waits here measure the saturated-backlog shape.
    latency_full = batched.pop("latency")
    serving_latency = {
        k: v for k, v in latency_full.items()
        if k in ("ttft_s", "tpot_s", "queue_wait_s", "e2e_s")
    }
    # device-time attribution (ISSUE 6): dispatch→ready quantiles per
    # program kind, the measured device lag behind host observation, and
    # the XLA compile bill of warm-up + timed pass (compile_snap was
    # taken before the per-slot/TP passes) — the PERF.json `device_time`
    # section future PRs track the trajectory against. The device lag is
    # the saturated-backlog shape, same caveat as serving_latency: the
    # burst is submitted up front and blocks go device-ready well before
    # the host replays them.
    device = batched.pop("device")
    device_lag = latency_full.get("device_lag_s", {})
    device_time = {
        "dispatch_ready": device["dispatch_ready"],
        "dispatches_tracked": device["tracked"],
        "dispatch_track_dropped": device["dropped"],
        "mean_device_lag_s": device_lag.get("mean_s", 0.0),
        "p99_device_lag_s": device_lag.get("p99_s", 0.0),
        "compile": compile_snap,
    }
    perslot.pop("latency", None)
    perslot.pop("device", None)
    out = {
        "metric": "continuous_batching_serving_tokens_per_sec",
        "value": batched["tokens_per_sec"],
        "unit": "tokens/s",
        "slots": slots,
        "n_requests": n_requests,
        "prompt_lens_cycle": prompt_lens,
        "budgets_cycle": budgets,
        "serving_latency": serving_latency,
        "device_time": device_time,
        "batched_admission": batched,
        "per_slot_admission": perslot,
        "open_loop": {**open_loop,
                      "byte_identical_vs_burst": toks_ol == toks_b},
        "admission_dispatch_ratio": round(
            perslot["admission_dispatches"]
            / max(1, batched["admission_dispatches"]), 2),
        "num_devices": jax.device_count(),
    }
    if jax.device_count() >= 2:
        from tony_tpu.models.generate import prepare_decode
        from tony_tpu.parallel import MeshSpec, build_mesh

        tensor = 2 if cfg.n_kv_heads % 2 == 0 else 1
        data = 2 if jax.device_count() >= 4 else 1
        mesh = build_mesh(MeshSpec(data=data, fsdp=1, tensor=tensor),
                          devices=jax.devices()[:data * tensor])
        prep = prepare_decode(params, cfg, mesh=mesh)
        serve(prep, batched=True, mesh=mesh)          # warm-up
        tp, toks_tp = serve(prep, batched=True, mesh=mesh)
        tp.pop("latency", None)
        tp.pop("device", None)
        out["tp"] = {**tp, "mesh": dict(mesh.shape),
                     "parity_vs_single_device": toks_tp == toks_b}
    print(json.dumps(out))
    return 0


def run_paged_kv_bench() -> int:
    """Paged-KV allocator benchmark (one JSON line -> PERF.json
    `paged_kv`; see the module docstring). TINY shapes throughout —
    every gate here is an INVARIANT (byte-identity, concurrency bound,
    shed order, bounded TPOT ratio), not a host-speed number, and the
    storm arm is chaos-paced so the per-turn sleep dominates compute
    and the ratio is deterministic on any host."""
    import time as _time

    sys.path.insert(0, str(REPO))
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tony_tpu.models import transformer
    from tony_tpu.models.serving import QueueFullError, Request, SlotServer

    cfg = transformer.TransformerConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32)
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    B, max_len, chunk = 8, 64, 8
    ring_slots = 4
    pool_blocks = ring_slots * max_len // B     # EQUAL device memory
    rng = np.random.default_rng(16)

    # ---- arm 1: byte-identity + concurrency above the ring bound ----
    # Requests sized so the pool holds ~10 concurrent block tables
    # (mean ~3 blocks each) where the ring engine pins concurrency at
    # ring_slots=4 regardless of actual KV bytes.
    plens, budgets_c = [6, 10, 14, 18], [6, 12, 8, 10]
    n_requests = 16
    prompts = [rng.integers(0, cfg.vocab_size, size=plens[i % 4],
                            dtype=np.int32) for i in range(n_requests)]

    def drive(srv):
        """run_until_drained, sampling peak concurrent active slots."""
        reqs = [Request(prompt=p, max_new_tokens=budgets_c[i % 4])
                for i, p in enumerate(prompts)]
        for r in reqs:
            srv.submit(r)
        done: dict = {}
        peak = 0
        t0 = _time.time()
        while not srv.idle:
            srv.step()
            peak = max(peak, srv.n_active)
            if srv._done:
                done.update(srv.drain_completed())
        done.update(srv.drain_completed())
        wall = _time.time() - t0
        toks = {i: done[r.id].tokens for i, r in enumerate(reqs)}
        reasons = [done[r.id].finish_reason for r in reqs]
        return toks, peak, wall, reasons

    def mk_ring():
        return SlotServer(params, cfg, slots=ring_slots, max_len=max_len,
                          block_size=4, prefill_chunk=chunk)

    def mk_paged(**kw):
        kw.setdefault("slots", 12)
        kw.setdefault("kv_pool_blocks", pool_blocks)
        return SlotServer(params, cfg, max_len=max_len, block_size=4,
                          prefill_chunk=chunk, paged=True, kv_block=B,
                          **kw)

    drive(mk_ring())                            # compile warm-up
    toks_ring, peak_ring, wall_ring, reasons_r = drive(mk_ring())
    drive(mk_paged())
    paged_srv = mk_paged()
    toks_paged, peak_paged, wall_paged, reasons_p = drive(paged_srv)
    pkv = paged_srv.stats()["paged_kv"]
    assert toks_paged == toks_ring, (
        "paged engine diverged from the ring engine on greedy outputs")
    assert all(r in ("stop", "length") for r in reasons_r + reasons_p), (
        f"failed/early requests: {reasons_r} {reasons_p}")
    assert peak_paged > ring_slots, (
        f"paged peak concurrency {peak_paged} did not exceed the ring "
        f"slots x max_len bound ({ring_slots}) at equal device memory")
    assert pkv["pool_blocks_peak"] <= pool_blocks
    paged_srv._allocator.check()

    # ---- arm 2: admission-storm TPOT, interleave on vs off ----------
    # 20ms per scheduling turn dwarfs TINY compute, so TPOT measures
    # TURN CADENCE: interleaved prefill rides the decode turn (cadence
    # unchanged, ratio ~1.0x) while the uncapped pump drains the whole
    # storm's chunks inside ONE turn (a concentrated stall every
    # in-flight stream feels).
    os.environ["TONY_TEST_SERVING_STEP_DELAY_MS"] = "20"
    try:
        def run_storm(interleave, storm=True):
            srv = SlotServer(
                params, cfg, slots=16, max_len=max_len, block_size=4,
                prefill_chunk=chunk, paged=True, kv_block=B,
                kv_pool_blocks=128, prefill_interleave=interleave)
            r2 = np.random.default_rng(17)
            cohort = [Request(prompt=r2.integers(0, cfg.vocab_size,
                                                 size=8, dtype=np.int32),
                              max_new_tokens=32) for _ in range(4)]
            for r in cohort:
                srv.submit(r)
            for _ in range(6):          # cohort admitted + mid-decode
                srv.step()
                srv.checkpoint_progress()
            if storm:
                for _ in range(12):     # 6 prefill chunks each
                    srv.submit(Request(
                        prompt=r2.integers(0, cfg.vocab_size, size=48,
                                           dtype=np.int32),
                        max_new_tokens=1))
            done: dict = {}
            turn_walls = []
            while not srv.idle:
                t1 = _time.time()
                srv.step()
                # predictive processing is lazy; pace it per turn the
                # way ServeApp's journal checkpoint does, so the host
                # first_token/finished marks (the TPOT spans) track
                # turn cadence instead of collapsing into one
                # end-of-run processing burst
                srv.checkpoint_progress()
                turn_walls.append(_time.time() - t1)
                if srv._done:
                    done.update(srv.drain_completed())
            done.update(srv.drain_completed())
            assert all(c.finish_reason in ("stop", "length")
                       for c in done.values())
            # cohort-only TPOT, exact from the request traces (the
            # stats histogram is bucket-resolution; at 4 samples the
            # quantization would dominate the gated ratio) — the
            # storm's max_new=1 requests emit no TPOT samples
            tpots = []
            for c in done.values():
                spans = dict(c.trace["spans"])
                n = len(c.tokens)
                if "first_token" in spans and "finished" in spans \
                        and n >= 2:
                    tpots.append(
                        (spans["finished"] - spans["first_token"])
                        / (n - 1))
            assert len(tpots) == 4, f"cohort TPOT samples: {len(tpots)}"
            return {
                "tpot_p99_s": max(tpots),
                "max_turn_s": round(max(turn_walls), 4),
                "chunks_interleaved":
                    srv.stats()["paged_kv"]["prefill_chunks_interleaved"],
            }

        run_storm(chunk, storm=True)    # compile warm-up: every program
        run_storm(0, storm=True)        # shape both timed arms will hit
        quiescent = run_storm(chunk, storm=False)
        storm_on = run_storm(chunk, storm=True)
        storm_off = run_storm(0, storm=True)
    finally:
        del os.environ["TONY_TEST_SERVING_STEP_DELAY_MS"]
    tpot_ratio_on = storm_on["tpot_p99_s"] / quiescent["tpot_p99_s"]
    assert tpot_ratio_on <= 1.2, (
        f"storm TPOT p99 with interleaving is {tpot_ratio_on:.2f}x "
        "quiescent (gate: <= 1.2x)")
    assert storm_on["chunks_interleaved"] > 0, (
        "the storm never exercised the interleave cap")
    assert storm_off["max_turn_s"] > 1.5 * storm_on["max_turn_s"], (
        "uncapped admission should stall one turn for the whole "
        f"storm's prefill: off {storm_off['max_turn_s']}s vs "
        f"on {storm_on['max_turn_s']}s")

    # ---- arm 3: admission tiers — batch sheds before interactive ----
    srv = SlotServer(params, cfg, slots=2, max_len=max_len, block_size=4,
                     prefill_chunk=chunk, paged=True, kv_block=B,
                     max_queue=4, batch_queue_frac=0.5)
    r3 = np.random.default_rng(18)

    def _req(priority):
        return Request(prompt=r3.integers(0, cfg.vocab_size, size=6,
                                          dtype=np.int32),
                       max_new_tokens=12, priority=priority)

    occupants = [_req("interactive") for _ in range(2)]
    for r in occupants:
        srv.submit(r)
    for _ in range(4):                  # both slots occupied, mid-decode
        srv.step()
    refused = {"batch": 0, "interactive": 0}
    retry_afters = []
    submitted = []
    # batch fills its (frac-limited) share of the queue, then 429s
    for _ in range(3):
        try:
            submitted.append(srv.submit(_req("batch")))
        except QueueFullError as e:
            refused[e.priority] += 1
            retry_afters.append(e.retry_after_s)
    # interactive fills the rest, then displaces the queued batch work
    for _ in range(5):
        try:
            submitted.append(srv.submit(_req("interactive")))
        except QueueFullError as e:
            refused[e.priority] += 1
            retry_afters.append(e.retry_after_s)
    done = srv.run_until_drained()
    shed = srv.stats()["shed_by_class"]
    shed_completions = [c for c in done.values()
                        if c.finish_reason == "shed"]
    assert refused["batch"] >= 1, "batch tier never hit its 429 line"
    assert shed["batch"] >= len(shed_completions) >= 2, (
        f"queued batch work was not displaced: {shed}")
    assert all(1 <= ra <= 60 for ra in retry_afters), retry_afters
    # every interactive request either finished or was refused AT THE
    # DOOR with Retry-After — none failed, none displaced mid-queue
    n_interactive_ok = sum(
        1 for c in done.values() if c.finish_reason in ("stop", "length"))
    assert n_interactive_ok == 2 + 5 - refused["interactive"] + \
        3 - refused["batch"] - len(shed_completions), done
    srv._allocator.check()

    out = {
        "metric": "paged_kv_storm_tpot_p99_ratio_vs_quiescent",
        "value": round(tpot_ratio_on, 3),
        "unit": "x (chunked-prefill interleaving ON; gate <= 1.2x)",
        "kv_block": B,
        "pool_blocks": pool_blocks,
        "equal_device_memory_kv_rows": pool_blocks * B,
        "ring_concurrency_bound": ring_slots,
        "peak_concurrent_paged": peak_paged,
        "byte_identical_vs_ring": True,
        "zero_failed_requests": True,
        "ring_wall_s": round(wall_ring, 3),
        "paged_wall_s": round(wall_paged, 3),
        "admission_defers": pkv["admission_defers"],
        "storm": {
            "chaos_step_delay_ms": 20,
            "quiescent_tpot_p99_s": round(quiescent["tpot_p99_s"], 4),
            "interleave_on_tpot_p99_s":
                round(storm_on["tpot_p99_s"], 4),
            "interleave_off_tpot_p99_s":
                round(storm_off["tpot_p99_s"], 4),
            "interleave_on_max_turn_s": storm_on["max_turn_s"],
            "interleave_off_max_turn_s": storm_off["max_turn_s"],
            "chunks_interleaved": storm_on["chunks_interleaved"],
        },
        "tiers": {
            "shed_by_class": shed,
            "queued_batch_displaced": len(shed_completions),
            "refused_429": refused,
            "retry_after_s_range": [min(retry_afters),
                                    max(retry_afters)],
            "batch_shed_before_interactive":
                shed["interactive"] <= refused["interactive"],
        },
    }
    print(json.dumps(out))
    return 0


def run_disagg_bench() -> int:
    """Disaggregated prefill/decode serving gate (one JSON line ->
    PERF.json `disaggregated_serving`; see the module docstring).
    TINY shapes; the TPOT comparison is real-compute (NOT chaos-paced:
    the win IS the compute a decode turn no longer carries) and every
    correctness property — byte-identity, zero failed requests, the
    SIGKILL replay fallback — is an enforced invariant."""
    import re as _re
    import signal as _signal
    import subprocess
    import threading
    import time as _time
    import urllib.request

    sys.path.insert(0, str(REPO))
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tony_tpu.models import transformer
    from tony_tpu.models.serving import (
        QueueFullError, Request, SlotServer,
    )

    cfg = transformer.TransformerConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32)
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    B, max_len, chunk, slots, pool = 8, 64, 8, 8, 96
    rng = np.random.default_rng(17)

    # mixed workload: an interactive decode cohort already in flight
    # when a long-prompt prefill storm arrives. Cohort TPOT is what the
    # decode tier's SLO protects; the storm is pure prefill pressure.
    n_cohort, cohort_new = 6, 48
    n_storm, storm_new = 16, 2
    cohort_p = [rng.integers(0, cfg.vocab_size, size=8, dtype=np.int32)
                for _ in range(n_cohort)]
    storm_p = [rng.integers(0, cfg.vocab_size, size=48, dtype=np.int32)
               for _ in range(n_storm)]

    def mk(role="both"):
        return SlotServer(params, cfg, slots=slots, max_len=max_len,
                          block_size=4, prefill_chunk=chunk, paged=True,
                          kv_block=B, kv_pool_blocks=pool, role=role)

    def creq(i):
        return Request(prompt=cohort_p[i], max_new_tokens=cohort_new)

    def sreq(i):
        return Request(prompt=storm_p[i], max_new_tokens=storm_new)

    def _p99(walls):
        assert len(walls) >= 10, f"too few turn samples: {len(walls)}"
        s = sorted(walls)
        return s[min(len(s) - 1, int(0.99 * len(s)))]

    # ---- byte reference: every request solo on ONE paged engine ----
    solo = mk()
    solo_reqs = ([creq(i) for i in range(n_cohort)]
                 + [sreq(i) for i in range(n_storm)])
    for r in solo_reqs:
        solo.submit(r)
    solo_done = solo.run_until_drained()
    refs = [solo_done[r.id].tokens for r in solo_reqs]

    # Both legs drive every engine serially in ONE process, so a
    # stream's trace spans would absorb the OTHER replica's compute —
    # the opposite of the separate-hardware reality. The faithful
    # per-replica TPOT is the engine's OWN per-turn step wall while
    # cohort work is in flight: an in-flight stream emits one token
    # per scheduling turn, so its TPOT is exactly its replica's turn
    # time, and whatever rides that turn (storm prefill chunks on a
    # role=both replica; nothing on a decode specialist) is what the
    # measurement must charge.

    def run_both_leg():
        """2 x role='both' at equal hardware: each replica carries half
        the cohort AND half the storm — storm prefill chunks ride the
        cohort's decode turns (bounded by the interleave cap, but
        riding them all the same)."""
        engines = [mk(), mk()]
        reqs = [creq(i) for i in range(n_cohort)]
        cohort_ids: list = [set(), set()]
        for i, r in enumerate(reqs):
            engines[i % 2].submit(r)
            cohort_ids[i % 2].add(r.id)
        for _ in range(3):              # cohort admitted, mid-decode
            for e in engines:
                e.step()
                e.checkpoint_progress()
        for i in range(n_storm):
            engines[i % 2].submit(sreq(i))
        done: list[dict] = [{}, {}]
        walls: list = []
        while not all(e.idle for e in engines):
            for ei, e in enumerate(engines):
                if not e.idle:
                    t1 = _time.time()
                    e.step()
                    w = _time.time() - t1
                    e.checkpoint_progress()
                    if cohort_ids[ei] - set(done[ei]):
                        walls.append(w)
                if e._done:
                    done[ei].update(e.drain_completed())
        for ei, e in enumerate(engines):
            done[ei].update(e.drain_completed())
            e._allocator.check()
        reasons = [c.finish_reason for d in done for c in d.values()]
        assert all(r in ("stop", "length") for r in reasons), reasons
        return _p99(walls)

    def run_disagg_leg():
        """1 prefill specialist + 1 decode replica (equal hardware):
        every request prefills on the specialist and decodes — via the
        exported-block handoff — on the decode replica, whose turns
        carry ONLY decode work."""
        pre, dec = mk("prefill"), mk("decode")
        done_pre: dict = {}
        done_dec: dict = {}
        handoffs: list = []             # payloads awaiting a dec slot
        rid_map: dict = {}              # original id -> dec-side id
        kv_imports = 0

        def pump_pre():
            nonlocal kv_imports
            if not pre.idle:
                pre.step()
                pre.checkpoint_progress()
            if pre._done:
                done_pre.update(pre.drain_completed())
            for rid in list(done_pre):
                comp = done_pre.pop(rid)
                assert comp.finish_reason == "prefilled", comp
                handoffs.append(pre.export_blocks(rid))
            while handoffs:
                try:
                    new_rid = dec.import_blocks(handoffs[0])
                except QueueFullError:
                    break               # dec full; retry next turn
                # the decode replica assigns its own request id; the
                # entry carries the original for the caller's join
                rid_map[handoffs[0]["entry"]["id"]] = new_rid
                handoffs.pop(0)
                kv_imports += 1

        # leg ordering mirrors the both leg: cohort first, mid-decode,
        # then the storm drops
        cohort = [creq(i) for i in range(n_cohort)]
        for r in cohort:
            pre.submit(r)
        while kv_imports < n_cohort:    # cohort handed off to dec
            pump_pre()
        for _ in range(3):              # cohort admitted, mid-decode
            dec.step()
            dec.checkpoint_progress()
        storm = [sreq(i) for i in range(n_storm)]
        for r in storm:
            pre.submit(r)
        all_reqs = cohort + storm
        walls: list = []
        cohort_orig = {r.id for r in cohort}
        while len(done_dec) < len(all_reqs):
            pump_pre()
            if not dec.idle:
                t1 = _time.time()
                dec.step()
                w = _time.time() - t1
                dec.checkpoint_progress()
                if {rid_map[i] for i in cohort_orig
                        if i in rid_map} - set(done_dec):
                    walls.append(w)
            if dec._done:
                done_dec.update(dec.drain_completed())
        pre._allocator.check()
        dec._allocator.check()
        assert dec.stats()["paged_kv"]["kv_imports"] == len(all_reqs)
        assert pre.stats()["paged_kv"]["kv_exports"] == len(all_reqs)
        reasons = [c.finish_reason for c in done_dec.values()]
        assert all(r in ("stop", "length") for r in reasons), reasons
        toks = [done_dec[rid_map[r.id]].tokens for r in all_reqs]
        return _p99(walls), toks

    run_both_leg()                      # compile warm-up, both shapes
    run_disagg_leg()
    tpot_both = run_both_leg()
    tpot_disagg, disagg_toks = run_disagg_leg()
    speedup = tpot_both / tpot_disagg
    assert disagg_toks == refs, (
        "disaggregated completions diverged from solo greedy")
    assert speedup >= 1.2, (
        f"decode TPOT p99: 2x both {tpot_both:.4f}s vs disagg "
        f"{tpot_disagg:.4f}s = {speedup:.2f}x (gate: >= 1.2x)")

    # ---- fleet leg: mid-transfer SIGKILL -> journal-replay fallback --
    import tempfile as _tempfile

    from tony_tpu.router import FleetRouter

    f_requests = 10
    f_budgets = [8, 12, 16]
    f_prompts = [rng.integers(0, cfg.vocab_size, size=24,
                              dtype=np.int32).tolist()
                 for _ in range(f_requests)]
    # the serve CLI always sets n_kv_heads=n_heads (and the default
    # max_seq_len), so the fleet byte-reference uses the CLI's shape —
    # NOT the in-process cfg above
    f_cfg = transformer.TransformerConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=128, dtype=jnp.float32)
    f_params = transformer.init(jax.random.PRNGKey(0), f_cfg)
    f_solo = SlotServer(f_params, f_cfg, slots=slots, max_len=max_len,
                        block_size=4, prefill_chunk=chunk, paged=True,
                        kv_block=B, kv_pool_blocks=pool)
    f_reqs = [Request(prompt=p,
                      max_new_tokens=f_budgets[i % len(f_budgets)])
              for i, p in enumerate(f_prompts)]
    for r in f_reqs:
        f_solo.submit(r)
    f_done = f_solo.run_until_drained()
    f_refs = [f_done[r.id].tokens for r in f_reqs]

    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           # slow each turn so the prefill leg stays in flight long
           # enough for a genuinely MID-transfer kill
           "TONY_TEST_SERVING_STEP_DELAY_MS": "25"}
    env.pop("XLA_FLAGS", None)

    class Srv:
        def __init__(self, name, role, trace_dir):
            self.name, self.role, self.trace_dir = name, role, trace_dir
            self.proc = self.port = None
            self.spawn()

        def spawn(self):
            self.proc = subprocess.Popen(
                [sys.executable, "-m", "tony_tpu.cli.main", "serve",
                 "--port", "0", "--vocab", "256", "--d-model", "64",
                 "--n-layers", "2", "--n-heads", "4",
                 "--d-ff", "128", "--dtype", "float32",
                 "--seed", "0", "--slots", str(slots),
                 "--max-len", str(max_len), "--block-size", "4",
                 "--prefill-chunk", str(chunk), "--paged-kv",
                 "--kv-block", str(B), "--kv-pool-blocks", str(pool),
                 "--role", self.role, "--trace-dir", self.trace_dir],
                cwd=REPO, env=env, text=True,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
            self.port = None

        def await_ready(self, timeout=240.0):
            deadline = _time.time() + timeout
            while self.port is None and _time.time() < deadline:
                line = self.proc.stdout.readline()
                m = _re.search(r"http://[\d.]+:(\d+)", line or "")
                if m:
                    self.port = int(m.group(1))
            assert self.port, f"{self.name} never printed its port"
            threading.Thread(target=self.proc.stdout.read,
                             daemon=True).start()
            while _time.time() < deadline:
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{self.port}/healthz",
                            timeout=2) as r:
                        if r.status == 200:
                            return
                except Exception:
                    _time.sleep(0.2)
            raise AssertionError(f"{self.name} never became healthy")

        def stats(self):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{self.port}/stats",
                    timeout=10) as r:
                return json.loads(r.read().decode())

        def stop(self):
            if self.proc.poll() is None:
                self.proc.kill()
            self.proc.wait(timeout=15)

    td = _tempfile.mkdtemp(prefix="tony-disagg-bench-")
    pre_s = Srv("pre", "prefill", os.path.join(td, "pre"))
    dec_s = Srv("dec", "decode", os.path.join(td, "dec"))
    router = None
    try:
        pre_s.await_ready()
        dec_s.await_ready()
        router = FleetRouter(
            [("pre", "127.0.0.1", pre_s.port),
             ("dec", "127.0.0.1", dec_s.port)],
            prefill_chunk=chunk, health_interval_s=0.15,
            stats_every=1, seed=0)
        router.start()
        deadline = _time.time() + 30
        while _time.time() < deadline:
            st = router.stats()["replicas"]
            if st.get("pre", {}).get("role") == "prefill" \
                    and st.get("dec", {}).get("role") == "decode":
                break
            _time.sleep(0.1)

        fleet_results: dict[int, object] = {}

        def call(i):
            try:
                fleet_results[i] = router.generate(
                    f_prompts[i],
                    max_new_tokens=f_budgets[i % len(f_budgets)],
                    timeout_s=300)
            except Exception as e:
                fleet_results[i] = e

        t0 = _time.time()
        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(f_requests)]
        for t in threads:
            t.start()
            _time.sleep(0.05)
        # kill the prefill specialist once the transfer path has
        # genuinely moved blocks (>=1 completed handoff) AND a prefill
        # leg is in flight — a mid-transfer death, not a cold one
        deadline = _time.time() + 120
        killed = False
        while _time.time() < deadline:
            rs = router.stats()
            if rs["disagg_handoffs"] >= 1 and rs["disagg_requests"] \
                    > rs["disagg_handoffs"] + rs["disagg_fallbacks"]:
                os.kill(pre_s.stats()["pid"], _signal.SIGKILL)
                killed = True
                break
            _time.sleep(0.02)
        assert killed, "the transfer path never reached a kill window"
        for t in threads:
            t.join(timeout=600)
        fleet_wall = _time.time() - t0
        assert not any(t.is_alive() for t in threads), "hung callers"
        failed = [i for i, r in fleet_results.items()
                  if not isinstance(r, dict)]
        assert not failed, (
            f"disagg SIGKILL leg failed requests: "
            f"{[(i, fleet_results[i]) for i in failed]}")
        mismatch = [i for i in range(f_requests)
                    if fleet_results[i]["tokens"] != f_refs[i]]
        assert not mismatch, (
            f"disagg fleet diverged from solo greedy on: {mismatch}")
        rstats = router.stats()
        assert rstats["failed"] == 0
        assert rstats["disagg_handoffs"] >= 1, (
            "no handoff completed before the kill")
        assert rstats["disagg_fallbacks"] >= 1, (
            "the mid-transfer kill must exercise the replay fallback")
        dec_stats = dec_s.stats()
        kv_imported = dec_stats["paged_kv"]["kv_imports"]
    finally:
        if router is not None:
            router.shutdown()
        for s in (pre_s, dec_s):
            try:
                s.stop()
            except Exception:
                pass

    out = {
        "metric": "disagg_decode_tpot_p99_speedup_vs_both",
        "value": round(speedup, 3),
        "unit": "x (1 prefill + 1 decode vs 2x role=both at equal "
                "hardware; gate >= 1.2x)",
        "kv_block": B,
        "pool_blocks_per_replica": pool,
        "mixed_workload": {
            "cohort": {"n": n_cohort, "prompt_len": 8,
                       "max_new": cohort_new},
            "storm": {"n": n_storm, "prompt_len": 48,
                      "max_new": storm_new},
        },
        "both_tpot_p99_s": round(tpot_both, 4),
        "disagg_tpot_p99_s": round(tpot_disagg, 4),
        "byte_identical_vs_solo": True,
        "zero_failed_requests": True,
        "sigkill_leg": {
            "requests": f_requests,
            "failed": 0,
            "byte_identical": True,
            "handoffs_before_kill": rstats["disagg_handoffs"],
            "replay_fallbacks": rstats["disagg_fallbacks"],
            "decode_kv_imports": kv_imported,
            "wall_s": round(fleet_wall, 3),
            "chaos_step_delay_ms": 25,
        },
        "num_devices": jax.device_count(),
    }
    print(json.dumps(out))
    return 0


def run_shared_prefix_bench() -> int:
    """Prefix-cache serving benchmark (one JSON line; see module
    docstring). Submission order, budgets, and slot scheduling are
    identical between the cold and warm servers, so the only difference
    is WHERE prompt-body KV comes from — recomputed (cold) or copied out
    of the shared pool (warm). The bench asserts the completions are
    byte-identical: prefix reuse is a pure data-movement optimization,
    never a numerics change (int8 pools store the quantized bytes)."""
    import time as _time

    sys.path.insert(0, str(REPO))
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tony_tpu.models import transformer
    from tony_tpu.models.serving import Request, SlotServer

    cfg = transformer.TransformerConfig(
        vocab_size=2048, d_model=256, n_layers=4, n_heads=8, n_kv_heads=4,
        d_ff=1024, max_seq_len=512,
        dtype=jnp.bfloat16 if jax.default_backend() == "tpu"
        else jnp.float32,
    )
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    slots, max_len, chunk = 8, 512, 64
    n_requests, template_len = 24, 192          # template = 3 full chunks
    suffix_cycle = [9, 13, 17, 21]
    budgets = [32, 48, 24, 40]
    rng = np.random.default_rng(7)
    template = rng.integers(0, cfg.vocab_size, size=template_len,
                            dtype=np.int32)
    prompts = [
        np.concatenate([template, rng.integers(
            0, cfg.vocab_size, size=suffix_cycle[i % len(suffix_cycle)],
            dtype=np.int32)])
        for i in range(n_requests)
    ]
    body_tokens = sum(p.size - 1 for p in prompts)

    def serve(*, blocks):
        srv = SlotServer(params, cfg, slots=slots, max_len=max_len,
                         block_size=16, prefill_chunk=chunk,
                         prefix_cache_blocks=blocks)
        reqs = [Request(prompt=p, max_new_tokens=budgets[i % len(budgets)])
                for i, p in enumerate(prompts)]
        for r in reqs:
            srv.submit(r)
        t0 = _time.time()
        done = srv.run_until_drained()
        wall = _time.time() - t0
        toks = {i: done[r.id].tokens for i, r in enumerate(reqs)}
        n_tokens = sum(len(t) for t in toks.values())
        return {
            "wall_s": round(wall, 3),
            "tokens_per_sec": round(n_tokens / wall, 1),
            "useful_tokens": n_tokens,
            "admission_dispatches": srv.admission_dispatches,
            "prefill_tokens_computed": srv.prefill_tokens_computed,
            "prefill_tokens_reused": srv.prefill_tokens_reused,
            **({"prefix_cache": srv.stats()["prefix_cache"]} if blocks
               else {}),
        }, toks

    pool_blocks = 32
    serve(blocks=0)                              # compile warm-up
    cold, toks_cold = serve(blocks=0)
    serve(blocks=pool_blocks)                    # warm the hit-path too
    hit, toks_hit = serve(blocks=pool_blocks)
    assert toks_hit == toks_cold, (
        "prefix cache changed completions — reuse must be byte-identical")
    reused_frac = hit["prefill_tokens_reused"] / body_tokens
    out = {
        "metric": "prefix_cache_serving_reused_token_fraction",
        "value": round(reused_frac, 4),
        "unit": "fraction of prompt-body tokens served from cache",
        "slots": slots,
        "n_requests": n_requests,
        "template_len": template_len,
        "suffix_cycle": suffix_cycle,
        "budgets_cycle": budgets,
        "prefill_chunk": chunk,
        "prefix_cache_blocks": pool_blocks,
        "body_tokens_total": body_tokens,
        "completions_identical_hit_vs_cold": True,
        "cold": cold,
        "hit": hit,
        "num_devices": jax.device_count(),
    }
    print(json.dumps(out))
    return 0


def _scrape_ttft_hist(base_url: str):
    """Reconstruct the serving_ttft_seconds histogram from a replica's
    /metrics exposition (cumulative ``le`` buckets) into an
    observability.Histogram — scraped before and after a timed pass, the
    bucket DELTA gives that pass's quantiles with no warm-up pollution."""
    import re as _re
    import urllib.request

    from tony_tpu.observability import Histogram

    with urllib.request.urlopen(base_url + "/metrics", timeout=10) as r:
        text = r.read().decode()
    cum = []
    for m in _re.finditer(
            r'^serving_ttft_seconds_bucket\{le="([^"]+)"\} (\d+)$',
            text, _re.M):
        cum.append((m.group(1), int(m.group(2))))
    h = Histogram()
    assert len(cum) == len(h.counts), "ttft bucket layout drifted"
    prev = 0
    for i, (_, c) in enumerate(cum):
        h.counts[i] = c - prev
        prev = c
    h.count = prev
    return h


def _hist_delta(before, after):
    """after - before as a fresh Histogram (per-pass bucket deltas;
    merge the per-replica results before taking fleet-wide quantiles —
    max-of-per-replica-p99s would overstate the tail under uneven
    load)."""
    from tony_tpu.observability import Histogram

    d = Histogram()
    d.counts = [a - b for a, b in zip(after.counts, before.counts)]
    d.count = after.count - before.count
    return d


def run_serving_fleet_bench() -> int:
    """Fleet benchmark (one JSON line; ISSUE 7): a 2-3 replica
    SlotServer fleet of real serve processes (PR 2 shape, prefix
    caches ON — the production path) behind the FleetRouter, on
    forced-CPU host devices with one replica pinned per core (one
    replica per accelerator host; an unpinned XLA CPU server would
    spread over every core and the "N replicas vs 1" comparison would
    measure contention, not capacity). Two comparisons, enforced
    rather than just reported:

    - **capacity scaling**: closed-loop, concurrency-matched,
      best-of-`trials` per arm after a discarded steady-state pass —
      fleet capacity must exceed 1.5x one replica. Closed loop because
      per-pass open-loop throughput at these wall times swings ~3x
      with scheduler placement (every arrival-rate calibration scheme
      measured the arrival process or the noise, not the fleet). The
      headroom is compute AND cache capacity: the per-replica trie
      budget holds 2/3 of the template working set, so the
      affinity-routed fleet holds it collectively while the single
      replica churns it through LRU eviction. Open-loop CAPACITY arms
      ride along: Poisson arrivals offered at each arm's own measured
      capacity, best-of-`trials`, enforcing a softer 1.3x fleet
      advantage (per-pass open-loop walls swing with placement).
      Poisson OPEN-LOOP passes at 1.2x the measured fleet capacity are
      reported alongside (the lone replica collapses into deep
      queueing at fleet-rate traffic).
    - **prefix-affinity vs random routing**: the same open-loop
      schedule routed sticky vs least-loaded, after an untimed
      steady-state prepass per policy. Affinity must beat random on
      the fleet-wide reused-token fraction. p99 TTFT (per-replica
      serving_ttft_seconds bucket deltas over the timed pass, MERGED
      fleet-wide) is reported for both.
    """
    import re as _re
    import subprocess
    import threading
    import urllib.request
    import numpy as np

    sys.path.insert(0, str(REPO))
    from tony_tpu.router import FleetRouter

    # the PR 2 bench shape (d256/L4, chunk 64): heavy enough that the
    # REPLICAS are the measured bottleneck. At toy shapes (d128) a
    # single replica plus the router/load-generator saturate the whole
    # host and both arms measure the client, not the fleet; and the
    # prefix-COPY path only beats recomputing prefill once the model is
    # this large (docs/performance.md "Fleet serving").
    slots, max_len, chunk = 6, 512, 64
    n_requests, max_new = 64, 8
    trials = 3      # best-of per throughput arm: short walls on a shared
    #                 2-core host swing; the max is the capacity
    # enough distinct templates that rendezvous hashing balances them
    # over 2-3 replicas (6 keys over 2 bins can land 5/1; 12 rarely do)
    templates = 12
    # per-replica trie budget: 2/3 of the template working set (12
    # templates x 4 chunks = 48 blocks): an affinity-routed FLEET's
    # per-replica share (~24 blocks) fits with headroom, while a single
    # replica — or a randomly-routed fleet whose every replica sees
    # every template — churns all 48 through LRU eviction and recomputes
    # 256-token prefills. Fleet serving scales cache capacity, not just
    # compute. (Exact-fit budgets thrash: ref-pinned in-use paths block
    # eviction, so size the fitting arm with slack.)
    cache_blocks = 32

    def serve_args(blocks: int) -> list[str]:
        out = [
            sys.executable, "-m", "tony_tpu.cli.main", "serve",
            "--port", "0", "--host", "127.0.0.1",
            "--vocab", "2048", "--d-model", "256", "--n-layers", "4",
            "--n-heads", "8", "--d-ff", "1024", "--dtype", "float32",
            "--seed", "0", "--slots", str(slots),
            "--max-len", str(max_len), "--block-size", "16",
            "--prefill-chunk", str(chunk), "--drain-timeout-s", "2",
        ]
        if blocks:
            out += ["--prefix-cache-blocks", str(blocks)]
        return out

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)      # each replica is a single-device server
    ncpu = os.cpu_count() or 2
    n_fleet = 3 if ncpu >= 3 else 2

    class Replica:
        def __init__(self, name, core: int, blocks: int):
            self.name = name
            self.proc = subprocess.Popen(
                serve_args(blocks), cwd=REPO, env=env, text=True,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
            try:
                os.sched_setaffinity(self.proc.pid, {core % ncpu})
            except OSError:
                pass        # affinity is best-effort off-Linux
            self.port = None

        def await_ready(self, timeout=180.0):
            deadline = time.time() + timeout
            line = ""
            while self.port is None and time.time() < deadline:
                line = self.proc.stdout.readline()
                m = _re.search(r"http://[\d.]+:(\d+)", line or "")
                if m:
                    self.port = int(m.group(1))
            assert self.port, f"{self.name} never printed its port: {line}"
            # drain stdout on a thread so the serve process never blocks
            # on a full pipe
            threading.Thread(target=self.proc.stdout.read,
                             daemon=True).start()
            while time.time() < deadline:
                try:
                    with urllib.request.urlopen(
                            self.base_url + "/healthz", timeout=2) as r:
                        if r.status == 200:
                            return
                except Exception:
                    time.sleep(0.2)
            raise AssertionError(f"{self.name} never became healthy")

        @property
        def base_url(self):
            return f"http://127.0.0.1:{self.port}"

        def stats(self):
            with urllib.request.urlopen(self.base_url + "/stats",
                                        timeout=10) as r:
                return json.loads(r.read().decode())

        def stop(self):
            self.proc.terminate()
            try:
                self.proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                self.proc.kill()

    rng = np.random.default_rng(5)
    bodies = [rng.integers(0, 2048, size=4 * chunk, dtype=np.int32)
              for _ in range(templates)]
    prompts = [
        np.concatenate([bodies[i % templates],
                        rng.integers(0, 2048, size=4 + i % 9,
                                     dtype=np.int32)]).tolist()
        for i in range(n_requests)
    ]

    def warm(rep):
        """Compile every program shape the timed pass will hit (batched
        admission pads rows to powers of two: drive slots-wide bursts)
        WITHOUT seeding the prefix trie (cache_prompt off)."""
        def one(i):
            body = json.dumps({
                "prompt": rng.integers(0, 2048,
                                       size=2 * chunk + i).tolist(),
                "max_new_tokens": 8, "cache_prompt": False}).encode()
            req = urllib.request.Request(rep.base_url + "/generate",
                                         data=body)
            with urllib.request.urlopen(req, timeout=300) as r:
                r.read()
        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(2 * slots)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)

    def fresh_fleet(n, blocks=0):
        """A pass gets FRESH replica processes: each pass's prefix tries
        start cold, so reuse fractions compare routing policies, not
        which pass inherited a warm trie."""
        reps = [Replica(f"replica:{i}", core=i, blocks=blocks)
                for i in range(n)]
        for r in reps:
            r.await_ready()
        warmers = [threading.Thread(target=warm, args=(r,)) for r in reps]
        for t in warmers:
            t.start()
        for t in warmers:
            t.join(timeout=600)
        return reps

    def run_pass(reps, *, affinity, schedule, prepass=False):
        # spill_queue_depth: a sticky replica 3 slot-widths deep in
        # backlog spills to its rendezvous runner-up — affinity is worth
        # a queued beat, not an unbounded pile-up behind one replica.
        # Generous probe timeout + eject_after: a saturated pinned core
        # answers /healthz slowly, and this harness must not grade
        # health-probe churn.
        router = FleetRouter(
            [(r.name, "127.0.0.1", r.port) for r in reps],
            prefill_chunk=chunk, affinity=affinity,
            health_interval_s=0.25, spill_queue_depth=3 * slots,
            eject_after=4, probe_timeout_s=5.0, seed=0)
        router.start()

        def fire(sched):
            results: dict[int, object] = {}
            t_done: dict[int, float] = {}

            def call(i, at):
                time.sleep(max(0.0, t0 + at - time.time()))
                try:
                    results[i] = router.generate(prompts[i],
                                                 max_new_tokens=max_new,
                                                 timeout_s=600)
                    t_done[i] = time.time()
                except Exception as exc:
                    results[i] = exc
            threads = [threading.Thread(target=call, args=(i, at))
                       for i, at in enumerate(sched)]
            t0 = time.time()
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=900)
            failed = [i for i, r in results.items()
                      if not isinstance(r, dict)]
            assert not failed, f"fleet pass dropped requests: {failed}"
            return results, t_done, t0

        if prepass:
            # un-timed steady-state pass: populate each trie THE WAY THIS
            # ROUTING POLICY populates it, so the timed pass measures
            # steady state instead of cold-trie insert costs
            fire([0.0] * len(schedule))
        before = {r.name: (r.stats(), _scrape_ttft_hist(r.base_url))
                  for r in reps}
        results, t_done, t0 = fire(schedule)
        wall = max(t_done.values()) - t0
        tokens = sum(len(r["tokens"]) for r in results.values())
        computed = reused = 0
        ttft_fleet = None
        for r in reps:
            st_b, h_b = before[r.name]
            st_a, h_a = r.stats(), _scrape_ttft_hist(r.base_url)
            computed += (st_a["prefill_tokens_computed"]
                         - st_b["prefill_tokens_computed"])
            reused += (st_a["prefill_tokens_reused"]
                       - st_b["prefill_tokens_reused"])
            delta = _hist_delta(h_b, h_a)
            if ttft_fleet is None:
                ttft_fleet = delta
            else:
                ttft_fleet.merge(delta)
        ttft_p99 = ttft_fleet.quantile(0.99) if ttft_fleet else 0.0
        st = router.stats()
        router.shutdown()
        return {
            "wall_s": round(wall, 3),
            "tokens_per_sec": round(tokens / wall, 1),
            "useful_tokens": tokens,
            "prefill_reused_frac": round(
                reused / max(1, computed + reused), 4),
            "ttft_p99_s": round(ttft_p99, 4),
            "affinity_hit_ratio": st["affinity"]["hit_ratio"],
            "retries": sum(rep["retries"]
                           for rep in st["replicas"].values()),
            "shed_429": sum(rep["shed"]
                            for rep in st["replicas"].values()),
        }

    def closed_loop_capacity(reps, concurrency):
        """Arm capacity at a BOUNDED concurrency (2 slot-widths per
        replica): a classic K-worker closed loop, least-loaded so the
        work spreads. An all-at-once burst would measure the
        deep-backlog thrash regime (64 handler threads against a pinned
        core), not capacity."""
        router = FleetRouter(
            [(r.name, "127.0.0.1", r.port) for r in reps],
            prefill_chunk=chunk, affinity=True,
            spill_queue_depth=3 * slots, eject_after=4,
            probe_timeout_s=5.0, seed=0)
        it = iter(range(n_requests))
        lock = threading.Lock()
        tokens = [0]

        def worker():
            while True:
                with lock:
                    i = next(it, None)
                if i is None:
                    return
                resp = router.generate(prompts[i], max_new_tokens=max_new,
                                       timeout_s=600)
                with lock:
                    tokens[0] += len(resp["tokens"])
        t0 = time.time()
        threads = [threading.Thread(target=worker)
                   for _ in range(concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=900)
        wall = time.time() - t0
        router.shutdown()
        return tokens[0] / wall

    # ---- throughput scaling (cache ON — the production path) --------
    # Separate replica PROCESSES per arm so each arm's prefix tries
    # evolve under its own policy: the single arm's one replica churns
    # the whole template working set through its half-sized trie; the
    # affinity-routed fleet holds it collectively. Arms alternate,
    # best-of-`trials` each — adjacent in time like the mnist bench's
    # A/B pairs, so host noise hits both arms alike. The single-arm
    # replica shares core 0 with one fleet replica; only one arm is
    # ever driven at a time (an idle serve loop costs ~nothing).
    single_arm = fresh_fleet(1, blocks=cache_blocks)
    fleet = fresh_fleet(n_fleet, blocks=cache_blocks)
    try:
        # capacity = best-of-`trials` closed-loop measurements per arm,
        # concurrency matched to each arm's slot budget, arms alternated
        # so host noise hits both alike. The SPEEDUP is the capacity
        # ratio: per-pass open-loop throughput on this class of host
        # swings ~3x run to run (scheduler placement against the pinned
        # replicas), which defeated every arrival-rate calibration
        # scheme — closed loops self-pace and need none.
        # one discarded closed-loop pass per arm brings each arm's tries
        # to ITS policy's steady state before anything is measured
        closed_loop_capacity(single_arm, concurrency=2 * slots)
        closed_loop_capacity(fleet, concurrency=2 * slots * n_fleet)
        single_runs, fleet_runs = [], []
        for _ in range(trials):
            single_runs.append(closed_loop_capacity(
                single_arm, concurrency=2 * slots))
            fleet_runs.append(closed_loop_capacity(
                fleet, concurrency=2 * slots * n_fleet))
        cap_single = max(single_runs)
        cap_fleet = max(fleet_runs)
        # open-loop CAPACITY arms (ISSUE 16): the same capacity
        # question asked the way traffic actually arrives — seeded
        # Poisson arrivals offered at each arm's OWN measured
        # closed-loop capacity, best-of-`trials`. Per-pass open-loop
        # walls on this host class swing with scheduler placement (the
        # ~3x above), so the enforced ratio here is softer (1.3x) than
        # the closed-loop 1.5x; the closed-loop number stays the
        # headline capacity.
        def open_loop_capacity(reps, cap):
            sched = np.cumsum(rng.exponential(
                scale=max_new / cap, size=n_requests)).tolist()
            return run_pass(reps, affinity=True,
                            schedule=sched)["tokens_per_sec"]
        ol_single_runs, ol_fleet_runs = [], []
        for _ in range(trials):
            ol_single_runs.append(
                open_loop_capacity(single_arm, cap_single))
            ol_fleet_runs.append(open_loop_capacity(fleet, cap_fleet))
        ol_single = max(ol_single_runs)
        ol_fleet = max(ol_fleet_runs)
        # the open-loop (Poisson) passes run at 1.2x the measured FLEET
        # capacity: the single arm is then deeply saturated (the
        # open-loop collapse a lone replica suffers at fleet-rate
        # traffic), the fleet just-saturated — both walls are reported
        interarrival = max_new / (cap_fleet * 1.2)
        schedule = np.cumsum(rng.exponential(
            scale=interarrival, size=n_requests)).tolist()
        single = run_pass(single_arm, affinity=True, schedule=schedule)
        fleet_pass = run_pass(fleet, affinity=True, schedule=schedule)
        # affinity open-loop pass: the fleet's tries are already in the
        # affinity-policy steady state from the capacity trials
        affinity_pass = run_pass(fleet, affinity=True, schedule=schedule,
                                 prepass=True)
    finally:
        for r in single_arm + fleet:
            r.stop()
    fleet = fresh_fleet(n_fleet, blocks=cache_blocks)
    try:
        random_pass = run_pass(fleet, affinity=False, schedule=schedule,
                               prepass=True)
    finally:
        for r in fleet:
            r.stop()

    print(f"# capacity single {cap_single:.0f} {single_runs} | fleet "
          f"{cap_fleet:.0f} {fleet_runs} | open-loop capacity single "
          f"{ol_single_runs} fleet {ol_fleet_runs} | "
          f"open-loop single {single} | "
          f"fleet {fleet_pass} | affinity {affinity_pass} | "
          f"random {random_pass}", file=sys.stderr)
    speedup = round(cap_fleet / cap_single, 3)
    assert speedup > 1.5, (
        f"fleet speedup {speedup} <= 1.5x single replica")
    speedup_open_loop = round(ol_fleet / ol_single, 3)
    assert speedup_open_loop > 1.3, (
        f"open-loop fleet speedup {speedup_open_loop} <= 1.3x single "
        f"replica (single {ol_single_runs}, fleet {ol_fleet_runs})")
    assert (affinity_pass["prefill_reused_frac"]
            > random_pass["prefill_reused_frac"]), (
        "prefix-affinity routing must beat random routing on trie reuse")
    out = {
        "metric": "serving_fleet_speedup_vs_single_replica",
        "value": speedup,
        "unit": "x capacity (closed-loop, concurrency-matched, "
                "best-of-trials per arm)",
        "replicas": n_fleet,
        "slots_per_replica": slots,
        "n_requests": n_requests,
        "templates": templates,
        "max_new_tokens": max_new,
        "prefill_chunk": chunk,
        "poisson_interarrival_s": round(interarrival, 4),
        "one_core_per_replica": True,
        "throughput_trials_per_arm": trials,
        "capacity_single_tokens_per_sec": round(cap_single, 1),
        "capacity_fleet_tokens_per_sec": round(cap_fleet, 1),
        "capacity_single_all_trials": [round(v, 1) for v in single_runs],
        "capacity_fleet_all_trials": [round(v, 1) for v in fleet_runs],
        "speedup_open_loop": speedup_open_loop,
        "capacity_single_open_loop_tokens_per_sec": round(ol_single, 1),
        "capacity_fleet_open_loop_tokens_per_sec": round(ol_fleet, 1),
        "open_loop_capacity_all_trials": {
            "single": [round(v, 1) for v in ol_single_runs],
            "fleet": [round(v, 1) for v in ol_fleet_runs],
        },
        "open_loop_single_replica": single,
        "open_loop_fleet": fleet_pass,
        "prefix_cache_blocks_per_replica": cache_blocks,
        "fleet_affinity": affinity_pass,
        "fleet_random": random_pass,
        "affinity_gain": {
            "reused_frac": [affinity_pass["prefill_reused_frac"],
                            random_pass["prefill_reused_frac"]],
            "ttft_p99_s": [affinity_pass["ttft_p99_s"],
                           random_pass["ttft_p99_s"]],
            "affinity_hit_ratio": affinity_pass["affinity_hit_ratio"],
        },
    }
    print(json.dumps(out))
    return 0


def run_router_ha_bench() -> int:
    """Router-tier HA gate (one JSON line -> PERF.json `router_ha`;
    docs/serving.md "Router tier HA"): a REAL driver gang-launches 2
    serving replicas AND 2 shared-nothing front doors — the `router`
    framework, each executor supervising a real `tony-tpu route` child
    on the task's published port — then
    TONY_TEST_ROUTER_SIGKILL_AT_REQUEST deterministically SIGKILLs
    door 0 on receipt of its Nth front-door POST, mid-burst. Enforced
    rather than reported:

    - **zero failed requests**: every client whose door died re-POSTs
      the same ``request_id`` on the surviving door and completes (the
      replica-journaled ``req:<id>`` progress key makes resume
      portable across doors);
    - **byte-identical responses**: every rerouted request's tokens
      equal a fresh undisturbed run of the same prompt — buffered AND
      streamed (the SSE relay of the same prompt yields the same
      token sequence);
    - **affinity preserved**: both doors, probed live, route the same
      keyed prompt to the same replica (shared-nothing rendezvous
      agreement, after one door was relaunched);
    - **the driver relaunches the dead door** on its restart budget
      (journal: router:0 restarts == 1, replicas untouched) and the
      relaunched door serves.

    Router death is a latency cost: the reported value is the p50
    latency of the requests that lost their front door over the p50 of
    the undisturbed ones."""
    import signal as _signal
    import statistics as _stats
    import tempfile as _tempfile
    import threading
    import urllib.request

    sys.path.insert(0, str(REPO))
    import numpy as np

    from tony_tpu import constants as c
    from tony_tpu.client import TonyClient
    from tony_tpu.conf import TonyConf
    from tony_tpu.events.driver_journal import load_state
    from tony_tpu.router import DriverDiscovery

    e = dict(vocab=64, d_model=16, n_layers=1, n_heads=2, d_ff=32,
             slots=4, max_len=96, block_size=4, prefill_chunk=8)
    MAX_NEW = 8
    STEP_DELAY_MS = 30      # ~0.25s of decode per request: the SIGKILL
    #                         catches real relays in flight
    N_REQUESTS = 48
    KILL_AT = 10            # door 0 dies on its 10th front-door POST

    td = _tempfile.mkdtemp(prefix="tony-router-ha-bench-")
    root = Path(td)
    serve_cmd = (
        f"{sys.executable} -m tony_tpu.cli.main serve "
        "--port $TONY_SERVE_PORT --host 127.0.0.1 "
        f"--vocab {e['vocab']} --d-model {e['d_model']} "
        f"--n-layers {e['n_layers']} --n-heads {e['n_heads']} "
        f"--d-ff {e['d_ff']} --dtype float32 --seed 0 "
        f"--slots {e['slots']} --max-len {e['max_len']} "
        f"--block-size {e['block_size']} "
        f"--prefill-chunk {e['prefill_chunk']} "
        "--max-queue 64 --drain-timeout-s 5")
    route_cmd = (
        f"{sys.executable} -m tony_tpu.cli.main route "
        "--port $TONY_SERVE_PORT --host 127.0.0.1 "
        "--job-dir $TONY_JOB_DIR --role replica "
        f"--prefill-chunk {e['prefill_chunk']} "
        "--health-interval-s 0.3 --probe-timeout-s 5.0 "
        "--discovery-min-interval-s 0.5 --stats-every 2 "
        "--drain-timeout-s 10")
    conf = TonyConf({
        "tony.staging.dir": str(root / "staging"),
        "tony.history.location": str(root / "history"),
        "tony.history.intermediate": str(root / "history/intermediate"),
        "tony.history.finished": str(root / "history/finished"),
        "tony.am.monitor-interval-ms": 100,
        "tony.application.framework": "serving",
        "tony.task.registration-poll-interval-ms": 100,
        "tony.task.heartbeat-interval-ms": 250,
        "tony.serving.healthz-interval-ms": 200,
        "tony.replica.instances": 2,
        "tony.replica.command": serve_cmd,
        "tony.replica.max-restarts": 1,
        "tony.router.instances": 2,
        "tony.router.command": route_cmd,
        "tony.router.framework": "router",
        "tony.router.max-restarts": 2,
        # the injection env reaches every child; only route processes
        # read it, and only the one whose TONY_TASK_INDEX matches dies.
        # NOTE: the RELAUNCHED door 0 carries the same spec — the
        # post-burst probes below stay well under KILL_AT posts.
        "tony.execution.env": " ".join([
            f"PYTHONPATH={REPO}", "JAX_PLATFORMS=cpu",
            f"{c.TEST_SERVING_STEP_DELAY_MS}={STEP_DELAY_MS}",
            f"{c.TEST_ROUTER_SIGKILL_AT_REQUEST}=0#{KILL_AT}"]),
    })
    t_bench = time.time()
    client = TonyClient(conf, poll_interval_s=0.2)
    client.submit()
    job_dir = Path(client.job_dir)
    disco_router = DriverDiscovery(str(job_dir), role="router",
                                   token=client.token)
    disco_replica = DriverDiscovery(str(job_dir), role="replica",
                                    token=client.token)

    def endpoints(disco):
        try:
            return {tid: (host, port) for tid, host, port in disco()}
        except Exception:
            return {}

    def post(port, payload, timeout=120):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read().decode())

    def sse_tokens(port, payload, timeout=120):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate?stream=true",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        toks, final = [], None
        with urllib.request.urlopen(req, timeout=timeout) as r:
            for raw in r:
                line = raw.decode().strip()
                if not line.startswith("data: "):
                    continue
                frame = json.loads(line[len("data: "):])
                if "finish_reason" in frame:
                    final = frame
                else:
                    toks.extend(frame.get("tokens", []))
        return toks, final

    rng = np.random.default_rng(17)
    chunk = e["prefill_chunk"]
    template = rng.integers(0, e["vocab"], size=2 * chunk,
                            dtype=np.int32)
    prompts = [np.concatenate(
        [template, rng.integers(0, e["vocab"], size=1 + i % 5,
                                dtype=np.int32)]).tolist()
        for i in range(N_REQUESTS)]

    results: dict[int, object] = {}
    latencies: dict[int, float] = {}
    retried: set[int] = set()
    marks: dict[str, float] = {}
    try:
        deadline = time.time() + 240
        doors = reps = {}
        while time.time() < deadline:
            doors = endpoints(disco_router)
            reps = endpoints(disco_replica)
            if len(doors) == 2 and len(reps) == 2:
                break
            time.sleep(0.3)
        assert len(doors) == 2, f"router tier never fully up: {doors}"
        assert len(reps) == 2, f"replica fleet never fully up: {reps}"
        door_ports = [doors["router:0"][1], doors["router:1"][1]]
        dead_port = door_ports[0]

        # ---- the burst: round-robined across both doors; door 0
        # SIGKILLs itself on its KILL_AT-th POST. A client whose door
        # died (mid-flight or refused) re-POSTs the SAME request_id on
        # the other door; alternation also covers the relaunch window.
        def call(i):
            payload = {"prompt": prompts[i], "max_new_tokens": MAX_NEW,
                       "request_id": f"burst-{i}"}
            t0 = time.time()
            attempt, last = 0, None
            while time.time() - t0 < 180:
                port = door_ports[(i + attempt) % 2]
                try:
                    results[i] = post(port, payload)
                    latencies[i] = time.time() - t0
                    return
                except Exception as exc:
                    last = exc
                    retried.add(i)
                    if "died" not in marks:
                        marks["died"] = time.time()
                    attempt += 1
                    time.sleep(0.05)
            results[i] = last

        threads = []
        t_burst = time.time()
        for i in range(N_REQUESTS):
            th = threading.Thread(target=call, args=(i,))
            th.start()
            threads.append(th)
            time.sleep(0.03)
        for th in threads:
            th.join(timeout=300)
        marks["burst_done"] = time.time()

        # ---- gate 1: zero failed requests
        failed = {i: r for i, r in results.items()
                  if not isinstance(r, dict)}
        assert not failed, (
            f"{len(failed)} requests failed across the door kill: "
            f"{dict(list(failed.items())[:3])}")
        assert len(results) == N_REQUESTS
        assert retried, (
            "the SIGKILL never disrupted a request — the burst "
            "finished before door 0's kill threshold?")
        assert "died" in marks

        # ---- gate 2: the driver relaunches the dead door, and it
        # serves (the route child exited on SIGKILL; the adapter's
        # nonzero exit spent one unit of router:0's restart budget)
        relaunched_port = None
        deadline = time.time() + 180
        while time.time() < deadline:
            doors = endpoints(disco_router)
            if "router:0" in doors and doors["router:0"][1]:
                try:
                    r0 = post(doors["router:0"][1],
                              {"prompt": prompts[0],
                               "max_new_tokens": MAX_NEW}, timeout=30)
                    if isinstance(r0, dict) and r0.get("tokens"):
                        relaunched_port = doors["router:0"][1]
                        marks["relaunched"] = time.time()
                        break
                except Exception:
                    pass
            time.sleep(0.5)
        assert relaunched_port is not None, (
            "driver never relaunched the SIGKILLed door")
        survivor = door_ports[1]

        # ---- gate 3: byte-identical responses for every rerouted
        # request — buffered re-runs on the survivor, plus the SSE
        # relay of the same prompt on BOTH doors (streams included)
        checked = sorted(retried)[:12]
        for i in checked:
            ref = post(survivor, {"prompt": prompts[i],
                                  "max_new_tokens": MAX_NEW,
                                  "request_id": f"ref-{i}"})
            assert ref["tokens"] == results[i]["tokens"], (
                f"request {i} rerouted mid-kill diverged: "
                f"{results[i]['tokens']} vs fresh {ref['tokens']}")
            assert ref["finish_reason"] == results[i]["finish_reason"]
        s_toks, s_final = sse_tokens(
            survivor, {"prompt": prompts[checked[0]],
                       "max_new_tokens": MAX_NEW})
        r_toks, r_final = sse_tokens(
            relaunched_port, {"prompt": prompts[checked[0]],
                              "max_new_tokens": MAX_NEW})
        assert s_toks == r_toks == results[checked[0]]["tokens"], (
            f"streamed relays diverged: {s_toks} vs {r_toks} vs "
            f"buffered {results[checked[0]]['tokens']}")
        assert s_final and s_final["finish_reason"] == "length"
        assert r_final and r_final["finish_reason"] == "length"

        # ---- gate 4: live affinity agreement — both doors (one of
        # them freshly relaunched with a cold replica view) route the
        # same keyed prompt to the same replica, with zero coordination
        probes = [np.concatenate(
            [rng.integers(0, e["vocab"], size=2 * chunk,
                          dtype=np.int32),
             rng.integers(0, e["vocab"], size=2, dtype=np.int32)]
            ).tolist() for _ in range(3)]
        disagreements = []
        for k, probe in enumerate(probes):
            a = post(survivor, {"prompt": probe,
                                "max_new_tokens": 1})
            b = post(relaunched_port, {"prompt": probe,
                                       "max_new_tokens": 1})
            if a.get("replica") != b.get("replica"):
                disagreements.append((k, a.get("replica"),
                                      b.get("replica")))
        assert not disagreements, (
            f"shared-nothing doors disagreed on affinity owners: "
            f"{disagreements}")

        # ---- forensics: the kill spent router:0's budget, nothing
        # else moved; the survivor harvested journaled progress
        state = load_state(job_dir / c.DRIVER_JOURNAL_FILE)
        r0_restarts = state.tasks["router:0"].restarts
        assert r0_restarts == 1, (
            f"router:0 restarts {r0_restarts} != 1")
        other = {tid: t.restarts for tid, t in state.tasks.items()
                 if tid != "router:0" and t.restarts}
        assert not other, f"collateral restarts: {other}"
        with urllib.request.urlopen(
                f"http://127.0.0.1:{survivor}/stats", timeout=10) as r:
            surv_stats = json.loads(r.read().decode())
        assert surv_stats["failed"] == 0, surv_stats
    finally:
        proc = client._driver_proc
        if proc is not None and proc.poll() is None:
            try:
                os.killpg(proc.pid, _signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
            try:
                proc.wait(timeout=30)
            except Exception:
                try:
                    os.killpg(proc.pid, _signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass

    smooth = [latencies[i] for i in latencies if i not in retried]
    disrupted = [latencies[i] for i in retried if i in latencies]
    p50_smooth = _stats.median(smooth)
    p50_disrupted = _stats.median(disrupted)
    out = {
        "metric": "router_ha_latency_cost",
        "value": round(p50_disrupted / p50_smooth, 2),
        "unit": "x p50 latency for requests that lost their front door "
                "(vs undisturbed; zero failed)",
        "doors": 2,
        "replicas": 2,
        "requests": N_REQUESTS,
        "failed_requests": 0,
        "rerouted_requests": len(retried),
        "byte_identical_reroutes_checked": len(checked),
        "streams_byte_identical": True,
        "affinity_agreement_probes": len(probes),
        "kill_at_request": KILL_AT,
        "router0_restarts": 1,
        "collateral_restarts": 0,
        "survivor_resumed_tokens": surv_stats.get("resumed_tokens", 0),
        "survivor_failed": 0,
        "p50_latency_s_undisturbed": round(p50_smooth, 3),
        "p50_latency_s_rerouted": round(p50_disrupted, 3),
        "p99_latency_s_rerouted": round(
            sorted(disrupted)[int(0.99 * (len(disrupted) - 1))], 3),
        "door_relaunch_s": round(
            marks["relaunched"] - marks["died"], 1),
        "burst_wall_s": round(marks["burst_done"] - t_burst, 1),
        "wall_s": round(time.time() - t_bench, 1),
    }
    print(json.dumps(out))
    return 0


def run_serving_spec_bench() -> int:
    """Speculative decoding inside continuous batching + multi-model
    hot-swap (one JSON line -> PERF.json `speculative_serving`).

    Arm A/B — spec off vs on, REAL acceptance: a target and a 12x-
    smaller draft are trained on the same Markov corpus (the bench_
    transformer speculative methodology: same-distribution alignment,
    not a modeled parameter), then the identical request burst serves
    through a plain SlotServer and a draft-speculating one. Gates:
    byte-identical completions (speculation is never a numerics
    change), >= 1.3x tokens/s, acceptance histogram populated.

    Arm C — multi-model + roll hot-swap: a serve subprocess registers
    TWO models, takes a concurrent two-model burst, and is SIGTERM-
    drained mid-burst (the PR 7 roll path) and relaunched with one
    model's checkpoint SWAPPED under the same name + the same journal
    dir. Clients retry through the roll; the gate is zero failed
    requests and both models serving after the swap."""
    import re as _re
    import signal as _signal
    import threading
    import urllib.request

    sys.path.insert(0, str(REPO))
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench_transformer import _markov_batch
    from tony_tpu.models import transformer
    from tony_tpu.models.generate import prepare_decode
    from tony_tpu.models.serving import Request, SlotServer
    from tony_tpu.parallel import MeshSpec, build_mesh
    from tony_tpu.train import create_train_step

    V = 1024
    # d512/L6: deep enough into the weight-streaming regime that the
    # (gamma+1)-wide verify genuinely amortizes the stream even on CPU
    # (at d384 the verify is compute-bound and the measured speedup sat
    # within noise of the 1.3x gate; at d512 the acceptance-0 floor
    # alone measures ~0.49x, putting full-acceptance headroom near 2x)
    cfg = transformer.TransformerConfig(
        vocab_size=V, d_model=512, n_layers=6, n_heads=8, n_kv_heads=8,
        d_ff=2048, max_seq_len=256, dtype=jnp.float32)
    draft_cfg = transformer.TransformerConfig(
        vocab_size=V, d_model=128, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=512, max_seq_len=256, dtype=jnp.float32)
    rng = np.random.default_rng(0)
    # 0.9-primary chain: predictable enough that a trained draft's
    # greedy continuation tracks the trained target's (the condition a
    # production draft/target pair has), noisy enough that nothing is
    # memorized verbatim
    succ = rng.integers(0, V, (V, 2)).astype(np.int32)

    def markov(r, batch, seq):
        x = np.empty((batch, seq + 1), np.int32)
        x[:, 0] = r.integers(0, V, batch)
        for t in range(seq):
            pick = r.random(batch) < 0.9
            x[:, t + 1] = np.where(pick, succ[x[:, t], 0],
                                   succ[x[:, t], 1])
        return x[:, :-1], x[:, 1:]

    def train(model_cfg, steps, seed):
        mesh = build_mesh(MeshSpec(data=-1, fsdp=1))
        bundle = create_train_step(model_cfg, mesh,
                                   key=jax.random.PRNGKey(seed))
        params, opt = bundle.params, bundle.opt_state
        r = np.random.default_rng(seed)
        m = None
        for chunk in range(steps // 50):
            for _ in range(50):
                tk, tg = markov(r, 8, 64)
                params, opt, m = bundle.step_fn(
                    params, opt, jnp.asarray(tk), jnp.asarray(tg))
            float(m["loss"])            # sync per 50-step window
        return params, float(m["loss"])

    t0 = time.time()
    tp_raw, t_loss = train(cfg, 300, seed=0)
    dp_raw, d_loss = train(draft_cfg, 300, seed=1)
    train_s = time.time() - t0
    tp = prepare_decode(tp_raw, cfg)
    dp = prepare_decode(dp_raw, draft_cfg)
    del tp_raw, dp_raw

    # held-out prompts from the same chain
    er = np.random.default_rng(99)
    prompts = [markov(er, 1, 32)[0][0] for _ in range(24)]
    budget = 48

    def serve_arm(draft=None, spec_gamma=0):
        kw = {}
        if draft is not None:
            # gamma ceiling 8: at the measured ~0.99 acceptance the
            # autotuner rides the ceiling, and the wider window is
            # where the weight-stream amortization pays (knob sweep:
            # 1.58x at gamma_max 4 -> 2.2x at 8). pipeline_depth 1:
            # speculation runs the sync (EOS-style) scheduler, where a
            # freed slot waits a full pipeline lag for re-admission —
            # at ~5 tokens/round that lag is whole requests, and CPU
            # compute is serial anyway so the deeper runway buys
            # nothing (plain predictive serving keeps its default).
            kw = dict(draft=draft, draft_cfg=draft_cfg,
                      spec_gamma=spec_gamma, spec_gamma_max=8,
                      pipeline_depth=1)
        srv = SlotServer(tp, cfg, slots=8, max_len=128, block_size=8,
                         prefill_chunk=32, **kw)

        def one_pass():
            reqs = [Request(prompt=p, max_new_tokens=budget)
                    for p in prompts]
            for r in reqs:
                srv.submit(r)
            t0 = time.time()
            done = srv.run_until_drained()
            wall = time.time() - t0
            toks = {i: done[r.id].tokens for i, r in enumerate(reqs)}
            n = sum(len(t) for t in toks.values())
            return n / wall, wall, toks

        one_pass()                      # compile + autotune warm-up
        best, best_wall, toks = 0.0, 0.0, None
        for _ in range(3):
            rate, wall, t = one_pass()
            if rate > best:
                best, best_wall, toks = rate, wall, t
        st = srv.stats()
        srv.shutdown()
        return {"tokens_per_sec": round(best, 1),
                "wall_s": round(best_wall, 3)}, toks, st

    plain, toks_plain, _ = serve_arm()
    spec, toks_spec, spec_st = serve_arm(draft=dp)
    assert toks_plain == toks_spec, (
        "speculation changed completions — the byte-identity contract "
        "is broken")
    speedup = round(spec["tokens_per_sec"] / plain["tokens_per_sec"], 3)
    sstats = spec_st["speculative"]
    assert sstats["acceptance"]["count"] > 0, (
        "acceptance histogram empty — the gate has nothing to stand on")
    assert speedup >= 1.3, (
        f"speculative serving speedup {speedup} < 1.3x gate "
        f"(acceptance_ewma {sstats['acceptance_ewma']})")
    # the honest worst case alongside: a random draft (~0 acceptance)
    # pays gamma draft steps per correction token — still byte-exact,
    # gamma pinned so the autotuner can't rescue the number
    dp0 = prepare_decode(
        jax.jit(lambda k: transformer.init(k, draft_cfg))(
            jax.random.PRNGKey(7)), draft_cfg)
    floor, toks_floor, floor_st = serve_arm(draft=dp0, spec_gamma=4)
    assert toks_floor == toks_plain, "floor arm broke byte-identity"

    # ---- arm C: multi-model serve + roll hot-swap, zero failed ----
    import socket

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def spawn_serve(port, trace_dir, main_spec):
        args = [sys.executable, "-m", "tony_tpu.cli.main", "serve",
                "--port", str(port), "--vocab", "256",
                "--d-model", "64", "--n-layers", "2", "--n-heads", "4",
                "--d-ff", "128", "--dtype", "float32",
                "--slots", "4", "--max-len", "64", "--block-size", "4",
                "--prefill-chunk", "8",
                "--model", f"main={main_spec}",
                "--model", "alt=random:7",
                "--trace-dir", str(trace_dir),
                "--drain-timeout-s", "60"]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(args, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)
        deadline = time.time() + 240
        while time.time() < deadline:
            line = proc.stdout.readline()
            if _re.search(r"http://[\d.]+:\d+", line or ""):
                threading.Thread(target=proc.stdout.read,
                                 daemon=True).start()
                return proc
        raise RuntimeError("serve never became ready")

    with tempfile.TemporaryDirectory(prefix="tony-spec-bench-") as td:
        port = free_port()
        proc = spawn_serve(port, td, "random:0")
        n_req, failed, succeeded = 24, [], []
        client_retries = [0]
        lock = threading.Lock()

        def call(i):
            model = "main" if i % 2 == 0 else "alt"
            body = json.dumps({
                "prompt": [(i * 7 + j) % 256 for j in range(6)],
                "max_new_tokens": 8, "model": model,
                "timeout_s": 240}).encode()
            deadline = time.time() + 240
            while time.time() < deadline:
                try:
                    req = urllib.request.Request(
                        f"http://127.0.0.1:{port}/generate", data=body)
                    with urllib.request.urlopen(req, timeout=240) as r:
                        json.loads(r.read())
                        with lock:
                            succeeded.append(i)
                        return
                except Exception:
                    # the roll window: refused/5xx/cut mid-request —
                    # the router would retry elsewhere; the bench
                    # client retries the same (only) endpoint
                    with lock:
                        client_retries[0] += 1
                    time.sleep(0.3)
            with lock:
                failed.append(i)

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(n_req)]
        t_roll0 = time.time()
        for i, t in enumerate(threads):
            t.start()
            if i == n_req // 3:
                # mid-burst: the roll (PR 7 semantics = SIGTERM drain;
                # in-flight finish, then the process exits cleanly)
                proc.send_signal(_signal.SIGTERM)
        proc.wait(timeout=300)
        # relaunch with main's checkpoint SWAPPED under the same name,
        # same journal dir (recovery finishes anything the drain cut)
        proc2 = spawn_serve(port, td, "random:5")
        for t in threads:
            t.join(timeout=300)
        roll_wall = time.time() - t_roll0
        # both models serve after the swap
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/stats", timeout=10) as r:
            st2 = json.loads(r.read())
        proc2.terminate()
        proc2.wait(timeout=60)
        assert not failed, f"roll dropped requests: {failed}"
        assert len(succeeded) == n_req
        assert set(st2["models"]) == {"main", "alt"}, st2.get("models")

    out = {
        "metric": "speculative_serving_speedup",
        "value": speedup,
        "unit": "x tokens/s vs spec-off serving",
        "target_params_m": round(
            transformer.num_params(tp.params) / 1e6, 1),
        "draft_params_m": round(
            transformer.num_params(dp.params) / 1e6, 1),
        "trained_on": f"markov chain V={V} (0.9 primary), 300 steps "
                      f"each (losses {t_loss:.3f} / {d_loss:.3f}, "
                      f"{train_s:.0f}s)",
        "byte_identical": True,
        "slots": 8,
        "n_requests": len(prompts),
        "budget": budget,
        "plain": plain,
        "speculative": spec,
        "gamma": sstats["gamma"],
        "gamma_autotuned": not sstats["gamma_pinned"],
        "acceptance_ewma": sstats["acceptance_ewma"],
        "accepted_tokens": sstats["accepted_tokens"],
        "proposed_tokens": sstats["proposed_tokens"],
        "verify_rounds": sstats["rounds"],
        "acceptance_zero_floor": {
            **floor,
            "ratio_vs_plain": round(
                floor["tokens_per_sec"] / plain["tokens_per_sec"], 3),
            "acceptance_ewma": floor_st["speculative"]["acceptance_ewma"],
        },
        "multi_model": {
            "requests": n_req,
            "failed": 0,
            "client_retries_through_roll": client_retries[0],
            "roll_wall_s": round(roll_wall, 1),
            "models_after_swap": sorted(st2["models"]),
            "swapped": "main random:0 -> random:5 (same name, same "
                       "journal dir, SIGTERM drain between)",
        },
        "num_devices": jax.device_count(),
    }
    print(json.dumps(out))
    return 0


def run_serving_robustness_bench(chaos: bool) -> int:
    """Overload + chaos serving benchmark (one JSON line; see module
    docstring). The submission burst is 64 requests against 8 slots and
    an 8-deep queue, so shedding MUST engage; with ``chaos`` the server
    additionally eats seeded injected dispatch failures at 5% per
    scheduling turn and must recover via SlotServer.reset() under the
    ServeApp restart budget. The bench enforces the acceptance
    invariants (zero hung waiters, every request terminates, recovery
    within budget) rather than just reporting them."""
    import statistics as _stats
    import threading
    import time as _time

    sys.path.insert(0, str(REPO))
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tony_tpu import constants as c
    from tony_tpu.models import transformer
    from tony_tpu.models.serving import (
        Completion, QueueFullError, Request, SlotServer,
    )

    cfg = transformer.TransformerConfig(
        vocab_size=2048, d_model=256, n_layers=4, n_heads=8, n_kv_heads=4,
        d_ff=1024, max_seq_len=512,
        dtype=jnp.bfloat16 if jax.default_backend() == "tpu"
        else jnp.float32,
    )
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    slots, max_len, max_queue = 8, 512, 8
    n_requests = 64
    fail_rate = 0.05 if chaos else 0.0
    prompt_lens = [16, 48, 96]
    budgets = [32, 64, 48, 24]
    rng = np.random.default_rng(3)
    prompts = [
        rng.integers(0, cfg.vocab_size, size=prompt_lens[i % len(prompt_lens)],
                     dtype=np.int32)
        for i in range(n_requests)
    ]

    # compile every program variant BEFORE injection turns on (the chaos
    # knobs are read at construction): the measured pass then exercises
    # scheduling + recovery, not XLA compilation
    warm = SlotServer(params, cfg, slots=slots, max_len=max_len,
                      block_size=16, prefill_chunk=64)
    for i in range(slots):
        warm.submit(Request(prompt=prompts[i], max_new_tokens=8))
    warm.run_until_drained()
    del warm    # the jit cache is what the warm-up buys; its KV ring
    #             would otherwise double serving HBM for the whole run

    knobs = {c.TEST_SERVING_DISPATCH_FAIL_RATE: str(fail_rate),
             c.TEST_SERVING_CHAOS_SEED: "1234"}
    saved = {k: os.environ.get(k) for k in knobs}
    os.environ.update(knobs)
    try:
        from tony_tpu.cli.serve import ServeApp

        srv = SlotServer(params, cfg, slots=slots, max_len=max_len,
                         block_size=16, prefill_chunk=64,
                         max_queue=max_queue)
        app = ServeApp(srv, max_loop_restarts=16, loop_backoff_s=0.05)
        app.start()
        results: dict[int, object] = {}
        latencies: dict[int, float] = {}

        def call(i):
            t0 = _time.time()
            try:
                comp = app.generate(prompts[i],
                                    budgets[i % len(budgets)], timeout=300)
                results[i] = comp
                latencies[i] = _time.time() - t0
            except Exception as e:      # shed / lost / expired
                results[i] = e

        t_start = _time.time()
        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(n_requests)]
        for t in threads:
            t.start()
            # sustained overload, not a one-shot firehose: arrivals spread
            # over ~2.5s against ~10s of service demand, so the queue
            # oscillates around full — some requests shed, most serve —
            # instead of 7/8 of the burst bouncing off a cold queue
            _time.sleep(0.04)
        for t in threads:
            t.join(timeout=600)
        wall = _time.time() - t_start
        hung = sum(t.is_alive() for t in threads)
        app.shutdown()
    finally:
        for k, v in saved.items():
            os.environ.pop(k, None) if v is None else os.environ.update(
                {k: v})

    completed = {i: r for i, r in results.items()
                 if isinstance(r, Completion)}
    shed = sum(isinstance(r, QueueFullError) for r in results.values())
    expired = sum(isinstance(r, TimeoutError) for r in results.values())
    failed = (len(results) - len(completed) - shed - expired)
    goodput_tokens = sum(len(r.tokens) for r in completed.values())
    # the acceptance invariants, enforced: a bench that silently records
    # a hang would grade the exact failure this harness exists to catch
    assert hung == 0, f"{hung} waiters hung"
    assert len(results) == n_requests, "a request vanished without outcome"
    assert app.status != "down", "restart budget exhausted mid-bench"
    if chaos:
        assert srv.chaos_faults_injected >= 1, "chaos never fired"
        assert app.loop_restarts >= 1, "no recovery exercised"
    out = {
        "metric": "serving_robustness_goodput_tokens_per_sec",
        "value": round(goodput_tokens / wall, 1),
        "unit": "tokens/s of COMPLETED requests, chaos+overload included",
        "chaos": chaos,
        "dispatch_fail_rate": fail_rate,
        "chaos_seed": 1234,
        "slots": slots,
        "max_queue": max_queue,
        "submitted": n_requests,
        "completed": len(completed),
        "shed_429": shed,
        "failed_loop_error": failed,
        "expired_or_timed_out": expired,
        "hung_waiters": hung,
        "every_request_terminated": True,
        "p50_latency_s_completed": round(
            _stats.median(latencies.values()), 3) if latencies else None,
        "wall_s": round(wall, 3),
        "chaos_faults_injected": srv.chaos_faults_injected,
        "loop_failures": app.loop_failures,
        "loop_restarts": app.loop_restarts,
        "engine_resets": srv.resets,
        "cancelled": srv.cancelled_requests,
        "num_devices": jax.device_count(),
    }
    print(json.dumps(out))
    return 0


def run_serving_replay_bench() -> int:
    """Request-durability gate (one JSON line -> PERF.json
    `serving_replay`; docs/serving.md "Request durability & replay").
    Three arms, invariants ENFORCED rather than reported:

    A) **Loop-crash replay** (in-process): an uninterrupted run is the
       byte-reference; a second run eats two DETERMINISTIC mid-decode
       loop crashes (TONY_TEST_SERVING_CRASH_AT_BLOCKS) and must
       deliver ZERO failed requests with byte-identical completions,
       with replay recompute bounded by one re-prefill of
       prompt+emitted per replay (the prefix is never re-decoded).
    B) **Fail-fast preserved**: the same crash with replay disabled
       must FAIL the in-flight set (the pre-journal contract) — the
       journal-off path keeps its semantics.
    C) **Fleet SIGKILL failover + journal recovery** (subprocess): two
       TINY serve replicas with file journals behind a FleetRouter;
       one replica is SIGKILLed with requests in flight — zero failed
       requests, byte-identical to an in-process reference, at least
       one resume-carrying failover — and the killed replica
       RESTARTED against the same --trace-dir recovers its journal and
       finishes the orphaned requests (stats replays >= 1,
       attrs.recovered_from in its trace file).
    """
    import re as _re
    import signal as _signal
    import subprocess
    import threading
    import urllib.request

    sys.path.insert(0, str(REPO))
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tony_tpu import constants as c
    from tony_tpu.models import transformer
    from tony_tpu.models.serving import Completion, Request, SlotServer

    # ---- arm A/B: in-process loop-crash replay (robustness shape) ----
    cfg = transformer.TransformerConfig(
        vocab_size=2048, d_model=256, n_layers=4, n_heads=8, n_kv_heads=4,
        d_ff=1024, max_seq_len=512,
        dtype=jnp.bfloat16 if jax.default_backend() == "tpu"
        else jnp.float32,
    )
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    slots, max_len, n_requests = 8, 512, 16
    rng = np.random.default_rng(7)
    prompt_lens = [16, 48, 96]
    # MIXED budgets: short requests complete early, which forces the
    # open-loop pipeline to process — so the journal holds PARTIAL
    # emitted prefixes for the long requests when the crash lands, and
    # the replay arm demonstrably carries tokens across the boundary
    budgets = [16, 64, 32, 48]
    prompts = [rng.integers(0, cfg.vocab_size,
                            size=prompt_lens[i % len(prompt_lens)],
                            dtype=np.int32)
               for i in range(n_requests)]
    srv_kw = dict(slots=slots, max_len=max_len, block_size=16,
                  prefill_chunk=64)

    def run_arm(extra_env: dict, replay: bool):
        from tony_tpu.cli.serve import ServeApp, ServingLoopError

        saved = {k: os.environ.get(k) for k in extra_env}
        os.environ.update(extra_env)
        try:
            srv = SlotServer(params, cfg, replay=replay, **srv_kw)
            app = ServeApp(srv, max_loop_restarts=16, loop_backoff_s=0.02)
            app.start()
            results: dict[int, object] = {}

            def call(i):
                try:
                    results[i] = app.generate(
                        prompts[i], budgets[i % len(budgets)],
                        timeout=600)
                except Exception as e:
                    results[i] = e

            t0 = time.time()
            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(n_requests)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=900)
            wall = time.time() - t0
            assert not any(t.is_alive() for t in threads), "hung waiters"
            app.shutdown()
            return srv, app, results, wall
        finally:
            for k, v in saved.items():
                (os.environ.pop(k, None) if v is None
                 else os.environ.update({k: v}))

    # byte-reference: uninterrupted
    ref_srv, _, ref_results, ref_wall = run_arm({}, replay=True)
    assert all(isinstance(r, Completion) for r in ref_results.values())
    refs = {i: ref_results[i].tokens for i in range(n_requests)}

    # arm A: two mid-decode crashes, journal ON — ordinals deep enough
    # that short requests have completed (their processing revealed the
    # long requests' partial prefixes to the journal)
    srv, app, results, crash_wall = run_arm(
        {c.TEST_SERVING_CRASH_AT_BLOCKS: "3,7"}, replay=True)
    failed = [i for i, r in results.items()
              if not isinstance(r, Completion)]
    assert not failed, f"replay arm failed requests: {failed}"
    mismatched = [i for i in range(n_requests)
                  if results[i].tokens != refs[i]]
    assert not mismatched, f"replay diverged on requests: {mismatched}"
    assert srv.chaos_faults_injected == 2 and app.loop_restarts >= 1
    assert srv.replays >= 1, "crashes hit in-flight work; must replay"
    # recompute bound: the extra prefill vs the uninterrupted run is at
    # most one prompt+prefix re-prefill per replay — the emitted prefix
    # re-prefills, it is NEVER re-decoded
    extra_prefill = (srv.prefill_tokens_computed
                     - ref_srv.prefill_tokens_computed)
    bound = srv.replays * max(len(p) for p in prompts) \
        + srv.replayed_tokens
    assert extra_prefill <= bound, (
        f"replay recompute {extra_prefill} exceeds the "
        f"prompt+emitted-prefix bound {bound}")

    # arm B: same crash, replay OFF -> fail-fast preserved
    from tony_tpu.cli.serve import ServingLoopError

    srv_off, app_off, results_off, _ = run_arm(
        {c.TEST_SERVING_CRASH_AT_BLOCKS: "2"}, replay=False)
    failed_off = [i for i, r in results_off.items()
                  if isinstance(r, ServingLoopError)]
    assert failed_off, (
        "journal-off crash must fail the in-flight set (fail-fast)")
    assert srv_off.replays == 0

    # ---- arm C: fleet SIGKILL failover + journal recovery ----
    import tempfile as _tempfile

    from tony_tpu.router import FleetRouter

    tiny = dict(vocab=256, d_model=64, n_layers=2, n_heads=4, d_ff=128)
    t_slots, t_max_len, t_chunk, t_block = 4, 128, 8, 4
    t_requests = 12
    # mixed budgets: early completions force the open-loop pipeline to
    # process, revealing the long requests' partial prefixes to the
    # journal (same trick as arm A) — so the /progress polls have real
    # prefixes to journal before the kill
    t_budgets = [16, 48, 32, 64]
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           # slow each scheduling turn so the burst stays in flight
           # long enough for progress polls + a mid-decode kill (the
           # TINY model would otherwise drain the burst in a beat)
           "TONY_TEST_SERVING_STEP_DELAY_MS": "25"}
    env.pop("XLA_FLAGS", None)

    tiny_cfg = transformer.TransformerConfig(
        vocab_size=tiny["vocab"], d_model=tiny["d_model"],
        n_layers=tiny["n_layers"], n_heads=tiny["n_heads"],
        n_kv_heads=tiny["n_heads"], d_ff=tiny["d_ff"],
        dtype=jnp.float32)
    tiny_params = transformer.init(jax.random.PRNGKey(0), tiny_cfg)
    t_rng = np.random.default_rng(11)
    template = t_rng.integers(0, tiny["vocab"], size=t_chunk,
                              dtype=np.int32)
    t_prompts = [np.concatenate(
        [template, t_rng.integers(0, tiny["vocab"], size=2 + i % 5,
                                  dtype=np.int32)]).tolist()
        for i in range(t_requests)]
    ref2_srv = SlotServer(tiny_params, tiny_cfg, slots=t_slots,
                          max_len=t_max_len, block_size=t_block,
                          prefill_chunk=t_chunk)
    ref2_reqs = [Request(prompt=p,
                         max_new_tokens=t_budgets[i % len(t_budgets)])
                 for i, p in enumerate(t_prompts)]
    for r in ref2_reqs:
        ref2_srv.submit(r)
    ref2_done = ref2_srv.run_until_drained()
    t_refs = [ref2_done[r.id].tokens for r in ref2_reqs]

    class Srv:
        def __init__(self, name, trace_dir):
            self.name, self.trace_dir = name, trace_dir
            self.proc = self.port = None
            self.spawn()

        def spawn(self):
            self.proc = subprocess.Popen(
                [sys.executable, "-m", "tony_tpu.cli.main", "serve",
                 "--port", "0", "--vocab", str(tiny["vocab"]),
                 "--d-model", str(tiny["d_model"]),
                 "--n-layers", str(tiny["n_layers"]),
                 "--n-heads", str(tiny["n_heads"]),
                 "--d-ff", str(tiny["d_ff"]), "--dtype", "float32",
                 "--seed", "0", "--slots", str(t_slots),
                 "--max-len", str(t_max_len),
                 "--block-size", str(t_block),
                 "--prefill-chunk", str(t_chunk),
                 "--trace-dir", self.trace_dir],
                cwd=REPO, env=env, text=True,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
            self.port = None

        def await_ready(self, timeout=240.0):
            deadline = time.time() + timeout
            while self.port is None and time.time() < deadline:
                line = self.proc.stdout.readline()
                m = _re.search(r"http://[\d.]+:(\d+)", line or "")
                if m:
                    self.port = int(m.group(1))
            assert self.port, f"{self.name} never printed its port"
            threading.Thread(target=self.proc.stdout.read,
                             daemon=True).start()
            while time.time() < deadline:
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{self.port}/healthz",
                            timeout=2) as r:
                        if r.status == 200:
                            return
                except Exception:
                    time.sleep(0.2)
            raise AssertionError(f"{self.name} never became healthy")

        def stats(self):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{self.port}/stats",
                    timeout=10) as r:
                return json.loads(r.read().decode())

        def stop(self):
            if self.proc.poll() is None:
                self.proc.kill()
            self.proc.wait(timeout=15)

    td = _tempfile.mkdtemp(prefix="tony-replay-bench-")
    reps = [Srv("a", os.path.join(td, "a")),
            Srv("b", os.path.join(td, "b"))]
    router = None
    try:
        for rep in reps:
            rep.await_ready()
        router = FleetRouter(
            [(rep.name, "127.0.0.1", rep.port) for rep in reps],
            prefill_chunk=t_chunk, health_interval_s=0.15,
            stats_every=2, seed=0)
        router.start()
        fleet_results: dict[int, object] = {}

        def call2(i):
            try:
                fleet_results[i] = router.generate(
                    t_prompts[i],
                    max_new_tokens=t_budgets[i % len(t_budgets)],
                    timeout_s=300)
            except Exception as e:
                fleet_results[i] = e

        t0 = time.time()
        threads = [threading.Thread(target=call2, args=(i,))
                   for i in range(t_requests)]
        for t in threads:
            t.start()
            time.sleep(0.03)
        # kill the affinity-sticky replica once it genuinely has this
        # burst's requests in flight (the template keys every request to
        # ONE replica, so the kill always interrupts real decode work)
        # ... ideally once the health loop's /progress polls have also
        # journaled a nonempty emitted prefix, so the failover
        # demonstrably CARRIES tokens — bounded wait; having ANY
        # outstanding work is the hard requirement, the prefix is
        # opportunistic (compile warm-up emits nothing for a while)
        victim = None
        deadline = time.time() + 60
        prefix_deadline = time.time() + 20
        while time.time() < deadline:
            with router._lock:
                names = set(router._outstanding.values())
                have_prefix = any(router._resume.values())
            cand = next((rep for rep in reps if rep.name in names), None)
            if cand is not None:
                victim = cand
                if have_prefix or time.time() >= prefix_deadline:
                    break
            time.sleep(0.02)
        assert victim is not None, "no request ever went in flight"
        victim_pid = victim.stats()["pid"]
        os.kill(victim_pid, _signal.SIGKILL)
        for t in threads:
            t.join(timeout=600)
        fleet_wall = time.time() - t0
        assert not any(t.is_alive() for t in threads), "hung callers"
        fleet_failed = [i for i, r in fleet_results.items()
                        if not isinstance(r, dict)]
        assert not fleet_failed, (
            f"fleet SIGKILL arm failed requests: "
            f"{[(i, fleet_results[i]) for i in fleet_failed]}")
        fleet_mismatch = [i for i in range(t_requests)
                          if fleet_results[i]["tokens"] != t_refs[i]]
        assert not fleet_mismatch, (
            f"fleet failover diverged on requests: {fleet_mismatch}")
        rstats = router.stats()
        assert rstats["failed"] == 0
        assert rstats["failovers"] >= 1, (
            "the SIGKILL interrupted in-flight work; failover must fire")

        # the killed replica restarts against the SAME trace dir and
        # finishes the orphaned requests from its file journal
        victim.stop()
        victim.spawn()
        victim.await_ready()
        deadline = time.time() + 300
        recovered_stats = None
        while time.time() < deadline:
            st = victim.stats()
            if (st.get("replays", 0) >= 1
                    and st.get("journal", {}).get("entries", 1) == 0
                    and st.get("active", 1) == 0):
                recovered_stats = st
                break
            time.sleep(0.25)
        assert recovered_stats is not None, (
            "restarted replica never finished its journal recovery")
        from tony_tpu.events.trace import read_traces

        recs = read_traces(os.path.join(victim.trace_dir,
                                        "requests.trace.jsonl"))
        recovered = [r for r in recs
                     if r["attrs"].get("recovered_from") is not None
                     and r["spans"] and r["spans"][-1][0] == "finished"]
        assert recovered, "no recovered_from trace in the restarted replica"
    finally:
        if router is not None:
            router.shutdown()
        for rep in reps:
            try:
                rep.stop()
            except Exception:
                pass

    out = {
        "metric": "serving_replay_zero_failed_requests",
        "value": 0,
        "unit": "failed requests across loop-crash and replica-SIGKILL "
                "arms (byte-identical completions enforced)",
        "loop_crash": {
            "requests": n_requests,
            "crashes_injected": srv.chaos_faults_injected,
            "loop_restarts": app.loop_restarts,
            "replays": srv.replays,
            "replayed_tokens": srv.replayed_tokens,
            "byte_identical": True,
            "replay_recompute_prefill_tokens": int(extra_prefill),
            "replay_recompute_bound": int(bound),
            "extra_decode_blocks": int(srv.blocks_dispatched
                                       - ref_srv.blocks_dispatched),
            "uninterrupted_wall_s": round(ref_wall, 3),
            "crash_wall_s": round(crash_wall, 3),
            "replay_catchup_p99_s": round(
                srv.telemetry.hist["replay_catchup_s"].quantile(0.99), 3),
        },
        "fail_fast_preserved": {
            "replay_off_failed_requests": len(failed_off),
            "replays": srv_off.replays,
        },
        "fleet_sigkill": {
            "requests": t_requests,
            "failed": 0,
            "byte_identical": True,
            "router_failovers": rstats["failovers"],
            "resumed_tokens": rstats["resumed_tokens"],
            "wall_s": round(fleet_wall, 3),
            "restart_recovered_requests": len(recovered),
            "restart_replays": recovered_stats["replays"],
        },
        "num_devices": jax.device_count(),
    }
    print(json.dumps(out))
    return 0


def run_distributed_tracing_bench() -> int:
    """Distributed-tracing gate (one JSON line -> PERF.json
    `distributed_tracing`; docs/observability.md "Distributed
    tracing"). Runs the disagg + router-SIGKILL story end to end: a
    prefill + a decode replica (--paged-kv) behind two router front
    doors, all four processes dumping --trace-dir JSONL; door 0 is
    SIGKILLed upon receiving its Nth /generate mid-burst, clients
    re-POST the same request_id at door 1, and the bench merges every
    tier's trace file with TraceCollector and enforces the four gates
    documented in docs/observability.md "Distributed tracing"."""
    import re as _re
    import tempfile as _tempfile
    import threading
    import urllib.error
    import urllib.request

    import jax
    import numpy as np

    from tony_tpu import constants as c
    from tony_tpu.events.trace import (
        TRACE_FILE,
        TraceCollector,
        coverage_s,
    )
    from tony_tpu.observability import (
        TRACE_ID_RESPONSE_HEADER,
        TraceContext,
    )

    e = dict(vocab=64, d_model=16, n_layers=1, n_heads=2, d_ff=32)
    SLOTS, MAX_LEN, CHUNK, BLOCK = 4, 96, 8, 4
    N_REQUESTS, MAX_NEW, KILL_AT = 24, 8, 8
    STEP_DELAY_MS = 40      # slow decode so the kill hits in-flight work
    STAGGER_S = 0.02        # burst spacing: #KILL_AT arrives ~0.15s in
    DEADLINE_S = 240.0
    # the documented bound on e2e time the merged span tree may leave
    # unaccounted: client->door network, the dead door's pre-relay
    # work, and the failover client's detect+re-POST beat
    GAP_BOUND_S = 2.0

    rng = np.random.default_rng(17)
    prompts = [rng.integers(0, e["vocab"], size=10 + i % 6,
                            dtype=np.int32).tolist()
               for i in range(N_REQUESTS)]

    base_env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    base_env.pop("XLA_FLAGS", None)
    base_env.pop(c.TEST_ROUTER_SIGKILL_AT_REQUEST, None)
    serve_env = {**base_env,
                 c.TEST_SERVING_STEP_DELAY_MS: str(STEP_DELAY_MS)}

    td = _tempfile.mkdtemp(prefix="tony-tracing-bench-")

    class Proc:
        """One tier process (serve replica or route front door); both
        print their endpoint as '... on http://host:port ...'."""

        def __init__(self, name, argv, env):
            self.name = name
            self.trace_dir = os.path.join(td, name)
            self.proc = subprocess.Popen(
                argv + ["--trace-dir", self.trace_dir],
                cwd=REPO, env=env, text=True,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
            self.port = None

        def await_ready(self, timeout=240.0):
            deadline = time.time() + timeout
            while self.port is None and time.time() < deadline:
                line = self.proc.stdout.readline()
                if line == "" and self.proc.poll() is not None:
                    break
                m = _re.search(r" on http://[\d.]+:(\d+)", line or "")
                if m:
                    self.port = int(m.group(1))
            assert self.port, f"{self.name} never printed its endpoint"
            threading.Thread(target=self.proc.stdout.read,
                             daemon=True).start()
            while time.time() < deadline:
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{self.port}/healthz",
                            timeout=2) as r:
                        if r.status == 200:
                            return
                except Exception:
                    pass        # 503 until the fleet is in rotation
                time.sleep(0.2)
            raise AssertionError(f"{self.name} never became healthy")

        def get_json(self, path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{self.port}{path}",
                    timeout=10) as r:
                return json.loads(r.read().decode())

        def stop(self):
            if self.proc.poll() is None:
                self.proc.kill()
            self.proc.wait(timeout=15)

    def serve_argv(role):
        return [sys.executable, "-m", "tony_tpu.cli.main", "serve",
                "--port", "0", "--vocab", str(e["vocab"]),
                "--d-model", str(e["d_model"]),
                "--n-layers", str(e["n_layers"]),
                "--n-heads", str(e["n_heads"]),
                "--d-ff", str(e["d_ff"]), "--dtype", "float32",
                "--seed", "0", "--slots", str(SLOTS),
                "--max-len", str(MAX_LEN), "--block-size", str(BLOCK),
                "--prefill-chunk", str(CHUNK),
                "--paged-kv", "--role", role]

    def route_argv(replicas):
        argv = [sys.executable, "-m", "tony_tpu.cli.main", "route",
                "--port", "0", "--prefill-chunk", str(CHUNK),
                "--health-interval-s", "0.15", "--stats-every", "1"]
        for rep in replicas:
            argv += ["--replica", f"127.0.0.1:{rep.port}"]
        return argv

    reps = doors = []
    results: dict[int, object] = {}
    try:
        reps = [Proc("prefill", serve_argv("prefill"), serve_env),
                Proc("decode", serve_argv("decode"), serve_env)]
        for rep in reps:
            rep.await_ready()
        doors = [
            Proc("door0", route_argv(reps),
                 {**base_env,
                  c.TEST_ROUTER_SIGKILL_AT_REQUEST: str(KILL_AT)}),
            Proc("door1", route_argv(reps), base_env)]
        for door in doors:
            door.await_ready()
        # both doors must have POLLED the replicas' role advertisements
        # before the burst, or the early requests route classically and
        # the disagg story never runs
        for door in doors:
            deadline = time.time() + 60
            while time.time() < deadline:
                st = door.get_json("/stats")
                roles = {r.get("role")
                         for r in st["replicas"].values()
                         if r.get("up")}
                if {"prefill", "decode"} <= roles:
                    break
                time.sleep(0.1)
            else:
                raise AssertionError(
                    f"{door.name} never discovered both roles")

        def post(door, body, timeout):
            req = urllib.request.Request(
                f"http://127.0.0.1:{door.port}/generate",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return (json.loads(r.read().decode()),
                        r.headers.get(TRACE_ID_RESPONSE_HEADER))

        # warm both legs' compiles through door 1 so the timed burst
        # (and its kill window) isn't dominated by first-call tracing;
        # warmup trace_ids are distinct so the gates ignore them
        post(doors[1], {"prompt": prompts[0], "max_new_tokens": MAX_NEW,
                        "timeout_s": DEADLINE_S,
                        "request_id": "warmup-0"}, DEADLINE_S)

        def call(i):
            body = {"prompt": prompts[i], "max_new_tokens": MAX_NEW,
                    "timeout_s": DEADLINE_S,
                    "request_id": f"burst-{i}"}
            t0 = time.time()
            attempt = 0
            while True:
                door = doors[attempt % 2]   # door 0 first, then flip
                try:
                    resp, tid = post(door, body,
                                     max(1.0, t0 + DEADLINE_S
                                         - time.time()))
                    results[i] = {"resp": resp, "trace_id": tid,
                                  "e2e_s": time.time() - t0}
                    return
                except Exception as err:
                    attempt += 1
                    if time.time() - t0 > DEADLINE_S:
                        results[i] = err
                        return
                    time.sleep(0.25)

        t0 = time.time()
        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(N_REQUESTS)]
        for t in threads:
            t.start()
            time.sleep(STAGGER_S)
        for t in threads:
            t.join(timeout=600)
        burst_wall = time.time() - t0
        assert not any(t.is_alive() for t in threads), "hung callers"
        assert doors[0].proc.poll() is not None, (
            "door 0 survived its SIGKILL injection")
        failed = [i for i, r in results.items()
                  if not isinstance(r, dict)]
        assert not failed, (
            f"failed requests: {[(i, results[i]) for i in failed]}")

        # drain the orphans: the dead door's relays keep decoding on
        # the replicas and must SEAL their spans before the sweep
        deadline = time.time() + 120
        while time.time() < deadline:
            if all(rep.get_json("/stats").get("active", 1) == 0
                   for rep in reps):
                break
            time.sleep(0.25)

        leg_counts = {m.group(1): int(m.group(2)) for m in _re.finditer(
            r'router_leg_seconds_count\{leg="(\w+)"\} (\d+)',
            urllib.request.urlopen(
                f"http://127.0.0.1:{doors[1].port}/metrics",
                timeout=10).read().decode())}
    finally:
        for p in list(doors) + list(reps):
            try:
                p.stop()
            except Exception:
                pass

    # ---- the merge + the four gates ----
    collector = TraceCollector()
    for name in ("prefill", "decode", "door0", "door1"):
        path = os.path.join(td, name, TRACE_FILE)
        if os.path.exists(path):
            collector.add_file(path)
    assert collector.files_read == 4, (
        f"expected 4 tier trace files, read {collector.files_read}")
    merged = collector.merged()

    # gate 1: every completed request -> exactly ONE merged trace,
    # keyed by the deterministic request_id-derived trace_id that the
    # front door's response header echoed back
    expected = {i: TraceContext.for_request_id(f"burst-{i}").trace_id
                for i in range(N_REQUESTS)}
    bad_echo = [i for i in range(N_REQUESTS)
                if results[i]["trace_id"] != expected[i]]
    assert not bad_echo, (
        f"response header trace_id mismatch on requests: {bad_echo}")
    missing = [i for i in range(N_REQUESTS)
               if expected[i] not in merged]
    assert not missing, f"no merged trace for requests: {missing}"
    burst = {i: merged[expected[i]] for i in range(N_REQUESTS)}

    # gate 2: zero orphan spans — every span's parent produced a
    # record, INCLUDING children of the SIGKILLed door (its write-ahead
    # open records are the parents)
    orphans = sum(len(t["orphans"]) for t in burst.values())
    assert orphans == 0, (
        f"{orphans} orphan spans: "
        f"{[(i, t['orphans']) for i, t in burst.items() if t['orphans']]}")

    # gate 3: the failover story is VISIBLE — >= 1 trace carries router
    # spans from two distinct door nonces (door 0's unsealed open
    # record + door 1's sealed relay), and the dead door left >= 1
    # unsealed span for the merge to surface
    def routers_of(trace):
        return {s["attrs"].get("router") for s in trace["spans"]
                if s["attrs"].get("service") == "router"} - {None}

    two_door = [i for i, t in burst.items() if len(routers_of(t)) >= 2]
    assert two_door, ("no trace shows both doors: the kill either hit "
                      "an idle door or the open records were lost")
    unsealed = sum(
        1 for t in burst.values() for s in t["spans"]
        if s["attrs"].get("service") == "router"
        and s["terminal"] is None)
    assert unsealed >= 1, "the SIGKILLed door left no unsealed span"
    assert collector.superseded >= 1, (
        "no open record was superseded by its sealed twin; the "
        "write-ahead path is not exercising the merge fence")

    # the disagg handoff is ONE trace: the prefill leg (a serve span
    # finishing "prefilled") and the decode import leg (a serve span
    # with imported_blocks) both sit under a single trace_id
    def disagg_legs(trace):
        serves = [s["attrs"] for s in trace["spans"]
                  if s["attrs"].get("service") == "serve"]
        return (any(a.get("finish_reason") == "prefilled"
                    for a in serves)
                and any(a.get("imported_blocks") for a in serves))

    disagg_traces = [i for i, t in burst.items() if disagg_legs(t)]
    assert disagg_traces, "no trace spans both disagg replicas"
    assert leg_counts.get("prefill", 0) >= 1, leg_counts
    assert leg_counts.get("decode", 0) >= 1, leg_counts

    # gate 4: the span-union coverage accounts for the client-observed
    # e2e within the documented bound (failover detect+re-POST and
    # client->door network are the only permitted dark time)
    gaps = {i: results[i]["e2e_s"] - coverage_s(burst[i])
            for i in range(N_REQUESTS)}
    max_gap = max(gaps.values())
    assert max_gap <= GAP_BOUND_S, (
        f"unaccounted e2e gap {max_gap:.3f}s exceeds the "
        f"{GAP_BOUND_S}s bound: {sorted(gaps.items(), key=lambda kv: -kv[1])[:4]}")

    out = {
        "metric": "distributed_tracing_one_trace_per_request",
        "value": len(burst),
        "unit": "merged cross-tier traces for a 24-request disagg "
                "burst surviving a router SIGKILL (exactly one per "
                "completed request)",
        "requests": N_REQUESTS,
        "failed": 0,
        "trace_files_merged": collector.files_read,
        "spans_total": sum(len(t["spans"]) for t in burst.values()),
        "orphan_spans": 0,
        "header_echo_verified": True,
        "failover_two_door_traces": len(two_door),
        "unsealed_router_spans": unsealed,
        "superseded_open_records": collector.superseded,
        "torn_or_identityless_skipped": collector.skipped,
        "disagg_two_replica_traces": len(disagg_traces),
        "router_leg_counts": leg_counts,
        "max_unaccounted_gap_s": round(max_gap, 3),
        "gap_bound_s": GAP_BOUND_S,
        "burst_wall_s": round(burst_wall, 3),
        "num_devices": jax.device_count(),
    }
    print(json.dumps(out))
    return 0


def run_serving_streaming_bench() -> int:
    """Streaming-serving gate (one JSON line -> PERF.json
    `streaming_serving`; docs/serving.md "Streaming & OpenAI
    compatibility"). An open-loop POISSON arrival process at fleet
    scale, every request streamed per-token through the router, with
    one mid-stream replica SIGKILL. ENFORCED invariants:

    - zero failed requests (the kill becomes latency via router
      stream-failover, never an error);
    - every request's CONCATENATED stream is byte-identical to the
      non-streamed greedy completion (in-process SlotServer reference)
      — including the requests whose stream moved replicas mid-flight;
    - at least one stream failover actually fired (the kill landed on
      live streams) with the resume prefix harvested from the stream;
    - per-token inter-token-latency quantiles measured CLIENT-side
      (per-token arrival timestamps; tokens of one SSE chunk share an
      arrival instant, so intra-chunk gaps are genuine zeros).
    """
    import re as _re
    import signal as _signal
    import subprocess
    import threading
    import urllib.request

    sys.path.insert(0, str(REPO))
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tony_tpu.models import transformer
    from tony_tpu.models.serving import Request, SlotServer
    from tony_tpu.router import FleetRouter

    tiny = dict(vocab=256, d_model=64, n_layers=2, n_heads=4, d_ff=128)
    slots, max_len, chunk, block = 4, 128, 8, 4
    n_requests = 24
    budgets = [16, 48, 32, 64]
    mean_interarrival_s = 0.08          # open-loop Poisson, seeded
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           # slow each scheduling turn so streams stay live long enough
           # for a genuinely MID-stream kill on the TINY model
           "TONY_TEST_SERVING_STEP_DELAY_MS": "20"}
    env.pop("XLA_FLAGS", None)

    cfg = transformer.TransformerConfig(
        vocab_size=tiny["vocab"], d_model=tiny["d_model"],
        n_layers=tiny["n_layers"], n_heads=tiny["n_heads"],
        n_kv_heads=tiny["n_heads"], d_ff=tiny["d_ff"], dtype=jnp.float32)
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(17)
    template = rng.integers(0, tiny["vocab"], size=chunk, dtype=np.int32)
    prompts = [np.concatenate(
        [template, rng.integers(0, tiny["vocab"], size=2 + i % 5,
                                dtype=np.int32)]).tolist()
        for i in range(n_requests)]
    arrivals = np.cumsum(rng.exponential(mean_interarrival_s,
                                         size=n_requests))

    # non-streamed greedy reference: the byte-identity target
    ref_srv = SlotServer(params, cfg, slots=slots, max_len=max_len,
                         block_size=block, prefill_chunk=chunk)
    ref_reqs = [Request(prompt=p,
                        max_new_tokens=budgets[i % len(budgets)])
                for i, p in enumerate(prompts)]
    for r in ref_reqs:
        ref_srv.submit(r)
    ref_done = ref_srv.run_until_drained()
    refs = [ref_done[r.id].tokens for r in ref_reqs]

    class Srv:
        def __init__(self, name):
            self.name = name
            self.proc = self.port = None
            self.proc = subprocess.Popen(
                [sys.executable, "-m", "tony_tpu.cli.main", "serve",
                 "--port", "0", "--vocab", str(tiny["vocab"]),
                 "--d-model", str(tiny["d_model"]),
                 "--n-layers", str(tiny["n_layers"]),
                 "--n-heads", str(tiny["n_heads"]),
                 "--d-ff", str(tiny["d_ff"]), "--dtype", "float32",
                 "--seed", "0", "--slots", str(slots),
                 "--max-len", str(max_len), "--block-size", str(block),
                 "--prefill-chunk", str(chunk)],
                cwd=REPO, env=env, text=True,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

        def await_ready(self, timeout=240.0):
            deadline = time.time() + timeout
            while self.port is None and time.time() < deadline:
                line = self.proc.stdout.readline()
                m = _re.search(r"http://[\d.]+:(\d+)", line or "")
                if m:
                    self.port = int(m.group(1))
            assert self.port, f"{self.name} never printed its port"
            threading.Thread(target=self.proc.stdout.read,
                             daemon=True).start()
            while time.time() < deadline:
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{self.port}/healthz",
                            timeout=2) as r:
                        if r.status == 200:
                            return
                except Exception:
                    time.sleep(0.2)
            raise AssertionError(f"{self.name} never became healthy")

        def pid(self):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{self.port}/stats",
                    timeout=10) as r:
                return json.loads(r.read().decode())["pid"]

        def stop(self):
            if self.proc.poll() is None:
                self.proc.kill()
            self.proc.wait(timeout=15)

    reps = [Srv("a"), Srv("b")]
    router = None
    try:
        for rep in reps:
            rep.await_ready()
        router = FleetRouter(
            [(rep.name, "127.0.0.1", rep.port) for rep in reps],
            prefill_chunk=chunk, health_interval_s=0.15, stats_every=2,
            seed=0)
        router.start()

        # warm both replicas' compiled programs off the clock
        for rep_i in range(2):
            router.generate(prompts[rep_i], max_new_tokens=4,
                            timeout_s=300)

        results: dict[int, object] = {}
        stamps: dict[int, list[float]] = {}     # per-token arrival t

        def call(i, delay):
            time.sleep(delay)
            ts = stamps[i] = []

            def on_tokens(toks):
                now = time.monotonic()
                ts.extend([now] * len(toks))

            try:
                results[i] = router.generate(
                    prompts[i],
                    max_new_tokens=budgets[i % len(budgets)],
                    timeout_s=600, on_tokens=on_tokens)
            except Exception as e:
                results[i] = e

        t0 = time.time()
        threads = [threading.Thread(target=call,
                                    args=(i, float(arrivals[i])))
                   for i in range(n_requests)]
        for t in threads:
            t.start()
        # SIGKILL the replica the streams are sticky to, once tokens
        # are demonstrably flowing through live relayed streams —
        # ideally once at least one of the VICTIM's own streams has a
        # harvested prefix, so the failover demonstrably carries
        # tokens (bounded wait; live outstanding streams are the hard
        # requirement, the prefix is opportunistic)
        victim = None
        deadline = time.time() + 120
        prefix_deadline = time.time() + 20
        while time.time() < deadline:
            with router._lock:
                names = set(router._outstanding.values())
                flowing = router.streamed_tokens_total > 0
            cand = next((rep for rep in reps if rep.name in names), None)
            if cand is not None and flowing:
                victim = cand
                # does the VICTIM itself carry a harvestable prefix
                # (its own outstanding streams, not just anyone's)?
                with router._lock:
                    victim_has_prefix = any(
                        router._resume.get(rid)
                        for rid, name in router._outstanding.items()
                        if name == cand.name)
                if victim_has_prefix or time.time() >= prefix_deadline:
                    break
            time.sleep(0.02)
        assert victim is not None, "no live stream to kill under"
        os.kill(victim.pid(), _signal.SIGKILL)
        for t in threads:
            t.join(timeout=900)
        wall = time.time() - t0
        assert not any(t.is_alive() for t in threads), "hung streams"

        failed = [i for i, r in results.items()
                  if not isinstance(r, dict)]
        assert not failed, (
            f"streaming arm failed requests: "
            f"{[(i, results[i]) for i in failed]}")
        # byte-identity, TWICE over: the per-token stream the client
        # assembled AND the final response both equal the non-streamed
        # greedy reference
        mismatched = [i for i in range(n_requests)
                      if results[i]["tokens"] != refs[i]]
        assert not mismatched, (
            f"streamed output diverged from non-streamed greedy on: "
            f"{mismatched}")
        per_token_counts = [len(stamps[i]) for i in range(n_requests)]
        assert per_token_counts == [len(r) for r in refs], (
            "client-side token stream lengths diverged from refs")
        rstats = router.stats()
        assert rstats["failed"] == 0
        assert rstats["stream_failovers"] >= 1, (
            "the SIGKILL must land on live streams")
        assert rstats["stream_disconnects"] == 0

        # client-observed latency: TTFT (arrival->first token) is not
        # derivable from stamps alone here, so report ITL only — the
        # per-token gaps INCLUDING intra-chunk zeros (what a client
        # sees), plus the nonzero chunk-gap view
        gaps = []
        for i in range(n_requests):
            ts = stamps[i]
            gaps.extend(b - a for a, b in zip(ts, ts[1:]))
        gaps.sort()

        def q(p):
            return gaps[min(len(gaps) - 1,
                            int(p * (len(gaps) - 1)))] if gaps else 0.0

        chunk_gaps = sorted(g for g in gaps if g > 0)

        def cq(p):
            return chunk_gaps[min(len(chunk_gaps) - 1,
                                  int(p * (len(chunk_gaps) - 1)))] \
                if chunk_gaps else 0.0

        out = {
            "metric": "streaming_serving_zero_failed_requests",
            "value": 0,
            "unit": "failed requests across an open-loop Poisson "
                    "streamed burst with one mid-stream replica "
                    "SIGKILL (byte-identity to non-streamed greedy "
                    "enforced)",
            "requests": n_requests,
            "poisson_mean_interarrival_s": mean_interarrival_s,
            "byte_identical": True,
            "streamed_tokens": rstats["streamed_tokens"],
            "stream_failovers": rstats["stream_failovers"],
            "failovers": rstats["failovers"],
            "resumed_tokens": rstats["resumed_tokens"],
            "stream_disconnects": rstats["stream_disconnects"],
            "itl_p50_s": round(q(0.50), 4),
            "itl_p99_s": round(q(0.99), 4),
            "chunk_gap_p50_s": round(cq(0.50), 4),
            "chunk_gap_p99_s": round(cq(0.99), 4),
            "wall_s": round(wall, 3),
            "num_devices": jax.device_count(),
        }
        print(json.dumps(out))
        return 0
    finally:
        if router is not None:
            router.shutdown()
        for rep in reps:
            try:
                rep.stop()
            except Exception:
                pass


def run_elastic_bench() -> int:
    """Elastic-training robustness benchmark (docs/training-robustness.md),
    run TWICE — warm pool off, then on — so the recovery bound shows what
    adoption buys: a real 2-worker local job runs
    examples/elastic_train.py (tiny deterministic jitted update,
    overlapped orbax checkpoints every SAVE_INTERVAL steps, full
    preemption-drain contract) while the driver's seeded chaos harness
    SIGKILLs containers at KILL_RATE per monitor tick and fires one
    preemption drain when the gang reaches PREEMPT_AT_STEP. Elasticity
    is ON with a restart budget, so every loss is either a budgeted
    restart, a budget-free preempt relaunch, or a gang resize — never a
    failed job.

    Each arm ENFORCES the acceptance invariants rather than just
    reporting them: the job must SUCCEED (zero failed jobs), at least
    one chaos kill and the preemption must actually have fired, every
    worker's StepTimer JSONL must show ≤ SAVE_INTERVAL recomputed steps
    per recovery and NO silent step skips, and each recovery's
    loss→running wall time is read off tasks.trace.jsonl. On top, the
    per-recovery loss→first-step-after-relaunch gap is read off the
    per-step JSONL wall clocks (the gap across each step REWIND), and
    the pool-on arm must show at least one adopted relaunch
    (child_adopted in the traces) — the adopted relaunch skips the
    child's import/backend bill (`backend_and_data_s` in the launch
    waterfall), which is exactly the step-gap delta between the arms."""
    off = _run_elastic_arm(warm_pool=False)
    on = _run_elastic_arm(warm_pool=True)
    assert on["adopted_relaunches"] >= 1, (
        "the pool-on arm never adopted a relaunch; warm pool broken?")
    out = {
        "metric": "training_robustness_elastic_chaos",
        "value": off["value"],
        "unit": off["unit"],
        "job_status": "SUCCEEDED",
        "failed_jobs": 0,
        "chaos": off["chaos"],
        "total_steps": off["total_steps"],
        "save_interval": off["save_interval"],
        "step_ms": off["step_ms"],
        "warm_pool_off": off,
        "warm_pool_on": on,
    }
    print(json.dumps(out))
    return 0


def _run_elastic_arm(warm_pool: bool) -> dict:
    import tempfile as _tempfile

    sys.path.insert(0, str(REPO))
    from tony_tpu import constants as c
    from tony_tpu.api import JobStatus
    from tony_tpu.client import TonyClient
    from tony_tpu.conf import TonyConf
    from tony_tpu.events.trace import TASK_TRACE_FILE, read_traces

    SAVE_INTERVAL = 5
    TOTAL_STEPS = 150
    STEP_MS = 50
    KILL_RATE = 0.006           # per 100ms monitor tick; E[kills] ~ 2
    PREEMPT_AT = 60
    SEED = 1234
    workers = 2

    chaos_env = {
        c.TEST_DRIVER_KILL_RATE: str(KILL_RATE),
        c.TEST_DRIVER_PREEMPT_AT_STEP: str(PREEMPT_AT),
        c.TEST_DRIVER_CHAOS_SEED: str(SEED),
    }
    td = _tempfile.mkdtemp(prefix="tony-elastic-bench-")
    root = Path(td)
    cmd = (f"{sys.executable} -m tony_tpu.examples.elastic_train "
           f"--steps {TOTAL_STEPS} --save-interval {SAVE_INTERVAL} "
           f"--ckpt-dir {root}/ckpt_$TONY_TASK_INDEX")
    conf = TonyConf({
        "tony.staging.dir": str(root / "staging"),
        "tony.history.location": str(root / "history"),
        "tony.history.intermediate": str(root / "history/intermediate"),
        "tony.history.finished": str(root / "history/finished"),
        "tony.am.monitor-interval-ms": 100,
        "tony.task.registration-poll-interval-ms": 100,
        "tony.task.heartbeat-interval-ms": 250,
        "tony.task.metrics-interval-ms": 500,
        "tony.task.preempt-grace-ms": 4000,
        "tony.worker.instances": workers,
        "tony.worker.command": cmd,
        "tony.worker.max-restarts": 3,
        "tony.train.elastic-enabled": True,
        "tony.train.elastic-min-instances": 1,
        "tony.train.rescale-retry-ms": 3000,
        # pool-on: every relaunch (budgeted restart, preempt, resize)
        # adopts a pre-warmed standby instead of paying the cold child
        # bill again — the driver seeds the pool at prepare and the
        # executors replenish after each adoption
        "tony.warmpool.size": workers if warm_pool else 0,
        "tony.execution.env": " ".join(
            [f"ELASTIC_TRAIN_STEP_MS={STEP_MS}", "JAX_PLATFORMS=cpu"]
            # chaos kills arrive seconds apart: replenish fast enough
            # that back-to-back recoveries still find a standby
            + (["TONY_WARMPOOL_REPLENISH_DELAY_S=1"] if warm_pool else [])
            + [f"{k}={v}" for k, v in chaos_env.items()]),
    })
    # the chaos knobs must reach the DRIVER process (it reads them at
    # construction); the client launches the driver with its own env
    old_env = {k: os.environ.get(k) for k in chaos_env}
    os.environ.update(chaos_env)
    t0 = time.time()
    try:
        client = TonyClient(conf, poll_interval_s=0.2)
        client.submit()
        status = client.monitor()
    finally:
        for k, v in old_env.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)
    wall = time.time() - t0

    assert status == JobStatus.SUCCEEDED, (
        f"elastic job FAILED under chaos: {client.final_state}")

    # ---- recovery forensics from the task traces
    inter = (root / "history/intermediate" / client.app_id)
    recs = {r["id"]: r for r in read_traces(inter / TASK_TRACE_FILE)}
    kills = preempts = resizes = adopted = 0
    recoveries = []     # (task, kind, loss->running seconds)
    for task_id, rec in recs.items():
        spans = rec["spans"]
        resizes = max(resizes, sum(1 for n, *_ in spans if n == "resized"))
        # adopted RELAUNCHES only: a first-attempt adoption (the driver
        # seeds the pool at prepare) must not satisfy the recovery gate
        names = [n for n, *_ in spans]
        first_loss = next((i for i, n in enumerate(names) if n in
                           ("restarted", "preempted", "resized")),
                          len(names))
        adopted += sum(1 for n in names[first_loss:]
                       if n == "child_adopted")
        for i, (name, t_mark) in enumerate(spans):
            if name not in ("restarted", "preempted", "resized"):
                continue
            if name == "restarted":
                kills += 1
            elif name == "preempted":
                preempts += 1
            t_run = next((t for n, t in spans[i + 1:] if n == "running"),
                         None)
            if t_run is not None:
                recoveries.append(
                    {"task": task_id, "kind": name,
                     "loss_to_running_s": round(t_run - t_mark, 3)})
    assert preempts >= 1, "the seeded preemption never fired"
    assert kills + preempts + resizes >= 2, (
        f"chaos too quiet to gate on (kills={kills} preempts={preempts} "
        f"resizes={resizes}); raise KILL_RATE")

    # ---- recompute bound + continuity from the per-step StepTimer JSONLs,
    # plus the loss->first-step-after-relaunch gap: consecutive per-step
    # records share one worker wall clock, so the ts delta across each
    # step REWIND is the full recovery — kill detection, relaunch, child
    # startup (the part adoption removes), restore, first new step
    per_worker = {}
    step_gaps = []
    for w in range(workers):
        log_path = Path(client.job_dir) / "logs" / f"worker_{w}.steps.jsonl"
        steps = []
        for line in log_path.read_text().splitlines():
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec.get("train_step"), int):
                steps.append((rec["train_step"], rec.get("ts")))
        recomputed, worst = 0, 0
        gaps = []
        for (prev, prev_ts), (cur, cur_ts) in zip(steps, steps[1:]):
            if cur <= prev:
                recomputed += prev - cur + 1
                worst = max(worst, prev - cur + 1)
                if isinstance(prev_ts, (int, float)) and isinstance(
                        cur_ts, (int, float)):
                    gaps.append(round(cur_ts - prev_ts, 3))
            else:
                assert cur == prev + 1, (
                    f"worker_{w}: silent step skip {prev}->{cur}")
        assert worst <= SAVE_INTERVAL, (
            f"worker_{w} recomputed {worst} steps in one recovery "
            f"> save_interval {SAVE_INTERVAL}")
        step_gaps += gaps
        per_worker[f"worker_{w}"] = {
            "records": len(steps),
            "last_step": steps[-1][0] if steps else None,
            "recomputed_steps_total": recomputed,
            "worst_single_recovery_recompute": worst,
            "recovery_step_gaps_s": gaps,
        }
    survivors_finished = [w for w, d in per_worker.items()
                          if d["last_step"] == TOTAL_STEPS - 1]
    assert survivors_finished, "no worker reached the final step"

    rec_times = [r["loss_to_running_s"] for r in recoveries]
    return {
        "value": round(max(rec_times), 3) if rec_times else None,
        "unit": "worst loss->running recovery seconds under seeded chaos",
        "warm_pool": warm_pool,
        "job_status": status.value,
        "chaos": {"kill_rate_per_tick": KILL_RATE,
                  "preempt_at_step": PREEMPT_AT, "seed": SEED},
        "total_steps": TOTAL_STEPS,
        "save_interval": SAVE_INTERVAL,
        "step_ms": STEP_MS,
        "budgeted_restarts": kills,
        "preemptions": preempts,
        "gang_resizes": resizes,
        "adopted_relaunches": adopted,
        "recoveries": recoveries,
        "loss_to_first_step_s_worst": max(step_gaps) if step_gaps else None,
        "loss_to_first_step_s_all": sorted(step_gaps),
        "per_worker": per_worker,
        "wall_s": round(wall, 1),
    }


def run_launch_path_bench() -> int:
    """Launch-path benchmark (docs/performance.md "Launch path"): the
    same 1-worker mnist job submitted three ways, all in one run on one
    host, waterfalls split the same way as `launch_cold`/`launch_warm`:

      cold     first-ever submit: cold XLA disk cache, cold child
               (pays import + backend init + data staging + compile)
      warm     resubmit, pool OFF: warm disk caches, still a cold child
      adopted  resubmit, pool ON: the task ADOPTS a pre-warmed standby
               (jax imported, backend up, warmup hook ran) from a
               host-level pool seeded before submit

    Asserts the adopted arm actually adopted (child_adopted in the task
    trace), that training results are identical to the cold child
    (same final loss + accuracy — adoption must not change the math),
    and reports cold/adopted speedup — the PERF.json `launch_path`
    gate. The warmup hook (`tony.warmpool.warmup-module`) is
    examples/warmup_mnist: the standby also prepays optax/model imports
    and one staged device transfer, the data-staging half of the bill."""
    import shutil
    import tempfile as _tempfile

    sys.path.insert(0, str(REPO))
    from tony_tpu import warmpool
    from tony_tpu.client import TonyClient
    from tony_tpu.conf import TonyConf
    from tony_tpu.events.trace import TASK_TRACE_FILE, read_traces

    # TINY first block: on CPU the 1000-step scan of the main bench puts
    # ~10s of block EXECUTION inside compile_first_block_s, drowning the
    # launch signal this bench exists to measure (on the TPU bench shape
    # the block is milliseconds); 20 steps keeps the phase ~pure compile
    STEPS, SPC, BATCH_ = 80, 20, 256
    td = Path(_tempfile.mkdtemp(prefix="tony-launch-bench-"))
    cache = td / "xla-cache"
    pool_dir = td / "warmpool"

    def run_arm(name: str, pool: bool) -> dict:
        out = td / f"{name}.json"
        conf = TonyConf({
            "tony.staging.dir": str(td / f"staging-{name}"),
            "tony.history.location": str(td / "hist"),
            "tony.history.intermediate": str(td / "hist/intermediate"),
            "tony.history.finished": str(td / "hist/finished"),
            "tony.am.monitor-interval-ms": 50,
            "tony.task.registration-poll-interval-ms": 50,
            "tony.worker.instances": 1,
            "tony.worker.command": (
                f"{sys.executable} -m tony_tpu.examples.mnist_jax "
                f"--steps {STEPS} --steps-per-call {SPC} "
                f"--batch-size {BATCH_} --metrics-out {out} "
                f"--compile-cache {cache}"),
            "tony.warmpool.size": 1 if pool else 0,
            "tony.warmpool.dir": str(pool_dir) if pool else "",
            "tony.warmpool.warmup-module": "tony_tpu.examples.warmup_mnist",
        })
        client = TonyClient(conf, poll_interval_s=0.05)
        t_submit = time.time()
        client.submit()
        status = client.monitor()
        if status.value != "SUCCEEDED":
            for p in sorted(Path(client.job_dir).rglob("*.std*")):
                print(f"==== {p} ====\n{p.read_text()[-2000:]}",
                      file=sys.stderr)
            raise RuntimeError(f"{name} arm finished {status}")
        m = json.loads(out.read_text())
        bd = _launch_breakdown(m, t_submit)
        recs = read_traces(td / "hist/intermediate" / client.app_id
                           / TASK_TRACE_FILE)
        bd["adopted"] = any(
            n == "child_adopted" for r in recs for n, *_ in r["spans"])
        bd["final_loss"] = m["final_loss"]
        bd["accuracy"] = round(m["accuracy"], 4)
        return bd

    try:
        cold = run_arm("cold", pool=False)
        warm = run_arm("warm", pool=False)
        # pre-warm a HOST-level pool (what an operator keeps running),
        # then let the job adopt from it — this is the path every
        # relaunch/resize/roll takes with a per-job pool too
        pool = warmpool.WarmPool(
            pool_dir, size=1,
            warmup_module="tony_tpu.examples.warmup_mnist",
            # the hook prepays the workload's own staging AND train-block
            # compile (mnist_jax.build_train_block) at the job's shapes,
            # into the job's shared persistent cache
            spawn_env={"TONY_WARMUP_MNIST_SPC": str(SPC),
                       "TONY_WARMUP_MNIST_BATCH": str(BATCH_),
                       "TONY_WARMUP_MNIST_CACHE": str(cache)})
        pool.ensure()
        deadline = time.time() + 300
        while warmpool.count_ready(pool_dir) < 1:
            if time.time() > deadline:
                raise RuntimeError(
                    "standby never became ready; see "
                    + (pool_dir / "spawn.log").read_text()[-2000:])
            time.sleep(0.2)
        adopted = run_arm("adopted", pool=True)
        assert adopted["adopted"], "the adopted arm never adopted"
        assert not cold["adopted"] and not warm["adopted"]
        # adoption must not change the training math
        assert adopted["final_loss"] == cold["final_loss"], (
            cold["final_loss"], adopted["final_loss"])
        assert adopted["accuracy"] == cold["accuracy"]
        speedup = (cold["total_submit_to_first_step_s"]
                   / adopted["total_submit_to_first_step_s"])
        # the acceptance gate, enforced like the fleet bench's 1.5x:
        # adoption must prepay enough of the cold bill to be >=3x
        assert speedup >= 3.0, (
            f"adopted path only {speedup:.2f}x vs cold (gate: 3x); "
            f"cold={cold} adopted={adopted}")
        print(
            f"# launch path: cold "
            f"{cold['total_submit_to_first_step_s']:.1f}s | warm "
            f"{warm['total_submit_to_first_step_s']:.1f}s | adopted "
            f"{adopted['total_submit_to_first_step_s']:.1f}s "
            f"({speedup:.2f}x vs cold)", file=sys.stderr)
        print(json.dumps({
            "metric": "launch_path",
            "value": round(speedup, 2),
            "unit": "cold/adopted submit->first-step speedup",
            "cold": cold,
            "warm": warm,
            "adopted": adopted,
            "warmup_module": "tony_tpu.examples.warmup_mnist",
            "workload": {"steps": STEPS, "steps_per_call": SPC,
                         "batch": BATCH_},
        }))
    finally:
        try:
            warmpool.WarmPool(pool_dir, size=0).reap()
        except Exception:
            pass
        shutil.rmtree(td, ignore_errors=True)
    return 0


def run_autoscale_bench() -> int:
    """Closed-loop autoscaling + multi-tenant arbitration gate (module
    docstring; one JSON line -> PERF.json `autoscaling`)."""
    import signal as _signal
    import tempfile as _tempfile
    import threading

    sys.path.insert(0, str(REPO))
    import numpy as np

    from tony_tpu import constants as c
    from tony_tpu.client import TonyClient
    from tony_tpu.conf import TonyConf
    from tony_tpu.events.driver_journal import load_state
    from tony_tpu.events.trace import TASK_TRACE_FILE, read_traces
    from tony_tpu.router import DriverDiscovery, FleetRouter

    # the TINY fleet shape (the gate is the control loop, not model
    # throughput); the step delay sets a KNOWN single-replica capacity
    # so the ramp reliably breaches the queue SLO
    e = dict(vocab=64, d_model=16, n_layers=1, n_heads=2, d_ff=32,
             slots=2, max_len=96, block_size=4, prefill_chunk=8)
    MAX_NEW = 16
    STEP_DELAY_MS = 100
    SAVE_INTERVAL = 5
    TRAIN_STEPS = 900
    STEP_MS = 150
    QUEUE_SLO = 6
    COOLDOWN_S = 6.0
    # ramp: a seeded Poisson burst floods the single replica, then a
    # sustained tail keeps traffic flowing while the scaled-up fleet
    # drains the backlog (the post-scale TTFT window)
    BURST_REQS, BURST_MEAN_S = 36, 0.08
    TAIL_REQS, TAIL_MEAN_S = 100, 0.35

    td = _tempfile.mkdtemp(prefix="tony-autoscale-bench-")
    root = Path(td)
    serve_cmd = (
        f"{sys.executable} -m tony_tpu.cli.main serve "
        "--port $TONY_SERVE_PORT --host 127.0.0.1 "
        f"--vocab {e['vocab']} --d-model {e['d_model']} "
        f"--n-layers {e['n_layers']} --n-heads {e['n_heads']} "
        f"--d-ff {e['d_ff']} --dtype float32 --seed 0 "
        f"--slots {e['slots']} --max-len {e['max_len']} "
        f"--block-size {e['block_size']} "
        f"--prefill-chunk {e['prefill_chunk']} "
        "--max-queue 64 --drain-timeout-s 10")
    train_cmd = (f"{sys.executable} -m tony_tpu.examples.elastic_train "
                 f"--steps {TRAIN_STEPS} --save-interval {SAVE_INTERVAL} "
                 f"--ckpt-dir {root}/ckpt_$TONY_TASK_INDEX")
    conf = TonyConf({
        "tony.staging.dir": str(root / "staging"),
        "tony.history.location": str(root / "history"),
        "tony.history.intermediate": str(root / "history/intermediate"),
        "tony.history.finished": str(root / "history/finished"),
        "tony.am.monitor-interval-ms": 100,
        "tony.application.framework": "serving",
        # job success = the TRAINING role's outcome; replicas serve for
        # the life of the job and are torn down with it
        "tony.application.untracked.jobtypes": "replica",
        "tony.task.registration-poll-interval-ms": 100,
        "tony.task.heartbeat-interval-ms": 250,
        "tony.task.driver-outage-grace-ms": 60000,
        "tony.serving.healthz-interval-ms": 200,
        "tony.replica.instances": 2,
        "tony.replica.command": serve_cmd,
        "tony.replica.max-restarts": 1,
        "tony.worker.instances": 2,
        "tony.worker.command": train_cmd,
        "tony.worker.max-restarts": 1,
        "tony.worker.framework": "jax",
        "tony.worker.priority-class": "batch",
        "tony.train.elastic-enabled": True,
        "tony.train.elastic-min-instances": 1,
        "tony.train.rescale-retry-ms": 300,
        "tony.train.checkpoint-dir": f"{root}/ckpt_$TONY_TASK_INDEX",
        "tony.warmpool.size": 1,
        "tony.autoscale.enabled": True,
        "tony.autoscale.role": "replica",
        "tony.autoscale.min": 1,
        "tony.autoscale.queue-depth-slo": QUEUE_SLO,
        "tony.autoscale.cooldown-s": COOLDOWN_S,
        "tony.autoscale.interval-s": 0.5,
        "tony.autoscale.breach-ticks": 2,
        "tony.quota.pool-slots": 3,
        "tony.execution.env": " ".join([
            f"PYTHONPATH={REPO}", "JAX_PLATFORMS=cpu",
            f"{c.TEST_SERVING_STEP_DELAY_MS}={STEP_DELAY_MS}",
            f"ELASTIC_TRAIN_STEP_MS={STEP_MS}"]),
    })
    t0 = time.time()
    client = TonyClient(conf, poll_interval_s=0.2)
    client.submit()
    job_dir = Path(client.job_dir)
    router = FleetRouter(
        [], prefill_chunk=e["prefill_chunk"],
        discover=DriverDiscovery(str(job_dir), role="replica",
                                 token=client.token),
        health_interval_s=0.3, eject_after=3, stats_every=2, seed=0)
    results: dict[int, object] = {}
    marks: dict[str, float] = {}
    rec = logf = None
    try:
        deadline = time.time() + 240
        while time.time() < deadline:
            router.health_tick()
            if router.stats()["live"] >= 1:
                break
            time.sleep(0.3)
        assert router.stats()["live"] == 1, (
            f"expected exactly replica:0 up (slot 1 parked): "
            f"{router.stats()}")
        router.start()

        # ---- kill watcher: the moment the scaled-up replica is LIVE
        # (scale-up journaled + actuated + serving), SIGKILL the driver
        # and relaunch it with --recover, mid-ramp
        stop_watch = threading.Event()

        def watch():
            nonlocal rec, logf
            while not stop_watch.wait(0.3):
                if router.stats()["live"] >= 2:
                    marks["live2"] = time.time()
                    os.kill(client._driver_proc.pid, _signal.SIGKILL)
                    client._driver_proc.wait(timeout=10)
                    marks["killed"] = time.time()
                    rec, logf = _spawn_recovered_driver(job_dir,
                                                        strip_env=[])
                    return

        watcher = threading.Thread(target=watch, daemon=True)
        watcher.start()

        rng = np.random.default_rng(11)
        chunk = e["prefill_chunk"]
        template = rng.integers(0, e["vocab"], size=chunk,
                                dtype=np.int32)
        n_total = BURST_REQS + TAIL_REQS
        prompts = [np.concatenate(
            [template, rng.integers(0, e["vocab"], size=1 + i % 3,
                                    dtype=np.int32)]).tolist()
            for i in range(n_total)]
        waits = np.concatenate([
            rng.exponential(BURST_MEAN_S, BURST_REQS),
            rng.exponential(TAIL_MEAN_S, TAIL_REQS)])

        def call(i):
            t_submit = time.time()
            first = {"t": None}

            def on_toks(_new):
                if first["t"] is None:
                    first["t"] = time.time()

            try:
                r = router.generate(prompts[i], max_new_tokens=MAX_NEW,
                                    timeout_s=240, on_tokens=on_toks)
                r["t_submit"] = t_submit
                r["ttft_s"] = ((first["t"] or time.time()) - t_submit)
                results[i] = r
            except Exception as exc:
                results[i] = exc

        threads = []
        t_traffic = time.time()
        for i in range(n_total):
            th = threading.Thread(target=call, args=(i,))
            th.start()
            threads.append(th)
            time.sleep(float(waits[i]))
        for th in threads:
            th.join(timeout=300)
        marks["traffic_done"] = time.time()
        watcher.join(timeout=60)
        assert "live2" in marks, (
            "the autoscaler never brought the second replica live "
            f"under the ramp: {router.stats()}")

        # ---- zero failed serving requests, across donation, scale-up,
        # the driver outage, and the scale-down drain
        failed = {i: r for i, r in results.items()
                  if not isinstance(r, dict)}
        assert not failed, (
            f"{len(failed)} requests failed across the ramp: "
            f"{dict(list(failed.items())[:3])}")
        assert len(results) == n_total

        # ---- TTFT recovery: requests submitted while one replica ate
        # the backlog vs requests submitted once the scaled-up fleet
        # was live and settled
        state = load_state(job_dir / c.DRIVER_JOURNAL_FILE)
        ups = [op for op in state.scale_ops if op["dir"] == "up"]
        assert len(ups) == 1, (
            f"expected exactly one journaled scale-up: {state.scale_ops}")
        t_up = float(ups[0]["t"])
        pre = sorted(r["ttft_s"] for r in results.values()
                     if r["t_submit"] < t_up)
        post = sorted(r["ttft_s"] for r in results.values()
                      if r["t_submit"] > marks["live2"] + 2.0)
        assert len(pre) >= 5 and len(post) >= 5, (
            f"phase windows too thin to gate on: pre={len(pre)} "
            f"post={len(post)}")

        def p99(xs):
            return xs[min(len(xs) - 1, int(0.99 * (len(xs) - 1)))]

        pre_p99, post_p99 = p99(pre), p99(post)
        assert post_p99 < 0.8 * pre_p99, (
            f"TTFT p99 never recovered after the scale-up: breach "
            f"window {pre_p99:.2f}s vs post-scale {post_p99:.2f}s")
        by_replica: dict[str, int] = {}
        for r in results.values():
            by_replica[r["replica"]] = by_replica.get(r["replica"], 0) + 1
        assert len(by_replica) == 2, (
            f"the scaled-up replica never took traffic: {by_replica}")

        # ---- ramp-down: fleet scales back, batch reclaims the slot,
        # training SUCCEEDS
        final = _wait_recovered_terminal(job_dir, rec, client.token,
                                         timeout_s=420)
        rec.wait(timeout=60)
        assert final["status"] == "SUCCEEDED", final
    finally:
        router.shutdown()
        for proc in (rec, client._driver_proc):
            if proc is not None and proc.poll() is None:
                try:
                    os.killpg(proc.pid, _signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
        if rec is not None:
            try:
                rec.wait(timeout=20)
            except subprocess.TimeoutExpired:
                os.killpg(rec.pid, _signal.SIGKILL)
        if logf is not None:
            logf.close()
    wall = time.time() - t0

    # ---- journal forensics: the ledger shows exactly one up and one
    # down across the driver SIGKILL — no duplicate, no flap — and the
    # donation round-tripped
    state = load_state(job_dir / c.DRIVER_JOURNAL_FILE)
    dirs = [op["dir"] for op in state.scale_ops]
    assert dirs == ["up", "down"], (
        f"scale ledger flapped or duplicated across recovery: {dirs}")
    assert state.recoveries >= 1, "driver recovery not journaled"
    assert len(state.parked) == 1 and all(
        t.startswith("replica:") for t in state.parked), state.parked
    assert state.donated == set() and state.donations == {}, (
        f"donated slot never reclaimed: {state.donated} "
        f"{state.donations}")
    replica_restarts = sum(
        t.restarts for tid, t in state.tasks.items()
        if tid.startswith("replica:"))
    assert replica_restarts == 0, (
        f"replicas spent restart budget: {replica_restarts}")

    # ---- trace forensics. The scale-up and donation marks were made
    # by the driver incarnation the bench SIGKILLs, and unsealed trace
    # records die with their driver (PR 12 semantics: the JOURNAL is
    # the durable decision record — asserted above); the marks made by
    # the RECOVERED driver must be in the file.
    trace_path = None
    for base in (root / "history/intermediate",
                 root / "history/finished"):
        for cand in base.glob(f"{client.app_id}*/{TASK_TRACE_FILE}"):
            trace_path = cand
    assert trace_path is not None, "tasks.trace.jsonl not found"
    spans_by_task: dict[str, list] = {}
    for rec_ in read_traces(trace_path):
        spans_by_task[rec_["id"]] = [n for n, *_ in rec_["spans"]]
    all_spans = [n for names in spans_by_task.values() for n in names]
    for mark in ("scaled_down", "reclaimed", "ckpt_prestaged"):
        assert mark in all_spans, (
            f"'{mark}' trace mark missing; spans: {spans_by_task}")
    donor = next(t for t, names in spans_by_task.items()
                 if "reclaimed" in names)
    assert donor.startswith("worker:"), (
        f"reclaim landed on a non-batch task: {donor}")
    assert "ckpt_prestaged" in spans_by_task[donor], (
        f"reclaimed {donor} came back without the checkpoint "
        f"prestaged: {spans_by_task[donor]}")
    adopted_relaunches = sum(
        1 for t, names in spans_by_task.items()
        if t.startswith("worker:")
        for i, n in enumerate(names)
        if n == "child_adopted" and any(
            m in names[:i] for m in ("resized", "reclaimed", "donated")))

    # ---- recompute bound: each drain (donation, survivor resizes,
    # reclaim) rewinds at most save_interval steps
    per_worker = {}
    for w in range(2):
        log_path = job_dir / "logs" / f"worker_{w}.steps.jsonl"
        steps = []
        for line in log_path.read_text().splitlines():
            try:
                rec_ = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec_.get("train_step"), int):
                steps.append(rec_["train_step"])
        recomputed, worst = 0, 0
        for prev, cur in zip(steps, steps[1:]):
            if cur <= prev:
                recomputed += prev - cur + 1
                worst = max(worst, prev - cur + 1)
            else:
                assert cur == prev + 1, (
                    f"worker_{w}: silent step skip {prev}->{cur}")
        assert worst <= SAVE_INTERVAL, (
            f"worker_{w} recomputed {worst} steps in one recovery "
            f"> save_interval {SAVE_INTERVAL}")
        assert steps and steps[-1] == TRAIN_STEPS - 1, (
            f"worker_{w} never reached the final step")
        per_worker[f"worker_{w}"] = {
            "records": len(steps), "last_step": steps[-1],
            "recomputed_steps_total": recomputed,
            "worst_single_recovery_recompute": worst}

    out = {
        "metric": "autoscaling",
        "value": round(pre_p99 / post_p99, 2),
        "unit": "x TTFT-p99 recovery (breach window vs post-scale-up "
                "window, client-observed through the router)",
        "job_status": "SUCCEEDED",
        "requests": n_total,
        "failed_requests": 0,
        "ttft_p99_breach_s": round(pre_p99, 3),
        "ttft_p99_post_scale_s": round(post_p99, 3),
        "ttft_p50_breach_s": round(pre[len(pre) // 2], 3),
        "ttft_p50_post_scale_s": round(post[len(post) // 2], 3),
        "queue_depth_slo": QUEUE_SLO,
        "scale_ops": dirs,
        "scale_up_to_live_s": round(marks["live2"] - t_up, 1),
        "driver_killed_mid_ramp": True,
        "driver_recoveries": state.recoveries,
        "replica_restarts": 0,
        "donations": 1,
        "reclaims": 1,
        "donor": donor,
        "ckpt_prestaged": True,
        "adopted_relaunches": adopted_relaunches,
        "save_interval": SAVE_INTERVAL,
        "per_worker": per_worker,
        "per_replica_requests": by_replica,
        "traffic_wall_s": round(marks["traffic_done"] - t_traffic, 1),
        "wall_s": round(wall, 1),
    }
    print(json.dumps(out))
    return 0


def run_slo_bench() -> int:
    """Fleet metrics pipeline + SLO burn-rate alerting gate (module
    docstring; one JSON line -> PERF.json `slo_alerting`)."""
    import signal as _signal
    import tempfile as _tempfile
    import threading
    import urllib.request

    sys.path.insert(0, str(REPO))
    import numpy as np

    from tony_tpu import constants as c
    from tony_tpu.client import TonyClient
    from tony_tpu.conf import TonyConf
    from tony_tpu.observability import parse_prom_text
    from tony_tpu.router import DriverDiscovery

    e = dict(vocab=64, d_model=16, n_layers=1, n_heads=2, d_ff=32,
             slots=2, max_len=96, block_size=4, prefill_chunk=8)
    MAX_NEW = 8
    STEP_DELAY_MS = 100     # slow decode: the lone survivor's capacity
    #                         sits far below the incident arrival rate
    # availability SLO: W=120s -> fast pair (20s, 2s) @ 14.4x burn
    # (error rate > 14.4% in BOTH trailing windows), slow pair
    # (120s, 20s) @ 6x. The burst overloads the survivor hard enough
    # that the fast pair fires within a few 0.5s scrape rounds; only
    # the FAST alert's clear is gated (the slow pair needs the
    # incident to age out of the full 120s window).
    TARGET, WINDOW_S, SCRAPE_S = 0.99, 120.0, 0.5
    WARMUP_REQS, WARMUP_GAP_S = 15, 0.2
    PRESSURE_MEAN_S = 0.02      # ~50 req/s of sustained incident load

    td = _tempfile.mkdtemp(prefix="tony-slo-bench-")
    root = Path(td)
    serve_cmd = (
        f"{sys.executable} -m tony_tpu.cli.main serve "
        "--port $TONY_SERVE_PORT --host 127.0.0.1 "
        f"--vocab {e['vocab']} --d-model {e['d_model']} "
        f"--n-layers {e['n_layers']} --n-heads {e['n_heads']} "
        f"--d-ff {e['d_ff']} --dtype float32 --seed 0 "
        f"--slots {e['slots']} --max-len {e['max_len']} "
        f"--block-size {e['block_size']} "
        f"--prefill-chunk {e['prefill_chunk']} "
        # deep enough that the cold-start compile stall never sheds the
        # healthy warm-up (a shed is a REAL bad event and would burn
        # budget before the incident); the sustained incident load
        # still fills it behind a lone survivor within a few seconds
        "--max-queue 64 --drain-timeout-s 5")
    route_cmd = (
        f"{sys.executable} -m tony_tpu.cli.main route "
        "--port $TONY_SERVE_PORT --host 127.0.0.1 "
        "--job-dir $TONY_JOB_DIR --role replica "
        f"--prefill-chunk {e['prefill_chunk']} "
        "--health-interval-s 0.3 --probe-timeout-s 5.0 "
        "--discovery-min-interval-s 0.5 --stats-every 2 "
        "--drain-timeout-s 10")
    conf = TonyConf({
        "tony.staging.dir": str(root / "staging"),
        "tony.history.location": str(root / "history"),
        "tony.history.intermediate": str(root / "history/intermediate"),
        "tony.history.finished": str(root / "history/finished"),
        "tony.am.monitor-interval-ms": 100,
        "tony.application.framework": "serving",
        "tony.task.registration-poll-interval-ms": 100,
        "tony.task.heartbeat-interval-ms": 250,
        "tony.task.driver-outage-grace-ms": 60000,
        "tony.serving.healthz-interval-ms": 200,
        "tony.replica.instances": 2,
        "tony.replica.command": serve_cmd,
        "tony.replica.max-restarts": 1,
        "tony.router.instances": 1,
        "tony.router.command": route_cmd,
        "tony.router.framework": "router",
        "tony.router.max-restarts": 1,
        # the hub scrapes the named serving role's replicas even with
        # the autoscaler off (autoscale.enabled stays false)
        "tony.autoscale.role": "replica",
        "tony.slo.availability.objective": "availability",
        "tony.slo.availability.target": TARGET,
        "tony.slo.availability.window-s": WINDOW_S,
        "tony.slo.scrape-interval-s": SCRAPE_S,
        "tony.execution.env": " ".join([
            f"PYTHONPATH={REPO}", "JAX_PLATFORMS=cpu",
            f"{c.TEST_SERVING_STEP_DELAY_MS}={STEP_DELAY_MS}"]),
    })
    t_bench = time.time()
    client = TonyClient(conf, poll_interval_s=0.2)
    client.submit()
    job_dir = Path(client.job_dir)
    disco_router = DriverDiscovery(str(job_dir), role="router",
                                   token=client.token)
    disco_replica = DriverDiscovery(str(job_dir), role="replica",
                                    token=client.token)

    def endpoints(disco):
        try:
            return {tid: (host, port) for tid, host, port in disco()}
        except Exception:
            return {}

    def get_json(url, timeout=10):
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode())

    def slo_snap(want_pid=None):
        """The live driver's /slo snapshot via driver.json; None while
        the endpoint (or the wanted driver incarnation) isn't up."""
        try:
            info = json.loads((job_dir / c.DRIVER_INFO_FILE).read_text())
            if want_pid is not None and info.get("pid") != want_pid:
                return None
            port = info["metrics_port"]
            return get_json(f"http://127.0.0.1:{port}/slo", timeout=5)
        except Exception:
            return None

    def fast_alert(snap):
        if not snap or not snap.get("evaluated"):
            return None
        for a in snap["alerts"]:
            if a["slo"] == "availability" and a["severity"] == "fast":
                return a["firing"]
        return None

    def journal_alert_records():
        recs = []
        for line in (job_dir / c.DRIVER_JOURNAL_FILE).read_text(
                ).splitlines():
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("op") == "slo_alert":
                recs.append(rec)
        return recs

    results: dict[int, str] = {}
    marks: dict[str, float] = {}
    rec = logf = None
    try:
        deadline = time.time() + 240
        doors = reps = {}
        while time.time() < deadline:
            doors = endpoints(disco_router)
            reps = endpoints(disco_replica)
            if len(doors) == 1 and len(reps) == 2:
                break
            time.sleep(0.3)
        assert len(doors) == 1, f"front door never up: {doors}"
        assert len(reps) == 2, f"replica fleet never fully up: {reps}"
        door_port = doors["router:0"][1]

        chunk = e["prefill_chunk"]

        def prompt(i):
            # per-call generator: prompt() runs on many client threads
            # at once and a shared numpy Generator is not thread-safe
            return np.random.default_rng(1000 + i).integers(
                0, e["vocab"], size=chunk + 1 + i % 3,
                dtype=np.int32).tolist()

        def call(i, tag):
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{door_port}/generate",
                    data=json.dumps({"prompt": prompt(i),
                                     "max_new_tokens": MAX_NEW}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=120) as r:
                    json.loads(r.read().decode())
                results[i] = "ok"
            except Exception:
                # shed/failed during the incident — the SLO's bad events
                results[i] = f"{tag}_err"

        # ---- phase 1: healthy warm-up — ZERO alerts
        t_first_request = time.time()
        warm = [threading.Thread(target=call, args=(i, "warm"))
                for i in range(WARMUP_REQS)]
        for th in warm:
            th.start()
            time.sleep(WARMUP_GAP_S)
        for th in warm:
            th.join(timeout=120)
        assert all(results[i] == "ok" for i in range(WARMUP_REQS)), (
            f"healthy warm-up had failures: {results}")
        deadline = time.time() + 30
        snap = None
        while time.time() < deadline:
            snap = slo_snap()
            if snap and snap.get("evaluated"):
                break
            time.sleep(0.3)
        assert snap and snap.get("evaluated"), "SLO engine never evaluated"
        assert snap["history"] == [], (
            f"alerts fired on a HEALTHY warm-up: {snap['history']}")
        assert all(not a["firing"] for a in snap["alerts"]), snap["alerts"]

        # ---- phase 2: replica SIGKILL + SUSTAINED Poisson overload ->
        # the survivor sheds, the fast pair must fire inside its
        # window. The pressure keeps flowing until the recovered
        # driver confirms the resumed alert: the fast pair's SHORT
        # window empties ~2s after sheds stop, and a cleared alert
        # would make the driver kill land post-incident.
        victim_stats = get_json(
            f"http://127.0.0.1:{reps['replica:0'][1]}/stats")
        os.kill(victim_stats["pid"], _signal.SIGKILL)
        marks["replica_killed"] = time.time()
        stop_pressure = threading.Event()
        pressure_n = {"i": WARMUP_REQS}
        pressure_rng = np.random.default_rng(29)

        def pressure():
            # ~50 req/s against a shedding survivor (and still past the
            # relaunched 2-replica fleet's capacity): bad events flow
            # continuously across the replica kill, the driver kill,
            # and the recovery
            while not stop_pressure.is_set():
                i = pressure_n["i"]
                pressure_n["i"] += 1
                threading.Thread(target=call, args=(i, "incident"),
                                 daemon=True).start()
                time.sleep(float(pressure_rng.exponential(
                    PRESSURE_MEAN_S)))

        pressure_t = threading.Thread(target=pressure, daemon=True)
        pressure_t.start()
        fired_at = None
        deadline = time.time() + 60
        while time.time() < deadline:
            if fast_alert(slo_snap()) is True:
                fired_at = time.time()
                break
            time.sleep(0.2)
        assert fired_at is not None, (
            "fast burn-rate alert never fired under the overload "
            f"incident: {slo_snap()}")
        marks["alert_fired"] = fired_at
        firings = [r for r in journal_alert_records()
                   if r["severity"] == "fast" and r["state"] == "firing"]
        assert len(firings) == 1, firings

        # ---- phase 3: driver SIGKILL + --recover MID-INCIDENT — the
        # replayed tsdb + journal-seeded alert state must RESUME the
        # firing alert without a duplicate transition
        os.kill(client._driver_proc.pid, _signal.SIGKILL)
        client._driver_proc.wait(timeout=10)
        marks["driver_killed"] = time.time()
        rec, logf = _spawn_recovered_driver(job_dir, strip_env=[])
        resumed = None
        deadline = time.time() + 90
        while time.time() < deadline:
            resumed = fast_alert(slo_snap(want_pid=rec.pid))
            if resumed is not None:
                break
            time.sleep(0.3)
        assert resumed is True, (
            "recovered driver did not resume the mid-incident firing "
            f"alert: {slo_snap(want_pid=rec.pid)}")
        marks["alert_resumed"] = time.time()
        fast_recs = [r for r in journal_alert_records()
                     if r["severity"] == "fast"]
        assert [r["state"] for r in fast_recs] == ["firing"], (
            f"duplicate/flapped firing transition across recovery: "
            f"{fast_recs}")

        # ---- phase 4: the SIGKILLed replica relaunches on its restart
        # budget; end the incident — the alert must CLEAR and healthy
        # service resume
        deadline = time.time() + 120
        relaunched = False
        while time.time() < deadline:
            reps = endpoints(disco_replica)
            if len(reps) == 2:
                try:
                    pids = {tid: get_json(
                        f"http://127.0.0.1:{p}/stats", timeout=5)["pid"]
                        for tid, (_, p) in reps.items()}
                    if pids["replica:0"] != victim_stats["pid"]:
                        relaunched = True
                        break
                except Exception:
                    pass
            time.sleep(0.5)
        assert relaunched, f"SIGKILLed replica never relaunched: {reps}"
        stop_pressure.set()
        pressure_t.join(timeout=10)
        marks["incident_over"] = time.time()
        cleared_at = None
        deadline = time.time() + 90
        while time.time() < deadline:
            if fast_alert(slo_snap(want_pid=rec.pid)) is False:
                cleared_at = time.time()
                break
            time.sleep(0.3)
        assert cleared_at is not None, (
            "fast alert never cleared after the incident ended: "
            f"{slo_snap(want_pid=rec.pid)}")
        marks["alert_cleared"] = cleared_at
        fast_recs = [r for r in journal_alert_records()
                     if r["severity"] == "fast"]
        assert [r["state"] for r in fast_recs] == ["firing", "clear"], (
            f"fast alert transition ledger wrong: {fast_recs}")
        # healthy service restored through the relaunched fleet
        probe_i = pressure_n["i"] + 1
        call(probe_i, "post")
        assert results[probe_i] == "ok", (
            "fleet did not serve healthily after the incident")

        # ---- phase 5: budget exactness — the engine's availability
        # accounting must equal (failed+shed)/total from the router's
        # own exposition, bit-for-bit. Valid only while ALL traffic is
        # inside the trailing SLO window (counters born at zero).
        assert time.time() - t_first_request < WINDOW_S - 5, (
            f"bench overran the SLO window "
            f"({time.time() - t_first_request:.0f}s of "
            f"{WINDOW_S:g}s): the budget-exactness gate would see "
            "traffic age out")
        def router_metrics_text():
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{door_port}/metrics",
                    timeout=10) as r:
                return r.read().decode()

        def counter_triple():
            fams = parse_prom_text(router_metrics_text())
            return tuple(
                sum(fams[name].values()) if name in fams else 0.0
                for name in ("router_requests_total",
                             "router_shed_total",
                             "router_requests_failed_total"))

        # in-flight stragglers may still land: wait for the router's
        # counters to go static, then let the hub scrape them
        prev = counter_triple()
        deadline = time.time() + 30
        while time.time() < deadline:
            time.sleep(1.0)
            cur = counter_triple()
            if cur == prev:
                break
            prev = cur
        time.sleep(3 * SCRAPE_S)   # let the hub land the final counters
        requests_total, shed_total, failed_total = prev
        snap = slo_snap(want_pid=rec.pid)
        avail = next(s for s in snap["eval"]["slos"]
                     if s["name"] == "availability")
        assert abs(avail["total"] - requests_total) < 1e-9, (
            f"engine total {avail['total']} != router "
            f"{requests_total}")
        assert abs(avail["bad"] - (shed_total + failed_total)) < 1e-9, (
            f"engine bad {avail['bad']} != shed+failed "
            f"{shed_total + failed_total}")
        expected_rate = (shed_total + failed_total) / requests_total
        assert abs(avail["error_rate"] - expected_rate) < 1e-9, (
            f"budget spend {avail['error_rate']} != (failed+shed)/total "
            f"{expected_rate}")
    finally:
        for proc in (rec, client._driver_proc):
            if proc is not None and proc.poll() is None:
                try:
                    os.killpg(proc.pid, _signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
        if rec is not None:
            try:
                rec.wait(timeout=20)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(rec.pid, _signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        if logf is not None:
            logf.close()

    n_err = sum(1 for v in results.values() if v != "ok")
    out = {
        "metric": "slo_alerting",
        "value": round(marks["alert_fired"] - marks["replica_killed"], 1),
        "unit": "s replica-SIGKILL -> fast burn-rate alert firing "
                "(multi-window, journaled, resumed across a driver "
                "SIGKILL + --recover mid-incident)",
        "objective": "availability",
        "target": TARGET,
        "window_s": WINDOW_S,
        "requests": len(results),
        "bad_requests_client_observed": n_err,
        "router_requests_total": requests_total,
        "router_bad_total": shed_total + failed_total,
        "error_rate": round(expected_rate, 6),
        "error_budget_remaining": round(
            avail["error_budget_remaining"], 4),
        "budget_accounting_exact": True,
        "warmup_alerts": 0,
        "fast_transitions": ["firing", "clear"],
        "duplicate_firing_transitions": 0,
        "alert_fire_s": round(
            marks["alert_fired"] - marks["replica_killed"], 1),
        "alert_resume_after_recover_s": round(
            marks["alert_resumed"] - marks["driver_killed"], 1),
        "alert_clear_s": round(
            marks["alert_cleared"] - marks["replica_killed"], 1),
        "driver_killed_mid_incident": True,
        "wall_s": round(time.time() - t_bench, 1),
    }
    print(json.dumps(out))
    return 0


def run_driver_failover_bench() -> int:
    """Control-plane robustness gate (module docstring; one JSON line ->
    PERF.json `control_plane_robustness`): driver death must be a
    latency cost for BOTH workload kinds — training keeps stepping and
    re-adopts, serving keeps answering from the router's last-known
    fleet."""
    training = _failover_training_arm()
    fleet = _failover_fleet_arm()
    out = {
        "metric": "control_plane_robustness",
        "value": training["recovery_to_first_heartbeat_s_worst"],
        "unit": "worst driver-recovery -> first re-attached worker "
                "heartbeat seconds (training arm)",
        "job_status": "SUCCEEDED",
        "outage_attributable_worker_restarts": 0,
        "training": training,
        "fleet": fleet,
    }
    print(json.dumps(out))
    return 0


def _wait_recovered_terminal(job_dir: Path, rec_proc, token: str,
                             timeout_s: float = 180.0) -> dict:
    """Poll the RECOVERED driver (through the rewritten driver.json) to
    a terminal application state, then ack finish_application so it can
    exit. Returns the final state dict."""
    from tony_tpu import constants as c
    from tony_tpu.rpc import RpcClient
    from tony_tpu.rpc.protocol import derive_role_key

    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if rec_proc.poll() is not None:
            raise AssertionError(
                f"recovered driver exited early (code {rec_proc.returncode})"
                f"; see {job_dir / 'driver.log'}")
        try:
            info = json.loads((job_dir / c.DRIVER_INFO_FILE).read_text())
            if info.get("pid") != rec_proc.pid:
                time.sleep(0.3)
                continue
            rpc = RpcClient(info["host"], info["port"],
                            token=derive_role_key(token, "client"),
                            role="client", max_retries=2)
            state = rpc.call("get_application_state")
            if state["status"] in ("SUCCEEDED", "FAILED", "KILLED"):
                rpc.call("finish_application")
                rpc.close()
                return state
            rpc.close()
        except Exception:
            pass
        time.sleep(0.3)
    raise AssertionError("recovered driver never reached a terminal state")


def _spawn_recovered_driver(job_dir: Path, strip_env: list[str]):
    """Relaunch the driver with --recover (journal replay), WITHOUT the
    chaos knob that killed its predecessor."""
    env = {k: v for k, v in os.environ.items() if k not in strip_env}
    pkg = str(REPO)
    env["PYTHONPATH"] = pkg + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    logf = open(job_dir / "driver.log", "ab")
    proc = subprocess.Popen(
        [sys.executable, "-S", "-m", "tony_tpu.driver",
         "--job-dir", str(job_dir), "--recover"],
        env=env, stdout=logf, stderr=subprocess.STDOUT,
        start_new_session=True)
    return proc, logf


def _failover_training_arm() -> dict:
    import tempfile as _tempfile

    sys.path.insert(0, str(REPO))
    from tony_tpu import constants as c
    from tony_tpu.client import TonyClient
    from tony_tpu.conf import TonyConf
    from tony_tpu.events.driver_journal import load_state
    from tony_tpu.events.trace import TASK_TRACE_FILE, read_traces

    SAVE_INTERVAL = 5
    TOTAL_STEPS = 200
    STEP_MS = 50
    SIGKILL_AT = 40
    workers = 2

    td = _tempfile.mkdtemp(prefix="tony-failover-bench-")
    root = Path(td)
    cmd = (f"{sys.executable} -m tony_tpu.examples.elastic_train "
           f"--steps {TOTAL_STEPS} --save-interval {SAVE_INTERVAL} "
           f"--ckpt-dir {root}/ckpt_$TONY_TASK_INDEX")
    conf = TonyConf({
        "tony.staging.dir": str(root / "staging"),
        "tony.history.location": str(root / "history"),
        "tony.history.intermediate": str(root / "history/intermediate"),
        "tony.history.finished": str(root / "history/finished"),
        "tony.am.monitor-interval-ms": 100,
        "tony.task.registration-poll-interval-ms": 100,
        "tony.task.heartbeat-interval-ms": 250,
        "tony.task.metrics-interval-ms": 500,
        # the whole point: executors must outlive the driver by far more
        # than the kill->recover gap
        "tony.task.driver-outage-grace-ms": 60000,
        "tony.worker.instances": workers,
        "tony.worker.command": cmd,
        "tony.worker.max-restarts": 1,
        "tony.execution.env": " ".join(
            [f"ELASTIC_TRAIN_STEP_MS={STEP_MS}", "JAX_PLATFORMS=cpu"]),
    })
    # the SIGKILL knob must reach the DRIVER process only; the recovered
    # driver is spawned with it stripped (or it would re-fire: the gang
    # is already past the trigger step)
    os.environ[c.TEST_DRIVER_SIGKILL_AT_STEP] = str(SIGKILL_AT)
    t0 = time.time()
    try:
        client = TonyClient(conf, poll_interval_s=0.2)
        client.submit()
        client._driver_proc.wait(timeout=180)
    finally:
        os.environ.pop(c.TEST_DRIVER_SIGKILL_AT_STEP, None)
    t_kill = time.time()
    assert client._driver_proc.returncode == -9, (
        f"driver did not SIGKILL itself (rc "
        f"{client._driver_proc.returncode})")
    job_dir = Path(client.job_dir)

    rec, logf = _spawn_recovered_driver(
        job_dir, strip_env=[c.TEST_DRIVER_SIGKILL_AT_STEP])
    try:
        final = _wait_recovered_terminal(job_dir, rec, client.token)
        rec.wait(timeout=60)
    finally:
        if rec.poll() is None:
            import signal as _signal

            os.killpg(rec.pid, _signal.SIGKILL)
        logf.close()
    wall = time.time() - t0
    assert final["status"] == "SUCCEEDED", final

    # ---- forensics: re-adoption, zero outage-attributable restarts
    inter = root / "history/intermediate" / client.app_id
    last = {}
    all_spans = []
    for rec_ in read_traces(inter / TASK_TRACE_FILE):
        last[rec_["id"]] = rec_
        all_spans += [n for n, *_ in rec_["spans"]]
    assert all_spans.count("readopted") == workers, (
        f"expected {workers} readopted tasks, spans: {all_spans}")
    for bad in ("restarted", "preempted", "resized"):
        assert bad not in all_spans, (
            f"outage-attributable '{bad}' relaunch: {all_spans}")
    recoveries = []
    for tid, rec_ in last.items():
        spans = rec_["spans"]
        names = [n for n, *_ in spans]
        assert names[0] == "readopted" and names[-1] == "finished", names
        t_adopt = spans[0][1]
        t_beat = next(t for n, t in spans[1:] if n == "first_heartbeat")
        recoveries.append(
            {"task": tid,
             "readopt_to_first_heartbeat_s": round(t_beat - t_adopt, 3)})
    worst = max(r["readopt_to_first_heartbeat_s"] for r in recoveries)
    assert worst <= 15.0, (
        f"recovery->first-heartbeat {worst}s exceeds the bound")

    # ---- zero recompute: the children never stopped stepping
    per_worker = {}
    for w in range(workers):
        log_path = job_dir / "logs" / f"worker_{w}.steps.jsonl"
        steps = []
        for line in log_path.read_text().splitlines():
            try:
                rec_ = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec_.get("train_step"), int):
                steps.append(rec_["train_step"])
        for prev, cur in zip(steps, steps[1:]):
            assert cur == prev + 1, (
                f"worker_{w}: step discontinuity {prev}->{cur} — the "
                f"outage cost training work")
        assert steps and steps[-1] == TOTAL_STEPS - 1, (
            f"worker_{w} never reached the final step")
        per_worker[f"worker_{w}"] = {"records": len(steps),
                                     "last_step": steps[-1]}
    state = load_state(job_dir / "driver.journal.jsonl")
    assert state is not None and state.recoveries >= 1

    return {
        "job_status": final["status"],
        "sigkill_at_step": SIGKILL_AT,
        "total_steps": TOTAL_STEPS,
        "step_ms": STEP_MS,
        "save_interval": SAVE_INTERVAL,
        "tasks_readopted": workers,
        "worker_restarts": 0,
        "recomputed_steps": 0,
        "recoveries": recoveries,
        "recovery_to_first_heartbeat_s_worst": worst,
        "kill_to_job_success_s": round(time.time() - t_kill, 1),
        "per_worker": per_worker,
        "wall_s": round(wall, 1),
    }


def _failover_fleet_arm() -> dict:
    import signal as _signal
    import tempfile as _tempfile
    import threading

    sys.path.insert(0, str(REPO))
    from tony_tpu import constants as c
    from tony_tpu.client import TonyClient
    from tony_tpu.conf import TonyConf
    from tony_tpu.events.driver_journal import load_state
    from tony_tpu.router import DriverDiscovery, FleetRouter

    # the TINY shape the router e2e uses: the gate is request survival
    # across a control-plane outage, not model throughput
    e = dict(vocab=64, d_model=16, n_layers=1, n_heads=2, d_ff=32,
             slots=2, max_len=96, block_size=4, prefill_chunk=8)
    REQUESTS = 48
    MAX_NEW = 24
    td = _tempfile.mkdtemp(prefix="tony-failover-fleet-")
    root = Path(td)
    serve_cmd = (
        f"{sys.executable} -m tony_tpu.cli.main serve "
        "--port $TONY_SERVE_PORT --host 127.0.0.1 "
        f"--vocab {e['vocab']} --d-model {e['d_model']} "
        f"--n-layers {e['n_layers']} --n-heads {e['n_heads']} "
        f"--d-ff {e['d_ff']} --dtype float32 --seed 0 "
        f"--slots {e['slots']} --max-len {e['max_len']} "
        f"--block-size {e['block_size']} "
        f"--prefill-chunk {e['prefill_chunk']} "
        "--max-queue 64 --drain-timeout-s 2")
    conf = TonyConf({
        "tony.staging.dir": str(root / "staging"),
        "tony.history.location": str(root / "history"),
        "tony.history.intermediate": str(root / "history/intermediate"),
        "tony.history.finished": str(root / "history/finished"),
        "tony.am.monitor-interval-ms": 100,
        "tony.application.framework": "serving",
        "tony.task.heartbeat-interval-ms": 250,
        "tony.task.driver-outage-grace-ms": 60000,
        "tony.serving.healthz-interval-ms": 200,
        "tony.replica.instances": 2,
        "tony.replica.command": serve_cmd,
        "tony.replica.max-restarts": 1,
        # slow each scheduling turn so the burst genuinely spans the
        # driver's death + recovery window
        "tony.execution.env": " ".join([
            f"PYTHONPATH={REPO}", "JAX_PLATFORMS=cpu",
            f"{c.TEST_SERVING_STEP_DELAY_MS}=10"]),
    })
    client = TonyClient(conf, poll_interval_s=0.2)
    client.submit()
    job_dir = Path(client.job_dir)
    router = FleetRouter(
        [], prefill_chunk=e["prefill_chunk"],
        discover=DriverDiscovery(str(job_dir), role="replica",
                                 token=client.token),
        health_interval_s=0.3, eject_after=2, stats_every=2, seed=0)
    results: dict[int, object] = {}
    stale_seen = {"high": False, "cleared_after_high": False}
    rec = logf = None
    try:
        deadline = time.time() + 240
        while time.time() < deadline:
            router.health_tick()
            if router.stats()["live"] == 2:
                break
            time.sleep(0.3)
        assert router.stats()["live"] == 2, (
            f"fleet never came up: {router.stats()}")
        router.start()

        import numpy as np

        rng = np.random.default_rng(7)
        chunk = e["prefill_chunk"]
        templates = [rng.integers(0, e["vocab"], size=chunk,
                                  dtype=np.int32),
                     rng.integers(0, e["vocab"], size=2 * chunk,
                                  dtype=np.int32)]
        prompts = [np.concatenate(
            [templates[i % 2],
             rng.integers(0, e["vocab"], size=1 + i % 3,
                          dtype=np.int32)]).tolist()
            for i in range(REQUESTS)]

        def call(i):
            try:
                results[i] = router.generate(
                    prompts[i], max_new_tokens=MAX_NEW, timeout_s=300)
            except Exception as exc:
                results[i] = exc

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(REQUESTS)]
        t_burst = time.time()
        for i, t in enumerate(threads):
            t.start()
            time.sleep(0.08)
            if i == REQUESTS // 3:
                # mid-burst: SIGKILL the driver. The replicas (own
                # sessions) keep serving; the router flies blind on its
                # last-known fleet until the recovered driver answers.
                os.kill(client._driver_proc.pid, _signal.SIGKILL)
                client._driver_proc.wait(timeout=10)
                t_kill = time.time()
            if i == REQUESTS // 3 + 4:
                # a few requests into the outage: discovery must be
                # marked stale while requests keep completing
                router.health_tick()
                stale_seen["high"] = router.stats()["discovery_stale"]
                rec, logf = _spawn_recovered_driver(job_dir, strip_env=[])
        for t in threads:
            t.join(timeout=300)
        t_done = time.time()
        # recovered driver up + discovery clear again
        deadline = time.time() + 60
        while time.time() < deadline:
            st = router.stats()
            if not st["discovery_stale"] and st["live"] == 2:
                stale_seen["cleared_after_high"] = True
                break
            time.sleep(0.3)
        failed = {i: r for i, r in results.items()
                  if not isinstance(r, dict)}
        assert not failed, (
            f"{len(failed)} requests failed across the driver outage: "
            f"{dict(list(failed.items())[:3])}")
        assert len(results) == REQUESTS
        assert stale_seen["high"], (
            "router never marked discovery stale during the outage")
        assert stale_seen["cleared_after_high"], (
            "discovery never recovered after the driver came back")
        state = load_state(job_dir / "driver.journal.jsonl")
        restarts = sum(t.restarts for t in state.tasks.values())
        assert restarts == 0, (
            f"replicas restarted across the outage: {restarts}")
        by_replica: dict[str, int] = {}
        for r in results.values():
            by_replica[r["replica"]] = by_replica.get(r["replica"], 0) + 1
        return {
            "requests": REQUESTS,
            "failed_requests": 0,
            "replica_restarts": 0,
            "discovery_stale_observed": True,
            "discovery_recovered": True,
            "kill_to_burst_done_s": round(t_done - t_kill, 1),
            "burst_wall_s": round(t_done - t_burst, 1),
            "per_replica_requests": by_replica,
            "driver_recoveries": state.recoveries,
        }
    finally:
        router.shutdown()
        # teardown: SIGTERM the recovered driver (its signal path stops
        # every container, adopted handles included), then hard-reap
        for proc in (rec, client._driver_proc):
            if proc is not None and proc.poll() is None:
                try:
                    os.killpg(proc.pid, _signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
        if rec is not None:
            try:
                rec.wait(timeout=20)
            except subprocess.TimeoutExpired:
                os.killpg(rec.pid, _signal.SIGKILL)
        if logf is not None:
            logf.close()


def main() -> int:
    if "--autoscale" in sys.argv:
        return run_autoscale_bench()
    if "--driver-failover" in sys.argv:
        return run_driver_failover_bench()
    if "--launch-path" in sys.argv:
        return run_launch_path_bench()
    if "--elastic" in sys.argv:
        return run_elastic_bench()
    if "--serving" in sys.argv:
        if "--slo" in sys.argv:
            return run_slo_bench()
        if "--router-ha" in sys.argv:
            return run_router_ha_bench()
        if "--tracing" in sys.argv:
            return run_distributed_tracing_bench()
        if "--paged-kv" in sys.argv:
            return run_paged_kv_bench()
        if "--disagg" in sys.argv:
            return run_disagg_bench()
        if "--streaming" in sys.argv:
            return run_serving_streaming_bench()
        if "--spec" in sys.argv:
            return run_serving_spec_bench()
        if "--replay" in sys.argv:
            return run_serving_replay_bench()
        if "--fleet" in sys.argv:
            return run_serving_fleet_bench()
        if "--overload" in sys.argv or "--chaos" in sys.argv:
            return run_serving_robustness_bench(
                chaos="--chaos" in sys.argv)
        if "--shared-prefix" in sys.argv:
            return run_shared_prefix_bench()
        return run_serving_bench()
    plain_runs, orch_runs, submits = [], [], []
    loads = []
    wall = 0.0
    with tempfile.TemporaryDirectory(prefix="tony-bench-") as td:
        tmp = Path(td)
        for rep in range(PAIRS):
            # pair 0 runs orchestrated first so its launch breakdown is
            # genuinely COLD (a preceding plain run would warm the shared
            # compile cache); later pairs alternate so within-pair drift
            # (link warming, cache effects) hits each arm first equally
            if rep % 2 == 0:
                orch, wall, t_submit, ol = run_orchestrated(tmp, rep)
                plain, pl = run_plain(tmp, rep)
            else:
                plain, pl = run_plain(tmp, rep)
                orch, wall, t_submit, ol = run_orchestrated(tmp, rep)
            orch_runs.append(orch)
            plain_runs.append(plain)
            submits.append(t_submit)
            loads.append({"orchestrated": ol, "plain": pl,
                          "order": "orch_first" if rep % 2 == 0 else "plain_first"})

    plain_all = [round(r["steps_per_sec"], 2) for r in plain_runs]
    orch_all = [round(r["steps_per_sec"], 2) for r in orch_runs]
    plain_sps = max(plain_all)
    orch_sps = max(orch_all)
    # score the MEDIAN of paired ratios: each pair's runs are adjacent in
    # time so the ratio cancels slow tunnel/device drift, and the median is
    # robust to a bad pair in either direction. The per-run rate is the
    # two-point device rate (see module docstring) — the wall-rate pairing
    # is recorded alongside for continuity with r01-r04.
    paired = [
        round(o["steps_per_sec"] / p["steps_per_sec"], 4)
        for o, p in zip(orch_runs, plain_runs)
    ]
    paired_wall = [
        round(o["steps_per_sec_wall"] / p["steps_per_sec_wall"], 4)
        for o, p in zip(orch_runs, plain_runs)
    ]
    vs_baseline = round(statistics.median(paired), 4)
    best_orch = max(orch_runs, key=lambda r: r["steps_per_sec"])
    launch_cold = _launch_breakdown(orch_runs[0], submits[0])
    warm_i = min(range(1, PAIRS),
                 key=lambda i: orch_runs[i]["time_to_first_step_s"],
                 default=0)
    launch_warm = _launch_breakdown(orch_runs[warm_i], submits[warm_i])
    print(
        f"# plain: {plain_sps:.1f} steps/s {plain_all} | "
        f"orchestrated: {orch_sps:.1f} steps/s {orch_all} | "
        f"paired {paired} wall-paired {paired_wall} | "
        f"launch cold: {launch_cold['total_submit_to_first_step_s']:.1f}s "
        f"(orchestration {launch_cold['orchestration_submit_to_exec_s']:.1f}s) | "
        f"warm: {launch_warm['total_submit_to_first_step_s']:.1f}s | "
        f"last job wall: {wall:.1f}s | devices: {best_orch['num_devices']} | "
        f"acc: {best_orch['accuracy']:.3f}",
        file=sys.stderr,
    )
    print(json.dumps({
        "metric": "mnist_steps_per_sec_per_chip_orchestrated",
        "value": round(orch_sps, 2),
        "unit": "steps/s",
        "vs_baseline": vs_baseline,
        "vs_baseline_paired_all": paired,
        "vs_baseline_paired_wall_rate": paired_wall,
        "vs_baseline_max_over_max": round(orch_sps / plain_sps, 4),
        "plain_steps_per_sec_all": plain_all,
        "orchestrated_steps_per_sec_all": orch_all,
        "call_overhead_s_orchestrated": [
            r.get("call_overhead_s") for r in orch_runs],
        "call_overhead_s_plain": [
            r.get("call_overhead_s") for r in plain_runs],
        # any True here means that run's two-point fit was jitter-swamped
        # and fell back to its wall rate — inspect before trusting the pair
        "two_point_degenerate": [
            [r.get("two_point_degenerate") for r in orch_runs],
            [r.get("two_point_degenerate") for r in plain_runs]],
        "host_load_per_pair": loads,
        "launch_cold": launch_cold,
        "launch_warm": launch_warm,
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
