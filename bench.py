"""Benchmark: orchestrated mnist training throughput vs plain jax-on-TPU.

BASELINE.md metric: "mnist steps/sec/chip submitted via the ClusterSubmitter
-equivalent, target >= 90% of plain jax-on-TPU step throughput"
(BASELINE.json north star). This script measures

  1. plain JAX: the mnist train loop of tony_tpu/examples/mnist_jax.py run
     directly as a subprocess on the local accelerator(s)
  2. orchestrated: the SAME script submitted as a 1-worker job through
     TonyClient -> driver -> executor (the ClusterSubmitter path)

and reports orchestrated steps/sec with vs_baseline = orchestrated / plain.
Orchestration happens off the training path (heartbeats + metrics RPC only),
so the ratio should be ~1.0.

Noise control: the accelerator may be reached over a network tunnel whose
latency/load varies run to run, so (a) the workload itself times scan-batched
on-device steps and reports a median-window rate (see mnist_jax.py), and
(b) this script interleaves plain/orchestrated runs (A/B pairs) and scores
each arm by its best run, so both arms face the same environment and a
transient stall in either direction can't fabricate or mask a gap.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import json
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent
STEPS = 6000
STEPS_PER_CALL = 1000
BATCH = 512
PAIRS = 2


def _workload_args(out: Path) -> list[str]:
    return [
        "--steps", str(STEPS), "--steps-per-call", str(STEPS_PER_CALL),
        "--batch-size", str(BATCH), "--metrics-out", str(out),
    ]


def run_plain(tmp: Path, rep: int) -> dict:
    out = tmp / f"plain{rep}.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tony_tpu.examples.mnist_jax",
         *_workload_args(out)],
        cwd=REPO, capture_output=True, text=True, timeout=900,
    )
    if proc.returncode != 0:
        print(proc.stdout, proc.stderr, file=sys.stderr)
        raise RuntimeError("plain jax run failed")
    return json.loads(out.read_text())


def run_orchestrated(tmp: Path, rep: int) -> tuple[dict, float]:
    sys.path.insert(0, str(REPO))
    from tony_tpu.client import TonyClient
    from tony_tpu.conf import TonyConf

    out = tmp / f"orch{rep}.json"
    conf = TonyConf({
        "tony.staging.dir": str(tmp / f"staging{rep}"),
        "tony.history.intermediate": str(tmp / "hist/intermediate"),
        "tony.worker.instances": 1,
        "tony.worker.command": (
            f"{sys.executable} -m tony_tpu.examples.mnist_jax "
            + " ".join(_workload_args(out))
        ),
        "tony.am.monitor-interval-ms": 100,
    })
    client = TonyClient(conf, poll_interval_s=0.1)
    t_submit = time.time()
    client.submit()
    status = client.monitor()
    if status.value != "SUCCEEDED":
        log_dir = Path(client.job_dir)
        for p in sorted(log_dir.rglob("*.std*")) + sorted(log_dir.rglob("*.log")):
            print(f"==== {p} ====\n{p.read_text()[-2000:]}", file=sys.stderr)
        raise RuntimeError(f"orchestrated job finished {status}")
    return json.loads(out.read_text()), time.time() - t_submit


def main() -> int:
    plain_runs, orch_runs = [], []
    wall = 0.0
    with tempfile.TemporaryDirectory(prefix="tony-bench-") as td:
        tmp = Path(td)
        for rep in range(PAIRS):
            plain_runs.append(run_plain(tmp, rep))
            orch, wall = run_orchestrated(tmp, rep)
            orch_runs.append(orch)

    plain_sps = max(r["steps_per_sec"] for r in plain_runs)
    orch_sps = max(r["steps_per_sec"] for r in orch_runs)
    best_orch = max(orch_runs, key=lambda r: r["steps_per_sec"])
    print(
        f"# plain: {plain_sps:.1f} steps/s {[round(r['steps_per_sec'], 1) for r in plain_runs]} | "
        f"orchestrated: {orch_sps:.1f} steps/s {[round(r['steps_per_sec'], 1) for r in orch_runs]} | "
        f"launch-to-first-step: {best_orch['time_to_first_step_s']:.2f}s | "
        f"last job wall: {wall:.1f}s | devices: {best_orch['num_devices']} | "
        f"acc: {best_orch['accuracy']:.3f}",
        file=sys.stderr,
    )
    print(json.dumps({
        "metric": "mnist_steps_per_sec_per_chip_orchestrated",
        "value": round(orch_sps, 2),
        "unit": "steps/s",
        "vs_baseline": round(orch_sps / plain_sps, 4),
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
