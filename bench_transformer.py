"""Transformer perf on the real chip: tokens/s, model FLOP/s, MFU, flash-vs-XLA.

The capability-layer counterpart of bench.py (which measures orchestration
overhead on the mnist workload): this trains the flagship decoder-only
transformer (models/transformer.py) at a fixed config on the local
accelerator and records

  - training throughput in tokens/s (median over timed steps)
  - achieved model FLOP/s and MFU against the chip's peak bf16 FLOP/s
  - the flash-attention (Pallas) vs XLA reference attention speedup at the
    flagship head_dim for fwd+bwd

Writes PERF.json at the repo root (the driver-visible artifact README.md's
perf table is generated from) and prints one JSON line on stdout.

Model-FLOP accounting (matmul terms only, causal attention at L/2 average
context, bwd = 2x fwd — the standard MFU convention):
  fwd/token = sum_layers[2*d*(d + 2*kv) + 2*d^2 + 6*d*d_ff + 2*d*L] + 2*d*V
No reference counterpart: TonY publishes no model-level numbers (BASELINE.md);
this artifact is the rebuild's own "is it actually fast" record.
"""

from __future__ import annotations

import argparse
import json
import statistics
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent

# peak dense bf16 FLOP/s per chip (public spec sheets)
PEAK_BF16 = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5lite": 197e12,     # device_kind reports "TPU v5 lite" on v5e
    "v5litepod": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}


def chip_peak_flops() -> tuple[str, float | None]:
    import jax
    import os

    kind = jax.devices()[0].device_kind.lower()
    for name, peak in PEAK_BF16.items():
        if name in kind.replace(" ", ""):
            return kind, peak
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    if gen in PEAK_BF16:
        return f"{kind} ({gen})", PEAK_BF16[gen]
    return kind, None


def train_flops_per_token(cfg) -> float:
    d, hd = cfg.d_model, cfg.head_dim
    kv = cfg.n_kv_heads * hd
    L = cfg.max_seq_len
    per_layer = (
        2 * d * (d + 2 * kv)      # QKV projections
        + 2 * d * d               # attention output projection
        + 6 * d * cfg.d_ff        # SwiGLU (gate, up, down)
        + 2 * d * L               # causal scores + values at L/2 avg context
    )
    fwd = cfg.n_layers * per_layer + 2 * d * cfg.vocab_size  # + unembed
    return 3.0 * fwd  # bwd = 2x fwd


def bench_train(steps: int, batch: int) -> dict:
    import jax
    # remat "attn" (save the flash kernel's out+lse): +0.5-0.7pp MFU over
    # "full" at L=2048 and the policy every long-context row already uses
    cfg, timing, n_params = _timed_train_run(seq_len=2048, batch=batch,
                                             steps=steps,
                                             remat_policy="attn")
    import jax

    step_s = timing["step_s"]
    toks = batch * cfg.max_seq_len
    fpt = train_flops_per_token(cfg)
    chip, peak = chip_peak_flops()
    n_chips = jax.device_count()
    return {
        "model": {
            "d_model": cfg.d_model, "n_layers": cfg.n_layers,
            "n_heads": cfg.n_heads, "d_ff": cfg.d_ff,
            "vocab_size": cfg.vocab_size, "seq_len": cfg.max_seq_len,
            "params_m": round(n_params / 1e6, 1), "dtype": "bfloat16",
        },
        "batch": batch,
        "tokens_per_step": toks,
        "step_time_s_median": round(step_s, 4),
        "step_times_s": [round(t, 4) for t in timing["window_times"]],
        "compile_plus_first_step_s": round(timing["compile_s"], 1),
        "n_chips": n_chips,
        "tokens_per_sec_per_chip": round(toks / step_s / n_chips, 1),
        "model_tflops_per_sec_per_chip": round(
            fpt * toks / step_s / n_chips / 1e12, 2
        ),
        "train_flops_per_token_g": round(fpt / 1e9, 3),
        "chip": chip,
        "peak_bf16_tflops_per_chip": peak / 1e12 if peak else None,
        "mfu": round(fpt * toks / step_s / (peak * n_chips), 4) if peak else None,
        "mfu_bound_note": (
            "ablated (r05): fwd-only runs at 54.9% of peak, backward ~52%, "
            "adam 2.7% of the step; invariant across batch 8-24 and remat "
            "policies; executed-FLOP utilization incl. remat recompute "
            "~68% - per-shape XLA efficiency bound, see docs/performance.md"
        ),
        "loss_finite": timing["loss_finite"],
        "tpu_metrics_sampled": timing["tpu_metrics"],
    }


def _timed_train_run(seq_len: int, batch: int, steps: int, windows: int = 4,
                     remat_policy: str = "full", attn_window: int = 0):
    """Build the flagship config at `seq_len`, train `windows` timed windows
    of `steps` steps each, and return (cfg, timing, n_params). One timing
    methodology for every train bench: window timing dispatches the steps
    asynchronously with one hard sync per window, amortizing the
    host<->device round-trip (~100ms per blocked call on a tunneled
    accelerator); median over windows rejects transient stalls. Frees the
    run's device state before returning so sequential runs don't stack in
    HBM."""
    import jax
    import jax.numpy as jnp

    from tony_tpu.models import transformer
    from tony_tpu.parallel import MeshSpec, build_mesh
    from tony_tpu.train import create_train_step, synthetic_lm_batch

    cfg = transformer.TransformerConfig(
        vocab_size=32768, d_model=1024, n_layers=12, n_heads=8, n_kv_heads=8,
        d_ff=4096, max_seq_len=seq_len, dtype=jnp.bfloat16, attn_impl="auto",
        remat=True, remat_policy=remat_policy, attn_window=attn_window,
    )
    mesh = build_mesh(MeshSpec(data=-1, fsdp=1))
    bundle = create_train_step(cfg, mesh)
    tokens, targets = synthetic_lm_batch(
        jax.random.PRNGKey(0), batch, seq_len, cfg.vocab_size
    )
    tokens = jax.device_put(tokens, bundle.tok_sharding)
    targets = jax.device_put(targets, bundle.tok_sharding)

    params, opt_state = bundle.params, bundle.opt_state
    n_params = transformer.num_params(params)
    t0 = time.time()
    params, opt_state, m = bundle.step_fn(params, opt_state, tokens, targets)
    float(m["loss"])  # hard sync (device->host transfer)
    compile_s = time.time() - t0

    times = []
    for _ in range(windows):
        t0 = time.time()
        for _ in range(steps):
            params, opt_state, m = bundle.step_fn(
                params, opt_state, tokens, targets
            )
        float(m["loss"])
        times.append((time.time() - t0) / steps)

    # sample the accelerator channel WHILE the training state is live —
    # after the del below, live-buffer accounting (the tunnel chip's only
    # working channel, tony_tpu.metrics) has nothing to report
    from tony_tpu.metrics import sample_tpu_metrics

    tpu_metrics, tpu_reason = sample_tpu_metrics(explain=True)
    timing = {
        "step_s": statistics.median(times),
        "window_times": times,
        "compile_s": compile_s,
        "loss_finite": bool(jnp.isfinite(m["loss"])),
        "tpu_metrics": tpu_metrics or {"unavailable": tpu_reason},
    }
    # drop device references so the next sequence length's model doesn't
    # coexist with this one in HBM
    del bundle, params, opt_state, tokens, targets, m
    return cfg, timing, n_params


def bench_flash_vs_xla(seq_lens=(2048, 4096, 16384), iters: int = 64,
                       reps: int = 3) -> dict:
    """fwd+bwd attention: Pallas flash kernel vs the best compilable XLA
    reference — the materializing O(L^2)-memory reference at short L, the
    chunked+remat baseline (chunked_reference_attention) at L where the
    materializing one cannot compile. Each row records which baseline ran
    (xla_ref_impl), and long rows record the materializing path's
    uncompilability as a structured field, not an error string.

    Each timed call runs `iters` *dependent* grad iterations inside one jit
    (dQ feeds the next Q), so per-iteration time reflects device compute,
    not the per-dispatch round-trip of a tunneled accelerator."""
    import jax
    import jax.numpy as jnp

    from tony_tpu.ops.attention import (
        chunked_reference_attention, flash_attention, reference_attention,
    )

    H, D = 8, 128
    out = {}
    for L in seq_lens:
        B = 4 if L <= 4096 else 1
        n_iters = iters if L <= 4096 else 8
        # the materializing reference's L x L f32 scores (plus backward
        # residuals) stop compiling around L=8k on a 16GB chip
        chunked = L > 8192
        ks = jax.random.split(jax.random.PRNGKey(L), 3)
        q, k, v = (
            jax.random.normal(kk, (B, H, L, D), jnp.bfloat16) for kk in ks
        )

        def flash_loss(q, k, v):
            return flash_attention(q, k, v, causal=True).astype(jnp.float32).sum()

        def ref_loss(q, k, v):
            if chunked:
                o = chunked_reference_attention(q, k, v, causal=True)
            else:
                o = reference_attention(
                    q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                    v.transpose(0, 2, 1, 3), causal=True,
                ).transpose(0, 2, 1, 3)
            return o.astype(jnp.float32).sum()

        def chained(loss_fn):
            grad_fn = jax.grad(loss_fn, argnums=(0, 1, 2))

            @jax.jit
            def run(q, k, v):
                def body(carry, _):
                    q, k, v = carry
                    dq, dk, dv = grad_fn(q, k, v)
                    # dependency chain: next iteration consumes the grads
                    return (q + 1e-6 * dq, k + 1e-6 * dk, v + 1e-6 * dv), ()

                (q, k, v), _ = jax.lax.scan(body, (q, k, v), None,
                                            length=n_iters)
                return q.astype(jnp.float32).sum()

            return run

        results = {}
        for name, fn in (("flash", flash_loss), ("xla_ref", ref_loss)):
            try:
                run = chained(fn)
                float(run(q, k, v))  # compile
                times = []
                for _ in range(reps):
                    t0 = time.time()
                    float(run(q, k, v))
                    times.append(time.time() - t0)
                results[name] = statistics.median(times) / n_iters
            except Exception as e:  # the XLA arm can OOM at long L
                results[name] = None
                results[name + "_error"] = " ".join(str(e).split())[:160]
        row = {"batch": B,
               "xla_ref_impl": ("chunked_remat_q512" if chunked
                                else "materializing")}
        if chunked:
            row["materializing_xla"] = "uncompilable_at_this_L"
            row["enables_regime"] = True  # flash makes 16k+ trainable at all
        for name in ("flash", "xla_ref"):
            row[name + "_ms"] = (round(results[name] * 1e3, 2)
                                 if results[name] else None)
            if results.get(name + "_error"):
                row[name + "_error"] = results[name + "_error"]
        row["speedup"] = (
            round(results["xla_ref"] / results["flash"], 2)
            if results["flash"] and results["xla_ref"] else None
        )
        out[f"L{L}"] = row
    return out


def _two_point(walltime, new_tokens: int, *args) -> tuple[float, float, float]:
    """(wall_long, wall_short, per-step device seconds): the two-point fit
    shared by every decode bench — same program except the decode step
    count, so the subtraction isolates the per-step device cost from the
    fixed per-call (dispatch + prefill) overhead."""
    if new_tokens < 2:
        raise ValueError("two-point fit needs new_tokens >= 2")
    short_new = max(1, new_tokens // 2)
    dt = walltime(new_tokens, *args)
    dt_short = walltime(short_new, *args)
    return dt, dt_short, (dt - dt_short) / (new_tokens - short_new)


def bench_decode(batch: int = 8, prompt_len: int = 128,
                 new_tokens: int = 256, reps: int = 5) -> dict:
    """KV-cache autoregressive decode throughput on the flagship model
    (greedy; the whole prefill+scan loop is one jit, timed with a hard
    sync).

    Wall-clock on a tunneled chip bundles a fixed per-call cost (dispatch
    round trip ~100ms+, plus the one prefill) with the device's per-step
    cost, so a single wall rate under-reports the chip by 30-60%. A
    two-point measurement — SAME prompt, SAME cache capacity (generate's
    max_len pin), different new-token counts — runs the identical program
    except for the decode step count, so
    step_ms = (wall_long - wall_short) / (steps_long - steps_short)
    isolates the per-step device cost exactly. The JSON reports both the
    honest wall rate and the derived device rate, with the residual
    (dispatch + prefill + sampling setup) recorded as call_overhead_s
    (see docs/performance.md roofline)."""
    import jax
    import jax.numpy as jnp

    from tony_tpu.models import transformer
    from tony_tpu.models.generate import generate

    max_len = prompt_len + new_tokens
    cfg = transformer.TransformerConfig(
        vocab_size=32768, d_model=1024, n_layers=12, n_heads=8,
        n_kv_heads=8, d_ff=4096, max_seq_len=max_len,
        dtype=jnp.bfloat16, attn_impl="auto",
    )
    params = jax.jit(lambda k: transformer.init(k, cfg))(jax.random.PRNGKey(0))
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (batch, prompt_len), 0, cfg.vocab_size
    )

    def walltime(n_new: int, kv_dtype: str = "native",
                 weight_dtype: str = "native") -> float:
        kw = dict(max_len=max_len, kv_dtype=kv_dtype,
                  weight_dtype=weight_dtype)
        int(generate(params, cfg, prompt, n_new, **kw)[0, 0])
        times = []
        for _ in range(reps):
            t0 = time.time()
            out = generate(params, cfg, prompt, n_new, **kw)
            int(out[0, 0])  # hard sync
            times.append(time.time() - t0)
        return statistics.median(times)

    dt, _, step_s = _two_point(walltime, new_tokens)
    overhead_s = max(0.0, dt - (new_tokens - 1) * step_s)

    # mitigation measurement for the wall-vs-device gap: a serving loop
    # that keeps several requests in flight dispatches the next generate
    # before syncing the previous, so the fixed per-call cost (tunnel
    # round trip + prefill queueing) overlaps device compute. depth=4
    # identical calls, one hard sync on the last (FIFO queue => all done).
    def pipelined_rate(depth: int = 4) -> float:
        kw = dict(max_len=max_len)
        int(generate(params, cfg, prompt, new_tokens, **kw)[0, 0])  # warm
        times = []
        for _ in range(reps):
            t0 = time.time()
            outs = [generate(params, cfg, prompt, new_tokens, **kw)
                    for _ in range(depth)]
            int(outs[-1][0, 0])
            times.append(time.time() - t0)
        return depth * batch * new_tokens / statistics.median(times)
    # int8 cache arm: device step only (same program shape, half the cache
    # bytes with scale-folded reads)
    _, _, q_step_s = _two_point(walltime, new_tokens, "int8")
    # w8a16 arm: int8 weights AND cache — halves the weight stream that
    # floors decode, scales folded out of every matmul
    _, _, w8_step_s = _two_point(walltime, new_tokens, "int8", "int8")
    return {
        "batch": batch,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "wall_s_median": round(dt, 3),
        "decode_tokens_per_sec": round(batch * new_tokens / dt, 1),
        "per_sequence_tokens_per_sec": round(new_tokens / dt, 1),
        "device_step_ms": round(step_s * 1000, 3),
        "device_tokens_per_sec": round(batch / step_s, 1),
        "call_overhead_s": round(overhead_s, 3),
        "pipelined_depth4_tokens_per_sec": round(pipelined_rate(), 1),
        "int8_cache_device_step_ms": round(q_step_s * 1000, 3),
        "int8_cache_device_tokens_per_sec": round(batch / q_step_s, 1),
        "int8_weights_cache_device_step_ms": round(w8_step_s * 1000, 3),
        "int8_weights_cache_device_tokens_per_sec": round(
            batch / w8_step_s, 1),
    }


def bench_moe_decode(batch: int = 8, prompt_len: int = 128,
                     new_tokens: int = 128, reps: int = 5) -> dict:
    """MoE decode on a routed flagship variant (8 experts, top-2, same
    d_model/layers as the dense flagship): native vs w8a16 expert weights.
    Einsum-dispatch MoE streams ALL E experts' weights every step (static
    shapes — routing picks capacity slots, not which weights load), so the
    weight stream is ~E/2x the dense model's MLP stream and int8 halves it.
    Same two-point device-step methodology as bench_decode."""
    import jax
    import jax.numpy as jnp

    from tony_tpu.models import transformer
    from tony_tpu.models.generate import generate, prepare_decode

    max_len = prompt_len + new_tokens
    cfg = transformer.TransformerConfig(
        vocab_size=32768, d_model=1024, n_layers=12, n_heads=8,
        n_kv_heads=8, d_ff=2048, n_experts=8, expert_top_k=2,
        max_seq_len=max_len, dtype=jnp.bfloat16, attn_impl="auto",
    )
    params = jax.jit(lambda k: transformer.init(k, cfg))(jax.random.PRNGKey(0))
    n_params = transformer.num_params(params)
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (batch, prompt_len), 0, cfg.vocab_size
    )

    def walltime(n_new: int, weight_dtype: str) -> float:
        # prepare once outside the timed region (servers hold prebuilt
        # weights); the jit itself is cached across calls
        prep = prepare_decode(params, cfg, weight_dtype=weight_dtype)
        kw = dict(max_len=max_len, kv_dtype="int8")
        int(generate(prep, cfg, prompt, n_new, **kw)[0, 0])
        times = []
        for _ in range(reps):
            t0 = time.time()
            out = generate(prep, cfg, prompt, n_new, **kw)
            int(out[0, 0])
            times.append(time.time() - t0)
        return statistics.median(times)

    _, _, step_s = _two_point(walltime, new_tokens, "native")
    _, _, w8_step_s = _two_point(walltime, new_tokens, "int8")
    return {
        "model": {"n_experts": cfg.n_experts, "top_k": cfg.expert_top_k,
                  "d_ff": cfg.d_ff, "params_m": round(n_params / 1e6, 1)},
        "batch": batch,
        "kv_dtype": "int8",
        "device_step_ms": round(step_s * 1000, 3),
        "device_tokens_per_sec": round(batch / step_s, 1),
        "w8_device_step_ms": round(w8_step_s * 1000, 3),
        "w8_device_tokens_per_sec": round(batch / w8_step_s, 1),
        "w8_speedup": round(step_s / w8_step_s, 2),
    }


def bench_long_decode(prompt_len: int = 16384, new_tokens: int = 64,
                      reps: int = 3) -> dict:
    """Long-context serving: prefill a 16k-token prompt (the flash kernel,
    O(block) memory) then decode against the full-length int8 cache —
    the serve-side counterpart of the long-context training rows. The
    two-point fit splits per-step decode cost (attention over the 16k
    cache dominates) from the one-time prefill."""
    import jax
    import jax.numpy as jnp

    from tony_tpu.models import transformer
    from tony_tpu.models.generate import generate, prepare_decode

    cfg = transformer.TransformerConfig(
        vocab_size=32768, d_model=1024, n_layers=12, n_heads=8,
        n_kv_heads=8, d_ff=4096, max_seq_len=prompt_len + new_tokens,
        dtype=jnp.bfloat16, attn_impl="auto",
    )
    params = jax.jit(lambda k: transformer.init(k, cfg))(jax.random.PRNGKey(0))
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (1, prompt_len), 0, cfg.vocab_size)
    prep = prepare_decode(params, cfg)
    max_len = prompt_len + new_tokens

    def wall(n):
        kw = dict(max_len=max_len, kv_dtype="int8")
        int(generate(prep, cfg, prompt, n, **kw)[0, 0])
        times = []
        for _ in range(reps):
            t0 = time.time()
            int(generate(prep, cfg, prompt, n, **kw)[0, 0])
            times.append(time.time() - t0)
        return statistics.median(times)

    dt, _, step_s = _two_point(wall, new_tokens)
    prefill_s = max(0.0, dt - (new_tokens - 1) * step_s)
    # HBM roofline for this step: int8 KV (+bf16 scales) + the bf16 weight
    # stream, over the chip's ~819GB/s. The flash-decode kernel streams
    # the cache at ~1.2x its own bound standalone; the step-level residual
    # is scheduling around the cache writes (docs/performance.md).
    Ly, kvH, D, d, dff, V = 12, 8, 128, 1024, 4096, 32768
    M = prompt_len + new_tokens
    step_bytes = (Ly * 2 * kvH * M * D * 1            # int8 KV read
                  + Ly * 2 * kvH * M * 2              # scales
                  + Ly * (d * 3 * d + d * d + 3 * d * dff) * 2
                  + d * V * 2)                        # weights + unembed
    bound_ms = step_bytes / 819e9 * 1e3
    return {
        "prompt_len": prompt_len, "new_tokens": new_tokens, "batch": 1,
        "kv_dtype": "int8",
        "wall_s": round(dt, 3),
        "decode_step_ms": round(step_s * 1e3, 3),
        "decode_tokens_per_sec": round(1.0 / step_s, 1),
        "hbm_bound_step_ms": round(bound_ms, 3),
        "pct_of_hbm_bound": round(bound_ms / (step_s * 1e3), 3),
        "prefill_plus_overhead_s": round(prefill_s, 3),
        "prefill_tokens_per_sec": round(prompt_len / prefill_s, 1),
    }


def bench_serving(slots: int = 8, n_requests: int = 24,
                  reps: int = 3) -> dict:
    """Continuous batching vs static batching on the flagship model, over
    the mixed workload a live service actually sees: prompt lengths AND
    generation budgets both vary per request. The static comparator is
    the strongest strategy generate() supports: group requests by prompt
    length (it requires equal-length prompts per batch), run each group
    as one batch to its LONGEST budget (no per-row budget exists — that
    is static batching's structural cost). The slot pool takes the same
    requests FIFO, chunk-prefills each into a freed slot, and retires
    each at its own budget. Same prepared weights, same cache capacity,
    same useful-token count in both arms; wall-clock includes each arm's
    real scheduling overhead — the slot pool pays its admission
    dispatches and result transfers (a tunnel round trip each, ~0 on a
    real TPU host), the static arm pays one sync per run."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tony_tpu.models import transformer
    from tony_tpu.models.generate import generate, prepare_decode
    from tony_tpu.models.serving import Request, SlotServer

    budgets = [64, 256, 96, 160, 32, 224, 128, 192]   # mean 144, max 256
    plens = [64, 96, 160, 256]
    max_new = [budgets[i % len(budgets)] for i in range(n_requests)]
    plen = [plens[(i // 2) % len(plens)] for i in range(n_requests)]
    max_len = max(plens) + max(budgets)
    cfg = transformer.TransformerConfig(
        vocab_size=32768, d_model=1024, n_layers=12, n_heads=8,
        n_kv_heads=8, d_ff=4096, max_seq_len=max_len,
        dtype=jnp.bfloat16, attn_impl="auto",
    )
    params = jax.jit(lambda k: transformer.init(k, cfg))(jax.random.PRNGKey(0))
    prep = prepare_decode(params, cfg)
    prompts = [
        np.asarray(jax.random.randint(
            jax.random.PRNGKey(100 + i), (plen[i],), 0, cfg.vocab_size),
            np.int32)
        for i in range(n_requests)
    ]
    useful = sum(max_new)

    def serving_wall() -> float:
        times = []
        for _ in range(reps + 1):       # first run compiles, dropped below
            srv = SlotServer(prep, cfg, slots=slots, max_len=max_len,
                             block_size=32, prefill_chunk=max(plens))
            t0 = time.time()
            for p, mn in zip(prompts, max_new):
                srv.submit(Request(prompt=p, max_new_tokens=mn))
            done = srv.run_until_drained()
            times.append(time.time() - t0)
            assert len(done) == n_requests
        return statistics.median(times[1:])

    def static_wall() -> float:
        groups: dict[int, list[int]] = {}
        for i, L in enumerate(plen):
            groups.setdefault(L, []).append(i)
        batches = []
        for L, idxs in groups.items():
            for j in range(0, len(idxs), slots):
                part = idxs[j:j + slots]
                batches.append((
                    jnp.asarray(np.stack([prompts[i] for i in part])),
                    max(max_new[i] for i in part),
                ))
        for b, mn in batches:           # warm every (shape, mn) program
            int(generate(prep, cfg, b, mn, max_len=max_len)[0, 0])
        times = []
        for _ in range(reps):
            t0 = time.time()
            outs = [generate(prep, cfg, b, mn, max_len=max_len)
                    for b, mn in batches]
            int(outs[-1][0, 0])         # FIFO queue: last done = all done
            times.append(time.time() - t0)
        return statistics.median(times)

    st = static_wall()
    sv = serving_wall()
    return {
        "slots": slots, "n_requests": n_requests,
        "prompt_lens_cycle": plens, "budgets_cycle": budgets,
        "useful_tokens": useful,
        "continuous_wall_s": round(sv, 3),
        "continuous_tokens_per_sec": round(useful / sv, 1),
        "static_batch_wall_s": round(st, 3),
        "static_batch_tokens_per_sec": round(useful / st, 1),
        "continuous_over_static": round(st / sv, 3),
    }


def _markov_batch(rng, succ, batch, seq_len):
    """Sequences from a sparse first-order chain: each state follows its
    primary successor w.p. 0.85, its secondary otherwise — enough entropy
    that nothing is memorizable verbatim, enough structure that a trained
    model's greedy continuation is predictable by a SMALLER trained model
    (the real-world condition speculative decoding exploits)."""
    import numpy as np

    V = succ.shape[0]
    x = np.empty((batch, seq_len + 1), np.int32)
    x[:, 0] = rng.integers(0, V, batch)
    for t in range(seq_len):
        pick = rng.random(batch) < 0.85
        x[:, t + 1] = np.where(pick, succ[x[:, t], 0], succ[x[:, t], 1])
    return x[:, :-1], x[:, 1:]


def bench_spec_decode(prompt_len: int = 64, new_tokens: int = 256,
                      gamma: int = 4, reps: int = 5,
                      train_steps: int = 500) -> dict:
    """Speculative decode measured FOR REAL: a flagship-dimension target
    and a 33x-smaller draft are both trained on-chip on the same Markov
    corpus (~1 min), so the draft's agreement with the target is the
    genuine article — the same-distribution alignment a production
    draft/target pair has — not a modeled parameter. Reports measured
    acceptance, measured wall speedup, and the two-point device-side
    speedup (both arms same discipline, RTT cancelled). The acceptance-0
    floor (a round's cost when every draft is rejected) stays as the
    honest worst case; the speedup-vs-acceptance curve is a footnote
    derived from the same measured costs."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tony_tpu.models import transformer
    from tony_tpu.models.generate import generate, prepare_decode
    from tony_tpu.models.speculative import speculative_generate
    from tony_tpu.parallel import MeshSpec, build_mesh
    from tony_tpu.train import create_train_step

    V = 4096                    # flagship dims, LM-learnable vocab
    max_len = prompt_len + new_tokens
    cfg = transformer.TransformerConfig(
        vocab_size=V, d_model=1024, n_layers=12, n_heads=8,
        n_kv_heads=8, d_ff=4096, max_seq_len=max(512, max_len),
        dtype=jnp.bfloat16, attn_impl="auto",
    )
    draft = transformer.TransformerConfig(
        vocab_size=V, d_model=256, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=1024, max_seq_len=max(512, max_len),
        dtype=jnp.bfloat16, attn_impl="auto",
    )
    rng = np.random.default_rng(0)
    succ = rng.integers(0, V, (V, 2)).astype(np.int32)

    def train(model_cfg, steps, seed):
        mesh = build_mesh(MeshSpec(data=-1, fsdp=1))
        bundle = create_train_step(model_cfg, mesh,
                                   key=jax.random.PRNGKey(seed))
        params, opt = bundle.params, bundle.opt_state
        r = np.random.default_rng(seed)
        for chunk in range(steps // 50):
            for _ in range(50):
                tk, tg = _markov_batch(r, succ, 16, 128)
                params, opt, m = bundle.step_fn(
                    params, opt, jnp.asarray(tk), jnp.asarray(tg))
            float(m["loss"])    # sync per 50-step window
        return params, float(m["loss"])

    tp_raw, t_loss = train(cfg, train_steps, seed=0)
    dp_raw, d_loss = train(draft, train_steps, seed=1)
    tp = prepare_decode(tp_raw, cfg)
    dp = prepare_decode(dp_raw, draft)
    del tp_raw, dp_raw

    # held-out prompts drawn from the same chain
    er = np.random.default_rng(99)
    pt, _ = _markov_batch(er, succ, 1, prompt_len)
    prompt = jnp.asarray(pt)

    def vanilla_wall(n_new):
        int(generate(tp, cfg, prompt, n_new, max_len=max_len)[0, 0])
        times = []
        for _ in range(reps):
            t0 = time.time()
            int(generate(tp, cfg, prompt, n_new, max_len=max_len)[0, 0])
            times.append(time.time() - t0)
        return statistics.median(times)

    def spec_wall(n_new):
        int(speculative_generate(tp, cfg, dp, draft, prompt, n_new,
                                 gamma=gamma)[0, 0])
        times = []
        for _ in range(reps):
            t0 = time.time()
            int(speculative_generate(tp, cfg, dp, draft, prompt, n_new,
                                     gamma=gamma)[0, 0])
            times.append(time.time() - t0)
        return statistics.median(times)

    wall_plain, _, step_s = _two_point(vanilla_wall, new_tokens)
    wall_spec, _, spec_tok_s = _two_point(spec_wall, new_tokens)
    # acceptance measured over several held-out prompts
    accs, delivered = [], 0
    for i in range(4):
        p, _ = _markov_batch(np.random.default_rng(100 + i), succ, 1,
                             prompt_len)
        _, stats = speculative_generate(
            tp, cfg, dp, draft, jnp.asarray(p), new_tokens, gamma=gamma,
            return_stats=True)
        accs.append(stats["acceptance_rate"])
        delivered += stats["delivered"]
    acceptance = float(np.mean(accs))

    # acceptance-0 floor from the same measured costs: per-round cost via
    # a random-init draft (agreement ~0 -> two-point isolates the round)
    dp0 = prepare_decode(
        jax.jit(lambda k: transformer.init(k, draft))(jax.random.PRNGKey(7)),
        draft)

    def spec0_wall(n_new):
        int(speculative_generate(tp, cfg, dp0, draft, prompt, n_new,
                                 gamma=gamma)[0, 0])
        times = []
        for _ in range(reps):
            t0 = time.time()
            int(speculative_generate(tp, cfg, dp0, draft, prompt, n_new,
                                     gamma=gamma)[0, 0])
            times.append(time.time() - t0)
        return statistics.median(times)

    _, _, round_s = _two_point(spec0_wall, new_tokens)

    def modeled(a):
        e = sum(a ** i for i in range(gamma + 1))  # expected tokens/round
        return round(e * step_s / round_s, 2)

    return {
        "gamma": gamma,
        "target_params_m": round(
            transformer.num_params(tp.params) / 1e6, 1),
        "draft_params_m": round(
            transformer.num_params(dp.params) / 1e6, 1),
        "trained_on": f"markov chain V={V}, {train_steps} steps each "
                      f"(losses {t_loss:.3f} / {d_loss:.3f})",
        "measured_acceptance": round(acceptance, 3),
        "measured_wall_speedup": round(wall_plain / wall_spec, 2),
        "measured_device_speedup": round(step_s / spec_tok_s, 2),
        "target_step_ms": round(step_s * 1e3, 3),
        "spec_ms_per_token": round(spec_tok_s * 1e3, 3),
        "new_tokens": new_tokens,
        "footnote_round_ms": round(round_s * 1e3, 3),
        "footnote_speedup_at_acceptance_0": modeled(0.0),
        "footnote_modeled_speedup_at_0.8": modeled(0.8),
    }


# constant token budget per step across the long-context sweep, so MFU and
# tokens/s are comparable between sequence lengths
TOKENS_PER_STEP = 16384


def bench_long_context(seq_lens=(8192, 16384, 32768), steps: int = 4,
                       prior: dict | None = None) -> dict:
    """Train the flagship at long context on one chip — constant tokens/step
    (batch shrinks as L grows), remat on, streaming flash kernels. The
    point: quadratic-attention MFU holds up and HBM doesn't blow. A length
    that fails (e.g. transient OOM) records the error but keeps that key's
    previously recorded numbers from `prior` alongside, so one bad rerun
    can't silently erase the artifact's history."""
    out = {}
    for L in seq_lens:
        batch = max(1, TOKENS_PER_STEP // L)
        try:
            # remat_policy="attn" pins the flash forward's (out, lse)
            # residuals so the backward never re-runs it — the recompute
            # that "full" pays grows quadratically with L (+7.5% at 8k,
            # +17% at 32k; neutral at 2k where the resident kernel is cheap)
            cfg, timing, _ = _timed_train_run(seq_len=L, batch=batch,
                                              steps=steps, windows=3,
                                              remat_policy="attn")
            st = timing["step_s"]
            toks = batch * L
            fpt = train_flops_per_token(cfg)
            _, peak = chip_peak_flops()
            out[f"L{L}"] = {
                "batch": batch,
                "step_time_s": round(st, 3),
                "tokens_per_sec": round(toks / st, 1),
                "mfu": round(fpt * toks / st / peak, 4) if peak else None,
                "loss_finite": timing["loss_finite"],
                "attn_share_of_model_flops": round(
                    cfg.n_layers * 2 * cfg.d_model * L / (fpt / 3.0), 3
                ),
            }
        except Exception as e:
            entry = {"error": str(e)[:200]}
            if prior and isinstance(prior.get(f"L{L}"), dict):
                entry["last_good"] = {
                    k: v for k, v in prior[f"L{L}"].items() if k != "error"
                }
            out[f"L{L}"] = entry

    # sliding-window showcase at the longest L: the band-pruned kernel's
    # O(L*window) cost vs full causal's O(L^2) (window 4096 ~= mistral).
    # Only meaningful when the band is a strict subset of the sequence.
    L, win = max(seq_lens, default=0), 4096
    if L <= win:
        return out
    key = f"L{L}_window{win}"
    batch = max(1, TOKENS_PER_STEP // L)
    try:
        _, timing, _ = _timed_train_run(
            seq_len=L, batch=batch, steps=steps, windows=3,
            remat_policy="attn", attn_window=win,
        )
        toks = batch * L
        out[key] = {
            "batch": batch,
            "attn_window": win,
            "step_time_s": round(timing["step_s"], 3),
            "tokens_per_sec": round(toks / timing["step_s"], 1),
            "loss_finite": timing["loss_finite"],
        }
    except Exception as e:
        entry = {"error": str(e)[:200]}
        if prior and isinstance(prior.get(key), dict):
            entry["last_good"] = {
                k: v for k, v in prior[key].items() if k != "error"
            }
        out[key] = entry
    return out


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--out", default=str(REPO / "PERF.json"))
    parser.add_argument("--skip-attn", action="store_true")
    parser.add_argument("--skip-decode", action="store_true")
    parser.add_argument("--skip-long", action="store_true")
    args = parser.parse_args()

    perf = {"train": bench_train(args.steps, args.batch)}
    # the executor-side TPU sampler, exercised mid-train (while state is
    # live in HBM — bench_train stashes the sample); when no channel
    # serves data the artifact records WHY instead of a bare {}
    perf["tpu_metrics_sampled"] = perf["train"].pop(
        "tpu_metrics_sampled", {"unavailable": "train bench did not run"})
    try:
        prior = json.loads(Path(args.out).read_text())
    except (OSError, ValueError):
        prior = {}  # absent or corrupt (e.g. a prior run killed mid-write)
    # skipped sections keep their values from a prior full run
    if not args.skip_attn:
        perf["flash_vs_xla_fwd_bwd"] = bench_flash_vs_xla()
    elif "flash_vs_xla_fwd_bwd" in prior:
        perf["flash_vs_xla_fwd_bwd"] = prior["flash_vs_xla_fwd_bwd"]
    if not args.skip_decode:
        perf["kv_cache_decode"] = bench_decode(batch=args.batch)
        perf["moe_decode"] = bench_moe_decode(batch=args.batch)
        perf["speculative_decode"] = bench_spec_decode()
        perf["long_context_decode"] = bench_long_decode()
        perf["continuous_batching"] = bench_serving()
    elif "kv_cache_decode" in prior:
        for k in ("kv_cache_decode", "moe_decode", "speculative_decode",
                  "long_context_decode", "continuous_batching"):
            if k in prior:
                perf[k] = prior[k]
    if not args.skip_long:
        perf["long_context_train"] = bench_long_context(
            prior=prior.get("long_context_train")
        )
    elif "long_context_train" in prior:
        perf["long_context_train"] = prior["long_context_train"]

    Path(args.out).write_text(json.dumps(perf, indent=2) + "\n")
    t = perf["train"]
    print(json.dumps({
        "metric": "transformer_tokens_per_sec_per_chip",
        "value": t["tokens_per_sec_per_chip"],
        "unit": "tokens/s",
        "mfu": t["mfu"],
        "model_tflops_per_sec_per_chip": t["model_tflops_per_sec_per_chip"],
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
