"""CLI, proxy, and portal tests — reference tony-cli tests + portal
controller/BrowserTest round-trips."""

import json
import socket
import sys
import threading
import urllib.request

from tony_tpu.cli.main import main as cli_main
from tony_tpu.cli.proxy import ProxyServer
from tony_tpu.conf import TonyConf
from tony_tpu.portal.server import serve_portal

PY = sys.executable


def test_cli_local_submit(tmp_job_dirs, fixture_script, capsys):
    rc = cli_main([
        "local",
        "--command", f"{PY} {fixture_script('exit_0.py')}",
        "--instances", "2",
        "-D", f"tony.staging.dir={tmp_job_dirs['staging']}",
        "-D", f"tony.history.intermediate={tmp_job_dirs['history']}/intermediate",
        "-D", "tony.am.monitor-interval-ms=100",
    ])
    assert rc == 0


def test_cli_notebook_proxy_fetch(tmp_job_dirs, fixture_script):
    """Notebook submitter end-to-end: single-node app + local tunnel, HTTP
    round-trip through the proxy (reference NotebookSubmitter.java:71-133)."""
    import re
    import subprocess

    proc = subprocess.Popen(
        [PY, "-m", "tony_tpu.cli.main", "notebook",
         "--command", f"{PY} {fixture_script('mini_notebook.py')}",
         "--timeout-ms", "120000",
         "-D", f"tony.staging.dir={tmp_job_dirs['staging']}",
         "-D", f"tony.history.intermediate={tmp_job_dirs['history']}/intermediate",
         "-D", "tony.am.monitor-interval-ms=100"],
        stderr=subprocess.PIPE, text=True,
    )
    try:
        url = None
        for line in proc.stderr:
            m = re.search(r"notebook reachable at (http://\S+)", line)
            if m:
                url = m.group(1)
                break
        assert url, "notebook tunnel URL never printed"
        # the notebook server may take a beat to bind after RUNNING
        body = b""
        for _ in range(50):
            try:
                body = urllib.request.urlopen(url, timeout=2).read()
                break
            except OSError:
                import time

                time.sleep(0.2)
        assert body == b"mini-notebook-ok"
    finally:
        proc.terminate()  # CLI's SIGTERM hook kills the whole app tree
        proc.wait(timeout=10)


def test_cli_local_failure_exit_code(tmp_job_dirs, fixture_script):
    rc = cli_main([
        "local",
        "--command", f"{PY} {fixture_script('exit_1.py')}",
        "-D", f"tony.staging.dir={tmp_job_dirs['staging']}",
        "-D", f"tony.history.intermediate={tmp_job_dirs['history']}/intermediate",
        "-D", "tony.am.monitor-interval-ms=100",
    ])
    assert rc == 1


def test_proxy_tunnels_bytes():
    # echo server
    upstream = socket.socket()
    upstream.bind(("127.0.0.1", 0))
    upstream.listen(1)
    up_port = upstream.getsockname()[1]

    def echo():
        conn, _ = upstream.accept()
        while True:
            data = conn.recv(4096)
            if not data:
                return
            conn.sendall(data.upper())

    threading.Thread(target=echo, daemon=True).start()

    proxy = ProxyServer("127.0.0.1", up_port)
    proxy.start()
    try:
        client = socket.create_connection(("127.0.0.1", proxy.local_port), timeout=5)
        client.sendall(b"hello tunnel")
        assert client.recv(4096) == b"HELLO TUNNEL"
        client.close()
    finally:
        proxy.stop()
        upstream.close()


def test_portal_paging_sorting_and_token(tmp_path):
    """300 synthetic jobs page correctly through the JS-free sort/page
    query params (the reference's DataTables index,
    tony-portal/app/views/index.scala.html), and every route answers 401
    without the bearer token (tony.portal.token)."""
    from tony_tpu.events.history import history_file_name

    inter = tmp_path / "hist" / "intermediate"
    for i in range(300):
        job = inter / f"app_{i:04d}"
        job.mkdir(parents=True)
        name = history_file_name(
            f"app_{i:04d}", start_ms=1_000_000 + i * 1000,
            end_ms=1_000_000 + i * 1000 + 500,
            user=f"user{i % 7}", status="SUCCEEDED" if i % 3 else "FAILED",
        )
        (job / name).write_text("")
    conf = TonyConf({
        "tony.staging.dir": str(tmp_path / "staging"),
        "tony.history.intermediate": str(inter),
        "tony.history.finished": str(tmp_path / "hist" / "finished"),
        "tony.portal.token": "s3cret",
    })
    server = serve_portal(conf, port=0, block=False)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        def get(path, accept="application/json", token="s3cret",
                via_header=True):
            headers = {"Accept": accept}
            if token and via_header:
                headers["Authorization"] = f"Bearer {token}"
            elif token:
                path += ("&" if "?" in path else "?") + f"token={token}"
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", headers=headers
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.status, resp.read().decode()

        # --- auth: every route 401s without the token, both auth forms work
        for path in ("/", "/jobs/app_0001", "/config/app_0001",
                     "/logs/app_0001"):
            try:
                get(path, token="")
                assert False, f"expected 401 for {path}"
            except urllib.error.HTTPError as e:
                assert e.code == 401
        try:
            get("/", token="wrong")
            assert False, "expected 401 for a bad token"
        except urllib.error.HTTPError as e:
            assert e.code == 401
        # non-ASCII token must be a clean 401, not a handler crash
        try:
            get("/?token=%C3%A9", token="")
            assert False, "expected 401 for a non-ascii token"
        except urllib.error.HTTPError as e:
            assert e.code == 401
        assert get("/", via_header=True)[0] == 200
        assert get("/?page=2", via_header=False)[0] == 200

        # --- bare JSON index keeps the pre-paging contract: the FULL list
        _, body = get("/")
        jobs = json.loads(body)
        assert len(jobs) == 300
        assert jobs[0]["app_id"] == "app_0299"  # newest first

        # --- explicit sort + paging opts into the metadata envelope
        _, body = get("/?sort=job&dir=asc&page=3&per=100")
        env = json.loads(body)
        assert (env["total"], env["pages"], env["page"]) == (300, 3, 3)
        jobs = env["jobs"]
        assert len(jobs) == 100
        assert jobs[0]["app_id"] == "app_0200"
        assert jobs[-1]["app_id"] == "app_0299"

        # --- last page is the remainder; out-of-range clamps to it
        _, body = get("/?per=70&page=99")
        assert len(json.loads(body)["jobs"]) == 300 - 4 * 70

        # --- sort by user, status works
        _, body = get("/?sort=user&dir=desc&per=5")
        assert [j["user"] for j in json.loads(body)["jobs"]] == ["user6"] * 5
        _, body = get("/?sort=status&dir=asc&per=5")
        assert all(j["status"] == "FAILED"
                   for j in json.loads(body)["jobs"])

        # --- browser flow: ?token= is exchanged for an HttpOnly cookie +
        # redirect to a token-free URL; HTML never reflects the token into
        # hrefs (it would leak via history/shared links/access logs)
        import http.cookiejar

        jar = http.cookiejar.CookieJar()
        opener = urllib.request.build_opener(
            urllib.request.HTTPCookieProcessor(jar)
        )

        def browse(path):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}",
                headers={"Accept": "text/html"},
            )
            with opener.open(req, timeout=10) as resp:
                return resp.status, resp.url, resp.read().decode()

        status, final_url, body = browse(
            "/?sort=job&dir=asc&per=20&page=2&token=s3cret"
        )
        assert status == 200
        assert "token" not in final_url, "redirect must strip the token"
        assert {c.name for c in jar} == {"tony_portal_token"}
        assert "page 2/15" in body
        assert "next &raquo;" in body and "&laquo; prev" in body
        assert "s3cret" not in body, "token reflected into HTML"

        # --- the cookie alone now authorizes every route, token-free links
        status, _, body = browse("/jobs/app_0001")
        assert status == 200
        assert "/config/app_0001" in body and "/logs/app_0001" in body
        assert "s3cret" not in body

        # --- a WRONG query token 401s without setting any cookie
        try:
            browse("/?token=wrong&x=1")
            assert False, "expected 401 for a bad browser token"
        except urllib.error.HTTPError as e:
            assert e.code == 401
    finally:
        server.shutdown()
        server.server_close()


def test_portal_cookie_survives_delimiter_token_and_blocks_open_redirect(
        tmp_path):
    """A token containing cookie delimiters (';', '=', spaces) must survive
    the Set-Cookie round-trip (the value is %-quoted, not sent raw —
    'abc;def' raw would truncate to 'abc' and 401 every following request),
    and a scheme-relative '//evil.com' path must not become an off-site
    Location after the token→cookie exchange."""
    import http.cookiejar

    tok = "a b;c=d,é"
    inter = tmp_path / "hist" / "intermediate"
    inter.mkdir(parents=True)
    conf = TonyConf({
        "tony.staging.dir": str(tmp_path / "staging"),
        "tony.history.intermediate": str(inter),
        "tony.history.finished": str(tmp_path / "hist" / "finished"),
        "tony.portal.token": tok,
    })
    server = serve_portal(conf, port=0, block=False)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        jar = http.cookiejar.CookieJar()
        opener = urllib.request.build_opener(
            urllib.request.HTTPCookieProcessor(jar)
        )

        def browse(path):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}",
                headers={"Accept": "text/html"},
            )
            with opener.open(req, timeout=10) as resp:
                return resp.status, resp.url

        from urllib.parse import quote
        status, final_url = browse("/?token=" + quote(tok))
        assert status == 200 and "token" not in final_url
        # the cookie alone must authorize the next request (round-trip
        # preserved the delimiter characters)
        assert browse("/")[0] == 200

        # open-redirect guard: '//evil.com/' collapses to the on-site path
        # '/evil.com/' — the portal 404s it rather than emitting a
        # scheme-relative Location the browser would follow off-site
        try:
            browse("//evil.com/?token=" + quote(tok))
            assert False, "expected on-site 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
            assert f"127.0.0.1:{port}" in e.url, \
                f"scheme-relative redirect escaped the portal: {e.url}"
    finally:
        server.shutdown()
        server.server_close()


def test_portal_request_timeline_and_metrics(tmp_path):
    """Observability routes: /traces/<id> renders the per-request
    waterfall from the job's requests.trace.jsonl (written by ``serve
    --trace-dir``), JSON and HTML, 404s cleanly when absent; /metrics
    serves the portal's own counters/latency in parseable Prometheus
    text."""
    import re

    from tony_tpu.events.history import history_file_name
    from tony_tpu.events.trace import TraceWriter

    inter = tmp_path / "hist" / "intermediate"
    job = inter / "app_traced"
    job.mkdir(parents=True)
    (job / history_file_name("app_traced", 1000, end_ms=9000, user="u",
                             status="SUCCEEDED")).write_text("")
    bare = inter / "app_bare"           # history but no trace file
    bare.mkdir(parents=True)
    (bare / history_file_name("app_bare", 1000, end_ms=2000, user="u",
                              status="SUCCEEDED")).write_text("")
    w = TraceWriter(job)
    w.write({"id": 0, "spans": [
        ["submitted", 10.0], ["admitted", 10.4], ["prefill_done", 10.5],
        ["first_token", 11.0], ["finished", 12.5]],
        "attrs": {"n_tokens": 9, "finish_reason": "length",
                  "prefix_hit_blocks": 2, "submitted_unix": 1700.0}})
    w.write({"id": 1, "spans": [["submitted", 10.1], ["shed", 10.11]],
             "attrs": {"finish_reason": "shed", "submitted_unix": 1700.1}})
    # valid JSON, malformed shape: must not 500 the timeline page
    w.write({"id": 9, "spans": [["submitted"]]})
    w.close()

    conf = TonyConf({
        "tony.staging.dir": str(tmp_path / "staging"),
        "tony.history.intermediate": str(inter),
        "tony.history.finished": str(tmp_path / "hist" / "finished"),
    })
    server = serve_portal(conf, port=0, block=False)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        def get(path, accept="application/json"):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", headers={"Accept": accept})
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.status, resp.headers, resp.read().decode()

        # JSON: the parsed records, verbatim (malformed one included)
        status, _, body = get("/traces/app_traced")
        traces = json.loads(body)
        assert status == 200 and [t["id"] for t in traces] == [0, 1, 9]

        # HTML: waterfall table with outcomes + phase durations, linked
        # from the job page
        status, _, body = get("/traces/app_traced", accept="text/html")
        assert status == 200
        assert "request timeline" in body and "length" in body
        assert "shed" in body and "host-monotonic" in body
        status, _, body = get("/jobs/app_traced", accept="text/html")
        assert "/traces/app_traced" in body

        # no trace file -> JSON 404, not a crash
        try:
            get("/traces/app_bare")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404

        # /metrics: the portal's own telemetry, Prometheus text format
        status, headers, body = get("/metrics")
        assert status == 200
        assert headers["Content-Type"].startswith("text/plain")
        line_re = re.compile(
            r"^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+|"
            r"[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [^\s]+)$")
        for line in body.strip().splitlines():
            assert line_re.match(line), f"unparseable line: {line!r}"
        assert 'portal_http_requests_total{route="traces"} 3' in body
        assert "portal_request_seconds_bucket" in body
        assert "portal_jobs_indexed 2" in body
    finally:
        server.shutdown()
        server.server_close()


def test_portal_serves_history(tmp_job_dirs, fixture_script):
    # run a real job to generate history
    from tony_tpu.client import TonyClient

    conf = TonyConf({
        "tony.staging.dir": tmp_job_dirs["staging"],
        "tony.history.intermediate": tmp_job_dirs["history"] + "/intermediate",
        "tony.history.finished": tmp_job_dirs["history"] + "/finished",
        "tony.worker.instances": 1,
        "tony.worker.command": f"{PY} {fixture_script('exit_0.py')}",
        "tony.am.monitor-interval-ms": 100,
    })
    client = TonyClient(conf, poll_interval_s=0.1)
    client.submit()
    assert client.monitor().value == "SUCCEEDED"
    app_id = client.app_id

    server = serve_portal(conf, port=0, block=False)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        def get(path, accept="application/json"):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", headers={"Accept": accept}
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.status, resp.read().decode()

        status, body = get("/")
        jobs = json.loads(body)
        assert status == 200
        assert any(j["app_id"] == app_id for j in jobs)
        assert jobs[0]["status"] in ("SUCCEEDED", "RUNNING")

        status, body = get(f"/jobs/{app_id}")
        events = json.loads(body)
        assert status == 200
        assert events[0]["type"] == "APPLICATION_INITED"
        assert events[-1]["type"] == "APPLICATION_FINISHED"

        status, body = get(f"/config/{app_id}")
        assert status == 200
        assert json.loads(body)["tony.worker.instances"] == 1

        status, body = get(f"/logs/{app_id}")
        assert status == 200

        # html index renders
        status, body = get("/", accept="text/html")
        assert status == 200 and app_id in body

        # html job-detail page renders the event timeline + nav links
        status, body = get(f"/jobs/{app_id}", accept="text/html")
        assert status == 200
        assert "APPLICATION_INITED" in body and "TASK_FINISHED" in body
        assert f"/config/{app_id}" in body and f"/logs/{app_id}" in body

        # unknown job id stays a JSON 404 either way
        status404 = urllib.request.Request(
            f"http://127.0.0.1:{port}/jobs/doesnotexist",
            headers={"Accept": "text/html"})
        try:
            urllib.request.urlopen(status404, timeout=10)
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        server.shutdown()
        server.server_close()
