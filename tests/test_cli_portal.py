"""CLI, proxy, and portal tests — reference tony-cli tests + portal
controller/BrowserTest round-trips."""

import json
import socket
import sys
import threading
import urllib.request

from tony_tpu.cli.main import main as cli_main
from tony_tpu.cli.proxy import ProxyServer
from tony_tpu.conf import TonyConf
from tony_tpu.portal.server import serve_portal

PY = sys.executable


def test_cli_local_submit(tmp_job_dirs, fixture_script, capsys):
    rc = cli_main([
        "local",
        "--command", f"{PY} {fixture_script('exit_0.py')}",
        "--instances", "2",
        "-D", f"tony.staging.dir={tmp_job_dirs['staging']}",
        "-D", f"tony.history.intermediate={tmp_job_dirs['history']}/intermediate",
        "-D", "tony.am.monitor-interval-ms=100",
    ])
    assert rc == 0


def test_cli_notebook_proxy_fetch(tmp_job_dirs, fixture_script):
    """Notebook submitter end-to-end: single-node app + local tunnel, HTTP
    round-trip through the proxy (reference NotebookSubmitter.java:71-133)."""
    import re
    import subprocess

    proc = subprocess.Popen(
        [PY, "-m", "tony_tpu.cli.main", "notebook",
         "--command", f"{PY} {fixture_script('mini_notebook.py')}",
         "--timeout-ms", "120000",
         "-D", f"tony.staging.dir={tmp_job_dirs['staging']}",
         "-D", f"tony.history.intermediate={tmp_job_dirs['history']}/intermediate",
         "-D", "tony.am.monitor-interval-ms=100"],
        stderr=subprocess.PIPE, text=True,
    )
    try:
        url = None
        for line in proc.stderr:
            m = re.search(r"notebook reachable at (http://\S+)", line)
            if m:
                url = m.group(1)
                break
        assert url, "notebook tunnel URL never printed"
        # the notebook server may take a beat to bind after RUNNING
        body = b""
        for _ in range(50):
            try:
                body = urllib.request.urlopen(url, timeout=2).read()
                break
            except OSError:
                import time

                time.sleep(0.2)
        assert body == b"mini-notebook-ok"
    finally:
        proc.terminate()  # CLI's SIGTERM hook kills the whole app tree
        proc.wait(timeout=10)


def test_cli_local_failure_exit_code(tmp_job_dirs, fixture_script):
    rc = cli_main([
        "local",
        "--command", f"{PY} {fixture_script('exit_1.py')}",
        "-D", f"tony.staging.dir={tmp_job_dirs['staging']}",
        "-D", f"tony.history.intermediate={tmp_job_dirs['history']}/intermediate",
        "-D", "tony.am.monitor-interval-ms=100",
    ])
    assert rc == 1


def test_proxy_tunnels_bytes():
    # echo server
    upstream = socket.socket()
    upstream.bind(("127.0.0.1", 0))
    upstream.listen(1)
    up_port = upstream.getsockname()[1]

    def echo():
        conn, _ = upstream.accept()
        while True:
            data = conn.recv(4096)
            if not data:
                return
            conn.sendall(data.upper())

    threading.Thread(target=echo, daemon=True).start()

    proxy = ProxyServer("127.0.0.1", up_port)
    proxy.start()
    try:
        client = socket.create_connection(("127.0.0.1", proxy.local_port), timeout=5)
        client.sendall(b"hello tunnel")
        assert client.recv(4096) == b"HELLO TUNNEL"
        client.close()
    finally:
        proxy.stop()
        upstream.close()


def test_portal_serves_history(tmp_job_dirs, fixture_script):
    # run a real job to generate history
    from tony_tpu.client import TonyClient

    conf = TonyConf({
        "tony.staging.dir": tmp_job_dirs["staging"],
        "tony.history.intermediate": tmp_job_dirs["history"] + "/intermediate",
        "tony.history.finished": tmp_job_dirs["history"] + "/finished",
        "tony.worker.instances": 1,
        "tony.worker.command": f"{PY} {fixture_script('exit_0.py')}",
        "tony.am.monitor-interval-ms": 100,
    })
    client = TonyClient(conf, poll_interval_s=0.1)
    client.submit()
    assert client.monitor().value == "SUCCEEDED"
    app_id = client.app_id

    server = serve_portal(conf, port=0, block=False)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        def get(path, accept="application/json"):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", headers={"Accept": accept}
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.status, resp.read().decode()

        status, body = get("/")
        jobs = json.loads(body)
        assert status == 200
        assert any(j["app_id"] == app_id for j in jobs)
        assert jobs[0]["status"] in ("SUCCEEDED", "RUNNING")

        status, body = get(f"/jobs/{app_id}")
        events = json.loads(body)
        assert status == 200
        assert events[0]["type"] == "APPLICATION_INITED"
        assert events[-1]["type"] == "APPLICATION_FINISHED"

        status, body = get(f"/config/{app_id}")
        assert status == 200
        assert json.loads(body)["tony.worker.instances"] == 1

        status, body = get(f"/logs/{app_id}")
        assert status == 200

        # html index renders
        status, body = get("/", accept="text/html")
        assert status == 200 and app_id in body

        # html job-detail page renders the event timeline + nav links
        status, body = get(f"/jobs/{app_id}", accept="text/html")
        assert status == 200
        assert "APPLICATION_INITED" in body and "TASK_FINISHED" in body
        assert f"/config/{app_id}" in body and f"/logs/{app_id}" in body

        # unknown job id stays a JSON 404 either way
        status404 = urllib.request.Request(
            f"http://127.0.0.1:{port}/jobs/doesnotexist",
            headers={"Accept": "text/html"})
        try:
            urllib.request.urlopen(status404, timeout=10)
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        server.shutdown()
        server.server_close()
