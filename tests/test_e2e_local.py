"""End-to-end mini-cluster tests: real client -> driver subprocess -> executor
subprocesses -> fixture python scripts.

This is the TPU-native analogue of the reference's centerpiece suite
TestTonyE2E.java (696 LoC, 28 scenarios against an in-process MiniCluster):
same shape — trivial python fixtures as "training scripts", env-var fault
injection, assertions on final job status and task states.
"""

import json
import os
import sys
import threading
import time
from pathlib import Path

import pytest

from tony_tpu.api import JobStatus, TaskStatus
from tony_tpu.client import TonyClient
from tony_tpu.conf import TonyConf

PY = sys.executable


def base_conf(dirs, **extra):
    conf = TonyConf({
        "tony.staging.dir": dirs["staging"],
        "tony.history.location": dirs["history"],
        "tony.history.intermediate": dirs["history"] + "/intermediate",
        "tony.history.finished": dirs["history"] + "/finished",
        "tony.am.monitor-interval-ms": 100,
        "tony.task.registration-poll-interval-ms": 100,
        **extra,
    })
    return conf


def run_job(dirs, **extra) -> tuple[JobStatus, TonyClient]:
    client = TonyClient(base_conf(dirs, **extra), poll_interval_s=0.1)
    client.submit()
    status = client.monitor()
    return status, client


def dump_logs(client):
    """Best-effort log dump on failure for debuggability."""
    out = []
    for p in sorted(Path(client.job_dir).rglob("*.log")) + sorted(
        Path(client.job_dir).rglob("*.std*")
    ):
        out.append(f"==== {p} ====\n{p.read_text()[-3000:]}")
    return "\n".join(out)


# --------------------------------------------------------------- happy paths

def test_single_worker_passes(tmp_job_dirs, fixture_script):
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.worker.instances": 1,
           "tony.worker.command": f"{PY} {fixture_script('exit_0.py')}"},
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)
    assert client.task_infos and client.task_infos[0].status == "SUCCEEDED"
    # per-task log URL is populated and points at the real stdout file
    # (reference prints container log URLs, util/Utils.java:220-235)
    url = client.task_infos[0].url
    assert url.endswith("worker_0.stdout"), url
    assert Path(url).exists(), url


def test_multi_worker_gang_passes(tmp_job_dirs, fixture_script):
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.worker.instances": 3,
           "tony.worker.command": f"{PY} {fixture_script('check_jax_env.py')}"},
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)


def test_jax_ranks_are_distinct(tmp_job_dirs, fixture_script, tmp_path):
    rank_dir = tmp_path / "ranks"
    rank_dir.mkdir()
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.worker.instances": 3,
           "tony.worker.command": f"{PY} {fixture_script('write_rank_file.py')}",
           "tony.execution.env": f"RANK_OUT_DIR={rank_dir}"},
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)
    ranks = sorted(p.name for p in rank_dir.iterdir())
    assert ranks == ["rank_0", "rank_1", "rank_2"]


@pytest.mark.slow
def test_large_gang_48_workers(tmp_job_dirs):
    """Moderate-scale gang: 48 executors allocate, pass the gang barrier,
    register, heartbeat, and complete — the task-table/scheduler/liveness
    machinery at the container counts the reference's YARN deployments run
    (each worker asserts it sees the full gang size). ~9s wall (observed
    up to ~34s on the loaded 2-core tier-1 host). Slow-marked with its
    192-executor sibling: the pair dominated tier-1 variance and flaked
    under load (ROADMAP), and the gate keeps the cheaper gang coverage
    (multi_worker_gang, straggler_skew, worker_failure)."""
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.worker.instances": 48,
           "tony.worker.command":
               PY + " -S -c \"import os; "
               "assert os.environ['TONY_NUM_TOTAL_TASKS']=='48'\""},
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)
    assert len(client.task_infos) == 48
    assert all(t.status == "SUCCEEDED" for t in client.task_infos)


@pytest.mark.slow
def test_gang_scale_192_stub_executors(tmp_job_dirs, tmp_path):
    """Driver scale one notch past the 48-proc test: 192 stub executors —
    threads speaking the REAL framed-JSON RPC protocol over real sockets,
    each holding a persistent connection like a live executor — against one
    in-process driver. Asserts the ThreadingTCPServer control plane keeps
    the gang barrier and heartbeat processing bounded at the container
    counts the reference's YARN deployments run (hundreds per AM): barrier
    release (first registration -> last cluster-spec handout) under 30s,
    worst single heartbeat RTT under 2s while all 192 connections live.
    ~10s wall; prints the measured barrier-release time."""
    import tony_tpu.constants as c
    from tony_tpu.cluster.provisioner import ContainerHandle, Provisioner
    from tony_tpu.driver import Driver
    from tony_tpu.rpc import RpcClient

    N = 192
    t_register: list[float] = []
    t_spec: list[float] = []
    hb_rtts: list[float] = []
    errors: list[str] = []
    lock = threading.Lock()

    class StubExecutorProvisioner(Provisioner):
        """launch() = start a thread that behaves like an executor agent:
        register, poll the gang barrier, heartbeat, report success."""

        def __init__(self):
            super().__init__()
            self.threads: list[threading.Thread] = []

        def launch(self, spec, index, env, log_dir):
            handle = ContainerHandle(
                container_id=f"stub_{spec.name}_{index}",
                host="127.0.0.1", role=spec.name, index=index,
            )
            t = threading.Thread(
                target=self._run, args=(spec, index, env, handle),
                daemon=True,
            )
            self.threads.append(t)
            t.start()
            return handle

        def _run(self, spec, index, env, handle):
            task_id = f"{spec.name}:{index}"
            try:
                rpc = RpcClient(
                    env[c.ENV_DRIVER_HOST], int(env[c.ENV_DRIVER_PORT]),
                    token=env.get(c.ENV_TOKEN, ""), role="executor",
                )
                with lock:
                    t_register.append(time.time())
                payload = rpc.call("register_worker", task_id=task_id,
                                   host="127.0.0.1", port=20000 + index)
                while payload is None:
                    # real executors heartbeat THROUGH the barrier wait
                    # (Heartbeater starts before the gang barrier) — with
                    # 192 sequential launches the barrier takes seconds,
                    # longer than heartbeat expiry
                    rpc.call("heartbeat", task_id=task_id)
                    time.sleep(0.05)
                    payload = rpc.call("get_cluster_spec", task_id=task_id)
                with lock:
                    t_spec.append(time.time())
                assert payload["num_processes"] == N
                for _ in range(3):
                    t0 = time.time()
                    rpc.call("heartbeat", task_id=task_id)
                    with lock:
                        hb_rtts.append(time.time() - t0)
                    time.sleep(0.05)
                rpc.call("register_execution_result", task_id=task_id,
                         exit_code=0)
                rpc.close()
            except Exception as e:  # surfaced via the errors list
                with lock:
                    errors.append(f"{task_id}: {type(e).__name__}: {e}")
                cb = self.on_completion
                if cb:
                    cb(handle, 1)
                return
            cb = self.on_completion
            if cb:
                cb(handle, 0)

        def stop_container(self, handle):
            pass

        def stop_all(self):
            pass

    conf = base_conf(
        tmp_job_dirs,
        **{"tony.worker.instances": N, "tony.worker.command": "stub"},
    )
    job_dir = tmp_path / "job"
    job_dir.mkdir()
    conf.write_final(job_dir)
    driver = Driver(conf, app_id="scale_test", job_dir=str(job_dir),
                    token="scale-secret",
                    provisioner=StubExecutorProvisioner())
    driver.client_signal.set()  # no client: don't wait for the ack
    status = driver.run()
    assert not errors, errors[:5]
    assert status == JobStatus.SUCCEEDED, driver.session.failure_message
    assert len(t_spec) == N
    barrier_release = max(t_spec) - min(t_register)
    print(f"\n192-executor gang: barrier release {barrier_release:.2f}s, "
          f"max heartbeat RTT {max(hb_rtts)*1e3:.0f}ms "
          f"over {len(hb_rtts)} heartbeats")
    assert barrier_release < 30, f"barrier took {barrier_release:.1f}s"
    assert max(hb_rtts) < 2.0, f"heartbeat RTT {max(hb_rtts):.2f}s"


def test_worker_failure_fails_job(tmp_job_dirs, fixture_script):
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.worker.instances": 1,
           "tony.worker.command": f"{PY} {fixture_script('exit_1.py')}"},
    )
    assert status == JobStatus.FAILED


def test_non_chief_failure_tolerated(tmp_job_dirs, fixture_script):
    """worker:0 (chief) passes, worker:1 fails -> job still succeeds
    (reference testAMNotStopJobAfterNonChiefWorkerFailed, TestTonyE2E.java:323)."""
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.chief.instances": 1,
           "tony.chief.command": f"{PY} {fixture_script('exit_0.py')}",
           "tony.worker.instances": 2,
           "tony.worker.command": (
               f"bash -c 'if [ \"$TONY_TASK_INDEX\" = 1 ]; then exit 1; else exit 0; fi'"
           )},
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)
    by_id = {t.task_id: t for t in client.task_infos}
    assert by_id["worker:1"].status == "FAILED"


def test_chief_failure_fails_job(tmp_job_dirs, fixture_script):
    """Reference testAMStopsJobAfterWorker0Killed (TestTonyE2E.java:298)."""
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.worker.instances": 2,
           "tony.worker.command": (
               f"bash -c 'if [ \"$TONY_TASK_INDEX\" = 0 ]; then exit 1; else sleep 60; fi'"
           )},
    )
    assert status == JobStatus.FAILED
    assert "chief" in client.final_state.get("message", "")


# ----------------------------------------------------------- runtime adapters

def test_tensorflow_ps_worker_env(tmp_job_dirs, fixture_script):
    """The BASELINE.md PS-strategy topology: 2 ps + 4 workers + chief +
    evaluator, with the evaluator excluded from the cluster dict the way the
    reference's constructTFConfig filters it (util/Utils.java:503-520)."""
    cmd = f"{PY} {fixture_script('check_tf_env.py')}"
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.application.framework": "tensorflow",
           "tony.ps.instances": 2, "tony.ps.command": cmd,
           "tony.worker.instances": 4, "tony.worker.command": cmd,
           "tony.chief.instances": 1, "tony.chief.command": cmd,
           "tony.evaluator.instances": 1, "tony.evaluator.command": cmd},
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)


def test_pytorch_env(tmp_job_dirs, fixture_script):
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.application.framework": "pytorch",
           "tony.worker.instances": 2,
           "tony.worker.command": f"{PY} {fixture_script('check_pytorch_env.py')}"},
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)


def test_mxnet_env(tmp_job_dirs, fixture_script):
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.application.framework": "mxnet",
           "tony.scheduler.instances": 1,
           "tony.scheduler.command": f"{PY} {fixture_script('check_mxnet_env.py')}",
           "tony.server.instances": 1,
           "tony.server.command": f"{PY} {fixture_script('check_mxnet_env.py')}",
           "tony.worker.instances": 2,
           "tony.worker.command": f"{PY} {fixture_script('check_mxnet_env.py')}"},
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)


def test_horovod_two_phase_rendezvous(tmp_job_dirs, fixture_script):
    """Driver role injected + slot table distributed (reference
    testHorovodModeShouldPass, TestTonyE2E.java:531)."""
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.application.framework": "horovod",
           "tony.horovod.mode.test": True,
           "tony.worker.instances": 2,
           "tony.worker.command": f"{PY} {fixture_script('check_horovod_env.py')}"},
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)
    roles = {t.name for t in client.task_infos}
    assert roles == {"worker", "driver"}, "driver role must be injected"


@pytest.mark.slow
def test_real_torch_distributed_allreduce(tmp_job_dirs, fixture_script):
    """4 workers (the BASELINE.md DDP topology) join a real c10d gloo group
    from the emitted INIT_METHOD contract and allreduce — the pytorch
    analogue of the jax.distributed collective e2e (reference mnist-pytorch
    example contract). Slow-marked (~26s: torch import + gloo rendezvous
    x4 procs) to keep tier-1 under its 870s cap; the jax-collective e2e
    keeps real-distributed coverage in the gate."""
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.application.framework": "pytorch",
           "tony.worker.instances": 4,
           "tony.worker.command": f"{PY} {fixture_script('torch_allreduce.py')}"},
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)


def test_horovod_eight_worker_slot_table(tmp_job_dirs, fixture_script, tmp_path):
    """The BASELINE.md ring-allreduce topology: 8 workers, every one handed a
    distinct rank from the driver's slot table."""
    rank_dir = tmp_path / "hvd_ranks"
    rank_dir.mkdir()
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.application.framework": "horovod",
           "tony.horovod.mode.test": True,
           "tony.worker.instances": 8,
           "tony.worker.command": f"{PY} {fixture_script('check_horovod_env.py')}",
           "tony.execution.env": f"RANK_OUT_DIR={rank_dir}"},
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)
    ranks = sorted(p.name for p in rank_dir.iterdir())
    assert ranks == [f"hvd_rank_{i}" for i in range(8)], ranks


def test_horovod_driver_fast_fail(tmp_job_dirs, fixture_script):
    """Rendezvous driver crash fails the whole job fast via untracked-task
    fast-fail (reference testHorovodDriverCrash / horovod_driver.py -f)."""
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.application.framework": "horovod",
           "tony.horovod.driver.fast-fail": True,
           "tony.worker.instances": 2,
           "tony.worker.command": f"{PY} {fixture_script('sleep_long.py')}"},
    )
    assert status == JobStatus.FAILED, dump_logs(client)
    assert "driver" in client.final_state.get("message", "")


def test_horovod_debug_driver(tmp_job_dirs, fixture_script):
    """User-supplied rendezvous driver published via the marker file
    (reference testHorovodDebugModeShouldPass, TestTonyE2E.java:531-589)."""
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.application.framework": "horovod",
           "tony.horovod.driver.debug-command":
               f"{PY} {fixture_script('horovod_debug_driver.py')}",
           "tony.worker.instances": 2,
           "tony.worker.command": f"{PY} {fixture_script('check_horovod_env.py')}"},
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)


def test_standalone_mode(tmp_job_dirs, fixture_script):
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.application.framework": "standalone",
           "tony.worker.instances": 1,
           "tony.worker.command": f"{PY} {fixture_script('exit_0.py')}"},
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)


def test_standalone_rejects_multiple_instances(tmp_job_dirs, fixture_script):
    """Reference StandaloneRuntime.java:69-75."""
    client = TonyClient(
        base_conf(
            tmp_job_dirs,
            **{"tony.application.framework": "standalone",
               "tony.worker.instances": 2,
               "tony.worker.command": f"{PY} {fixture_script('exit_0.py')}"},
        ),
        poll_interval_s=0.1,
    )
    client.submit()
    with pytest.raises((RuntimeError, TimeoutError)):
        client.monitor()


# -------------------------------------------------------------- dag + events

def test_dag_scheduling_end_to_end(tmp_job_dirs, fixture_script, tmp_path):
    """prep runs before worker (reference testTonyAMSchedulerShouldPass:271)."""
    marker = tmp_path / "order.txt"
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.prep.instances": 1,
           "tony.prep.command": f"bash -c 'echo prep >> {marker}'",
           "tony.worker.instances": 1,
           "tony.worker.command": f"bash -c 'echo worker >> {marker}'",
           "tony.worker.depends-on": "prep",
           # staged start means the gang barrier must not wait for worker
           "tony.application.distributed-mode": "FCFS"},
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)
    assert marker.read_text().splitlines() == ["prep", "worker"]


def test_history_events_written(tmp_job_dirs, fixture_script):
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.worker.instances": 1,
           "tony.worker.command": f"{PY} {fixture_script('exit_0.py')}"},
    )
    assert status == JobStatus.SUCCEEDED
    inter = Path(tmp_job_dirs["history"]) / "intermediate" / client.app_id
    jhists = list(inter.glob("*.jhist"))
    assert len(jhists) == 1 and "SUCCEEDED" in jhists[0].name
    lines = [json.loads(l) for l in jhists[0].read_text().splitlines()]
    types = [l["type"] for l in lines]
    assert types[0] == "APPLICATION_INITED"
    assert "TASK_STARTED" in types and "TASK_FINISHED" in types
    assert types[-1] == "APPLICATION_FINISHED"


def test_tpu_metrics_flow_into_task_finished(tmp_job_dirs, fixture_script,
                                             tmp_path, monkeypatch):
    """Full observability chain for accelerator metrics: the executor's
    TaskMonitor samples the TPU channel (a fake libtpu.sdk injected via
    PYTHONPATH — the same import surface the real chip serves), pushes over
    the metrics RPC, and the driver stamps them into the TASK_FINISHED
    history event (reference: GPU metrics via GpuDiscoverer ->
    TaskMonitor -> jhist, TaskMonitor.java:101-170)."""
    pkg = tmp_path / "fakelibs" / "libtpu"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "sdk.py").write_text(
        "class _Metric:\n"
        "    def __init__(self, data): self._d = data\n"
        "    def data(self): return self._d\n"
        "class tpumonitoring:\n"
        "    _DATA = {'duty_cycle_pct': ['62.5'],\n"
        "             'hbm_capacity_usage': ['3000000']}\n"
        "    @staticmethod\n"
        "    def get_metric(name):\n"
        "        return _Metric(tpumonitoring._DATA[name])\n"
    )
    existing = os.environ.get("PYTHONPATH", "")
    monkeypatch.setenv(
        "PYTHONPATH",
        str(tmp_path / "fakelibs") + (os.pathsep + existing if existing else ""),
    )
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.worker.instances": 1,
           "tony.worker.command": f"{PY} {fixture_script('exit_0.py')}",
           "tony.task.metrics-interval-ms": 200},
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)
    inter = Path(tmp_job_dirs["history"]) / "intermediate" / client.app_id
    lines = [json.loads(l) for l in
             next(iter(inter.glob("*.jhist"))).read_text().splitlines()]
    finished = [l for l in lines if l["type"] == "TASK_FINISHED"]
    assert len(finished) == 1
    metrics = {m["name"]: m["value"]
               for m in finished[0]["payload"]["metrics"]}
    assert metrics["max_tpu_duty_cycle_pct"] == 62.5
    assert metrics["max_tpu_hbm_used_mb"] == 3.0
    assert "max_memory_rss_mb" in metrics and metrics["max_memory_rss_mb"] > 0


def test_task_traces_and_driver_metrics_e2e(tmp_job_dirs):
    """Acceptance chain for cluster-side telemetry: a real 2-worker job
    produces tasks.trace.jsonl with all-terminal lifecycle traces
    (executor spans merged in), the driver's /metrics endpoint serves
    the gang-launch + heartbeat histograms and the straggler gauges in
    Prometheus text WHILE the job runs, the jhist stream embeds the
    TASK_TRACE events, and the portal renders the /tasks waterfall."""
    import urllib.request

    from tony_tpu.events.trace import TASK_TRACE_FILE, read_traces

    client = TonyClient(base_conf(
        tmp_job_dirs,
        **{"tony.worker.instances": 2,
           "tony.worker.command": "bash -c 'sleep 1.5'",
           "tony.task.heartbeat-interval-ms": 100,
           "tony.task.metrics-interval-ms": 100},
    ), poll_interval_s=0.1)
    client.submit()
    # driver.json appears once prepare() ran; it advertises metrics_port
    info_path = Path(client.job_dir) / "driver.json"
    deadline = time.time() + 60
    port = None
    while time.time() < deadline and port is None:
        if info_path.exists():
            try:
                port = json.loads(info_path.read_text()).get("metrics_port")
            except ValueError:      # mid-rename torn read
                port = None
        time.sleep(0.05)
    assert port, "driver never advertised its metrics port"
    text = ""
    want = 'driver_gang_launch_seconds_count{role="worker"} 2'
    while time.time() < deadline and want not in text:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
                assert r.headers["Content-Type"].startswith("text/plain")
                text = r.read().decode()
        except OSError:
            pass
        time.sleep(0.1)
    assert want in text, f"live /metrics never saw both registrations:\n{text[:2000]}"
    assert "driver_heartbeat_interval_seconds_bucket" in text
    assert 'driver_straggler_registration_s{role="worker",stat="max"}' in text
    assert 'driver_straggler_heartbeat_s{role="worker",stat="median"}' in text

    status = client.monitor()
    assert status == JobStatus.SUCCEEDED, dump_logs(client)
    inter = Path(tmp_job_dirs["history"]) / "intermediate" / client.app_id
    recs = read_traces(inter / TASK_TRACE_FILE)
    assert {r["id"] for r in recs} == {"worker:0", "worker:1"}
    for rec in recs:
        names = [n for n, _ in rec["spans"]]
        assert names[-1] == "finished", names
        for span in ("requested", "allocated", "launched", "registered",
                     "first_heartbeat", "running", "work_dir_ready",
                     "child_spawned"):
            assert span in names, f"{span} missing from {names}"
    jhist = next(iter(inter.glob("*.jhist")))
    lines = [json.loads(l) for l in jhist.read_text().splitlines()]
    embedded = [l for l in lines if l["type"] == "TASK_TRACE"]
    assert {e["payload"]["trace"]["id"] for e in embedded} == {
        "worker:0", "worker:1"}

    # portal waterfall over the same history dir
    from tony_tpu.portal.server import serve_portal

    server = serve_portal(base_conf(tmp_job_dirs), port=0, block=False)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        url = (f"http://127.0.0.1:{server.server_address[1]}"
               f"/tasks/{client.app_id}")
        req = urllib.request.Request(url, headers={"Accept": "text/html"})
        with urllib.request.urlopen(req, timeout=10) as r:
            body = r.read().decode()
        assert "gang-launch waterfall" in body and "worker:1" in body
    finally:
        server.shutdown()
        server.server_close()


# ------------------------------------------------------------ fault injection

def test_executor_crash_before_register_fails_job(tmp_job_dirs, fixture_script):
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.worker.instances": 1,
           "tony.worker.command": f"{PY} {fixture_script('exit_0.py')}",
           "tony.worker.env": "TONY_TEST_TASK_EXECUTOR_CRASH=1"},
    )
    assert status == JobStatus.FAILED


def test_missed_heartbeats_fail_job(tmp_job_dirs, fixture_script):
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.worker.instances": 1,
           "tony.worker.command": f"{PY} {fixture_script('sleep_long.py')}",
           "tony.task.heartbeat-interval-ms": 100,
           "tony.task.max-missed-heartbeats": 3,
           # executor skips enough heartbeats to be deemed dead
           "tony.worker.env": "TONY_TEST_EXECUTOR_NUM_HB_MISS=1000"},
    )
    assert status == JobStatus.FAILED
    assert "heartbeat" in client.final_state.get("message", "")


def test_delayed_completion_does_not_fail_finished_task(tmp_job_dirs, fixture_script):
    """The container-completion callback is delayed far beyond heartbeat
    expiry; a task that already reported success must NOT be deemed dead
    (the HB-unregister race, reference
    TEST_TASK_COMPLETION_NOTIFICATION_DELAYED, ApplicationMaster.java:1075-1087)."""
    os.environ["TONY_TEST_COMPLETION_NOTIFICATION_DELAY_MS"] = "3000"
    try:
        status, client = run_job(
            tmp_job_dirs,
            **{"tony.worker.instances": 1,
               "tony.worker.command": f"{PY} {fixture_script('exit_0.py')}",
               "tony.task.heartbeat-interval-ms": 100,
               "tony.task.max-missed-heartbeats": 3},
        )
    finally:
        del os.environ["TONY_TEST_COMPLETION_NOTIFICATION_DELAY_MS"]
    assert status == JobStatus.SUCCEEDED, dump_logs(client)


def test_worker_termination_on_chief_registration(tmp_job_dirs, fixture_script):
    """The driver kills a listed worker once the chief registers (reference
    TEST_WORKER_TERMINATION, ApplicationMaster.java:1338-1349 +
    testAMStopsJobAfterWorker0Killed)."""
    os.environ["TONY_TEST_WORKER_TERMINATION"] = "worker:1"
    try:
        status, client = run_job(
            tmp_job_dirs,
            **{"tony.worker.instances": 2,
               "tony.worker.command": f"{PY} {fixture_script('sleep_long.py')}",
               "tony.application.fail-on-worker-failure-enabled": True},
        )
    finally:
        del os.environ["TONY_TEST_WORKER_TERMINATION"]
    assert status == JobStatus.FAILED, dump_logs(client)
    assert "worker:1 failed" in client.final_state.get("message", "")


def test_straggler_skew_still_passes(tmp_job_dirs, fixture_script):
    """Gang barrier holds through a 2s straggler (reference
    TEST_TASK_EXECUTOR_SKEW, TaskExecutor.java:366-386)."""
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.worker.instances": 2,
           "tony.worker.command": f"{PY} {fixture_script('check_jax_env.py')}",
           "tony.worker.env": "TONY_TEST_EXECUTOR_SKEW=worker#1#2000"},
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)


def test_execution_timeout_kills_user_process(tmp_job_dirs, fixture_script):
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.worker.instances": 1,
           "tony.worker.command": f"{PY} {fixture_script('sleep_long.py')}",
           "tony.task.executor.execution-timeout-ms": 1500},
    )
    assert status == JobStatus.FAILED


def test_driver_retry_after_failure(tmp_job_dirs, fixture_script, tmp_path):
    """First session fails (worker exits 1 on attempt 0), retry succeeds —
    reference AM-retry semantics (ApplicationMaster.reset:611-627): the
    command succeeds only once a marker file exists, which attempt 0 creates."""
    marker = tmp_path / "attempted"
    cmd = (
        f"bash -c 'if [ -f {marker} ]; then exit 0; else touch {marker}; exit 1; fi'"
    )
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.worker.instances": 1,
           "tony.worker.command": cmd,
           "tony.am.retry-count": 1},
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)


def test_e2e_slice_lifecycle_create_preempt_recreate_delete(
    tmp_job_dirs, fixture_script, tmp_path
):
    """The full RM-capacity lifecycle through a real job: no slice exists at
    submit, so the driver CREATES one (awaiting READY through the stub's
    CREATING phase), the first attempt is 'preempted' (the task destroys the
    slice state and dies), the retry RE-CREATES the slice with new host
    addresses and succeeds, and teardown DELETES the driver-created slice —
    reference TonyClient.submitApplication:317-353 +
    ApplicationMaster.java:1100-1119, driven by a stub gcloud."""
    stub = fixture_script("stub_slice.py")
    d = tmp_path / "slice"
    status, client = run_job(
        tmp_job_dirs,
        **{
            "tony.worker.instances": 1,
            "tony.worker.command": f"{PY} {fixture_script('preempt_once.py')}",
            "tony.am.retry-count": 1,
            "tony.cluster.provisioner": "tpu-pod",
            # stand-in for ssh: run the executor locally with the task env
            "tony.cluster.launch-template":
                "env {env} " + PY + " -S -m tony_tpu.executor",
            "tony.tpu.discover-command": f"{PY} -S {stub} describe {d}",
            "tony.tpu.create-command": f"{PY} -S {stub} create {d} 1 2",
            "tony.tpu.delete-command": f"{PY} -S {stub} delete {d}",
            "tony.tpu.accelerator-type": "v5litepod-8",  # 1-host slice
            "tony.tpu.create-timeout-s": 15,
            "tony.tpu.create-poll-interval-s": 0.02,
            "tony.tpu.discover-retries": 1,
            "tony.execution.env": f"STUB_SLICE_DIR={d}",
        },
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)
    # created twice (initial + post-preemption), final teardown deleted it
    creates = (d / "create.log").read_text().splitlines()
    assert creates == ["create gen=1", "create gen=2"], creates
    assert (d / "delete.log").exists()
    assert not (d / "slice.json").exists(), "teardown must delete the slice"
    out = (Path(client.job_dir) / "logs" / "worker_0.stdout").read_text()
    assert "attempt 1 ran on recreated slice" in out, dump_logs(client)


def test_e2e_multislice_create_preempt_recreate_delete(
    tmp_job_dirs, fixture_script, tmp_path
):
    """Two-slice job end to end: neither slice exists at submit, so the
    driver creates BOTH ({slice}-templated lifecycle commands, one cloud
    resource per slice); the gang spans both slices and every worker sees
    the multislice env contract (TONY_SLICE_* + MEGASCALE_* mapping); the
    first attempt 'preempts' slice 1 (its worker destroys the slice state
    and dies), the retry re-creates ONLY slice 1; teardown deletes both
    driver-created slices. Reference analogue: the RM granting containers
    across racks, ApplicationMaster.java:1100-1119."""
    stub = fixture_script("stub_slice.py")
    base = tmp_path / "slices"
    status, client = run_job(
        tmp_job_dirs,
        **{
            "tony.worker.instances": 2,
            "tony.worker.command":
                f"{PY} {fixture_script('multislice_task.py')}",
            "tony.am.retry-count": 1,
            "tony.cluster.provisioner": "tpu-pod",
            "tony.cluster.launch-template":
                "env {env} " + PY + " -S -m tony_tpu.executor",
            "tony.tpu.num-slices": 2,
            "tony.tpu.discover-command":
                f"{PY} -S {stub} describe {base}/s{{slice}}",
            "tony.tpu.create-command":
                f"{PY} -S {stub} create {base}/s{{slice}} 1 0",
            "tony.tpu.delete-command":
                f"{PY} -S {stub} delete {base}/s{{slice}}",
            "tony.tpu.accelerator-type": "v5litepod-8",  # 1 host per slice
            "tony.tpu.create-timeout-s": 15,
            "tony.tpu.create-poll-interval-s": 0.02,
            "tony.tpu.discover-retries": 1,
            "tony.execution.env": f"STUB_PREEMPT_DIR={base}/s1",
        },
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)
    # slice 0 created once and never again; slice 1 created twice
    assert (base / "s0" / "create.log").read_text().splitlines() == \
        ["create gen=1"]
    assert (base / "s1" / "create.log").read_text().splitlines() == \
        ["create gen=1", "create gen=2"], \
        (base / "s1" / "create.log").read_text()
    # teardown deleted both driver-created slices
    for s in ("s0", "s1"):
        assert not (base / s / "slice.json").exists(), f"{s} leaked"
        assert (base / s / "delete.log").exists()
    logs = Path(client.job_dir) / "logs"
    assert "attempt 1 slice 0 ok" in (logs / "worker_0.stdout").read_text()
    assert "attempt 1 slice 1 ok" in (logs / "worker_1.stdout").read_text()


def test_e2e_killed_job_releases_created_slice(
    tmp_job_dirs, fixture_script, tmp_path
):
    """SIGTERM to the driver (a client kill) must delete a slice the driver
    created — otherwise a killed job leaks billable capacity that nothing
    tracks afterwards."""
    import signal
    import subprocess

    stub = fixture_script("stub_slice.py")
    d = tmp_path / "slice"
    conf = base_conf(
        tmp_job_dirs,
        **{
            "tony.worker.instances": 1,
            "tony.worker.command": f"{PY} {fixture_script('sleep_long.py')}",
            "tony.cluster.provisioner": "tpu-pod",
            "tony.cluster.launch-template":
                "env {env} " + PY + " -S -m tony_tpu.executor",
            "tony.tpu.discover-command": f"{PY} -S {stub} describe {d}",
            "tony.tpu.create-command": f"{PY} -S {stub} create {d} 1 0",
            "tony.tpu.delete-command": f"{PY} -S {stub} delete {d}",
            "tony.tpu.accelerator-type": "v5litepod-8",
            "tony.tpu.create-poll-interval-s": 0.02,
            "tony.tpu.discover-retries": 1,
        },
    )
    client = TonyClient(conf, poll_interval_s=0.1)
    client.submit()
    # wait past startup: the executor's stdout file existing means the
    # driver created the slice, installed its signal handlers, and launched
    log_f = Path(client.job_dir) / "logs" / "worker_0.stdout"
    deadline = time.time() + 30
    while time.time() < deadline and not log_f.exists():
        time.sleep(0.1)
    assert log_f.exists(), "driver never launched the worker"
    assert (d / "slice.json").exists(), "driver never created the slice"
    client._driver_proc.send_signal(signal.SIGTERM)
    try:
        client._driver_proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        client._driver_proc.kill()
        raise AssertionError("driver did not exit on SIGTERM")
    deadline = time.time() + 10
    while time.time() < deadline and (d / "slice.json").exists():
        time.sleep(0.1)
    assert not (d / "slice.json").exists(), \
        "killed driver leaked its created slice"


def test_e2e_kill_during_await_ready_releases_slice(
    tmp_job_dirs, fixture_script, tmp_path
):
    """The likeliest kill window: SIGTERM while the driver is still inside
    the (possibly minutes-long) await-READY poll. The provisioner registers
    itself with the signal path BEFORE acquisition, so the slice it just
    created is deleted even though Driver construction never finished."""
    import signal
    import subprocess

    stub = fixture_script("stub_slice.py")
    d = tmp_path / "slice"
    conf = base_conf(
        tmp_job_dirs,
        **{
            "tony.worker.instances": 1,
            "tony.worker.command": "true",
            "tony.cluster.provisioner": "tpu-pod",
            "tony.tpu.discover-command": f"{PY} -S {stub} describe {d}",
            # never reaches READY within this test
            "tony.tpu.create-command": f"{PY} -S {stub} create {d} 1 100000",
            "tony.tpu.delete-command": f"{PY} -S {stub} delete {d}",
            "tony.tpu.accelerator-type": "v5litepod-8",
            "tony.tpu.create-timeout-s": 120,
            "tony.tpu.create-poll-interval-s": 0.1,
            "tony.tpu.discover-retries": 1,
        },
    )
    client = TonyClient(conf, poll_interval_s=0.1)
    client.submit()
    deadline = time.time() + 30
    while time.time() < deadline and not (d / "slice.json").exists():
        time.sleep(0.05)
    assert (d / "slice.json").exists(), "driver never created the slice"
    client._driver_proc.send_signal(signal.SIGTERM)
    try:
        client._driver_proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        client._driver_proc.kill()
        raise AssertionError("driver did not exit on SIGTERM mid-await")
    deadline = time.time() + 10
    while time.time() < deadline and (d / "slice.json").exists():
        time.sleep(0.1)
    assert not (d / "slice.json").exists(), \
        "kill during await-READY leaked the created slice"


@pytest.mark.env_flaky
def test_real_jax_distributed_collective(tmp_job_dirs, fixture_script):
    """2-worker job where the user processes actually join jax.distributed
    via the coordinator address the runtime emitted, and run a psum. This is
    the end-to-end proof the bootstrap contract works (SURVEY.md §7 step 6).

    env_flaky: the container's jax CPU (gloo) collective availability
    comes and goes across the day — identically on an unmodified
    checkout (ROADMAP "known flakes") — so the harness reruns a failure
    once before reporting it."""
    import tony_tpu

    repo_root = str(Path(tony_tpu.__file__).resolve().parent.parent)
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.worker.instances": 2,
           "tony.worker.command": f"{PY} {fixture_script('distributed_psum.py')}",
           "tony.execution.env": f"TONY_REPO_ROOT={repo_root}",
           # jax.distributed gloo bootstrap can take a few seconds
           "tony.task.heartbeat-interval-ms": 1000},
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)


def test_e2e_preemption_mid_training_resumes_exact_stream(
    tmp_job_dirs, fixture_script, tmp_path
):
    """The composed recovery story, end to end: a CHECKPOINTED training job
    on a driver-created stub slice is spot-preempted mid-run (the task
    destroys the slice state and dies at step 7), the driver retry
    re-acquires capacity (slice re-created, new host generation) and the
    job RESUMES from the last checkpoint (step 6) — and the resumed stream
    is EXACT: every post-resume step consumes the deterministic loader's
    batch_at(step) and reproduces the loss an unpreempted golden run
    produces, no step repeated, none skipped. This is the composition the
    pieces (slice recreate e2e, driver retry e2e, orbax latest_step
    resume, (seed, step)-pure loader) individually promise — reference
    recovery contract: AM retry restarts user code which resumes from its
    own checkpoints (ApplicationMaster.java:611-627,
    mnist_distributed.py:237-241)."""
    import numpy as np

    import tony_tpu

    repo_root = str(Path(tony_tpu.__file__).resolve().parent.parent)
    stub = fixture_script("stub_slice.py")
    d = tmp_path / "slice"
    out_dir = tmp_path / "train"
    out_dir.mkdir()
    data_bin = tmp_path / "tokens.bin"
    rng = np.random.default_rng(7)
    rng.integers(0, 256, size=4096, dtype=np.uint16).tofile(data_bin)

    status, client = run_job(
        tmp_job_dirs,
        **{
            "tony.worker.instances": 1,
            "tony.worker.command":
                f"{PY} {fixture_script('train_preempt_resume.py')}",
            "tony.am.retry-count": 1,
            "tony.cluster.provisioner": "tpu-pod",
            "tony.cluster.launch-template":
                "env {env} " + PY + " -S -m tony_tpu.executor",
            "tony.tpu.discover-command": f"{PY} -S {stub} describe {d}",
            "tony.tpu.create-command": f"{PY} -S {stub} create {d} 1 2",
            "tony.tpu.delete-command": f"{PY} -S {stub} delete {d}",
            "tony.tpu.accelerator-type": "v5litepod-8",
            "tony.tpu.create-timeout-s": 15,
            "tony.tpu.create-poll-interval-s": 0.02,
            "tony.tpu.discover-retries": 1,
            "tony.execution.env": (
                f"TONY_REPO_ROOT={repo_root} STUB_SLICE_DIR={d} "
                f"TRAIN_OUT_DIR={out_dir} DATA_BIN={data_bin}"),
            # checkpoint restore + train on CPU takes a few seconds
            "tony.task.heartbeat-interval-ms": 1000,
        },
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)
    # capacity was re-acquired: the slice was created twice
    creates = (d / "slice.log" if False else d / "create.log").read_text()
    assert creates.splitlines() == ["create gen=1", "create gen=2"], creates

    stream = [json.loads(l)
              for l in (out_dir / "stream.jsonl").read_text().splitlines()]
    s0 = [e for e in stream if e["session"] == 0]
    s1 = [e for e in stream if e["session"] == 1]
    # session 0 ran steps 0..6 then died; session 1 resumed at EXACTLY 7
    # (checkpoint step 6 + 1) and finished 7..11 — no repeat, no skip
    assert [e["step"] for e in s0] == list(range(0, 7)), s0
    assert [e["step"] for e in s1] == list(range(7, 12)), s1

    # golden: the same 12 steps unpreempted, in-process — identical seeds,
    # identical CPU math. The combined preempted stream must match it
    # exactly: batches by content hash, losses to the float.
    import hashlib

    import jax

    from tony_tpu import train as trainlib
    from tony_tpu.data import (
        ShardedBatchLoader, TokenDataset, device_put_sharded_batch,
    )
    from tony_tpu.models import transformer as tfm
    from tony_tpu.parallel import mesh_from_string

    mesh = mesh_from_string("fsdp=-1")
    cfg = tfm.TransformerConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=128, max_seq_len=32, dtype=jax.numpy.float32,
    )
    bundle = trainlib.create_train_step(cfg, mesh)
    params, opt_state = bundle.params, bundle.opt_state
    loader = ShardedBatchLoader(
        TokenDataset.from_raw(data_bin, np.uint16), 8, 32, seed=0,
        process_index=0, process_count=1,
    )
    combined = s0 + s1
    for step_i in range(12):
        tokens, targets = loader.batch_at(step_i)
        sha = hashlib.sha256(tokens.tobytes()).hexdigest()[:16]
        dev = device_put_sharded_batch(
            (tokens, targets), mesh, sharding=bundle.tok_sharding,
            global_batch=8, global_seq=32)
        params, opt_state, metrics = bundle.step_fn(
            params, opt_state, dev[0], dev[1])
        entry = combined[step_i]
        assert entry["step"] == step_i
        assert entry["batch_sha"] == sha, (
            f"step {step_i}: resumed job consumed a different batch")
        assert abs(entry["loss"] - float(metrics["loss"])) < 1e-5, (
            f"step {step_i}: loss diverged from the unpreempted golden "
            f"({entry['loss']} vs {float(metrics['loss'])})")


def test_per_task_restart_within_session(tmp_job_dirs, fixture_script, tmp_path):
    """A non-chief task with a restart budget recovers in-place without a
    whole-job retry — capability beyond the reference (SURVEY.md §5: no
    per-task restart in TonY)."""
    marker = tmp_path / "attempt"
    # worker:1 fails on its first attempt only; worker:0 (chief) waits briefly
    cmd = (
        f"bash -c 'if [ \"$TONY_TASK_INDEX\" = 1 ] && [ ! -f {marker} ]; "
        f"then touch {marker}; exit 7; fi; exit 0'"
    )
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.worker.instances": 2,
           "tony.worker.command": cmd,
           "tony.worker.max-restarts": 2,
           "tony.application.fail-on-worker-failure-enabled": True},
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)
    assert marker.exists()


def test_heartbeat_death_consumes_restart_budget(
        tmp_job_dirs, fixture_script, tmp_path):
    """A hung executor (heartbeat expiry) is a RESTARTABLE failure: it
    must route through the per-task restart budget before failing the
    job — the seed behavior called session._fail on the first expiry
    even with tony.<role>.max-restarts attempts left. Every attempt here
    hangs (the skip-all-heartbeats knob rides the role env), so the
    driver should burn 1 + max-restarts launches and only then fail
    with the heartbeat message — and the killed attempts' container
    completions must not double-spend the budget."""
    attempts = tmp_path / "attempts"
    cmd = (f"bash -c 'echo launch >> {attempts}; "
           f"exec {PY} {fixture_script('sleep_long.py')}'")
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.worker.instances": 1,
           "tony.worker.command": cmd,
           "tony.worker.max-restarts": 2,
           "tony.task.heartbeat-interval-ms": 100,
           "tony.task.max-missed-heartbeats": 5,
           "tony.worker.env": "TONY_TEST_EXECUTOR_NUM_HB_MISS=1000"},
    )
    assert status == JobStatus.FAILED, dump_logs(client)
    assert "heartbeat" in client.final_state.get("message", "")
    # 1 original + exactly the 2 budgeted restarts reached the command
    n = (len(attempts.read_text().splitlines()) if attempts.exists() else 0)
    assert n == 3, (n, dump_logs(client))


def test_driver_crash_reported_to_client(tmp_job_dirs, fixture_script):
    """Driver dies mid-run (reference TEST_AM_CRASH,
    ApplicationMaster.java:382-393); the client must detect and not hang."""
    import os

    os.environ["TONY_TEST_DRIVER_CRASH"] = "1.5"
    try:
        status, client = run_job(
            tmp_job_dirs,
            **{"tony.worker.instances": 1,
               "tony.worker.command": f"{PY} {fixture_script('sleep_long.py')}"},
        )
    finally:
        del os.environ["TONY_TEST_DRIVER_CRASH"]
    assert status in (JobStatus.FAILED, JobStatus.KILLED)


def test_executor_dies_with_driver(tmp_job_dirs, fixture_script):
    """Executors must not outlive a hard-killed driver PAST THE OUTAGE
    GRACE: since the control-plane recovery work (ISSUE 12), a driver
    transport outage is first ridden for tony.task.driver-outage-grace-ms
    (the window a `--recover` relaunch re-adopts through — executors
    keep working and re-resolve driver.json); only when no recovered
    driver appears do they drain the user process and exit (the role
    YARN plays in the reference by reaping a dead AM's containers)."""
    import signal as _signal
    import subprocess

    client = TonyClient(
        base_conf(
            tmp_job_dirs,
            **{"tony.worker.instances": 1,
               "tony.worker.command": f"{PY} {fixture_script('sleep_long.py')}",
               "tony.task.heartbeat-interval-ms": 100,
               "tony.task.max-missed-heartbeats": 5,
               # short grace: this test IS the no-recovery-arrived path
               "tony.task.driver-outage-grace-ms": 1500,
               "tony.task.preempt-grace-ms": 1500},
        ),
        poll_interval_s=0.1,
    )
    client.submit()
    # wait for the worker to be RUNNING, then SIGKILL the driver process
    deadline = time.time() + 30
    while time.time() < deadline:
        if client._driver_proc.poll() is not None:
            raise AssertionError("driver died early:\n" + dump_logs(client))
        infos = {t.task_id: t.status for t in client._poll_task_infos()} \
            if hasattr(client, "_poll_task_infos") else {}
        if _job_executors(client.app_id):
            break
        time.sleep(0.2)
    executors = _job_executors(client.app_id)
    assert executors, "no executor process found"
    os.kill(client._driver_proc.pid, _signal.SIGKILL)
    t_kill = time.time()
    # the executor must SURVIVE the early outage window (a recovered
    # driver would re-adopt it here) ...
    time.sleep(0.8)
    assert _job_executors(client.app_id), (
        "executor gave up inside the outage grace")
    # ... then drain and exit once the grace (1.5s) + the child's drain
    # window run dry — seconds, not minutes
    deadline = t_kill + 20
    while time.time() < deadline and _job_executors(client.app_id):
        time.sleep(0.5)
    leftover = _job_executors(client.app_id)
    for pid in leftover:
        os.kill(pid, _signal.SIGKILL)
    assert not leftover, f"executors outlived the driver: {leftover}"


def _job_executors(app_id: str) -> list[int]:
    """Pids of tony_tpu.executor processes belonging to this job (matched by
    the TONY_APP_ID in their environment, so concurrent jobs don't collide)."""
    import subprocess

    out = subprocess.run(
        ["pgrep", "-f", "tony_tpu.executor"], capture_output=True, text=True
    )
    pids = []
    for p in out.stdout.split():
        try:
            environ = Path(f"/proc/{p}/environ").read_bytes()
            if app_id.encode() in environ:
                pids.append(int(p))
        except OSError:
            continue
    return pids


def test_registration_timeout(tmp_job_dirs, fixture_script):
    """A task that launches but never registers fails the job after
    tony.am.registration-timeout-ms (reference ApplicationMaster.java:1314-1334)."""
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.worker.instances": 2,
           "tony.worker.command": f"{PY} {fixture_script('exit_0.py')}",
           # worker:1 skews its registration far beyond the timeout
           "tony.worker.env": "TONY_TEST_EXECUTOR_SKEW=worker#1#600000",
           "tony.am.registration-timeout-ms": 1500},
    )
    assert status == JobStatus.FAILED
    assert "register" in client.final_state.get("message", "")


def test_ray_head_worker_env(tmp_job_dirs, fixture_script):
    """Ray runtime: head address exported to all tasks (reference
    ray-on-tony example flow, done natively)."""
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.application.framework": "ray",
           "tony.head.instances": 1,
           "tony.head.command": f"{PY} {fixture_script('check_ray_env.py')}",
           "tony.worker.instances": 2,
           "tony.worker.command": f"{PY} {fixture_script('check_ray_env.py')}"},
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)


def test_client_callback_api(tmp_job_dirs, fixture_script):
    """Programmatic embedding API: CallbackHandler.on_application_id_received
    + TaskUpdateListener (reference client/CallbackHandler.java,
    TestTonyE2E.java:430)."""
    seen = {"app_id": None, "updates": 0}

    class Handler:
        def on_application_id_received(self, app_id):
            seen["app_id"] = app_id

    client = TonyClient(
        base_conf(
            tmp_job_dirs,
            **{"tony.worker.instances": 1,
               "tony.worker.command": f"{PY} {fixture_script('exit_0.py')}"},
        ),
        callback_handler=Handler(),
        poll_interval_s=0.1,
    )
    client.add_listener(lambda infos: seen.__setitem__("updates", seen["updates"] + 1))
    client.submit()
    status = client.monitor()
    assert status == JobStatus.SUCCEEDED
    assert seen["app_id"] == client.app_id
    assert seen["updates"] >= 1


# ---------------------------------------------------------- containerized run

def test_docker_containerized_task(tmp_job_dirs, fixture_script, tmp_path,
                                   monkeypatch):
    """With tony.docker.enabled the executor wraps the user command in
    `docker run` (reference Docker-on-YARN, HadoopCompatibleAdapter.java:
    45-159). A shim `docker` on PATH verifies the wrapping: it applies the
    -e contract env, injects a marker, and execs the inner command."""
    shim_dir = tmp_path / "bin"
    shim_dir.mkdir()
    shim = shim_dir / "docker"
    shim.write_text(f"""#!{PY}
import os, sys
args = sys.argv[1:]
assert args[0] == "run", args
env = dict(os.environ)
env["DOCKER_SHIM_USED"] = "1"
i = 1
while i < len(args):
    a = args[i]
    if a in ("--rm",):
        i += 1
    elif a in ("--network", "-v", "-w", "--user", "--name"):
        i += 2
    elif a == "-e":
        k, _, v = args[i + 1].partition("=")
        env[k] = v
        i += 2
    else:
        break  # image
inner = args[i + 1:]          # ["bash", "-c", command]
os.execvpe(inner[0], inner, env)
""")
    shim.chmod(0o755)
    monkeypatch.setenv("PATH", f"{shim_dir}:{os.environ['PATH']}")
    status, client = run_job(
        tmp_job_dirs,
        **{"tony.worker.instances": 1,
           "tony.docker.enabled": True,
           "tony.docker.containers.image": "tony-test-image:latest",
           "tony.execution.env": "TONY_E2E_PASSTHRU=yes",
           "tony.worker.command": f"{PY} {fixture_script('check_docker_env.py')}"},
    )
    assert status == JobStatus.SUCCEEDED, dump_logs(client)


def test_allocation_timeout_breaks_gang_deadlock(tmp_job_dirs, fixture_script):
    """One gang member never receives capacity; the allocation-timeout
    health check must fail the job instead of hanging forever (reference
    gang-deadlock breaker, MLGenericRuntime.java:110-147 / issue #573)."""
    os.environ["TONY_TEST_ALLOCATION_HOLD"] = "worker#1"
    try:
        status, client = run_job(
            tmp_job_dirs,
            **{"tony.worker.instances": 2,
               "tony.worker.command": f"{PY} {fixture_script('exit_0.py')}",
               "tony.am.allocation-timeout-ms": 1500,
               "tony.am.monitor-interval-ms": 100},
        )
    finally:
        del os.environ["TONY_TEST_ALLOCATION_HOLD"]
    assert status == JobStatus.FAILED
    assert "allocation" in client.final_state.get("message", "").lower()
