"""DAG scheduler tests — reference TestTaskScheduler (cycle detection,
staged release of dependents)."""

import pytest

from tony_tpu.conf import TonyConf
from tony_tpu.scheduler import DependencyCycleError, TaskScheduler, build_dependency_graph, check_dag


def test_cycle_rejected():
    conf = TonyConf({
        "tony.a.instances": 1, "tony.a.depends-on": "b",
        "tony.b.instances": 1, "tony.b.depends-on": "a",
    })
    with pytest.raises(DependencyCycleError):
        TaskScheduler(conf, conf.role_specs(), lambda s: None)


def test_unknown_dependency_rejected():
    conf = TonyConf({"tony.a.instances": 1, "tony.a.depends-on": "ghost"})
    with pytest.raises(ValueError, match="unknown"):
        build_dependency_graph(conf, conf.role_specs())


def test_topological_order():
    deps = {"c": {"b"}, "b": {"a"}, "a": set()}
    assert check_dag(deps) == ["a", "b", "c"]


def test_staged_release():
    conf = TonyConf({
        "tony.prep.instances": 2,
        "tony.worker.instances": 2, "tony.worker.depends-on": "prep",
        "tony.eval.instances": 1, "tony.eval.depends-on": "worker",
    })
    requested = []
    sched = TaskScheduler(conf, conf.role_specs(), lambda s: requested.append(s.name))
    sched.schedule()
    assert requested == ["prep"]
    assert sched.dependency_pending("worker")
    sched.on_task_completed("prep", succeeded=True)
    assert requested == ["prep"], "only 1 of 2 prep instances done"
    sched.on_task_completed("prep", succeeded=True)
    assert requested == ["prep", "worker"]
    sched.on_task_completed("worker", succeeded=True)
    sched.on_task_completed("worker", succeeded=True)
    assert requested == ["prep", "worker", "eval"]


def test_failed_dependency_blocks_dependents():
    conf = TonyConf({
        "tony.prep.instances": 1,
        "tony.worker.instances": 1, "tony.worker.depends-on": "prep",
    })
    requested = []
    sched = TaskScheduler(conf, conf.role_specs(), lambda s: requested.append(s.name))
    sched.schedule()
    sched.on_task_completed("prep", succeeded=False)
    assert requested == ["prep"], "failed dependency must not release dependents"
    assert sched.unscheduled_roles() == ["worker"]


def test_prepare_training_stage_convenience():
    conf = TonyConf({
        "tony.etl.instances": 1,
        "tony.worker.instances": 2,
        "tony.application.prepare-stage": "etl",
        "tony.application.training-stage": "worker",
    })
    deps = build_dependency_graph(conf, conf.role_specs())
    assert deps["worker"] == {"etl"}
