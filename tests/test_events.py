"""Event history tests — reference TestEventHandler (avro round-trip),
TestHistoryFileUtils, HistoryFileMoverTest/HistoryFilePurgerTest."""

import time

from tony_tpu.events import (
    Event,
    EventHandler,
    EventType,
    HistoryFileMover,
    HistoryFilePurger,
    history_file_name,
    parse_history_file_name,
)
from tony_tpu.events.handler import read_events
from tony_tpu.events.trace import TRACE_FILE, TraceWriter, read_traces
from tony_tpu.events.types import (
    application_inited,
    request_trace,
    task_finished,
)


def test_filename_codec_roundtrip():
    name = history_file_name("app_1", 1000, end_ms=2000, user="alice", status="SUCCEEDED")
    meta = parse_history_file_name(name)
    assert meta.app_id == "app_1"
    assert meta.start_ms == 1000 and meta.end_ms == 2000
    assert meta.user == "alice" and meta.status == "SUCCEEDED"

    inprog = history_file_name("app_2", 1000, user="bob")
    meta2 = parse_history_file_name(inprog)
    assert meta2.end_ms is None and meta2.status == ""


def test_event_handler_writes_and_finalizes(tmp_path):
    h = EventHandler(str(tmp_path), "app_42", user="u")
    h.start()
    h.emit(application_inited("app_42", 3, "localhost"))
    h.emit(task_finished("worker:0", "SUCCEEDED", 0, [{"name": "rss", "value": 1.0}]))
    final = h.stop("SUCCEEDED")
    assert final.exists() and final.name.endswith("-SUCCEEDED.jhist")
    assert not h.path.exists(), ".inprogress must be renamed"
    events = read_events(final)
    assert [e.type for e in events] == [EventType.APPLICATION_INITED, EventType.TASK_FINISHED]
    assert events[1].payload["metrics"][0]["name"] == "rss"


def test_event_json_roundtrip():
    e = Event(EventType.TASK_STARTED, {"task_id": "w:1"}, timestamp=123)
    e2 = Event.from_json(e.to_json())
    assert e2.type == e.type and e2.payload == e.payload and e2.timestamp == 123


def test_request_trace_event_roundtrip():
    rec = {"id": 4, "spans": [["submitted", 1.0], ["finished", 2.0]],
           "attrs": {"n_tokens": 3}}
    e = Event.from_json(request_trace(rec).to_json())
    assert e.type == EventType.REQUEST_TRACE
    assert e.payload["trace"]["id"] == 4


def test_trace_writer_roundtrip_and_torn_line(tmp_path):
    """TraceWriter appends JSONL records read_traces round-trips; a torn
    (malformed) line is skipped instead of hiding the rest."""
    w = TraceWriter(tmp_path / "job")
    assert w.path.name == TRACE_FILE
    recs = [
        {"id": 0, "spans": [["submitted", 1.0], ["finished", 2.5]],
         "attrs": {"n_tokens": 2, "finish_reason": "length"}},
        {"id": 1, "spans": [["submitted", 1.1], ["shed", 1.2]],
         "attrs": {"finish_reason": "shed"}},
    ]
    for r in recs:
        w.write(r)
    w.close()
    with open(w.path, "a") as f:
        f.write('{"id": 2, "spans": [["subm')     # crash-torn tail
    assert read_traces(w.path) == recs


def test_mover_moves_finished_and_finalizes_orphans(tmp_path):
    inter = tmp_path / "intermediate"
    fin = tmp_path / "finished"
    # finished job
    done = inter / "app_done"
    done.mkdir(parents=True)
    (done / history_file_name("app_done", 1000, 2000, "u", "SUCCEEDED")).write_text("")
    # orphaned in-progress (driver killed)
    dead = inter / "app_dead"
    dead.mkdir(parents=True)
    (dead / (history_file_name("app_dead", 1000, user="u") + ".inprogress")).write_text("")
    # still-running job stays put
    running = inter / "app_running"
    running.mkdir(parents=True)
    now_name = history_file_name("app_running", int(time.time() * 1000), user="u")
    # running jobs have ONLY non-inprogress? No: running jobs have .inprogress too,
    # but mover marks them KILLED only when orphaned; we treat any .inprogress as
    # orphaned on a mover pass, which matches portal semantics (mover only runs
    # against drivers that stopped updating).

    mover = HistoryFileMover(str(inter), str(fin))
    moved = mover.move_once()
    assert len(moved) == 2
    moved_files = list(fin.rglob("*.jhist"))
    assert any("SUCCEEDED" in f.name for f in moved_files)
    assert any("KILLED" in f.name for f in moved_files)


def test_purger(tmp_path):
    fin = tmp_path / "finished" / "2020" / "01" / "01" / "app_old"
    fin.mkdir(parents=True)
    (fin / history_file_name("app_old", 1000, 2000, "u", "FAILED")).write_text("")
    new = tmp_path / "finished" / "2099" / "01" / "01" / "app_new"
    new.mkdir(parents=True)
    future_ms = int((time.time() + 1000) * 1000)
    (new / history_file_name("app_new", future_ms, future_ms, "u", "SUCCEEDED")).write_text("")
    purger = HistoryFilePurger(str(tmp_path / "finished"), retention_sec=3600)
    purged = purger.purge_once()
    assert len(purged) == 1
    assert not fin.exists()
    assert new.exists()
