"""Event history tests — reference TestEventHandler (avro round-trip),
TestHistoryFileUtils, HistoryFileMoverTest/HistoryFilePurgerTest."""

import time

from tony_tpu.events import (
    Event,
    EventHandler,
    EventType,
    HistoryFileMover,
    HistoryFilePurger,
    history_file_name,
    parse_history_file_name,
)
from tony_tpu.events.handler import read_events
from tony_tpu.events.trace import TRACE_FILE, TraceWriter, read_traces
from tony_tpu.events.types import (
    application_inited,
    request_trace,
    task_finished,
)


def test_filename_codec_roundtrip():
    name = history_file_name("app_1", 1000, end_ms=2000, user="alice", status="SUCCEEDED")
    meta = parse_history_file_name(name)
    assert meta.app_id == "app_1"
    assert meta.start_ms == 1000 and meta.end_ms == 2000
    assert meta.user == "alice" and meta.status == "SUCCEEDED"

    inprog = history_file_name("app_2", 1000, user="bob")
    meta2 = parse_history_file_name(inprog)
    assert meta2.end_ms is None and meta2.status == ""


def test_event_handler_writes_and_finalizes(tmp_path):
    h = EventHandler(str(tmp_path), "app_42", user="u")
    h.start()
    h.emit(application_inited("app_42", 3, "localhost"))
    h.emit(task_finished("worker:0", "SUCCEEDED", 0, [{"name": "rss", "value": 1.0}]))
    final = h.stop("SUCCEEDED")
    assert final.exists() and final.name.endswith("-SUCCEEDED.jhist")
    assert not h.path.exists(), ".inprogress must be renamed"
    events = read_events(final)
    assert [e.type for e in events] == [EventType.APPLICATION_INITED, EventType.TASK_FINISHED]
    assert events[1].payload["metrics"][0]["name"] == "rss"


def test_event_json_roundtrip():
    e = Event(EventType.TASK_STARTED, {"task_id": "w:1"}, timestamp=123)
    e2 = Event.from_json(e.to_json())
    assert e2.type == e.type and e2.payload == e.payload and e2.timestamp == 123


def test_request_trace_event_roundtrip():
    rec = {"id": 4, "spans": [["submitted", 1.0], ["finished", 2.0]],
           "attrs": {"n_tokens": 3}}
    e = Event.from_json(request_trace(rec).to_json())
    assert e.type == EventType.REQUEST_TRACE
    assert e.payload["trace"]["id"] == 4


def test_trace_writer_roundtrip_and_torn_line(tmp_path):
    """TraceWriter appends JSONL records read_traces round-trips; a torn
    (malformed) line is skipped instead of hiding the rest."""
    w = TraceWriter(tmp_path / "job")
    assert w.path.name == TRACE_FILE
    recs = [
        {"id": 0, "spans": [["submitted", 1.0], ["finished", 2.5]],
         "attrs": {"n_tokens": 2, "finish_reason": "length"}},
        {"id": 1, "spans": [["submitted", 1.1], ["shed", 1.2]],
         "attrs": {"finish_reason": "shed"}},
    ]
    for r in recs:
        w.write(r)
    w.close()
    with open(w.path, "a") as f:
        f.write('{"id": 2, "spans": [["subm')     # crash-torn tail
    assert read_traces(w.path) == recs


def test_request_journal_roundtrip_and_torn_line(tmp_path):
    """The durability record behind serving replay (docs/serving.md
    "Request durability & replay"): submit/emit/end round-trip through
    the file, a crash-torn tail line is skipped, an emit for an unknown
    id is skipped, and a finished request's entry never resurfaces."""
    from tony_tpu.events.journal import (
        JOURNAL_FILE, RequestJournal, read_journal,
    )

    path = tmp_path / JOURNAL_FILE
    j = RequestJournal(path)
    j.submit(1, [5, 6, 7], 8, temperature=0.5, top_k=3, seed=42)
    j.submit(2, [9], 4)
    j.emit(1, [10, 11])
    j.emit(1, [12])
    j.emit(999, [1])            # unknown id: ignored in-memory too
    j.finish(2)                 # delivered: sealed
    j.finish(2)                 # idempotent
    assert len(j) == 1
    entry = j.get(1)
    assert entry.emitted == [10, 11, 12] and entry.prompt == [5, 6, 7]
    assert j.get(2) is None
    j.close()
    with open(path, "a") as f:
        f.write('{"op": "emit", "id": 1, "tok')      # crash-torn tail
    entries = read_journal(path)
    assert [e.id for e in entries] == [1]
    e = entries[0]
    assert (e.prompt, e.emitted, e.max_new_tokens) == ([5, 6, 7],
                                                       [10, 11, 12], 8)
    assert (e.temperature, e.top_k, e.seed) == (0.5, 3, 42)


def test_request_journal_steady_state_compaction(tmp_path):
    """The file journal must not grow for the life of the process:
    every compact_every sealed entries it rewrites down to the LIVE
    set (tmp+rename), and the post-compaction file still round-trips —
    including a live entry's emitted prefix."""
    from tony_tpu.events.journal import (
        JOURNAL_FILE, RequestJournal, read_journal,
    )

    path = tmp_path / JOURNAL_FILE
    j = RequestJournal(path, compact_every=8)
    j.submit(1000, [1, 2, 3], 16)       # stays live across compactions
    j.emit(1000, [4, 5])
    for rid in range(20):               # 20 sealed -> 2 compactions
        j.submit(rid, [7] * 4, 4)
        j.emit(rid, [9, 9])
        j.finish(rid)
    assert j.compactions == 2 and j.write_errors == 0
    text = path.read_text()
    assert text.count('"op": "submit"') <= 1 + (20 % 8) * 1 + 1, (
        "dead records must not survive a compaction")
    # the live entry survives compaction with its prefix, and further
    # appends after the handle swap still land
    j.emit(1000, [6])
    j.close()
    entries = read_journal(path)
    live = {e.id: e for e in entries}
    assert live[1000].emitted == [4, 5, 6]
    assert all(rid not in live for rid in range(20))


def test_request_journal_recover_never_loses_then_compacts(tmp_path):
    """recover() hands back the dead process's unfinished entries but
    deliberately does NOT drop their records yet: until the
    resubmission is journaled, they are the only copy — a crash in the
    gap must double-replay, never lose. compact() (which
    SlotServer.recover_journal calls after resubmitting) then rewrites
    the file down to the live set, so a later recovery sees exactly
    the resubmitted entries. In-memory journals (path=None) support
    the same ops with no file."""
    from tony_tpu.events.journal import JOURNAL_FILE, RequestJournal

    path = tmp_path / JOURNAL_FILE
    j = RequestJournal(path)
    j.submit(7, [1, 2], 6)
    j.emit(7, [3])
    j.close()                   # simulated process death
    j2, entries = RequestJournal.recover(path)
    assert [(e.id, e.emitted) for e in entries] == [(7, [3])]
    # the dead record is still on disk: a crash BEFORE the
    # resubmission lands replays it again instead of losing it
    _, still_there = RequestJournal.recover(path)
    assert [(e.id, e.emitted) for e in still_there] == [(7, [3])]
    # a resumed resubmission pre-seeds the emitted record; compact()
    # then drops the dead process's records atomically
    j2.submit(0, entries[0].prompt, entries[0].max_new_tokens,
              emitted=entries[0].emitted)
    assert j2.get(0).emitted == [3]
    j2.compact()
    j2.close()
    _, again = RequestJournal.recover(path)
    assert [(e.id, e.emitted) for e in again] == [(0, [3])]
    mem = RequestJournal()
    mem.submit(1, [4], 2)
    mem.emit(1, [5])
    assert mem.get(1).emitted == [5] and mem.path is None
    mem.finish(1)
    mem.compact()               # no file: a no-op, never an error
    assert len(mem) == 0


def test_mover_moves_finished_and_finalizes_orphans(tmp_path):
    inter = tmp_path / "intermediate"
    fin = tmp_path / "finished"
    # finished job
    done = inter / "app_done"
    done.mkdir(parents=True)
    (done / history_file_name("app_done", 1000, 2000, "u", "SUCCEEDED")).write_text("")
    # orphaned in-progress (driver killed)
    dead = inter / "app_dead"
    dead.mkdir(parents=True)
    (dead / (history_file_name("app_dead", 1000, user="u") + ".inprogress")).write_text("")
    # still-running job stays put
    running = inter / "app_running"
    running.mkdir(parents=True)
    now_name = history_file_name("app_running", int(time.time() * 1000), user="u")
    # running jobs have ONLY non-inprogress? No: running jobs have .inprogress too,
    # but mover marks them KILLED only when orphaned; we treat any .inprogress as
    # orphaned on a mover pass, which matches portal semantics (mover only runs
    # against drivers that stopped updating).

    mover = HistoryFileMover(str(inter), str(fin))
    moved = mover.move_once()
    assert len(moved) == 2
    moved_files = list(fin.rglob("*.jhist"))
    assert any("SUCCEEDED" in f.name for f in moved_files)
    assert any("KILLED" in f.name for f in moved_files)


def test_purger(tmp_path):
    fin = tmp_path / "finished" / "2020" / "01" / "01" / "app_old"
    fin.mkdir(parents=True)
    (fin / history_file_name("app_old", 1000, 2000, "u", "FAILED")).write_text("")
    new = tmp_path / "finished" / "2099" / "01" / "01" / "app_new"
    new.mkdir(parents=True)
    future_ms = int((time.time() + 1000) * 1000)
    (new / history_file_name("app_new", future_ms, future_ms, "u", "SUCCEEDED")).write_text("")
    purger = HistoryFilePurger(str(tmp_path / "finished"), retention_sec=3600)
    purged = purger.purge_once()
    assert len(purged) == 1
    assert not fin.exists()
    assert new.exists()
