"""Streaming serving subsystem (tony_tpu/api/ + SlotServer token
streams + the serve /v1 endpoints — docs/serving.md "Streaming &
OpenAI compatibility").

Contracts under test, bottom-up: the TokenStream channel (absolute-
position dedupe, bounded-queue backpressure accounting, guaranteed
terminal), the OpenAI payload mapping (params accepted, keys emitted,
finish_reason mapping — PINNED against docs/serving.md by the
api-contract lint so surface drift fails by name), SSE delivery over
live HTTP byte-identical to the buffered path and to solo generate,
multi-model /v1 routing, and streamed byte-identity ACROSS a mid-decode
loop crash (the PR 11 replay riding underneath an open stream).
"""

import json
import re
import threading
import time
import urllib.error
import urllib.request
from http.server import ThreadingHTTPServer
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.api import openai as oai
from tony_tpu.api.stream import SSE_DONE, TokenStream, sse_frame
from tony_tpu.cli.serve import ServeApp, make_handler
from tony_tpu.models import transformer
from tony_tpu.models.generate import generate
from tony_tpu.models.registry import ModelRegistry
from tony_tpu.models.serving import Request, SlotServer

TINY = transformer.TransformerConfig(
    vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=128, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return transformer.init(jax.random.PRNGKey(0), TINY)


def _prompt(n, seed=3):
    return np.asarray(jax.random.randint(
        jax.random.PRNGKey(seed), (n,), 0, TINY.vocab_size), np.int32)


def _solo(params, prompt, max_new):
    out = generate(params, TINY, jnp.asarray(prompt)[None], max_new)
    return [int(t) for t in np.asarray(out)[0]]


def _srv(params, **kw):
    """test_serving_robustness.py shapes — the tier-1 run reuses the
    already-compiled programs."""
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_chunk", 8)
    return SlotServer(params, TINY, **kw)


def _http_app(params, **kw):
    srv = _srv(params, **kw)
    app = ServeApp(srv)
    app.start()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(app))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return srv, app, httpd, httpd.server_address[1]


def _sse_post(port, path, payload, timeout=120):
    """POST expecting an SSE response; returns the data-frame strings."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    frames = []
    with urllib.request.urlopen(req, timeout=timeout) as r:
        assert r.headers["Content-Type"] == "text/event-stream"
        for raw in r:
            line = raw.decode().strip()
            if line.startswith("data: "):
                frames.append(line[len("data: "):])
    return frames


def _json_post(port, path, payload, timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read().decode())


# --------------------------------------------------------------------------
# TokenStream: the channel itself (no model, no HTTP)
# --------------------------------------------------------------------------

def test_token_stream_absolute_feed_dedupes():
    """Feeds carry the ABSOLUTE emitted list; only the unseen suffix is
    delivered — the property that makes replays and failover prefix
    re-sends invisible to the consumer."""
    ts = TokenStream()
    assert ts.feed([1, 2, 3]) == (3, False)
    assert ts.feed([1, 2, 3]) == (0, False)         # replay re-send
    assert ts.feed([1, 2, 3, 4, 5]) == (2, False)   # only the suffix
    ts.finish("length")
    toks, reason, err = ts.drain_all(timeout=5)
    assert toks == [1, 2, 3, 4, 5] and reason == "length" and err is None


def test_token_stream_backpressure_coalesces_never_drops():
    """A consumer that can't drain bounds the CHUNK count, not the
    tokens: overflow coalesces into the newest chunk and is accounted
    as a stall — byte-identity survives arbitrarily slow clients."""
    ts = TokenStream(max_chunks=2)
    emitted = []
    stalls = 0
    for i in range(10):
        emitted.append(i)
        _, stalled = ts.feed(emitted)
        stalls += bool(stalled)
    assert stalls == 10 - 2 == ts.stalls
    assert len(ts._chunks) == 2
    ts.finish("stop")
    toks, reason, _ = ts.drain_all(timeout=5)
    assert toks == list(range(10)) and reason == "stop"


def test_token_stream_terminal_semantics():
    """First terminal wins (a finish after a fail stays failed); the
    iterator always ends with exactly one done/error event, after
    every queued chunk."""
    ts = TokenStream()
    ts.feed([7])
    ts.fail("boom")
    ts.finish("length")                 # too late: failed stays failed
    toks, reason, err = ts.drain_all(timeout=5)
    assert toks == [7] and reason is None and err == "boom"
    # wait beats surface while nothing is queued
    ts2 = TokenStream()
    assert ts2.take(timeout=0.01) == ("wait", None)
    ts2.finish("stop")
    assert ts2.take(timeout=0.01) == ("done", "stop")


# --------------------------------------------------------------------------
# OpenAI payload mapping units
# --------------------------------------------------------------------------

def test_codec_ids_roundtrip_and_bytes_mode():
    ids = oai.TokenCodec("ids")
    assert ids.encode("17 4 99") == [17, 4, 99]
    assert ids.decode([17, 4, 99]) == "17 4 99"
    with pytest.raises(ValueError, match="decimal token ids"):
        ids.encode("hello world")
    by = oai.TokenCodec("bytes", vocab_size=256)
    assert by.encode("hi") == [104, 105]
    assert by.decode([104, 105]) == "hi"
    with pytest.raises(ValueError, match="vocab >= 256"):
        oai.TokenCodec("bytes", vocab_size=128).encode("x")
    with pytest.raises(ValueError, match="unknown text codec"):
        oai.TokenCodec("words")


def test_parse_completion_request():
    codec = oai.TokenCodec("ids")
    req = oai.parse_completion_request(
        {"prompt": [1, 2, 3], "max_tokens": 9, "temperature": 0.5,
         "top_k": 4, "stream": True, "model": "m"}, codec)
    assert req["prompt_tokens"] == [1, 2, 3]
    assert req["max_new_tokens"] == 9 and req["stream"] is True
    assert req["temperature"] == 0.5 and req["top_k"] == 4
    assert req["model"] == "m"
    # defaults: OpenAI's max_tokens=16, no sampling overrides
    req = oai.parse_completion_request({"prompt": "5 6"}, codec)
    assert req["prompt_tokens"] == [5, 6]
    assert req["max_new_tokens"] == 16 and req["stream"] is False
    assert "temperature" not in req and "top_k" not in req
    for bad in ({"prompt": []}, {"prompt": 7}, {"prompt": [True]},
                {"prompt": [1], "n": 2},
                {"prompt": [1], "stream": "yes"},
                {"prompt": [1], "timeout_s": 0}):
        with pytest.raises((ValueError, TypeError)):
            oai.parse_completion_request(bad, codec)


def test_parse_chat_request_concatenates_messages():
    codec = oai.TokenCodec("ids")
    req = oai.parse_chat_request(
        {"messages": [{"role": "system", "content": "1 2"},
                      {"role": "user", "content": "3"}]}, codec)
    assert req["prompt_tokens"] == [1, 2, 3]
    for bad in ({"messages": []}, {"messages": "hi"},
                {"messages": [{"role": "user"}]},
                {"messages": [{"content": ""}]}):
        with pytest.raises(ValueError):
            oai.parse_chat_request(bad, codec)


def test_response_shapes_match_pinned_keys():
    codec = oai.TokenCodec("ids")
    comp = oai.completion_response(3, "m", [9, 8], "length", 5, codec)
    assert set(comp) == set(oai.COMPLETION_RESPONSE_KEYS)
    assert set(comp["choices"][0]) == set(oai.CHOICE_KEYS)
    assert set(comp["usage"]) == set(oai.USAGE_KEYS)
    assert comp["usage"] == {"prompt_tokens": 5, "completion_tokens": 2,
                             "total_tokens": 7}
    assert comp["id"].startswith("cmpl-") and comp["object"] == \
        "text_completion"
    chat = oai.chat_response(3, "m", [9, 8], "stop", 5, codec)
    assert set(chat) == set(oai.CHAT_RESPONSE_KEYS)
    assert set(chat["choices"][0]) == set(oai.CHAT_CHOICE_KEYS)
    assert chat["choices"][0]["message"] == {"role": "assistant",
                                             "content": "9 8"}
    # finish_reason mapping is the pinned table, applied
    for eng, wire in oai.FINISH_REASON_MAP.items():
        got = oai.completion_response(0, "m", [], eng, 0, codec)
        assert got["choices"][0]["finish_reason"] == wire
    # streamed chunks: delta frames carry no finish_reason, the closer
    # does; the first chat delta carries the assistant role
    ch = oai.completion_chunk(1, "m", [4], codec)
    assert ch["choices"][0]["finish_reason"] is None
    closer = oai.chat_chunk(1, "m", [], codec, finish_reason="length",
                            first=False)
    assert closer["choices"][0]["finish_reason"] == "length"
    first = oai.chat_chunk(1, "m", [4], codec, first=True)
    assert first["choices"][0]["delta"]["role"] == "assistant"


# --------------------------------------------------------------------------
# api-contract lint: code <-> docs/serving.md, both directions
# --------------------------------------------------------------------------

def _doc_section(doc: str, marker: str) -> str:
    m = re.search(rf"<!-- {marker}:start -->(.*?)<!-- {marker}:end -->",
                  doc, re.S)
    assert m, f"docs/serving.md lost its {marker} markers"
    return m.group(1)


def test_api_contract_pinned_against_docs():
    """Surface-drift lint: the /v1 request params the server honors,
    the response keys it emits, and the finish_reason mapping are
    pinned between api/openai.py and docs/serving.md's marked tables —
    adding/renaming on either side without the other fails BY NAME."""
    doc = (Path(__file__).resolve().parent.parent
           / "docs" / "serving.md").read_text()

    def names(marker):
        return set(re.findall(r"`([a-z_0-9]+)`",
                              _doc_section(doc, marker)))

    assert names("api-params-completions") == set(
        oai.COMPLETION_REQUEST_PARAMS), "completions params drifted"
    assert names("api-params-chat") == set(oai.CHAT_REQUEST_PARAMS), \
        "chat params drifted"
    assert names("api-response-keys") == (
        set(oai.COMPLETION_RESPONSE_KEYS) | set(oai.CHOICE_KEYS)
        | set(oai.CHAT_CHOICE_KEYS) | set(oai.USAGE_KEYS)), \
        "response keys drifted"
    # the finish_reason table maps engine -> wire, row for row
    rows = re.findall(r"\|\s*`(\w+)`\s*\|\s*`(\w+)`\s*\|",
                      _doc_section(doc, "api-finish-reasons"))
    assert dict(rows) == dict(oai.FINISH_REASON_MAP), \
        "finish_reason mapping drifted"
    # the engine side of the mapping must cover the pinned completion
    # vocabulary exactly (models/serving.py enum)
    from tony_tpu.models.serving import COMPLETION_FINISH_REASONS

    assert set(oai.FINISH_REASON_MAP) == set(COMPLETION_FINISH_REASONS)
    # admission-tier surface: both /v1 param sets honor `priority`
    # (engine classes, docs "Paged KV & admission tiers"), a shed
    # completion maps onto the wire, and every 429 producer advertises
    # Retry-After — serve derives it (engine estimate folded with the
    # autoscaler cooldown hint), the router PROPAGATES the replica
    # value instead of synthesizing its own
    import inspect

    import tony_tpu.cli.serve as serve_mod
    import tony_tpu.router as router_mod

    assert "priority" in oai.COMPLETION_REQUEST_PARAMS
    assert "priority" in oai.CHAT_REQUEST_PARAMS
    assert oai.FINISH_REASON_MAP.get("shed") == "shed"
    serve_src = inspect.getsource(serve_mod)
    router_src = inspect.getsource(router_mod)
    assert "Retry-After" in serve_src and "retry_after_s" in serve_src
    assert "Retry-After" in router_src and "min_retry_after" in router_src
    # disaggregated-serving surface (ISSUE 17): the /kv/import transfer
    # payload keys and the embedded journal-entry keys are pinned
    # against docs/serving.md's marked tables, and the replica role is
    # advertised where the router reads it — /stats on both layers
    from tony_tpu.models.serving import KV_ENTRY_KEYS, KV_IMPORT_KEYS

    assert names("kv-import-keys") == set(KV_IMPORT_KEYS), \
        "/kv/import payload keys drifted"
    assert names("kv-entry-keys") == set(KV_ENTRY_KEYS), \
        "KV transfer entry keys drifted"
    assert "/kv/import" in serve_src and "/kv/import" in router_src
    assert '"role"' in serve_src and '"role"' in router_src
    assert '"handoff"' in serve_src and '"handoff"' in router_src
    # router-tier HA surface (ISSUE 18): the "router" framework string
    # is pinned in every layer that speaks it — the runtime registry
    # maps it to a task adapter, the driver auto-detects the role by
    # it, and keys.py stopped reserving it as a role name; the route
    # CLI's SIGTERM drain flag and the portable cross-router progress
    # key (client request_id -> ``req:<id>``) are contract, not detail
    import tony_tpu.conf.keys as keys_mod
    import tony_tpu.driver as driver_mod
    import tony_tpu.runtimes as runtimes_mod

    registry_src = inspect.getsource(runtimes_mod)
    assert '("router",' in registry_src, (
        "runtimes registry lost the router framework")
    driver_src = inspect.getsource(driver_mod)
    assert 'fw == "router"' in driver_src, (
        "driver lost router-role framework auto-detection")
    assert "router" not in keys_mod._RESERVED_NON_ROLES, (
        "keys.py re-reserved 'router' — router roles can't be declared")
    assert "--drain-timeout-s" in router_src, (
        "route CLI lost its SIGTERM drain flag")
    assert 'f"req:{request_id}"' in router_src, (
        "router lost the portable cross-router progress key")
    assert '"request_id"' in router_src, (
        "router /generate lost the request_id body param")
    # distributed-tracing surface (ISSUE 19): the trace header names
    # are constants in observability.py, pinned against the doc's
    # marked table AND against both front-door sources, both
    # directions — renaming any side without the others fails here
    from tony_tpu.observability import (TRACE_HEADER,
                                        TRACE_ID_RESPONSE_HEADER)

    doc_headers = set(re.findall(r"`(X-Tony-[A-Za-z-]+)`",
                                 _doc_section(doc, "trace-headers")))
    assert doc_headers == {TRACE_HEADER, TRACE_ID_RESPONSE_HEADER}, \
        "trace header table drifted from observability.py constants"
    for src, who in ((serve_src, "serve"), (router_src, "router")):
        assert "TRACE_HEADER" in src, f"{who} lost X-Tony-Trace parsing"
        assert "TRACE_ID_RESPONSE_HEADER" in src, (
            f"{who} lost the X-Tony-Trace-Id response echo")
        assert '"trace_id"' in src, (
            f"{who} lost the SSE closing-frame trace_id field")
    # the transfer entry carries the trace context (header-less
    # imports must still land in the originating trace)
    assert "trace" in KV_ENTRY_KEYS


# --------------------------------------------------------------------------
# live HTTP: SSE byte-identity, /v1 endpoints, multi-model, crash replay
# --------------------------------------------------------------------------

def test_generate_sse_byte_identical_to_buffered(params):
    """THE streaming contract: /generate?stream=true delivers the same
    tokens, in order, across >= 2 incremental SSE frames, as the
    buffered POST and solo generate; the closing frame carries the
    finish_reason and the stream accounting shows up in /stats and
    /metrics."""
    srv, app, httpd, port = _http_app(params)
    try:
        prompt = [int(t) for t in _prompt(6, seed=31)]
        solo = _solo(params, np.asarray(prompt, np.int32), 12)
        frames = [json.loads(f) for f in _sse_post(
            port, "/generate?stream=true",
            {"prompt": prompt, "max_new_tokens": 12})]
        token_frames = [f for f in frames if "finish_reason" not in f]
        final = frames[-1]
        assert len(token_frames) >= 2, "delivery must be incremental"
        toks = [t for f in token_frames for t in f["tokens"]]
        assert toks == solo, "streamed tokens diverged from solo"
        assert final["finish_reason"] == "length"
        assert final["n_tokens"] == 12
        # buffered path agrees
        buf = _json_post(port, "/generate",
                         {"prompt": prompt, "max_new_tokens": 12})
        assert buf["tokens"] == solo
        st = app.stats()
        assert st["streams_opened"] == 1 and st["streams_active"] == 0
        assert st["stream_disconnects"] == 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            text = r.read().decode()
        for fam in ("serving_streams_active",
                    "serving_streams_opened_total",
                    "serving_stream_backpressure_stalls_total",
                    "serving_stream_disconnects_total",
                    "serving_stream_itl_seconds"):
            assert fam in text, f"{fam} missing from /metrics"
    finally:
        httpd.shutdown()
        httpd.server_close()
        app.shutdown()


def test_openai_endpoints_stream_and_buffered(params):
    """/v1/completions and /v1/chat/completions: the OpenAI wire shape
    end to end — buffered responses carry the pinned keys and usage,
    streams chunk the same tokens and end with [DONE], and the ids
    codec round-trips text prompts."""
    srv, app, httpd, port = _http_app(params)
    try:
        prompt = [int(t) for t in _prompt(6, seed=37)]
        solo = _solo(params, np.asarray(prompt, np.int32), 10)
        text = " ".join(str(t) for t in prompt)
        # completions, buffered, token-array prompt
        resp = _json_post(port, "/v1/completions",
                          {"prompt": prompt, "max_tokens": 10})
        assert resp["object"] == "text_completion"
        assert resp["choices"][0]["tokens"] == solo
        assert resp["choices"][0]["text"] == \
            " ".join(str(t) for t in solo)
        assert resp["usage"] == {"prompt_tokens": 6,
                                 "completion_tokens": 10,
                                 "total_tokens": 16}
        # completions, streamed, TEXT prompt through the ids codec
        frames = _sse_post(port, "/v1/completions",
                           {"prompt": text, "max_tokens": 10,
                            "stream": True})
        assert frames[-1] == "[DONE]"
        chunks = [json.loads(f)["choices"][0] for f in frames[:-1]]
        toks = [t for c in chunks for t in c["tokens"]]
        assert toks == solo
        assert chunks[-1]["finish_reason"] == "length"
        assert all(c["finish_reason"] is None for c in chunks[:-1])
        # chat, streamed: first delta carries the role, contents concat
        frames = _sse_post(port, "/v1/chat/completions",
                           {"messages": [{"role": "user",
                                          "content": text}],
                            "max_tokens": 10, "stream": True})
        assert frames[-1] == "[DONE]"
        chunks = [json.loads(f)["choices"][0] for f in frames[:-1]]
        assert chunks[0]["delta"].get("role") == "assistant"
        assert [t for c in chunks for t in c["tokens"]] == solo
        # chat, buffered
        resp = _json_post(port, "/v1/chat/completions",
                          {"messages": [{"role": "user",
                                         "content": text}],
                           "max_tokens": 10})
        assert resp["object"] == "chat.completion"
        assert resp["choices"][0]["message"]["content"] == \
            " ".join(str(t) for t in solo)
        # malformed: OpenAI error envelope, proper 400
        try:
            _json_post(port, "/v1/completions", {"prompt": []})
            raise AssertionError("empty prompt must 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
            err = json.loads(e.read().decode())["error"]
            assert err["type"] == "invalid_request_error"
    finally:
        httpd.shutdown()
        httpd.server_close()
        app.shutdown()


def test_openai_model_field_routes_through_registry(params):
    """The /v1 ``model`` field routes through the ModelRegistry: two
    engines in one process serve their own weights, the response
    echoes the model, an unknown name is a 400 invalid_request_error
    (never a silent fallback to the wrong weights)."""
    reg = ModelRegistry()
    reg.register("alpha", params, TINY, source="test")
    # beta: different weights -> different completions prove routing
    beta_params = transformer.init(jax.random.PRNGKey(9), TINY)
    reg.register("beta", beta_params, TINY, source="test")
    engines = {
        name: SlotServer(registry=reg, model=name, slots=2, max_len=64,
                         block_size=4, prefill_chunk=8)
        for name in ("alpha", "beta")}
    app = ServeApp(engines)
    app.start()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(app))
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        prompt = [int(t) for t in _prompt(5, seed=41)]
        solo_a = _solo(params, np.asarray(prompt, np.int32), 8)
        out_b = generate(beta_params, TINY,
                         jnp.asarray(np.asarray(prompt, np.int32))[None],
                         8)
        solo_b = [int(t) for t in np.asarray(out_b)[0]]
        assert solo_a != solo_b, "seeds must give distinct streams"
        ra = _json_post(port, "/v1/completions",
                        {"prompt": prompt, "max_tokens": 8,
                         "model": "alpha"})
        rb = _json_post(port, "/v1/completions",
                        {"prompt": prompt, "max_tokens": 8,
                         "model": "beta"})
        assert ra["choices"][0]["tokens"] == solo_a
        assert rb["choices"][0]["tokens"] == solo_b
        assert ra["model"] == "alpha" and rb["model"] == "beta"
        # streamed, model-routed
        frames = _sse_post(port, "/v1/completions",
                           {"prompt": prompt, "max_tokens": 8,
                            "model": "beta", "stream": True})
        toks = [t for f in frames[:-1]
                for t in json.loads(f)["choices"][0]["tokens"]]
        assert toks == solo_b
        try:
            _json_post(port, "/v1/completions",
                       {"prompt": prompt, "max_tokens": 4,
                        "model": "ghost"})
            raise AssertionError("unknown model must 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert json.loads(e.read().decode())["error"]["type"] == \
                "invalid_request_error"
    finally:
        httpd.shutdown()
        httpd.server_close()
        app.shutdown()


def test_streamed_request_survives_loop_crash_byte_identical(
        params, monkeypatch):
    """Replay under an OPEN stream: a deterministic mid-decode loop
    crash (PR 11 injection) replays the request with its journaled
    prefix while the SSE consumer keeps reading — the delivered stream
    has no duplicates, no gaps, and is byte-identical to solo. The
    absolute-position feed is what makes the re-emitted prefix
    invisible."""
    monkeypatch.setenv("TONY_TEST_SERVING_CRASH_AT_BLOCKS", "2")
    srv = _srv(params)
    assert srv._chaos_crash_blocks == {2}
    app = ServeApp(srv, max_loop_restarts=8, loop_backoff_s=0.02)
    app.start()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(app))
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        prompt = [int(t) for t in _prompt(6, seed=43)]
        solo = _solo(params, np.asarray(prompt, np.int32), 16)
        frames = [json.loads(f) for f in _sse_post(
            port, "/generate?stream=true",
            {"prompt": prompt, "max_new_tokens": 16})]
        toks = [t for f in frames if "finish_reason" not in f
                for t in f["tokens"]]
        assert frames[-1]["finish_reason"] == "length"
        assert toks == solo, (
            "streamed tokens across a loop-crash replay diverged")
        assert srv.chaos_faults_injected == 1 and srv.replays >= 1
    finally:
        httpd.shutdown()
        httpd.server_close()
        app.shutdown()


def test_stream_fails_loudly_when_replay_off(params, monkeypatch):
    """Fail-fast preserved under streaming: with the journal off, a
    loop crash ERRORS the open stream (one in-band error frame) instead
    of hanging the consumer to its timeout."""
    monkeypatch.setenv("TONY_TEST_SERVING_CRASH_AT_BLOCKS", "1")
    srv = _srv(params, replay=False)
    app = ServeApp(srv, max_loop_restarts=8, loop_backoff_s=0.02)
    app.start()
    try:
        ts = TokenStream()
        rid, ev = app.submit_async(_prompt(5, seed=47), 16, timeout=60,
                                   stream=ts)
        toks, reason, err = ts.drain_all(timeout=60)
        assert reason is None and err is not None, (
            "replay-off crash must error the stream")
        app.discard_result(rid)
    finally:
        app.shutdown()


# --------------------------------------------------------------------------
# per-request stop sequences + /v1 logprobs (ISSUE 15 satellites)
# --------------------------------------------------------------------------

def _earliest_stop_end(tokens, seq):
    """Reference scanner for the engine's stop contract: the earliest
    index (exclusive) where ``seq`` completes inside ``tokens``."""
    n = len(seq)
    for e in range(n, len(tokens) + 1):
        if tokens[e - n:e] == list(seq):
            return e
    return None


def test_per_request_stop_buffered_and_streamed(params):
    """A per-request stop SEQUENCE truncates the greedy stream at the
    earliest match end — same tokens on the buffered POST and across
    SSE frames, finish_reason "stop", and the server-wide default is
    untouched for a stop-less follow-up request."""
    srv, app, httpd, port = _http_app(params)
    try:
        prompt = [int(t) for t in _prompt(6, seed=53)]
        solo = _solo(params, np.asarray(prompt, np.int32), 16)
        seq = solo[4:6]
        end = _earliest_stop_end(solo, seq)
        assert end is not None
        expect = solo[:end]
        body = _json_post(port, "/generate",
                          {"prompt": prompt, "max_new_tokens": 16,
                           "stop": seq})          # flat list = ONE seq
        assert body["tokens"] == expect and \
            body["finish_reason"] == "stop"
        frames = [json.loads(f) for f in _sse_post(
            port, "/generate?stream=true",
            {"prompt": prompt, "max_new_tokens": 16,
             "stop": [seq]})]                     # list-of-lists form
        toks = [t for f in frames if "finish_reason" not in f
                for t in f["tokens"]]
        assert toks == expect
        assert frames[-1]["finish_reason"] == "stop"
        assert frames[-1]["n_tokens"] == len(expect)
        # the freed slot's next stop-less occupant is unaffected
        again = _json_post(port, "/generate",
                           {"prompt": prompt, "max_new_tokens": 16})
        assert again["tokens"] == solo and \
            again["finish_reason"] == "length"
        # malformed stop payloads are 400s, not engine faults
        for bad in ("x", [], [[]], [["a"]]):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps({"prompt": prompt,
                                 "stop": bad}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=30)
            assert ei.value.code == 400
    finally:
        httpd.shutdown()
        httpd.server_close()
        app.shutdown()


def test_per_request_stop_replay_safe_across_loop_crash(
        params, monkeypatch):
    """The journal carries the request's stop sequences: a mid-decode
    loop crash replays the request WITH them, and the replayed result
    is identical to an uncrashed server's (PR 11 discipline — the
    truncated stream is the durable one)."""
    prompt = [int(t) for t in _prompt(6, seed=59)]
    solo = _solo(params, np.asarray(prompt, np.int32), 16)
    seq = solo[5:7]
    end = _earliest_stop_end(solo, seq)
    expect = solo[:end]
    monkeypatch.setenv("TONY_TEST_SERVING_CRASH_AT_BLOCKS", "1")
    srv = _srv(params)
    app = ServeApp(srv, max_loop_restarts=8, loop_backoff_s=0.02)
    app.start()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(app))
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        body = _json_post(port, "/generate",
                          {"prompt": prompt, "max_new_tokens": 16,
                           "stop": seq})
        assert body["tokens"] == expect and \
            body["finish_reason"] == "stop"
        assert srv.chaos_faults_injected == 1 and srv.replays >= 1
    finally:
        httpd.shutdown()
        httpd.server_close()
        app.shutdown()


def test_v1_logprobs_choices_and_stop(params):
    """/v1 logprobs from the already-computed logits row: completions
    carry the classic tokens/token_logprobs/top_logprobs arrays, chat
    carries the content list; greedy means the chosen token IS the top
    alternative. ``stop`` rides the codec (text -> token ids) on both
    endpoints; logprobs+stream is a 400 by contract."""
    srv, app, httpd, port = _http_app(params)
    try:
        prompt = [int(t) for t in _prompt(6, seed=61)]
        solo = _solo(params, np.asarray(prompt, np.int32), 8)
        resp = _json_post(port, "/v1/completions",
                          {"prompt": prompt, "max_tokens": 8,
                           "logprobs": 3})
        ch = resp["choices"][0]
        assert ch["tokens"] == solo
        lp = ch["logprobs"]
        assert len(lp["tokens"]) == len(lp["token_logprobs"]) == \
            len(lp["top_logprobs"]) == 8
        for tok, tok_lp, top in zip(ch["tokens"], lp["token_logprobs"],
                                    lp["top_logprobs"]):
            assert tok_lp is not None and tok_lp <= 0.0
            assert len(top) <= 3
            # greedy: the emitted token is the argmax -> the best
            # alternative, at its own logprob
            assert top[str(tok)] == max(top.values())
            assert abs(top[str(tok)] - tok_lp) < 1e-4
        # logprobs-less requests carry an explicit null (pinned key)
        plain = _json_post(port, "/v1/completions",
                           {"prompt": prompt, "max_tokens": 4})
        assert plain["choices"][0]["logprobs"] is None
        # chat: boolean switch + top_logprobs count, content-list shape
        text = " ".join(str(t) for t in prompt)
        resp = _json_post(port, "/v1/chat/completions",
                          {"messages": [{"role": "user",
                                         "content": text}],
                           "max_tokens": 6, "logprobs": True,
                           "top_logprobs": 2})
        content = resp["choices"][0]["logprobs"]["content"]
        assert len(content) == 6
        for entry in content:
            assert set(entry) == {"token", "logprob", "top_logprobs"}
            assert len(entry["top_logprobs"]) <= 2
            assert entry["top_logprobs"][0]["token"] == entry["token"]
        # stop through the codec: a one-token text stop truncates
        stop_tok = solo[3]
        resp = _json_post(port, "/v1/completions",
                          {"prompt": prompt, "max_tokens": 8,
                           "stop": str(stop_tok)})
        e = _earliest_stop_end(solo, [stop_tok])
        assert resp["choices"][0]["tokens"] == solo[:e]
        assert resp["choices"][0]["finish_reason"] == "stop"
        # streamed logprobs are rejected with the OpenAI envelope
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/completions",
            data=json.dumps({"prompt": prompt, "max_tokens": 4,
                             "stream": True, "logprobs": 1}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 400
        err = json.loads(ei.value.read().decode())["error"]
        assert err["type"] == "invalid_request_error"
    finally:
        httpd.shutdown()
        httpd.server_close()
        app.shutdown()


# --------------------------------------------------------------------------
# SSE reconnect (Last-Event-ID) + engine-derived Retry-After
# --------------------------------------------------------------------------

def _sse_post_with_ids(port, path, payload, headers=None, timeout=120):
    """POST expecting SSE; returns (data_frames, id_lines)."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    frames, ids = [], []
    with urllib.request.urlopen(req, timeout=timeout) as r:
        assert r.headers["Content-Type"] == "text/event-stream"
        for raw in r:
            line = raw.decode().strip()
            if line.startswith("data: "):
                frames.append(json.loads(line[len("data: "):]))
            elif line.startswith("id: "):
                ids.append(line[len("id: "):])
    return frames, ids


def test_sse_last_event_id_reconnect_resumes(params):
    """docs/serving.md "SSE reconnect": a client that lost its stream
    re-POSTs with the last frame's ``id: <rid>:<n>`` — the server pops
    the parked prefix (what on_disconnect saved), teacher-forces it,
    withholds the first n already-acked tokens, and the concatenated
    re-delivery is byte-identical to the unbroken stream past the ack
    point. A malformed header is ignored (fresh run)."""
    srv, app, httpd, port = _http_app(params)
    try:
        prompt = _prompt(6, seed=33).tolist()
        ref = _json_post(port, "/generate",
                         {"prompt": prompt, "max_new_tokens": 8})
        full, rid = ref["tokens"], ref["id"]
        assert len(full) == 8
        # the disconnect path: the handler parked the delivered prefix
        app.save_resume_prefix(rid, full[:5])
        # client acked 3 of those 5 before the link died
        frames, ids = _sse_post_with_ids(
            port, "/generate?stream=true",
            {"prompt": prompt, "max_new_tokens": 8},
            headers={"Last-Event-ID": f"{rid}:3"})
        got = [t for f in frames if "tokens" in f for t in f["tokens"]]
        assert got == full[3:], "resumed delivery diverged from stream"
        closing = frames[-1]
        assert closing["finish_reason"] == "length"
        assert closing["n_tokens"] == len(full) - 3
        # every frame carries the reconnect cursor; the final id acks
        # the full absolute position (teacher-forced prefix included)
        assert ids and all(":" in i for i in ids)
        assert ids[-1].split(":")[1] == str(len(full))
        # the parked prefix is single-use: it was popped
        assert app.resume_prefix(rid) is None
        # malformed header -> fresh full run, not an error
        frames, _ = _sse_post_with_ids(
            port, "/generate?stream=true",
            {"prompt": prompt, "max_new_tokens": 8},
            headers={"Last-Event-ID": "not-a-cursor"})
        got = [t for f in frames if "tokens" in f for t in f["tokens"]]
        assert got == full
    finally:
        httpd.shutdown()
        httpd.server_close()
        app.shutdown()


def test_retry_after_folds_engine_estimate_and_autoscale_hint(params):
    """The 429 Retry-After contract (docs/serving.md "Paged KV &
    admission tiers"): the advertised value is the MAX of the engine's
    service-rate estimate and the autoscaler's pushed cooldown hint
    (POST /autoscale/hint), clamped to [1, 60] — and the hint decays
    on its own so a dead driver cannot pin it forever."""
    srv, app, httpd, port = _http_app(params, max_queue=1)
    try:
        assert app.retry_after_s(engine_estimate=7.4) == 8
        assert app.retry_after_s(engine_estimate=10_000) == 60
        app.set_autoscale_hint(23.0)
        assert app.retry_after_s(engine_estimate=2.0) == 23
        app.set_autoscale_hint(0.0)     # decay-to-zero shape
        assert app.retry_after_s(engine_estimate=2.0) == 2
        # over HTTP: push a hint, then saturate the 1-deep queue and
        # read the folded header off a real 429
        _json_post(port, "/autoscale/hint", {"cooldown_s": 17.0})
        hits: list[int] = []

        def occupy(s):
            try:
                _json_post(port, "/generate",
                           {"prompt": _prompt(6, seed=s).tolist(),
                            "max_new_tokens": 10})
            except urllib.error.HTTPError as e:
                if e.code == 429:
                    hits.append(int(e.headers["Retry-After"]))
                e.read()
        occupied = [threading.Thread(target=occupy, args=(50 + i,))
                    for i in range(6)]
        for t in occupied:
            t.start()
        for t in occupied:
            t.join(timeout=60)
        assert hits, "6 concurrent posts never saturated the 1-deep queue"
        # the pushed 17s hint dominates the TINY engine's 1-2s estimate
        # but decays in real time between the push and each 429 — allow
        # for a few seconds of warm-up/prefill before the sheds landed
        assert all(10 <= ra <= 60 for ra in hits), hits
        # a bad hint is a 400, never a silent reset
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/autoscale/hint",
            data=json.dumps({"cooldown_s": -3}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400
    finally:
        httpd.shutdown()
        httpd.server_close()
        app.shutdown()


# --------------------------------------------------------------------------
# disaggregated serving over HTTP (PR 17)
# --------------------------------------------------------------------------


def test_kv_import_http_two_legs_byte_identical(params):
    """The full HTTP transfer contract: POST /generate on a prefill-
    role replica answers finish_reason="prefilled" with the handoff
    payload riding the SAME response; POSTing that payload VERBATIM to
    a decode replica's /kv/import resumes the decode byte-identically
    to a solo paged engine — buffered AND ?stream=true — and a damaged
    payload is a LOUD 400, backpressure the usual 429 + Retry-After."""
    from tony_tpu.models.serving import KV_IMPORT_KEYS

    prompt = [int(t) for t in _prompt(7, seed=91)]
    solo = _solo(params, np.asarray(prompt, np.int32), 10)

    pre_srv, pre_app, pre_httpd, pre_port = _http_app(
        params, paged=True, role="prefill")
    dec_srv, dec_app, dec_httpd, dec_port = _http_app(
        params, paged=True, role="decode")
    try:
        # roles ride /stats — the router's discovery surface
        for port, role in ((pre_port, "prefill"), (dec_port, "decode")):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/stats", timeout=10) as r:
                assert json.loads(r.read().decode())["role"] == role

        def leg1():
            body = _json_post(pre_port, "/generate",
                              {"prompt": prompt, "max_new_tokens": 10})
            assert body["finish_reason"] == "prefilled"
            assert body["tokens"] == []
            assert set(body["handoff"]) == set(KV_IMPORT_KEYS)
            return body["handoff"]

        # buffered decode leg
        buf = _json_post(dec_port, "/kv/import", leg1())
        assert buf["tokens"] == solo
        assert buf["finish_reason"] == "length"
        # streamed decode leg: same tokens, incremental frames
        frames = [json.loads(f) for f in _sse_post(
            dec_port, "/kv/import?stream=true", leg1())]
        toks = [t for f in frames if "finish_reason" not in f
                for t in f["tokens"]]
        assert toks == solo
        assert frames[-1]["finish_reason"] == "length"
        # torn payload: loud 400, counted, never queued
        torn = leg1()
        torn["blocks_k"] = torn["blocks_k"][:-24]
        with pytest.raises(urllib.error.HTTPError) as ei:
            _json_post(dec_port, "/kv/import", torn)
        assert ei.value.code == 400
        # pool-occupancy gauges + transfer counters on both /metrics
        with urllib.request.urlopen(
                f"http://127.0.0.1:{dec_port}/metrics", timeout=10) as r:
            text = r.read().decode()
        for state in ("free", "slot", "trie", "shared"):
            assert f'serving_kv_pool_blocks{{state="{state}"}}' in text
        assert "serving_kv_imports_total 2" in text
        assert "serving_kv_import_rejects_total 1" in text
        with urllib.request.urlopen(
                f"http://127.0.0.1:{pre_port}/metrics", timeout=10) as r:
            assert "serving_kv_exports_total 3" in r.read().decode()
    finally:
        for httpd, app in ((pre_httpd, pre_app), (dec_httpd, dec_app)):
            httpd.shutdown()
            httpd.server_close()
            app.shutdown()
