"""Continuous-batching slot-pool server (models/serving.py).

The contract under test: a request served through the slot pool — admitted
into whatever slot frees up, decoded alongside unrelated requests, its
prompt chunk-prefilled at arbitrary offsets — emits EXACTLY the tokens a
solo generate() call emits. That exactness is what makes continuous
batching safe to deploy: batching policy must never change results.
Reference analogue: TonY keeps long-lived services alive and routes to
them (NotebookSubmitter.java:71-133, ProxyServer.java:27-39); the model
serving layer itself is this framework's TPU-native capability extension
(SURVEY.md §2.3).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import transformer
from tony_tpu.models.generate import generate, prepare_decode
from tony_tpu.models.serving import (
    Completion, PrefixCache, Request, SlotServer,
)

TINY = transformer.TransformerConfig(
    vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=128, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return transformer.init(jax.random.PRNGKey(0), TINY)


def _prompts(n, key=3, lo=2, hi=14):
    """n random prompts of varied lengths."""
    k = jax.random.PRNGKey(key)
    out = []
    for i in range(n):
        k, a, b = jax.random.split(k, 3)
        lp = int(jax.random.randint(a, (), lo, hi))
        out.append(np.asarray(
            jax.random.randint(b, (lp,), 0, TINY.vocab_size), np.int32))
    return out


def _solo(params, prompt, max_new, **kw):
    out = generate(params, TINY, jnp.asarray(prompt)[None], max_new, **kw)
    return [int(t) for t in np.asarray(out)[0]]


def test_slot_server_parity_with_solo_generate(params):
    """12 mixed-length requests through 3 slots (forcing admission into
    freed slots mid-flight) — every completion token-exact vs a solo
    generate() run of the same prompt."""
    prompts = _prompts(12)
    srv = SlotServer(params, TINY, slots=3, max_len=64, block_size=4,
                     prefill_chunk=8)
    reqs = [Request(prompt=p, max_new_tokens=6 + (i % 5))
            for i, p in enumerate(prompts)]
    for r in reqs:
        srv.submit(r)
    done = srv.run_until_drained()
    assert len(done) == len(reqs)
    for r, p in zip(reqs, prompts):
        comp = done[r.id]
        assert comp.finish_reason == "length"
        assert comp.tokens == _solo(params, p, r.max_new_tokens), (
            f"request {r.id} (prompt len {p.size}) diverged")


def test_slot_server_eos_frees_slot_and_matches_generate(params):
    """Stop tokens end a request mid-block; the emitted stream (stop token
    included) matches generate(stop_tokens=...), and the freed slot admits
    a queued request."""
    prompts = _prompts(6, key=11)
    # discover each prompt's greedy stream to pick a stop token that
    # actually fires for some requests
    solo = [_solo(params, p, 10) for p in prompts]
    stop = solo[0][3]
    srv = SlotServer(params, TINY, slots=2, max_len=64, block_size=4,
                     prefill_chunk=8, stop_tokens=(stop,), pad_id=255)
    reqs = [Request(prompt=p, max_new_tokens=10) for p in prompts]
    for r in reqs:
        srv.submit(r)
    done = srv.run_until_drained()
    assert len(done) == len(reqs)
    saw_stop = False
    for r, p in zip(reqs, prompts):
        ref = _solo(params, p, 10, stop_tokens=(stop,), pad_id=255)
        got = done[r.id].tokens
        # generate pads past the stop; the server emits only up to it
        if stop in ref:
            ref = ref[:ref.index(stop) + 1]
            assert done[r.id].finish_reason == "stop"
            saw_stop = True
        assert got == ref, f"request {r.id} diverged"
    assert saw_stop, "test needs at least one request hitting the stop"


def test_slot_server_int8_kv_and_weights(params):
    """kv_dtype/weight_dtype wire through the slot pool: quantized cache +
    scale buffers + int8 decode weights serve mixed bursts, with identical
    completions regardless of admission policy. vs solo generate() the
    int8 paths agree within QUANTIZATION TOLERANCE, not bit-exactly:
    serving chunk-prefills the prompt body through the quantized cache
    (and raw, unfused prefill weights) where generate's true prefill
    attends raw K/V (and the w8-fused weights) — a near-tie at int8
    resolution can flip a greedy token, and does under some jax versions.
    Exactness claims belong to the native-dtype paths (tested above);
    here we assert policy-invariance plus majority agreement with solo
    (a plumbing regression produces garbage everywhere, not one flipped
    near-tie)."""
    prompts = _prompts(4, key=7)
    outs = {}
    for batched in (True, False):
        srv = SlotServer(params, TINY, slots=2, max_len=64, block_size=4,
                         prefill_chunk=8, kv_dtype="int8",
                         weight_dtype="int8", batched_admission=batched)
        reqs = [Request(prompt=p, max_new_tokens=5) for p in prompts]
        for r in reqs:
            srv.submit(r)
        done = srv.run_until_drained()
        outs[batched] = [done[r.id].tokens for r in reqs]
    assert outs[True] == outs[False]
    refs = [_solo(params, p, 5, kv_dtype="int8", weight_dtype="int8")
            for p in prompts]
    for toks in outs[True]:
        assert len(toks) == 5
        assert all(0 <= t < TINY.vocab_size for t in toks)
    agree = sum(t == r for t, r in zip(outs[True], refs))
    assert agree * 2 >= len(refs), (outs[True], refs)


def test_slot_server_prepared_weights_and_incremental_api(params):
    """prepare_decode weights serve without per-call fusion; submit/step/
    drain_completed works incrementally (the live-service loop shape) with
    requests arriving WHILE others decode."""
    prompts = _prompts(5, key=23)
    prep = prepare_decode(params, TINY)
    srv = SlotServer(prep, TINY, slots=2, max_len=64, block_size=2,
                     prefill_chunk=8)
    reqs = [Request(prompt=p, max_new_tokens=6) for p in prompts]
    srv.submit(reqs[0])
    srv.submit(reqs[1])
    done: dict[int, Completion] = {}
    late = list(reqs[2:])
    for i in range(200):
        srv.step()
        done.update(srv.drain_completed())
        if late:                      # arrivals mid-decode
            srv.submit(late.pop(0))
        if len(done) == len(reqs) and not late:
            break
    assert len(done) == len(reqs)
    for r, p in zip(reqs, prompts):
        assert done[r.id].tokens == _solo(params, p, 6)


def test_slot_server_rejections(params):
    srv = SlotServer(params, TINY, slots=1, max_len=16)
    with pytest.raises(ValueError, match="max_len"):
        srv.submit(Request(prompt=list(range(10)), max_new_tokens=10))
    with pytest.raises(ValueError, match="empty"):
        srv.submit(Request(prompt=[], max_new_tokens=4))
    with pytest.raises(ValueError, match="max_new_tokens"):
        srv.submit(Request(prompt=[1], max_new_tokens=0))


def test_slot_server_single_token_prompt(params):
    """A 1-token prompt has no prefill body at all — the token is fed
    directly as the first decode input."""
    srv = SlotServer(params, TINY, slots=2, max_len=32, block_size=4)
    r = Request(prompt=[7], max_new_tokens=6)
    srv.submit(r)
    done = srv.run_until_drained()
    assert done[r.id].tokens == _solo(params, np.asarray([7], np.int32), 6)


def test_serve_http_end_to_end(params):
    """`tony-tpu serve`'s HTTP surface: concurrent POST /generate requests
    through the ServeApp loop return token-exact completions; /stats
    reports the pool. In-process (ThreadingHTTPServer on an ephemeral
    port) — the same app object the CLI main wires up."""
    import json
    import threading
    import urllib.request
    from http.server import ThreadingHTTPServer

    from tony_tpu.cli.serve import ServeApp, make_handler
    from tony_tpu.models.serving import SlotServer

    slot_server = SlotServer(params, TINY, slots=2, max_len=64,
                             block_size=4, prefill_chunk=8)
    app = ServeApp(slot_server)
    app.start()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(app))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        prompts = _prompts(4, key=31)
        results = {}

        def post(i, p):
            body = json.dumps({"prompt": [int(x) for x in p],
                               "max_new_tokens": 5}).encode()
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/generate", data=body, timeout=120
            ) as r:
                results[i] = json.loads(r.read())

        threads = [threading.Thread(target=post, args=(i, p))
                   for i, p in enumerate(prompts)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=120)
        assert len(results) == 4
        for i, p in enumerate(prompts):
            assert results[i]["tokens"] == _solo(params, p, 5)
            assert results[i]["finish_reason"] == "length"

        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=10
        ) as r:
            stats = json.loads(r.read())
        assert stats["slots"] == 2 and stats["active"] == 0

        # malformed request -> 400, service stays up
        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/generate",
                data=b'{"max_new_tokens": 5}', timeout=10)
        assert ei.value.code == 400
    finally:
        httpd.shutdown()
        httpd.server_close()
        app.shutdown()


def test_serve_loop_failure_fails_pending_and_healthz(params):
    """If the serving loop raises, waiters must get an immediate error
    (not hang to their timeouts), /healthz must flip to 503 with the
    cause, and new submissions must be rejected fast."""
    import json
    import threading
    import urllib.error
    import urllib.request
    from http.server import ThreadingHTTPServer

    from tony_tpu.cli.serve import ServeApp, ServingLoopError, make_handler

    class ExplodingServer:
        """SlotServer stand-in whose step() dies once a request is in."""
        slots, max_len, block_size = 1, 32, 4
        n_active, pending = 0, 0

        def __init__(self):
            self.idle = True

        def submit(self, req):
            self.idle = False
            return req.id

        def step(self):
            raise RuntimeError("XlaRuntimeError: device lost")

    app = ServeApp(ExplodingServer())
    app.start()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(app))
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            assert json.loads(r.read())["healthy"] is True
        # the request must FAIL (503), well before the 600s default timeout
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/generate",
                data=b'{"prompt": [1], "max_new_tokens": 4}', timeout=30)
        assert ei.value.code == 503
        assert "device lost" in json.loads(ei.value.read())["error"]
        # unhealthy is observable and sticky
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10)
        assert ei.value.code == 503
        assert "device lost" in json.loads(ei.value.read())["error"]
        # new submissions are rejected immediately, not queued into a
        # dead loop
        with pytest.raises(ServingLoopError):
            app.generate([1], 4, timeout=5)
    finally:
        httpd.shutdown()
        httpd.server_close()
        app.shutdown()


def test_slot_server_prefill_tail_past_ring_capacity(params):
    """The final prefill chunk's padded tail can span past the ring
    capacity (prefill_chunk not dividing max_len): those writes must be
    DROPPED, not wrapped onto the slot's own earliest prompt K/V — a wrap
    silently corrupts positions the attention mask legitimately reads."""
    import numpy as np

    prompt = np.asarray(
        jax.random.randint(jax.random.PRNGKey(41), (36,), 0,
                           TINY.vocab_size), np.int32)
    # body=35 -> chunks at 0,16,32; last chunk spans logical 32..47 > 40
    srv = SlotServer(params, TINY, slots=2, max_len=40, block_size=4,
                     prefill_chunk=16)
    r = Request(prompt=prompt, max_new_tokens=4)
    srv.submit(r)
    done = srv.run_until_drained()
    assert done[r.id].tokens == _solo(params, prompt, 4)


def test_slot_server_batched_admission_matches_per_slot(params):
    """Batched multi-slot admission (one _prefill_batch dispatch per chunk
    ROUND) must emit exactly the per-slot path's tokens — admission policy
    can never change results — while dispatching strictly fewer prefill
    programs on a burst (that serial sum-of-chunks dispatch train is the
    admission stall it exists to remove)."""
    prompts = _prompts(9, key=61, lo=2, hi=22)   # multi-chunk at chunk=8
    # a 1-token prompt in a burst: its batched row is finalize-only
    # (n_valid=0, every KV write dropped) — the degenerate case must ride
    # along exactly
    prompts[4] = prompts[4][:1]
    outs, counts = {}, {}
    for batched in (True, False):
        srv = SlotServer(params, TINY, slots=3, max_len=64, block_size=4,
                         prefill_chunk=8, batched_admission=batched)
        reqs = [Request(prompt=p, max_new_tokens=5 + (i % 3))
                for i, p in enumerate(prompts)]
        for r in reqs:
            srv.submit(r)
        done = srv.run_until_drained()
        outs[batched] = [done[r.id].tokens for r in reqs]
        counts[batched] = srv.admission_dispatches
    assert outs[True] == outs[False]
    assert counts[True] < counts[False], counts
    # and the batched path stays exact vs solo generate
    for toks, p, r in zip(outs[True], prompts,
                          [5 + (i % 3) for i in range(len(prompts))]):
        assert toks == _solo(params, p, r)


@pytest.mark.slow
def test_slot_server_batched_admission_with_eos(params):
    """Mid-flight re-admission bursts (slots freed by EOS at different
    times) go through the batched program too; completions still match
    generate(stop_tokens=...)."""
    prompts = _prompts(8, key=67)
    solo = [_solo(params, p, 8) for p in prompts]
    stop = solo[0][2]
    srv = SlotServer(params, TINY, slots=3, max_len=64, block_size=4,
                     prefill_chunk=8, stop_tokens=(stop,), pad_id=255)
    reqs = [Request(prompt=p, max_new_tokens=8) for p in prompts]
    for r in reqs:
        srv.submit(r)
    done = srv.run_until_drained()
    assert len(done) == len(reqs)
    for r, p in zip(reqs, prompts):
        ref = _solo(params, p, 8, stop_tokens=(stop,), pad_id=255)
        if stop in ref:
            ref = ref[:ref.index(stop) + 1]
        assert done[r.id].tokens == ref, f"request {r.id} diverged"


def _tp_mesh(data=2, tensor=2):
    from tony_tpu.parallel import MeshSpec, build_mesh

    return build_mesh(MeshSpec(data=data, fsdp=1, tensor=tensor),
                      devices=jax.devices()[:data * tensor])


def test_slot_server_tp_mesh_parity(params):
    """THE tensor-parallel serving contract: a mesh-sharded SlotServer
    (KV pool over ("batch", "kv"), per-slot state over the batch axes, 4
    forced host-platform devices) produces greedy completions
    token-identical to the single-device SlotServer AND to solo
    generate() — sharding, like batching, must never change results."""
    mesh = _tp_mesh()
    prompts = _prompts(10, key=71)
    budgets = [5 + (i % 4) for i in range(len(prompts))]

    def run(server_params, **kw):
        srv = SlotServer(server_params, TINY, slots=4, max_len=64,
                         block_size=4, prefill_chunk=8, **kw)
        reqs = [Request(prompt=p, max_new_tokens=b)
                for p, b in zip(prompts, budgets)]
        for r in reqs:
            srv.submit(r)
        done = srv.run_until_drained()
        return [done[r.id].tokens for r in reqs]

    single = run(params)
    prep = prepare_decode(params, TINY, mesh=mesh)
    assert prep.fused is None           # fusion is single-device-only
    sharded = run(prep)
    assert sharded == single
    # raw params + mesh kwarg prepares internally; same tokens
    assert run(params, mesh=mesh) == single
    # and the per-request solo-generate contract carries over the mesh
    for toks, p, b in zip(sharded, prompts, budgets):
        assert toks == _solo(params, p, b)


@pytest.mark.slow
def test_slot_server_tp_mesh_eos_and_per_slot(params):
    """EOS mode and the serial per-slot admission path both compose with
    the mesh (the sync/burst bookkeeping is sharding-agnostic)."""
    mesh = _tp_mesh()
    prompts = _prompts(6, key=73)
    stop = _solo(params, prompts[0], 8)[2]
    prep = prepare_decode(params, TINY, mesh=mesh)
    srv = SlotServer(prep, TINY, slots=2, max_len=64, block_size=4,
                     prefill_chunk=8, stop_tokens=(stop,), pad_id=255,
                     batched_admission=False)
    reqs = [Request(prompt=p, max_new_tokens=8) for p in prompts]
    for r in reqs:
        srv.submit(r)
    done = srv.run_until_drained()
    for r, p in zip(reqs, prompts):
        ref = _solo(params, p, 8, stop_tokens=(stop,), pad_id=255)
        if stop in ref:
            ref = ref[:ref.index(stop) + 1]
        assert done[r.id].tokens == ref


def test_slot_server_mesh_rejections(params):
    """slots not divisible by the batch axes, and a mesh passed alongside
    meshless prepared weights, fail loudly instead of mis-sharding."""
    mesh = _tp_mesh()
    prep = prepare_decode(params, TINY, mesh=mesh)
    with pytest.raises(ValueError, match="slots=3"):
        SlotServer(prep, TINY, slots=3, max_len=64)
    with pytest.raises(ValueError, match="without a mesh"):
        SlotServer(prepare_decode(params, TINY), TINY, slots=4,
                   max_len=64, mesh=mesh)


# --------------------------------------------------------------------------
# chunk-aligned prefix KV cache
# --------------------------------------------------------------------------

_TEMPLATE = np.asarray(
    jax.random.randint(jax.random.PRNGKey(97), (16,), 0, TINY.vocab_size),
    np.int32)                    # 2 full chunks at prefill_chunk=8


def _templated(n, lo=2, hi=9, key=101):
    """n prompts sharing the 16-token template + short unique suffixes."""
    return [np.concatenate([_TEMPLATE, s]) for s in _prompts(n, key, lo, hi)]


def _serve_all(params, prompts, budgets, **kw):
    srv = SlotServer(params, TINY, slots=2, max_len=64, block_size=4,
                     prefill_chunk=8, **kw)
    reqs = [Request(prompt=p, max_new_tokens=b)
            for p, b in zip(prompts, budgets)]
    for r in reqs:
        srv.submit(r)
    done = srv.run_until_drained()
    return [done[r.id].tokens for r in reqs], srv


def test_prefix_cache_hit_path_token_identical(params):
    """THE prefix-cache contract: completions with the cache enabled are
    token-identical to the cold path AND to solo generate() — reuse is
    pure data movement, never a numerics change. Includes the degenerate
    full-hit prompt (body == cached prefix: no suffix to prefill at
    all)."""
    prompts = _templated(6)
    # body exactly the 2 template chunks -> full hit, finalize-only chunk
    prompts.append(np.concatenate([_TEMPLATE, _TEMPLATE[:1]]))
    budgets = [5 + (i % 3) for i in range(len(prompts))]
    cold, _ = _serve_all(params, prompts, budgets)
    warm, srv = _serve_all(params, prompts, budgets, prefix_cache_blocks=8)
    assert warm == cold
    for toks, p, b in zip(warm, prompts, budgets):
        assert toks == _solo(params, p, b), "hit path diverged from solo"
    st = srv.stats()["prefix_cache"]
    # slots=2 -> the first burst of 2 misses and populates; the rest hit
    assert st["hits"] >= 4 and st["misses"] >= 1
    assert srv.prefill_tokens_reused >= 4 * _TEMPLATE.size
    assert st["copy_dispatches"] >= 1 and st["insert_dispatches"] >= 1
    assert srv.admission_dispatches < (
        _serve_all(params, prompts, budgets)[1].admission_dispatches)


def test_prefix_cache_int8_kv_hit_identical(params):
    """int8 kv: the pool stores the QUANTIZED blocks + scales, so hit and
    cold paths read the same bytes — completions exactly identical (a
    stronger claim than the int8 serving-vs-solo tolerance, which is
    about chunked prefill vs true prefill, not about reuse)."""
    prompts = _templated(5, key=103)
    budgets = [5] * len(prompts)
    cold, _ = _serve_all(params, prompts, budgets, kv_dtype="int8")
    warm, srv = _serve_all(params, prompts, budgets, kv_dtype="int8",
                           prefix_cache_blocks=8)
    assert warm == cold
    assert srv.prefill_tokens_reused > 0


@pytest.mark.slow
def test_prefix_cache_tp_mesh_hit_identical(params):
    """The prefix pool composes with tensor-parallel serving: pool blocks
    shard over ("batch", "kv") like the slot cache, and the hit path
    stays token-identical to the cold path and to the single-device
    server on 4 forced host devices."""
    mesh = _tp_mesh()
    prompts = _templated(6, key=107)
    budgets = [5 + (i % 3) for i in range(len(prompts))]

    def run(server_params, **kw):
        srv = SlotServer(server_params, TINY, slots=4, max_len=64,
                         block_size=4, prefill_chunk=8, **kw)
        reqs = [Request(prompt=p, max_new_tokens=b)
                for p, b in zip(prompts, budgets)]
        for r in reqs:
            srv.submit(r)
        done = srv.run_until_drained()
        return [done[r.id].tokens for r in reqs], srv

    prep = prepare_decode(params, TINY, mesh=mesh)
    cold_tp, _ = run(prep)
    warm_tp, srv = run(prep, prefix_cache_blocks=8)
    warm_single, _ = run(params, prefix_cache_blocks=8)
    assert warm_tp == cold_tp
    assert warm_tp == warm_single
    assert srv.prefill_tokens_reused > 0


def test_prefix_cache_ring_wrap_reuse(params):
    """A copied prefix that spans the max_len ring boundary must land at
    the wrapped indices exactly as prefill's own writes would. Filler
    requests (cache_prompt=False, so they leave the trie alone) advance
    the global cursor until the next admission's ring offset forces the
    template copy to wrap, then the templated request must still match
    solo generate()."""
    max_len = 48
    template = np.asarray(
        jax.random.randint(jax.random.PRNGKey(113), (32,), 0,
                           TINY.vocab_size), np.int32)    # 4 chunks
    sfx = _prompts(2, key=127, lo=2, hi=4)
    srv = SlotServer(params, TINY, slots=2, max_len=max_len, block_size=4,
                     prefill_chunk=8, prefix_cache_blocks=8)

    def run_one(prompt, **kw):
        r = Request(prompt=prompt, max_new_tokens=4, **kw)
        srv.submit(r)
        return srv.run_until_drained()[r.id].tokens

    first = np.concatenate([template, sfx[0]])
    assert run_one(first) == _solo(params, first, 4)      # populates trie
    wrapped = False
    second = np.concatenate([template, sfx[1]])
    body = second.size - 1
    for _ in range(40):          # advance the cursor into the wrap zone
        offset = (srv._cursor - body) % max_len
        if offset + template.size > max_len:    # prefix copy will wrap
            wrapped = True
            break
        filler = np.asarray(
            jax.random.randint(jax.random.PRNGKey(srv._cursor + 1), (5,),
                               0, TINY.vocab_size), np.int32)
        run_one(filler, cache_prompt=False)
    assert wrapped, "test never reached a wrapping offset"
    reused_before = srv.prefill_tokens_reused
    assert run_one(second) == _solo(params, second, 4), (
        "wrapped prefix copy corrupted the ring")
    assert srv.prefill_tokens_reused == reused_before + template.size


def test_prefix_cache_refcount_and_eviction_unit():
    """The host trie/allocator contract, no model needed: the budget is
    respected, eviction is LRU over unreferenced LEAVES only, evicting a
    referenced (or interior) node is impossible, and insertion degrades
    to a shorter cached prefix when nothing is evictable."""
    pc = PrefixCache(2, 4)
    a = np.arange(8, dtype=np.int32)            # 2 chunks
    created = pc.insert(a)
    assert [ci for ci, _ in created] == [0, 1] and pc.blocks_used == 2
    pc.release([n for _, n in created])         # drop the insert-refs

    path = pc.lookup(a)
    assert [n.block for n in path] == [n.block for _, n in created]
    pc.acquire(path)
    # both blocks are on a referenced path: nothing evictable
    assert pc.alloc() is None
    b = np.arange(100, 108, dtype=np.int32)
    assert pc.insert(b) == []                   # degrades, doesn't fail
    pc.release(path)

    # unreferenced now: eviction peels the LEAF (deepest chunk) first
    blk = pc.alloc()
    assert blk == path[1].block and pc.evictions == 1
    assert pc.lookup(a) == path[:1]             # 1-chunk prefix still hits
    # the surviving root child became a leaf -> evictable next
    assert pc.alloc() == path[0].block and pc.evictions == 2
    assert pc.lookup(a) == []

    # LRU: two sibling templates, refresh the older one, evict -> the
    # stale one goes
    pc2 = PrefixCache(2, 4)
    na = pc2.insert(np.arange(4, dtype=np.int32))
    nb = pc2.insert(np.arange(50, 54, dtype=np.int32))
    pc2.release([n for _, n in na] + [n for _, n in nb])
    pc2.lookup(np.arange(4, dtype=np.int32))    # touch A -> B is LRU
    assert pc2.alloc() == nb[0][1].block


def test_prefix_cache_eviction_stress_server(params):
    """A 2-block pool cycling through 3 distinct 2-chunk templates: every
    admission evicts, the budget holds, and every completion stays exact
    vs solo generate()."""
    keys = (131, 137, 139)
    templates = [np.asarray(
        jax.random.randint(jax.random.PRNGKey(k), (16,), 0,
                           TINY.vocab_size), np.int32) for k in keys]
    srv = SlotServer(params, TINY, slots=2, max_len=64, block_size=4,
                     prefill_chunk=8, prefix_cache_blocks=2)
    for rnd in range(3):
        for t in templates:
            prompt = np.concatenate([t, t[:3]])
            r = Request(prompt=prompt, max_new_tokens=4)
            srv.submit(r)
            got = srv.run_until_drained()[r.id].tokens
            assert got == _solo(params, prompt, 4)
            pc = srv._prefix_cache
            assert pc.blocks_used <= pc.n_blocks == 2
    assert srv.stats()["prefix_cache"]["evictions"] > 0


def test_serve_app_stats_exposes_serving_counters(params):
    """ServeApp.stats (the /stats payload) carries the SlotServer's
    prefix-cache/prefill counters plus the MetricsAccumulator snapshot of
    the serving-load gauges."""
    from tony_tpu.cli.serve import ServeApp

    slot_server = SlotServer(params, TINY, slots=2, max_len=64,
                             block_size=4, prefill_chunk=8,
                             prefix_cache_blocks=4)
    app = ServeApp(slot_server)
    app.start()
    try:
        prompt = [int(t) for t in _TEMPLATE] + [3]
        app.generate(prompt, 4, timeout=120)
        app.generate(prompt[:-1] + [7], 4, timeout=120)
        st = app.stats()
    finally:
        app.shutdown()
    assert st["prefix_cache"]["hits"] >= 1
    assert st["prefill_tokens_reused"] >= _TEMPLATE.size
    assert st["admission_dispatches"] >= 1
    assert st["active"] == 0 and st["slots"] == 2
    names = {m["name"] for m in st["metrics"]}
    assert {"max_serving_active_slots", "avg_serving_queue_depth"} <= names


def test_slot_server_per_request_top_k(params):
    """Per-request top_k shares the pool like per-request temperature: a
    top_k=1 request at a hot temperature is argmax by construction, so it
    must reproduce solo greedy generate() even while its neighbors sample
    from the server-global (unfiltered) distribution."""
    prompts = _prompts(6, key=149)
    srv = SlotServer(params, TINY, slots=3, max_len=64, block_size=4,
                     prefill_chunk=8, temperature=0.8, top_k=0, seed=11)
    reqs = [Request(prompt=p, max_new_tokens=6,
                    temperature=4.0 if i % 2 == 0 else None,
                    top_k=1 if i % 2 == 0 else None)
            for i, p in enumerate(prompts)]
    for r in reqs:
        srv.submit(r)
    done = srv.run_until_drained()
    assert len(done) == len(reqs)
    for i, (r, p) in enumerate(zip(reqs, prompts)):
        toks = done[r.id].tokens
        assert len(toks) == 6
        assert all(0 <= t < TINY.vocab_size for t in toks)
        if i % 2 == 0:      # top_k=1 == greedy, neighbors sampling freely
            assert toks == _solo(params, p, 6), f"top_k=1 request {i} diverged"


def test_slot_server_per_request_temperature(params):
    """Greedy and sampled requests share one pool: per-row temperatures
    mean a temperature-0 request stays token-exact vs solo greedy
    generate() even while its neighbors sample."""
    prompts = _prompts(6, key=53)
    srv = SlotServer(params, TINY, slots=3, max_len=64, block_size=4,
                     prefill_chunk=8, temperature=0.9, seed=3)
    reqs = [Request(prompt=p, max_new_tokens=6,
                    temperature=0.0 if i % 2 == 0 else None)
            for i, p in enumerate(prompts)]
    for r in reqs:
        srv.submit(r)
    done = srv.run_until_drained()
    assert len(done) == len(reqs)
    for i, (r, p) in enumerate(zip(reqs, prompts)):
        toks = done[r.id].tokens
        assert len(toks) == 6
        assert all(0 <= t < TINY.vocab_size for t in toks)
        if i % 2 == 0:   # greedy rows: exact despite sampled neighbors
            assert toks == _solo(params, p, 6), f"greedy request {i} diverged"
