"""Tier-1 wall-budget guard.

The tier-1 gate (ROADMAP "Tier-1 verify") runs every non-slow test under
one 870s timeout, and the budget is VERY thin: historically a single
test creeping to ~28s (the hf-import parity cluster) ate the headroom
silently until the whole gate flirted with the cap. This lint fails the
SPECIFIC offender by name instead: conftest.py records every test's
call-phase duration and reorders this test to run last, so any non-slow
test that exceeded the per-test ceiling in THIS session fails the run
with its measured time.

Ceiling: ``TONY_TIER1_TEST_BUDGET_S`` (seconds, default 45). Raise it
per-run for slow hosts; a test that legitimately needs more than the
ceiling belongs in ``@pytest.mark.slow`` (run with ``-m slow``), not in
tier-1.
"""

import os

import conftest


def test_tier1_wall_budget():
    try:
        budget_s = float(os.environ.get("TONY_TIER1_TEST_BUDGET_S", "45"))
    except ValueError:
        budget_s = 45.0
    if budget_s <= 0:       # 0/negative disables (debug runs)
        return
    offenders = {
        nodeid: round(duration, 1)
        for nodeid, duration in conftest.TEST_DURATIONS.items()
        if duration > budget_s
        and nodeid not in conftest.SLOW_NODEIDS
        and "test_tier1_wall_budget" not in nodeid
    }
    assert not offenders, (
        f"non-slow tests exceeded the {budget_s:.0f}s per-test budget "
        f"(mark them @pytest.mark.slow or shrink them; override with "
        f"TONY_TIER1_TEST_BUDGET_S): {offenders}")
