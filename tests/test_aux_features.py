"""Tests for auxiliary capabilities: resource localization, workflow shim,
in-driver preprocess mode, TB sidecar URL, metrics accumulator."""

import json
import os
import sys
import zipfile
from pathlib import Path

import pytest

from tony_tpu.api import JobStatus
from tony_tpu.client import TonyClient
from tony_tpu.conf import TonyConf
from tony_tpu.integrations import WorkflowJob, props_to_conf
from tony_tpu.integrations.workflow import load_properties
from tony_tpu.metrics import MetricsAccumulator
from tony_tpu.utils import localization as loc

PY = sys.executable


def base_conf(dirs, **extra):
    return TonyConf({
        "tony.staging.dir": dirs["staging"],
        "tony.history.intermediate": dirs["history"] + "/intermediate",
        "tony.am.monitor-interval-ms": 100,
        **extra,
    })


# ------------------------------------------------------------- localization

def test_resource_spec_parsing():
    s = loc.ResourceSpec.parse("/a/b/data.txt#mydata")
    assert s.path == "/a/b/data.txt" and s.alias == "mydata" and not s.archive
    s2 = loc.ResourceSpec.parse("/a/venv.zip::archive")
    assert s2.archive and s2.alias == "venv.zip"
    s3 = loc.ResourceSpec.parse("/a/plain.bin")
    assert s3.alias == "plain.bin"


def test_stage_and_localize_roundtrip(tmp_path):
    src = tmp_path / "data.txt"
    src.write_text("payload")
    zpath = tmp_path / "bundle.zip"
    with zipfile.ZipFile(zpath, "w") as zf:
        zf.writestr("inner/file.txt", "zipped")

    specs = loc.parse_resources([f"{src}#renamed.txt", f"{zpath}#bundle::archive"])
    staged = loc.stage_resources(specs, tmp_path / "staging")
    work = tmp_path / "work"
    loc.localize_resources(staged, work)
    assert (work / "renamed.txt").read_text() == "payload"
    assert (work / "bundle" / "inner" / "file.txt").read_text() == "zipped"


def test_e2e_resource_localization(tmp_job_dirs, tmp_path):
    data = tmp_path / "asset.txt"
    data.write_text("hello-resource")
    conf = base_conf(
        tmp_job_dirs,
        **{"tony.worker.instances": 1,
           "tony.worker.resources": f"{data}#input.txt",
           # cwd of the user process is the task work dir with the resource
           "tony.worker.command": "bash -c 'grep -q hello-resource input.txt'"},
    )
    client = TonyClient(conf, poll_interval_s=0.1)
    client.submit()
    assert client.monitor() == JobStatus.SUCCEEDED


# ----------------------------------------------------------------- workflow

def test_props_to_conf_and_tags():
    conf = props_to_conf(
        {"tony.worker.instances": "3", "unrelated.key": "x",
         "tony.application.name": "wf-job"},
        tags={"flow": "f1", "project": "p1"},
    )
    assert conf["tony.worker.instances"] == 3
    assert "unrelated.key" not in conf
    assert "flow=f1" in conf["tony.application.tags"]


def test_properties_file_roundtrip(tmp_path):
    p = tmp_path / "job.properties"
    p.write_text("# comment\ntony.worker.instances=2\ntony.x.y: value with spaces\n")
    props = load_properties(p)
    assert props["tony.worker.instances"] == "2"
    assert props["tony.x.y"] == "value with spaces"


def test_workflow_job_runs(tmp_job_dirs, fixture_script):
    job = WorkflowJob({
        "tony.staging.dir": tmp_job_dirs["staging"],
        "tony.history.intermediate": tmp_job_dirs["history"] + "/intermediate",
        "tony.worker.instances": "1",
        "tony.worker.command": f"{PY} {fixture_script('exit_0.py')}",
        "tony.am.monitor-interval-ms": "100",
    }, tags={"flow": "test-flow"})
    assert job.run() == 0


# ------------------------------------------------------ preprocess + sidecar

def test_preprocess_runs_in_driver(tmp_job_dirs, tmp_path):
    """enable-preprocess + single task -> no container, driver forks the
    command itself (reference doPreprocessingJob:784-836)."""
    marker = tmp_path / "ran_in_driver"
    conf = base_conf(
        tmp_job_dirs,
        **{"tony.application.enable-preprocess": True,
           "tony.worker.instances": 1,
           "tony.worker.command": f"bash -c 'echo $PPID > {marker}'"},
    )
    client = TonyClient(conf, poll_interval_s=0.1)
    client.submit()
    assert client.monitor() == JobStatus.SUCCEEDED
    assert marker.exists()
    # no executor containers were launched
    assert not (Path(client.job_dir) / "logs" / "worker_0.stderr").exists()


def test_tensorboard_sidecar_registers_url(tmp_job_dirs):
    conf = base_conf(
        tmp_job_dirs,
        **{"tony.worker.instances": 1,
           "tony.worker.command": "bash -c 'sleep 1'",
           "tony.tensorboard.instances": 1,
           "tony.tensorboard.command": "bash -c 'test -n \"$TB_PORT\" && sleep 1'",
           "tony.application.untracked.jobtypes": "tensorboard"},
    )
    client = TonyClient(conf, poll_interval_s=0.1)
    client.submit()
    status = client.monitor()
    assert status == JobStatus.SUCCEEDED
    assert client.final_state.get("tensorboard_url", "").startswith("http://")


# -------------------------------------------------------------------- metrics

def test_tpu_metric_parsing():
    """The libtpu-SDK metric reducer — analogue of the reference's
    TestGpuDeviceInformationParser fixture tests."""
    from tony_tpu.metrics import (
        TPU_DUTY_CYCLE, TPU_HBM_USED, parse_tpu_metric_values,
    )

    assert parse_tpu_metric_values(
        "duty_cycle_pct", ["0.00", "20.00", "40.00", "0.00"]
    ) == {TPU_DUTY_CYCLE: 15.0}
    assert parse_tpu_metric_values(
        "hbm_capacity_usage", ["1073741824", "0"]
    ) == {TPU_HBM_USED: 1073741824 / 1e6}
    # empty list = runtime not serving metrics on this host -> sample nothing
    assert parse_tpu_metric_values("duty_cycle_pct", []) == {}
    with pytest.raises(ValueError):
        parse_tpu_metric_values("unknown_metric", ["1"])
    with pytest.raises(ValueError):
        parse_tpu_metric_values("duty_cycle_pct", ["not-a-number"])


def test_sample_tpu_metrics_with_mocked_sdk(monkeypatch):
    """End-to-end sampler against a mocked libtpu.sdk module tree."""
    import sys
    import types

    from tony_tpu import metrics as M

    class FakeMetric:
        def __init__(self, data):
            self._d = data

        def data(self):
            return self._d

    data = {
        "duty_cycle_pct": ["50.00", "100.00"],
        "hbm_capacity_usage": ["2000000", "3000000"],
    }
    tpumonitoring = types.SimpleNamespace(
        get_metric=lambda name: FakeMetric(data[name]),
        list_supported_metrics=lambda: list(data),
    )
    sdk = types.ModuleType("libtpu.sdk")
    sdk.tpumonitoring = tpumonitoring
    libtpu = types.ModuleType("libtpu")
    libtpu.sdk = sdk
    monkeypatch.setitem(sys.modules, "libtpu", libtpu)
    monkeypatch.setitem(sys.modules, "libtpu.sdk", sdk)

    out = M.sample_tpu_metrics()
    assert out == {M.TPU_DUTY_CYCLE: 75.0, M.TPU_HBM_USED: 5.0}

    # a runtime error on one metric must not lose the other
    def flaky(name):
        if name == "duty_cycle_pct":
            raise RuntimeError("runtime not initialized")
        return FakeMetric(data[name])

    tpumonitoring.get_metric = flaky
    assert M.sample_tpu_metrics() == {M.TPU_HBM_USED: 5.0}


def test_sample_tpu_metrics_jax_memory_stats_fallback(monkeypatch):
    """When tpumonitoring serves no per-chip HBM data (the axon-tunneled
    chip does exactly that), an ALREADY-imported jax client's
    memory_stats() fills in live occupancy. The fallback must never import
    jax itself — from the executor's monitor that would initialize a second
    TPU client contending with the child for the chip."""
    import sys
    import types

    from tony_tpu import metrics as M

    class FakeDev:
        def __init__(self, bytes_in_use, platform="tpu"):
            self._b = bytes_in_use
            self.platform = platform

        def memory_stats(self):
            if self._b is None:
                return None          # the axon tunnel reports no stats
            return {"bytes_in_use": self._b,
                    "peak_bytes_in_use": self._b * 2}

    fake_jax = types.ModuleType("jax")
    fake_jax.local_devices = lambda: [FakeDev(4_000_000), FakeDev(8_000_000)]
    # a live backend must be POSITIVELY visible in the bridge registry or
    # the fallback stays out (fail-safe against jax version bumps)
    fake_jax._src = types.SimpleNamespace(
        xla_bridge=types.SimpleNamespace(_backends={"tpu": object()}))
    monkeypatch.setitem(sys.modules, "jax", fake_jax)
    monkeypatch.delitem(sys.modules, "libtpu", raising=False)
    monkeypatch.delitem(sys.modules, "libtpu.sdk", raising=False)

    out, reason = M.sample_tpu_metrics(explain=True)
    # SUM over chips, like the sdk — plus the peak-bytes watermark gauge
    # (capacity planning's number) where the runtime serves it
    assert out == {M.TPU_HBM_USED: 12.0, M.TPU_HBM_PEAK: 24.0}
    assert reason is None                     # non-empty sample: no excuse

    # a runtime that serves occupancy but no watermark: the peak series
    # is OMITTED, never rendered as zero
    class NoPeakDev(FakeDev):
        def memory_stats(self):
            return {"bytes_in_use": self._b}

    fake_jax.local_devices = lambda: [NoPeakDev(4_000_000)]
    out, _ = M.sample_tpu_metrics(explain=True)
    assert out == {M.TPU_HBM_USED: 4.0}

    # non-TPU devices must never masquerade as TPU memory
    fake_jax.local_devices = lambda: [FakeDev(4_000_000, platform="gpu"),
                                      FakeDev(4_000_000, platform="cpu")]
    out, _ = M.sample_tpu_metrics(explain=True)
    assert out == {}

    # TPU devices without stats (the tunnel) -> live-buffer floor
    fake_jax.local_devices = lambda: [FakeDev(None)]
    fake_jax.live_arrays = lambda: [types.SimpleNamespace(nbytes=2_000_000)]
    out, reason = M.sample_tpu_metrics(explain=True)
    assert out == {M.TPU_HBM_LIVE: 2.0}
    assert reason is None

    # no stats AND no live arrays -> empty, with the primary-channel reason
    fake_jax.live_arrays = lambda: []
    out, reason = M.sample_tpu_metrics(explain=True)
    assert out == {}
    # primary-channel diagnosis survives: either libtpu is absent or its
    # runtime served no data (this image ships libtpu without local chips)
    assert ("tpumonitoring not importable" in reason
            or "no per-chip data" in reason)

    # bridge registry missing (jax version bump moved the private module/
    # attribute): FAIL SAFE — report nothing rather than call
    # local_devices(), which would initialize a second TPU client inside
    # the executor's monitor
    del fake_jax._src
    fake_jax.local_devices = lambda: (_ for _ in ()).throw(
        AssertionError("fail-safe must not touch local_devices"))
    assert M._jax_memory_stats() == {}

    # jax absent from sys.modules -> the fallback must not try to import it
    monkeypatch.delitem(sys.modules, "jax", raising=False)
    real_import = __builtins__["__import__"] if isinstance(__builtins__, dict) \
        else __builtins__.__import__

    def guard(name, *a, **k):
        assert name != "jax", "fallback must not import jax"
        return real_import(name, *a, **k)

    monkeypatch.setattr("builtins.__import__", guard)
    assert M._jax_memory_stats() == {}


def test_horovod_real_rendezvous_inits_host_plan(monkeypatch):
    """With horovod importable, the rendezvous server must be started AND
    initialised with the host-assignment plan (reference
    horovod_driver.py:32-42 static_driver_fn) — a started-but-uninitialised
    server can never rendezvous workers. Horovod isn't installed here, so
    mock its module tree and assert the plan reaches server.init()."""
    import sys
    import types

    from tony_tpu.runtimes.horovod import (
        HorovodTaskAdapter, compute_slot_assignments,
    )

    calls = {}

    def parse_hosts(host_str):
        calls["parse"] = host_str
        return ["parsed:" + host_str]

    def get_host_assignments(hosts, min_np):
        calls["assign_args"] = (hosts, min_np)
        return ["plan-entry-0", "plan-entry-1"]

    class FakeRendezvousServer:
        def start(self):
            calls["started"] = True
            return 43210

        def init(self, plan):
            calls["init_plan"] = plan

    mods = {
        "horovod": types.ModuleType("horovod"),
        "horovod.runner": types.ModuleType("horovod.runner"),
        "horovod.runner.common": types.ModuleType("horovod.runner.common"),
        "horovod.runner.common.util": types.ModuleType("horovod.runner.common.util"),
        "horovod.runner.common.util.hosts": types.ModuleType(
            "horovod.runner.common.util.hosts"
        ),
        "horovod.runner.http": types.ModuleType("horovod.runner.http"),
        "horovod.runner.http.http_server": types.ModuleType(
            "horovod.runner.http.http_server"
        ),
    }
    mods["horovod.runner.common.util.hosts"].parse_hosts = parse_hosts
    mods["horovod.runner.common.util.hosts"].get_host_assignments = (
        get_host_assignments
    )
    mods["horovod.runner.http.http_server"].RendezvousServer = FakeRendezvousServer
    for name, mod in mods.items():
        monkeypatch.setitem(sys.modules, name, mod)

    adapter = HorovodTaskAdapter()
    host_slots = [("hostA", 2), ("hostB", 2)]
    slots = compute_slot_assignments(host_slots)
    port = adapter._start_rendezvous(host_slots, slots, test_mode=False)

    assert port == 43210
    assert calls["parse"] == "hostA:2,hostB:2"
    assert calls["assign_args"] == (["parsed:hostA:2,hostB:2"], 1)
    # the critical step: the plan from get_host_assignments reaches init()
    assert calls["init_plan"] == ["plan-entry-0", "plan-entry-1"]
    # and the server object is retained so it isn't garbage collected
    assert isinstance(adapter._real_server, FakeRendezvousServer)


def test_metrics_accumulator_avg_max():
    acc = MetricsAccumulator()
    for v in (1.0, 3.0, 2.0):
        acc.observe("rss", v)
    snap = {m["name"]: m["value"] for m in acc.snapshot()}
    assert snap["max_rss"] == 3.0
    assert abs(snap["avg_rss"] - 2.0) < 1e-9


# ------------------------------------------------------------ tpu provisioner

def test_tpu_provisioner_discovery_and_geometry():
    from tony_tpu.cluster.tpu import TpuPodProvisioner, slice_num_hosts

    assert slice_num_hosts("v5litepod-16") == 4
    assert slice_num_hosts("v5litepod-8") == 1
    conf = TonyConf({
        "tony.tpu.discover-command": "printf 'host-a\\nhost-b\\nhost-c\\nhost-d\\n'",
        "tony.tpu.accelerator-type": "v5litepod-16",
        "tony.worker.instances": 4,
        "tony.worker.chips": 4,
    })
    prov = TpuPodProvisioner(conf)
    assert prov.hosts == ["host-a", "host-b", "host-c", "host-d"]
    prov.validate_layout(conf)  # 4 tpu tasks on 4 hosts: ok

    over = TonyConf({
        "tony.cluster.static-hosts": "h1,h2",
        "tony.worker.instances": 3,
        "tony.worker.chips": 4,
    })
    prov2 = TpuPodProvisioner(over)
    import pytest as _pytest
    with _pytest.raises(ValueError, match="slice hosts"):
        prov2.validate_layout(over)


def test_tpu_provisioner_host_count_mismatch():
    import pytest as _pytest
    from tony_tpu.cluster.tpu import TpuPodProvisioner

    conf = TonyConf({
        "tony.cluster.static-hosts": "h1,h2,h3",
        "tony.tpu.accelerator-type": "v5litepod-16",  # expects 4 hosts
        "tony.worker.instances": 1,
        # the mismatch is re-probed discover-retries times; the default
        # 10s inter-attempt poll made this unit ~20s of pure sleep
        # (ROADMAP tier-1 budget item)
        "tony.tpu.create-poll-interval-s": 0,
    })
    with _pytest.raises(ValueError, match="hosts"):
        TpuPodProvisioner(conf)


def test_step_timer():
    from tony_tpu.train.profiling import StepTimer

    t = StepTimer(window=5)
    for _ in range(6):
        t.tick()
    assert t.steps_per_sec > 0


# ------------------------------------------------------------ container launch

def test_build_container_command():
    from tony_tpu.conf import TonyConf
    from tony_tpu.utils.containers import build_container_command, container_enabled

    conf = TonyConf({
        "tony.docker.enabled": True,
        "tony.docker.containers.image": "img:1",
        "tony.docker.containers.mount": "/data:/data:ro,/ckpt:/ckpt",
        "tony.docker.extra-args": "--device,/dev/accel0",
    })
    assert container_enabled(conf)
    argv = build_container_command(
        "python t.py", {"TONY_JOB_NAME": "worker"}, conf, work_dir="/wd"
    )
    assert argv[:5] == ["docker", "run", "--rm", "--network", "host"]
    assert argv[-4:] == ["img:1", "bash", "-c", "python t.py"]
    pairs = set(zip(argv, argv[1:]))
    assert {("--user", f"{os.getuid()}:{os.getgid()}"), ("-v", "/wd:/wd"),
            ("-w", "/wd"), ("-v", "/data:/data:ro"), ("-v", "/ckpt:/ckpt"),
            ("-e", "TONY_JOB_NAME=worker"),
            ("--device", "/dev/accel0")} <= pairs, argv


def test_container_per_role_image_and_missing_image():
    import pytest as _pytest

    from tony_tpu.conf import TonyConf
    from tony_tpu.utils.containers import build_container_command

    conf = TonyConf({
        "tony.docker.enabled": True,
        "tony.docker.containers.image": "base:1",
        "tony.docker.evaluator.image": "eval:2",
    })
    assert "eval:2" in build_container_command("c", {}, conf, role="evaluator")
    assert "base:1" in build_container_command("c", {}, conf, role="worker")
    with _pytest.raises(ValueError, match="image"):
        build_container_command("c", {}, TonyConf({"tony.docker.enabled": True}))


def _slice_conf(tmp_path, n_hosts=4, ready_after=0, accel="v5litepod-16",
                **extra):
    """Lifecycle conf wired to the stub cloud CLI (state dir = tmp_path)."""
    stub = Path(__file__).parent / "fixtures" / "scripts" / "stub_slice.py"
    d = tmp_path / "slice"
    return TonyConf({
        "tony.tpu.discover-command": f"{PY} -S {stub} describe {d}",
        "tony.tpu.create-command":
            f"{PY} -S {stub} create {d} {n_hosts} {ready_after}",
        "tony.tpu.delete-command": f"{PY} -S {stub} delete {d}",
        "tony.tpu.accelerator-type": accel,
        "tony.tpu.create-timeout-s": 15,
        "tony.tpu.create-poll-interval-s": 0.02,
        # keep tests fast: absence is expected in most scenarios, so don't
        # armor against flakes (the flake test overrides this)
        "tony.tpu.discover-retries": 1,
        **extra,
    }), d


def test_tpu_slice_create_await_ready_teardown(tmp_path):
    """No pre-created slice: the provisioner materializes one, polls
    through the CREATING phase to READY, and teardown deletes it — the
    capacity-allocation half of the reference RM
    (TonyClient.submitApplication:317-353, async grants
    ApplicationMaster.java:1100-1119)."""
    from tony_tpu.cluster.tpu import TpuPodProvisioner

    conf, d = _slice_conf(tmp_path, ready_after=2)
    prov = TpuPodProvisioner(conf)
    assert prov.created
    assert prov.hosts == [f"host{i}-g1" for i in range(4)]
    assert (d / "slice.json").exists()
    prov.teardown()
    assert not (d / "slice.json").exists()


def test_tpu_slice_recreate_on_preemption(tmp_path):
    """A pre-created slice is NOT driver-owned (teardown leaves it), but
    once preemption destroys it, refresh() re-creates — and from then on
    the driver owns the replacement."""
    import subprocess as sp

    from tony_tpu.cluster.tpu import TpuPodProvisioner

    conf, d = _slice_conf(tmp_path)
    sp.run(str(conf.get("tony.tpu.create-command")), shell=True, check=True)
    prov = TpuPodProvisioner(conf)
    assert not prov.created  # discovered, not created
    assert prov.hosts == [f"host{i}-g1" for i in range(4)]
    prov.teardown()
    assert (d / "slice.json").exists(), "teardown must not delete user slices"

    (d / "slice.json").unlink()  # spot preemption destroys the slice
    prov.refresh()
    assert prov.created
    assert prov.hosts == [f"host{i}-g2" for i in range(4)], \
        "recreated slice must re-discover NEW host addresses"
    prov.teardown()
    assert not (d / "slice.json").exists()


def test_tpu_slice_create_timeout_deletes_leak(tmp_path):
    """A slice that never reaches READY fails allocation with a clear
    timeout instead of hanging the driver — and the created-but-unready
    slice is deleted, not leaked as untracked billable capacity."""
    from tony_tpu.cluster.tpu import TpuPodProvisioner

    conf, d = _slice_conf(
        tmp_path, ready_after=10_000,
        **{"tony.tpu.create-timeout-s": 0.2},
    )
    with pytest.raises(TimeoutError, match="not READY"):
        TpuPodProvisioner(conf)
    assert not (d / "slice.json").exists(), "unready slice leaked"


def test_tpu_slice_carcass_cleared_before_create(tmp_path):
    """Submitting while a preemption carcass (wrong host count) still holds
    the slice name: the provisioner deletes the remnant first so the cloud
    create doesn't fail with 'already exists'."""
    import subprocess as sp

    from tony_tpu.cluster.tpu import TpuPodProvisioner

    conf, d = _slice_conf(tmp_path)  # create command makes 4 hosts
    stub = Path(__file__).parent / "fixtures" / "scripts" / "stub_slice.py"
    sp.run(f"{PY} -S {stub} create {d} 2 0", shell=True, check=True)  # carcass
    prov = TpuPodProvisioner(conf)
    assert prov.created
    assert prov.hosts == [f"host{i}-g2" for i in range(4)]
    assert "delete" in (d / "delete.log").read_text()


def test_tpu_slice_transient_discovery_flake_does_not_destroy(tmp_path):
    """One transient describe failure (API 5xx, timeout) must NOT make the
    lifecycle path delete+recreate healthy capacity: discovery is retried
    tony.tpu.discover-retries times before the slice is declared gone."""
    import subprocess as sp

    from tony_tpu.cluster.tpu import TpuPodProvisioner

    conf, d = _slice_conf(tmp_path)
    stub = Path(__file__).parent / "fixtures" / "scripts" / "stub_slice.py"
    sp.run(f"{PY} -S {stub} create {d} 4 0", shell=True, check=True)
    flaked = tmp_path / "flaked"
    conf.set(
        "tony.tpu.discover-command",
        # first call fails (transient), later calls describe normally
        f"if [ ! -f {flaked} ]; then touch {flaked}; echo 5xx >&2; exit 1; "
        f"else {PY} -S {stub} describe {d}; fi",
    )
    conf.set("tony.tpu.discover-retries", 3)
    prov = TpuPodProvisioner(conf)
    assert not prov.created, "flake must not trigger the create path"
    assert prov.hosts == [f"host{i}-g1" for i in range(4)]
    assert not (d / "delete.log").exists(), "healthy slice was deleted"


def test_tpu_slice_sustained_outage_refuses_delete_recreate(tmp_path):
    """A discovery outage longer than the whole retry budget — but with NO
    positive not-found evidence (5xx-style stderr) — must abort instead of
    engaging delete+recreate: the slice may be healthy capacity the driver
    does not own, and 'describe kept failing' is not proof it is gone."""
    import subprocess as sp

    from tony_tpu.cluster.tpu import TpuPodProvisioner

    conf, d = _slice_conf(tmp_path)
    sp.run(str(conf.get("tony.tpu.create-command")), shell=True, check=True)
    conf.set(
        "tony.tpu.discover-command",
        "echo 'ERROR: backend error 503' >&2; exit 1",
    )
    conf.set("tony.tpu.discover-retries", 2)
    with pytest.raises(RuntimeError, match="refusing to delete"):
        TpuPodProvisioner(conf)
    assert not (d / "delete.log").exists(), \
        "transient outage destroyed a healthy slice"
    assert (d / "slice.json").exists()


def test_tpu_slice_custom_not_found_pattern(tmp_path):
    """A CLI whose absent-resource message doesn't match the default
    pattern still engages the lifecycle path once
    tony.tpu.not-found-pattern names it."""
    from tony_tpu.cluster.tpu import TpuPodProvisioner

    conf, d = _slice_conf(tmp_path)
    stub = Path(__file__).parent / "fixtures" / "scripts" / "stub_slice.py"
    flagged = tmp_path / "created_once"
    # before the create runs, describe reports an unusual absence message;
    # the create command drops a marker so later describes hit the stub
    conf.set(
        "tony.tpu.discover-command",
        f"if [ -f {flagged} ]; then {PY} -S {stub} describe {d}; "
        f"else echo 'no such resource in project' >&2; exit 1; fi",
    )
    base_create = str(conf.get("tony.tpu.create-command"))
    conf.set("tony.tpu.create-command", f"touch {flagged} && {base_create}")
    # default pattern would refuse ("no such resource" matches nothing)
    with pytest.raises(RuntimeError, match="refusing to delete"):
        TpuPodProvisioner(conf)
    conf.set("tony.tpu.not-found-pattern", "no such resource")
    prov = TpuPodProvisioner(conf)
    assert prov.created
    assert prov.hosts == [f"host{i}-g1" for i in range(4)]


def test_tpu_slice_malformed_not_found_pattern_fails_fast(tmp_path):
    """An unbalanced-paren tony.tpu.not-found-pattern is a config error at
    provisioner construction — before any cloud I/O — not an re.error
    surfacing mid-await-READY where cleanup would misread it as a failed
    create and delete the slice."""
    from tony_tpu.cluster.tpu import TpuPodProvisioner

    conf, d = _slice_conf(tmp_path)
    conf.set("tony.tpu.not-found-pattern", "not found (")
    with pytest.raises(ValueError, match="not-found-pattern"):
        TpuPodProvisioner(conf)
    assert not (d / "create.log").exists(), "config error ran the create"


def test_tpu_slice_create_without_discovery_fails_fast(tmp_path):
    """create-command with no discover mechanism is a config error reported
    immediately, not a 30-minute await-READY against nothing."""
    from tony_tpu.cluster.tpu import TpuPodProvisioner

    conf = TonyConf({
        "tony.tpu.create-command": "true",
        "tony.tpu.discover-retries": 1,
        "tony.tpu.create-poll-interval-s": 0.01,
    })
    with pytest.raises(ValueError, match="no way to await READY"):
        TpuPodProvisioner(conf)


def test_tpu_multislice_requires_slice_placeholder(tmp_path):
    """num-slices > 1 with a lifecycle template missing {slice} is a config
    error, not N operations against ONE cloud resource (double-booked
    hosts, a slice-1 refresh deleting slice 0's capacity)."""
    from tony_tpu.cluster.tpu import TpuPodProvisioner

    base = {
        "tony.tpu.num-slices": 2,
        "tony.tpu.discover-retries": 1,
        "tony.tpu.create-poll-interval-s": 0.01,
    }
    conf = TonyConf({**base, "tony.tpu.discover-command": "echo host0"})
    with pytest.raises(ValueError, match=r"\{slice\} placeholder"):
        TpuPodProvisioner(conf)
    # templated discover but raw delete: still rejected
    conf2 = TonyConf({
        **base,
        "tony.tpu.discover-command": "echo host-s{slice}",
        "tony.tpu.delete-command": "true",
    })
    with pytest.raises(ValueError, match="delete-command.*placeholder"):
        TpuPodProvisioner(conf2)


def test_tpu_slice_await_without_geometry_needs_stable_list(tmp_path):
    """Without tony.tpu.accelerator-type there is no expected host count;
    await-READY must not accept the first (possibly partial, mid-creation)
    non-empty list — it waits for the list to repeat across
    tony.tpu.ready-stable-polls consecutive polls (default 3)."""
    from tony_tpu.cluster.tpu import TpuPodProvisioner

    conf, _ = _slice_conf(tmp_path, ready_after=2, accel="")
    prov = TpuPodProvisioner(conf)
    # the stub reports growing partials (2 then 3 hosts) before the full 4
    assert prov.hosts == [f"host{i}-g1" for i in range(4)]


def test_tpu_provisioner_refresh_rediscovers_hosts(tmp_path):
    """Driver retry must re-run discovery (a recreated spot slice has new
    addresses); static host lists are a no-op refresh."""
    from tony_tpu.cluster.tpu import TpuPodProvisioner

    state = tmp_path / "hosts.txt"
    state.write_text("old-a\nold-b\nold-c\nold-d\n")
    conf = TonyConf({
        "tony.tpu.discover-command": f"cat {state}",
        "tony.tpu.accelerator-type": "v5litepod-16",
        # no inter-retry sleeps: the partial-recreate refresh below is
        # retried discover-retries times and the default 10s poll made
        # this unit ~20s of pure sleep (ROADMAP tier-1 budget item)
        "tony.tpu.create-poll-interval-s": 0,
    })
    prov = TpuPodProvisioner(conf)
    assert prov.hosts == ["old-a", "old-b", "old-c", "old-d"]
    state.write_text("new-a\nnew-b\nnew-c\nnew-d\n")  # slice recreated
    prov.refresh()
    assert prov.hosts == ["new-a", "new-b", "new-c", "new-d"]

    # a partially-recreated slice (wrong host count) must be rejected,
    # keeping the previous host list
    import pytest
    state.write_text("half-a\nhalf-b\n")
    with pytest.raises(ValueError, match="recreating"):
        prov.refresh()
    assert prov.hosts == ["new-a", "new-b", "new-c", "new-d"]

    static = TpuPodProvisioner(TonyConf({
        "tony.cluster.static-hosts": "h1,h2",
    }))
    static.refresh()
    assert static.hosts == ["h1", "h2"]


# ------------------------------------------------- multislice env contract

def test_jax_adapter_multislice_requires_slice0_host(monkeypatch):
    """TONY_NUM_SLICES>1 without TONY_SLICE0_HOST must fail fast at env-build
    time — otherwise MEGASCALE_COORDINATOR_ADDRESS would be the malformed
    ':8080' and libtpu would fail much later with an opaque transport error."""
    from tony_tpu import constants as c
    from tony_tpu.runtimes.base import TaskContext
    from tony_tpu.runtimes.jax_runtime import JaxTaskAdapter

    ctx = TaskContext(
        job_name="worker", task_index=0, task_num=2, num_total_tasks=2,
        is_chief=True, command="true",
        cluster_payload={"cluster": {"worker": ["h0:1", "h1:1"]},
                         "ranks": {"worker:0": 0, "worker:1": 1},
                         "num_processes": 2,
                         "coordinator_address": "h0:1"},
        base_child_env={},
    )
    adapter = JaxTaskAdapter()

    monkeypatch.setenv(c.ENV_NUM_SLICES, "2")
    monkeypatch.setenv(c.ENV_SLICE_ID, "1")
    monkeypatch.delenv(c.ENV_SLICE0_HOST, raising=False)
    with pytest.raises(RuntimeError, match="TONY_SLICE0_HOST"):
        adapter.build_env(ctx)
    monkeypatch.setenv(c.ENV_SLICE0_HOST, "")
    with pytest.raises(RuntimeError, match="TONY_SLICE0_HOST"):
        adapter.build_env(ctx)

    monkeypatch.setenv(c.ENV_SLICE0_HOST, "slice0-host")
    env = adapter.build_env(ctx)
    assert env["MEGASCALE_COORDINATOR_ADDRESS"] == (
        f"slice0-host:{c.MEGASCALE_PORT}")
    assert env["MEGASCALE_NUM_SLICES"] == "2"
    assert env["MEGASCALE_SLICE_ID"] == "1"
