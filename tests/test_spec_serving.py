"""Speculative decoding in continuous batching + the multi-model
registry (models/serving.py `_spec_block`, models/registry.py).

The contracts under test:

- **Byte-identity**: a greedy request served with a draft model
  speculating is token-for-token identical to spec-off serving AND to a
  solo generate() run — for a random draft (acceptance ~0, every round
  exercises the correction path) and a self-draft (acceptance ~1, the
  all-accept/bonus path). Speculation is a pure latency/throughput
  optimization, never a numerics change.
- **Event-log discipline survives speculation**: loop-crash replay is
  byte-identical greedy (rejected draft tokens never reach the journal
  — the journaled prefix at any instant is a true prefix of the final
  stream), and cancel-mid-verify returns an exact solo-stream prefix
  with the freed slot's next occupant token-identical (the PR 3
  contract).
- **Gamma autotune**: the per-slot acceptance EWMA drives the draft
  window up under an agreeing draft and down to 1 under a random one;
  --spec-gamma pins it.
- **Multi-model**: a ServeApp over {name -> SlotServer} engines serves
  two models concurrently with correct per-model outputs, routes
  model= to the right engine, 400s unknown names, and labels /stats
  and /metrics per model (the `serving_models` info gauge + model-
  labeled families).

All shapes are TINY and shared across tests so the compiled program
set stays within the tier-1 budget.
"""

import json
import threading
import urllib.error
import urllib.request
from http.server import ThreadingHTTPServer

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import transformer
from tony_tpu.models.generate import generate
from tony_tpu.models.registry import ModelRegistry
from tony_tpu.models.serving import Request, SlotServer

TINY = transformer.TransformerConfig(
    vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=128, dtype=jnp.float32,
)
DRAFT = transformer.TransformerConfig(
    vocab_size=256, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
    d_ff=64, max_seq_len=128, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return transformer.init(jax.random.PRNGKey(0), TINY)


@pytest.fixture(scope="module")
def dparams():
    return transformer.init(jax.random.PRNGKey(1), DRAFT)


def _prompts(n, key=3, lo=2, hi=14):
    k = jax.random.PRNGKey(key)
    out = []
    for _ in range(n):
        k, a, b = jax.random.split(k, 3)
        lp = int(jax.random.randint(a, (), lo, hi))
        out.append(np.asarray(
            jax.random.randint(b, (lp,), 0, TINY.vocab_size), np.int32))
    return out


def _solo(params, prompt, max_new, **kw):
    out = generate(params, TINY, jnp.asarray(prompt)[None], max_new, **kw)
    return [int(t) for t in np.asarray(out)[0]]


def _srv(params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_chunk", 8)
    return SlotServer(params, TINY, **kw)


def _serve_burst(srv, prompts, budgets):
    reqs = [Request(prompt=p, max_new_tokens=b)
            for p, b in zip(prompts, budgets)]
    for r in reqs:
        srv.submit(r)
    done = srv.run_until_drained()
    return reqs, done


# --------------------------------------------------------------------------
# model registry
# --------------------------------------------------------------------------

def test_model_registry_unit():
    reg = ModelRegistry()
    with pytest.raises(KeyError):
        reg.default
    e1 = reg.register("target", {"w": 1}, TINY, source="random:0")
    assert e1.generation == 0 and reg.default is e1
    assert "target" in reg and len(reg) == 1
    # re-registration (in-process hot swap) bumps the generation
    e2 = reg.register("target", {"w": 2}, TINY, source="random:9")
    assert e2.generation == 1 and reg.get("target").weights == {"w": 2}
    # draft pairing resolves through the registry; dangling names fail
    # at resolution, not registration
    reg.register("mini", {"w": 3}, DRAFT)
    reg.get("target").draft = "mini"
    assert reg.resolve_draft("target").name == "mini"
    assert reg.resolve_draft("mini") is None
    reg.get("target").draft = "ghost"
    with pytest.raises(KeyError, match="ghost"):
        reg.resolve_draft("target")
    with pytest.raises(KeyError, match="unknown model"):
        reg.get("nope")
    with pytest.raises(ValueError):
        reg.register("self", {"w": 4}, TINY, draft="self")
    assert reg.names() == ["target", "mini"], (
        "a rejected registration must not half-register")


def test_slot_server_builds_internal_registry(params, dparams):
    """The classic (params, cfg) constructor still works and now exposes
    the registry surface: the weights are a named entry, an inline
    draft registers as a second entry, and the pairing is recorded."""
    srv = _srv(params, draft=dparams, draft_cfg=DRAFT, spec_gamma=2)
    try:
        assert srv.model == "default"
        assert set(srv.registry.names()) == {"default", "draft"}
        assert srv.registry.get("default").draft == "draft"
        assert srv.registry.resolve_draft("default").cfg is DRAFT
        # registry-first construction serves the same entry
        srv2 = SlotServer(registry=srv.registry, model="default",
                          slots=2, max_len=64, block_size=4,
                          prefill_chunk=8)
        assert srv2._spec and srv2.draft_model == "draft"
        srv2.shutdown()
    finally:
        srv.shutdown()


# --------------------------------------------------------------------------
# byte-identity: spec on == spec off == solo, both acceptance regimes
# --------------------------------------------------------------------------

def test_spec_parity_random_draft(params, dparams):
    """A random draft agrees with the target almost never — every round
    exercises the rejection/correction path — and the output is STILL
    byte-identical to spec-off serving and solo generate (a broken
    draft can only cost speed)."""
    prompts = _prompts(8)
    budgets = [6 + (i % 5) for i in range(8)]
    plain = _srv(params)
    _, done_p = _serve_burst(plain, prompts, budgets)
    spec = _srv(params, draft=dparams, draft_cfg=DRAFT, spec_gamma=2)
    reqs, done_s = _serve_burst(spec, prompts, budgets)
    for i, r in enumerate(reqs):
        want = _solo(params, prompts[i], budgets[i])
        assert done_s[r.id].tokens == want, f"request {i} diverged"
    st = spec.stats()["speculative"]
    assert st["rounds"] > 0 and st["proposed_tokens"] > 0
    assert st["acceptance"]["count"] > 0, "acceptance histogram empty"
    assert st["acceptance_ewma"] < 0.3, "random draft should rarely agree"
    # trace attrs carry the per-request speculation tallies
    tr = done_s[reqs[0].id].trace
    assert tr["attrs"]["spec_rounds"] >= 1
    plain.shutdown()
    spec.shutdown()


def test_spec_parity_self_draft_accepts_everything(params):
    """Draft == target: every proposal verifies (acceptance ~1, the
    all-accept + bonus-token path), output still byte-identical, and
    the verify-round count is well under one-round-per-token."""
    prompts = _prompts(6, key=5)
    budgets = [8] * 6
    spec = _srv(params, draft=params, draft_cfg=TINY, spec_gamma=2)
    reqs, done = _serve_burst(spec, prompts, budgets)
    for i, r in enumerate(reqs):
        assert done[r.id].tokens == _solo(params, prompts[i], budgets[i])
    st = spec.stats()["speculative"]
    assert st["acceptance_ewma"] > 0.8
    assert st["accepted_tokens"] > 0
    # with gamma=2 and full acceptance, each round delivers up to 3
    # tokens: the per-request verify-round histogram must sit well
    # under the budget of 8
    assert st["verify_rounds_per_request"]["count"] == len(reqs)
    assert st["verify_rounds_per_request"]["p90_s"] <= 5
    spec.shutdown()


def test_spec_eos_matches_generate(params, dparams):
    """Stop tokens end requests mid-round: the emitted stream (stop
    token kept, nothing after) matches generate(stop_tokens=...) for
    every request, under speculation."""
    prompts = _prompts(6, key=11)
    solo = [_solo(params, p, 10) for p in prompts]
    stop = solo[0][4]
    spec = _srv(params, draft=dparams, draft_cfg=DRAFT, spec_gamma=2,
                stop_tokens=(stop,))
    reqs, done = _serve_burst(spec, prompts, [10] * 6)
    stopped = 0
    for i, r in enumerate(reqs):
        want = _solo(params, prompts[i], 10, stop_tokens=(stop,))
        if stop in want:                # generate pads past the stop
            want = want[:want.index(stop) + 1]
            stopped += 1
        assert done[r.id].tokens == want, f"request {i} diverged"
        assert done[r.id].finish_reason == (
            "stop" if want[-1] == stop else "length")
    assert stopped >= 1, "stop token never fired; test is vacuous"
    spec.shutdown()


# --------------------------------------------------------------------------
# event-log discipline × speculation
# --------------------------------------------------------------------------

def test_spec_cancel_mid_verify_token_identical(params, dparams):
    """Cancel between verify rounds: the partial is an EXACT prefix of
    the solo stream, and the freed slot's next occupant is
    token-identical to a fresh server (the PR 3 cancel contract,
    unchanged by speculation)."""
    prompts = _prompts(4, key=7, lo=4, hi=10)
    srv = SlotServer(params, TINY, slots=1, max_len=64, block_size=4,
                     prefill_chunk=8, draft=dparams, draft_cfg=DRAFT,
                     spec_gamma=2)
    a = Request(prompt=prompts[0], max_new_tokens=12)
    b = Request(prompt=prompts[1], max_new_tokens=6)
    srv.submit(a)
    srv.submit(b)
    for _ in range(3):                  # a is mid-decode, b queued
        srv.step()
    assert srv.cancel(a.id)
    done = srv.run_until_drained()
    ca = done[a.id]
    assert ca.finish_reason == "cancelled"
    full = _solo(params, prompts[0], 12)
    assert ca.tokens == full[:len(ca.tokens)], "partial not a true prefix"
    assert done[b.id].tokens == _solo(params, prompts[1], 6), (
        "the freed slot's next occupant diverged")
    srv.shutdown()


def test_spec_crash_replay_byte_identical(params, dparams, monkeypatch):
    """Loop crash mid-speculation (deterministic chaos at a spec-round
    ordinal) -> reset() replays the journaled prefixes; completions are
    byte-identical greedy, nothing is lost, and the journaled prefix
    at the crash instant was a TRUE prefix of the final stream
    (rejected draft tokens never reached the journal)."""
    monkeypatch.setenv("TONY_TEST_SERVING_CRASH_AT_BLOCKS", "3")
    prompts = _prompts(6, key=13)
    srv = _srv(params, draft=dparams, draft_cfg=DRAFT, spec_gamma=2)
    reqs = [Request(prompt=p, max_new_tokens=8) for p in prompts]
    for r in reqs:
        srv.submit(r)
    crashed, out, crash_prefixes = False, {}, {}
    while not srv.idle:
        try:
            srv.step()
        except RuntimeError:
            crashed = True
            # snapshot the journal AT the crash: these prefixes must be
            # true prefixes of the final streams
            for r in reqs:
                entry = srv._journal.get(r.id)
                if entry is not None and entry.emitted:
                    crash_prefixes[r.id] = list(entry.emitted)
            lost = srv.reset()
            assert lost == [], f"journal replay lost requests: {lost}"
        out.update(srv.drain_completed())
    out.update(srv.drain_completed())
    assert crashed, "the chaos crash never fired; test is vacuous"
    assert srv.replays >= 1
    for i, r in enumerate(reqs):
        want = _solo(params, prompts[i], 8)
        assert out[r.id].tokens == want, f"request {i} diverged"
        pre = crash_prefixes.get(r.id)
        if pre:
            assert want[:len(pre)] == pre, (
                "journal held tokens the final stream disowns — a "
                "rejected draft leaked into the journal")
    srv.shutdown()


# --------------------------------------------------------------------------
# gamma autotune
# --------------------------------------------------------------------------

def test_spec_gamma_autotune_and_pin(params, dparams):
    """The acceptance EWMA steers gamma: an agreeing (self) draft
    drives it to the max, a random draft drives it to 1; a pinned
    gamma never moves."""
    prompts = _prompts(4, key=17)
    up = _srv(params, draft=params, draft_cfg=TINY, spec_gamma_max=4)
    _serve_burst(up, prompts, [10] * 4)
    assert up._current_gamma() == 4, (
        f"full acceptance should max gamma, got {up._current_gamma()}")
    up.shutdown()
    down = _srv(params, draft=dparams, draft_cfg=DRAFT, spec_gamma_max=4)
    _serve_burst(down, prompts, [10] * 4)
    assert down._current_gamma() == 1, (
        f"random draft should shrink gamma to 1, got "
        f"{down._current_gamma()}")
    down.shutdown()
    pinned = _srv(params, draft=params, draft_cfg=TINY, spec_gamma=2)
    _serve_burst(pinned, prompts, [6] * 4)
    assert pinned._current_gamma() == 2
    assert pinned.stats()["speculative"]["gamma_pinned"] is True
    pinned.shutdown()


def test_spec_rejects_invalid_configs(params, dparams):
    with pytest.raises(ValueError, match="greedy-only"):
        SlotServer(params, TINY, draft=dparams, draft_cfg=DRAFT,
                   temperature=0.7)
    with pytest.raises(ValueError, match="draft_cfg"):
        SlotServer(params, TINY, draft=dparams)
    bad_vocab = transformer.TransformerConfig(
        vocab_size=128, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=64, max_seq_len=128, dtype=jnp.float32)
    with pytest.raises(ValueError, match="vocabulary"):
        SlotServer(params, TINY, draft=dparams, draft_cfg=bad_vocab)
    srv = _srv(params, draft=dparams, draft_cfg=DRAFT, spec_gamma=2)
    with pytest.raises(ValueError, match="greedy-only"):
        srv.submit(Request(prompt=[1, 2, 3], max_new_tokens=4,
                           temperature=0.5))
    # a greedy request with an explicit temperature of 0 is fine
    srv.submit(Request(prompt=[1, 2, 3], max_new_tokens=2,
                       temperature=0.0))
    srv.run_until_drained()
    srv.shutdown()


# --------------------------------------------------------------------------
# multi-model ServeApp
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def params_b():
    return transformer.init(jax.random.PRNGKey(9), TINY)


def _two_model_app(params, params_b, **engine_kw):
    from tony_tpu.cli.serve import ServeApp

    reg = ModelRegistry()
    reg.register("alpha", params, TINY, source="random:0")
    reg.register("beta", params_b, TINY, source="random:9")
    engines = {
        n: SlotServer(registry=reg, model=n, slots=2, max_len=64,
                      block_size=4, prefill_chunk=8, **engine_kw)
        for n in ("alpha", "beta")}
    app = ServeApp(engines)
    app.start()
    return app


def test_multi_model_concurrent_and_unknown(params, params_b):
    """Two engines behind one app: concurrent requests to both models
    return each model's own (distinct) greedy stream; nameless requests
    get the default (first) model; unknown names raise."""
    from tony_tpu.cli.serve import UnknownModelError

    app = _two_model_app(params, params_b)
    try:
        prompt = [3, 5, 7, 9, 11]
        wa = _solo(params, np.asarray(prompt, np.int32), 6)
        wb = _solo(params_b, np.asarray(prompt, np.int32), 6)
        assert wa != wb, "seeds collided; test is vacuous"
        results = {}

        def call(model):
            results[model] = app.generate(prompt, 6, timeout=120,
                                          model=model)

        ts = [threading.Thread(target=call, args=(m,))
              for m in ("alpha", "beta")]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert results["alpha"].tokens == wa
        assert results["beta"].tokens == wb
        assert app.generate(prompt, 6, timeout=120).tokens == wa
        with pytest.raises(UnknownModelError, match="nope"):
            app.generate(prompt, 4, timeout=10, model="nope")
        st = app.stats()
        assert set(st["models"]) == {"alpha", "beta"}
        assert st["slots"] == 4, "multi-model /stats aggregates load"
        assert st["models"]["beta"]["model"] == "beta"
    finally:
        app.shutdown()


def test_multi_model_metrics_labels(params, params_b, dparams):
    """/metrics carries the serving_models info gauge, model-labeled
    partitions of the serving families, and — for a spec-enabled
    engine — the serving_spec_* families."""
    from tony_tpu.cli.serve import ServeApp, make_handler

    reg = ModelRegistry()
    reg.register("alpha", params, TINY, source="random:0")
    reg.register("mini", dparams, DRAFT, source="random:1")
    reg.get("alpha").draft = "mini"
    engines = {"alpha": SlotServer(registry=reg, model="alpha", slots=2,
                                   max_len=64, block_size=4,
                                   prefill_chunk=8, spec_gamma=2),
               "beta": SlotServer(params_b, TINY, model="beta", slots=2,
                                  max_len=64, block_size=4,
                                  prefill_chunk=8)}
    assert engines["alpha"]._spec, "registry draft pairing not resolved"
    app = ServeApp(engines)
    app.start()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(app))
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        app.generate([1, 2, 3, 4], 4, timeout=120, model="alpha")
        app.generate([1, 2, 3, 4], 4, timeout=120, model="beta")
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            text = r.read().decode()
        for needle in (
                'serving_models{model="alpha"} 1',
                'serving_models{model="beta"} 1',
                'serving_active_slots{model="alpha"}',
                'serving_queue_depth{model="beta"}',
                'serving_ttft_seconds_bucket{model="beta"',
                'serving_spec_rounds_total{model="alpha"}',
                'serving_spec_proposed_tokens_total{model="alpha"}',
                'serving_spec_accepted_tokens_total{model="alpha"}',
                'serving_spec_gamma{model="alpha"}',
                'serving_spec_acceptance_rate_bucket{model="alpha"',
                'serving_spec_verify_rounds_count{model="alpha"}'):
            assert needle in text, f"missing from /metrics: {needle}"
        # the spec families are per-model: the non-spec engine has none
        assert 'serving_spec_rounds_total{model="beta"}' not in text
        # /stats carries the spec section under the right model
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/stats", timeout=10) as r:
            st = json.loads(r.read())
        assert st["models"]["alpha"]["speculative"]["rounds"] > 0
        assert "speculative" not in st["models"]["beta"]
    finally:
        app.shutdown()
        httpd.server_close()


def test_multi_model_drained_completions_survive_other_engines_crash(
        params, params_b):
    """Round-robin stepping: completions engine A drained this turn are
    DELIVERED even when engine B's step() raises right after — draining
    popped them from A and sealed their journal entries, so dropping
    them would strand their waiters unrecoverably (review finding on
    the multi-engine loop)."""
    from tony_tpu.cli.serve import ServeApp

    app = _two_model_app(params, params_b)
    try:
        # engine beta's FIRST step blows up (armed before the threads
        # start, so there is no race against the loop finishing beta's
        # request first); alpha's request proceeds normally — in loop
        # turns where both are busy, alpha (first in dict order) steps
        # and may drain before beta's step raises
        beta = app.engines["beta"]
        orig_step = beta.step
        state = {"fired": False}

        def boom():
            if not state["fired"]:
                state["fired"] = True
                raise RuntimeError("chaos: beta step died")
            return orig_step()

        beta.step = boom
        prompt = [3, 5, 7, 9, 11]
        wa = _solo(params, np.asarray(prompt, np.int32), 4)
        results = {}

        def call(model):
            try:
                results[model] = app.generate(prompt, 4, timeout=120,
                                              model=model)
            except Exception as e:           # beta may fail its request
                results[model] = e

        ts = [threading.Thread(target=call, args=(m,))
              for m in ("alpha", "beta")]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=150)
        assert state["fired"], "the injected failure never fired"
        # alpha's completion was delivered (not stranded to timeout);
        # beta either replayed to success (journal on) or failed loudly
        ra = results["alpha"]
        assert not isinstance(ra, Exception), ra
        assert ra.tokens == wa
        assert not isinstance(results["beta"], TimeoutError)
    finally:
        app.shutdown()


# --------------------------------------------------------------------------
# journal model tagging
# --------------------------------------------------------------------------

def test_journal_model_field_roundtrip(tmp_path):
    """Journal entries carry the serving model name through the file,
    compaction, and recovery — multi-model restarts resubmit each
    request to the engine that owns its weights."""
    from tony_tpu.events.journal import RequestJournal

    path = tmp_path / "requests.journal.jsonl"
    j = RequestJournal(path=path)
    j.submit(1, [1, 2], 8, model="alpha")
    j.emit(1, [5])
    j.submit(2, [3], 4, model="beta")
    j.submit(3, [4], 4)                 # legacy shape: no model
    j.close()
    j2, entries = RequestJournal.recover(path)
    by_id = {e.id: e for e in entries}
    assert by_id[1].model == "alpha" and by_id[1].emitted == [5]
    assert by_id[2].model == "beta"
    assert by_id[3].model is None
    j2.close()


@pytest.mark.slow
def test_spec_byte_identity_heavy_shape():
    """Heavy variant of the byte-identity gate (the tier-1 tests pin it
    at TINY shapes): a bench-like shape — deeper model, longer prompts
    and budgets, prefix cache on, stop tokens live, gamma autotuned to
    its ceiling — still serves byte-identical spec-on vs spec-off.
    Slow: compiles a full extra program set."""
    big = transformer.TransformerConfig(
        vocab_size=1024, d_model=256, n_layers=4, n_heads=8,
        n_kv_heads=8, d_ff=1024, max_seq_len=256, dtype=jnp.float32)
    small = transformer.TransformerConfig(
        vocab_size=1024, d_model=64, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=256, max_seq_len=256, dtype=jnp.float32)
    bp = transformer.init(jax.random.PRNGKey(0), big)
    sp = transformer.init(jax.random.PRNGKey(1), small)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 1024, size=int(n), dtype=np.int32)
               for n in rng.integers(24, 80, size=12)]
    budgets = [int(b) for b in rng.integers(32, 96, size=12)]
    stops = (17,)

    def run(**kw):
        srv = SlotServer(bp, big, slots=4, max_len=192, block_size=8,
                         prefill_chunk=32, prefix_cache_blocks=16,
                         stop_tokens=stops, **kw)
        reqs, done = _serve_burst(srv, prompts, budgets)
        out = [(done[r.id].tokens, done[r.id].finish_reason)
               for r in reqs]
        st = srv.stats()
        srv.shutdown()
        return out, st

    plain, _ = run()
    spec, st = run(draft=sp, draft_cfg=small, spec_gamma_max=8)
    assert spec == plain, "heavy-shape speculation changed completions"
    assert st["speculative"]["rounds"] > 0


@pytest.mark.slow
def test_shared_journal_recovery_compacts_once(tmp_path, params, params_b):
    """Multi-engine recovery of ONE shared journal file: the first
    engine's resubmission must NOT compact the file (that would erase
    the only durable copy of the other engine's still-unrecovered
    entries — a crash in the window would silently lose them); the
    single deferred compaction keeps every resubmitted entry durable
    (review finding on the per-engine recovery loop)."""
    from tony_tpu.events.journal import RequestJournal, read_journal

    path = tmp_path / "requests.journal.jsonl"
    dead = RequestJournal(path=path)
    dead.submit(9001, [1, 2, 3], 8, model="alpha")
    dead.emit(9001, [5, 6])
    dead.submit(9002, [4, 5, 6], 8, model="beta")
    dead.emit(9002, [7])
    dead.close()

    journal, entries = RequestJournal.recover(path)
    engines = {
        n: SlotServer(p, TINY, slots=2, max_len=64, block_size=4,
                      prefill_chunk=8, journal=journal)
        for n, p in (("alpha", params), ("beta", params_b))}
    try:
        a_entries = [e for e in entries if e.model == "alpha"]
        assert engines["alpha"].recover_journal(a_entries,
                                                compact=False) == 1
        # beta's dead-process record must still be on disk: nothing
        # compacted yet
        on_disk = {e.id for e in read_journal(path)}
        assert 9002 in on_disk, (
            "first engine's recovery erased the other engine's only "
            "durable copy")
        b_entries = [e for e in entries if e.model == "beta"]
        assert engines["beta"].recover_journal(b_entries,
                                               compact=False) == 1
        journal.compact()
        # post-compaction: exactly the two LIVE resubmissions survive,
        # with their emitted prefixes carried
        live = read_journal(path)
        assert len(live) == 2
        assert all(e.emitted for e in live)
        for eng in engines.values():
            assert eng.run_until_drained(), "recovered request unserved"
    finally:
        for eng in engines.values():
            eng.shutdown()
        journal.close()


@pytest.mark.slow
def test_spec_sigkill_recovery_subprocess(tmp_path):
    """SIGKILL a serve process mid-speculation (chaos at a spec-round
    ordinal); the restarted process recovers the file journal and
    finishes the orphaned requests. Slow: two subprocess serve
    launches with compile bills."""
    import os
    import re
    import signal
    import subprocess
    import sys
    import time

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               TONY_TEST_SERVING_SIGKILL_AT_BLOCK="3")
    args = [sys.executable, "-m", "tony_tpu.cli.main", "serve",
            "--port", "0", "--vocab", "256", "--d-model", "64",
            "--n-layers", "2", "--n-heads", "4", "--d-ff", "128",
            "--dtype", "float32", "--slots", "2", "--max-len", "64",
            "--block-size", "4", "--prefill-chunk", "8",
            "--draft-model", "random:1",
            "--draft-d-model", "32", "--draft-n-layers", "1",
            "--draft-n-heads", "2", "--draft-d-ff", "64",
            "--spec-gamma", "2",
            "--trace-dir", str(tmp_path)]
    proc = subprocess.Popen(args, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    port = None
    deadline = time.time() + 240
    while port is None and time.time() < deadline:
        line = proc.stdout.readline()
        m = re.search(r"http://[\d.]+:(\d+)", line or "")
        if m:
            port = int(m.group(1))
    assert port, "serve never printed its port"
    threading.Thread(target=proc.stdout.read, daemon=True).start()

    prompt = list(range(2, 10))
    body = json.dumps({"prompt": prompt, "max_new_tokens": 12,
                       "timeout_s": 300}).encode()

    def post():
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate", data=body)
            with urllib.request.urlopen(req, timeout=300):
                pass
        except Exception:
            pass                        # the process dies mid-request

    t = threading.Thread(target=post, daemon=True)
    t.start()
    proc.wait(timeout=240)
    assert proc.returncode == -signal.SIGKILL
    # the journal survived the kill with a live entry
    from tony_tpu.events.journal import JOURNAL_FILE, read_journal

    entries = read_journal(tmp_path / JOURNAL_FILE)
    assert entries, "no journaled in-flight request survived the kill"
    # restart WITHOUT chaos: recovery finishes the orphaned request
    env2 = dict(os.environ, JAX_PLATFORMS="cpu")
    proc2 = subprocess.Popen(args, env=env2, stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.time() + 240
        recovered = False
        while time.time() < deadline:
            line = proc2.stdout.readline()
            if "journal recovery: resumed" in (line or ""):
                recovered = True
            if re.search(r"http://[\d.]+:(\d+)", line or ""):
                break
        assert recovered, "restart did not recover the journal"
        # drain until the recovered request seals (its waiter is gone;
        # the completion is recorded and dropped) — the journal file
        # compacting to empty is the observable terminal
        deadline = time.time() + 120
        while time.time() < deadline:
            if not read_journal(tmp_path / JOURNAL_FILE):
                break
            time.sleep(0.5)
        assert not read_journal(tmp_path / JOURNAL_FILE), (
            "recovered request never finished")
    finally:
        proc2.kill()
        proc2.wait(timeout=30)


def test_spec_per_request_stop_sequences(params, dparams):
    """Per-request stop SEQUENCES under speculation (ISSUE 15
    satellite): a multi-token stop match ends the request at the
    earliest match end — identical to spec-off serving with the same
    stop — even when the match completes mid-verify-round, and
    stop-less neighbors in the same burst are untouched."""
    prompts = _prompts(4, key=17)
    budgets = [10] * 4
    solo = [_solo(params, p, 10) for p in prompts]
    seq = solo[0][3:5]                  # bigram from request 0's stream

    def expect(toks):
        for e in range(2, len(toks) + 1):
            if toks[e - 2:e] == seq:
                return toks[:e]
        return toks

    plain = _srv(params)
    preqs = [Request(prompt=p, max_new_tokens=b,
                     stop=[list(seq)] if i == 0 else None)
             for i, (p, b) in enumerate(zip(prompts, budgets))]
    for r in preqs:
        plain.submit(r)
    done_p = plain.run_until_drained()
    spec = _srv(params, draft=dparams, draft_cfg=DRAFT, spec_gamma=2)
    sreqs = [Request(prompt=p, max_new_tokens=b,
                     stop=[list(seq)] if i == 0 else None)
             for i, (p, b) in enumerate(zip(prompts, budgets))]
    for r in sreqs:
        spec.submit(r)
    done_s = spec.run_until_drained()
    for i in range(4):
        want = expect(solo[i]) if i == 0 else solo[i]
        assert done_p[preqs[i].id].tokens == want, f"plain {i}"
        assert done_s[sreqs[i].id].tokens == want, f"spec {i}"
    assert done_s[sreqs[0].id].finish_reason == "stop"
    assert done_s[sreqs[1].id].finish_reason == "length"
    # logprobs are out of scope under speculation, by contract
    with pytest.raises(ValueError, match="logprobs"):
        spec.submit(Request(prompt=prompts[0], max_new_tokens=4,
                            logprobs=2))
    plain.shutdown()
    spec.shutdown()
