"""Data plane: token file round-trip, deterministic sharded loading,
exact resume, prefetch equivalence, device placement on the test mesh."""

import numpy as np
import pytest

from tony_tpu.data import (
    PrefetchLoader,
    ShardedBatchLoader,
    TokenDataset,
    device_put_sharded_batch,
    write_tokens,
)


def _toy_dataset(n=4096, vocab=1000, seed=0):
    rng = np.random.default_rng(seed)
    return TokenDataset.from_array(rng.integers(0, vocab, size=n))


def test_token_file_round_trip(tmp_path):
    path = tmp_path / "corpus.bin"
    write_tokens(path, np.arange(1000) % 7)
    write_tokens(path, np.arange(5))  # append
    ds = TokenDataset.from_bin(path)
    assert len(ds) == 1005
    np.testing.assert_array_equal(ds.window(0, 7), np.arange(7) % 7)
    np.testing.assert_array_equal(ds.window(1000, 5), np.arange(5))
    assert ds.window(0, 3).dtype == np.int32


def test_token_file_uint32_and_range_check(tmp_path):
    with pytest.raises(ValueError, match="uint32"):
        write_tokens(tmp_path / "x.bin", [70000], dtype=np.uint16)
    path = write_tokens(tmp_path / "big.bin", [70000, 1], dtype=np.uint32)
    ds = TokenDataset.from_bin(path)
    np.testing.assert_array_equal(ds.window(0, 2), [70000, 1])


def test_token_file_rejects_garbage(tmp_path):
    p = tmp_path / "bad.bin"
    p.write_bytes(b"not a token file at all")
    with pytest.raises(ValueError, match="token file"):
        TokenDataset.from_bin(p)


def test_loader_shapes_and_target_shift():
    ds = _toy_dataset()
    loader = ShardedBatchLoader(ds, global_batch=8, seq_len=32)
    x, y = next(loader)
    assert x.shape == (8, 32) and y.shape == (8, 32)
    # targets are inputs shifted by one within each window
    np.testing.assert_array_equal(x[:, 1:], y[:, :-1])


def test_loader_is_deterministic_in_seed_and_step():
    ds = _toy_dataset()
    a = ShardedBatchLoader(ds, 8, 32, seed=7)
    b = ShardedBatchLoader(ds, 8, 32, seed=7)
    for _ in range(5):
        (xa, ya), (xb, yb) = next(a), next(b)
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)
    c = ShardedBatchLoader(ds, 8, 32, seed=8)
    assert not np.array_equal(next(c)[0], ShardedBatchLoader(ds, 8, 32, seed=7).batch_at(0)[0])


def test_loader_epoch_reshuffles_but_covers_everything():
    ds = _toy_dataset(n=8 * 32 * 4 + 1)  # exactly 4 steps/epoch
    loader = ShardedBatchLoader(ds, 8, 32, seed=1)
    assert loader.steps_per_epoch == 4

    def epoch_rows(epoch):
        rows = []
        for i in range(4):
            x, _ = loader.batch_at(epoch * 4 + i)
            rows.append(x)
        return np.concatenate(rows)

    e0, e1 = epoch_rows(0), epoch_rows(1)
    # same multiset of windows (sort rows lexicographically), different order
    assert not np.array_equal(e0, e1)
    np.testing.assert_array_equal(
        np.sort(e0.view([("", e0.dtype)] * e0.shape[1]), axis=0),
        np.sort(e1.view([("", e1.dtype)] * e1.shape[1]), axis=0),
    )


def test_loader_process_shards_partition_global_batch():
    ds = _toy_dataset()
    whole = ShardedBatchLoader(ds, 8, 16, seed=3)
    shards = [
        ShardedBatchLoader(ds, 8, 16, seed=3, process_index=p, process_count=4)
        for p in range(4)
    ]
    gx, _ = whole.batch_at(2)
    parts = [s.batch_at(2)[0] for s in shards]
    assert all(p.shape == (2, 16) for p in parts)
    # interleaved reassembly p::4 recovers the global batch exactly
    rebuilt = np.empty_like(gx)
    for p, part in enumerate(parts):
        rebuilt[p::4] = part
    np.testing.assert_array_equal(rebuilt, gx)


def test_loader_resume_is_exact():
    ds = _toy_dataset()
    loader = ShardedBatchLoader(ds, 8, 32, seed=5)
    stream = [next(loader) for _ in range(6)]
    state = None
    loader2 = ShardedBatchLoader(ds, 8, 32, seed=5)
    for _ in range(3):
        next(loader2)
    state = loader2.state()
    resumed = ShardedBatchLoader(ds, 8, 32, seed=5)
    resumed.restore(state)
    for i in range(3, 6):
        x, y = next(resumed)
        np.testing.assert_array_equal(x, stream[i][0])
        np.testing.assert_array_equal(y, stream[i][1])
    with pytest.raises(ValueError, match="seed"):
        ShardedBatchLoader(ds, 8, 32, seed=6).restore(state)


def test_loader_validates_sizes():
    ds = _toy_dataset(n=100)
    with pytest.raises(ValueError, match="divisible"):
        ShardedBatchLoader(ds, 8, 16, process_count=3)
    with pytest.raises(ValueError, match="windows"):
        ShardedBatchLoader(ds, 8, 16)  # only 6 windows of 16 fit in 100


def test_prefetch_matches_sync_and_propagates_errors():
    ds = _toy_dataset()
    sync = ShardedBatchLoader(ds, 8, 32, seed=2)
    pre = PrefetchLoader(ShardedBatchLoader(ds, 8, 32, seed=2))
    for _ in range(5):
        (xs, ys), (xp, yp) = next(sync), next(pre)
        np.testing.assert_array_equal(xs, xp)
        np.testing.assert_array_equal(ys, yp)
    pre.close()

    def boom():
        yield (np.zeros(1), np.zeros(1))
        raise RuntimeError("disk on fire")

    it = PrefetchLoader(boom())
    next(it)
    with pytest.raises(RuntimeError, match="disk on fire"):
        next(it)


def test_device_put_sharded_batch_on_mesh():
    import jax
    from tony_tpu.parallel import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(data=2, fsdp=4))
    ds = _toy_dataset()
    loader = ShardedBatchLoader(ds, 8, 32)
    x, y = next(loader)
    gx, gy = device_put_sharded_batch((x, y), mesh)
    assert gx.shape == (8, 32)
    assert not gx.sharding.is_fully_replicated
    np.testing.assert_array_equal(np.asarray(gx), x)
    # feeds straight into a jitted mean without resharding errors
    assert np.isfinite(float(jax.jit(lambda a: a.astype(np.float32).mean())(gx)))


def test_lm_train_example_consumes_token_file(tmp_path):
    """lm_train --data end-to-end on the CPU mesh: real loader feeding the
    sharded train step, metrics written, loss finite."""
    import json
    from tony_tpu.examples import lm_train

    rng = np.random.default_rng(0)
    path = write_tokens(tmp_path / "corpus.bin", rng.integers(0, 256, size=20000))
    out = tmp_path / "m.json"
    rc = lm_train.main([
        "--steps", "3", "--batch-size", "8", "--seq-len", "32",
        "--vocab", "256", "--d-model", "32", "--n-layers", "1",
        "--n-heads", "2", "--d-ff", "64", "--dtype", "float32",
        "--mesh", "data=2,fsdp=4", "--data", str(path),
        "--metrics-out", str(out),
    ])
    assert rc == 0
    metrics = json.loads(out.read_text())
    assert np.isfinite(metrics["final_loss"])
    assert metrics["mesh"]["data"] == 2 and metrics["mesh"]["fsdp"] == 4


def test_append_uses_file_header_dtype(tmp_path):
    """Appending to an existing file must honor the header dtype (mixing
    widths would corrupt the memmap) and range-check against it."""
    path = write_tokens(tmp_path / "c.bin", [1, 2, 3])  # uint16 header
    write_tokens(path, [4, 5], dtype=np.uint32)  # coerced to file's uint16
    ds = TokenDataset.from_bin(path)
    np.testing.assert_array_equal(ds.window(0, 5), [1, 2, 3, 4, 5])
    with pytest.raises(ValueError, match="uint16"):
        write_tokens(path, [70000], dtype=np.uint32)


def test_prefetch_terminal_state_does_not_hang():
    """After StopIteration/error, further next() calls must re-raise
    immediately instead of blocking on an empty queue forever."""
    it = PrefetchLoader(iter([(np.zeros(1), np.zeros(1))]))
    next(it)
    for _ in range(3):
        with pytest.raises(StopIteration):
            next(it)

    def boom():
        raise RuntimeError("dead disk")
        yield  # pragma: no cover

    bad = PrefetchLoader(boom())
    for _ in range(3):
        with pytest.raises(RuntimeError, match="dead disk"):
            next(bad)


def test_prefetch_state_counts_consumed_not_produced():
    """The producer runs ahead; PrefetchLoader.state() must reflect batches
    the consumer actually saw so checkpoint/restore doesn't skip data."""
    import time as _time

    ds = _toy_dataset()
    inner = ShardedBatchLoader(ds, 8, 32, seed=4)
    pre = PrefetchLoader(inner, depth=2)
    consumed = [next(pre) for _ in range(3)]
    _time.sleep(0.2)  # let the producer run ahead
    assert inner.step > 3  # producer genuinely ahead
    state = pre.state()
    assert state["step"] == 3
    pre.close()

    resumed = ShardedBatchLoader(ds, 8, 32, seed=4)
    resumed.restore(state)
    x_next, _ = next(resumed)
    # the first batch after restore is the first one the consumer never saw
    follow = ShardedBatchLoader(ds, 8, 32, seed=4)
    expected = follow.batch_at(3)[0]
    np.testing.assert_array_equal(x_next, expected)
    np.testing.assert_array_equal(consumed[0][0], follow.batch_at(0)[0])


def test_loader_shard_info_and_seed_validation(tmp_path):
    from tony_tpu.parallel import MeshSpec, build_mesh
    from tony_tpu.data import loader_shard_info

    seq_mesh = build_mesh(MeshSpec(fsdp=1, seq=8))
    assert loader_shard_info(seq_mesh, 2, 4) == (0, 1)  # replicated contract
    dp_mesh = build_mesh(MeshSpec(data=2, fsdp=4))
    assert loader_shard_info(dp_mesh, 2, 4) == (2, 4)
    with pytest.raises(ValueError, match="seed"):
        ShardedBatchLoader(_toy_dataset(), 8, 32, seed=-1)


def test_seq_sharded_loader_contents():
    """Sequence shards concatenate bit-for-bit into the unsharded batch,
    and each shard reads only its slice — the data-plane half of ring/
    Ulysses SP at context lengths a host can't (or shouldn't) load whole."""
    ds = _toy_dataset()
    full = ShardedBatchLoader(ds, 8, 32, seed=7)
    fx, fy = full.batch_at(5)
    C = 4
    shards = [
        ShardedBatchLoader(ds, 8, 32, seed=7,
                           seq_shard_index=s, seq_shard_count=C)
        for s in range(C)
    ]
    parts = [sh.batch_at(5) for sh in shards]
    for s, (px, py) in enumerate(parts):
        assert px.shape == (8, 8)  # local_seq = 32/4
        np.testing.assert_array_equal(px, fx[:, s * 8:(s + 1) * 8])
        np.testing.assert_array_equal(py, fy[:, s * 8:(s + 1) * 8])
    np.testing.assert_array_equal(
        np.concatenate([p[0] for p in parts], axis=1), fx
    )
    np.testing.assert_array_equal(
        np.concatenate([p[1] for p in parts], axis=1), fy
    )
    # resume state round-trips the seq-shard addressing, and a mismatch is
    # rejected (it would silently change the stream)
    st = shards[1].state()
    shards[1].restore(st)
    with pytest.raises(ValueError, match="seq_shard_index"):
        shards[2].restore(st)
    with pytest.raises(ValueError, match="divisible"):
        ShardedBatchLoader(ds, 8, 32, seq_shard_count=5)


def test_seq_shard_info_from_mesh():
    """seq_shard_info maps a process's devices to the seq-axis block it
    should load."""
    from tony_tpu.parallel import MeshSpec, build_mesh
    from tony_tpu.data import seq_shard_info

    mesh = build_mesh(MeshSpec(fsdp=1, seq=8))
    # single process owning everything -> load the full sequence
    assert seq_shard_info(mesh, 0) == (0, 1)
    # simulate 4 hosts of 2 devices tiling the seq axis contiguously:
    # device at seq coord c belongs to process c // 2
    coord = {id(d): i for i, d in enumerate(mesh.devices.flat)}
    dp = lambda d: coord[id(d)] // 2
    assert seq_shard_info(mesh, 0, device_process=dp) == (0, 4)
    assert seq_shard_info(mesh, 3, device_process=dp) == (3, 4)
    # interleaved layout (process owns coords {0, 4}) must be rejected
    dp_bad = lambda d: coord[id(d)] % 4
    with pytest.raises(ValueError, match="non-contiguous"):
        seq_shard_info(mesh, 0, device_process=dp_bad)
    # no seq axis -> no seq sharding
    dp_mesh = build_mesh(MeshSpec(data=2, fsdp=4))
    assert seq_shard_info(dp_mesh, 0) == (0, 1)


def test_token_file_rejects_future_version(tmp_path):
    p = write_tokens(tmp_path / "v.bin", [1, 2, 3])
    raw = bytearray(p.read_bytes())
    raw[4:8] = (99).to_bytes(4, "little")
    p.write_bytes(bytes(raw))
    with pytest.raises(ValueError, match="version"):
        TokenDataset.from_bin(p)


def test_write_tokens_rejects_negative():
    import tempfile, pathlib
    with tempfile.TemporaryDirectory() as td:
        with pytest.raises(ValueError, match="negative"):
            write_tokens(pathlib.Path(td) / "n.bin", [-1, 5])


def test_prefetch_close_with_blocked_producer_depth1():
    """depth=1 close() while the producer is blocked on a full queue must
    not leave the thread alive (regression: final _DONE put deadlocked)."""
    def forever():
        i = 0
        while True:
            yield i
            i += 1

    pre = PrefetchLoader(forever(), depth=1)
    next(pre)
    pre.close()
    assert not pre._thread.is_alive()


def test_batch_axes_follow_rules_table():
    from tony_tpu.parallel import MeshSpec, build_mesh, DP_RULES
    from tony_tpu.data import sharded_batch_axes, loader_shard_info, BATCH_AXES

    assert BATCH_AXES == tuple(DP_RULES["batch"])  # single source of truth
    mesh = build_mesh(MeshSpec(data=2, fsdp=4))
    # custom rules that consume batch over data only
    rules = {"batch": ("data",)}
    assert sharded_batch_axes(mesh, rules=rules) == ("data",)
    assert loader_shard_info(mesh, 1, 2, rules={"batch": ()}) == (0, 1)


def test_max_token_scans_whole_stream(tmp_path):
    toks = np.zeros(5000, dtype=np.int64)
    toks[4999] = 300  # id at the very end must be found
    p = write_tokens(tmp_path / "t.bin", toks)
    ds = TokenDataset.from_bin(p)
    assert ds._header_max == 300  # write_tokens caches it -> O(1) validation
    assert ds.max_token() == 300
    # files from other writers (field = 0) fall back to the full chunked scan
    raw = bytearray(p.read_bytes())
    raw[12:16] = b"\x00" * 4
    p.write_bytes(bytes(raw))
    ds2 = TokenDataset.from_bin(p)
    assert ds2._header_max is None
    assert ds2.max_token(chunk=64) == 300
    # append keeps the cached max current
    write_tokens(tmp_path / "t2.bin", [5])
    write_tokens(tmp_path / "t2.bin", [9, 2])
    assert TokenDataset.from_bin(tmp_path / "t2.bin").max_token() == 9


def test_lm_train_data_on_seq_mesh(tmp_path):
    """Regression: --data with a sequence-parallel mesh must place batches
    with the step's P(batch, seq) sharding (a batch-only spec crashed jit)."""
    import json
    from tony_tpu.examples import lm_train

    rng = np.random.default_rng(1)
    path = write_tokens(tmp_path / "c.bin", rng.integers(0, 128, size=40000))
    out = tmp_path / "m.json"
    rc = lm_train.main([
        "--steps", "2", "--batch-size", "2", "--seq-len", "64",
        "--vocab", "128", "--d-model", "32", "--n-layers", "1",
        "--n-heads", "2", "--d-ff", "64", "--dtype", "float32",
        "--mesh", "seq=8", "--data", str(path), "--metrics-out", str(out),
    ])
    assert rc == 0
    assert np.isfinite(json.loads(out.read_text())["final_loss"])


def test_restore_validates_stream_addressing_fields():
    ds = _toy_dataset()
    state = ShardedBatchLoader(ds, 8, 32, seed=5).state()
    with pytest.raises(ValueError, match="global_batch"):
        ShardedBatchLoader(ds, 4, 32, seed=5).restore(state)
    with pytest.raises(ValueError, match="seq_len"):
        ShardedBatchLoader(ds, 8, 16, seed=5).restore(state)


def test_device_put_handles_mixed_rank_leaves():
    """1-D per-example leaves (lengths/weights) must get a batch-only spec,
    not a rank-2 spec that crashes placement."""
    from tony_tpu.parallel import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(data=2, fsdp=4))
    batch = {"tokens": np.zeros((8, 16), np.int32),
             "weights": np.ones((8,), np.float32)}
    placed = device_put_sharded_batch(batch, mesh)
    assert placed["tokens"].shape == (8, 16)
    assert placed["weights"].shape == (8,)


def test_from_raw_headerless_stream(tmp_path):
    """nanoGPT-style raw uint16 files load via from_raw; lm_train falls back
    to it automatically when the TTPU magic is absent."""
    import json
    from tony_tpu.examples import lm_train

    toks = np.random.default_rng(3).integers(0, 200, size=30000).astype(np.uint16)
    p = tmp_path / "raw.bin"
    p.write_bytes(toks.tobytes())
    ds = TokenDataset.from_raw(p)
    assert len(ds) == 30000
    np.testing.assert_array_equal(ds.window(0, 10), toks[:10].astype(np.int32))
    assert ds.max_token() == int(toks.max())

    out = tmp_path / "m.json"
    rc = lm_train.main([
        "--steps", "2", "--batch-size", "8", "--seq-len", "32",
        "--vocab", "256", "--d-model", "32", "--n-layers", "1",
        "--n-heads", "2", "--d-ff", "64", "--dtype", "float32",
        "--mesh", "data=2,fsdp=4", "--data", str(p), "--metrics-out", str(out),
    ])
    assert rc == 0
    assert np.isfinite(json.loads(out.read_text())["final_loss"])


def test_bad_ttpu_header_not_reinterpreted_as_raw(tmp_path):
    """A TTPU file with an unsupported version must error in lm_train, not
    silently decode its header bytes as tokens via the raw fallback."""
    from tony_tpu.data.dataset import has_ttpu_magic
    from tony_tpu.examples import lm_train

    p = write_tokens(tmp_path / "v.bin", np.zeros(30000, dtype=np.int64))
    raw = bytearray(p.read_bytes())
    raw[4:8] = (99).to_bytes(4, "little")
    p.write_bytes(bytes(raw))
    assert has_ttpu_magic(p)
    with pytest.raises(ValueError, match="version"):
        lm_train.main([
            "--steps", "1", "--batch-size", "8", "--seq-len", "32",
            "--vocab", "256", "--d-model", "32", "--n-layers", "1",
            "--n-heads", "2", "--d-ff", "64", "--dtype", "float32",
            "--mesh", "data=2,fsdp=4", "--data", str(p),
        ])


def test_dataset_split_views(tmp_path):
    ds = _toy_dataset(n=1000)
    train, val = ds.split(0.1)
    assert len(train) == 900 and len(val) == 100
    np.testing.assert_array_equal(val.window(0, 5), ds.window(900, 5))
    with pytest.raises(ValueError, match="holdout_frac"):
        ds.split(1.5)


def test_lm_train_eval_split(tmp_path):
    """--eval-every reports held-out loss/ppl in metrics (train/serve loop
    parity with real frameworks)."""
    import json
    from tony_tpu.examples import lm_train

    rng = np.random.default_rng(5)
    path = write_tokens(tmp_path / "c.bin", rng.integers(0, 128, size=60000))
    out = tmp_path / "m.json"
    rc = lm_train.main([
        "--steps", "4", "--batch-size", "8", "--seq-len", "32",
        "--vocab", "128", "--d-model", "32", "--n-layers", "1",
        "--n-heads", "2", "--d-ff", "64", "--dtype", "float32",
        "--mesh", "data=2,fsdp=4", "--data", str(path),
        "--eval-every", "2", "--eval-batches", "2",
        "--metrics-out", str(out),
    ])
    assert rc == 0
    metrics = json.loads(out.read_text())
    assert np.isfinite(metrics["eval_loss"]) and metrics["eval_ppl"] > 1
