"""RPC transport tests: dispatch, auth, retry, concurrency."""

import threading

import pytest

from tony_tpu.rpc import RpcClient, RpcError, RpcServer


def make_server(token=""):
    server = RpcServer(token=token)
    server.register("echo", lambda **kw: kw)
    server.register("add", lambda a, b: a + b)
    def boom():
        raise ValueError("kaboom")
    server.register("boom", boom)
    server.start()
    return server


def test_roundtrip_and_error():
    server = make_server()
    try:
        client = RpcClient("127.0.0.1", server.port)
        assert client.call("add", a=2, b=3) == 5
        assert client.call("echo", x=[1, 2], y={"k": "v"}) == {"x": [1, 2], "y": {"k": "v"}}
        with pytest.raises(RpcError, match="kaboom"):
            client.call("boom")
        with pytest.raises(RpcError, match="unknown method"):
            client.call("nope")
        client.close()
    finally:
        server.stop()


def test_hmac_auth():
    server = make_server(token="s3cret")
    try:
        good = RpcClient("127.0.0.1", server.port, token="s3cret")
        assert good.call("add", a=1, b=1) == 2
        bad = RpcClient("127.0.0.1", server.port, token="wrong")
        with pytest.raises(RpcError, match="authentication"):
            bad.call("add", a=1, b=1)
        good.close(); bad.close()
    finally:
        server.stop()


def test_role_based_authorization():
    """Per-method ACL: an executor-signed finish_application is rejected
    (authorization), a client-signed one accepted; a caller signing the
    client role with the executor key fails authentication (the role claim
    is covered by the MAC, and keys are one-way per role)."""
    from tony_tpu.rpc.protocol import derive_role_key

    secret = "job-s3cret"
    roles = {
        "client": derive_role_key(secret, "client"),
        "executor": derive_role_key(secret, "executor"),
    }
    server = RpcServer(roles=roles, acl={"finish_application": {"client"}})
    server.register("finish_application", lambda: "done")
    server.register("heartbeat", lambda task_id: True)
    server.start()
    try:
        ex = RpcClient("127.0.0.1", server.port,
                       token=roles["executor"], role="executor")
        assert ex.call("heartbeat", task_id="w:0") is True
        with pytest.raises(RpcError, match="authorization failed"):
            ex.call("finish_application")
        # executor key + client role claim: authentication fails (can't
        # derive the client key from the executor key)
        forged = RpcClient("127.0.0.1", server.port,
                           token=roles["executor"], role="client")
        with pytest.raises(RpcError, match="authentication"):
            forged.call("finish_application")
        # unknown role claim
        nobody = RpcClient("127.0.0.1", server.port,
                           token=roles["executor"], role="admin")
        with pytest.raises(RpcError, match="authentication"):
            nobody.call("heartbeat", task_id="w:0")
        cl = RpcClient("127.0.0.1", server.port,
                       token=roles["client"], role="client")
        assert cl.call("finish_application") == "done"
        assert cl.call("heartbeat", task_id="w:0") is True  # not in ACL
        ex.close(); forged.close(); nobody.close(); cl.close()
    finally:
        server.stop()


def test_reconnect_after_server_restart():
    server = make_server()
    port = server.port
    client = RpcClient("127.0.0.1", port, max_retries=20)
    assert client.call("add", a=1, b=2) == 3
    server.stop()
    server2 = RpcServer(port=port)
    server2.register("add", lambda a, b: a + b)
    server2.start()
    try:
        assert client.call("add", a=5, b=5) == 10
    finally:
        client.close()
        server2.stop()


def test_concurrent_clients():
    server = make_server()
    results, errors = [], []

    def worker(i):
        try:
            c = RpcClient("127.0.0.1", server.port)
            for j in range(20):
                results.append(c.call("add", a=i, b=j))
            c.close()
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    server.stop()
    assert not errors
    assert len(results) == 160


def test_service_object_registration():
    class Svc:
        def hello(self, name):
            return f"hi {name}"
        def _private(self):
            return "no"

    server = RpcServer()
    server.register_service(Svc())
    server.start()
    try:
        c = RpcClient("127.0.0.1", server.port)
        assert c.call("hello", name="x") == "hi x"
        with pytest.raises(RpcError):
            c.call("_private")
        c.close()
    finally:
        server.stop()
