"""Paged KV allocator (models/serving.py, PR 16 tentpole).

The contract under test: swapping the slots x max_len ring cache for a
shared pool of block-tables is a SCHEDULING change, never a numerics
change — greedy completions are byte-identical to the ring engine in
every mode the ring serves (predictive, EOS, int8, prefix cache,
interleaved prefill) — plus the host-side lifecycle invariants that make
the pool safe to share: refcounts never orphan a block that a slot
table, the trie, or both still reach; cancelling mid-prefill returns
every block; admission defers on pool pressure instead of failing; and
the admission-tier machinery sheds queued batch work before refusing
interactive work. The allocator/trie story is pure host bookkeeping, so
the invariants are unit-tested without a model where possible.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import transformer
from tony_tpu.models.generate import generate
from tony_tpu.models.serving import (
    BlockAllocator, PrefixCache, QueueFullError, Request, SlotServer,
)

TINY = transformer.TransformerConfig(
    vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=128, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return transformer.init(jax.random.PRNGKey(0), TINY)


def _prompt(n, seed=3):
    return np.asarray(jax.random.randint(
        jax.random.PRNGKey(seed), (n,), 0, TINY.vocab_size), np.int32)


def _mk(params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_chunk", 8)
    return SlotServer(params, TINY, **kw)


def _reqs(n=5, max_new=10):
    return [Request(prompt=_prompt(7 + i, seed=i), max_new_tokens=max_new)
            for i in range(n)]


def _run(srv, reqs):
    for r in reqs:
        srv.submit(r)
    return srv.run_until_drained()


def _same(ring, paged):
    """Completion parity keyed by submission order (Request.id is a
    process-global counter, so ids differ between servers)."""
    rk, pk = sorted(ring), sorted(paged)
    assert len(rk) == len(pk)
    for a, b in zip(rk, pk):
        assert ring[a].tokens == paged[b].tokens, (a, b)
        assert ring[a].finish_reason == paged[b].finish_reason


# --------------------------------------------------- byte-identity


def test_paged_byte_identity_predictive_and_interleaved(params):
    """Ring vs paged vs paged-with-interleave on the same burst: the
    table engine and the chunked-prefill interleave cap reschedule
    work, they never change it."""
    ring = _run(_mk(params), _reqs())
    paged = _run(_mk(params, paged=True), _reqs())
    inter = _run(_mk(params, paged=True, prefill_interleave=4), _reqs())
    _same(ring, paged)
    _same(ring, inter)


def test_paged_byte_identity_eos_mode(params):
    """Stop tokens route through the non-predictive host loop — the
    paged gather/scatter view must land stops on the same token."""
    ring = _run(_mk(params, stop_tokens=(5,)), _reqs())
    paged = _run(_mk(params, stop_tokens=(5,), paged=True), _reqs())
    _same(ring, paged)


def test_paged_byte_identity_prefix_cache(params):
    """Shared-template burst with the trie on: paged serves trie hits
    zero-copy (the hit IS the block) yet completes byte-identically to
    the ring engine's copy-based prefix path."""
    tmpl = _prompt(24, seed=99)
    def preqs():
        return [Request(prompt=np.concatenate([tmpl, _prompt(3 + i,
                                                             seed=i)]),
                        max_new_tokens=8) for i in range(6)]
    ring = _run(_mk(params, prefix_cache_blocks=16), preqs())
    srv = _mk(params, prefix_cache_blocks=16, paged=True, kv_block=8)
    paged = _run(srv, preqs())
    _same(ring, paged)
    st = srv.stats()
    assert st["prefix_cache"]["hits"] > 0
    assert st["prefill_tokens_reused"] > 0
    srv._allocator.check()


def test_ring_to_table_migration_preserves_int8_carveout(params):
    """int8 KV under the table engine: ring vs paged stays EXACT (both
    chunk-prefill through the same quantized cache — the migration is
    block placement, not arithmetic), while vs solo generate() the
    existing quantization-tolerance carve-out holds unchanged: majority
    agreement, not bit-exactness (serving attends the quantized cache
    where generate's true prefill attends raw K/V; a near-tie at int8
    resolution can flip a greedy token)."""
    # one prompt LENGTH (varied content): solo generate() jits per
    # prompt shape, and four shapes would put this test near the tier-1
    # per-test wall budget for no extra coverage
    prompts = [_prompt(10, seed=40 + i) for i in range(4)]
    outs = {}
    for paged in (False, True):
        srv = _mk(params, kv_dtype="int8", paged=paged)
        done = _run(srv, [Request(prompt=p, max_new_tokens=5)
                          for p in prompts])
        outs[paged] = [done[k].tokens for k in sorted(done)]
        if paged:
            srv._allocator.check()
    assert outs[False] == outs[True], "migration changed int8 numerics"
    refs = [
        [int(t) for t in np.asarray(generate(
            params, TINY, jnp.asarray(p)[None], 5, kv_dtype="int8"))[0]]
        for p in prompts
    ]
    agree = sum(t == r for t, r in zip(outs[True], refs))
    assert agree * 2 >= len(refs), (outs[True], refs)


# ----------------------------------------- pool lifecycle invariants


def test_pool_gated_admission_small_pool_defers_and_completes(params):
    """A pool far below slots x max_len: admission defers on free
    blocks instead of failing, every request still completes, and the
    pool drains back to empty with the refcount invariant intact."""
    srv = _mk(params, paged=True, kv_block=8, kv_pool_blocks=8)
    done = _run(srv, _reqs(6))
    assert len(done) == 6
    assert all(c.finish_reason in ("stop", "length")
               for c in done.values())
    st = srv.stats()["paged_kv"]
    assert st["pool_blocks_free"] == 8
    assert st["pool_blocks_used"] == 0
    assert st["admission_defers"] > 0
    srv._allocator.check()


def test_cancel_mid_prefill_frees_blocks(params):
    """Cancel a request whose prompt is still queued in
    _pending_prefill (interleave cap = 2 tokens/turn guarantees chunks
    remain pending after the first step): the cancellation must deliver
    finish_reason "cancelled" AND return every block it held — pool
    empty, no orphans — once the survivors drain."""
    srv = _mk(params, paged=True, prefill_interleave=2)
    reqs = [Request(prompt=_prompt(20 + 4 * i, seed=i), max_new_tokens=6)
            for i in range(3)]
    for r in reqs:
        srv.submit(r)
    srv.step()
    pend = [p[0].req.id for p in srv._pending_prefill]
    assert pend, "interleave cap should leave chunks pending"
    rid = pend[0]
    assert srv.cancel(rid)
    comp = srv.drain_completed()[rid]
    assert comp.finish_reason == "cancelled"
    srv.run_until_drained()
    assert srv.stats()["paged_kv"]["pool_blocks_used"] == 0
    srv._allocator.check()


def test_trie_reclaim_under_pool_pressure_never_orphans(params):
    """A pool sized so cached prefixes must be reclaimed to admit new
    requests: the trie yields only sole-owner leaves (blocks still in a
    slot's table are skipped), completions stay byte-identical to the
    ring+trie engine, and after the drain every block is accounted for."""
    tmpl = _prompt(24, seed=77)
    def preqs():
        return [Request(prompt=np.concatenate([tmpl, _prompt(4 + i,
                                                             seed=i)]),
                        max_new_tokens=6) for i in range(6)]
    ring = _run(_mk(params, prefix_cache_blocks=8), preqs())
    srv = _mk(params, paged=True, kv_block=8, kv_pool_blocks=10,
              prefix_cache_blocks=8)
    paged = _run(srv, preqs())
    _same(ring, paged)
    st = srv.stats()
    assert st["paged_kv"]["admission_defers"] > 0, \
        "pool never came under pressure — the reclaim path was not hit"
    # whatever the trie still caches is exactly what the pool holds
    assert (st["paged_kv"]["pool_blocks_used"]
            == st["prefix_cache"]["blocks_used"])
    srv._allocator.check()


# --------------------------------------------------- host-only units


def test_block_allocator_refcount_invariant():
    alloc = BlockAllocator(4)
    blocks = alloc.alloc_for("interactive", 2)
    assert len(blocks) == 2 and alloc.free_blocks == 2
    alloc.ref(blocks[0])                    # shared with the trie
    alloc.unref(blocks[0])                  # slot table lets go
    assert alloc.free_blocks == 2           # trie ref keeps it alive
    alloc.unref(blocks[0])
    assert alloc.free_blocks == 3           # last holder frees
    alloc.check()
    with pytest.raises(AssertionError, match="underflow"):
        alloc.unref(blocks[0])


def test_block_allocator_class_budget_all_or_nothing():
    alloc = BlockAllocator(8, {"batch": 3})
    assert alloc.alloc_for("batch", 4) is None      # over budget: nothing
    got = alloc.alloc_for("batch", 3)
    assert len(got) == 3
    assert alloc.alloc_for("batch", 1) is None      # budget exhausted
    assert len(alloc.alloc_for("interactive", 5)) == 5  # other tier fine
    alloc.credit("batch", 3)
    for b in got:
        alloc.unref(b)
    assert len(alloc.alloc_for("batch", 3)) == 3    # credit reopens it
    with pytest.raises(ValueError, match="unknown priority class"):
        BlockAllocator(4, {"bulk": 2})


def test_trie_eviction_skips_slot_shared_blocks():
    """Unit-level PrefixCache+allocator: a leaf whose block a slot
    table still references (allocator refcount > 1) is not evictable —
    handing it to a new writer would corrupt the reader's KV."""
    alloc = BlockAllocator(4)
    trie = PrefixCache(4, chunk=2, allocator=alloc)
    body = np.asarray([1, 2, 3, 4], np.int32)
    blocks = alloc.alloc_for("interactive", 2)
    assert trie.adopt(body, {0: blocks[0], 1: blocks[1]}) == 2
    # slot releases its table refs; the trie solely owns both blocks
    for b in blocks:
        alloc.unref(b)
    # a new slot hits chunk 0 and holds its block again
    path = trie.lookup(body)
    assert [n.block for n in path] == blocks
    alloc.ref(blocks[0])
    assert trie.reclaim(4) == 1             # only the sole-owner leaf
    assert alloc.refs[blocks[0]] == 2       # shared leaf survived intact
    trie.reclaim(0)
    alloc.unref(blocks[0])                  # slot table lets go...
    assert trie.reclaim(4) == 1             # ...now it is reclaimable
    assert alloc.free_blocks == 4
    alloc.check()


# ------------------------------------------------- tiers & carve-outs


def test_class_budgets_shed_order_and_retry_after(params):
    """Queue pressure with both tiers queued: queued batch work is
    displaced (finish_reason "shed") to make room for interactive
    arrivals before any interactive request is refused, and a refusal
    carries the engine-derived Retry-After + the refused class."""
    srv = _mk(params, paged=True, max_queue=4, batch_queue_frac=0.5)
    # two long-running occupants pin both slots
    occ = [Request(prompt=_prompt(8, seed=90 + i), max_new_tokens=12)
           for i in range(2)]
    for r in occ:
        srv.submit(r)
    for _ in range(4):
        srv.step()
    refused = {"batch": 0, "interactive": 0}
    for i in range(3):
        try:
            srv.submit(Request(prompt=_prompt(6, seed=i),
                               max_new_tokens=4, priority="batch"))
        except QueueFullError:
            refused["batch"] += 1
    retry_afters = []
    for i in range(5):
        try:
            srv.submit(Request(prompt=_prompt(6, seed=10 + i),
                               max_new_tokens=4, priority="interactive"))
        except QueueFullError as exc:
            refused["interactive"] += 1
            assert exc.priority == "interactive"
            retry_afters.append(exc.retry_after_s)
    done = srv.run_until_drained()
    shed = [c for c in done.values() if c.finish_reason == "shed"]
    st = srv.stats()
    # batch pays first: displaced from the queue before interactive 429s
    assert refused["batch"] >= 1            # batch-queue cap refuses
    assert len(shed) >= 1                   # queued batch displaced
    assert st["shed_by_class"]["batch"] >= len(shed)
    assert all(isinstance(s, int) and 1 <= s <= 60 for s in retry_afters)
    ok = [c for c in done.values() if c.finish_reason in ("stop",
                                                          "length")]
    assert "failed" not in {c.finish_reason for c in done.values()}
    # every admitted-or-queued request is accounted for: occupants +
    # accepted interactive + accepted batch - displaced
    assert len(ok) == (2 + (5 - refused["interactive"])
                       + (3 - refused["batch"]) - len(shed))
    srv._allocator.check()


def test_paged_mode_constructor_carveouts(params):
    """The documented incompatibilities fail loudly at construction.
    (The PR 16 spec/mesh carve-outs are gone — paged now composes with
    both; see the byte-identity tests — so the remaining refusals are
    the structural ones plus the disaggregation role rules.)"""
    with pytest.raises(ValueError, match="multiple of"):
        _mk(params, paged=True, max_len=60, kv_block=8)
    with pytest.raises(ValueError, match="prefill_chunk"):
        _mk(params, paged=True, prefill_chunk=10, kv_block=4)
    with pytest.raises(ValueError, match="requires paged"):
        _mk(params, prefill_interleave=2)
    with pytest.raises(ValueError, match="requires paged"):
        _mk(params, class_budgets={"batch": 4})
    with pytest.raises(ValueError, match="role"):
        _mk(params, paged=True, role="verifier")
    with pytest.raises(ValueError, match="paged"):
        _mk(params, role="prefill")
    draft_cfg = transformer.TransformerConfig(
        vocab_size=256, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=64, max_seq_len=128, dtype=jnp.float32)
    draft = transformer.init(jax.random.PRNGKey(1), draft_cfg)
    with pytest.raises(ValueError, match="prefill"):
        _mk(params, paged=True, role="prefill",
            draft=draft, draft_cfg=draft_cfg)


# ------------------------------------- disaggregated serving (PR 17)
# KV block transfer: a prefill-role replica exports finished block
# tables; a decode replica imports them and resumes byte-identically.
# The serde pair is pure host code, so damage modes are unit-tested
# without HTTP; the refcount invariants ride the same engines.


def _prefill_decode_pair(params, **kw):
    pre = _mk(params, paged=True, role="prefill", **kw)
    dec = _mk(params, paged=True, role="decode", **kw)
    return pre, dec


def _export_one(pre, prompt, max_new=8):
    """Prefill one request on a prefill-role server; return its payload."""
    r = Request(prompt=prompt, max_new_tokens=max_new)
    pre.submit(r)
    done = pre.run_until_drained()
    comp = done[r.id]
    assert comp.finish_reason == "prefilled" and comp.tokens == []
    return pre.export_blocks(r.id)


def test_kv_block_serialize_roundtrip_f32_and_int8(params):
    """serialize_kv_blocks <-> deserialize_kv_blocks is exact for both
    the f32 pool and the int8 pool (payload AND scales), and the wire
    payload carries exactly the pinned key set."""
    from tony_tpu.models.serving import (
        KV_IMPORT_KEYS, deserialize_kv_blocks,
    )

    for kv_dtype in (None, "int8"):
        kw = {"kv_dtype": kv_dtype} if kv_dtype else {}
        pre = _mk(params, paged=True, role="prefill", **kw)
        payload = _export_one(pre, _prompt(11, seed=41))
        assert set(payload) == set(KV_IMPORT_KEYS)
        k, v, ks, vs = deserialize_kv_blocks(payload)
        assert k.shape == v.shape and k.shape[1] == payload["n_blocks"]
        if kv_dtype == "int8":
            assert k.dtype == np.int8 and ks is not None and vs is not None
            assert ks.shape == k.shape[:4]
        else:
            assert ks is None and vs is None
        # a JSON round trip (the wire format) changes nothing
        import json as _json

        k2, v2, ks2, vs2 = deserialize_kv_blocks(
            _json.loads(_json.dumps(payload)))
        np.testing.assert_array_equal(k, k2)
        np.testing.assert_array_equal(v, v2)
        if ks is not None:
            np.testing.assert_array_equal(ks, ks2)
            np.testing.assert_array_equal(vs, vs2)


def test_export_import_byte_identity_and_refcounts(params):
    """THE transfer contract: prefill on one engine, decode on another,
    tokens byte-identical to a solo paged engine. Refcount invariants:
    the exporter's pool drains back to fully free (the snapshot is host
    bytes — export never leaks a block), and both allocators pass
    check() after the handoff."""
    prompts = [_prompt(9, seed=11), _prompt(13, seed=12)]
    solo = _run(_mk(params, paged=True),
                [Request(prompt=p, max_new_tokens=8) for p in prompts])
    solo_toks = [solo[key].tokens for key in sorted(solo)]

    pre, dec = _prefill_decode_pair(params)
    total = pre.stats()["paged_kv"]["pool_blocks_total"]
    payloads = [_export_one(pre, p) for p in prompts]
    st = pre.stats()["paged_kv"]
    assert st["kv_exports"] == 2
    assert st["pool_blocks_free"] == total, (
        "export must free the prefill replica's blocks")
    pre._allocator.check()

    rids = [dec.import_blocks(pl) for pl in payloads]
    done = dec.run_until_drained()
    assert [done[r].tokens for r in rids] == solo_toks
    assert dec.stats()["paged_kv"]["kv_imports"] == 2
    dec._allocator.check()
    # pool_state partitions the pool: the four owner states sum to total
    ps = dec.stats()["paged_kv"]["pool_state"]
    assert set(ps) == {"free", "slot", "trie", "shared"}
    assert sum(ps.values()) == dec.stats()["paged_kv"]["pool_blocks_total"]


def test_import_rejects_damage_loudly_then_replays(params):
    """The torn-transfer contract: every damage mode raises ValueError
    (counted in kv_import_rejects), the importer's pool is untouched,
    and the fallback—re-prefilling from the entry's prompt, the journal
    replay story—still completes byte-identically."""
    prompt = _prompt(10, seed=51)
    solo = _run(_mk(params, paged=True),
                [Request(prompt=prompt, max_new_tokens=8)])
    solo_toks = [solo[key].tokens for key in sorted(solo)]

    pre, dec = _prefill_decode_pair(params)
    payload = _export_one(pre, prompt)
    free0 = dec.stats()["paged_kv"]["pool_blocks_free"]

    damaged = []
    p = dict(payload); p["version"] = 99
    damaged.append(("version", p))
    p = dict(payload); p["model"] = "other-model"
    damaged.append(("model", p))
    p = dict(payload); p["kv_block"] = 16
    damaged.append(("kv_block", p))
    p = dict(payload); p["blocks_k"] = p["blocks_k"][:-24]  # truncated
    damaged.append(("truncated", p))
    raw = bytearray(__import__("base64").b64decode(payload["blocks_v"]))
    raw[0] ^= 0xFF                                          # bit flip
    p = dict(payload)
    p["blocks_v"] = __import__("base64").b64encode(bytes(raw)).decode()
    damaged.append(("checksum", p))
    p = dict(payload); p["entry"] = None
    damaged.append(("entry", p))
    for name, bad in damaged:
        with pytest.raises(ValueError):
            dec.import_blocks(bad)
    st = dec.stats()["paged_kv"]
    assert st["kv_import_rejects"] == len(damaged)
    assert st["kv_imports"] == 0
    assert st["pool_blocks_free"] == free0, (
        "a rejected import must not leak pool blocks")
    dec._allocator.check()
    # the fallback leg: re-prefill from the entry's replay state
    entry = payload["entry"]
    fb = Request(prompt=np.asarray(entry["prompt"], np.int32),
                 max_new_tokens=entry["max_new_tokens"])
    dec.submit(fb)
    done = dec.run_until_drained()
    assert [done[fb.id].tokens] == solo_toks
    dec._allocator.check()


def test_import_backpressure_is_queue_full(params):
    """A handoff needs a seat NOW: with every slot busy, import_blocks
    raises QueueFullError (with a Retry-After estimate) instead of
    queueing — queueing would hide the decode tier's backpressure from
    the router."""
    pre, dec = _prefill_decode_pair(params)
    payloads = [_export_one(pre, _prompt(9 + i, seed=60 + i),
                            max_new=24) for i in range(3)]
    dec.import_blocks(payloads[0])
    dec.import_blocks(payloads[1])       # both slots now busy
    with pytest.raises(QueueFullError) as ei:
        dec.import_blocks(payloads[2])
    assert getattr(ei.value, "retry_after_s", 0) > 0
    assert dec.stats()["paged_kv"]["kv_import_rejects"] == 0, (
        "backpressure is not damage")
    dec.run_until_drained()
    dec._allocator.check()


def test_spec_paged_byte_identity(params):
    """PR 16 carve-out closed: speculative decoding on the paged pool
    (target + draft pools under one allocator, forced-sync rounds) is
    byte-identical to speculative decoding on the ring engine."""
    draft_cfg = transformer.TransformerConfig(
        vocab_size=256, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=64, max_seq_len=128, dtype=jnp.float32)
    draft = transformer.init(jax.random.PRNGKey(1), draft_cfg)

    def sreqs():
        return [Request(prompt=_prompt(7 + i, seed=20 + i),
                        max_new_tokens=8) for i in range(3)]

    ring = _run(_mk(params, draft=draft, draft_cfg=draft_cfg,
                    stop_tokens=(5,)), sreqs())
    srv = _mk(params, draft=draft, draft_cfg=draft_cfg,
              stop_tokens=(5,), paged=True)
    paged = _run(srv, sreqs())
    _same(ring, paged)
    assert srv.stats()["speculative"]["rounds"] > 0
    srv._allocator.check()


def test_paged_mesh_byte_identity(params):
    """PR 16 carve-out closed: the paged pool under a (data=2, tensor=2)
    mesh — pool sharded over its block axis like the ring cache's batch
    axis — is byte-identical to the single-device paged engine."""
    from tony_tpu.parallel import MeshSpec, build_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 forced host devices")
    prompts = [_prompt(9, seed=11), _prompt(13, seed=12)]
    solo = _run(_mk(params, paged=True),
                [Request(prompt=p, max_new_tokens=8) for p in prompts])
    solo_toks = [solo[key].tokens for key in sorted(solo)]
    mesh = build_mesh(MeshSpec(data=2, fsdp=1, tensor=2),
                      devices=jax.devices()[:4])
    msrv = SlotServer(params, TINY, slots=4, max_len=64, block_size=4,
                      prefill_chunk=8, paged=True, mesh=mesh)
    m = _run(msrv, [Request(prompt=p, max_new_tokens=8) for p in prompts])
    assert [m[key].tokens for key in sorted(m)] == solo_toks
    msrv._allocator.check()
