"""Router-tier HA (docs/serving.md "Router tier HA").

The shared-nothing contract under test, bottom-up: K independently
constructed FleetRouters — distinct instance nonces, shuffled discovery
orderings, divergent load views — must agree on every keyed pick AND
the full spill order, because the rendezvous ranking is a pure function
of (affinity key, replica NAME) and nothing else. Then the
request-survival machinery a router death leans on: a surviving router
harvests a dead peer's journaled progress from the owning replica via
the portable ``req:<request_id>`` key and teacher-forces the exact
prefix once; the drain contract (SIGTERM mirror of serve's) refuses
new front-door work while in-flight relays finish; and the ``tony-tpu
route`` process honors the deterministic SIGKILL injection knob the
router-HA bench drives.
"""

import json
import os
import random
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import ThreadingHTTPServer

import pytest

import tony_tpu.constants as c
from tony_tpu.router import FleetRouter, make_handler

from tests.test_router import StubReplica, _router, stubs  # noqa: F401

# --------------------------------------------------------------------------
# shared-nothing agreement (pure unit — no HTTP)
# --------------------------------------------------------------------------

NAMES = [f"replica:{i}" for i in range(6)]
ENDPOINTS = [(n, "127.0.0.1", 9000 + i) for i, n in enumerate(NAMES)]


def _fleet(k: int, rng: random.Random) -> list[FleetRouter]:
    """K shared-nothing routers over the same replica NAMES: shuffled
    endpoint orderings (discovery hands lists in arbitrary order),
    distinct seeds, and divergent load views (each router's inflight
    counts only its own relays)."""
    routers = []
    for _ in range(k):
        eps = list(ENDPOINTS)
        rng.shuffle(eps)
        r = FleetRouter(eps, prefill_chunk=4,
                        seed=rng.randrange(1 << 30))
        for rep in r.replicas.values():
            rep.queued = rng.randrange(10)
            rep.inflight = rng.randrange(10)
        routers.append(r)
    return routers


def test_k_router_affinity_agreement_property():
    """The tentpole's correctness core: N routers with zero shared
    state independently rank every keyed request identically — same
    owner, same runner-up spill order — across shuffled orderings,
    distinct nonces, and divergent load views. After an ejection, all
    routers agree again, and ONLY the ejected replica's keys move
    (each to its previous runner-up): rendezvous stability, the reason
    a router death never reshuffles the fleet's prefix caches."""
    rng = random.Random(18)
    routers = _fleet(5, rng)
    # the progress-key nonces really are per-instance (anti-splicing)
    assert len({r._nonce for r in routers}) == len(routers)

    prompts = [[rng.randrange(64) for _ in range(rng.randrange(4, 24))]
               for _ in range(40)]
    models = [None, "alpha", "beta"]
    cases = [(p, models[i % len(models)]) for i, p in enumerate(prompts)]

    before: dict[int, list[str]] = {}
    for i, (prompt, model) in enumerate(cases):
        key = routers[0].route_key(prompt, model)
        assert key is not None      # every case has a full block
        # model namespacing is part of the digest: same template,
        # different model -> (almost surely) different rendezvous bucket
        rankings = [[rep.name for rep in r._ranked_locked(key, model)]
                    for r in routers]
        assert all(rk == rankings[0] for rk in rankings), (
            f"case {i}: shared-nothing routers disagree: {rankings}")
        assert sorted(rankings[0]) == sorted(NAMES)
        before[i] = rankings[0]

    # eject one replica everywhere (each router notices independently)
    victim = before[0][0]
    for r in routers:
        r.replicas[victim].up = False
    for i, (prompt, model) in enumerate(cases):
        key = routers[0].route_key(prompt, model)
        rankings = [[rep.name for rep in r._ranked_locked(key, model)]
                    for r in routers]
        assert all(rk == rankings[0] for rk in rankings)
        # rendezvous stability: the ranking is the old one minus the
        # victim — non-victim keys keep their owner, the victim's keys
        # land exactly on their previous runner-up
        assert rankings[0] == [n for n in before[i] if n != victim]
        if before[i][0] == victim:
            assert rankings[0][0] == before[i][1]
        else:
            assert rankings[0][0] == before[i][0]


def test_route_key_is_model_namespaced_and_chunk_aligned():
    """Two models sharing a template must not collide on one bucket;
    prompts differing only past the last full block share a key."""
    r = FleetRouter(ENDPOINTS, prefill_chunk=4)
    base = [1, 2, 3, 4]
    assert r.route_key(base, "alpha") != r.route_key(base, "beta")
    assert r.route_key(base + [9]) == r.route_key(base + [7])
    assert r.route_key([1, 2, 3]) is None       # no full block


# --------------------------------------------------------------------------
# cross-router resume (stubs)
# --------------------------------------------------------------------------

def test_cross_router_resume_carries_journaled_prefix_once(stubs):  # noqa: F811
    """A front-door retry through a SURVIVING router (same client
    request_id) pre-polls the rendezvous owner's /progress under the
    portable ``req:<id>`` key and teacher-forces the dead router's
    journaled prefix EXACTLY once: the replica payload carries it as
    ``resume_tokens``, the response tokens start with it (serve-contract
    resume semantics: tokens include the prefix from position 0) and
    never repeat it."""
    a, b = stubs("a", "b")
    survivor = _router([a, b], prefill_chunk=4)
    survivor.health_tick()
    prompt = [1, 2, 3, 4, 5]
    owner = survivor._pick(survivor.route_key(prompt))
    owner_stub = a if owner.name == "a" else b
    # what the DEAD router's attempt journaled on the owning replica
    owner_stub.progress_tokens = [7, 8, 9]

    resp = survivor.generate(prompt, max_new_tokens=4, timeout_s=5,
                             request_id="req-abc.1")
    assert resp["replica"] == owner.name        # same rendezvous pick
    assert owner_stub.payloads[-1]["resume_tokens"] == [7, 8, 9]
    assert owner_stub.payloads[-1]["progress_key"] == "req:req-abc.1"
    # the prefix appears once, at the head — never duplicated
    assert resp["tokens"] == [7, 8, 9, len(prompt)]
    assert survivor.stats()["resumed_tokens"] == 3

    # no request_id -> private nonce key, no cross-router harvest
    resp = survivor.generate(prompt, max_new_tokens=4, timeout_s=5)
    assert "resume_tokens" not in owner_stub.payloads[-1]
    assert owner_stub.payloads[-1]["progress_key"].startswith(
        survivor._nonce + ":")
    assert resp["tokens"] == [len(prompt)]

    # journal already sealed (replica answers {}): fresh request, no
    # resume — the poll costs nothing else
    owner_stub.progress_tokens = None
    resp = survivor.generate(prompt, max_new_tokens=4, timeout_s=5,
                             request_id="req-abc.1")
    assert "resume_tokens" not in owner_stub.payloads[-1]
    assert resp["tokens"] == [len(prompt)]
    assert survivor.stats()["resumed_tokens"] == 3  # unchanged


def test_front_door_request_id_validation(stubs):  # noqa: F811
    """The HTTP front door accepts a sane request_id (it becomes a
    progress key fragment on replicas) and 400s hostile ones instead
    of letting them poison the journal namespace."""
    a = stubs("a")
    router = _router([a], prefill_chunk=4)
    router.health_tick()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(router))
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        def post(payload):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as r:
                return r.status, json.loads(r.read().decode())

        status, _ = post({"prompt": [1, 2, 3, 4], "max_new_tokens": 1,
                          "request_id": "Retry-7.of_9"})
        assert status == 200
        assert a.payloads[-1]["progress_key"] == "req:Retry-7.of_9"
        for bad in ("", "a b", "x" * 65, "né", "a,b"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                post({"prompt": [1, 2, 3, 4], "max_new_tokens": 1,
                      "request_id": bad})
            assert ei.value.code == 400
    finally:
        httpd.shutdown()
        httpd.server_close()


# --------------------------------------------------------------------------
# drain contract
# --------------------------------------------------------------------------

def test_drain_refuses_new_work_and_finishes_inflight(stubs):  # noqa: F811
    """begin_drain/drain (what the route CLI's SIGTERM handler runs):
    new front-door posts 503 with a retry-another-door hint, /healthz
    flips unhealthy (the LB eject signal), and drain() returns True
    only after every in-flight relay finished — zero-dropped scale-down
    by construction."""
    a = stubs("a")
    a.delay_s = 1.0
    router = _router([a], prefill_chunk=4)
    router.health_tick()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(router))
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{port}/generate"
    body = json.dumps({"prompt": [1, 2, 3, 4],
                       "max_new_tokens": 1}).encode()
    try:
        results: dict = {}

        def go():
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=15) as r:
                results["resp"] = json.loads(r.read().decode())

        t = threading.Thread(target=go)
        t.start()
        deadline = time.monotonic() + 5
        while (router.stats()["relay_inflight"] == 0
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert router.stats()["relay_inflight"] == 1

        drained: dict = {}
        dt = threading.Thread(
            target=lambda: drained.setdefault("ok", router.drain(15)))
        dt.start()
        deadline = time.monotonic() + 5
        while not router.draining and time.monotonic() < deadline:
            time.sleep(0.01)
        # draining: new posts are refused toward the other doors...
        with pytest.raises(urllib.error.HTTPError) as ei:
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 503
        # ... and /healthz ejects this router from the LB rotation
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10)
        assert ei.value.code == 503
        assert json.loads(ei.value.read().decode())["draining"] is True

        dt.join(timeout=15)
        t.join(timeout=15)
        assert drained["ok"] is True
        assert results["resp"]["finish_reason"] == "length"
        st = router.stats()
        assert st["relay_inflight"] == 0 and st["draining"] is True
        assert st["failed"] == 0
    finally:
        httpd.shutdown()
        httpd.server_close()


# --------------------------------------------------------------------------
# the route PROCESS: SIGKILL injection + SIGTERM drain (subprocess)
# --------------------------------------------------------------------------

def _spawn_route(stub, extra_env=None, extra_args=()):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("TONY_TEST_")}
    env.update({"JAX_PLATFORMS": "cpu", **(extra_env or {})})
    proc = subprocess.Popen(
        [sys.executable, "-m", "tony_tpu.cli.main", "route",
         "--port", "0", "--replica", f"127.0.0.1:{stub.port}",
         "--prefill-chunk", "4", "--health-interval-s", "0.2",
         *extra_args],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, env=env)
    deadline = time.monotonic() + 30
    port = None
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        m = re.search(r"routing on http://[^:]+:(\d+)", line)
        if m:
            port = int(m.group(1))
            break
    assert port, "route process never printed its readiness line"
    return proc, port


def _post(port, payload, timeout=15):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read().decode())


def test_route_sigkill_injection_kills_on_nth_request(stubs):  # noqa: F811
    """TONY_TEST_ROUTER_SIGKILL_AT_REQUEST=N: the route process
    SIGKILLs itself on RECEIPT of its Nth front-door generate request —
    before routing, so the client sees a severed connection, exactly
    the failure the router-HA bench's front-door retry must absorb."""
    a = stubs("a")
    proc, port = _spawn_route(
        a, extra_env={c.TEST_ROUTER_SIGKILL_AT_REQUEST: "2"})
    try:
        resp = _post(port, {"prompt": [1, 2, 3, 4], "max_new_tokens": 1})
        assert resp["finish_reason"] == "length"
        with pytest.raises((urllib.error.URLError, ConnectionError,
                            OSError)):
            _post(port, {"prompt": [1, 2, 3, 4], "max_new_tokens": 1})
        assert proc.wait(timeout=10) == -signal.SIGKILL
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)


def test_route_sigkill_injection_targets_task_index(stubs):  # noqa: F811
    """The "IDX#N" spelling arms the knob only on the router task whose
    TONY_TASK_INDEX matches — how the bench kills door 0 of a fleet
    that shares one tony.execution.env."""
    a = stubs("a")
    proc, port = _spawn_route(
        a, extra_env={c.TEST_ROUTER_SIGKILL_AT_REQUEST: "0#1",
                      c.ENV_TASK_INDEX: "1"})
    try:
        # index 1 ignores door 0's kill spec entirely
        for _ in range(3):
            resp = _post(port, {"prompt": [1, 2, 3, 4],
                                "max_new_tokens": 1})
            assert resp["finish_reason"] == "length"
        assert proc.poll() is None
    finally:
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=15) == 0


def test_route_sigterm_drains_inflight_then_exits_zero(stubs):  # noqa: F811
    """The satellite drain contract end-to-end: SIGTERM mid-relay ->
    the in-flight request still completes, new work is refused, and
    the process exits 0 (a scale-down, not a failure, against the
    driver's restart budget)."""
    a = stubs("a")
    a.delay_s = 1.5
    proc, port = _spawn_route(a, extra_args=("--drain-timeout-s", "20"))
    results: dict = {}

    def go():
        try:
            results["resp"] = _post(
                port, {"prompt": [1, 2, 3, 4], "max_new_tokens": 1},
                timeout=25)
        except Exception as e:      # pragma: no cover - failure detail
            results["err"] = e

    t = threading.Thread(target=go)
    try:
        t.start()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/stats", timeout=5) as r:
                if json.loads(r.read().decode())["relay_inflight"] > 0:
                    break
            time.sleep(0.02)
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=30) == 0
        t.join(timeout=30)
        assert results.get("err") is None, results["err"]
        assert results["resp"]["finish_reason"] == "length"
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        t.join(timeout=5)
