"""Task lifecycle traces + driver /metrics (the cluster-side half of the
observability layer — docs/observability.md "Task lifecycle traces").

The contract under test: every task the driver manages leaves a complete,
ordered, ALL-TERMINAL lifecycle trace in ``tasks.trace.jsonl`` (requested
-> allocated -> launched -> registered -> first_heartbeat -> running ->
finished|failed|killed|heartbeat_expired, with ``restarted`` marks and
the full chain repeating per attempt); the jhist stream embeds the same
records as TASK_TRACE events; executor-side spans shipped over
``update_metrics`` merge into the trace; and the driver's GET /metrics
renders gang-launch histograms, the heartbeat inter-arrival histogram,
restart/expiry counters, and the per-role straggler gauges in parseable
Prometheus text. Stub executors are threads speaking the real framed-JSON
RPC (the test_gang_scale pattern) so each scenario runs in ~a second.
"""

import json
import re
import threading
import time
import urllib.request
from pathlib import Path

import pytest

import tony_tpu.constants as c
from tony_tpu.api import JobStatus
from tony_tpu.cluster.provisioner import ContainerHandle, Provisioner
from tony_tpu.conf import TonyConf
from tony_tpu.driver import Driver
from tony_tpu.events.trace import TASK_TRACE_FILE, TraceWriter, read_traces
from tony_tpu.observability import TASK_TERMINAL_SPANS
from tony_tpu.rpc import RpcClient

# one exposition line: a comment, or name{labels} value (same golden
# regex as tests/test_observability.py)
_PROM_LINE = re.compile(
    r"^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+|"
    r"[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [^\s]+)$")


def _conf(dirs, **extra):
    return TonyConf({
        "tony.staging.dir": dirs["staging"],
        "tony.history.location": dirs["history"],
        "tony.history.intermediate": dirs["history"] + "/intermediate",
        "tony.history.finished": dirs["history"] + "/finished",
        "tony.am.monitor-interval-ms": 50,
        "tony.task.registration-poll-interval-ms": 50,
        **extra,
    })


def _span_names(rec):
    return [n for n, _ in rec["spans"]]


def _assert_ordered(rec):
    ts = [t for _, t in rec["spans"]]
    assert ts == sorted(ts), f"spans out of order: {rec['spans']}"


class ScriptedProvisioner(Provisioner):
    """launch() runs ``script(spec, index, env, handle, attempt)`` on a
    thread — each scenario scripts its executors' behavior; ``attempt``
    counts launches per task so restart scripts can branch."""

    def __init__(self, script):
        super().__init__()
        self._script = script
        self._attempts: dict[str, int] = {}
        self._lock = threading.Lock()
        self.launches: list[str] = []

    def launch(self, spec, index, env, log_dir):
        task_id = f"{spec.name}:{index}"
        with self._lock:
            attempt = self._attempts.get(task_id, 0)
            self._attempts[task_id] = attempt + 1
            self.launches.append(task_id)
        handle = ContainerHandle(
            container_id=f"stub_{task_id}_{attempt}",
            host="127.0.0.1", role=spec.name, index=index,
        )
        threading.Thread(
            target=self._run, args=(spec, index, env, handle, attempt),
            daemon=True,
        ).start()
        return handle

    def _run(self, spec, index, env, handle, attempt):
        try:
            code = self._script(spec, index, env, handle, attempt)
        except Exception as e:                  # pragma: no cover - debug aid
            print(f"stub executor failed: {type(e).__name__}: {e}",
                  flush=True)
            code = 1
        if code is not None and self.on_completion:
            self.on_completion(handle, code)

    def stop_container(self, handle):
        pass

    def stop_all(self):
        pass


def _driver(dirs, tmp_path, script, **conf_extra):
    conf = _conf(dirs, **conf_extra)
    job_dir = tmp_path / "job"
    job_dir.mkdir(exist_ok=True)
    conf.write_final(job_dir)
    driver = Driver(conf, app_id="trace_test", job_dir=str(job_dir),
                    token="trace-secret", provisioner=ScriptedProvisioner(script))
    driver.client_signal.set()      # no client: don't wait for the ack
    return driver


def _rpc_for(env):
    return RpcClient(env[c.ENV_DRIVER_HOST], int(env[c.ENV_DRIVER_PORT]),
                     token=env.get(c.ENV_TOKEN, ""), role="executor")


# --------------------------------------------------------------------------
# normal finish: full span chain, executor enrichment, live /metrics
# --------------------------------------------------------------------------

def test_task_trace_full_lifecycle_and_driver_metrics(tmp_job_dirs, tmp_path):
    """Two workers register, heartbeat, push metrics + executor spans,
    and finish. While they run the driver /metrics endpoint serves the
    gang-launch histogram, the heartbeat histogram, the straggler
    gauges, and the pushed per-task metrics; afterwards every trace in
    tasks.trace.jsonl is terminal 'finished' with the full ordered chain
    (executor spans merged in), and the jhist embeds TASK_TRACE events."""
    release = threading.Event()

    def script(spec, index, env, handle, attempt):
        rpc = _rpc_for(env)
        task_id = f"{spec.name}:{index}"
        payload = rpc.call("register_worker", task_id=task_id,
                           host="127.0.0.1", port=21000 + index)
        while payload is None:
            rpc.call("heartbeat", task_id=task_id)
            time.sleep(0.03)
            payload = rpc.call("get_cluster_spec", task_id=task_id)
        for _ in range(3):
            rpc.call("heartbeat", task_id=task_id)
            time.sleep(0.03)
        rpc.call("update_metrics", task_id=task_id,
                 metrics=[{"name": "max_memory_rss_mb", "value": 11.5}],
                 spans=[["work_dir_ready", time.time()],
                        ["child_spawned", time.time()]])
        assert release.wait(20), "test never released the stub executors"
        rpc.call("register_execution_result", task_id=task_id, exit_code=0)
        rpc.close()
        return 0

    driver = _driver(tmp_job_dirs, tmp_path, script,
                     **{"tony.worker.instances": 2,
                        "tony.worker.command": "stub",
                        "tony.task.heartbeat-interval-ms": 100})
    t = threading.Thread(target=driver.run, daemon=True)
    t.start()
    try:
        # wait for both registrations + the metrics push to land, then
        # scrape the live endpoint
        deadline = time.time() + 20
        text = ""
        while time.time() < deadline:
            port = driver.metrics_port
            if port is not None:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
                    assert r.status == 200
                    assert r.headers["Content-Type"].startswith("text/plain")
                    text = r.read().decode()
                if ('driver_gang_launch_seconds_count{role="worker"} 2'
                        in text
                        and 'driver_task_metric{task="worker:0",' in text
                        and 'driver_task_metric{task="worker:1",' in text):
                    break
            time.sleep(0.05)
        assert 'driver_gang_launch_seconds_count{role="worker"} 2' in text, (
            text[:3000])
        for line in text.strip().splitlines():
            assert _PROM_LINE.match(line), f"unparseable line: {line!r}"
        assert "driver_heartbeat_interval_seconds_bucket" in text
        assert "driver_task_restarts_total 0" in text
        assert "driver_heartbeat_expired_total 0" in text
        for gauge in ("driver_straggler_registration_s",
                      "driver_straggler_heartbeat_s"):
            for stat in ("max", "median"):
                assert f'{gauge}{{role="worker",stat="{stat}"}}' in text
        assert ('driver_task_metric{task="worker:0",'
                'name="max_memory_rss_mb"} 11.5' in text)
        # the advertised endpoint rides driver.json next to the RPC info
        info = json.loads((tmp_path / "job" / c.DRIVER_INFO_FILE).read_text())
        assert info["metrics_port"] == driver.metrics_port
    finally:
        release.set()
    t.join(timeout=30)
    assert not t.is_alive(), "driver did not finish"
    assert driver.session.status == JobStatus.SUCCEEDED, (
        driver.session.failure_message)

    inter = Path(tmp_job_dirs["history"]) / "intermediate" / "trace_test"
    recs = read_traces(inter / TASK_TRACE_FILE)
    assert {r["id"] for r in recs} == {"worker:0", "worker:1"}
    for rec in recs:
        names = _span_names(rec)
        assert names[-1] == "finished"
        # driver-observed chain. Ordering is pinned only where the code
        # sequences it: requested -> allocated -> launched are marked by
        # the launching thread in order, and first_heartbeat/running can
        # only follow registration. 'registered' (and for the gang's
        # LAST registrant even 'running') may interleave anywhere after
        # 'requested' — a fast executor registers while the launching
        # thread is still recording its marks, and the trace records
        # OBSERVATION order (the waterfall sorts by timestamp anyway).
        assert names[0] == "requested", names
        assert (names.index("requested") < names.index("allocated")
                < names.index("launched")), names
        assert "registered" in names[:5], names
        for span in ("first_heartbeat", "running"):
            assert names.index(span) > names.index("registered"), names
        # executor enrichment arrived over update_metrics
        assert "work_dir_ready" in names and "child_spawned" in names
        _assert_ordered(rec)
        assert rec["attrs"]["exit_code"] == 0
        assert rec["attrs"]["restarts"] == 0
    assert not driver.task_traces, "trace registry must drain with the tasks"

    jhist = next(iter(inter.glob("*.jhist")))
    events = [json.loads(l) for l in jhist.read_text().splitlines()]
    embedded = [e for e in events if e["type"] == "TASK_TRACE"]
    assert {e["payload"]["trace"]["id"] for e in embedded} == {
        "worker:0", "worker:1"}


# --------------------------------------------------------------------------
# restart budget: container exits spend it, the trace shows each attempt
# --------------------------------------------------------------------------

def test_task_trace_restart_budget_path(tmp_job_dirs, tmp_path):
    """A worker that crashes twice inside a max-restarts=2 budget, then
    succeeds: ONE trace carrying two 'restarted' marks, the
    requested->launched chain repeated per attempt, terminal 'finished',
    and driver_task_restarts_total == 2."""

    def script(spec, index, env, handle, attempt):
        time.sleep(0.05)
        return 1 if attempt < 2 else 0      # crash, crash, succeed

    driver = _driver(tmp_job_dirs, tmp_path, script,
                     **{"tony.worker.instances": 1,
                        "tony.worker.command": "stub",
                        "tony.worker.max-restarts": 2})
    status = driver.run()
    assert status == JobStatus.SUCCEEDED, driver.session.failure_message
    assert driver.provisioner.launches == ["worker:0"] * 3

    inter = Path(tmp_job_dirs["history"]) / "intermediate" / "trace_test"
    recs = read_traces(inter / TASK_TRACE_FILE)
    assert len(recs) == 1
    names = _span_names(recs[0])
    assert names.count("restarted") == 2
    assert names.count("requested") == 3 and names.count("launched") == 3
    assert names[-1] == "finished"
    _assert_ordered(recs[0])
    assert recs[0]["attrs"]["restarts"] == 2
    text = driver.render_metrics()
    assert "driver_task_restarts_total 2" in text
    assert "driver_heartbeat_expired_total 0" in text


# --------------------------------------------------------------------------
# heartbeat expiry: budgeted restart first, then a terminal expiry
# --------------------------------------------------------------------------

def test_task_trace_heartbeat_expiry_path(tmp_job_dirs, tmp_path):
    """Both attempts register, beat, then go silent. Attempt 1's expiry
    spends the restart budget ('restarted' mark + a fresh chain);
    attempt 2's expiry exhausts it — terminal 'heartbeat_expired', job
    FAILED, and the expiry/restart counters agree."""

    def script(spec, index, env, handle, attempt):
        rpc = _rpc_for(env)
        task_id = f"{spec.name}:{index}"
        payload = rpc.call("register_worker", task_id=task_id,
                           host="127.0.0.1", port=22000 + index)
        while payload is None:
            rpc.call("heartbeat", task_id=task_id)
            time.sleep(0.03)
            payload = rpc.call("get_cluster_spec", task_id=task_id)
        rpc.call("heartbeat", task_id=task_id)
        rpc.close()
        return None         # go silent: never beats again, never exits

    driver = _driver(tmp_job_dirs, tmp_path, script,
                     **{"tony.worker.instances": 1,
                        "tony.worker.command": "stub",
                        "tony.worker.max-restarts": 1,
                        "tony.task.heartbeat-interval-ms": 100,
                        "tony.task.max-missed-heartbeats": 3})
    status = driver.run()
    assert status == JobStatus.FAILED
    assert "missed 3 heartbeats" in driver.session.failure_message

    inter = Path(tmp_job_dirs["history"]) / "intermediate" / "trace_test"
    recs = read_traces(inter / TASK_TRACE_FILE)
    assert len(recs) == 1
    names = _span_names(recs[0])
    assert names[-1] == "heartbeat_expired"
    assert names.count("restarted") == 1
    assert names.count("registered") == 2, (
        f"both attempts must register in the same trace: {names}")
    assert names.count("first_heartbeat") == 2
    _assert_ordered(recs[0])
    assert recs[0]["attrs"]["restarts"] == 1
    text = driver.render_metrics()
    assert "driver_heartbeat_expired_total 2" in text
    assert "driver_task_restarts_total 1" in text


# --------------------------------------------------------------------------
# on-demand profiler command: HTTP/RPC queue -> heartbeat ride -> flag file
# --------------------------------------------------------------------------

def test_driver_profile_command_rides_heartbeat(tmp_job_dirs, tmp_path):
    """The training-worker capture path end to end (docs/observability.md
    "Device timing & profiling"): the operator queues a capture through
    the client-ACL'd ``request_task_profile`` RPC (an executor key is
    REJECTED, and with token auth on the unauthenticated metrics-server
    /profile route refuses with 403 — it must not bypass the ACL), the
    command rides the task's next heartbeat response exactly once (a
    newer request replaces an unread one), and the executor relays it
    into the ``$TONY_STEP_LOG.profile`` flag file the training child
    polls."""
    import urllib.error

    from tony_tpu.rpc.protocol import RpcError, derive_role_key

    got: dict = {}
    registered = threading.Event()
    queued = threading.Event()

    def script(spec, index, env, handle, attempt):
        rpc = _rpc_for(env)
        task_id = f"{spec.name}:{index}"
        payload = rpc.call("register_worker", task_id=task_id,
                           host="127.0.0.1", port=23000 + index)
        while payload is None:
            rpc.call("heartbeat", task_id=task_id)
            time.sleep(0.03)
            payload = rpc.call("get_cluster_spec", task_id=task_id)
        # the executor key must not be able to aim the profiler at peers
        try:
            rpc.call("request_task_profile", task_id=task_id, seconds=1)
            got["acl"] = "allowed"
        except RpcError as e:
            got["acl"] = str(e)
        registered.set()
        assert queued.wait(20), "test never queued the profile command"
        cmd, deadline = None, time.time() + 20
        while cmd is None and time.time() < deadline:
            res = rpc.call("heartbeat", task_id=task_id)
            if isinstance(res, dict):
                cmd = res.get("profile")
            else:
                time.sleep(0.03)
        got["cmd"] = cmd
        got["again"] = rpc.call("heartbeat", task_id=task_id)  # one-shot
        if cmd:
            from tony_tpu.executor import write_profile_flag
            got["flag"] = write_profile_flag(
                str(tmp_path / "w0.steps.jsonl"), cmd)
        rpc.call("register_execution_result", task_id=task_id, exit_code=0)
        rpc.close()
        return 0

    driver = _driver(tmp_job_dirs, tmp_path, script,
                     **{"tony.worker.instances": 1,
                        "tony.worker.command": "stub",
                        "tony.task.heartbeat-interval-ms": 100})
    t = threading.Thread(target=driver.run, daemon=True)
    t.start()
    try:
        assert registered.wait(20), "worker never registered"
        deadline = time.time() + 20
        while driver.metrics_port is None and time.time() < deadline:
            time.sleep(0.02)
        port = driver.metrics_port

        # with token auth ON the unauthenticated /profile HTTP route
        # must refuse — it would otherwise hand any network peer the
        # action the RPC ACL restricts to the client key
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/profile?task=worker:0&seconds=5",
                timeout=10)
        assert e.value.code == 403

        # the sanctioned path: the client-signed RPC
        cl = RpcClient("127.0.0.1", driver.rpc_server.port,
                       token=derive_role_key("trace-secret", "client"),
                       role="client")
        try:
            # unknown task -> False, out-of-range window -> error
            assert cl.call("request_task_profile",
                           task_id="worker:9", seconds=5) is False
            with pytest.raises(RpcError, match="seconds"):
                cl.call("request_task_profile",
                        task_id="worker:0", seconds=9999)
            # queue twice before any beat reads it: the NEWER wins
            assert cl.call("request_task_profile",
                           task_id="worker:0", seconds=2) is True
            assert cl.call("request_task_profile",
                           task_id="worker:0", seconds=3) is True
        finally:
            cl.close()
        queued.set()
    finally:
        registered.set()
        queued.set()
    t.join(timeout=30)
    assert not t.is_alive(), "driver did not finish"
    assert driver.session.status == JobStatus.SUCCEEDED, (
        driver.session.failure_message)

    assert "authorization" in got["acl"], (
        f"executor key must be refused: {got['acl']}")
    assert got["cmd"] == {"seconds": 3.0}, (
        "the replacement request must be the one delivered")
    assert got["again"] is True, "the command is one-shot per queue"
    flag = tmp_path / ("w0.steps.jsonl" + c.PROFILE_REQUEST_SUFFIX)
    assert got["flag"] == str(flag) and flag.exists()
    req = json.loads(flag.read_text())
    assert req["seconds"] == 3.0
    assert f"/{c.PROFILE_DIR_NAME}/" in req["out_dir"]
    # terminal task: nothing left to profile
    assert driver.request_profile("worker:0", 1.0) is False


def test_driver_profile_http_route_when_auth_off(tmp_job_dirs, tmp_path):
    """Without token auth (local dev) the metrics server's /profile
    convenience route is live: unknown task -> 404, bad window -> 400."""
    import urllib.error

    conf = _conf(tmp_job_dirs, **{"tony.worker.instances": 1,
                                  "tony.worker.command": "stub"})
    job_dir = tmp_path / "job_http"
    job_dir.mkdir()
    conf.write_final(job_dir)
    driver = Driver(conf, app_id="trace_http", job_dir=str(job_dir),
                    token="", provisioner=ScriptedProvisioner(
                        lambda *a: 0))
    driver._start_metrics_server()
    try:
        port = driver.metrics_port
        assert port is not None
        for query, code in (("task=worker:0&seconds=5", 404),
                            ("task=worker:0&seconds=9999", 400),
                            ("task=worker:0&seconds=bogus", 400)):
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/profile?{query}", timeout=10)
            assert e.value.code == code, query
    finally:
        driver._metrics_httpd.shutdown()
        driver._metrics_httpd.server_close()
        driver.rpc_server.stop()


# --------------------------------------------------------------------------
# executor-side satellites: TaskMonitor channel, Heartbeater jitter/miss
# --------------------------------------------------------------------------

class _CapturingRpc:
    def __init__(self):
        self.calls = []

    def call(self, method, **params):
        self.calls.append((method, params))
        return True


def test_task_monitor_push_carries_spans_child_status_and_steps(tmp_path):
    """One update_metrics push carries everything the driver needs:
    accumulator metrics (incl. child_alive and the step-time quantiles
    sampled from the training child's StepTimer JSONL) plus the
    executor lifecycle spans, time-sorted."""
    from tony_tpu.metrics import (
        CHILD_ALIVE, STEP_TIME_MEAN_S, STEP_TIME_P99_S, XLA_COMPILES,
        XLA_COMPILE_TIME_S, TaskMonitor,
    )
    from tony_tpu.train.profiling import StepTimer

    step_log = tmp_path / "w0.steps.jsonl"
    timer = StepTimer(step_log, window=4)
    for _ in range(9):      # crosses the window boundary -> one record
        timer.tick()
    assert step_log.exists()
    rec = json.loads(step_log.read_text().splitlines()[-1])
    assert "p50_s" in rec and "p99_s" in rec    # StepTimer histogram feed
    # compile telemetry rides the same record (process-global listener)
    assert rec["xla_compiles"] >= 0 and rec["xla_compile_time_s"] >= 0.0
    assert "xla_recompiles_post_warm" in rec

    class _Ctx:             # a finished child: poll() returns an exit code
        spans = [["child_spawned", 50.0]]

        class child_process:
            pid = 1

            @staticmethod
            def poll():
                return 0

    rpc = _CapturingRpc()
    mon = TaskMonitor(rpc, "worker:0", interval_s=60)
    mon.set_context(_Ctx())
    mon.set_step_log(str(step_log))
    mon.add_span("work_dir_ready", t=40.0)
    mon.refresh()
    (method, params), = rpc.calls
    assert method == "update_metrics" and params["task_id"] == "worker:0"
    names = {m["name"] for m in params["metrics"]}
    assert f"max_{CHILD_ALIVE}" in names
    assert f"max_{STEP_TIME_MEAN_S}" in names
    assert f"max_{STEP_TIME_P99_S}" in names
    by_name = {m["name"]: m["value"] for m in params["metrics"]}
    assert by_name[f"max_{CHILD_ALIVE}"] == 0.0     # child already exited
    # compile totals take SET semantics (latest total, never an average
    # of a monotone counter): max_ and avg_ agree with the record
    assert by_name[f"max_{XLA_COMPILES}"] == rec["xla_compiles"]
    assert by_name[f"avg_{XLA_COMPILES}"] == rec["xla_compiles"]
    assert by_name[f"max_{XLA_COMPILE_TIME_S}"] == (
        rec["xla_compile_time_s"])
    # monitor + ctx spans merged, time-sorted
    assert params["spans"] == [["work_dir_ready", 40.0],
                               ["child_spawned", 50.0]]


def test_heartbeater_jitter_and_missed_counter():
    """The heartbeat wait is jittered (never exactly the base interval,
    bounded ±10%) and REFUSED beats (the driver answered and said no —
    an RpcError, not a transport failure, which since the control-plane
    recovery work rides the driver-outage grace instead) feed the
    monitor's missed counter."""
    from tony_tpu.executor import Heartbeater
    from tony_tpu.metrics import HEARTBEATS_MISSED
    from tony_tpu.rpc import RpcError

    class _FailingClient:
        def call(self, method, **params):
            raise RpcError("heartbeat refused")

    class _Notes:
        def __init__(self):
            self.notes = []

        def note(self, name, value):
            self.notes.append((name, value))

    notes = _Notes()
    hb = Heartbeater(_FailingClient(), "worker:0", interval_s=0.01,
                     max_failures=3, on_driver_lost=None, monitor=notes)
    waits = [hb._interval * hb._rng.uniform(0.9, 1.1) for _ in range(50)]
    assert all(0.009 <= w <= 0.011 for w in waits)
    assert len(set(waits)) > 1, "jitter must actually vary the wait"
    hb.start()
    deadline = time.time() + 5
    while hb.missed < 3 and time.time() < deadline:
        time.sleep(0.01)
    hb.stop_event.set()
    hb.join(timeout=5)
    assert hb.missed >= 3
    missed = [v for n, v in notes.notes if n == HEARTBEATS_MISSED]
    assert missed and missed == sorted(missed) and missed[-1] == hb.missed


# --------------------------------------------------------------------------
# torn-line tolerance + portal waterfall
# --------------------------------------------------------------------------

def test_task_trace_torn_line_read(tmp_path):
    """A record torn mid-write (crash) must not hide the other tasks'
    traces — same contract as the request-trace reader."""
    w = TraceWriter(tmp_path, filename=TASK_TRACE_FILE)
    w.write({"id": "worker:0",
             "spans": [["requested", 1.0], ["finished", 2.0]],
             "attrs": {"restarts": 0}})
    w.close()
    with open(tmp_path / TASK_TRACE_FILE, "a") as f:
        f.write('{"id": "worker:1", "spans": [["requested", 1.')  # torn
    recs = read_traces(tmp_path / TASK_TRACE_FILE)
    assert [r["id"] for r in recs] == ["worker:0"]
    assert _span_names(recs[0])[-1] in TASK_TERMINAL_SPANS


def test_portal_task_waterfall(tmp_path):
    """/tasks/<app_id>: the gang-launch waterfall renders from
    tasks.trace.jsonl (HTML + JSON), is linked from the job page, 404s
    cleanly when absent, and drops malformed records instead of 500ing."""
    import urllib.error

    from tony_tpu.events.history import history_file_name
    from tony_tpu.portal.server import serve_portal

    inter = tmp_path / "hist" / "intermediate"
    job = inter / "app_tasks"
    job.mkdir(parents=True)
    (job / history_file_name("app_tasks", 1000, end_ms=9000, user="u",
                             status="SUCCEEDED")).write_text("")
    bare = inter / "app_bare"
    bare.mkdir(parents=True)
    (bare / history_file_name("app_bare", 1000, end_ms=2000, user="u",
                              status="SUCCEEDED")).write_text("")
    w = TraceWriter(job, filename=TASK_TRACE_FILE)
    w.write({"id": "worker:0", "spans": [
        ["requested", 10.0], ["allocated", 10.1], ["launched", 10.15],
        ["registered", 10.6], ["first_heartbeat", 10.7], ["running", 10.9],
        ["finished", 12.0]], "attrs": {"restarts": 0, "exit_code": 0}})
    w.write({"id": "worker:1", "spans": [
        ["requested", 10.0], ["allocated", 10.1], ["launched", 10.15],
        ["registered", 11.4], ["restarted", 11.5], ["requested", 11.5],
        ["heartbeat_expired", 12.5]], "attrs": {"restarts": 1}})
    # budget-free relaunch marks (preemption drain + elastic resize)
    # must render as their own colored segments, not the unknown-gray
    w.write({"id": "worker:2", "spans": [
        ["requested", 10.0], ["registered", 10.4], ["preempting", 10.8],
        ["preempted", 11.0], ["requested", 11.0], ["registered", 11.2],
        ["resized", 11.6], ["requested", 11.6], ["finished", 12.2]],
        "attrs": {"restarts": 0, "gang_generation": 1}})
    w.write({"id": "bad", "spans": [["requested"]]})    # malformed shape
    w.close()

    conf = TonyConf({
        "tony.staging.dir": str(tmp_path / "staging"),
        "tony.history.intermediate": str(inter),
        "tony.history.finished": str(tmp_path / "hist" / "finished"),
    })
    server = serve_portal(conf, port=0, block=False)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        def get(path, accept="application/json"):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", headers={"Accept": accept})
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.status, resp.read().decode()

        status, body = get("/tasks/app_tasks")
        assert status == 200
        assert [t["id"] for t in json.loads(body)] == [
            "worker:0", "worker:1", "worker:2", "bad"]

        status, body = get("/tasks/app_tasks", accept="text/html")
        assert status == 200
        assert "gang-launch waterfall" in body
        assert "worker:0" in body and "heartbeat_expired" in body
        # the preempt/resize marks render with their dedicated colors
        # (portal _TASK_SEG_COLORS), visible in segment tooltips + fills
        from tony_tpu.portal.server import _TASK_SEG_COLORS
        for mark in ("preempted", "resized"):
            assert mark in body
            assert _TASK_SEG_COLORS[mark] in body
        assert "3 tasks" in body        # malformed record dropped

        status, body = get("/jobs/app_tasks", accept="text/html")
        assert "/tasks/app_tasks" in body

        try:
            get("/tasks/app_bare")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        server.shutdown()
        server.server_close()
