"""Model + train-step tests: forward shapes, loss decreases, sharded training
across rule tables, ring-attention training, MoE model, checkpointing."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import transformer
from tony_tpu.models.mnist import (
    accuracy, init_mlp, loss_fn as mnist_loss, mlp_apply, synthetic_mnist,
)
from tony_tpu.parallel import MeshSpec, build_mesh, DP_RULES, FSDP_TP_RULES
from tony_tpu.train import create_train_step, make_forward, synthetic_lm_batch

TINY = transformer.TransformerConfig(
    vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=64, dtype=jnp.float32, attn_impl="ref",
)


def test_forward_shapes_and_finite():
    params = transformer.init(jax.random.PRNGKey(0), TINY)
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits, aux = transformer.apply(params, tokens, TINY)
    assert logits.shape == (2, 16, 128)
    assert np.isfinite(np.asarray(logits)).all()
    assert float(aux) == 0.0  # dense model: no aux loss


def test_param_axes_tree_matches_params():
    params = transformer.init(jax.random.PRNGKey(0), TINY)
    axes = transformer.param_logical_axes(TINY)
    flat_p = jax.tree.leaves(params)
    flat_a = jax.tree.leaves(axes, is_leaf=lambda x: isinstance(x, tuple))
    assert len(flat_p) == len(flat_a)
    for p, a in zip(flat_p, flat_a):
        assert p.ndim == len(a), (p.shape, a)


def test_causality():
    """Changing a future token must not affect past logits."""
    params = transformer.init(jax.random.PRNGKey(0), TINY)
    t1 = jnp.zeros((1, 8), jnp.int32)
    t2 = t1.at[0, 7].set(5)
    l1, _ = transformer.apply(params, t1, TINY)
    l2, _ = transformer.apply(params, t2, TINY)
    np.testing.assert_allclose(
        np.asarray(l1[0, :7]), np.asarray(l2[0, :7]), atol=1e-5
    )


@pytest.mark.parametrize("rules_name", ["dp", "fsdp_tp"])
@pytest.mark.slow
def test_sharded_training_loss_decreases(rules_name):
    mesh = build_mesh(
        MeshSpec(data=2, fsdp=2, tensor=2) if rules_name == "fsdp_tp"
        else MeshSpec(data=4, fsdp=2)
    )
    rules = FSDP_TP_RULES if rules_name == "fsdp_tp" else DP_RULES
    bundle = create_train_step(TINY, mesh, rules=rules, key=jax.random.PRNGKey(0))
    params, opt_state = bundle.params, bundle.opt_state
    tokens, targets = synthetic_lm_batch(jax.random.PRNGKey(0), 8, 16, 128)
    losses = []
    for _ in range(10):
        params, opt_state, metrics = bundle.step_fn(params, opt_state, tokens, targets)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] - 0.05, losses
    assert np.isfinite(losses).all()


@pytest.mark.slow
def test_ring_attention_training():
    """Train step with the sequence sharded over a 4-way seq axis."""
    mesh = build_mesh(MeshSpec(data=2, fsdp=1, seq=4))
    bundle = create_train_step(
        TINY, mesh, rules=dict(DP_RULES), key=jax.random.PRNGKey(0),
        use_ring_attention=True,
    )
    params, opt_state = bundle.params, bundle.opt_state
    tokens, targets = synthetic_lm_batch(jax.random.PRNGKey(0), 4, 32, 128)
    losses = []
    for _ in range(8):
        params, opt_state, metrics = bundle.step_fn(params, opt_state, tokens, targets)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] - 0.05, losses


def test_ring_training_matches_flashless_single_device():
    """Ring-attention loss == reference-attention loss on the same batch."""
    mesh_sp = build_mesh(MeshSpec(fsdp=1, seq=8))
    bundle = create_train_step(
        TINY, mesh_sp, rules=dict(DP_RULES), key=jax.random.PRNGKey(0),
        use_ring_attention=True,
    )
    tokens, targets = synthetic_lm_batch(jax.random.PRNGKey(0), 2, 32, 128)
    _, _, m_ring = bundle.step_fn(bundle.params, bundle.opt_state, tokens, targets)

    params = transformer.init(jax.random.PRNGKey(0), TINY)
    ref_loss = transformer.loss_fn(params, tokens, targets, TINY)
    np.testing.assert_allclose(
        float(m_ring["loss"]), float(ref_loss), rtol=2e-4
    )


@pytest.mark.slow
def test_moe_model_trains():
    cfg = transformer.TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=64, n_experts=4, expert_top_k=2, capacity_factor=2.0,
        dtype=jnp.float32, attn_impl="ref",
    )
    mesh = build_mesh(MeshSpec(data=2, fsdp=1, expert=4))
    from tony_tpu.parallel import merge_rules, EP_RULES

    rules = merge_rules(DP_RULES, EP_RULES)
    bundle = create_train_step(cfg, mesh, rules=rules, key=jax.random.PRNGKey(0))
    params, opt_state = bundle.params, bundle.opt_state
    tokens, targets = synthetic_lm_batch(jax.random.PRNGKey(0), 8, 16, 128)
    losses = []
    for _ in range(8):
        params, opt_state, metrics = bundle.step_fn(params, opt_state, tokens, targets)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] - 0.05, losses


@pytest.mark.slow
def test_gqa_and_remat_variants():
    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=1,
        d_ff=64, dtype=jnp.float32, attn_impl="ref", remat=True,
    )
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    tokens, targets = synthetic_lm_batch(jax.random.PRNGKey(0), 2, 16, 64)
    loss, grads = jax.value_and_grad(transformer.loss_fn)(params, tokens, targets, cfg)
    assert np.isfinite(float(loss))
    assert all(np.isfinite(np.asarray(g)).all() for g in jax.tree.leaves(grads))


def test_remat_policy_attn_matches_full():
    """remat_policy='attn' (pin the flash forward's out+lse residuals so
    the backward never re-runs the kernel) must produce the same loss and
    gradients as full remat — it changes what is cached, not what is
    computed. attn_impl='flash' so the named residuals actually exist
    (interpret-mode kernel on CPU)."""
    import dataclasses

    base = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=64, dtype=jnp.float32, attn_impl="flash", remat=True,
    )
    params = transformer.init(jax.random.PRNGKey(0), base)
    tokens, targets = synthetic_lm_batch(jax.random.PRNGKey(0), 2, 16, 64)
    outs = {}
    for policy in ("full", "attn"):
        cfg = dataclasses.replace(base, remat_policy=policy)
        outs[policy] = jax.value_and_grad(transformer.loss_fn)(
            params, tokens, targets, cfg
        )
    np.testing.assert_allclose(float(outs["full"][0]), float(outs["attn"][0]),
                               rtol=1e-6)
    for gf, ga in zip(jax.tree.leaves(outs["full"][1]),
                      jax.tree.leaves(outs["attn"][1])):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(ga),
                                   rtol=1e-5, atol=1e-6)


def test_mnist_mlp_learns():
    x, y = synthetic_mnist(jax.random.PRNGKey(0), n=2048)
    params = init_mlp(jax.random.PRNGKey(1), sizes=(784, 128, 10))
    import optax

    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, xb, yb):
        loss, grads = jax.value_and_grad(mnist_loss)(params, xb, yb)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    for i in range(30):
        sl = slice((i * 256) % 2048, (i * 256) % 2048 + 256)
        params, opt_state, loss = step(params, opt_state, x[sl], y[sl])
    assert float(accuracy(params, x, y)) > 0.8


def test_checkpoint_roundtrip(tmp_path):
    from tony_tpu.train.checkpoint import CheckpointManager

    params = transformer.init(jax.random.PRNGKey(0), TINY)
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(0, {"params": params, "step": 0})
    mgr.wait()
    assert mgr.latest_step() == 0
    restored = mgr.restore(template={"params": params, "step": 0})
    np.testing.assert_allclose(
        np.asarray(restored["params"]["embed"]), np.asarray(params["embed"])
    )
    mgr.close()


def test_forward_jit_compiles():
    fwd = make_forward(TINY)
    params = transformer.init(jax.random.PRNGKey(0), TINY)
    logits = fwd(params, jnp.zeros((1, 8), jnp.int32))
    assert logits.shape == (1, 8, 128)


@pytest.mark.slow
def test_pipeline_transformer_matches_and_trains():
    """Model-level pipeline parallelism: loss equals the unpipelined model,
    and training decreases it."""
    from tony_tpu.train.pipeline_step import create_pipeline_train_step

    cfg = transformer.TransformerConfig(
        vocab_size=128, d_model=64, n_layers=4, n_heads=4, n_kv_heads=4,
        d_ff=128, dtype=jnp.float32, attn_impl="ref",
    )
    mesh = build_mesh(MeshSpec(pipe=4, fsdp=2))
    bundle = create_pipeline_train_step(cfg, mesh, num_microbatches=4)
    tokens, targets = synthetic_lm_batch(jax.random.PRNGKey(0), 8, 16, 128)

    pipe_loss = float(bundle.loss_fn(bundle.params, tokens, targets))
    ref_params = transformer.init(jax.random.PRNGKey(0), cfg)
    ref_loss = float(transformer.loss_fn(ref_params, tokens, targets, cfg))
    np.testing.assert_allclose(pipe_loss, ref_loss, rtol=1e-5)

    params, opt_state = bundle.params, bundle.opt_state
    losses = []
    for _ in range(8):
        params, opt_state, m = bundle.step_fn(params, opt_state, tokens, targets)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.05, losses


@pytest.mark.slow
def test_pipeline_1f1b_transformer_matches_gpipe():
    """The 1F1B schedule (manual interleaved backward, O(stages) residuals)
    must train identically to the autodiff GPipe schedule: same loss, and
    one optimizer step from identical init produces the same params."""
    from tony_tpu.train.pipeline_step import create_pipeline_train_step

    cfg = transformer.TransformerConfig(
        vocab_size=128, d_model=64, n_layers=4, n_heads=4, n_kv_heads=4,
        d_ff=128, dtype=jnp.float32, attn_impl="ref",
    )
    mesh = build_mesh(MeshSpec(pipe=4, fsdp=2))
    tokens, targets = synthetic_lm_batch(jax.random.PRNGKey(0), 8, 16, 128)
    # pads distributed UNEVENLY across microbatches: the 1f1b head must
    # weight by the global valid count, not per-microbatch means
    targets = targets.at[0, :10].set(-1).at[1, :4].set(-1)

    g = create_pipeline_train_step(cfg, mesh, num_microbatches=4)
    f = create_pipeline_train_step(cfg, mesh, num_microbatches=4,
                                   schedule="1f1b")

    gl = float(g.loss_fn(g.params, tokens, targets))
    fl = float(f.loss_fn(f.params, tokens, targets))
    np.testing.assert_allclose(fl, gl, rtol=1e-5)

    gp, go, gm = g.step_fn(g.params, g.opt_state, tokens, targets)
    fp, fo, fm = f.step_fn(f.params, f.opt_state, tokens, targets)
    np.testing.assert_allclose(float(fm["loss"]), float(gm["loss"]), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5
        ),
        fp, gp,
    )

    # and it trains
    losses = []
    params, opt_state = fp, fo
    for _ in range(6):
        params, opt_state, m = f.step_fn(params, opt_state, tokens, targets)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.05, losses


@pytest.mark.slow
def test_pipeline_circular_transformer_matches_gpipe():
    """The circular (interleaved) schedule must produce the same loss as
    GPipe on identical params/batch, and train."""
    from tony_tpu.train.pipeline_step import create_pipeline_train_step

    cfg = transformer.TransformerConfig(
        vocab_size=128, d_model=64, n_layers=4, n_heads=4, n_kv_heads=4,
        d_ff=128, dtype=jnp.float32, attn_impl="ref",
    )
    mesh = build_mesh(MeshSpec(pipe=2, fsdp=4))
    tokens, targets = synthetic_lm_batch(jax.random.PRNGKey(0), 8, 16, 128)

    g = create_pipeline_train_step(cfg, mesh, num_microbatches=4)
    c = create_pipeline_train_step(cfg, mesh, num_microbatches=4,
                                   schedule="circular", num_chunks=2)
    gl = float(g.loss_fn(g.params, tokens, targets))
    cl = float(c.loss_fn(c.params, tokens, targets))
    np.testing.assert_allclose(cl, gl, rtol=1e-5)

    params, opt_state = c.params, c.opt_state
    losses = []
    for _ in range(8):
        params, opt_state, m = c.step_fn(params, opt_state, tokens, targets)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.05, losses


def test_pipeline_1f1b_bfloat16_activations():
    """The 1f1b schedule must trace and run with the default bf16
    activation dtype (regression: an f32 mask promotion broke the scan
    carry dtype)."""
    from tony_tpu.train.pipeline_step import create_pipeline_train_step

    cfg = transformer.TransformerConfig(
        vocab_size=128, d_model=64, n_layers=4, n_heads=4, n_kv_heads=4,
        d_ff=128, dtype=jnp.bfloat16, attn_impl="ref",
    )
    mesh = build_mesh(MeshSpec(pipe=4, fsdp=2))
    bundle = create_pipeline_train_step(
        cfg, mesh, num_microbatches=4, schedule="1f1b"
    )
    tokens, targets = synthetic_lm_batch(jax.random.PRNGKey(1), 8, 16, 128)
    _, _, m = bundle.step_fn(bundle.params, bundle.opt_state, tokens, targets)
    assert np.isfinite(float(m["loss"]))


@pytest.mark.slow
def test_pipeline_moe_aux_survives_both_schedules():
    """PP x MoE: expert layers pipeline in both schedules, and the
    load-balancing aux loss is accumulated (loss > plain CE). Parity
    reference: per-microbatch forward of the same params (MoE routing is
    per-microbatch under pipelining)."""
    from tony_tpu.train.pipeline_step import create_pipeline_train_step

    cfg = transformer.TransformerConfig(
        vocab_size=128, d_model=64, n_layers=4, n_heads=4, n_kv_heads=4,
        d_ff=64, n_experts=4, expert_top_k=2, capacity_factor=2.0,
        aux_loss_weight=0.05, dtype=jnp.float32, attn_impl="ref",
    )
    mesh = build_mesh(MeshSpec(pipe=4, fsdp=2))
    tokens, targets = synthetic_lm_batch(jax.random.PRNGKey(2), 8, 16, 128)
    M = 4

    ref_params = transformer.init(jax.random.PRNGKey(0), cfg)
    micro_tok = tokens.reshape(M, -1, tokens.shape[1])
    micro_tgt = targets.reshape(M, -1, targets.shape[1])
    ref_loss = float(np.mean([
        float(transformer.loss_fn(ref_params, micro_tok[m], micro_tgt[m], cfg))
        for m in range(M)
    ]))
    ce_only = float(np.mean([
        float(transformer.token_nll(
            transformer.apply_hidden(ref_params, micro_tok[m], cfg)[0],
            ref_params["unembed"], micro_tgt[m], cfg,
        ))
        for m in range(M)
    ]))
    assert ref_loss > ce_only  # aux really contributes

    mesh2 = build_mesh(MeshSpec(pipe=2, fsdp=4))
    for schedule, m_, kw in (
        ("gpipe", mesh, {}),
        ("1f1b", mesh, {}),
        # circular needs n_layers % (S*V) == 0: S=2, V=2 for 4 layers
        ("circular", mesh2, {"num_chunks": 2}),
    ):
        bundle = create_pipeline_train_step(
            cfg, m_, num_microbatches=M, schedule=schedule, **kw
        )
        loss = float(bundle.loss_fn(bundle.params, tokens, targets))
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, err_msg=schedule)
        # one step trains without error and loss stays finite
        _, _, m = bundle.step_fn(
            bundle.params, bundle.opt_state, tokens, targets
        )
        assert np.isfinite(float(m["loss"])), schedule


@pytest.mark.slow
def test_bidirectional_encoder():
    """causal=False turns the stack into a BERT-style encoder: every
    position attends everywhere (verified against a manual full-attention
    forward), masked-LM training via -1-masked targets decreases loss, and
    autoregressive generate() is rejected."""
    import dataclasses

    cfg = dataclasses.replace(TINY, causal=False, attn_impl="ref")
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    tokens, _ = synthetic_lm_batch(jax.random.PRNGKey(0), 8, 16, cfg.vocab_size)

    # bidirectionality: last token's change must affect position 0's hidden
    h0, _ = transformer.apply_hidden(params, tokens, cfg)
    toks2 = tokens.at[:, -1].set((tokens[:, -1] + 1) % cfg.vocab_size)
    h1, _ = transformer.apply_hidden(params, toks2, cfg)
    assert float(jnp.abs(h0[:, 0] - h1[:, 0]).max()) > 0, (
        "position 0 blind to the future — stack is still causal"
    )
    # causal config: position 0 must NOT see the future
    cfg_c = dataclasses.replace(TINY, attn_impl="ref")
    hc0, _ = transformer.apply_hidden(params, tokens, cfg_c)
    hc1, _ = transformer.apply_hidden(params, toks2, cfg_c)
    np.testing.assert_allclose(np.asarray(hc0[:, 0]), np.asarray(hc1[:, 0]))

    # masked-LM: score only 20% masked positions (targets -1 elsewhere)
    from tony_tpu.train import create_train_step
    from tony_tpu.parallel import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(data=-1, fsdp=1))
    bundle = create_train_step(cfg, mesh)
    rng = np.random.default_rng(0)
    mlm_mask = rng.random((8, 16)) < 0.2
    mlm_mask[:, 0] = True  # at least one scored position per row
    targets = jnp.where(jnp.asarray(mlm_mask), tokens, -1)
    inputs = jnp.where(jnp.asarray(mlm_mask), cfg.vocab_size - 1, tokens)
    p, o = bundle.params, bundle.opt_state
    losses = []
    for _ in range(10):
        p, o, m = bundle.step_fn(p, o, inputs, targets)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.1, losses

    from tony_tpu.models.generate import generate

    with pytest.raises(ValueError, match="causal"):
        generate(params, cfg, tokens, 4)


def test_loss_fn_blockwise_ce_matches_dense():
    """cfg.ce_impl='blockwise' (logits never materialized) must reproduce the
    dense loss and gradients on the same params/batch."""
    import dataclasses

    cfg_dense = dataclasses.replace(TINY, ce_impl="dense")
    cfg_blk = dataclasses.replace(TINY, ce_impl="blockwise", ce_block_v=32)
    params = transformer.init(jax.random.PRNGKey(0), TINY)
    tokens, targets = synthetic_lm_batch(jax.random.PRNGKey(0), 2, 16, TINY.vocab_size)
    # pad a few targets to exercise the valid-mask path
    targets = targets.at[0, :3].set(-1)

    l_dense, g_dense = jax.value_and_grad(transformer.loss_fn)(
        params, tokens, targets, cfg_dense)
    l_blk, g_blk = jax.value_and_grad(transformer.loss_fn)(
        params, tokens, targets, cfg_blk)
    np.testing.assert_allclose(float(l_blk), float(l_dense), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_blk), jax.tree.leaves(g_dense)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.slow
def test_blockwise_ce_trains_sharded():
    """Blockwise CE inside the sharded train step (fsdp mesh, unembed
    sharded): loss must decrease and match the dense-CE step."""
    import dataclasses

    cfg = dataclasses.replace(TINY, ce_impl="blockwise", ce_block_v=32)
    mesh = build_mesh(MeshSpec(data=2, fsdp=4))
    bundle = create_train_step(
        cfg, mesh, rules=dict(FSDP_TP_RULES), key=jax.random.PRNGKey(0))
    bundle_dense = create_train_step(
        dataclasses.replace(cfg, ce_impl="dense"), mesh,
        rules=dict(FSDP_TP_RULES), key=jax.random.PRNGKey(0))
    tokens, targets = synthetic_lm_batch(jax.random.PRNGKey(1), 8, 16, cfg.vocab_size)
    p, o, m = bundle.step_fn(bundle.params, bundle.opt_state, tokens, targets)
    _, _, m_dense = bundle_dense.step_fn(
        bundle_dense.params, bundle_dense.opt_state, tokens, targets)
    np.testing.assert_allclose(float(m["loss"]), float(m_dense["loss"]), rtol=1e-4)
    _, _, m2 = bundle.step_fn(p, o, tokens, targets)
    assert float(m2["loss"]) < float(m["loss"])


def test_generate_matches_teacher_forcing_greedy():
    """KV-cache decode must reproduce full-forward argmax continuations
    exactly (prefill + per-step cache path == apply on the growing prefix)."""
    from tony_tpu.models.generate import generate

    params = transformer.init(jax.random.PRNGKey(0), TINY)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, TINY.vocab_size)
    out = generate(params, TINY, prompt, 6)
    assert out.shape == (2, 6)

    seq = prompt
    for i in range(6):
        logits, _ = transformer.apply(params, seq, TINY)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        np.testing.assert_array_equal(np.asarray(out[:, i]), np.asarray(nxt))
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)


def test_generate_int8_cache_option():
    """kv_dtype='int8' (half the cache bytes) generates valid tokens; the
    per-token-per-head symmetric quantizer's roundtrip error is bounded by
    its 1/127 resolution."""
    from tony_tpu.models.generate import _quantize_kv, generate

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 5, 16)) * 4.0
    q, scale = _quantize_kv(x)
    assert q.dtype == jnp.int8 and scale.shape == (2, 3, 5)
    deq = np.asarray(q, np.float32) * np.asarray(scale, np.float32)[..., None]
    xn = np.asarray(x)
    amax = np.abs(xn).max(axis=-1, keepdims=True)
    # half a quantization step per element, plus the bf16 rounding of the
    # scale itself (8 mantissa bits -> ~2^-8 relative on the dequant)
    bound = amax / 254.0 + np.abs(xn) * 2.0 ** -8 + 1e-6
    assert (np.abs(deq - xn) <= bound).all(), \
        float(np.max(np.abs(deq - xn) - bound))

    params = transformer.init(jax.random.PRNGKey(0), TINY)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                TINY.vocab_size)
    out = generate(params, TINY, prompt, 6, kv_dtype="int8")
    assert out.shape == (2, 6)
    assert ((np.asarray(out) >= 0) & (np.asarray(out) < TINY.vocab_size)).all()


def test_int8_scale_folded_attention_matches_explicit_dequant():
    """The scale-folded int8 attention (K scale on score columns
    post-matmul, V scale pre-applied to probs) must equal attention over
    an explicitly dequantized cache — guards the broadcast axes."""
    import dataclasses

    from tony_tpu.models.generate import _cached_attention, _quantize_kv

    cfg = dataclasses.replace(TINY, dtype=jnp.float32)
    key = jax.random.PRNGKey(3)
    b, l, kvh, d, m = 2, 1, TINY.n_heads, TINY.head_dim, 24
    kq = jax.random.split(key, 3)
    q = jax.random.normal(kq[0], (b, l, TINY.n_heads, d))
    k = jax.random.normal(kq[1], (b, kvh, m, d)) * 2.0  # head-major
    v = jax.random.normal(kq[2], (b, kvh, m, d)) * 2.0
    k_int, ks = _quantize_kv(k)
    v_int, vs = _quantize_kv(v)
    cache_len, l_new = jnp.int32(m - 1), 1

    folded = _cached_attention(cfg, q, k_int, v_int, cache_len, l_new,
                               k_scale=ks, v_scale=vs)
    k_deq = k_int.astype(jnp.float32) * np.asarray(ks, np.float32)[..., None]
    v_deq = v_int.astype(jnp.float32) * np.asarray(vs, np.float32)[..., None]
    explicit = _cached_attention(cfg, q, jnp.asarray(k_deq),
                                 jnp.asarray(v_deq), cache_len, l_new)
    np.testing.assert_allclose(np.asarray(folded), np.asarray(explicit),
                               atol=2e-2)


def test_int8_weight_quantization_matches_dequant():
    """w8a16 decode weights: the scale-folded matmul (x @ W_int8) * s must
    equal x @ dequant(W) exactly, and the quantizer's per-output-channel
    roundtrip error is bounded by its resolution."""
    import dataclasses

    from tony_tpu.models.generate import _quantize_weight, generate

    w = jax.random.normal(jax.random.PRNGKey(0), (3, 16, 24)) * 2.0
    q, s = _quantize_weight(w)
    assert q.dtype == jnp.int8 and s.shape == (3, 1, 24)
    deq = np.asarray(q, np.float32) * np.asarray(s, np.float32)
    amax = np.abs(np.asarray(w)).max(axis=-2, keepdims=True)
    assert (np.abs(deq - np.asarray(w)) <= amax / 254.0 + 1e-6).all()

    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16))
    folded = (x @ q[0].astype(jnp.float32)) * jnp.asarray(s[0, 0])
    explicit = x @ jnp.asarray(deq[0])
    np.testing.assert_allclose(np.asarray(folded), np.asarray(explicit),
                               rtol=1e-5, atol=1e-5)

    # end to end: int8 weights generate valid tokens
    params = transformer.init(jax.random.PRNGKey(0), TINY)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                TINY.vocab_size)
    out = generate(params, TINY, prompt, 6, weight_dtype="int8")
    assert out.shape == (2, 6)
    assert ((np.asarray(out) >= 0) & (np.asarray(out) < TINY.vocab_size)).all()


@pytest.mark.slow
def test_moe_w8_decode_numerics_bounded():
    """MoE w8a16: int8 expert weights with per-expert per-output-channel
    scales folded out of the matmuls. The prefill logits must stay within
    the int8 resolution of the native path (numerics-bounded parity), and
    generation must run end to end."""
    import dataclasses

    from tony_tpu.models.generate import (
        _forward_with_cache, _fuse_decode_weights, generate, init_cache,
    )

    moe = dataclasses.replace(TINY, n_experts=4, expert_top_k=2,
                              capacity_factor=2.0)
    params = transformer.init(jax.random.PRNGKey(0), moe)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                moe.vocab_size)

    fused8 = _fuse_decode_weights(params, moe, "int8")
    assert "w_in_s" in fused8 and fused8["w_in"].dtype == jnp.int8
    logits_native, _ = _forward_with_cache(
        params, moe, prompt, init_cache(moe, 2, 12), None, prefill=True)
    logits_w8, _ = _forward_with_cache(
        params, moe, prompt, init_cache(moe, 2, 12), fused8, prefill=True)
    ln, l8 = np.asarray(logits_native), np.asarray(logits_w8)
    # per-channel int8 keeps matmul outputs within ~1% of the activations'
    # dynamic range; bound each logit by a small fraction of the row span
    span = (ln.max(axis=-1) - ln.min(axis=-1))[..., None]
    assert (np.abs(l8 - ln) <= 0.05 * span + 0.05).all(), \
        float(np.abs(l8 - ln).max())

    out = generate(params, moe, prompt, 6, weight_dtype="int8")
    assert out.shape == (2, 6)
    assert ((np.asarray(out) >= 0) & (np.asarray(out) < moe.vocab_size)).all()


def test_decode_precast_keeps_moe_router_f32():
    """The decode weight pre-cast must NOT round the MoE router: _mlp reads
    it at f32 precisely so expert routing isn't perturbed (a bf16-rounded
    router can flip a close top-k margin and diverge cached generation
    from the full forward)."""
    import dataclasses

    from tony_tpu.models.generate import _cast_decode_params

    cfg = dataclasses.replace(
        TINY, dtype=jnp.bfloat16, n_experts=4, expert_top_k=2
    )
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    cast = _cast_decode_params(params, cfg)
    assert cast["layers"]["router"].dtype == jnp.float32
    assert cast["layers"]["wq"].dtype == jnp.bfloat16
    assert cast["embed"].dtype == jnp.bfloat16
    # bf16 MoE decode runs end to end with the f32 router
    from tony_tpu.models.generate import generate
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                cfg.vocab_size)
    out = generate(params, cfg, prompt, 4)
    assert out.shape == (2, 4)


def test_generate_gqa_cache_matches_teacher_forcing():
    """GQA config (cache stored at n_kv_heads) must also match."""
    from tony_tpu.models.generate import generate
    import dataclasses

    cfg = dataclasses.replace(TINY, n_kv_heads=1)
    params = transformer.init(jax.random.PRNGKey(2), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (1, 5), 0, cfg.vocab_size)
    out = generate(params, cfg, prompt, 4)
    seq = prompt
    for i in range(4):
        logits, _ = transformer.apply(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        np.testing.assert_array_equal(np.asarray(out[:, i]), np.asarray(nxt))
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)


def test_generate_sampling_modes():
    from tony_tpu.models.generate import generate

    params = transformer.init(jax.random.PRNGKey(0), TINY)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 4), 0, TINY.vocab_size)
    greedy = generate(params, TINY, prompt, 3)
    topk1 = generate(params, TINY, prompt, 3, temperature=0.7, top_k=1,
                     key=jax.random.PRNGKey(9))
    # top_k=1 collapses to greedy regardless of temperature
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(topk1))
    sampled = generate(params, TINY, prompt, 3, temperature=1.0,
                       key=jax.random.PRNGKey(9))
    assert sampled.shape == (2, 3)
    assert int(sampled.max()) < TINY.vocab_size and int(sampled.min()) >= 0


def test_generate_moe_matches_teacher_forcing():
    """MoE decode must not silently drop tokens: with ample capacity the
    cached path equals the full-forward argmax continuation."""
    from tony_tpu.models.generate import generate
    cfg = transformer.TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=64, n_experts=4, expert_top_k=2, capacity_factor=2.0,
        dtype=jnp.float32, attn_impl="ref",
    )
    params = transformer.init(jax.random.PRNGKey(4), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(5), (2, 6), 0, cfg.vocab_size)
    out = generate(params, cfg, prompt, 4)
    seq = prompt
    for i in range(4):
        logits, _ = transformer.apply(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        np.testing.assert_array_equal(np.asarray(out[:, i]), np.asarray(nxt))
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)


def test_generate_rejects_nonpositive_max_new():
    from tony_tpu.models.generate import generate

    params = transformer.init(jax.random.PRNGKey(0), TINY)
    prompt = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="max_new_tokens"):
        generate(params, TINY, prompt, 0)


def test_generate_stop_tokens_early_exit():
    """stop_tokens: each row returns exactly its pre-stop tokens (stop
    included), pad_id after, and the while_loop exits at the SLOWEST
    sequence's stop position, not at max_new_tokens."""
    from tony_tpu.models.generate import generate

    params = transformer.init(jax.random.PRNGKey(0), TINY)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                TINY.vocab_size)
    max_new = 12
    ref = np.asarray(generate(params, TINY, prompt, max_new))

    # staggered: row 0's token at position 2 and row 1's at position 5 —
    # greedy decode is deterministic, so pre-stop tokens must match ref
    stops = (int(ref[0, 2]), int(ref[1, 5]))
    pad = TINY.vocab_size - 1
    out, steps = generate(params, TINY, prompt, max_new,
                          stop_tokens=stops, pad_id=pad, return_steps=True)
    out = np.asarray(out)

    expected_steps = 0
    for r in range(2):
        hit = [i for i in range(max_new) if int(ref[r, i]) in stops]
        p = hit[0] if hit else max_new - 1
        expected_steps = max(expected_steps, p)
        np.testing.assert_array_equal(out[r, :p + 1], ref[r, :p + 1])
        assert (out[r, p + 1:] == pad).all(), out[r]
    assert int(steps) == expected_steps
    assert int(steps) < max_new - 1  # genuinely exited early


def test_generate_stop_on_first_token():
    """A row whose very first sampled token is a stop pays zero decode
    steps when the whole batch stops immediately."""
    from tony_tpu.models.generate import generate

    params = transformer.init(jax.random.PRNGKey(0), TINY)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                TINY.vocab_size)
    ref = np.asarray(generate(params, TINY, prompt, 4))
    stops = tuple({int(ref[0, 0]), int(ref[1, 0])})
    out, steps = generate(params, TINY, prompt, 4, stop_tokens=stops,
                          pad_id=0, return_steps=True)
    assert int(steps) == 0
    out = np.asarray(out)
    np.testing.assert_array_equal(out[:, 0], ref[:, 0])
    assert (out[:, 1:] == 0).all()


def test_prepare_decode_matches_in_call_path():
    """prepare_decode (build once, no per-call weight copies) must produce
    the same tokens as the in-call cast/fuse path — native and w8a16."""
    from tony_tpu.models.generate import generate, prepare_decode

    params = transformer.init(jax.random.PRNGKey(0), TINY)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                TINY.vocab_size)
    ref = np.asarray(generate(params, TINY, prompt, 6))
    prep = prepare_decode(params, TINY)
    assert prep.fused is not None and "wqkv" in prep.fused
    np.testing.assert_array_equal(
        np.asarray(generate(prep, TINY, prompt, 6)), ref)

    ref8 = np.asarray(generate(params, TINY, prompt, 6, weight_dtype="int8"))
    prep8 = prepare_decode(params, TINY, weight_dtype="int8")
    assert "wqkv_s" in prep8.fused
    np.testing.assert_array_equal(
        np.asarray(generate(prep8, TINY, prompt, 6)), ref8)


def test_generate_tp_mesh_parity():
    """Mesh-sharded decode (data x tensor; KV cache sharded over kv heads)
    must be token-exact vs the single-device greedy path — raw params and
    the prepare_decode server path both."""
    from tony_tpu.models.generate import generate, prepare_decode
    from tony_tpu.parallel import TP_DECODE_RULES

    mesh = build_mesh(MeshSpec(data=2, fsdp=1, tensor=2),
                      devices=jax.devices()[:4])
    params = transformer.init(jax.random.PRNGKey(0), TINY)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                TINY.vocab_size)
    ref = np.asarray(generate(params, TINY, prompt, 6))

    out = generate(params, TINY, prompt, 6, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(out), ref)

    prep = prepare_decode(params, TINY, mesh=mesh, rules=TP_DECODE_RULES)
    assert prep.fused is None  # fusion is single-device-only
    kv_shard = prep.params["layers"]["wk"].sharding
    assert "tensor" in str(kv_shard.spec), kv_shard  # kv genuinely sharded
    out2 = generate(prep, TINY, prompt, 6, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(out2), ref)

    # int8 cache under the mesh: scale buffers shard alongside; tokens valid
    out3 = np.asarray(generate(params, TINY, prompt, 6, kv_dtype="int8",
                               mesh=mesh))
    assert ((out3 >= 0) & (out3 < TINY.vocab_size)).all()

    # stop tokens compose with the mesh (while_loop under GSPMD)
    stops = (int(ref[0, 2]), int(ref[1, 4]))
    out4, steps = generate(params, TINY, prompt, 6, mesh=mesh,
                           stop_tokens=stops, pad_id=0, return_steps=True)
    assert int(steps) <= 4


def test_generate_moe_mesh_parity():
    """MoE decode composes with the mesh: TP (experts replicated) and
    TP x EP (experts sharded over the expert axis) both reproduce the
    single-device greedy tokens exactly — the einsum-dispatch MoE's
    sharding annotations carry the decode path like the training path."""
    import dataclasses

    from tony_tpu.models.generate import generate, prepare_decode
    from tony_tpu.parallel import EP_RULES, TP_DECODE_RULES, merge_rules

    moe = dataclasses.replace(TINY, n_experts=4, expert_top_k=2,
                              capacity_factor=2.0)
    params = transformer.init(jax.random.PRNGKey(0), moe)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                moe.vocab_size)
    ref = np.asarray(generate(params, moe, prompt, 6))

    mesh = build_mesh(MeshSpec(data=2, fsdp=1, tensor=2),
                      devices=jax.devices()[:4])
    out = np.asarray(generate(params, moe, prompt, 6, mesh=mesh))
    np.testing.assert_array_equal(out, ref)

    rules = merge_rules(TP_DECODE_RULES, EP_RULES)
    mesh2 = build_mesh(MeshSpec(fsdp=1, expert=2, tensor=2),
                       devices=jax.devices()[:4])
    prep = prepare_decode(params, moe, mesh=mesh2, rules=rules)
    ex_shard = prep.params["layers"]["w_in"].sharding
    assert "expert" in str(ex_shard.spec), ex_shard  # genuinely EP-sharded
    out2 = np.asarray(generate(prep, moe, prompt, 6, mesh=mesh2,
                               rules=rules))
    np.testing.assert_array_equal(out2, ref)


def test_generate_tp_mesh_rejections():
    """GQA with kvH < tensor axis, indivisible batch, and w8a16-under-TP
    all fail with clear errors instead of wrong layouts."""
    from tony_tpu.models.generate import generate, prepare_decode

    params = transformer.init(jax.random.PRNGKey(0), TINY)
    prompt = jnp.zeros((2, 4), jnp.int32)
    mesh8 = build_mesh(MeshSpec(fsdp=1, tensor=8))
    with pytest.raises(ValueError, match="n_kv_heads=2.*kv"):
        generate(params, TINY, prompt, 2, mesh=mesh8)

    mesh = build_mesh(MeshSpec(data=2, fsdp=1, tensor=2),
                      devices=jax.devices()[:4])
    with pytest.raises(ValueError, match="batch 3"):
        generate(params, TINY, jnp.zeros((3, 4), jnp.int32), 2, mesh=mesh)
    with pytest.raises(ValueError, match="int8"):
        prepare_decode(params, TINY, weight_dtype="int8", mesh=mesh)

    # prepared/call mismatches are errors, not silent wrong layouts
    prep = prepare_decode(params, TINY)
    with pytest.raises(ValueError, match="mesh mismatch"):
        generate(prep, TINY, prompt, 2, mesh=mesh)
    with pytest.raises(ValueError, match="prepared weights were built"):
        generate(prep, TINY, prompt, 2, weight_dtype="int8")


@pytest.mark.slow
def test_lm_generate_example_end_to_end(tmp_path):
    """Train briefly with checkpoints, then lm_generate restores and
    decodes from the checkpoint (the serve-side example)."""
    import json
    from tony_tpu.examples import lm_generate, lm_train

    args = ["--batch-size", "8", "--seq-len", "32", "--vocab", "128",
            "--d-model", "32", "--n-layers", "1", "--n-heads", "2",
            "--d-ff", "64", "--dtype", "float32", "--mesh", "data=2,fsdp=4"]
    rc = lm_train.main(["--steps", "3", "--checkpoint-dir",
                        str(tmp_path / "ck"), "--checkpoint-every", "2"] + args)
    assert rc == 0
    out = tmp_path / "gen.json"
    rc = lm_generate.main([
        "--checkpoint-dir", str(tmp_path / "ck"), "--vocab", "128",
        "--d-model", "32", "--n-layers", "1", "--n-heads", "2",
        "--d-ff", "64", "--dtype", "float32",
        "--prompt", "1 2 3", "--max-new", "5", "--metrics-out", str(out),
    ])
    assert rc == 0
    result = json.loads(out.read_text())
    assert len(result["tokens"]) == 5
    assert all(0 <= t < 128 for t in result["tokens"])


@pytest.mark.slow
def test_lm_generate_own_trained_draft_speculative(tmp_path):
    """lm_generate pairs an lm_train-trained DRAFT checkpoint with the
    target (--draft-checkpoint-dir + --draft-* shape flags) and decodes
    speculatively — tokens identical to the plain decode (the exactness
    guarantee through the CLI surface)."""
    import json
    from tony_tpu.examples import lm_generate, lm_train

    common = ["--batch-size", "8", "--seq-len", "32", "--vocab", "128",
              "--dtype", "float32", "--mesh", "fsdp=-1"]
    rc = lm_train.main(["--steps", "3", "--checkpoint-dir",
                        str(tmp_path / "target"), "--checkpoint-every", "2",
                        "--d-model", "32", "--n-layers", "2",
                        "--n-heads", "2", "--d-ff", "64"] + common)
    assert rc == 0
    rc = lm_train.main(["--steps", "3", "--checkpoint-dir",
                        str(tmp_path / "draft"), "--checkpoint-every", "2",
                        "--d-model", "16", "--n-layers", "1",
                        "--n-heads", "2", "--d-ff", "32"] + common)
    assert rc == 0

    target_flags = ["--checkpoint-dir", str(tmp_path / "target"),
                    "--vocab", "128", "--d-model", "32", "--n-layers", "2",
                    "--n-heads", "2", "--d-ff", "64", "--dtype", "float32",
                    "--prompt", "1 2 3 4", "--max-new", "6"]
    plain_out = tmp_path / "plain.json"
    assert lm_generate.main(
        target_flags + ["--metrics-out", str(plain_out)]) == 0
    spec_out = tmp_path / "spec.json"
    assert lm_generate.main(target_flags + [
        "--draft-checkpoint-dir", str(tmp_path / "draft"),
        "--draft-d-model", "16", "--draft-n-layers", "1",
        "--draft-n-heads", "2", "--draft-d-ff", "32",
        "--metrics-out", str(spec_out)]) == 0
    plain = json.loads(plain_out.read_text())["tokens"]
    spec = json.loads(spec_out.read_text())["tokens"]
    assert spec == plain, "speculative CLI output diverged from plain"


@pytest.mark.slow
def test_lm_generate_sharded_checkpoint_restore(tmp_path):
    """Serve-side big-model path: --tensor-parallel restores the checkpoint
    SHARDED (every leaf lands directly on its mesh devices — a model bigger
    than one chip's HBM never materializes whole), and decodes the same
    tokens as the single-device restore of the same checkpoint."""
    import json

    from tony_tpu.examples import lm_generate, lm_train

    model = ["--vocab", "128", "--d-model", "32", "--n-layers", "1",
             "--n-heads", "2", "--d-ff", "64", "--dtype", "float32"]
    rc = lm_train.main(["--steps", "3", "--checkpoint-dir",
                        str(tmp_path / "ck"), "--checkpoint-every", "2",
                        "--batch-size", "8", "--seq-len", "32",
                        "--mesh", "data=2,fsdp=4"] + model)
    assert rc == 0
    outs = []
    for i, extra in enumerate(([], ["--tensor-parallel", "2"])):
        out = tmp_path / f"gen{i}.json"
        rc = lm_generate.main(
            ["--checkpoint-dir", str(tmp_path / "ck"), "--prompt", "1 2 3",
             "--max-new", "5", "--metrics-out", str(out)] + model + extra)
        assert rc == 0
        outs.append(json.loads(out.read_text())["tokens"])
    assert outs[0] == outs[1], outs


@pytest.mark.slow
def test_generate_cache_continuation_multi_turn():
    """Multi-turn serving: generate(return_cache=True) returns a cache
    holding prompt + ALL emitted tokens, and continuing with only the new
    turn's tokens is token-exact vs a one-shot generate over the whole
    concatenated conversation — chat never re-prefills history."""
    from tony_tpu.models.generate import generate

    params = transformer.init(jax.random.PRNGKey(0), TINY)
    t1 = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0,
                            TINY.vocab_size)
    t2 = jax.random.randint(jax.random.PRNGKey(2), (2, 4), 0,
                            TINY.vocab_size)

    out1, cache = generate(params, TINY, t1, 5, max_len=32,
                           return_cache=True)
    assert int(cache.length) == 6 + 5  # prompt + ALL emitted
    out2, _ = generate(params, TINY, t2, 6, cache=cache, return_cache=True)

    full_prompt = jnp.concatenate([t1, out1, t2], axis=1)
    ref = generate(params, TINY, full_prompt, 6)
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(ref))

    # int8 cache continues too (kv_dtype inherited from the cache)
    o1, c8 = generate(params, TINY, t1, 5, max_len=32, kv_dtype="int8",
                      return_cache=True)
    assert c8.k.dtype == jnp.int8
    o2, _ = generate(params, TINY, t2, 4, cache=c8, return_cache=True)
    assert o2.shape == (2, 4)

    # rejections: donation without return, capacity overflow, batch
    # mismatch, kv conflict
    _, small = generate(params, TINY, t1, 5, max_len=16, return_cache=True)
    with pytest.raises(ValueError, match="return_cache"):
        generate(params, TINY, t2, 6, cache=small)
    with pytest.raises(ValueError, match="capacity"):
        generate(params, TINY, t2, 6, cache=small, return_cache=True)
    _, c2 = generate(params, TINY, t1, 2, max_len=32, return_cache=True)
    with pytest.raises(ValueError, match="batch"):
        generate(params, TINY, jnp.zeros((1, 2), jnp.int32), 2, cache=c2,
                 return_cache=True)
    with pytest.raises(ValueError, match="kv_dtype"):
        generate(params, TINY, t2, 2, cache=c2, kv_dtype="int8",
                 return_cache=True)


@pytest.mark.slow
def test_hf_import_llama_parity():
    """The flagship transformer IS the Llama graph: importing a random HF
    LlamaForCausalLM must reproduce its logits to float tolerance and its
    greedy generation token-for-token — the proof that every framework
    capability (TP decode, w8a16, speculative) applies to real public
    checkpoints."""
    torch = pytest.importorskip("torch")
    tfm = pytest.importorskip("transformers")

    from tony_tpu.models.generate import generate
    from tony_tpu.models.hf_import import config_from_hf, params_from_hf

    hf_cfg = tfm.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-6, rope_theta=10000.0)
    torch.manual_seed(0)
    hf = tfm.LlamaForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg, dtype=jnp.float32)
    params = params_from_hf(hf.state_dict(), cfg)

    ids = torch.randint(0, 128, (2, 16))
    with torch.no_grad():
        hf_logits = hf(ids).logits.numpy()
    ours = np.asarray(
        transformer.apply(params, jnp.asarray(ids.numpy()), cfg)[0])
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)

    hf_out = hf.generate(ids[:1], max_new_tokens=8,
                         do_sample=False)[0, 16:].numpy()
    ours_out = np.asarray(
        generate(params, cfg, jnp.asarray(ids[:1].numpy()), 8))[0]
    np.testing.assert_array_equal(hf_out, ours_out)


@pytest.mark.slow
def test_hf_import_mistral_sliding_window_parity():
    """Mistral variant: rms eps 1e-5 + sliding-window attention map onto
    cfg.norm_eps / cfg.attn_window; logits match at L > window where the
    band is active.

    Slow-marked with the rest of the hf-import cluster: whichever
    torch-importing test runs FIRST pays the ~20s+ torch+transformers
    import (this one, in file order — ROADMAP's '28s hf-import parity
    test'), so marking one test just migrates the bill; the whole
    cluster moves to the slow tier together and tier-1 keeps its
    headroom for the warm-pool tests."""
    torch = pytest.importorskip("torch")
    tfm = pytest.importorskip("transformers")

    from tony_tpu.models.hf_import import config_from_hf, params_from_hf

    hf_cfg = tfm.MistralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, sliding_window=8)
    torch.manual_seed(1)
    hf = tfm.MistralForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg, dtype=jnp.float32)
    assert cfg.attn_window == 8 and cfg.norm_eps == 1e-5
    params = params_from_hf(hf.state_dict(), cfg)
    ids = torch.randint(0, 128, (2, 32))
    with torch.no_grad():
        hf_logits = hf(ids).logits.numpy()
    ours = np.asarray(
        transformer.apply(params, jnp.asarray(ids.numpy()), cfg)[0])
    np.testing.assert_allclose(ours, hf_logits, rtol=3e-4, atol=3e-4)

    with pytest.raises(ValueError, match="unsupported model_type"):
        config_from_hf(tfm.GPT2Config())


@pytest.mark.slow
def test_hf_import_llama3_rope_scaling_parity():
    """Llama-3.x checkpoints ship rope_scaling (rope_type='llama3'): the
    scaled frequency table must reproduce the transformers implementation
    — logits to float tolerance at positions past the ORIGINAL context,
    where unscaled RoPE would rotate off the trained manifold."""
    torch = pytest.importorskip("torch")
    tfm = pytest.importorskip("transformers")

    from tony_tpu.models.hf_import import config_from_hf, params_from_hf

    hf_cfg = tfm.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=96, rope_theta=10000.0,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 32})
    torch.manual_seed(2)
    hf = tfm.LlamaForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg, dtype=jnp.float32)
    assert cfg.rope_scaling == ("llama3", 8.0, 1.0, 4.0, 32)
    params = params_from_hf(hf.state_dict(), cfg)
    # 80 positions: well past original_max_position_embeddings=32
    ids = torch.randint(0, 128, (2, 80))
    with torch.no_grad():
        hf_logits = hf(ids).logits.numpy()
    ours = np.asarray(
        transformer.apply(params, jnp.asarray(ids.numpy()), cfg)[0])
    np.testing.assert_allclose(ours, hf_logits, rtol=3e-4, atol=3e-4)

    with pytest.raises(ValueError, match="rope_scaling type"):
        config_from_hf(tfm.LlamaConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64,
            rope_scaling={"rope_type": "yarn", "factor": 4.0}))


@pytest.mark.slow
def test_hf_import_rejects_unimplemented_config_features():
    """Checkpoints whose configs need graph features the flagship does not
    implement (attention/mlp bias) must be rejected at import — silently
    dropping them would serve wrong logits."""
    torch = pytest.importorskip("torch")
    tfm = pytest.importorskip("transformers")

    from tony_tpu.models.hf_import import config_from_hf, params_from_hf

    base = dict(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64)

    biased = tfm.LlamaConfig(**base, attention_bias=True)
    with pytest.raises(ValueError, match="attention_bias"):
        config_from_hf(biased)

    # belt-and-suspenders: a state_dict that still carries bias tensors is
    # rejected even if the config gate were bypassed
    ok_cfg = config_from_hf(tfm.LlamaConfig(**base), dtype=jnp.float32)
    torch.manual_seed(0)
    sd = dict(tfm.LlamaForCausalLM(tfm.LlamaConfig(**base)).state_dict())
    sd["model.layers.0.self_attn.q_proj.bias"] = torch.zeros(64)
    with pytest.raises(ValueError, match="bias"):
        params_from_hf(sd, ok_cfg)


@pytest.mark.slow
def test_lm_generate_hf_checkpoint_serving(tmp_path):
    """lm_generate --hf-checkpoint serves a saved HF dir end to end, and
    tensor-parallel serving of the imported weights matches single-device
    token-for-token."""
    torch = pytest.importorskip("torch")
    tfm = pytest.importorskip("transformers")
    import json

    from tony_tpu.examples import lm_generate

    hf_cfg = tfm.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64)
    torch.manual_seed(0)
    tfm.LlamaForCausalLM(hf_cfg).save_pretrained(tmp_path / "hf")
    outs = []
    for i, extra in enumerate(([], ["--tensor-parallel", "2"])):
        out = tmp_path / f"gen{i}.json"
        rc = lm_generate.main(
            ["--hf-checkpoint", str(tmp_path / "hf"), "--dtype", "float32",
             "--prompt", "1 2 3 4", "--max-new", "8",
             "--metrics-out", str(out)] + extra)
        assert rc == 0
        outs.append(json.loads(out.read_text())["tokens"])
    assert outs[0] == outs[1] and len(outs[0]) == 8, outs


DRAFT_TINY = transformer.TransformerConfig(
    vocab_size=128, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
    d_ff=64, max_seq_len=64, dtype=jnp.float32, attn_impl="ref",
)


@pytest.mark.slow
def test_speculative_generate_exact_any_draft():
    """The acceptance rule guarantees output == vanilla greedy for ANY
    draft: a random (useless) draft and the target-as-its-own-draft must
    both reproduce generate()'s tokens exactly; self-draft accepts every
    proposal (rounds = ceil((N-1)/(gamma+1)) verify forwards)."""
    from tony_tpu.models.generate import generate
    from tony_tpu.models.speculative import speculative_generate

    tp = transformer.init(jax.random.PRNGKey(0), TINY)
    dp = transformer.init(jax.random.PRNGKey(7), DRAFT_TINY)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0,
                                TINY.vocab_size)
    ref = np.asarray(generate(tp, TINY, prompt, 12))

    out, stats = speculative_generate(tp, TINY, dp, DRAFT_TINY, prompt, 12,
                                      gamma=3, return_stats=True)
    np.testing.assert_array_equal(np.asarray(out), ref)
    assert stats["rounds"] >= 1

    out2, stats2 = speculative_generate(tp, TINY, tp, TINY, prompt, 12,
                                        gamma=3, return_stats=True)
    np.testing.assert_array_equal(np.asarray(out2), ref)
    assert stats2["acceptance_rate"] == 1.0
    assert stats2["rounds"] == -(-11 // 4)  # ceil((12-1)/(3+1))


@pytest.mark.slow
def test_speculative_generate_stop_tokens_match_generate():
    """EOS in the speculative path: output (stop kept, pad after) must
    match generate(stop_tokens=...) exactly for both a random draft and
    the high-acceptance self-draft (stop lands INSIDE an accepted prefix),
    and the round loop exits early."""
    from tony_tpu.models.generate import generate
    from tony_tpu.models.speculative import speculative_generate

    tp = transformer.init(jax.random.PRNGKey(0), TINY)
    dp = transformer.init(jax.random.PRNGKey(7), DRAFT_TINY)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0,
                                TINY.vocab_size)
    max_new = 14
    ref_free = np.asarray(generate(tp, TINY, prompt, max_new))
    stops = (int(ref_free[0, 4]),)
    pad = TINY.vocab_size - 1
    ref = np.asarray(generate(tp, TINY, prompt, max_new,
                              stop_tokens=stops, pad_id=pad))

    for draft_p, draft_c in ((dp, DRAFT_TINY), (tp, TINY)):
        out, stats = speculative_generate(
            tp, TINY, draft_p, draft_c, prompt, max_new, gamma=3,
            stop_tokens=stops, pad_id=pad, return_stats=True)
        np.testing.assert_array_equal(np.asarray(out), ref)
        # stop position bounds the verify-forward count
        assert stats["rounds"] <= 5, stats


def test_speculative_generate_moe_and_rejections():
    """MoE targets speculate too (drop-free capacity applied to both
    models); bad configs fail loudly."""
    import dataclasses

    from tony_tpu.models.generate import generate
    from tony_tpu.models.speculative import speculative_generate

    moe = dataclasses.replace(TINY, n_experts=4, expert_top_k=2,
                              capacity_factor=2.0)
    tp = transformer.init(jax.random.PRNGKey(0), moe)
    dp = transformer.init(jax.random.PRNGKey(7), DRAFT_TINY)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, 128)
    ref = np.asarray(generate(tp, moe, prompt, 8))
    out = speculative_generate(tp, moe, dp, DRAFT_TINY, prompt, 8, gamma=2)
    np.testing.assert_array_equal(np.asarray(out), ref)

    with pytest.raises(ValueError, match="batch-1"):
        speculative_generate(tp, moe, dp, DRAFT_TINY,
                             jnp.zeros((2, 4), jnp.int32), 4)
    with pytest.raises(ValueError, match="vocab"):
        bad = dataclasses.replace(DRAFT_TINY, vocab_size=256)
        speculative_generate(tp, moe, transformer.init(
            jax.random.PRNGKey(2), bad), bad, prompt, 4)
    with pytest.raises(ValueError, match="gamma"):
        speculative_generate(tp, moe, dp, DRAFT_TINY, prompt, 4, gamma=0)


def test_attn_window_model_variant():
    """Sliding-window config trains (ref path on CPU) and rejects the
    sequence-parallel combination."""
    import dataclasses

    cfg = dataclasses.replace(TINY, attn_window=8)
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    tokens, targets = synthetic_lm_batch(jax.random.PRNGKey(0), 2, 32, 128)
    loss, grads = jax.value_and_grad(transformer.loss_fn)(
        params, tokens, targets, cfg)
    assert np.isfinite(float(loss))
    # windowed loss differs from full-causal loss on the same params
    full = transformer.loss_fn(params, tokens, targets, TINY)
    assert abs(float(loss) - float(full)) > 1e-6

    bad = dataclasses.replace(TINY, attn_window=8, attn_impl="ring")
    mesh = build_mesh(MeshSpec(fsdp=1, seq=8))
    with pytest.raises(ValueError, match="attn_window"):
        transformer.loss_fn(params, tokens, targets, bad, mesh)


@pytest.mark.slow
def test_generate_sliding_window_matches_teacher_forcing():
    """Windowed models must decode with the trained band: cached decode ==
    full-forward argmax for attn_window configs, including prompts longer
    than the window."""
    import dataclasses
    from tony_tpu.models.generate import generate

    cfg = dataclasses.replace(TINY, attn_window=4)
    params = transformer.init(jax.random.PRNGKey(6), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(7), (1, 10), 0, cfg.vocab_size)
    out = generate(params, cfg, prompt, 5)
    seq = prompt
    for i in range(5):
        logits, _ = transformer.apply(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        np.testing.assert_array_equal(np.asarray(out[:, i]), np.asarray(nxt))
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
