"""Request-level serving telemetry (tony_tpu/observability.py).

The contract under test: every request that terminates — completed,
cancelled, expired, shed — leaves a complete, ordered lifecycle trace
(host-monotonic spans); the latency histograms those traces feed are
correct at the bucket level (boundaries, merge, quantiles); GET /metrics
renders everything in parseable Prometheus text format whose numbers
match /stats; and the 429 Retry-After header is a rate-derived estimate
that grows with the backlog instead of a constant. Model-backed tests
reuse the TINY shapes of tests/test_serving*.py so the tier-1 run hits
the already-compiled programs.
"""

import json
import re
import threading
import urllib.request
from http.server import ThreadingHTTPServer

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu import metrics as _metrics
from tony_tpu.cli.serve import ServeApp, make_handler
from tony_tpu.models import transformer
from tony_tpu.models.serving import (
    QueueFullError, Request, SlotServer,
)
from tony_tpu.observability import (
    Histogram,
    PromRenderer,
    RequestTrace,
    ServiceRateEstimator,
    ServingTelemetry,
    parse_prom_text,
)

TINY = transformer.TransformerConfig(
    vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=128, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return transformer.init(jax.random.PRNGKey(0), TINY)


def _srv(params, **kw):
    """Same shapes as tests/test_serving.py — the tier-1 run reuses the
    already-compiled programs."""
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_chunk", 8)
    return SlotServer(params, TINY, **kw)


def _prompt(n, seed=5):
    rng = np.random.default_rng(seed)
    return rng.integers(0, TINY.vocab_size, size=n, dtype=np.int32)


# --------------------------------------------------------------------------
# Histogram: boundaries, merge, quantiles
# --------------------------------------------------------------------------

def test_histogram_bucket_boundaries():
    h = Histogram(lo=1.0, hi=1000.0, per_decade=1)
    assert h.bounds == [1.0, 10.0, 100.0, 1000.0]
    h.observe(0.5)          # <= lo: first bucket
    h.observe(10.0)         # ON a boundary: le semantics, bucket le=10
    h.observe(10.0001)      # just past it: next bucket
    h.observe(5000.0)       # past hi: +Inf overflow
    assert h.counts == [1, 1, 1, 0, 1]
    assert h.count == 4
    assert h.sum == pytest.approx(0.5 + 10.0 + 10.0001 + 5000.0)


def test_histogram_merge():
    a = Histogram(lo=1.0, hi=100.0, per_decade=1)
    b = Histogram(lo=1.0, hi=100.0, per_decade=1)
    for v in (0.5, 5.0):
        a.observe(v)
    for v in (50.0, 5000.0):
        b.observe(v)
    a.merge(b)
    assert a.counts == [1, 1, 1, 1]
    assert a.count == 4 and a.sum == pytest.approx(5055.5)
    with pytest.raises(ValueError, match="different buckets"):
        a.merge(Histogram(lo=1.0, hi=100.0, per_decade=2))


def test_histogram_quantiles_known_distribution():
    h = Histogram(lo=1e-3, hi=100.0, per_decade=5)
    for k in range(1, 1001):                # uniform on (0, 1]
        h.observe(k / 1000.0)
    # bucket-resolution estimates: within the containing log bucket
    assert 0.35 < h.quantile(0.5) < 0.66
    assert 0.80 < h.quantile(0.99) <= 1.01
    qs = [h.quantile(q) for q in (0.0, 0.1, 0.5, 0.9, 0.99, 1.0)]
    assert qs == sorted(qs), "quantiles must be monotone in q"
    assert h.mean == pytest.approx(0.5005, rel=1e-6)
    assert Histogram().quantile(0.5) == 0.0         # empty: defined as 0
    with pytest.raises(ValueError):
        h.quantile(1.5)


def test_histogram_snapshot_shape():
    h = Histogram()
    h.observe(0.02)
    snap = h.snapshot()
    assert snap["count"] == 1
    assert set(snap) == {"count", "mean_s", "p50_s", "p90_s", "p99_s"}


# --------------------------------------------------------------------------
# Prometheus exposition: golden format
# --------------------------------------------------------------------------

# one exposition line: a comment, or name{labels} value
_PROM_LINE = re.compile(
    r"^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+|"
    r"[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [^\s]+)$")


def test_prom_renderer_golden():
    h = Histogram(lo=1.0, hi=100.0, per_decade=1)
    for v in (0.5, 5.0, 50.0, 500.0):
        h.observe(v)
    r = PromRenderer()
    r.gauge("g_one", 3, "a gauge")
    r.counter("c_total", 7, "a counter", labels={"kind": "x"})
    r.histogram("h_seconds", h, "a histogram")
    text = r.render()
    assert text == (
        "# HELP g_one a gauge\n"
        "# TYPE g_one gauge\n"
        "g_one 3\n"
        "# HELP c_total a counter\n"
        "# TYPE c_total counter\n"
        'c_total{kind="x"} 7\n'
        "# HELP h_seconds a histogram\n"
        "# TYPE h_seconds histogram\n"
        'h_seconds_bucket{le="1"} 1\n'
        'h_seconds_bucket{le="10"} 2\n'
        'h_seconds_bucket{le="100"} 3\n'
        'h_seconds_bucket{le="+Inf"} 4\n'
        "h_seconds_sum 555.5\n"
        "h_seconds_count 4\n"
    )
    for line in text.strip().splitlines():
        assert _PROM_LINE.match(line), f"unparseable line: {line!r}"


def test_prom_renderer_sanitizes_and_groups():
    r = PromRenderer()
    r.gauge("weird-name.x", 1, "g", labels={"a b": 'q"uote\nnl'})
    r.gauge("weird-name.x", 2, "g", labels={"a b": "two"})
    text = r.render()
    # one TYPE line for the family, two samples, escaped label value
    assert text.count("# TYPE weird_name_x gauge") == 1
    assert 'weird_name_x{a_b="q\\"uote\\nnl"} 1' in text
    assert 'weird_name_x{a_b="two"} 2' in text


# --------------------------------------------------------------------------
# Retry-After estimation
# --------------------------------------------------------------------------

def test_service_rate_estimator_retry_after():
    est = ServiceRateEstimator()
    assert est.retry_after_s(0, 8) == 1         # no observations: floor
    for _ in range(20):
        est.observe(8.0)
    assert est.service_time_s == pytest.approx(8.0)
    assert est.retry_after_s(0, 2) == 4         # 8s * 1 waiter / 2 slots
    assert est.retry_after_s(1000, 2) == 60     # ceiling clamp
    vals = [est.retry_after_s(q, 2) for q in range(0, 40, 4)]
    assert vals == sorted(vals) and vals[-1] > vals[0], (
        "Retry-After must grow with queue depth")
    fast = ServiceRateEstimator()
    fast.observe(0.01)
    assert fast.retry_after_s(0, 8) == 1        # sub-second: 1s floor


def test_retry_after_monotone_under_saturated_queue(params):
    """SlotServer surface: with a fixed observed service rate, every
    added waiter advances (never shrinks) the advertised retry — the
    header a saturated queue sends is ordered by backlog depth. No
    step() calls: submission-only, so no compiled programs run."""
    srv = _srv(params)
    srv._rate.observe(4.0)          # as if requests served in ~4s
    seen = []
    for i in range(12):
        srv.submit(Request(prompt=_prompt(3, seed=i), max_new_tokens=4))
        seen.append(srv.estimate_retry_after())
    assert seen == sorted(seen) and seen[-1] > seen[0]
    assert all(isinstance(v, int) and 1 <= v <= 60 for v in seen)


# --------------------------------------------------------------------------
# trace spans: ordering + completeness for every terminal
# --------------------------------------------------------------------------

def _span_names(comp):
    assert comp.trace is not None, "terminated request lost its trace"
    return [n for n, _ in comp.trace["spans"]]


def _assert_ordered(comp):
    ts = [t for _, t in comp.trace["spans"]]
    assert ts == sorted(ts), f"spans out of order: {comp.trace['spans']}"


def test_trace_lifecycle_every_terminal(params):
    """One server, four fates: a completed request records the full
    submitted->admitted->prefill_done->first_token->finished chain; a
    cancelled-in-queue request ends at cancelled with no admission; an
    expired request ends at expired; a shed request never enters the
    queue but still reaches the sink with a submitted->shed trace."""
    sink = []
    srv = _srv(params, max_queue=2, trace_sink=sink.append)
    a = Request(prompt=_prompt(5), max_new_tokens=6)
    b = Request(prompt=_prompt(4, seed=6), max_new_tokens=4)
    srv.submit(a)
    srv.submit(b)                   # queue now at max_queue=2
    shed_req = Request(prompt=_prompt(3, seed=7), max_new_tokens=4)
    with pytest.raises(QueueFullError) as shed_exc:
        srv.submit(shed_req)
    # the 429 handler reads the estimate off the error — no second
    # lock round trip on the shed fast path
    assert 1 <= shed_exc.value.retry_after_s <= 60
    assert srv.cancel(b.id) is True
    expired = Request(prompt=_prompt(4, seed=8), max_new_tokens=4,
                      deadline=-1.0)        # monotonic instant in the past
    srv.submit(expired)
    done = srv.run_until_drained()

    comp = done[a.id]
    assert comp.finish_reason == "length"
    assert _span_names(comp) == ["submitted", "admitted", "prefill_done",
                                 "first_token", "finished"]
    _assert_ordered(comp)
    assert comp.trace["attrs"]["n_tokens"] == len(comp.tokens) == 6
    assert comp.trace["attrs"]["finish_reason"] == "length"
    assert comp.trace["attrs"]["prefix_hit_blocks"] == 0
    assert comp.trace["attrs"]["prompt_tokens"] == 5

    assert _span_names(done[b.id]) == ["submitted", "cancelled"]
    assert _span_names(done[expired.id]) == ["submitted", "expired"]
    for rid in (b.id, expired.id):
        _assert_ordered(done[rid])

    # the shed request reached the sink even though submit() raised
    by_id = {r["id"]: r for r in sink}
    assert [n for n, _ in by_id[shed_req.id]["spans"]] == [
        "submitted", "shed"]
    assert set(by_id) == {a.id, b.id, expired.id, shed_req.id}, (
        "every terminated request must reach the trace sink")

    # histogram feed: only the served request has ttft/queue_wait/tpot,
    # every terminal contributes an e2e observation
    tel = srv.telemetry
    assert tel.hist["ttft_s"].count == 1
    assert tel.hist["queue_wait_s"].count == 1
    assert tel.hist["tpot_s"].count == 1
    assert tel.hist["e2e_s"].count == 4
    assert tel.hist["decode_block_s"].count == srv.blocks_dispatched > 0
    assert not srv._traces, "trace registry must drain with the requests"


def test_trace_mid_decode_cancel(params):
    """A request cancelled mid-decode still closes its trace in order:
    the spans it earned (admission, prefill, first token) stay, the
    terminal is cancelled, and n_tokens matches the partial output."""
    srv = _srv(params)
    a = Request(prompt=_prompt(4, seed=9), max_new_tokens=24)
    c = Request(prompt=_prompt(4, seed=10), max_new_tokens=24)
    srv.submit(a)
    srv.submit(c)
    for _ in range(3):
        srv.step()
    assert srv.cancel(a.id) is True
    done = srv.run_until_drained()
    comp = done[a.id]
    assert comp.finish_reason == "cancelled"
    names = _span_names(comp)
    assert names[0] == "submitted" and names[-1] == "cancelled"
    assert "admitted" in names and "prefill_done" in names
    _assert_ordered(comp)
    assert comp.trace["attrs"]["n_tokens"] == len(comp.tokens) > 0
    assert _span_names(done[c.id])[-1] == "finished"
    assert not srv._traces


def test_device_lag_measured_on_traces(params):
    """Device-time attribution on the live serving path: every served
    request's trace carries the MEASURED device lag (dispatch-tracker
    ready instant vs host observation) where the old contract only
    documented a pipeline_depth bound, the lag distribution feeds the
    device_lag_s histogram, and the tracker's per-kind dispatch→ready
    histograms cover prefill and decode blocks."""
    srv = _srv(params)
    try:
        a = Request(prompt=_prompt(5, seed=30), max_new_tokens=6)
        srv.submit(a)
        done = srv.run_until_drained()
        comp = done[a.id]
        assert comp.finish_reason == "length"
        lag = comp.trace["attrs"].get("device_lag_s")
        lag_ft = comp.trace["attrs"].get("device_lag_first_token_s")
        assert lag is not None and lag >= 0.0
        assert lag_ft is not None and lag_ft >= 0.0
        assert srv.telemetry.hist["device_lag_s"].count > 0
        assert srv.dispatch_tracker.drain(timeout=10)
        snap = srv.dispatch_tracker.snapshot()
        assert snap["in_flight"] == 0 and snap["dropped"] == 0
        assert snap["dispatch_ready"]["prefill"]["count"] >= 1
        assert snap["dispatch_ready"]["decode_block"]["count"] >= 1
        assert snap["tracked"] == sum(
            h["count"] for h in snap["dispatch_ready"].values())
        # stats() mirrors the tracker under "device"
        assert srv.stats()["device"]["tracked"] == snap["tracked"]
    finally:
        srv.shutdown()


def test_reset_seals_inflight_traces(params):
    """reset() with replay OFF must not leak traces: in-flight
    requests' traces end at the failed terminal, queued ones survive."""
    sink = []
    srv = _srv(params, trace_sink=sink.append, replay=False)
    a = Request(prompt=_prompt(4, seed=11), max_new_tokens=16)
    srv.submit(a)
    srv.step()                          # admit + first block
    queued = Request(prompt=_prompt(4, seed=12), max_new_tokens=4)
    srv.submit(queued)
    lost = srv.reset()
    assert lost == [a.id]
    by_id = {r["id"]: r for r in sink}
    assert [n for n, _ in by_id[a.id]["spans"]][-1] == "failed"
    assert queued.id in srv._traces, "queued request's trace must survive"
    done = srv.run_until_drained()
    assert _span_names(done[queued.id])[-1] == "finished"


def test_reset_replay_trace_continuity(params):
    """reset() with replay ON (default): the in-flight request's trace
    is NOT sealed — it gains a 'replayed' mark, repeats the admission
    chain, terminates once, and feeds the replay-catchup histogram."""
    sink = []
    srv = _srv(params, trace_sink=sink.append)
    a = Request(prompt=_prompt(4, seed=13), max_new_tokens=16)
    srv.submit(a)
    srv.step()                          # admit + first block
    assert srv.reset() == []
    assert not sink, "a replayed request's trace must not be sealed"
    done = srv.run_until_drained()
    names = _span_names(done[a.id])
    assert "replayed" in names and names[-1] == "finished"
    assert names.count("admitted") == 2, "the admission chain repeats"
    assert names.count("finished") == 1
    assert done[a.id].trace["attrs"]["replays"] == 1
    assert len(sink) == 1, "exactly one sealed record per request"
    assert srv.telemetry.hist["replay_catchup_s"].count == 1


# --------------------------------------------------------------------------
# GET /metrics: exposition golden test against a live serve instance
# --------------------------------------------------------------------------

def _parse_samples(text):
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name_labels, value = line.rsplit(" ", 1)
        out[name_labels] = float(value)
    return out


def test_metrics_endpoint_matches_stats(params):
    """GET /metrics on a running serve instance: Prometheus-parseable,
    contains the TTFT/TPOT/queue-wait histograms and every SERVING_*
    series, histogram buckets are cumulative with _count equal to the
    +Inf bucket, and the gauge values agree with GET /stats."""
    srv = _srv(params)
    app = ServeApp(srv)
    app.start()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(app))
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        comp = app.generate(_prompt(5, seed=13), 5, timeout=120)
        assert len(comp.tokens) == 5
        # let the dispatch reaper catch up so the device-time series are
        # consistent between the two scrapes below
        assert srv.dispatch_tracker.drain(timeout=10)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/stats", timeout=10) as r:
            stats = json.loads(r.read())

        for line in text.strip().splitlines():
            assert _PROM_LINE.match(line), f"unparseable line: {line!r}"
        # exposition conformance: the serve payload round-trips the
        # SHARED strict parser (the one the fleet hub scrapes with) —
        # cumulative buckets, +Inf == _count, no duplicate series
        fams = parse_prom_text(text, strict=True)
        assert "serving_ttft_seconds" in fams
        # every SERVING_* series named in metrics.py is present — except
        # the speculative families, which render only for spec-enabled
        # engines (this server has no draft; their live rendering is
        # asserted in tests/test_spec_serving.py's metrics-labels test),
        # and the paged-pool/KV-transfer families, which render only
        # for paged engines (live rendering asserted in
        # tests/test_streaming.py's disaggregated two-leg e2e)
        for attr in dir(_metrics):
            if attr.startswith("SERVING_") and \
                    not attr.startswith(("SERVING_SPEC_", "SERVING_KV_")):
                assert getattr(_metrics, attr) in text, (
                    f"{attr} series missing from /metrics")
        for fam in ("serving_ttft_seconds", "serving_tpot_seconds",
                    "serving_queue_wait_seconds", "serving_e2e_seconds",
                    "serving_device_lag_seconds",
                    "serving_xla_compile_seconds"):
            assert f"# TYPE {fam} histogram" in text
        # device-time attribution families: dispatch→ready per program
        # kind, the in-flight depth gauge, and the compile counters
        assert ('serving_dispatch_ready_seconds_bucket{kind="decode_block"'
                in text)
        assert 'serving_dispatch_ready_seconds_count{kind="prefill"}' in text
        assert "# TYPE serving_inflight_dispatches gauge" in text
        assert "# TYPE serving_xla_compiles_total counter" in text
        assert "serving_xla_recompiles_post_warm_total" in text

        samples = _parse_samples(text)
        assert samples["serving_inflight_dispatches"] == 0
        assert samples["serving_dispatches_tracked_total"] == (
            stats["device"]["tracked"]) > 0
        assert samples["serving_dispatch_track_dropped_total"] == 0
        assert samples["serving_dispatch_reap_errors_total"] == 0
        # a delivered completion drew the warmup line; the compile
        # snapshot on /stats matches the exposition counters
        assert stats["compile"]["warm"] is True
        assert samples["serving_xla_compiles_total"] == (
            stats["compile"]["compiles"])
        assert samples["serving_device_lag_seconds_count"] == (
            stats["latency"]["device_lag_s"]["count"]) > 0
        # histogram buckets are cumulative and consistent with _count —
        # the UNLABELED (process-aggregate) series; the {model=...}
        # partition interleaves its own cumulative series in the same
        # family (asserted in tests/test_spec_serving.py)
        buckets = [(nl, v) for nl, v in samples.items()
                   if nl.startswith('serving_ttft_seconds_bucket{le=')]
        counts = [v for _, v in buckets]
        assert counts and counts == sorted(counts), (
            "buckets must be cumulative")
        assert counts[-1] == samples["serving_ttft_seconds_count"] == 1
        # gauges/counters agree with /stats
        assert samples["serving_queue_depth"] == stats["queued"]
        assert samples["serving_active_slots"] == stats["active"]
        assert samples["serving_shed_total"] == stats["shed"]
        assert samples["serving_retry_after_s"] == stats["retry_after_s"]
        assert samples["serving_blocks_dispatched_total"] == (
            stats["blocks_dispatched"])
        # /stats grew the latency section with the same count
        assert stats["latency"]["ttft_s"]["count"] == 1
    finally:
        httpd.shutdown()
        httpd.server_close()
        app.shutdown()


# --------------------------------------------------------------------------
# satellites: StepTimer clock, telemetry plumbing units
# --------------------------------------------------------------------------

def test_step_timer_uses_monotonic_clock(monkeypatch):
    """Durations must come from time.monotonic() — a wall-clock jump
    (NTP) used to corrupt step stats with negative durations."""
    from tony_tpu.train import profiling

    fake = {"t": 100.0}
    monkeypatch.setattr(profiling.time, "monotonic", lambda: fake["t"])
    timer = profiling.StepTimer(window=4)
    timer.tick()
    fake["t"] += 2.5
    assert timer.tick() == pytest.approx(2.5)
    assert timer.steps_per_sec == pytest.approx(1 / 2.5)


def test_histogram_state_roundtrip():
    """state()/restore(): the persistence pair resumes cumulative
    buckets exactly (JSON round trip included — the serve CLI persists
    through json) and refuses mismatched bucket layouts."""
    h = Histogram(lo=1.0, hi=100.0, per_decade=1)
    for v in (0.5, 5.0, 5000.0):
        h.observe(v)
    dumped = json.loads(json.dumps(h.state()))
    h2 = Histogram(lo=1.0, hi=100.0, per_decade=1)
    h2.restore(dumped)
    assert h2.counts == h.counts
    assert h2.count == 3 and h2.sum == pytest.approx(h.sum)
    h2.observe(5.0)                     # restored histograms keep counting
    assert h2.count == 4
    with pytest.raises(ValueError, match="different buckets"):
        Histogram(lo=1.0, hi=100.0, per_decade=2).restore(dumped)


def test_telemetry_persists_across_reset_and_restart(params):
    """Histogram persistence (ROADMAP follow-up): SlotServer.reset()
    must NOT zero the latency histograms, and a fresh server (process
    restart) resumes the cumulative buckets via ServingTelemetry
    state()/restore() — /metrics rate() windows survive a re-arm."""
    srv = _srv(params)
    srv.submit(Request(prompt=_prompt(4, seed=20), max_new_tokens=4))
    srv.run_until_drained()
    assert srv.telemetry.hist["e2e_s"].count == 1
    ttft_sum = srv.telemetry.hist["ttft_s"].sum

    lost = srv.reset()                  # loop recovery: nothing in flight
    assert lost == []
    assert srv.telemetry.hist["e2e_s"].count == 1, (
        "reset() must preserve cumulative histogram buckets")

    state = json.loads(json.dumps(srv.telemetry.state()))
    srv2 = _srv(params)                 # fresh process: restore the dump
    srv2.telemetry.restore(state)
    assert srv2.telemetry.hist["ttft_s"].sum == pytest.approx(ttft_sum)
    srv2.submit(Request(prompt=_prompt(4, seed=21), max_new_tokens=4))
    srv2.run_until_drained()
    assert srv2.telemetry.hist["e2e_s"].count == 2, (
        "restored buckets must keep accumulating")
    # unknown histogram names in an old dump are skipped, not fatal
    srv2.telemetry.restore({"no_such_hist_s": {"bounds": [], "counts": [],
                                               "count": 0, "sum": 0.0}})


# --------------------------------------------------------------------------
# metrics-name lint: constants <-> renderers <-> docs must agree
# --------------------------------------------------------------------------

def test_metrics_names_rendered_and_documented():
    """Drift lint over the metric-name vocabulary: (a) every name
    constant in tony_tpu/metrics.py is documented in
    docs/observability.md; (b) every Prometheus-family constant
    (serving_*/driver_*/router_*) is referenced by a renderer
    (cli/serve.py, driver.py, portal/server.py, router.py); (c) every
    serving_/driver_/portal_/router_ family the doc names maps back to
    something the code actually renders. A new constant nobody renders,
    a renderer series nobody documents, or a doc entry for a deleted
    series all fail here."""
    import inspect
    from pathlib import Path

    import tony_tpu.cli.serve as serve_mod
    import tony_tpu.driver as driver_mod
    import tony_tpu.observability as obs
    import tony_tpu.portal.server as portal_mod
    import tony_tpu.router as router_mod
    import tony_tpu.slo as slo_mod

    consts = {name: val for name, val in vars(_metrics).items()
              if name.isupper() and isinstance(val, str)}
    assert consts, "metrics.py lost its name constants?"
    doc = (Path(__file__).resolve().parent.parent
           / "docs" / "observability.md").read_text()

    undocumented = sorted(v for v in consts.values() if f"`{v}`" not in doc)
    assert not undocumented, (
        f"metrics.py names missing from docs/observability.md "
        f"(backticked): {undocumented}")

    # slo.py renders INTO the driver's exposition (SLOEngine.render_into
    # appends the driver_slo_* families to the driver's renderer), so it
    # counts as a renderer source for the sweep
    sources = "".join(inspect.getsource(mod) for mod in
                      (serve_mod, driver_mod, portal_mod, router_mod,
                       slo_mod))
    unrendered = sorted(
        f"{name} ({val})" for name, val in consts.items()
        if val.startswith(("serving_", "driver_", "router_"))
        and name not in sources and f'"{val}"' not in sources)
    assert not unrendered, f"constants no renderer references: {unrendered}"

    rendered = set(consts.values())
    rendered |= set(re.findall(
        r'"((?:serving|driver|portal|router)_[a-z0-9_]+)"', sources))
    rendered |= {"serving_" + n[:-2] + "_seconds"
                 for n in obs.TELEMETRY_HISTOGRAMS}

    def base(name):
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in rendered:
                return name[:-len(suffix)]
        return name

    # PERF.json section names share the serving_ prefix but are bench
    # artifacts, not exposition families
    rendered |= {"serving_latency", "serving_robustness", "serving_fleet"}
    doc_names = set(re.findall(
        r"`((?:serving|driver|portal|router)_[a-z0-9_]+)`", doc))
    phantom = sorted(n for n in doc_names if base(n) not in rendered)
    assert not phantom, (
        f"docs/observability.md names no endpoint renders: {phantom}")

    # the device-time/compile families are pinned EXPLICITLY (not just
    # via the generic sweep): each must be rendered by an endpoint and
    # documented — renaming either side without the other fails here
    for fam in ("serving_dispatch_ready_seconds",
                "serving_inflight_dispatches",
                "serving_dispatches_tracked_total",
                "serving_dispatch_track_dropped_total",
                "serving_dispatch_reap_errors_total",
                "serving_device_lag_seconds",
                "serving_xla_compile_seconds",
                "serving_xla_compiles_total",
                "serving_xla_recompiles_post_warm_total",
                "driver_xla_compile_seconds",
                "driver_xla_compiles_total"):
        assert fam in rendered, f"device/compile family unrendered: {fam}"
        assert fam in doc_names, f"device/compile family undocumented: {fam}"

    # the fleet-router + fleet-replica families are pinned EXPLICITLY
    # the same way (ISSUE 7 lint discipline): each must be rendered by
    # an endpoint (router /metrics, driver /metrics) and documented —
    # renaming either side without the other fails here
    for fam in (_metrics.ROUTER_REPLICA_UP,
                _metrics.ROUTER_REPLICAS_LIVE,
                _metrics.ROUTER_REQUESTS_TOTAL,
                _metrics.ROUTER_RETRIES_TOTAL,
                _metrics.ROUTER_SHED_TOTAL,
                _metrics.ROUTER_FAILED_TOTAL,
                _metrics.ROUTER_EJECTIONS_TOTAL,
                _metrics.ROUTER_ROUTING_SECONDS,
                _metrics.ROUTER_E2E_SECONDS,
                _metrics.ROUTER_AFFINITY_HITS_TOTAL,
                _metrics.ROUTER_AFFINITY_REQUESTS_TOTAL,
                _metrics.ROUTER_AFFINITY_HIT_RATIO,
                _metrics.DRIVER_TASK_SERVICE_PORT,
                _metrics.DRIVER_TASK_ROLLS_TOTAL):
        assert fam in rendered, f"fleet family unrendered: {fam}"
        assert fam in doc_names, f"fleet family undocumented: {fam}"

    # the elastic-training families are pinned EXPLICITLY the same way
    # (ISSUE 9 lint discipline): each must be rendered by the driver
    # /metrics endpoint and documented — renaming either side without
    # the other fails here
    for fam in (_metrics.DRIVER_PREEMPTIONS_TOTAL,
                _metrics.DRIVER_GANG_RESIZES_TOTAL,
                _metrics.DRIVER_CHECKPOINT_AGE_S):
        assert fam in rendered, f"elastic family unrendered: {fam}"
        assert fam in doc_names, f"elastic family undocumented: {fam}"

    # the warm-pool families are pinned EXPLICITLY the same way
    # (ISSUE 10 lint discipline): each must be rendered by the driver
    # /metrics endpoint and documented — renaming either side without
    # the other fails here
    for fam in (_metrics.DRIVER_WARM_POOL_SIZE,
                _metrics.DRIVER_WARM_POOL_ADOPTIONS_TOTAL,
                _metrics.DRIVER_WARM_POOL_MISSES_TOTAL):
        assert fam in rendered, f"warm-pool family unrendered: {fam}"
        assert fam in doc_names, f"warm-pool family undocumented: {fam}"

    # the request-durability/replay families are pinned EXPLICITLY the
    # same way (ISSUE 11 lint discipline): each must be rendered by an
    # endpoint (serve /metrics, router /metrics) and documented —
    # renaming either side without the other fails here
    for fam in (_metrics.SERVING_REPLAYS_TOTAL,
                _metrics.SERVING_REPLAYED_TOKENS_TOTAL,
                _metrics.ROUTER_FAILOVERS_TOTAL,
                "serving_replay_catchup_seconds"):
        assert fam in rendered, f"replay family unrendered: {fam}"
        assert fam in doc_names, f"replay family undocumented: {fam}"

    # the control-plane-recovery families are pinned EXPLICITLY the
    # same way (ISSUE 12 lint discipline): each must be rendered by an
    # endpoint (driver /metrics, router /metrics) and documented —
    # renaming either side without the other fails here
    for fam in (_metrics.DRIVER_RECOVERIES_TOTAL,
                _metrics.DRIVER_TASKS_READOPTED_TOTAL,
                _metrics.ROUTER_DISCOVERY_STALE):
        assert fam in rendered, f"recovery family unrendered: {fam}"
        assert fam in doc_names, f"recovery family undocumented: {fam}"

    # the speculative-decoding + multi-model families are pinned
    # EXPLICITLY the same way (ISSUE 13 lint discipline): each must be
    # rendered by serve /metrics and documented — renaming either side
    # without the other fails here
    for fam in (_metrics.SERVING_MODELS,
                _metrics.SERVING_SPEC_ROUNDS_TOTAL,
                _metrics.SERVING_SPEC_PROPOSED_TOKENS_TOTAL,
                _metrics.SERVING_SPEC_ACCEPTED_TOKENS_TOTAL,
                _metrics.SERVING_SPEC_GAMMA,
                _metrics.SERVING_SPEC_ACCEPTANCE_RATE,
                _metrics.SERVING_SPEC_VERIFY_ROUNDS):
        assert fam in rendered, f"spec/model family unrendered: {fam}"
        assert fam in doc_names, f"spec/model family undocumented: {fam}"
    # the streaming-delivery families are pinned EXPLICITLY the same
    # way (ISSUE 14 lint discipline): each must be rendered by an
    # endpoint (serve /metrics, router /metrics) and documented —
    # renaming either side without the other fails here
    for fam in (_metrics.SERVING_STREAMS_ACTIVE,
                _metrics.SERVING_STREAMS_OPENED_TOTAL,
                _metrics.SERVING_STREAM_STALLS_TOTAL,
                _metrics.SERVING_STREAM_DISCONNECTS_TOTAL,
                _metrics.ROUTER_STREAMS_ACTIVE,
                _metrics.ROUTER_STREAMED_TOKENS_TOTAL,
                _metrics.ROUTER_STREAM_FAILOVERS_TOTAL,
                _metrics.ROUTER_STREAM_DISCONNECTS_TOTAL,
                "serving_stream_itl_seconds"):
        assert fam in rendered, f"streaming family unrendered: {fam}"
        assert fam in doc_names, f"streaming family undocumented: {fam}"

    # the autoscaler + quota families are pinned EXPLICITLY the same
    # way (ISSUE 15 lint discipline): each must be rendered by the
    # driver /metrics endpoint and documented — renaming either side
    # without the other fails here
    for fam in (_metrics.DRIVER_AUTOSCALE_SCALE_UPS_TOTAL,
                _metrics.DRIVER_AUTOSCALE_SCALE_DOWNS_TOTAL,
                _metrics.DRIVER_AUTOSCALE_REPLICAS,
                _metrics.DRIVER_AUTOSCALE_TTFT_P99_S,
                _metrics.DRIVER_AUTOSCALE_QUEUE_DEPTH,
                _metrics.DRIVER_QUOTA_POOL_SLOTS,
                _metrics.DRIVER_QUOTA_POOL_FREE,
                _metrics.DRIVER_QUOTA_SLOTS,
                _metrics.DRIVER_QUOTA_DONATIONS_TOTAL,
                _metrics.DRIVER_QUOTA_RECLAIMS_TOTAL):
        assert fam in rendered, f"autoscale/quota family unrendered: {fam}"
        assert fam in doc_names, (
            f"autoscale/quota family undocumented: {fam}")

    # the disaggregated-serving families are pinned EXPLICITLY the same
    # way (ISSUE 17 lint discipline): pool occupancy by owner plus the
    # KV-transfer counters on serve /metrics, and the split-request
    # accounting on router /metrics — each must be rendered and
    # documented; renaming either side without the other fails here
    for fam in (_metrics.SERVING_KV_POOL_BLOCKS,
                _metrics.SERVING_KV_EXPORTS_TOTAL,
                _metrics.SERVING_KV_IMPORTS_TOTAL,
                _metrics.SERVING_KV_IMPORT_REJECTS_TOTAL,
                _metrics.ROUTER_DISAGG_REQUESTS_TOTAL,
                _metrics.ROUTER_DISAGG_HANDOFFS_TOTAL,
                _metrics.ROUTER_DISAGG_FALLBACKS_TOTAL):
        assert fam in rendered, f"disagg family unrendered: {fam}"
        assert fam in doc_names, f"disagg family undocumented: {fam}"

    # the router-tier HA families are pinned EXPLICITLY the same way
    # (ISSUE 18 lint discipline): each front door's self-telemetry on
    # router /metrics, and the driver's {tier="router"} partition of
    # the autoscale families — each must be rendered and documented;
    # renaming either side without the other fails here
    for fam in (_metrics.ROUTER_FLEET_SIZE,
                _metrics.ROUTER_REPLICAS,
                _metrics.ROUTER_RELAY_INFLIGHT):
        assert fam in rendered, f"router-tier family unrendered: {fam}"
        assert fam in doc_names, f"router-tier family undocumented: {fam}"
    # the tier="router" label partition of the autoscale counters and
    # gauges is a rendered contract too, both directions: the driver
    # renderer must attach it and the doc must describe it
    driver_src = inspect.getsource(driver_mod)
    assert '{"tier": "router"}' in driver_src, (
        "driver /metrics lost its tier=router autoscale partition")
    assert 'tier="router"' in doc, (
        "docs/observability.md lost the tier=router label description")

    # the distributed-tracing families are pinned EXPLICITLY the same
    # way (ISSUE 19 lint discipline): the per-leg router histograms on
    # router /metrics — each must be rendered and documented; renaming
    # either side without the other fails here. The leg label
    # vocabulary is contract too, both directions: the router must
    # build a histogram per leg and the doc must name every leg.
    for fam in (_metrics.ROUTER_LEG_SECONDS,):
        assert fam in rendered, f"tracing family unrendered: {fam}"
        assert fam in doc_names, f"tracing family undocumented: {fam}"
    router_src = inspect.getsource(router_mod)
    for leg in ("prefill", "transfer", "decode", "relay"):
        assert f'"{leg}"' in router_src, (
            f"router lost the {leg} leg histogram")
        assert f"`{leg}`" in doc, (
            f"docs/observability.md lost the {leg} leg description")

    # the model-labeled partition is a rendered contract too: the serve
    # renderer must attach {model=...} labels somewhere (the per-model
    # block) and the doc must describe the label
    serve_src = inspect.getsource(serve_mod)
    assert '{"model": name}' in serve_src, (
        "serve /metrics lost its per-model label partition")
    assert "Per-model labels" in doc, (
        "docs/observability.md lost the per-model-labels section")

    # the metrics-pipeline + SLO families are pinned EXPLICITLY the
    # same way (ISSUE 20 lint discipline): the hub's self-telemetry,
    # the unified scrape-failure counter, and the burn-rate/budget/
    # alert families on driver /metrics — each must be rendered and
    # documented; renaming either side without the other fails here
    for fam in (_metrics.DRIVER_AUTOSCALE_SCRAPE_FAILURES_TOTAL,
                _metrics.DRIVER_METRICSHUB_SCRAPES_TOTAL,
                _metrics.DRIVER_METRICSHUB_SERIES,
                _metrics.DRIVER_METRICSHUB_TARGETS,
                _metrics.DRIVER_SLO_BURN_RATE,
                _metrics.DRIVER_SLO_ERROR_BUDGET_REMAINING,
                _metrics.DRIVER_SLO_ALERTS_FIRING):
        assert fam in rendered, f"slo/hub family unrendered: {fam}"
        assert fam in doc_names, f"slo/hub family undocumented: {fam}"


def test_finish_reason_vocabulary_pinned():
    """Lint over the finish_reason vocabulary, both directions: the
    constants in models/serving.py are the single source of truth, the
    code actually produces every value, docs/serving.md documents every
    value, the trace terminal set stays consistent with it, and the
    HTTP error mapping (shed -> 429, failed -> 503, router fleet-
    saturation -> 429) is still wired. A new terminal added to code
    without the enum/docs — or documented without being produced —
    fails here."""
    import inspect

    import tony_tpu.cli.serve as serve_mod
    import tony_tpu.models.serving as serving_mod
    import tony_tpu.router as router_mod
    from tony_tpu.models.serving import (
        COMPLETION_FINISH_REASONS, FINISH_REASONS,
    )

    # the pinned sets themselves (a rename/removal is a doc+router
    # migration, not a drive-by)
    assert COMPLETION_FINISH_REASONS == ("stop", "length", "cancelled",
                                         "expired", "shed", "prefilled")
    assert FINISH_REASONS == COMPLETION_FINISH_REASONS + ("failed",)
    # trace terminals <-> finish reasons: "finished" carries the
    # stop/length/prefilled reason in attrs; every other terminal IS
    # its reason
    from tony_tpu.observability import TERMINAL_SPANS

    assert set(TERMINAL_SPANS) - {"finished"} == \
        set(FINISH_REASONS) - set(("stop", "length", "prefilled"))
    assert "replayed" not in TERMINAL_SPANS, (
        "replay is a mid-life mark, never a terminal")

    serving_src = inspect.getsource(serving_mod)
    serve_src = inspect.getsource(serve_mod)
    router_src = inspect.getsource(router_mod)
    from pathlib import Path

    doc = (Path(__file__).resolve().parent.parent
           / "docs" / "serving.md").read_text()
    for reason in FINISH_REASONS:
        assert f'"{reason}"' in serving_src, (
            f"finish reason {reason!r} is in the enum but the engine "
            "source never names it")
        assert f'"{reason}"' in doc or f"`{reason}`" in doc, (
            f"finish reason {reason!r} undocumented in docs/serving.md")
    # the engine source names no finish_reason outside the enum: every
    # Completion(...) literal reason and _finish_trace terminal must be
    # in FINISH_REASONS (+ the trace-only "finished" wrapper)
    produced = set(re.findall(
        r'Completion\(\s*[\w.\[\]]+,\s*[\w.\[\]() ]+,\s*"(\w+)"',
        serving_src))
    produced |= set(re.findall(r'_finish_trace\([^)]*"(\w+)"', serving_src))
    produced |= set(re.findall(r'_seal_trace\([^)]*"(\w+)"', serving_src))
    unknown = produced - set(FINISH_REASONS) - {"finished"}
    assert not unknown, f"finish reasons outside the enum: {unknown}"
    assert {"cancelled", "expired", "failed", "shed"} <= produced, (
        f"enum reasons the engine no longer produces: {produced}")
    # HTTP mapping, both layers: shed -> 429 (serve QueueFullError, the
    # router's fleet saturation), failed/down -> 503
    assert "QueueFullError" in serve_src and "429" in serve_src
    assert "ServingLoopError" in serve_src and "503" in serve_src
    assert "FleetSaturatedError" in router_src and "429" in router_src


def test_telemetry_trace_feed_units():
    """observe_trace maps spans to the right histograms, including the
    per-token TPOT division, without a model in sight."""
    tel = ServingTelemetry()
    tr = RequestTrace(7)
    tr.mark("submitted", t=10.0)
    tr.mark("admitted", t=10.5)
    tr.mark("prefill_done", t=10.6)
    tr.mark("first_token", t=11.0)
    tr.attrs["n_tokens"] = 5
    tr.mark("finished", t=11.8)
    tel.observe_trace(tr)
    assert tel.hist["queue_wait_s"].sum == pytest.approx(0.5)
    assert tel.hist["prefill_s"].sum == pytest.approx(0.1)
    assert tel.hist["ttft_s"].sum == pytest.approx(1.0)
    assert tel.hist["e2e_s"].sum == pytest.approx(1.8)
    assert tel.hist["tpot_s"].sum == pytest.approx(0.8 / 4)  # (n-1) steps
    # a shed trace only feeds e2e
    tel2 = ServingTelemetry()
    shed = RequestTrace(8)
    shed.mark("submitted", t=1.0)
    shed.mark("shed", t=1.25)
    tel2.observe_trace(shed)
    assert tel2.hist["e2e_s"].count == 1
    assert tel2.hist["ttft_s"].count == 0
