"""Distributed request tracing (docs/observability.md "Distributed
tracing").

Contracts under test, bottom-up: the TraceContext identity rules
(random roots, DETERMINISTIC request_id-derived trace ids, header
adopt/malformed-reject, same-identity journal rebinding), the merge
layer (TraceCollector: cross-host wall re-anchoring, topological skew
repair, torn-line tolerance, the duplicate-span wall-clock fence,
orphan surfacing, interval-union coverage), the ``tony-tpu trace``
CLI over real files, the serve front door (header parse, journal
persistence + recovery lineage, response-header / SSE closing-frame
echo), and the router (header stamping on relays and both disagg
legs, deterministic cross-door trace join, the open write-ahead
record, per-leg histograms).
"""

import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.cli.main import main as cli_main
from tony_tpu.events.trace import (
    TraceCollector,
    coverage_s,
    render_waterfall,
)
from tony_tpu.observability import (
    TRACE_HEADER,
    TRACE_ID_RESPONSE_HEADER,
    RequestTrace,
    TraceContext,
)

TINY_KW = dict(slots=2, max_len=64, block_size=4, prefill_chunk=8)


# --------------------------------------------------------------------------
# TraceContext: the identity rules (no model, no HTTP)
# --------------------------------------------------------------------------

def test_context_mint_and_header_roundtrip():
    """A minted root has no parent; the header hop adopts the trace,
    records the SENDER's span as parent, and mints a fresh span — the
    one rule that makes every merged tree connect."""
    root = TraceContext.mint()
    assert root.parent_span_id is None
    assert root.trace_id != root.span_id
    hop = TraceContext.from_header(root.to_header())
    assert hop.trace_id == root.trace_id
    assert hop.parent_span_id == root.span_id
    assert hop.span_id not in (root.span_id, root.trace_id)
    # distinct mints never collide on either id
    other = TraceContext.mint()
    assert other.trace_id != root.trace_id


def test_context_for_request_id_is_deterministic():
    """The cross-door join: every shared-nothing door derives the SAME
    trace_id from the same client request_id (and different ids give
    different traces) — zero coordination, like the req:<id> progress
    key."""
    a1 = TraceContext.for_request_id("burst-7")
    a2 = TraceContext.for_request_id("burst-7")
    b = TraceContext.for_request_id("burst-8")
    assert a1.trace_id == a2.trace_id != b.trace_id
    # the trace id is stable across processes, so pin it
    assert len(a1.trace_id) == 16
    # spans stay fresh per door: same trace, different hop identity
    assert a1.span_id != a2.span_id


def test_context_from_header_rejects_malformed():
    """Tracing must never 400 a request: any malformed header value
    parses to None and the receiver mints a fresh root instead."""
    for bad in (None, "", "nocolon", "UPPER123:abcdef12", "ab:cdef",
                "abcdef12", "abcdef12:", ":abcdef12",
                "abcdef12:ghijklmn", "a" * 33 + ":" + "b" * 16,
                "abcdef12:abcd_f12"):
        assert TraceContext.from_header(bad) is None, bad


def test_context_from_dict_reuses_identity_child_is_fresh():
    """from_dict returns the SAME span identity (journal recovery must
    re-seal the dead attempt's span, not orphan a child under a parent
    that never wrote); child() is the explicit new-hop path."""
    ctx = TraceContext.from_header(TraceContext.mint().to_header())
    back = TraceContext.from_dict(ctx.as_dict())
    assert (back.trace_id, back.span_id, back.parent_span_id) == (
        ctx.trace_id, ctx.span_id, ctx.parent_span_id)
    kid = ctx.child()
    assert kid.trace_id == ctx.trace_id
    assert kid.parent_span_id == ctx.span_id
    assert kid.span_id != ctx.span_id
    assert TraceContext.from_dict(None) is None
    assert TraceContext.from_dict({"trace_id": "ab"}) is None


def test_request_trace_bind_rides_attrs():
    """Trace identity rides RequestTrace.attrs (to_dict unchanged), so
    every existing sink/record shape carries it for free."""
    tr = RequestTrace(3)
    ctx = TraceContext.mint()
    assert tr.bind(ctx) is tr
    assert tr.ctx is not None and tr.ctx.trace_id == ctx.trace_id
    rec = tr.to_dict()
    assert rec["attrs"]["trace_id"] == ctx.trace_id
    assert rec["attrs"]["span_id"] == ctx.span_id
    # unbound traces merge to nothing, not errors
    assert RequestTrace(4).ctx is None


# --------------------------------------------------------------------------
# TraceCollector: the cross-host merge
# --------------------------------------------------------------------------

def _rec(tid, sid, parent, service, unix, spans, rid=1, **attrs):
    a = {"trace_id": tid, "span_id": sid, "parent_span_id": parent,
         "service": service, "submitted_unix": unix, **attrs}
    return {"id": rid, "spans": [list(s) for s in spans], "attrs": a}


def test_collector_merges_and_repairs_clock_skew(tmp_path):
    """Two tiers on two (simulated) hosts, the child's wall anchor 1.2s
    BEHIND its parent: the merge re-anchors each record by its own
    submitted_unix, then shifts the skewed child forward to its
    parent's start — causality beats wall clocks, and the shift is
    surfaced as reanchored_s, never hidden."""
    router = _rec("t1", "aaaa1111", None, "router", 1000.0,
                  [["submitted", 50.0], ["routed", 50.1],
                   ["finished", 52.0]], router="r0")
    serve = _rec("t1", "bbbb2222", "aaaa1111", "serve", 998.8,
                 [["submitted", 7.0], ["admitted", 7.1],
                  ["finished", 8.5]], replica="rep0")
    (tmp_path / "a").mkdir(), (tmp_path / "b").mkdir()
    (tmp_path / "a" / "requests.trace.jsonl").write_text(
        json.dumps(router) + "\n")
    (tmp_path / "b" / "requests.trace.jsonl").write_text(
        json.dumps(serve) + "\n")
    col = TraceCollector()
    col.add_file(tmp_path / "a" / "requests.trace.jsonl")
    col.add_file(tmp_path / "b" / "requests.trace.jsonl")
    traces = col.merged()
    assert set(traces) == {"t1"}
    t = traces["t1"]
    assert [s["span_id"] for s in t["spans"]] == ["aaaa1111", "bbbb2222"]
    assert t["orphans"] == []
    parent, child = t["spans"]
    assert parent["start"] == pytest.approx(1000.0)
    # unskewed the child would start at 998.8 < 1000.0 — shifted
    assert child["start"] == pytest.approx(parent["start"])
    assert child["reanchored_s"] == pytest.approx(1.2)
    assert child["end"] - child["start"] == pytest.approx(1.5)
    # the waterfall renders both tiers and surfaces the shift
    text = render_waterfall(t)
    assert "router[r0]" in text and "serve[rep0]" in text
    assert "reanchored+1.200s" in text


def test_collector_fences_duplicate_span_pushes():
    """The wall-clock fence for re-pushed span identities: a sealed
    record supersedes the door's open write-ahead record regardless of
    push order, and among equally-rich seals the newest submitted_unix
    wins — a recovered attempt's re-seal never loses to a stale one."""
    open_rec = _rec("t1", "aaaa1111", None, "router", 1000.0,
                    [["submitted", 1.0]])
    sealed = _rec("t1", "aaaa1111", None, "router", 1000.0,
                  [["submitted", 1.0], ["finished", 2.0]])
    col = TraceCollector()
    col.add_record(sealed)
    col.add_record(open_rec)        # arrives late: still loses
    assert col.superseded == 1
    t = col.merged()["t1"]
    assert len(t["spans"]) == 1 and t["spans"][0]["terminal"] == "finished"
    # equally rich: newer wall anchor wins
    newer = _rec("t2", "cccc3333", None, "serve", 2000.0,
                 [["submitted", 1.0], ["finished", 2.0]], marker="new")
    older = _rec("t2", "cccc3333", None, "serve", 1990.0,
                 [["submitted", 1.0], ["finished", 2.0]], marker="old")
    col2 = TraceCollector()
    col2.add_record(older)
    col2.add_record(newer)
    assert col2.merged()["t2"]["spans"][0]["attrs"]["marker"] == "new"
    col3 = TraceCollector()
    col3.add_record(newer)
    col3.add_record(older)          # order-independent
    assert col3.merged()["t2"]["spans"][0]["attrs"]["marker"] == "new"


def test_collector_tolerates_torn_lines_and_identityless(tmp_path):
    """A crash mid-append tears one line; pre-tracing records carry no
    trace identity. Neither hides the other requests' spans and both
    are counted, not raised."""
    good = _rec("t1", "aaaa1111", None, "serve", 1000.0,
                [["submitted", 0.0], ["finished", 1.0]])
    legacy = {"id": 9, "spans": [["submitted", 0.0], ["finished", 1.0]],
              "attrs": {"submitted_unix": 1000.0}}
    path = tmp_path / "requests.trace.jsonl"
    path.write_text(json.dumps(legacy) + "\n"
                    + '{"id": 3, "spans": [["subm'  # torn by SIGKILL
                    + "\n" + json.dumps(good) + "\n")
    col = TraceCollector()
    col.add_file(path)
    col.add_file(tmp_path / "never-written.trace.jsonl")  # no-op
    assert col.files_read == 1 and col.skipped == 1
    traces = col.merged()
    assert set(traces) == {"t1"}
    assert len(traces["t1"]["spans"]) == 1


def test_collector_surfaces_orphans_and_coverage():
    """A span whose parent never produced a record is an orphan —
    surfaced, never dropped (the bench gate asserts zero of these);
    coverage is the UNION of span intervals so overlapping legs don't
    double count."""
    col = TraceCollector()
    col.add_record(_rec("t1", "aaaa1111", None, "router", 1000.0,
                        [["submitted", 0.0], ["finished", 4.0]]))
    col.add_record(_rec("t1", "bbbb2222", "aaaa1111", "serve", 1000.5,
                        [["submitted", 0.0], ["finished", 2.0]]))
    col.add_record(_rec("t1", "dddd4444", "gone0000", "serve", 1001.0,
                        [["submitted", 0.0], ["finished", 1.0]]))
    t = col.merged()["t1"]
    assert t["orphans"] == ["dddd4444"]
    assert "orphans: dddd4444" in render_waterfall(t)
    # intervals: [1000,1004] ∪ [1000.5,1002.5] ∪ [1001,1002] = 4.0
    assert coverage_s(t) == pytest.approx(4.0)
    # disjoint intervals sum
    assert coverage_s({"spans": [
        {"start": 0.0, "end": 1.0}, {"start": 3.0, "end": 4.5},
    ]}) == pytest.approx(2.5)


def test_cli_trace_lists_and_renders(tmp_path, capsys):
    """``tony-tpu trace`` end to end over real files: the bare listing
    is slowest-first with failure/orphan flags, the id view prints the
    waterfall, and unknown ids / empty dirs exit 1 with a reason."""
    slow = _rec("aaaa0000aaaa0000", "aaaa1111", None, "router", 1000.0,
                [["submitted", 0.0], ["finished", 5.0]], router="r0")
    fast = _rec("bbbb0000bbbb0000", "bbbb1111", None, "serve", 1000.0,
                [["submitted", 0.0], ["failed", 0.5]])
    d = tmp_path / "tier"
    d.mkdir()
    (d / "requests.trace.jsonl").write_text(
        json.dumps(slow) + "\n" + json.dumps(fast) + "\n")
    # task traces are a different granularity: never merged in
    (d / "tasks.trace.jsonl").write_text(json.dumps(slow) + "\n")
    assert cli_main(["trace", "--dir", str(d)]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert out[0].startswith("aaaa0000aaaa0000")     # slowest first
    assert "FAILED" in out[1]
    assert cli_main(["trace", "aaaa0000aaaa0000", "--dir", str(d)]) == 0
    assert "router[r0]" in capsys.readouterr().out
    assert cli_main(["trace", "zzzz", "--dir", str(d)]) == 1
    empty = tmp_path / "empty"
    empty.mkdir()
    assert cli_main(["trace", "--dir", str(empty)]) == 1


# --------------------------------------------------------------------------
# serve front door: header parse, journal lineage, response echo
# --------------------------------------------------------------------------

from tony_tpu.models import transformer  # noqa: E402

TINY = transformer.TransformerConfig(
    vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=128, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return transformer.init(jax.random.PRNGKey(0), TINY)


def _prompt(n, seed=3):
    return np.asarray(jax.random.randint(
        jax.random.PRNGKey(seed), (n,), 0, TINY.vocab_size), np.int32)


def _http_app(params, **kw):
    from tony_tpu.cli.serve import ServeApp, make_handler
    from tony_tpu.models.serving import SlotServer

    for k, v in TINY_KW.items():
        kw.setdefault(k, v)
    records = []
    srv = SlotServer(params, TINY, trace_sink=records.append, **kw)
    app = ServeApp(srv)
    app.start()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(app))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return srv, app, httpd, httpd.server_address[1], records


def test_serve_front_door_trace_contract(params):
    """The serve door end to end: an inbound X-Tony-Trace is adopted
    (sender's span becomes the parent, fresh span minted), echoed back
    as X-Tony-Trace-Id on the buffered response AND as the closing
    SSE frame's trace_id, and the sealed trace record carries the full
    identity; a header-less request mints its own root."""
    srv, app, httpd, port, records = _http_app(params)
    try:
        sender = TraceContext.mint()
        prompt = [int(t) for t in _prompt(5, seed=11)]
        body = json.dumps({"prompt": prompt, "max_new_tokens": 4}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json",
                     TRACE_HEADER: sender.to_header()})
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.headers[TRACE_ID_RESPONSE_HEADER] == sender.trace_id
            json.loads(r.read().decode())
        deadline = time.monotonic() + 30
        while not records and time.monotonic() < deadline:
            time.sleep(0.02)
        attrs = records[-1]["attrs"]
        assert attrs["trace_id"] == sender.trace_id
        assert attrs["parent_span_id"] == sender.span_id
        assert attrs["span_id"] != sender.span_id
        assert attrs["service"] == "serve"
        # SSE: the closing frame carries the trace id (headers are
        # long gone by then); malformed inbound header -> fresh root
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate?stream=true", data=body,
            headers={"Content-Type": "application/json",
                     TRACE_HEADER: "NOT A:HEADER"})
        frames = []
        with urllib.request.urlopen(req, timeout=120) as r:
            for raw in r:
                line = raw.decode().strip()
                if line.startswith("data: "):
                    frames.append(json.loads(line[len("data: "):]))
        final = frames[-1]
        assert final["finish_reason"] == "length"
        assert final["trace_id"] not in ("", None, sender.trace_id)
        # /v1 buffered responses echo the id too
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/completions",
            data=json.dumps({"prompt": prompt, "max_tokens": 3}).encode(),
            headers={"Content-Type": "application/json",
                     TRACE_HEADER: sender.to_header()})
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.headers[TRACE_ID_RESPONSE_HEADER] == sender.trace_id
    finally:
        httpd.shutdown()
        httpd.server_close()
        app.shutdown()


def test_journal_preserves_trace_identity_across_recovery(
        tmp_path, params):
    """SIGKILL lineage: the trace context persists into the journal
    (through compaction), and the recovered request re-binds the dead
    attempt's EXACT span identity — the merged trace shows one span
    with recovered_from lineage, never an orphaned child of a span
    nobody sealed."""
    from tony_tpu.events.journal import JOURNAL_FILE, RequestJournal
    from tony_tpu.models.serving import Request, SlotServer

    path = tmp_path / JOURNAL_FILE
    ctx = TraceContext.from_header(TraceContext.mint().to_header())
    srv1 = SlotServer(params, TINY, journal=RequestJournal(path),
                      **TINY_KW)
    req = Request(prompt=_prompt(4, seed=21), max_new_tokens=20,
                  trace=ctx)
    srv1.submit(req)
    for _ in range(2):
        srv1.step()
    srv1.drain_completed()          # prefix journaled; then "SIGKILL"
    j2, entries = RequestJournal.recover(path)
    assert len(entries) == 1
    assert entries[0].trace == ctx.as_dict(), (
        "trace context lost by the journal round-trip/compaction")
    sunk = []
    srv2 = SlotServer(params, TINY, journal=j2, trace_sink=sunk.append,
                      **TINY_KW)
    assert srv2.recover_journal(entries) == 1
    done = srv2.run_until_drained()
    (comp,) = done.values()
    attrs = comp.trace["attrs"]
    assert attrs["recovered_from"] == req.id
    assert attrs["trace_id"] == ctx.trace_id
    assert attrs["span_id"] == ctx.span_id, (
        "recovery must reuse the dead attempt's span identity")
    assert attrs["parent_span_id"] == ctx.parent_span_id
    srv1.shutdown()
    srv2.shutdown()


# --------------------------------------------------------------------------
# router: header stamping, cross-door join, open record, leg histograms
# --------------------------------------------------------------------------

class _TraceStub:
    """Header-recording fake replica: /generate answers one token (or
    a prefill handoff), /kv/import answers a decode completion; every
    POST's headers land in .post_headers by path."""

    def __init__(self, role=None, handoff=None):
        self.role = role
        self.handoff = handoff
        self.post_headers = []          # (path, headers-dict) pairs
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._send(200, {"healthy": True})
                elif self.path == "/stats":
                    payload = {"queued": 0, "active": 0, "slots": 2,
                               "max_queue": 0, "retry_after_s": 1}
                    if stub.role is not None:
                        payload["role"] = stub.role
                    self._send(200, payload)
                else:
                    self._send(200, {})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", "0"))
                json.loads(self.rfile.read(n) or b"{}")
                path = self.path.partition("?")[0]
                stub.post_headers.append((path, dict(self.headers)))
                if path == "/kv/import":
                    self._send(200, {"id": 1, "tokens": [7, 8],
                                     "finish_reason": "length"})
                elif stub.role == "prefill":
                    resp = {"id": 1, "tokens": [],
                            "finish_reason": "prefilled"}
                    if stub.handoff is not None:
                        resp["handoff"] = stub.handoff
                    self._send(200, resp)
                else:
                    self._send(200, {"id": 1, "tokens": [5],
                                     "finish_reason": "length"})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def headers_for(self, path):
        return [h for p, h in self.post_headers if p == path]

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_router_stamps_header_writes_open_record_and_legs():
    """One classic relay: the replica receives X-Tony-Trace carrying
    the ROUTER's span (so the replica's span parents under it), a
    request_id derives the deterministic cross-door trace_id, the
    sink sees the open write-ahead record BEFORE the sealed terminal
    (same span identity — a SIGKILLed door still leaves its relay
    span), and router_leg_seconds{leg="relay"} observes the hop."""
    from tony_tpu.router import FleetRouter

    stub = _TraceStub()
    sunk = []
    router = FleetRouter([("r0", "127.0.0.1", stub.port)], seed=0,
                         stats_every=1, trace_sink=sunk.append)
    try:
        router.health_tick()
        resp = router.generate([1, 2, 3], max_new_tokens=1, timeout_s=5,
                               request_id="req-42")
        assert resp["finish_reason"] == "length"
        (hdrs,) = stub.headers_for("/generate")
        got = TraceContext.from_header(hdrs.get(TRACE_HEADER))
        assert got is not None, "router did not stamp X-Tony-Trace"
        want = TraceContext.for_request_id("req-42")
        assert got.trace_id == want.trace_id, (
            "request_id must derive the deterministic trace_id")
        assert len(sunk) == 2, "expected open + sealed records"
        opened, sealed = sunk
        assert opened["attrs"]["span_id"] == sealed["attrs"]["span_id"]
        assert opened["spans"][-1][0] not in ("finished", "failed")
        assert sealed["spans"][-1][0] == "finished"
        # the replica parents under the router's span
        assert hdrs[TRACE_HEADER].endswith(sealed["attrs"]["span_id"])
        assert sealed["attrs"]["service"] == "router"
        assert sealed["attrs"]["leg_relay_s"] >= 0
        # the merge fences the open record under the sealed one
        col = TraceCollector()
        for rec in sunk:
            col.add_record(rec)
        assert col.superseded == 1
        text = router.prometheus_metrics()
        assert 'router_leg_seconds_bucket{leg="relay"' in text
        assert 'router_leg_seconds_count{leg="relay"} 1' in text
    finally:
        router.shutdown()
        stub.close()


def test_router_disagg_legs_share_one_trace():
    """The disaggregated split: prefill POST and /kv/import handoff
    both carry the SAME X-Tony-Trace value (one router span fathering
    both legs), and the prefill/decode leg histograms observe — the
    request is one story across three processes."""
    from tony_tpu.router import FleetRouter

    handoff = {"version": 1, "entry": {"id": 5, "prompt": [1, 2]}}
    pre = _TraceStub(role="prefill", handoff=handoff)
    dec = _TraceStub(role="decode")
    router = FleetRouter([("pre", "127.0.0.1", pre.port),
                          ("dec", "127.0.0.1", dec.port)],
                         seed=0, stats_every=1)
    try:
        router.health_tick()
        resp = router.generate([1, 2, 3, 4], max_new_tokens=2,
                               timeout_s=5)
        assert resp["tokens"] == [7, 8]
        (pre_hdrs,) = pre.headers_for("/generate")
        (imp_hdrs,) = dec.headers_for("/kv/import")
        assert pre_hdrs.get(TRACE_HEADER) is not None
        assert pre_hdrs[TRACE_HEADER] == imp_hdrs[TRACE_HEADER]
        text = router.prometheus_metrics()
        assert 'router_leg_seconds_count{leg="prefill"} 1' in text
        assert 'router_leg_seconds_count{leg="decode"} 1' in text
    finally:
        router.shutdown()
        pre.close()
        dec.close()
