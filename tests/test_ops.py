"""Pallas op tests (interpret mode on CPU): flash attention vs reference.

Every flash test runs THREE times via the autouse `attn_path` fixture:
on the VMEM-resident kernels (the default at CI-sized L), with
RESIDENT_MAX_L forced to 0 (the fused-streaming mid tier, 2048 < L <=
8192 in production), and with FUSED_STREAM_MAX_L also 0 (the split
dq/dkv O(block)-memory kernels that serve the longest sequences)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.ops import flash_attention, attention_blhd
from tony_tpu.parallel import reference_attention


@pytest.fixture(params=["resident", "stream_fused", "stream_split"],
                autouse=True)
def attn_path(request, monkeypatch):
    if request.param == "resident":
        yield request.param
        return
    import tony_tpu.ops.attention as A

    monkeypatch.setattr(A, "RESIDENT_MAX_L", 0)
    if request.param == "stream_split":
        monkeypatch.setattr(A, "FUSED_STREAM_MAX_L", 0)
    # _flash_fwd/_flash_bwd are jitted and the dispatch reads the module
    # globals at TRACE time — stale cache entries would silently run the
    # other path, so retrace everything on entry and exit
    jax.clear_caches()
    yield request.param
    jax.clear_caches()


def _ref_bhld(q, k, v, causal):
    # reference is [B, L, H, D]; ours is [B, H, L, D]
    o = reference_attention(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), causal=causal,
    )
    return o.transpose(0, 2, 1, 3)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("l", [128, 256])
def test_flash_matches_reference(causal, l):
    key = jax.random.PRNGKey(0)
    b, h, d = 2, 2, 32
    q, k, v = (
        jax.random.normal(kk, (b, h, l, d), jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    out = flash_attention(q, k, v, causal=causal)
    expected = _ref_bhld(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_flash_ragged_length_causal():
    """L not divisible by the block size exercises padding."""
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 200, 16))
    out = flash_attention(q, q, q, causal=True)
    expected = _ref_bhld(q, q, q, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_ragged_padded_blocks(causal):
    """L=300 pads to a 384-row block: padded KV columns must be masked
    in-kernel and padded Q rows zeroed via the lse residual (regression: the
    old lse=-inf padding made p=exp(s+1e30)=inf -> NaN dK/dV). Multi-block
    grids are covered by test_flash_multi_qblock_paths_small_blocks."""
    keys = jax.random.split(jax.random.PRNGKey(7), 3)
    q, k, v = (jax.random.normal(kk, (1, 2, 300, 16)) for kk in keys)
    out = flash_attention(q, k, v, causal=causal)
    expected = _ref_bhld(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)

    def loss_flash(args):
        return jnp.sum(flash_attention(*args, causal=causal) ** 2)

    def loss_ref(args):
        return jnp.sum(_ref_bhld(*args, causal) ** 2)

    g1 = jax.grad(loss_flash)((q, k, v))
    g2 = jax.grad(loss_ref)((q, k, v))
    for a, b in zip(g1, g2):
        assert bool(jnp.all(jnp.isfinite(a)))
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_cross_attention_ragged_kv():
    """L_q != L_k with ragged L_k (non-causal cross attention)."""
    q = jax.random.normal(jax.random.PRNGKey(8), (1, 2, 300, 16))
    k = jax.random.normal(jax.random.PRNGKey(9), (1, 2, 520, 16))
    out = flash_attention(q, k, k, causal=False)
    expected = _ref_bhld(q, k, k, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_flash_gradients_match_reference():
    q = jax.random.normal(jax.random.PRNGKey(1), (1, 2, 128, 16))

    def loss_flash(q):
        return jnp.sum(flash_attention(q, q, q, causal=True) ** 2)

    def loss_ref(q):
        return jnp.sum(_ref_bhld(q, q, q, True) ** 2)

    g1 = jax.grad(loss_flash)(q)
    g2 = jax.grad(loss_ref)(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-4)


def test_attention_blhd_layout():
    q = jax.random.normal(jax.random.PRNGKey(2), (2, 64, 4, 16))  # [B,L,H,D]
    out = attention_blhd(q, q, q, causal=True)
    expected = reference_attention(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_flash_bfloat16():
    q = jax.random.normal(jax.random.PRNGKey(3), (1, 2, 128, 32), jnp.bfloat16)
    out = flash_attention(q, q, q, causal=True)
    assert out.dtype == jnp.bfloat16
    expected = _ref_bhld(
        q.astype(jnp.float32), q.astype(jnp.float32), q.astype(jnp.float32), True
    )
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), np.asarray(expected), atol=3e-2
    )


# -------------------------------------------------------- blockwise CE

@pytest.mark.parametrize("v,block_v", [(64, 16), (50, 16), (40, 64)])
def test_blockwise_ce_matches_dense(v, block_v):
    """Streaming logsumexp + in-block target gather == dense log_softmax,
    including ragged vocab (v % block != 0) and block > vocab."""
    from tony_tpu.ops import blockwise_cross_entropy, dense_cross_entropy

    key = jax.random.PRNGKey(0)
    n, d = 32, 16
    x = jax.random.normal(key, (n, d), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (d, v), jnp.float32)
    t = jax.random.randint(jax.random.PRNGKey(2), (n,), 0, v)
    nll = blockwise_cross_entropy(x, w, t, block_v)
    expected = dense_cross_entropy(x, w, t)
    np.testing.assert_allclose(np.asarray(nll), np.asarray(expected), atol=1e-5)


def test_blockwise_ce_gradients_match_dense():
    """Custom VJP (blockwise dx and dW, never [N,V]) == XLA autodiff of the
    dense path, for a non-uniform per-row cotangent."""
    from tony_tpu.ops import blockwise_cross_entropy, dense_cross_entropy

    n, d, v, bv = 24, 8, 50, 16
    x = jax.random.normal(jax.random.PRNGKey(3), (n, d), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(4), (d, v), jnp.float32)
    t = jax.random.randint(jax.random.PRNGKey(5), (n,), 0, v)
    weights = jnp.linspace(0.1, 1.0, n)

    def loss_blk(x, w):
        return jnp.sum(blockwise_cross_entropy(x, w, t, bv) * weights)

    def loss_dense(x, w):
        return jnp.sum(dense_cross_entropy(x, w, t) * weights)

    gx1, gw1 = jax.grad(loss_blk, argnums=(0, 1))(x, w)
    gx2, gw2 = jax.grad(loss_dense, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx2), atol=1e-5)
    np.testing.assert_allclose(np.asarray(gw1), np.asarray(gw2), atol=1e-5)


def test_blockwise_ce_bfloat16_inputs():
    from tony_tpu.ops import blockwise_cross_entropy, dense_cross_entropy

    n, d, v = 16, 8, 64
    x = jax.random.normal(jax.random.PRNGKey(6), (n, d), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(7), (d, v), jnp.bfloat16)
    t = jax.random.randint(jax.random.PRNGKey(8), (n,), 0, v)
    nll = blockwise_cross_entropy(x, w, t, 16)
    assert nll.dtype == jnp.float32
    expected = dense_cross_entropy(x.astype(jnp.float32), w.astype(jnp.float32), t)
    np.testing.assert_allclose(np.asarray(nll), np.asarray(expected), atol=5e-2)


def test_flash_multi_qblock_paths_small_blocks():
    """Force nq>1 and nk>1 with explicit 128-row blocks (the default
    BLOCK_Q=512 makes every CI-sized sequence a single block, which would
    leave the qi>0 causal pruning untested). Under attn_path='streaming'
    this also exercises the _dkv diagonal-down lo start and the
    double-buffer slot rotation; under 'resident' the static tile
    classification."""
    from tony_tpu.ops.attention import _flash_bwd, _flash_fwd

    keys = jax.random.split(jax.random.PRNGKey(11), 4)
    q, k, v, g = (jax.random.normal(kk, (1, 2, 300, 16)) for kk in keys)
    out, lse = _flash_fwd(q, k, v, True, None, block_q=128, block_k=128,
                          interpret=True)
    expected = _ref_bhld(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)

    dq, dk, dv = _flash_bwd(q, k, v, out, lse, g, True, None,
                            block_q=128, block_k=128, interpret=True)
    eq, ek, ev = jax.grad(
        lambda q, k, v: jnp.sum(_ref_bhld(q, k, v, True) * g),
        argnums=(0, 1, 2),
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(eq), atol=1e-4)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(ek), atol=1e-4)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(ev), atol=1e-4)


# ------------------------------------------------------- sliding window

@pytest.mark.parametrize("window", [1, 7, 64, 500])
def test_flash_sliding_window_matches_reference(window):
    from tony_tpu.ops.attention import _flash_fwd

    keys = jax.random.split(jax.random.PRNGKey(13), 3)
    q, k, v = (jax.random.normal(kk, (1, 2, 300, 16)) for kk in keys)
    # small blocks force multi-block band pruning (lo > 0 for late q blocks)
    out, _ = _flash_fwd(q, k, v, True, None, block_q=128, block_k=128,
                        interpret=True, window=window)
    expected = reference_attention(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), causal=True, window=window,
    ).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_flash_sliding_window_gradients():
    from tony_tpu.ops.attention import _flash_bwd, _flash_fwd

    keys = jax.random.split(jax.random.PRNGKey(17), 4)
    q, k, v, g = (jax.random.normal(kk, (1, 1, 300, 16)) for kk in keys)
    w = 40
    out, lse = _flash_fwd(q, k, v, True, None, block_q=128, block_k=128,
                          interpret=True, window=w)
    dq, dk, dv = _flash_bwd(q, k, v, out, lse, g, True, None,
                            block_q=128, block_k=128, interpret=True, window=w)

    def ref(q, k, v):
        o = reference_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), causal=True, window=w,
        ).transpose(0, 2, 1, 3)
        return jnp.sum(o * g)

    eq, ek, ev = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(eq), atol=1e-4)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(ek), atol=1e-4)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(ev), atol=1e-4)


def test_flash_window_public_api_and_validation():
    q = jax.random.normal(jax.random.PRNGKey(19), (1, 2, 128, 16))
    out = flash_attention(q, q, q, causal=True, window=16)
    expected = reference_attention(
        q.transpose(0, 2, 1, 3), q.transpose(0, 2, 1, 3),
        q.transpose(0, 2, 1, 3), causal=True, window=16,
    ).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)
    g = jax.grad(lambda x: jnp.sum(flash_attention(x, x, x, True, None, 16) ** 2))(q)
    assert np.isfinite(np.asarray(g)).all()
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, q, q, causal=False, window=4)
    with pytest.raises(ValueError, match="window"):
        flash_attention(q, q, q, causal=True, window=0)


def test_chunked_reference_attention_matches_reference():
    """The bench's long-context XLA baseline (chunked+remat, the strongest
    thing plain XLA can compile at 16k) must match the materializing
    reference exactly where both compile — otherwise the recorded flash
    speedup is against a broken baseline."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tony_tpu.ops.attention import (
        chunked_reference_attention, reference_attention,
    )

    B, H, L, D = 2, 4, 512, 64
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, H, L, D), jnp.float32) for kk in ks)
    o1 = chunked_reference_attention(q, k, v, causal=True, q_block=128)
    o2 = reference_attention(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), causal=True,
    ).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                               rtol=2e-3, atol=2e-3)
    g1 = jax.grad(
        lambda a, b, c_: chunked_reference_attention(a, b, c_).sum(),
        argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(
        lambda a, b, c_: reference_attention(
            a.transpose(0, 2, 1, 3), b.transpose(0, 2, 1, 3),
            c_.transpose(0, 2, 1, 3), causal=True).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3)


# ------------------------------------------------------- flash decode kernel

def _decode_ref(q, ck, cv, length, window=0):
    """Dense reference: [B, kvH, rep, D] query vs [B, kvH, M, D] cache."""
    M = ck.shape[2]
    s = jnp.einsum("bhrd,bhmd->bhrm", q.astype(jnp.float32),
                   ck.astype(jnp.float32)) * ck.shape[-1] ** -0.5
    mask = jnp.arange(M) <= length
    if window:
        mask &= jnp.arange(M) > length - window
    s = jnp.where(mask[None, None, None], s, -1e30)
    return jnp.einsum("bhrm,bhmd->bhrd", jax.nn.softmax(s, -1),
                      cv.astype(jnp.float32))


def test_flash_decode_matches_reference():
    """Split-KV decode kernel vs dense reference: GQA grouping, ragged
    final block (M not a multiple of block_k), length masking."""
    from tony_tpu.ops.decode_attention import flash_decode

    B, kvH, rep, D, M = 2, 4, 2, 128, 700
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, kvH, rep, D), jnp.float32)
    ck = jax.random.normal(ks[1], (B, kvH, M, D), jnp.float32)
    cv = jax.random.normal(ks[2], (B, kvH, M, D), jnp.float32)
    for length in (0, 437, M - 1):
        out = flash_decode(q, ck, cv, jnp.int32(length), block_k=256,
                           interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(_decode_ref(q, ck, cv, length)),
            rtol=2e-5, atol=2e-5)


def test_flash_decode_window_and_int8():
    """Sliding-window band + int8 cache with folded dequant scales: the
    softmax denominator must sum RAW probabilities (V scales apply only
    to the value accumulation)."""
    from tony_tpu.ops.decode_attention import flash_decode

    B, kvH, rep, D, M = 1, 2, 4, 128, 384
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (B, kvH, rep, D), jnp.float32)
    ck = jax.random.normal(ks[1], (B, kvH, M, D), jnp.float32)
    cv = jax.random.normal(ks[2], (B, kvH, M, D), jnp.float32)
    out = flash_decode(q, ck, cv, jnp.int32(300), window=64, block_k=128,
                       interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_decode_ref(q, ck, cv, 300, window=64)),
        rtol=2e-5, atol=2e-5)

    def quant(x):
        amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
        sc = jnp.maximum(amax / 127.0, 1e-8)
        qv = jnp.clip(jnp.round(x / sc), -127, 127).astype(jnp.int8)
        return qv, sc[..., 0].astype(jnp.bfloat16)

    ck8, cks = quant(ck)
    cv8, cvs = quant(cv)
    out8 = flash_decode(q, ck8, cv8, jnp.int32(300), cks, cvs,
                        block_k=128, interpret=True)
    ref8 = _decode_ref(
        q,
        ck8.astype(jnp.float32) * cks[..., None].astype(jnp.float32),
        cv8.astype(jnp.float32) * cvs[..., None].astype(jnp.float32), 300)
    np.testing.assert_allclose(np.asarray(out8), np.asarray(ref8),
                               rtol=2e-3, atol=2e-3)


def test_flash_decode_layer_indexed_stack():
    """`layer=` reads one layer of the full [Ly, B, kvH, M, D] stack via
    the BlockSpecs (the caller never slices — a sliced pallas operand is
    a real copy)."""
    from tony_tpu.ops.decode_attention import flash_decode

    Ly, B, kvH, rep, D, M = 3, 1, 2, 1, 128, 256
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (B, kvH, rep, D), jnp.float32)
    ck = jax.random.normal(ks[1], (Ly, B, kvH, M, D), jnp.float32)
    cv = jax.random.normal(ks[2], (Ly, B, kvH, M, D), jnp.float32)
    for i in range(Ly):
        out = flash_decode(q, ck, cv, jnp.int32(100), layer=i,
                           block_k=128, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(_decode_ref(q, ck[i], cv[i], 100)),
            rtol=2e-5, atol=2e-5)
