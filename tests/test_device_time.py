"""Device-time attribution layer (docs/observability.md "Device timing &
profiling").

The contract under test: ``DispatchTracker`` measures dispatch→ready per
program kind off the hot path, in dispatch order, and survives reset()
without leaking threads or letting stale ready-instants cross the reset;
``CompileTelemetry`` counts actual XLA backend compiles (the jax
monitoring listener fires on a forced recompile) and flags post-warmup
recompile storms; the on-demand profiler capture path works end to end
with a stubbed profiler — serve's ``/debug/profile`` HTTP surface, the
executor's ``$TONY_STEP_LOG.profile`` flag-file contract, the training
child's StepTimer poll, the Heartbeater command relay — and the portal
lists and serves captured profiles. Everything here uses stub buffers /
stubbed ``jax.profiler`` entry points so the suite stays in single-digit
seconds; real capture is behind ``@pytest.mark.slow``.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import ThreadingHTTPServer

import pytest

from tony_tpu import constants as c
from tony_tpu.observability import (
    COMPILE_TELEMETRY,
    CompileTelemetry,
    DispatchTracker,
    install_compile_telemetry,
)

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


class _Buf:
    """Stub device buffer: block_until_ready() waits on an Event (or
    raises, for the dead-donated-buffer path)."""

    def __init__(self, ready: bool = True, raises: bool = False):
        self.ev = threading.Event()
        if ready:
            self.ev.set()
        self.raises = raises
        self.blocked = 0

    def block_until_ready(self):
        self.blocked += 1
        if self.raises:
            raise RuntimeError("buffer deleted (donated into a failed "
                               "dispatch)")
        assert self.ev.wait(10), "stub buffer never released"


def _reaper_count():
    return sum(1 for t in threading.enumerate()
               if t.name == "dispatch-reaper" and t.is_alive())


# --------------------------------------------------------------------------
# DispatchTracker: ordering, lag math, overflow, errors, reset, shutdown
# --------------------------------------------------------------------------

def test_dispatch_tracker_orders_and_histograms_per_kind():
    tr = DispatchTracker()
    try:
        bufs = [_Buf(ready=False) for _ in range(3)]
        seqs = [tr.track("prefill", bufs[0]),
                tr.track("decode_block", bufs[1]),
                tr.track("decode_block", bufs[2])]
        assert seqs == sorted(seqs), "sequence numbers must be monotone"
        assert tr.in_flight == 3
        for b in bufs:      # release in dispatch order — device order
            b.ev.set()
        assert tr.drain(timeout=10)
        assert tr.in_flight == 0
        assert tr.tracked_total == 3 and tr.dropped == 0
        snap = tr.snapshot()
        assert snap["dispatch_ready"]["prefill"]["count"] == 1
        assert snap["dispatch_ready"]["decode_block"]["count"] == 2
        # ready instants are recorded per seq and ordered like dispatch
        times = [tr.ready_time(s) for s in seqs]
        assert all(t is not None for t in times)
        assert times == sorted(times)
        # a consistent rendering copy matches the live counts
        hists = tr.histograms()
        assert hists["decode_block"].count == 2
    finally:
        tr.shutdown()


def test_dispatch_tracker_ready_time_lookup_rules():
    tr = DispatchTracker()
    try:
        seq = tr.track("decode_block", _Buf())
        assert tr.drain(timeout=10)
        t0 = tr.ready_time(seq)
        assert t0 is not None and t0 <= time.monotonic()
        # never-tracked seq beyond the counter: None without waiting
        assert tr.ready_time(seq + 1000) is None
        # eviction: the ring keeps READY_KEEP entries, older ones drop
        tr.READY_KEEP = 4
        seqs = [tr.track("decode_block", _Buf()) for _ in range(8)]
        assert tr.drain(timeout=10)
        assert tr.ready_time(seqs[0]) is None, "evicted entry must be None"
        assert tr.ready_time(seqs[-1]) is not None
        # the timeout path: a pending dispatch resolves while we wait
        slow = _Buf(ready=False)
        seq2 = tr.track("decode_block", slow)
        threading.Timer(0.05, slow.ev.set).start()
        assert tr.ready_time(seq2, timeout=5.0) is not None
    finally:
        tr.shutdown()


def test_dispatch_tracker_overflow_drops_telemetry_only():
    tr = DispatchTracker(max_pending=2)
    try:
        gate = _Buf(ready=False)        # wedges the reaper
        tr.track("prefill", gate)
        for _ in range(4):
            tr.track("prefill", _Buf())
        assert tr.dropped >= 2, "overflow must drop, not grow unboundedly"
        assert tr.in_flight <= tr.max_pending + 1
        gate.ev.set()
        assert tr.drain(timeout=10)
        assert tr.tracked_total + tr.dropped == 5
    finally:
        tr.shutdown()


def test_dispatch_tracker_tolerates_dead_buffers():
    tr = DispatchTracker()
    try:
        tr.track("prefill", _Buf(raises=True))
        after = _Buf()
        tr.track("decode_block", after)
        assert tr.drain(timeout=10)
        assert tr.reap_errors == 1
        assert tr.alive, "a dead buffer must not kill the reaper"
        assert tr.snapshot()["dispatch_ready"]["decode_block"]["count"] == 1
        assert "prefill" not in tr.snapshot()["dispatch_ready"]
    finally:
        tr.shutdown()


def test_dispatch_tracker_reset_rearms_without_blocking_or_leaking():
    n0 = _reaper_count()
    tr = DispatchTracker()
    assert _reaper_count() == n0 + 1
    thread = tr._thread
    done = tr.track("decode_block", _Buf())
    assert tr.drain(timeout=10)
    assert tr.ready_time(done) is not None
    stale = _Buf(ready=False)           # pending across the reset
    stale_seq = tr.track("decode_block", stale)
    t0 = time.monotonic()
    tr.reset()                          # must NOT block on the dead buffer
    assert time.monotonic() - t0 < 1.0
    assert tr._thread is thread and tr.alive, (
        "reset must re-arm the SAME reaper thread, not spawn another")
    assert _reaper_count() == n0 + 1
    # no stale ready-instant crosses the reset
    assert tr.ready_time(done) is None
    before = tr.snapshot()["dispatch_ready"].get(
        "decode_block", {}).get("count", 0)
    stale.ev.set()                      # pre-reset dispatch resolves late
    # post-reset dispatches keep recording on the same thread
    fresh = tr.track("decode_block", _Buf())
    assert tr.drain(timeout=10)
    assert tr.ready_time(fresh) is not None
    assert tr.ready_time(stale_seq) is None, (
        "a pre-reset dispatch must not record into the new generation")
    after = tr.snapshot()["dispatch_ready"]["decode_block"]["count"]
    assert after == before + 1, (
        "only the post-reset dispatch may feed the histogram")
    tr.shutdown()
    assert _reaper_count() == n0 and not tr.alive


def test_dispatch_tracker_shutdown_idempotent():
    tr = DispatchTracker()
    pending = _Buf(ready=False)
    tr.track("prefill", pending)
    tr.shutdown()                       # must not block on the wedge
    assert not tr.alive
    tr.shutdown()                       # idempotent
    before = tr.tracked_total
    tr.track("prefill", _Buf())         # post-shutdown: seq only, no queue
    assert tr.tracked_total == before
    pending.ev.set()


# --------------------------------------------------------------------------
# CompileTelemetry: counting, warmup line, storm warning, live listener
# --------------------------------------------------------------------------

def test_compile_telemetry_counts_and_storm_warning(caplog):
    ct = CompileTelemetry(storm_threshold=3)
    ct.note("/jax/core/compile/jaxpr_trace_duration", 9.0)  # not a compile
    assert ct.compiles == 0
    ct.note(_COMPILE_EVENT, 0.5)
    ct.note(_COMPILE_EVENT, 1.5)
    snap = ct.snapshot()
    assert snap["compiles"] == 2 and not snap["warm"]
    assert snap["compile_time_s"] == pytest.approx(2.0)
    assert snap["recompiles_post_warm"] == 0, "pre-warm compiles are free"
    ct.mark_warm()
    ct.mark_warm()                      # idempotent: line drawn once
    with caplog.at_level("WARNING", logger="tony_tpu.observability"):
        for _ in range(3):
            ct.note(_COMPILE_EVENT, 0.1)
    assert ct.recompiles_post_warm == 3
    storm = [r for r in caplog.records if "recompile storm" in r.message]
    assert len(storm) == 1, "storm warning fires exactly once"
    # rendering copy is consistent and independent of the live histogram
    h = ct.hist_copy()
    assert h.count == 5
    h.observe(1.0)
    assert ct.hist.count == 5


def test_compile_listener_captures_forced_recompile():
    """The jax.monitoring listener is live: jitting a never-seen shape
    forces an actual XLA backend compile and the process-global
    telemetry counts it; re-running the same shape (a cache hit)
    counts nothing."""
    import jax
    import jax.numpy as jnp

    ct = install_compile_telemetry()
    assert ct is COMPILE_TELEMETRY
    assert install_compile_telemetry() is ct     # idempotent

    @jax.jit
    def _probe(x):
        return x * 3 + 1

    before = ct.snapshot()["compiles"]
    _probe(jnp.ones((7,))).block_until_ready()   # unique shape: compiles
    mid = ct.snapshot()["compiles"]
    assert mid > before, "a forced compile must reach the listener"
    _probe(jnp.ones((7,))).block_until_ready()   # cache hit: no event
    assert ct.snapshot()["compiles"] == mid


def test_step_timer_compile_warm_gating():
    """A training StepTimer draws the compile warmup line at its first
    measured step (step 1 ran every program shape); the serving
    loop-TURN timer must not — its turns tick before any request has
    compiled anything, and the serving warm line belongs to the first
    delivered completion (ServeApp._deliver)."""
    from tony_tpu.train.profiling import StepTimer

    class _Fake:
        def __init__(self):
            self.warm = 0

        def mark_warm(self):
            self.warm += 1

    train_timer = StepTimer(window=4)
    train_timer._compile = train_fake = _Fake()
    train_timer.tick()
    train_timer.tick()
    assert train_fake.warm >= 1

    turn_timer = StepTimer(window=4, compile_warm_on_step=False)
    turn_timer._compile = turn_fake = _Fake()
    turn_timer.tick()
    turn_timer.tick()
    assert turn_fake.warm == 0


# --------------------------------------------------------------------------
# on-demand profiler capture: StepTimer flag poll + executor relay
# --------------------------------------------------------------------------

@pytest.fixture
def stub_profiler(monkeypatch):
    """Stub the jax.profiler seams: start writes a fake xplane file so
    the capture directory looks like a real dump."""
    from pathlib import Path

    from tony_tpu.train import profiling

    calls = {"start": [], "stop": 0}

    def _start(log_dir):
        calls["start"].append(str(log_dir))
        d = Path(log_dir)
        d.mkdir(parents=True, exist_ok=True)
        (d / "host.xplane.pb").write_bytes(b"\x00fake-xplane")

    monkeypatch.setattr(profiling, "_start_profiler", _start)
    monkeypatch.setattr(profiling, "_stop_profiler",
                        lambda: calls.__setitem__("stop", calls["stop"] + 1))
    return calls


def test_step_timer_profile_flag_contract(tmp_path, stub_profiler):
    """The full flag-file round trip: the executor relays a driver
    command by writing ``$TONY_STEP_LOG.profile`` (tmp+rename), the
    StepTimer picks it up at its record cadence, captures for the
    requested window, and deletes the flag."""
    from tony_tpu.executor import write_profile_flag
    from tony_tpu.train.profiling import StepTimer

    step_log = tmp_path / "logs" / "w0.steps.jsonl"
    step_log.parent.mkdir()
    timer = StepTimer(step_log, window=2)
    timer.tick(); timer.tick()          # record boundary, no flag yet
    assert stub_profiler["start"] == []

    flag = write_profile_flag(str(step_log), {"seconds": 0.0})
    assert flag == str(step_log) + c.PROFILE_REQUEST_SUFFIX
    req = json.loads(open(flag).read())
    assert req["seconds"] == 0.0
    assert f"/{c.PROFILE_DIR_NAME}/" in req["out_dir"]

    timer.tick(); timer.tick()          # boundary: flag consumed, capture on
    assert stub_profiler["start"] == [req["out_dir"]]
    assert not (tmp_path / "logs" / "w0.steps.jsonl.profile").exists(), (
        "consumed flag must be deleted")
    timer.tick()                        # window elapsed (0s): capture off
    assert stub_profiler["stop"] == 1
    # the dump landed where the portal will look for it
    assert (tmp_path / "logs" / c.PROFILE_DIR_NAME).is_dir()

    # a capture whose window outlives the loop: close() (also armed via
    # atexit) stops it early so the dump flushes instead of vanishing
    write_profile_flag(str(step_log), {"seconds": 60})
    timer.tick()                        # boundary: capture starts
    assert len(stub_profiler["start"]) == 2
    timer.close()
    assert stub_profiler["stop"] == 2
    timer.close()                       # idempotent
    assert stub_profiler["stop"] == 2


def test_step_timer_tolerates_torn_profile_flag(tmp_path, stub_profiler):
    from tony_tpu.train.profiling import StepTimer

    step_log = tmp_path / "w0.steps.jsonl"
    timer = StepTimer(step_log, window=2)
    flag = step_log.with_name(step_log.name + c.PROFILE_REQUEST_SUFFIX)
    flag.write_text('{"seconds": 1.')            # torn mid-write
    timer.tick(); timer.tick()
    assert stub_profiler["start"] == [], "torn request must not capture"
    assert not flag.exists(), "torn flag must be cleared, not wedge"
    timer.tick(); timer.tick()                   # loop is alive and well
    assert timer.step == 4


def test_write_profile_flag_requires_step_log():
    from tony_tpu.executor import write_profile_flag

    assert write_profile_flag(None, {"seconds": 2}) is None
    assert write_profile_flag("", {"seconds": 2}) is None


def test_heartbeater_relays_profile_command():
    """A dict heartbeat response carries a driver command; the
    Heartbeater hands it to on_command exactly once and a raising
    callback must not stop the beat (the beat IS liveness)."""
    from tony_tpu.executor import Heartbeater

    class _Client:
        def __init__(self):
            self.beats = 0

        def call(self, method, **params):
            self.beats += 1
            if self.beats == 1:
                return {"profile": {"seconds": 2.5}}
            return True

    got = []

    def on_command(cmd):
        got.append(cmd)
        raise RuntimeError("relay blew up")      # must not kill the thread

    client = _Client()
    hb = Heartbeater(client, "worker:0", interval_s=0.01,
                     on_command=on_command)
    hb.start()
    deadline = time.time() + 5
    while client.beats < 4 and time.time() < deadline:
        time.sleep(0.01)
    hb.stop_event.set()
    hb.join(timeout=5)
    assert client.beats >= 4, "beat must continue past a bad command"
    assert got == [{"seconds": 2.5}]


# --------------------------------------------------------------------------
# serve /debug/profile: HTTP smoke against a stub engine
# --------------------------------------------------------------------------

class _StubEngine:
    """Bare-minimum engine for ServeApp construction; the loop is never
    started, only the profile surface is exercised."""

    def shutdown(self):
        pass


def _profile_app(tmp_path, monkeypatch):
    import jax

    from tony_tpu.cli.serve import ServeApp

    def _start(log_dir, *a, **kw):
        from pathlib import Path

        p = Path(str(log_dir))
        p.mkdir(parents=True, exist_ok=True)
        (p / "plugins").mkdir(exist_ok=True)
        (p / "plugins" / "host.xplane.pb").write_bytes(b"\x00xp")

    monkeypatch.setattr(jax.profiler, "start_trace", _start)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    return ServeApp(_StubEngine(), trace_dir=str(tmp_path))


def test_debug_profile_http_smoke(tmp_path, monkeypatch):
    from tony_tpu.cli.serve import ServeApp, make_handler

    app = _profile_app(tmp_path, monkeypatch)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(app))
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/profile?seconds=0.01",
                timeout=10) as r:
            out = json.loads(r.read())
        assert out["seconds"] == 0.01
        assert out["files"], "capture must list the dumped files"
        assert any(f.endswith(".xplane.pb") for f in out["files"])
        assert out["dir"].startswith(str(tmp_path))
        assert f"/{c.PROFILE_DIR_NAME}/" in out["dir"] + "/"

        # out-of-range window -> 400
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/profile?seconds=9999",
                timeout=10)
        assert e.value.code == 400

        # concurrent capture -> 409 (jax's trace machinery is global)
        assert app._profile_lock.acquire(blocking=False)
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/profile?seconds=0.01",
                    timeout=10)
            assert e.value.code == 409
        finally:
            app._profile_lock.release()
    finally:
        httpd.shutdown()
        httpd.server_close()

    # no --trace-dir: nowhere to write -> 409, not a silent no-op
    bare = ServeApp(_StubEngine())
    with pytest.raises(RuntimeError, match="trace-dir"):
        bare.capture_profile(1.0)


# --------------------------------------------------------------------------
# portal: /profiles listing + download + traversal guard
# --------------------------------------------------------------------------

def test_portal_profiles_listing_and_download(tmp_path):
    from tony_tpu.conf import TonyConf
    from tony_tpu.events.history import history_file_name
    from tony_tpu.portal.server import serve_portal

    inter = tmp_path / "hist" / "intermediate"
    job = inter / "app_prof"
    job.mkdir(parents=True)
    (job / history_file_name("app_prof", 1000, end_ms=9000, user="u",
                             status="SUCCEEDED")).write_text("")
    # serve-side capture root (history job dir)
    cap = job / c.PROFILE_DIR_NAME / "serve_1700_2s"
    cap.mkdir(parents=True)
    (cap / "host.xplane.pb").write_bytes(b"\x00serve-xplane")
    # training-worker capture root (staging logs tree, flag-file path)
    wcap = (tmp_path / "staging" / "app_prof" / "logs"
            / c.PROFILE_DIR_NAME / "w0_1700")
    wcap.mkdir(parents=True)
    (wcap / "host.xplane.pb").write_bytes(b"\x00worker-xplane")
    secret = tmp_path / "hist" / "secret.txt"
    secret.write_text("not yours")
    bare = inter / "app_bare"
    bare.mkdir(parents=True)
    (bare / history_file_name("app_bare", 1000, end_ms=2000, user="u",
                              status="SUCCEEDED")).write_text("")

    conf = TonyConf({
        "tony.staging.dir": str(tmp_path / "staging"),
        "tony.history.intermediate": str(inter),
        "tony.history.finished": str(tmp_path / "hist" / "finished"),
    })
    server = serve_portal(conf, port=0, block=False)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        def get(path, accept="application/json"):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", headers={"Accept": accept})
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.status, resp.read()

        status, body = get("/profiles/app_prof")
        profiles = json.loads(body)
        assert status == 200
        names = {p["name"] for p in profiles}
        assert names == {"serve_1700_2s/host.xplane.pb",
                         "w0_1700/host.xplane.pb"}, (
            "both capture roots must be listed")
        assert all(p["bytes"] > 0 and p["mtime"] > 0 for p in profiles)

        status, body = get("/profiles/app_prof", accept="text/html")
        html = body.decode()
        assert status == 200 and "captured profiles" in html
        assert "serve_1700_2s/host.xplane.pb" in html
        assert "tensorboard --logdir" in html
        status, body = get("/jobs/app_prof", accept="text/html")
        assert "/profiles/app_prof" in body.decode(), (
            "job page must link the profile listing")

        status, body = get("/profiles/app_prof/serve_1700_2s/host.xplane.pb")
        assert status == 200 and body == b"\x00serve-xplane"

        for missing in ("/profiles/app_bare",            # never profiled
                        "/profiles/app_prof/nope.pb"):   # unknown file
            with pytest.raises(urllib.error.HTTPError) as e:
                get(missing)
            assert e.value.code == 404

        # traversal guard: a crafted relative name must not escape the
        # profile roots (checked at the index so every encoding that
        # reaches it is covered)
        from tony_tpu.portal.server import HistoryIndex

        idx = HistoryIndex(conf)
        assert idx.profile_file("app_prof", "../../secret.txt") is None
        assert idx.profile_file(
            "app_prof", "serve_1700_2s/../../../secret.txt") is None
        with pytest.raises(urllib.error.HTTPError) as e:
            get("/profiles/app_prof/%2e%2e/%2e%2e/secret.txt")
        assert e.value.code == 404
    finally:
        server.shutdown()
        server.server_close()


# --------------------------------------------------------------------------
# real capture (CPU profiler) — slow-marked, tier-1 skips it
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_real_profiler_capture_produces_xplane(tmp_path):
    """Unstubbed jax.profiler round trip through capture_profile: the
    dump contains an actual xplane proto."""
    import jax
    import jax.numpy as jnp

    from tony_tpu.cli.serve import ServeApp

    app = ServeApp(_StubEngine(), trace_dir=str(tmp_path))
    # give the profiler something to see
    t = threading.Thread(
        target=lambda: [jax.jit(lambda x: x @ x)(
            jnp.ones((64, 64))).block_until_ready() for _ in range(50)])
    t.start()
    out = app.capture_profile(0.5)
    t.join()
    assert any(f.endswith(".xplane.pb") for f in out["files"]), out
