"""Job-archive shipping tests: the rebuild's analogue of the reference's
HDFS staging upload + per-container extractResources
(TonyClient.java:232-315, util/Utils.java:758-771) — and the SSH launch
seam of StaticHostProvisioner, exercised with a local stand-in template.
"""

import json
import os
import sys
import tarfile
from pathlib import Path

import pytest

from tony_tpu.api import JobStatus
from tony_tpu.client import TonyClient
from tony_tpu.conf import FINAL_CONF_NAME, TonyConf
from tony_tpu.utils import shipping

PY = sys.executable
FIXTURES = Path(__file__).parent / "fixtures" / "scripts"


# ------------------------------------------------------------------- unit

def _staged_job_dir(tmp_path: Path) -> Path:
    job = tmp_path / "job"
    (job / "src").mkdir(parents=True)
    (job / "src" / "lib.py").write_text("X = 1\n")
    (job / "resources").mkdir()
    (job / "resources" / "data.txt").write_text("shipped-bytes")
    (job / FINAL_CONF_NAME).write_text(json.dumps({"tony.worker.instances": 1}))
    # runtime output that must NOT ship
    (job / "logs").mkdir()
    (job / "logs" / "worker_0.stdout").write_text("log line")
    (job / "driver.log").write_text("driver noise")
    return job


def test_archive_roundtrip_excludes_runtime_output(tmp_path):
    job = _staged_job_dir(tmp_path)
    archive = shipping.build_job_archive(job)
    with tarfile.open(archive) as tf:
        names = tf.getnames()
    assert FINAL_CONF_NAME in names
    assert "src/lib.py" in names
    assert "resources/data.txt" in names
    assert not any(n.startswith("logs") or n == "driver.log" for n in names)

    local = shipping.localize_job(str(archive), "app_x", base_dir=str(tmp_path / "lz"))
    assert (Path(local) / FINAL_CONF_NAME).exists()
    assert (Path(local) / "src" / "lib.py").read_text() == "X = 1\n"
    # idempotent: second call reuses the unpack
    again = shipping.localize_job(str(archive), "app_x", base_dir=str(tmp_path / "lz"))
    assert again == local


def test_localize_rejects_non_job_archive(tmp_path):
    bogus = tmp_path / "bogus.tar.gz"
    with tarfile.open(bogus, "w:gz") as tf:
        p = tmp_path / "stray.txt"
        p.write_text("hi")
        tf.add(p, arcname="stray.txt")
    with pytest.raises(FileNotFoundError):
        shipping.localize_job(str(bogus), "app_y", base_dir=str(tmp_path / "lz"))


def test_localize_verifies_sha256_and_rejects_tamper(tmp_path):
    """A bit-flipped archive must be refused BEFORE unpack when the submit
    -time digest is supplied — the integrity role of the reference's token
    -secured staging (TonyClient.java:981-1030)."""
    job = _staged_job_dir(tmp_path)
    archive = shipping.build_job_archive(job)
    digest = shipping.sha256_file(archive)

    # matching digest unpacks normally
    local = shipping.localize_job(
        str(archive), "app_ok", base_dir=str(tmp_path / "lz"), sha256=digest
    )
    assert (Path(local) / FINAL_CONF_NAME).exists()

    # flip one byte mid-file -> clear integrity error, nothing unpacked
    data = bytearray(archive.read_bytes())
    data[len(data) // 2] ^= 0x01
    tampered = tmp_path / "tampered.tar.gz"
    tampered.write_bytes(bytes(data))
    with pytest.raises(ValueError, match="integrity"):
        shipping.localize_job(
            str(tampered), "app_bad", base_dir=str(tmp_path / "lz2"),
            sha256=digest,
        )
    assert not (tmp_path / "lz2" / "app_bad").exists()

    # the idempotent-reuse path enforces the digest too: a dir localized
    # WITHOUT verification cannot satisfy a digest-expecting caller, and a
    # different expected digest is refused
    shipping.localize_job(str(archive), "app_mix", base_dir=str(tmp_path / "lz3"))
    with pytest.raises(ValueError, match="refusing to reuse"):
        shipping.localize_job(
            str(archive), "app_mix", base_dir=str(tmp_path / "lz3"),
            sha256=digest,
        )
    with pytest.raises(ValueError, match="refusing to reuse"):
        shipping.localize_job(
            str(archive), "app_ok", base_dir=str(tmp_path / "lz"),
            sha256="0" * 64,
        )
    # matching digest reuses normally
    again = shipping.localize_job(
        str(archive), "app_ok", base_dir=str(tmp_path / "lz"), sha256=digest
    )
    assert again == local


def test_fetch_file_uri(tmp_path):
    src = tmp_path / "a.bin"
    src.write_bytes(b"\x00\x01")
    out = shipping.fetch_archive(f"file://{src}", tmp_path / "dl" / "a.bin")
    assert out.read_bytes() == b"\x00\x01"


# -------------------------------------------------------------------- e2e

def _shipped_conf(dirs, tmp_path, **extra):
    """A job whose src + resources must reach the task through the archive."""
    src = tmp_path / "user_src"
    src.mkdir()
    (src / "lib.py").write_text("X = 1\n")
    res = tmp_path / "data.txt"
    res.write_text("shipped-bytes")
    local_base = tmp_path / "hostlocal"
    conf = TonyConf({
        "tony.staging.dir": dirs["staging"],
        "tony.history.intermediate": dirs["history"] + "/intermediate",
        "tony.am.monitor-interval-ms": 100,
        "tony.task.registration-poll-interval-ms": 100,
        "tony.application.src-dir": str(src),
        "tony.worker.instances": 1,
        "tony.worker.resources": str(res),
        "tony.worker.command": f"{PY} {FIXTURES / 'check_localized.py'}",
        "tony.task.localize": True,
        "tony.execution.env": f"TONY_LOCAL_DIR={local_base}",
        **extra,
    })
    return conf, local_base


def _run(conf):
    client = TonyClient(conf, poll_interval_s=0.1)
    client.submit()
    status = client.monitor()
    return status, client


def _logs(client):
    return "\n".join(
        f"==== {p} ====\n{p.read_text()[-3000:]}"
        for p in sorted(Path(client.job_dir).rglob("*.log"))
        + sorted(Path(client.job_dir).rglob("*.std*"))
    )


def test_e2e_executor_runs_from_shipped_archive(tmp_job_dirs, tmp_path):
    """tony.task.localize forces the executor to fetch + unpack the job
    archive into a host-local dir and run the task from the copy — the whole
    remote-distribution path minus the network transport."""
    conf, local_base = _shipped_conf(tmp_job_dirs, tmp_path)
    status, client = _run(conf)
    assert status == JobStatus.SUCCEEDED, _logs(client)
    # archive was built and the task really ran from the localized copy
    assert (Path(client.job_dir) / shipping.ARCHIVE_NAME).exists()
    unpacked = local_base / client.app_id
    assert (unpacked / FINAL_CONF_NAME).exists()
    out = (Path(client.job_dir) / "logs" / "worker_0.stdout").read_text()
    assert "localized OK" in out, _logs(client)


def test_e2e_app_placeholder_uri_and_upload_cmd(tmp_job_dirs, tmp_path):
    """{app} in archive-uri resolves to the generated application id, and
    the upload command template runs — the HDFS-upload seam with a cp
    stand-in for gsutil."""
    uri_tpl = str(tmp_path / "bucket" / "{app}" / "job_archive.tar.gz")
    conf, local_base = _shipped_conf(
        tmp_job_dirs, tmp_path,
        **{
            "tony.application.archive-uri": uri_tpl,
            "tony.application.archive-upload-cmd":
                "mkdir -p $(dirname {uri}) && cp {archive} {uri}",
        },
    )
    status, client = _run(conf)
    assert status == JobStatus.SUCCEEDED, _logs(client)
    uploaded = tmp_path / "bucket" / client.app_id / "job_archive.tar.gz"
    assert uploaded.exists(), "upload command did not place the archive"
    # frozen conf records the resolved (not templated) URI
    final = json.loads((Path(client.job_dir) / FINAL_CONF_NAME).read_text())
    assert final["tony.application.archive-uri"] == str(uploaded)

    # one conf object serves many submissions: the template must survive
    # the first submit, so the second job resolves to ITS OWN path
    status2, client2 = _run(conf)
    assert status2 == JobStatus.SUCCEEDED, _logs(client2)
    assert client2.app_id != client.app_id
    final2 = json.loads((Path(client2.job_dir) / FINAL_CONF_NAME).read_text())
    assert final2["tony.application.archive-uri"] == str(
        tmp_path / "bucket" / client2.app_id / "job_archive.tar.gz"
    )


def test_e2e_tampered_archive_fails_task(tmp_job_dirs, tmp_path):
    """End-to-end integrity: the frozen conf carries the archive sha256, the
    driver forwards it in the launch env, and an executor that fetches a
    corrupted copy fails with the integrity error instead of executing it.
    The upload command plays the tamperer (appends a byte in transit)."""
    uri = str(tmp_path / "bucket" / "job_archive.tar.gz")
    conf, _ = _shipped_conf(
        tmp_job_dirs, tmp_path,
        **{
            "tony.application.archive-uri": uri,
            "tony.application.archive-upload-cmd":
                "mkdir -p $(dirname {uri}) && cp {archive} {uri} "
                "&& printf x >> {uri}",
        },
    )
    status, client = _run(conf)
    assert status == JobStatus.FAILED
    final = json.loads((Path(client.job_dir) / FINAL_CONF_NAME).read_text())
    built = Path(client.job_dir) / shipping.ARCHIVE_NAME
    assert final["tony.application.archive-sha256"] == \
        shipping.sha256_file(built)
    logs = _logs(client)
    assert "integrity check failed" in logs, logs


def test_e2e_ssh_launch_seam_with_localization(tmp_job_dirs, tmp_path):
    """StaticHostProvisioner through a {env}-substituting launch template
    (local stand-in for ssh: `env {env} python -m tony_tpu.executor`) — the
    reference's NM container-launch seam (ApplicationMaster.java:1158-1227).
    Proves env quoting, watcher wiring, completion, and archive shipping
    end-to-end; 2 workers on one 'host' share the localized unpack."""
    template = "env {env} " + PY + " -S -m tony_tpu.executor"
    conf, local_base = _shipped_conf(
        tmp_job_dirs, tmp_path,
        **{
            "tony.worker.instances": 2,
            "tony.cluster.provisioner": "static",
            "tony.cluster.static-hosts": ["testhost"],
            "tony.cluster.launch-template": template,
        },
    )
    status, client = _run(conf)
    assert status == JobStatus.SUCCEEDED, _logs(client)
    assert {t.task_id for t in client.task_infos} == {"worker:0", "worker:1"}
    assert all(t.status == "SUCCEEDED" for t in client.task_infos)
    # both workers ran from the single localized copy on the "host"
    for i in (0, 1):
        out = (Path(client.job_dir) / "logs" / f"worker_{i}.stdout").read_text()
        assert f"localized OK: {local_base / client.app_id}" in out, _logs(client)


@pytest.mark.env_flaky
def test_e2e_multihost_jax_collective_via_ssh_seam(tmp_job_dirs, tmp_path):
    """The full remote multi-host contract in ONE test (round-2 verdict #8):
    StaticHostProvisioner places the two workers on two 'hosts' through the
    {env} bash launch template (local stand-in for ssh), each executor
    fetches + unpacks the SHIPPED archive (sha256-verified), runs the user
    script from the shipped src tree, joins jax.distributed via the
    TONY_COORDINATOR_ADDRESS/TONY_PROCESS_ID env contract, and the two
    processes execute a real cross-process psum — the reference's
    NM-launch + HDFS-localize + TF-gRPC data-plane path end to end."""
    import shutil

    import tony_tpu

    repo_root = str(Path(tony_tpu.__file__).resolve().parent.parent)
    src = tmp_path / "user_src"
    src.mkdir()
    shutil.copy(FIXTURES / "distributed_psum.py", src / "train.py")
    local_base = tmp_path / "hostlocal"
    conf = TonyConf({
        "tony.staging.dir": tmp_job_dirs["staging"],
        "tony.history.intermediate": tmp_job_dirs["history"] + "/intermediate",
        "tony.am.monitor-interval-ms": 100,
        "tony.task.registration-poll-interval-ms": 100,
        "tony.application.src-dir": str(src),
        "tony.worker.instances": 2,
        "tony.worker.command": f"{PY} src/train.py",
        "tony.task.localize": True,
        "tony.cluster.provisioner": "static",
        "tony.cluster.static-hosts": ["hostA", "hostB"],
        "tony.cluster.launch-template":
            "env {env} " + PY + " -S -m tony_tpu.executor",
        "tony.execution.env": [
            f"TONY_LOCAL_DIR={local_base}",
            f"TONY_REPO_ROOT={repo_root}",
        ],
        # jax.distributed gloo bootstrap can take a few seconds
        "tony.task.heartbeat-interval-ms": 1000,
    })
    status, client = _run(conf)
    assert status == JobStatus.SUCCEEDED, _logs(client)
    # both workers really ran from the localized unpack and joined the
    # collective (0+1 ranks both present)
    outs = [
        (Path(client.job_dir) / "logs" / f"worker_{i}.stdout").read_text()
        for i in (0, 1)
    ]
    assert any("process 0/2: collective OK" in o for o in outs), _logs(client)
    assert any("process 1/2: collective OK" in o for o in outs), _logs(client)
    assert (local_base / client.app_id / FINAL_CONF_NAME).exists()


def test_static_template_kill_cascade(tmp_path):
    """stop_container on a template-launched handle must take down the whole
    process group — the template's shell AND whatever it exec'd (for real
    ssh: the ssh client, whose teardown reaps the remote session)."""
    import os
    import time

    from tony_tpu.cluster.provisioner import StaticHostProvisioner
    from tony_tpu.conf import RoleSpec

    pidfile = tmp_path / "pid"
    prov = StaticHostProvisioner(
        ["h"],
        launch_template=(
            "env {env} bash -c 'echo $$ > " + str(pidfile) + "; exec sleep 300'"
        ),
    )
    handle = prov.launch(
        RoleSpec(name="worker", instances=1), 0, {"TONY_T": "x"},
        tmp_path / "logs",
    )
    deadline = time.time() + 10
    content = ""
    while time.time() < deadline:
        # the shell creates the file before writing the pid — wait for the
        # content, not just existence
        content = pidfile.read_text().strip() if pidfile.exists() else ""
        if content:
            break
        time.sleep(0.05)
    assert content, "template launch never wrote its pid"
    pid = int(content)
    os.kill(pid, 0)  # alive
    prov.stop_container(handle)
    deadline = time.time() + 10
    while time.time() < deadline:
        try:
            os.kill(pid, 0)
            time.sleep(0.1)
        except ProcessLookupError:
            break
    with pytest.raises(ProcessLookupError):
        os.kill(pid, 0)


def test_e2e_ssh_template_env_quoting_survives_spaces(tmp_job_dirs, tmp_path):
    """Values with spaces (the task command itself) must survive the
    template's {env} substitution through a real shell."""
    template = "env {env} " + PY + " -S -m tony_tpu.executor"
    script = tmp_path / "with space" / "ok.py"
    script.parent.mkdir()
    script.write_text("print('spaced ok')\n")
    conf = TonyConf({
        "tony.staging.dir": tmp_job_dirs["staging"],
        "tony.history.intermediate": tmp_job_dirs["history"] + "/intermediate",
        "tony.am.monitor-interval-ms": 100,
        "tony.worker.instances": 1,
        "tony.worker.command": f"{PY} '{script}'",
        "tony.cluster.provisioner": "static",
        "tony.cluster.static-hosts": ["testhost"],
        "tony.cluster.launch-template": template,
    })
    status, client = _run(conf)
    assert status == JobStatus.SUCCEEDED, _logs(client)
    out = (Path(client.job_dir) / "logs" / "worker_0.stdout").read_text()
    assert "spaced ok" in out
