"""Native library tests: build, procstats vs Python walk, epoll proxy."""

import os
import socket
import threading

import pytest

from tony_tpu import native


@pytest.fixture(scope="module")
def lib():
    lib = native.get_lib()
    if lib is None:
        pytest.skip("native toolchain unavailable")
    return lib


def test_native_rss_close_to_python(lib):
    from tony_tpu.metrics import _proc_tree_rss_mb

    native_val = native.proc_tree_rss_mb(os.getpid())
    assert native_val is not None and native_val > 1.0
    py_val = _proc_tree_rss_mb(os.getpid())
    # both walk the same /proc tree moments apart
    assert abs(native_val - py_val) / max(py_val, 1) < 0.2, (native_val, py_val)


def test_native_rss_unknown_pid(lib):
    assert native.proc_tree_rss_mb(99999999) is None


def test_native_proxy_tunnels(lib):
    upstream = socket.socket()
    upstream.bind(("127.0.0.1", 0))
    upstream.listen(4)
    up_port = upstream.getsockname()[1]

    def echo():
        while True:
            try:
                conn, _ = upstream.accept()
            except OSError:
                return
            def serve(c):
                while True:
                    data = c.recv(4096)
                    if not data:
                        return
                    c.sendall(data[::-1])
            threading.Thread(target=serve, args=(conn,), daemon=True).start()

    threading.Thread(target=echo, daemon=True).start()

    proxy = native.NativeProxy("127.0.0.1", up_port)
    proxy.start()
    try:
        assert proxy.local_port > 0
        # multiple concurrent connections through one epoll loop
        for payload in (b"abc", b"x" * 100000, b"hello"):
            c = socket.create_connection(("127.0.0.1", proxy.local_port), timeout=5)
            c.sendall(payload)
            got = b""
            while len(got) < len(payload):
                chunk = c.recv(65536)
                if not chunk:
                    break
                got += chunk
            assert got == payload[::-1]
            c.close()
    finally:
        proxy.stop()
        upstream.close()
