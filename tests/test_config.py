"""Config system tests — includes the defaults<->constants cross-check the
reference enforces in TestTonyConfigurationFields.java."""

import json

import pytest

from tony_tpu.conf import TonyConf, keys, load_defaults


def test_defaults_and_constants_cross_check():
    """Every global key constant appears in defaults.json and vice versa
    (reference TestTonyConfigurationFields, TonyConfigurationKeys.java:80-81)."""
    defaults = load_defaults()
    constants = {
        v for k, v in vars(keys).items()
        if k.isupper() and isinstance(v, str) and v.startswith("tony.")
        and not k.endswith("PREFIX")  # namespace prefixes, not concrete keys
    }
    missing_in_defaults = constants - set(defaults)
    assert not missing_in_defaults, f"constants missing defaults: {missing_in_defaults}"
    missing_constants = set(defaults) - constants
    assert not missing_constants, f"defaults missing constants: {missing_constants}"


def test_layering_order(tmp_path, monkeypatch):
    f1 = tmp_path / "a.json"
    f1.write_text(json.dumps({"tony.application.name": "from-file", "x.custom": 1}))
    site_dir = tmp_path / "site"
    site_dir.mkdir()
    (site_dir / "tony-site.json").write_text(
        json.dumps({"tony.application.name": "from-site"})
    )
    monkeypatch.setenv("TONY_CONF_DIR", str(site_dir))
    conf = TonyConf.resolve(
        conf_files=[f1], overrides=["tony.am.retry-count=3", "y.z=true"]
    )
    # site wins over file; overrides applied; defaults still present
    assert conf["tony.application.name"] == "from-site"
    assert conf.get_int(keys.AM_RETRY_COUNT) == 3
    assert conf["y.z"] is True
    assert conf["x.custom"] == 1
    assert conf.get_int(keys.TASK_MAX_MISSED_HEARTBEATS) == 25


def test_role_discovery_and_specs():
    conf = TonyConf({
        "tony.worker.instances": 4,
        "tony.worker.chips": 1,
        "tony.worker.command": "python train.py",
        "tony.ps.instances": 2,
        "tony.ps.depends-on": "",
        "tony.evaluator.instances": 1,
        # reserved prefixes must not become roles:
        "tony.task.instances": 99,
    })
    assert conf.roles() == ["evaluator", "ps", "worker"]
    specs = {s.name: s for s in conf.role_specs()}
    assert specs["worker"].instances == 4
    assert specs["worker"].chips == 1
    assert specs["worker"].command == "python train.py"
    priorities = [s.priority for s in conf.role_specs()]
    assert len(priorities) == len(set(priorities)), "priorities must be unique"


def test_validation_caps():
    conf = TonyConf({
        "tony.worker.instances": 4,
        "tony.task.max-total-instances": 2,
    })
    with pytest.raises(ValueError, match="exceeds"):
        conf.validate()
    conf2 = TonyConf({"tony.worker.instances": 0})
    with pytest.raises(ValueError):
        conf2.validate()
    conf3 = TonyConf({
        "tony.worker.instances": 2,
        "tony.worker.memory-mb": 1000,
        "tony.task.max-total-memory-mb": 1500,
    })
    with pytest.raises(ValueError, match="memory"):
        conf3.validate()
    ok = TonyConf({"tony.worker.instances": 2})
    ok.validate()


def test_final_conf_roundtrip(tmp_path):
    conf = TonyConf({"tony.worker.instances": 2, "custom.key": [1, 2]})
    conf.write_final(tmp_path)
    loaded = TonyConf.from_final(tmp_path)
    assert loaded["tony.worker.instances"] == 2
    assert loaded["custom.key"] == [1, 2]
