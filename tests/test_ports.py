"""Port reservation + version stamping (reference TestPortAllocation.java,
VersionInfo)."""

import socket

import pytest

from tony_tpu.conf import TonyConf
from tony_tpu.utils import ports, version


def test_ephemeral_port_release_then_rebind():
    res = ports.EphemeralPort.create()
    assert res.port > 0 and res.held
    # held: a plain bind to the same port collides
    with pytest.raises(OSError):
        s = socket.socket()
        try:
            s.bind(("", res.port))
        finally:
            s.close()
    res.release()
    assert not res.held
    # released: the child can now bind it (the reference's race window)
    s = socket.socket()
    s.bind(("", res.port))
    s.close()


@pytest.mark.skipif(not ports.reuse_port_supported(), reason="no SO_REUSEPORT")
def test_reusable_port_binds_while_held():
    with ports.ReusablePort.create() as res:
        # a child that sets SO_REUSEPORT binds the same port with NO release
        child = socket.socket()
        child.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        child.bind(("", res.port))
        child.close()
        # a child that does NOT set it still collides -> reservation is real
        plain = socket.socket()
        with pytest.raises(OSError):
            plain.bind(("", res.port))
        plain.close()


def test_allocate_strategy_selection():
    eph = ports.allocate(reuse=False)
    assert isinstance(eph, ports.EphemeralPort)
    eph.release()
    want = ports.ReusablePort if ports.reuse_port_supported() else ports.EphemeralPort
    r = ports.allocate(reuse=True)
    assert isinstance(r, want)
    r.release()


def test_version_info_stamped_into_conf():
    info = version.version_info()
    assert info[version.VERSION_KEY] == version.VERSION
    assert info[version.REVISION_KEY]
    conf = TonyConf({"tony.worker.instances": 1, "tony.worker.command": "true"})
    version.inject(conf)
    assert conf.get(version.VERSION_KEY) == version.VERSION
    assert conf.get(version.BRANCH_KEY)
