import os, sys
assert os.environ["DMLC_ROLE"] in ("scheduler", "server", "worker")
assert os.environ["DMLC_PS_ROOT_URI"]
assert int(os.environ["DMLC_PS_ROOT_PORT"]) > 0
assert int(os.environ["DMLC_NUM_SERVER"]) == 1
assert int(os.environ["DMLC_NUM_WORKER"]) == 2
sys.exit(0)
