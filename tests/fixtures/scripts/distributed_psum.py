"""True multi-process JAX job: joins via tony_tpu.train.init() (the env
contract emitted by the jax runtime adapter) and verifies a cross-process
collective — the TPU-native replacement for the reference's
TF-gRPC/c10d/Gloo data-plane checks."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

sys.path.insert(0, os.environ["TONY_REPO_ROOT"])
from tony_tpu import train

info = train.init(timeout_s=60)
n = info["num_processes"]
assert n >= 2, f"expected a real multi-process job, got {n}"
local_dev = jax.local_device_count()
assert jax.device_count() == n * local_dev, (jax.device_count(), n, local_dev)

mesh = Mesh(np.asarray(jax.devices()), ("data",))
# one row per local device, valued by process id + 1
local = np.full((local_dev, 4), info["process_id"] + 1, np.float32)
x = jax.make_array_from_process_local_data(NamedSharding(mesh, P("data")), local)
total = float(jax.jit(lambda x: x.sum(), out_shardings=NamedSharding(mesh, P()))(x))
expected = sum(4.0 * local_dev * (i + 1) for i in range(n))
assert abs(total - expected) < 1e-5, (total, expected)
print(f"process {info['process_id']}/{n}: collective OK ({total})")
sys.exit(0)
