"""Stub cloud CLI for slice-lifecycle tests: a 'slice' is a state dir.

Models the gcloud surface the lifecycle templates wrap without any cloud:
  create <dir> <n_hosts> [ready_after]  materialize; READY only after
                                        ready_after further describes
                                        (async allocation), generation++
  describe <dir>                        one host per line when READY;
                                        exit 1 while CREATING or absent
  delete <dir>                          remove the slice (idempotent)

Host names carry the generation (host0-g2 ...) so tests can assert a
recreated slice came back with NEW addresses, like a real spot slice.
"""
import json
import sys
from pathlib import Path


def main() -> int:
    cmd, d = sys.argv[1], Path(sys.argv[2])
    state_f = d / "slice.json"
    if cmd == "create":
        n = int(sys.argv[3])
        ready_after = int(sys.argv[4]) if len(sys.argv) > 4 else 0
        d.mkdir(parents=True, exist_ok=True)
        genf = d / "generation"
        gen = int(genf.read_text()) + 1 if genf.exists() else 1
        genf.write_text(str(gen))
        state_f.write_text(
            json.dumps({"n": n, "gen": gen, "polls_left": ready_after})
        )
        with (d / "create.log").open("a") as f:
            f.write(f"create gen={gen}\n")
    elif cmd == "describe":
        if not state_f.exists():
            print("NOT_FOUND", file=sys.stderr)
            return 1
        st = json.loads(state_f.read_text())
        if st["polls_left"] > 0:
            st["polls_left"] -= 1
            state_f.write_text(json.dumps(st))
            # mid-creation a real describe lists the endpoints provisioned
            # so far: print a growing partial list, or fail while empty
            partial = max(0, st["n"] - 1 - st["polls_left"])
            if partial == 0:
                print("CREATING", file=sys.stderr)
                return 1
            for i in range(partial):
                print(f"host{i}-g{st['gen']}")
            return 0
        for i in range(st["n"]):
            print(f"host{i}-g{st['gen']}")
    elif cmd == "delete":
        state_f.unlink(missing_ok=True)
        d.mkdir(parents=True, exist_ok=True)
        with (d / "delete.log").open("a") as f:
            f.write("delete\n")
    else:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
