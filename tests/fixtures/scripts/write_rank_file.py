"""Writes its rank to a file so tests can verify all tasks ran with distinct ranks."""
import os, sys
out_dir = os.environ["RANK_OUT_DIR"]
pid = os.environ["TONY_PROCESS_ID"]
with open(os.path.join(out_dir, f"rank_{pid}"), "w") as f:
    f.write(os.environ["TONY_JOB_NAME"] + ":" + os.environ["TONY_TASK_INDEX"])
sys.exit(0)
