import os, sys
assert os.environ["HOROVOD_CONTROLLER"] == "gloo"
assert os.environ["HOROVOD_GLOO_RENDEZVOUS_ADDR"]
assert int(os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"]) > 0
rank = int(os.environ["HOROVOD_RANK"]); size = int(os.environ["HOROVOD_SIZE"])
assert 0 <= rank < size, (rank, size)
assert int(os.environ["HOROVOD_LOCAL_RANK"]) < int(os.environ["HOROVOD_LOCAL_SIZE"])
sys.exit(0)
