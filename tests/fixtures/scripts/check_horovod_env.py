import os, sys
assert os.environ["HOROVOD_CONTROLLER"] == "gloo"
assert os.environ["HOROVOD_GLOO_RENDEZVOUS_ADDR"]
assert int(os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"]) > 0
rank = int(os.environ["HOROVOD_RANK"]); size = int(os.environ["HOROVOD_SIZE"])
assert 0 <= rank < size, (rank, size)
assert int(os.environ["HOROVOD_LOCAL_RANK"]) < int(os.environ["HOROVOD_LOCAL_SIZE"])
# optionally record our rank so the test can assert cross-task distinctness
out_dir = os.environ.get("RANK_OUT_DIR")
if out_dir:
    with open(os.path.join(out_dir, f"hvd_rank_{rank}"), "w") as f:
        f.write(os.environ["TONY_JOB_NAME"] + ":" + os.environ["TONY_TASK_INDEX"])
sys.exit(0)
