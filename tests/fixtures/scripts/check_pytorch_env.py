import os, sys
assert os.environ["INIT_METHOD"].startswith("tcp://"), os.environ["INIT_METHOD"]
rank, world = int(os.environ["RANK"]), int(os.environ["WORLD"])
assert 0 <= rank < world, (rank, world)
assert os.environ["MASTER_ADDR"]
assert int(os.environ["MASTER_PORT"]) > 0
sys.exit(0)
