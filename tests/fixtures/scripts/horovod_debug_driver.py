"""Debug rendezvous driver fixture (reference horovod_debug_driver.py): bind
a real TCP server, publish its port via the marker file, then serve forever."""
import json
import os
import socket
import time

sock = socket.socket()
sock.bind(("0.0.0.0", 0))
sock.listen(8)
port = sock.getsockname()[1]

marker = os.environ["HOROVOD_RDV_INFO_FILE"]
tmp = marker + ".tmp"
with open(tmp, "w") as f:
    json.dump({"port": port}, f)
os.rename(tmp, marker)

while True:
    time.sleep(3600)
