"""Notebook stand-in: an HTTP server on the task's advertised port."""
import http.server
import os

port = int(os.environ["TONY_TASK_PORT"])


class H(http.server.BaseHTTPRequestHandler):
    def do_GET(self):
        body = b"mini-notebook-ok"
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


http.server.HTTPServer(("0.0.0.0", port), H).serve_forever()
