"""Asserts the JAX runtime env contract (coordinator/process_id/num_processes
+ CLUSTER_SPEC) is present and coherent."""
import json, os, sys

spec = json.loads(os.environ["CLUSTER_SPEC"])
assert "worker" in spec, spec
coord = os.environ["TONY_COORDINATOR_ADDRESS"]
pid = int(os.environ["TONY_PROCESS_ID"])
nproc = int(os.environ["TONY_NUM_PROCESSES"])
total = sum(len(v) for v in spec.values())
assert nproc == total, (nproc, total)
assert 0 <= pid < nproc, (pid, nproc)
assert ":" in coord, coord
# rank 0's advertised address must be the coordinator
ranked = []
for role in sorted(spec):
    ranked.extend(spec[role])
assert coord == ranked[0], (coord, ranked)
sys.exit(0)
