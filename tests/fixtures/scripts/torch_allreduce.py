"""Real torch.distributed (gloo) allreduce via the env contract the pytorch
runtime adapter exports — proves the rendezvous bootstrap end-to-end."""
import os
import sys

import torch
import torch.distributed as dist

rank = int(os.environ["RANK"])
world = int(os.environ["WORLD"])
dist.init_process_group(
    "gloo", init_method=os.environ["INIT_METHOD"], rank=rank, world_size=world,
)
t = torch.tensor([float(rank + 1)])
dist.all_reduce(t)
expected = world * (world + 1) / 2
assert t.item() == expected, (t.item(), expected)
dist.destroy_process_group()
sys.exit(0)
