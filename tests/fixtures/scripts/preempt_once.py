"""Task that simulates a spot preemption on the first attempt: session 0
destroys the slice's state (as the cloud would) and dies; the retry
(session 1, on the re-created slice) succeeds."""
import os
import sys
from pathlib import Path

slice_dir = Path(os.environ["STUB_SLICE_DIR"])
session = int(os.environ["TONY_SESSION_ID"])
if session == 0:
    (slice_dir / "slice.json").unlink(missing_ok=True)
    print("preempted: slice destroyed", file=sys.stderr)
    sys.exit(1)
print(f"attempt {session} ran on recreated slice")
