"""Fixture: asserts it was launched through the docker shim with the task
env contract forwarded via -e flags (reference exit_0_check_env.py pattern)."""
import os

assert os.environ.get("DOCKER_SHIM_USED") == "1", "not launched via docker shim"
assert os.environ.get("TONY_JOB_NAME") == "worker", os.environ.get("TONY_JOB_NAME")
assert "TONY_TASK_INDEX" in os.environ
# tony.execution.env vars must be forwarded into the container explicitly
assert os.environ.get("TONY_E2E_PASSTHRU") == "yes", "execution.env not forwarded"
# the job dir contract must resolve inside the container (bind-mounted)
assert os.path.isdir(os.environ["TONY_JOB_DIR"]), "TONY_JOB_DIR not mounted"
print("docker-launched task env OK")
