import time
time.sleep(300)
