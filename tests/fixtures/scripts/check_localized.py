"""Asserts this task runs from a SHIPPED copy of the job: the executor's job
dir is a localized unpack under TONY_LOCAL_DIR (not the client's staging
dir), the per-task workdir lives under it, and the shipped role resource +
src tree are materialized in the cwd."""

import os

job_dir = os.environ["TONY_JOB_DIR"]
local_base = os.environ["TONY_LOCAL_DIR"]
assert job_dir.startswith(local_base), (job_dir, local_base)

cwd = os.getcwd()
assert cwd.startswith(job_dir), (cwd, job_dir)

with open("data.txt") as f:
    assert f.read() == "shipped-bytes", "resource content mismatch"

assert os.path.isfile(os.path.join("src", "lib.py")), "shipped src missing"
print("localized OK:", job_dir)
