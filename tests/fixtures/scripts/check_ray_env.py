import os, sys
assert os.environ["RAY_ADDRESS"] == os.environ["RAY_HEAD_ADDRESS"]
host, port = os.environ["RAY_HEAD_IP"], int(os.environ["RAY_HEAD_PORT"])
assert host and port > 0
sys.exit(0)
