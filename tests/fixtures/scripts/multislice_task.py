"""Worker for the multislice e2e: asserts the full multislice env contract
(provisioner-injected TONY_SLICE_* + the JAX adapter's MEGASCALE_* mapping),
then simulates a spot preemption of slice 1 on the first attempt — the
worker on slice 1 destroys its own slice's state (as the cloud would) and
dies; the retry must find slice 0 intact and slice 1 re-created."""
import os
import sys
from pathlib import Path

sid = int(os.environ["TONY_SLICE_ID"])
n = int(os.environ["TONY_NUM_SLICES"])
session = int(os.environ["TONY_SESSION_ID"])
task_index = int(os.environ["TONY_TASK_INDEX"])

assert n == 2, f"TONY_NUM_SLICES={n}"
# 1 host per slice, round-robin packing: task i lands on slice i
assert sid == task_index, (sid, task_index)
assert os.environ["TONY_SLICE0_HOST"].startswith("host0"), \
    os.environ["TONY_SLICE0_HOST"]
assert os.environ["MEGASCALE_NUM_SLICES"] == "2"
assert os.environ["MEGASCALE_SLICE_ID"] == str(sid)
assert os.environ["MEGASCALE_COORDINATOR_ADDRESS"].endswith(":8080"), \
    os.environ["MEGASCALE_COORDINATOR_ADDRESS"]

if session == 0:
    # a slice preemption collapses the whole gang: the worker ON the
    # preempted slice destroys its slice state (as the cloud would) and
    # dies; its gang peers lose their collective and die too (the chief's
    # failure is what fails the attempt under "succeed unless chief/
    # stop-on-failure fails" semantics)
    if sid == 1:
        Path(os.environ["STUB_PREEMPT_DIR"],
             "slice.json").unlink(missing_ok=True)
        print("preempted: slice 1 destroyed", file=sys.stderr)
    else:
        # the peer's collective breaks BECAUSE the slice vanished, so
        # it must observe the destruction before dying: the chief's
        # exit short-circuits the attempt, and a chief that races
        # ahead lets the driver's reset() kill this gang (and re-
        # discover slice 1) before the stub cloud state reflects the
        # preemption — the retry would then skip the re-create
        import time

        gone = Path(os.environ["STUB_PREEMPT_DIR"], "slice.json")
        deadline = time.time() + 10
        while gone.exists() and time.time() < deadline:
            time.sleep(0.01)
        print("gang peer lost (slice 1 preempted)", file=sys.stderr)
    sys.exit(1)
print(f"attempt {session} slice {sid} ok")
