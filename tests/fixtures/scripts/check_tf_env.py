import json, os, sys
tf_config = json.loads(os.environ["TF_CONFIG"])
assert os.environ["JOB_NAME"] in ("worker", "ps", "chief", "evaluator"), os.environ["JOB_NAME"]
assert tf_config["task"]["type"] == os.environ["JOB_NAME"]
assert tf_config["task"]["index"] == int(os.environ["TASK_INDEX"])
assert "worker" in tf_config["cluster"] and "ps" in tf_config["cluster"]
# sidecar/eval roles are filtered from the cluster dict (estimator semantics)
assert "tensorboard" not in tf_config["cluster"]
assert "evaluator" not in tf_config["cluster"]
sys.exit(0)
