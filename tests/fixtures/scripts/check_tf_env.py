import json, os, sys
tf_config = json.loads(os.environ["TF_CONFIG"])
assert os.environ["JOB_NAME"] in ("worker", "ps"), os.environ["JOB_NAME"]
assert tf_config["task"]["type"] == os.environ["JOB_NAME"]
assert tf_config["task"]["index"] == int(os.environ["TASK_INDEX"])
assert "worker" in tf_config["cluster"] and "ps" in tf_config["cluster"]
assert "tensorboard" not in tf_config["cluster"]
sys.exit(0)
