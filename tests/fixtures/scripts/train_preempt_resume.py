"""Checkpointed training task that is spot-preempted mid-run.

Session 0 trains from step 0, checkpointing on the configured cadence;
at PREEMPT_AT it destroys the stub slice's state (as the cloud would) and
dies mid-step-loop. The driver retry (session 1, on the re-created slice)
must resume from the latest checkpoint — NOT step 0 — and continue the
exact same training stream: same loader batches (the (seed, step)-pure
contract), same losses (restored params+opt_state + deterministic CPU
math). Every step appends {"session", "step", "loss", "batch_sha"} to
STREAM_OUT so the test can compare against an unpreempted golden run.
"""

import hashlib
import json
import os
import sys
from pathlib import Path

os.environ["JAX_PLATFORMS"] = "cpu"
import jax

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["TONY_REPO_ROOT"])

from tony_tpu import train  # noqa: E402
from tony_tpu.data import (  # noqa: E402
    ShardedBatchLoader, TokenDataset, device_put_sharded_batch,
)
from tony_tpu.models import transformer  # noqa: E402
from tony_tpu.parallel import mesh_from_string  # noqa: E402
from tony_tpu.train.checkpoint import CheckpointManager  # noqa: E402

TOTAL_STEPS = 12
PREEMPT_AT = 7          # session 0 dies before running this step
CKPT_EVERY = 3          # last checkpoint before preemption: step 6
B, L = 8, 32

session = int(os.environ["TONY_SESSION_ID"])
slice_dir = Path(os.environ["STUB_SLICE_DIR"])
out_dir = Path(os.environ["TRAIN_OUT_DIR"])
stream_f = out_dir / "stream.jsonl"

info = train.init()
mesh = mesh_from_string("fsdp=-1")
cfg = transformer.TransformerConfig(
    vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=4,
    d_ff=128, max_seq_len=L, dtype=jax.numpy.float32,
)
bundle = train.create_train_step(cfg, mesh)
params, opt_state = bundle.params, bundle.opt_state

mgr = CheckpointManager(str(out_dir / "ckpt"), save_interval=CKPT_EVERY)
start_step = 0
latest = mgr.latest_step()
if latest is not None:
    restored = mgr.restore(
        template={"params": params, "opt_state": opt_state})
    restored = jax.device_put(
        restored,
        jax.tree.map(lambda x: x.sharding,
                     {"params": params, "opt_state": opt_state}))
    params, opt_state = restored["params"], restored["opt_state"]
    start_step = latest + 1
    print(f"resumed from checkpoint step {latest}")
if session == 1:
    assert start_step == (PREEMPT_AT // CKPT_EVERY) * CKPT_EVERY + 1, (
        f"retry must resume from the last checkpoint, got start "
        f"{start_step}")

import numpy as np  # noqa: E402

dataset = TokenDataset.from_raw(os.environ["DATA_BIN"], np.uint16)
loader = ShardedBatchLoader(
    dataset, B, L, seed=0, process_index=0, process_count=1,
    start_step=start_step,
)

with stream_f.open("a") as f:
    for step_i in range(start_step, TOTAL_STEPS):
        if session == 0 and step_i == PREEMPT_AT:
            (slice_dir / "slice.json").unlink(missing_ok=True)
            print("preempted: slice destroyed mid-training", file=sys.stderr)
            os._exit(1)
        tokens, targets = next(loader)
        sha = hashlib.sha256(tokens.tobytes()).hexdigest()[:16]
        dev = device_put_sharded_batch(
            (tokens, targets), mesh, sharding=bundle.tok_sharding,
            global_batch=B, global_seq=L)
        params, opt_state, metrics = bundle.step_fn(
            params, opt_state, dev[0], dev[1])
        f.write(json.dumps({
            "session": session, "step": step_i,
            "loss": float(metrics["loss"]), "batch_sha": sha,
        }) + "\n")
        f.flush()
        if step_i % CKPT_EVERY == 0 and step_i > 0:
            mgr.save(step_i, {"params": params, "opt_state": opt_state})
            mgr.wait()

mgr.save(TOTAL_STEPS - 1, {"params": params, "opt_state": opt_state})
mgr.wait()
mgr.close()
print("training complete")
