"""Warm executor pool (tony_tpu/warmpool.py): adoption protocol units +
the e2e acceptance contracts — a launch adopts a pre-warmed standby, a
pool miss degrades to the cold spawn (never a failed launch), and no
teardown path orphans a standby.

Standbys here run with TONY_TEST_WARMPOOL_SKIP_WARMUP: the jax
import/backend warmup is the part the bench measures (PERF.json
``launch_path``); the tests pin the PROTOCOL, and a blank standby boots
in ~100ms so the whole file stays inside the tier-1 budget."""

import json
import os
import signal
import sys
import threading
import time
from pathlib import Path

import pytest

from tony_tpu import constants as c
from tony_tpu.warmpool import (
    AdoptedChild,
    WarmPool,
    _pid_alive,
    count_ready,
    env_compatible,
    parse_python_command,
)

PY = sys.executable


@pytest.fixture(autouse=True)
def _skip_warmup(monkeypatch):
    monkeypatch.setenv(c.TEST_WARMPOOL_SKIP_WARMUP, "1")


def _wait_ready(pool_dir, n, timeout=15.0):
    deadline = time.monotonic() + timeout
    while count_ready(pool_dir) < n:
        assert time.monotonic() < deadline, (
            f"pool never reached {n} ready standbys; "
            f"{(Path(pool_dir) / 'spawn.log').read_text() if (Path(pool_dir) / 'spawn.log').exists() else 'no spawn log'}")
        time.sleep(0.05)


def _wait_dead(pid, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline and _pid_alive(pid):
        time.sleep(0.05)
    assert not _pid_alive(pid), f"pid {pid} still alive"


# ------------------------------------------------------------ command parsing

def test_parse_python_command_forms():
    assert parse_python_command("python -m tony_tpu.examples.mnist_jax "
                                "--steps 5")["module"] == \
        "tony_tpu.examples.mnist_jax"
    spec = parse_python_command(f"{PY} train.py --lr 0.1")
    assert spec["script"] == "train.py" and spec["args"] == ["--lr", "0.1"]
    spec = parse_python_command("FOO=1 BAR=x python3 -u -m mod a b")
    assert spec["env"] == {"FOO": "1", "BAR": "x"}
    assert spec["module"] == "mod" and spec["args"] == ["a", "b"]
    # plain $VAR references survive (expanded at adoption like bash would)
    spec = parse_python_command("python t.py --out /x/ckpt_$TONY_TASK_INDEX")
    assert spec["args"] == ["--out", "/x/ckpt_$TONY_TASK_INDEX"]


def test_parse_python_command_rejects_shell_and_non_python():
    for cmd in ("python a.py && python b.py",   # compound
                "python a.py | tee log",        # pipeline
                "python a.py > out.txt",        # redirect
                "python -c 'print(1)'",         # -c payload
                "echo hi",                      # not python
                "./run.sh --x",                 # not python
                "tony-tpu serve --port 1",      # console script
                "python $(which x)",            # substitution
                ""):
        assert parse_python_command(cmd) is None, cmd


def test_env_fingerprint_compatibility():
    warmed = {"warmup": {"backend": "cpu"},
              "env_fingerprint": {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": ""}}
    assert env_compatible(warmed, {"JAX_PLATFORMS": "cpu"})
    # a warmed standby must not adopt a contract for a different backend
    assert not env_compatible(warmed, {"JAX_PLATFORMS": "tpu"})
    assert not env_compatible(warmed, {"JAX_PLATFORMS": "cpu",
                                       "XLA_FLAGS": "--foo"})
    # a blank (skip-warmup / failed-warmup) standby takes anything
    assert env_compatible({}, {"JAX_PLATFORMS": "tpu"})


# ------------------------------------------------------------- protocol units

@pytest.fixture
def pool(tmp_path):
    p = WarmPool(tmp_path / "pool", size=2)
    yield p
    p.reap()


def test_adopt_runs_entrypoint_with_contract(pool, tmp_path):
    """The adopted child applies the contract env, runs in the contract
    cwd, propagates the entrypoint's exit code, and frees its pool slot
    (claim files cleaned) — while the pool replenishes on demand."""
    pool.ensure()
    _wait_ready(pool.dir, 2)
    script = tmp_path / "task.py"
    script.write_text(
        "import os, sys, json, pathlib\n"
        "pathlib.Path('out.txt').write_text(json.dumps(\n"
        "    {'var': os.environ.get('MY_VAR'), 'cwd': os.getcwd()}))\n"
        "sys.exit(7)\n")
    workdir = tmp_path / "work"
    workdir.mkdir()
    env = {**os.environ, "MY_VAR": "hello"}
    child = pool.adopt(f"python {script}", env, cwd=str(workdir))
    assert child is not None
    assert child.wait(timeout=15) == 7
    out = json.loads((workdir / "out.txt").read_text())
    assert out == {"var": "hello", "cwd": str(workdir)}
    # slot freed: one standby left, no claim litter
    assert count_ready(pool.dir) == 1
    assert not list(pool.dir.glob("*.claimed"))
    # replenish restores the target size
    pool.ensure()
    _wait_ready(pool.dir, 2)


def test_adopt_miss_paths(pool):
    """Every miss is a clean None (the caller cold-spawns): empty pool,
    non-adoptable command, claim race."""
    env = dict(os.environ)
    # empty pool
    assert pool.adopt("python x.py", env) is None
    pool.ensure()
    _wait_ready(pool.dir, 2)
    # non-adoptable command leaves the standbys unclaimed
    assert pool.adopt("./run.sh", env) is None
    assert count_ready(pool.dir) == 2
    # two claims of a 2-standby pool both succeed; a third misses — the
    # rename claim is first-winner-takes-it, never a double adoption
    sleeper = pool.dir.parent / "sleep.py"
    sleeper.write_text("import time\ntime.sleep(30)\n")
    a = pool.adopt(f"python {sleeper}", env)
    b = pool.adopt(f"python {sleeper}", env)
    assert a is not None and b is not None and a.pid != b.pid
    assert pool.adopt(f"python {sleeper}", env) is None
    a.kill()
    b.kill()


def test_adopted_child_dies_with_adopter(pool, tmp_path):
    """Control-pipe EOF = adopter death: the adopted child SIGKILLs
    itself — the moral equivalent of the process-group kill a cold
    in-group child would have received from a chaos kill."""
    pool.ensure()
    _wait_ready(pool.dir, 2)
    sleeper = tmp_path / "sleep.py"
    sleeper.write_text("import time\ntime.sleep(60)\n")
    child = pool.adopt(f"python {sleeper}", dict(os.environ))
    assert child is not None
    time.sleep(0.2)
    child._sock.close()     # the adopter vanishes
    _wait_dead(child.pid)


def test_adopted_child_sigkill_reports_exit_killed(pool, tmp_path):
    """A standby killed without an exit report reads as EXIT_KILLED —
    the code the provisioner's group SIGKILL gives a cold child."""
    pool.ensure()
    _wait_ready(pool.dir, 1)
    sleeper = tmp_path / "sleep.py"
    sleeper.write_text("import time\ntime.sleep(60)\n")
    child = pool.adopt(f"python {sleeper}", dict(os.environ))
    assert child is not None
    os.kill(child.pid, signal.SIGKILL)
    assert child.wait(timeout=5) == c.EXIT_KILLED


def test_standby_self_reaps_on_pool_dir_removal(tmp_path):
    """Teardown on shared filesystems: removing the pool dir is enough —
    every standby notices its entry is gone and exits."""
    import shutil

    pool = WarmPool(tmp_path / "pool", size=1)
    pool.ensure()
    _wait_ready(pool.dir, 1)
    info = json.loads(next(pool.dir.glob("sb_*.json")).read_text())
    shutil.rmtree(pool.dir)
    _wait_dead(info["pid"])


def test_standby_survives_driver_restart_via_driver_json(tmp_path):
    """Control-plane recovery keeps the pool WARM: a standby watching a
    driver pid does not self-reap the moment that pid dies — it rides
    the outage grace, re-resolves the RECOVERED driver's pid from the
    rewritten driver.json, and keeps standing by (ISSUE 12). Removing
    its pool entry still reaps it (the normal teardown contract)."""
    import subprocess

    driver_json = tmp_path / "driver.json"
    # 'driver' incarnation 1: a short-lived real process
    proc = subprocess.Popen([PY, "-c", "import time; time.sleep(2)"])
    driver_json.write_text(json.dumps(
        {"host": "127.0.0.1", "port": 1, "pid": proc.pid,
         "driver_generation": 0}))
    pool = WarmPool(tmp_path / "pool", size=1,
                    watch_pid=proc.pid, driver_json=str(driver_json),
                    outage_grace_s=20.0)
    pool.ensure()
    _wait_ready(pool.dir, 1)
    info = json.loads(next(pool.dir.glob("sb_*.json")).read_text())
    proc.kill()
    proc.wait()
    # the 'recovered' driver rewrites driver.json with ITS pid (use this
    # test process: provably alive and local)
    driver_json.write_text(json.dumps(
        {"host": "127.0.0.1", "port": 1, "pid": os.getpid(),
         "driver_generation": 1}))
    # old behavior self-reaped within one ~1s poll; the standby must now
    # outlive the watched pid's death by several polls
    time.sleep(3.0)
    assert _pid_alive(info["pid"]), (
        "standby self-reaped across a recoverable driver restart")
    assert count_ready(pool.dir) == 1
    # normal teardown still works: entry gone -> standby exits
    for p in pool.dir.glob("sb_*.json"):
        p.unlink()
    _wait_dead(info["pid"])


def test_reap_kills_standbys_and_removes_dir(tmp_path):
    pool = WarmPool(tmp_path / "pool", size=2)
    pool.ensure()
    _wait_ready(pool.dir, 2)
    pids = [json.loads(p.read_text())["pid"]
            for p in pool.dir.glob("sb_*.json")]
    assert len(pids) == 2
    pool.reap()
    for pid in pids:
        _wait_dead(pid, timeout=3)
    assert not pool.dir.exists()


def test_preempt_style_exit_code_propagates(pool, tmp_path):
    """EXIT_PREEMPTED from an adopted training child reaches the adopter
    exactly — the driver's budget-free preempt relaunch keys off it."""
    pool.ensure()
    _wait_ready(pool.dir, 1)
    script = tmp_path / "drain.py"
    script.write_text(f"import sys\nsys.exit({c.EXIT_PREEMPTED})\n")
    child = pool.adopt(f"python {script}", dict(os.environ))
    assert child is not None
    assert child.wait(timeout=10) == c.EXIT_PREEMPTED


# --------------------------------------------------------------- e2e contract

def _wait(predicate, timeout, msg):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.1)
    raise AssertionError(msg)


def test_e2e_adopted_launch_metrics_and_clean_teardown(
        tmp_job_dirs, tmp_path, monkeypatch):
    """Acceptance e2e (in-process driver + real executors): a restarted
    worker ADOPTS a pre-warmed standby; the trace carries child_adopted
    with the pool-hit attr, driver /metrics counts the adoption, the
    TaskInfo reports launch_path, and after driver stop no standby
    survives (pool dir reaped) — executor SIGTERMs and chaos kills
    included in the chain."""
    import tests.conftest as _conftest
    from tony_tpu.cluster.provisioner import LocalProvisioner
    from tony_tpu.conf import TonyConf
    from tony_tpu.driver import Driver
    from tony_tpu.events.trace import TASK_TRACE_FILE, read_traces

    monkeypatch.setenv(c.TEST_WARMPOOL_SKIP_WARMUP, "1")
    marker = tmp_path / "failed_once"
    script = tmp_path / "fail_once.py"
    script.write_text(
        "import os, sys\n"
        f"m = {str(marker)!r}\n"
        "if not os.path.exists(m):\n"
        "    open(m, 'w').write('x')\n"
        "    sys.exit(1)\n"
        "sys.exit(0)\n")
    conf = TonyConf({
        "tony.staging.dir": tmp_job_dirs["staging"],
        "tony.history.location": tmp_job_dirs["history"],
        "tony.history.intermediate": tmp_job_dirs["history"] + "/intermediate",
        "tony.history.finished": tmp_job_dirs["history"] + "/finished",
        "tony.am.monitor-interval-ms": 100,
        "tony.task.registration-poll-interval-ms": 100,
        "tony.task.metrics-interval-ms": 300,
        "tony.worker.instances": 1,
        "tony.worker.command": f"{PY} {script}",
        "tony.worker.max-restarts": 1,
        "tony.warmpool.size": 1,
        # replenish immediately so the restarted attempt finds the
        # replacement standby (the production default defers it off the
        # adopted child's compile window)
        "tony.execution.env": [f"PYTHONPATH={_conftest.REPO_ROOT}",
                               f"{c.TEST_WARMPOOL_SKIP_WARMUP}=1",
                               "TONY_WARMPOOL_REPLENISH_DELAY_S=0"],
    })
    job_dir = tmp_path / "job"
    job_dir.mkdir()
    conf.write_final(job_dir)
    driver = Driver(conf, app_id="warm_e2e", job_dir=str(job_dir),
                    provisioner=LocalProvisioner())
    driver.client_signal.set()
    t = threading.Thread(target=driver.run, daemon=True)
    t.start()
    t.join(timeout=60)
    assert not t.is_alive(), "driver never finished"
    assert driver.session.status.value == "SUCCEEDED", (
        driver.session.failure_message)

    # the restarted attempt adopted (the pool was seeded at prepare and
    # replenished after any first-attempt adoption)
    inter = Path(tmp_job_dirs["history"]) / "intermediate" / "warm_e2e"
    recs = read_traces(inter / TASK_TRACE_FILE)
    assert recs, "no task trace sealed"
    spans = [n for r in recs for n, *_ in r["spans"]]
    assert "restarted" in spans
    assert "child_adopted" in spans, spans
    attrs = {k: v for r in recs for k, v in r.get("attrs", {}).items()}
    assert attrs.get("warm_pool") == "hit"
    assert driver._warm_adoptions >= 1
    body = driver.render_metrics()
    assert "driver_warm_pool_adoptions_total" in body
    assert "driver_warm_pool_size" in body
    assert "driver_warm_pool_misses_total" in body
    infos = {t_.task_id: t_ for t_ in driver.session.task_infos()}
    assert infos["worker:0"].launch_path == "adopted"

    # teardown reaped the per-job pool: directory gone, no standby alive
    pool_dir = job_dir / c.WARMPOOL_DIR_NAME
    _wait(lambda: not pool_dir.exists(), 5, "pool dir survived teardown")
    for proc_dir in Path("/proc").iterdir():
        if not proc_dir.name.isdigit():
            continue
        try:
            cmdline = (proc_dir / "cmdline").read_bytes().decode()
        except OSError:
            continue
        assert str(pool_dir) not in cmdline, (
            f"orphaned standby: pid {proc_dir.name}")


def test_e2e_pool_miss_falls_back_cold(tmp_job_dirs, tmp_path, monkeypatch):
    """A configured pool with a NON-adoptable command must not change the
    outcome: the launch spawns cold, the job succeeds, the trace records
    the miss, and the driver counts it."""
    import tests.conftest as _conftest
    from tony_tpu.cluster.provisioner import LocalProvisioner
    from tony_tpu.conf import TonyConf
    from tony_tpu.driver import Driver
    from tony_tpu.events.trace import TASK_TRACE_FILE, read_traces

    monkeypatch.setenv(c.TEST_WARMPOOL_SKIP_WARMUP, "1")
    script = tmp_path / "ok.py"
    script.write_text("import sys\nsys.exit(0)\n")
    conf = TonyConf({
        "tony.staging.dir": tmp_job_dirs["staging"],
        "tony.history.location": tmp_job_dirs["history"],
        "tony.history.intermediate": tmp_job_dirs["history"] + "/intermediate",
        "tony.history.finished": tmp_job_dirs["history"] + "/finished",
        "tony.am.monitor-interval-ms": 100,
        "tony.task.registration-poll-interval-ms": 100,
        "tony.task.metrics-interval-ms": 300,
        "tony.worker.instances": 1,
        # the shell operator makes this non-adoptable by design
        "tony.worker.command": f"{PY} {script} && true",
        "tony.warmpool.size": 1,
        "tony.execution.env": [f"PYTHONPATH={_conftest.REPO_ROOT}",
                               f"{c.TEST_WARMPOOL_SKIP_WARMUP}=1"],
    })
    job_dir = tmp_path / "job"
    job_dir.mkdir()
    conf.write_final(job_dir)
    driver = Driver(conf, app_id="warm_miss", job_dir=str(job_dir),
                    provisioner=LocalProvisioner())
    driver.client_signal.set()
    t = threading.Thread(target=driver.run, daemon=True)
    t.start()
    t.join(timeout=60)
    assert not t.is_alive(), "driver never finished"
    assert driver.session.status.value == "SUCCEEDED", (
        driver.session.failure_message)
    inter = Path(tmp_job_dirs["history"]) / "intermediate" / "warm_miss"
    recs = read_traces(inter / TASK_TRACE_FILE)
    spans = [n for r in recs for n, *_ in r["spans"]]
    assert "child_spawned" in spans and "child_adopted" not in spans
    attrs = {k: v for r in recs for k, v in r.get("attrs", {}).items()}
    assert attrs.get("warm_pool") == "miss"
    assert driver._warm_misses >= 1 and driver._warm_adoptions == 0
    infos = {t_.task_id: t_ for t_ in driver.session.task_infos()}
    assert infos["worker:0"].launch_path == "cold"
    assert not (job_dir / c.WARMPOOL_DIR_NAME).exists()
