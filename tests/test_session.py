"""Session state-machine tests — completion/failure policy per reference
TonySession.onTaskCompleted / updateSessionStatus (TonySession.java:260-347)."""

from tony_tpu.api import JobStatus, TaskStatus
from tony_tpu.conf import TonyConf
from tony_tpu.session import Session


def make_session(extra=None):
    conf = TonyConf({"tony.worker.instances": 2, "tony.ps.instances": 1, **(extra or {})})
    return Session(conf)


def test_registration_and_cluster_spec():
    s = make_session()
    assert not s.all_registered()
    s.register_task("worker:0", "h1", 1000)
    s.register_task("worker:1", "h2", 1001)
    assert not s.all_registered()
    s.register_task("ps:0", "h3", 1002)
    assert s.all_registered()
    spec = s.cluster_spec()
    assert spec == {"worker": ["h1:1000", "h2:1001"], "ps": ["h3:1002"]}


def test_chief_failure_kills_job():
    s = make_session()
    # no 'chief' role -> worker:0 is chief (TonySession.java:381-384)
    s.on_task_completed("worker", 0, exit_code=1)
    assert s.status == JobStatus.FAILED
    assert "chief" in s.failure_message


def test_non_chief_worker_failure_tolerated():
    s = make_session()
    s.on_task_completed("worker", 1, exit_code=1)
    assert s.status != JobStatus.FAILED
    s.on_task_completed("worker", 0, exit_code=0)
    s.on_task_completed("ps", 0, exit_code=0)
    assert s.update_status() == JobStatus.SUCCEEDED


def test_fail_on_worker_failure_flag():
    s = make_session({"tony.application.fail-on-worker-failure-enabled": True})
    s.on_task_completed("worker", 1, exit_code=1)
    assert s.status == JobStatus.FAILED


def test_stop_on_failure_roles():
    s = make_session({"tony.application.stop-on-failure-jobtypes": "ps"})
    s.on_task_completed("ps", 0, exit_code=1)
    assert s.status == JobStatus.FAILED


def test_all_tracked_failed():
    s = make_session()
    s.on_task_completed("worker", 1, exit_code=1)
    s.on_task_completed("ps", 0, exit_code=1)
    # worker:0 (chief) failing fails the job outright
    s.tasks["worker"][0].status = TaskStatus.KILLED
    s.tasks["worker"][0].exit_code = 137
    assert s.update_status() == JobStatus.FAILED


def test_untracked_roles_excluded_from_completion():
    s = make_session({
        "tony.tensorboard.instances": 1,
        "tony.application.untracked.jobtypes": "tensorboard",
    })
    assert s.total_tracked() == 3
    s.on_task_completed("worker", 0, exit_code=0)
    s.on_task_completed("worker", 1, exit_code=0)
    s.on_task_completed("ps", 0, exit_code=0)
    # tensorboard still running, but job is done
    assert s.update_status() == JobStatus.SUCCEEDED


def test_untracked_failure_fails_fast():
    """Reference ApplicationMaster.java:1265-1269 — untracked crash fails the job."""
    s = make_session({
        "tony.tensorboard.instances": 1,
        "tony.application.untracked.jobtypes": "tensorboard",
    })
    s.on_task_completed("tensorboard", 0, exit_code=2)
    assert s.status == JobStatus.FAILED


def test_allocation_matching_by_priority():
    s = make_session()
    specs = {sp.name: sp for sp in s.conf.role_specs()}
    t1 = s.get_and_init_matching_task(specs["worker"].priority, "c1")
    t2 = s.get_and_init_matching_task(specs["worker"].priority, "c2")
    t3 = s.get_and_init_matching_task(specs["worker"].priority, "c3")
    assert t1.task_id == "worker:0" and t2.task_id == "worker:1"
    assert t3 is None, "no more worker slots"
    assert t1.status == TaskStatus.ALLOCATED
