"""Closed-loop autoscaler + multi-tenant resource arbiter
(tony_tpu/autoscale.py + driver integration — docs/autoscaling.md).

The contract under test, bottom-up: windowed-TTFT math (Prometheus
bucket scraping, counter-reset clamping, quantile estimation), the
control law's hysteresis (breach ticks, cooldown, clear-for-a-cooldown
scale-down, the below-min floor rule), the arbiter's quota math and
donor ordering (batch-only, chief-safe, floor-safe, busy-excluded),
journal replay of the scale/park/donate ledgers (a recovered driver
resumes mid-cooldown instead of flapping), and two scripted-provisioner
e2es: scale-up/scale-down of a replica fleet against test-controlled
/stats + /metrics endpoints, and the full donation cycle — interactive
demand preempt-drains a batch trainer, the slot serves a replica, and
the trainer reclaims it (with the checkpoint prestaged via
TONY_PRESTAGE_CKPT) once serving scales back down. Stub executors speak
the real framed-JSON RPC (the test_elastic pattern), TINY everything,
well under the 45s per-test budget.
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import pytest

import tony_tpu.constants as c
from tony_tpu.autoscale import (
    AutoscaleController,
    FleetObservation,
    FleetWatcher,
    ResourceArbiter,
    bucket_delta,
    bucket_quantile,
    scrape_ttft_buckets,
)
from tony_tpu.cluster.provisioner import ContainerHandle, Provisioner
from tony_tpu.conf import TonyConf
from tony_tpu.driver import Driver
from tony_tpu.events.driver_journal import (
    DriverJournal, load_state, rewrite_journal,
)
from tony_tpu.rpc import RpcClient
from tony_tpu.session import Session

# --------------------------------------------------------------------------
# windowed-TTFT math: scrape -> delta -> quantile
# --------------------------------------------------------------------------

PROM = """\
# HELP serving_ttft_seconds ttft
# TYPE serving_ttft_seconds histogram
serving_ttft_seconds_bucket{le="0.1"} 10
serving_ttft_seconds_bucket{le="1.0"} 90
serving_ttft_seconds_bucket{le="+Inf"} 100
serving_ttft_seconds_bucket{model="m",le="0.1"} 5
serving_ttft_seconds_sum 42.0
serving_ttft_seconds_count 100
other_seconds_bucket{le="0.1"} 7
"""


def test_scrape_ttft_buckets_skips_labeled_partitions():
    got = scrape_ttft_buckets(PROM)
    assert got == {"0.1": 10.0, "1.0": 90.0, "+Inf": 100.0}, got


def test_bucket_quantile_and_delta():
    cur = {"0.1": 10.0, "1.0": 90.0, "+Inf": 100.0}
    # p50: rank 50 lands in (0.1, 1.0], 40/80 through the bucket
    assert abs(bucket_quantile(cur, 0.5) - 0.55) < 1e-9
    # overflow bucket answers its honest lower edge
    assert bucket_quantile(cur, 0.999) == 1.0
    assert bucket_quantile({}, 0.5) is None
    assert bucket_quantile({"0.1": 0.0}, 0.5) is None
    prev = {"0.1": 8.0, "1.0": 85.0, "+Inf": 90.0}
    assert bucket_delta(prev, cur) == {"0.1": 2.0, "1.0": 5.0,
                                       "+Inf": 10.0}
    # a restarted replica's counters reset: negative deltas clamp to
    # the CURRENT value (the fresh process's whole history)
    assert bucket_delta(cur, prev) == prev


# --------------------------------------------------------------------------
# control law: hysteresis, cooldown, floor
# --------------------------------------------------------------------------

def _ctl(**kw):
    kw.setdefault("ttft_slo_s", 1.0)
    kw.setdefault("queue_slo", 4)
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 3)
    kw.setdefault("cooldown_s", 10.0)
    kw.setdefault("breach_ticks", 2)
    return AutoscaleController(**kw)


def test_controller_breach_ticks_and_cooldown():
    """One breaching window never scales; the second does; a repeat
    inside the cooldown is suppressed even while still breaching."""
    ctl = _ctl()
    hot = FleetObservation(live=1, queued=10)
    assert ctl.decide(hot, 1, now=0.0) is None          # streak 1
    d = ctl.decide(hot, 1, now=1.0)
    assert d is not None and d.direction == "up"
    ctl.note_scaled("up", now=1.0)
    assert ctl.decide(hot, 2, now=2.0) is None          # cooldown
    assert ctl.decide(hot, 2, now=5.0) is None
    # past the cooldown, a persisting breach scales again
    assert ctl.decide(hot, 2, now=12.0) is None         # streak re-arms
    d = ctl.decide(hot, 2, now=13.0)
    assert d is not None and d.direction == "up"


def test_controller_ttft_slo_and_max_bound():
    ctl = _ctl(queue_slo=0)
    slow = FleetObservation(live=2, queued=0, ttft_p99_s=2.5,
                            window_samples=20)
    assert ctl.decide(slow, 2, now=0.0) is None
    d = ctl.decide(slow, 2, now=1.0)
    assert d is not None and "ttft" in d.reason
    # at max: breach or not, no decision
    ctl2 = _ctl(queue_slo=0, max_replicas=2)
    ctl2.decide(slow, 2, now=0.0)
    assert ctl2.decide(slow, 2, now=1.0) is None


def test_controller_scale_down_needs_clear_for_a_cooldown():
    """Scale-down only after the signals sit below HALF the SLO for a
    full cooldown — and a single breachy blip re-arms the clock."""
    ctl = _ctl()
    idle = FleetObservation(live=2, queued=0, ttft_p99_s=0.1,
                            window_samples=5)
    assert ctl.decide(idle, 2, now=0.0) is None
    assert ctl.decide(idle, 2, now=5.0) is None         # clear 5s < 10s
    blip = FleetObservation(live=2, queued=3)           # >half queue SLO
    assert ctl.decide(blip, 2, now=6.0) is None         # re-arms clear
    assert ctl.decide(idle, 2, now=7.0) is None
    assert ctl.decide(idle, 2, now=16.0) is None        # clear 9s
    d = ctl.decide(idle, 2, now=17.5)
    assert d is not None and d.direction == "down"
    ctl.note_scaled("down", now=17.5)
    # never below min
    assert ctl.decide(idle, 1, now=60.0) is None


def test_controller_floor_rule_relaunches_below_min():
    """A fleet below min (replica parked after budget exhaustion)
    scales up WITHOUT waiting for an SLO breach or breach ticks."""
    ctl = _ctl(min_replicas=2, max_replicas=3)
    idle = FleetObservation(live=1, queued=0)
    d = ctl.decide(idle, 1, now=0.0)
    assert d is not None and d.direction == "up" and "min" in d.reason


def test_controller_recovered_cooldown_suppresses_flap():
    """A controller built with a journaled last_scale_t (driver
    recovery) stays in cooldown — the no-flap contract."""
    ctl = _ctl(last_scale_t=100.0)
    hot = FleetObservation(live=1, queued=10)
    ctl.decide(hot, 1, now=101.0)
    assert ctl.decide(hot, 1, now=102.0) is None        # mid-cooldown
    d = ctl.decide(hot, 1, now=111.0)
    assert d is not None and d.direction == "up"


# --------------------------------------------------------------------------
# arbiter: quota math + donor ordering over a real Session
# --------------------------------------------------------------------------

def _session(**conf_extra):
    conf = TonyConf({
        "tony.replica.instances": 3,
        "tony.replica.command": "stub",
        "tony.trainer.instances": 3,
        "tony.trainer.command": "stub",
        "tony.trainer.priority-class": "batch",
        **conf_extra,
    })
    return Session(conf)


def _run(session, task_id):
    session.register_task(task_id, "127.0.0.1", 1)


def test_arbiter_quota_math():
    s = _session(**{"tony.replica.quota": 2})
    arb = ResourceArbiter(s, pool_slots=6)
    assert arb.free() == 6 and arb.held("replica") == 0
    _run(s, "replica:0")
    _run(s, "trainer:0")
    _run(s, "trainer:1")
    assert arb.held("replica") == 1 and arb.held("trainer") == 2
    assert arb.free() == 3
    assert arb.quota("replica") == 2 and arb.quota("trainer") == 3
    assert arb.can_grant("replica")
    _run(s, "replica:1")
    assert arb.over_quota("replica") and not arb.can_grant("replica")
    # detached slots are free pool capacity
    s.detach_task("trainer:1")
    assert arb.held("trainer") == 1 and arb.free() == 3
    snap = arb.snapshot()
    assert snap["class"] == {"replica": "interactive",
                             "trainer": "batch"}


def test_arbiter_pool_exhaustion_blocks_grant():
    s = _session()
    arb = ResourceArbiter(s, pool_slots=2)
    _run(s, "replica:0")
    _run(s, "trainer:0")
    assert arb.free() == 0 and not arb.can_grant("replica")


def test_arbiter_donor_ordering_and_floors():
    """Donors come only from the batch tier: highest-index RUNNING
    non-chief of the MOST-held batch role, never below the elastic
    floor, never a task already mid-drain (busy)."""
    s = _session()
    arb = ResourceArbiter(s, pool_slots=4)
    # interactive-only fleet: nobody donates
    _run(s, "replica:0")
    assert arb.pick_donor("replica") is None
    _run(s, "trainer:0")
    _run(s, "trainer:1")
    _run(s, "trainer:2")
    assert arb.pick_donor("replica") == "trainer:2"
    assert arb.pick_donor("replica", busy={"trainer:2"}) == "trainer:1"
    # the elastic floor holds: 3 held, floor 3 -> no donor
    assert arb.pick_donor("replica", elastic_min=3) is None
    # trainer:0 is this gang's chief (no chief role configured):
    # with only it running, nothing qualifies
    assert arb.pick_donor("replica", busy={"trainer:1", "trainer:2"}) \
        is None


# --------------------------------------------------------------------------
# journal: the scale/park/donate ledgers replay and survive compaction
# --------------------------------------------------------------------------

def test_journal_scale_ledgers_replay_and_compact(tmp_path):
    path = tmp_path / "driver.journal.jsonl"
    j = DriverJournal(path)
    j.record("meta", app_id="a", token="t", session_id=0, rpc_port=1,
             driver_generation=0)
    j.record("detach", task="replica:1")
    j.record("park", task="replica:1")
    j.record("detach", task="replica:2")
    j.record("park", task="replica:2")
    j.record("scale", dir="up", task="replica:1", t=100.0, reason="q")
    j.record("unpark", task="replica:1")
    j.record("reattach", task="replica:1")
    j.record("donate", task="trainer:1", **{"for": "replica"})
    j.record("donated", task="trainer:1")
    j.record("ledger", kind="scale_down", task="replica:0")
    j.close()
    state = load_state(path)
    assert state.parked == {"replica:2"}
    assert state.detached == {"replica:2"}
    assert state.donations == {} and state.donated == {"trainer:1"}
    assert state.scale_downs == {"replica:0"}
    assert [op["dir"] for op in state.scale_ops] == ["up"]
    assert state.scale_ops[0]["t"] == 100.0
    # compaction round-trips every ledger
    rewrite_journal(path, state)
    again = load_state(path)
    assert again.parked == state.parked
    assert again.donated == state.donated
    assert again.scale_downs == state.scale_downs
    assert again.scale_ops[-1]["t"] == 100.0
    # a reclaim clears the donated ledger; a launch clears scale_down;
    # and PARKING clears scale_down too (parking IS the drain's
    # discharge — a recovered driver must not see a parked slot as
    # still mid-drain)
    j2 = DriverJournal(path)
    j2.record("reclaimed", task="trainer:1")
    j2.record("launch", task="replica:0", attempt=2, container_id="x",
              pid=0, host="h", t=1.0, log_path="")
    j2.record("ledger", kind="scale_down", task="replica:3")
    j2.record("park", task="replica:3")
    j2.close()
    final = load_state(path)
    assert final.donated == set() and final.scale_downs == set()
    assert "replica:3" in final.parked


# --------------------------------------------------------------------------
# scripted-provisioner e2e plumbing (the test_elastic pattern)
# --------------------------------------------------------------------------

class ScriptedProvisioner(Provisioner):
    def __init__(self, script):
        super().__init__()
        self._script = script
        self._attempts: dict[str, int] = {}
        self._lock = threading.Lock()
        self.launches: list[str] = []
        self.launch_envs: dict[str, list[dict]] = {}
        self.stops: list[str] = []

    def launch(self, spec, index, env, log_dir):
        task_id = f"{spec.name}:{index}"
        with self._lock:
            attempt = self._attempts.get(task_id, 0)
            self._attempts[task_id] = attempt + 1
            self.launches.append(task_id)
            self.launch_envs.setdefault(task_id, []).append(dict(env))
        handle = ContainerHandle(
            container_id=f"stub_{task_id}_{attempt}",
            host="127.0.0.1", role=spec.name, index=index)
        handle.extra["stop"] = threading.Event()
        threading.Thread(
            target=self._run, args=(spec, index, env, handle, attempt),
            daemon=True).start()
        return handle

    def _run(self, spec, index, env, handle, attempt):
        try:
            code = self._script(spec, index, env, handle, attempt)
        except Exception as e:              # pragma: no cover - debug aid
            print(f"stub executor failed: {type(e).__name__}: {e}",
                  flush=True)
            code = 1
        if code is not None and self.on_completion:
            self.on_completion(handle, code)

    def stop_container(self, handle):
        with self._lock:
            self.stops.append(handle.container_id)
        handle.extra["stop"].set()

    def stop_all(self):
        pass


class _StatsServer:
    """A test-controlled replica endpoint: /stats + /metrics with
    mutable queue depth and TTFT bucket counts — the controller's
    telemetry inputs without a model."""

    def __init__(self):
        self.queued = 0
        self.slow = 0       # cumulative ttft observations in (1, +Inf]
        self.fast = 0       # cumulative ttft observations <= 0.1
        outer = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/stats":
                    body = json.dumps({
                        "queued": outer.queued, "active": 0}).encode()
                    ctype = "application/json"
                elif self.path == "/metrics":
                    f, s = outer.fast, outer.slow
                    body = (
                        f'serving_ttft_seconds_bucket{{le="0.1"}} {f}\n'
                        f'serving_ttft_seconds_bucket{{le="1.0"}} {f}\n'
                        f'serving_ttft_seconds_bucket{{le="+Inf"}} '
                        f'{f + s}\n').encode()
                    ctype = "text/plain"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.port = self.httpd.server_address[1]

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _conf(dirs, **extra):
    return TonyConf({
        "tony.staging.dir": dirs["staging"],
        "tony.history.location": dirs["history"],
        "tony.history.intermediate": dirs["history"] + "/intermediate",
        "tony.history.finished": dirs["history"] + "/finished",
        "tony.am.monitor-interval-ms": 50,
        "tony.task.registration-poll-interval-ms": 50,
        # a high interval parks the background runner; tests drive
        # autoscale_tick by hand for determinism
        "tony.autoscale.interval-s": 600,
        **extra,
    })


def _driver(dirs, tmp_path, script, name, **conf_extra):
    conf = _conf(dirs, **conf_extra)
    job_dir = tmp_path / f"job_{name}"
    job_dir.mkdir(exist_ok=True)
    conf.write_final(job_dir)
    driver = Driver(conf, app_id=name, job_dir=str(job_dir),
                    token="autoscale-secret",
                    provisioner=ScriptedProvisioner(script))
    driver.client_signal.set()
    return driver


def _rpc_for(env):
    return RpcClient(env[c.ENV_DRIVER_HOST], int(env[c.ENV_DRIVER_PORT]),
                     token=env.get(c.ENV_TOKEN, ""), role="executor")


def _wait(pred, timeout=20, every=0.05, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(every)
    raise AssertionError(f"timed out waiting for {msg}")


def test_autoscale_scale_up_then_down_e2e(tmp_job_dirs, tmp_path):
    """The closed loop against scripted replicas and test-controlled
    telemetry: a queue breach launches the parked replica:1 (journal
    decision + unpark + trace mark 'scaled_up'); a sustained clear
    drains the least-loaded replica back down (SIGTERM via the
    provisioner, completion parks the slot, 'scaled_down' mark), with
    the cooldown ledger journaled both times and zero restart budget
    spent."""
    stats = [_StatsServer() for _ in range(2)]

    def script(spec, index, env, handle, attempt):
        rpc = _rpc_for(env)
        task_id = f"{spec.name}:{index}"
        payload = rpc.call("register_worker", task_id=task_id,
                           host="127.0.0.1", port=23300 + index,
                           attempt=int(env.get(c.ENV_TASK_ATTEMPT, -1)))
        while payload is None:
            time.sleep(0.03)
            payload = rpc.call("get_cluster_spec", task_id=task_id)
        rpc.call("publish_ports", task_id=task_id,
                 ports={"serve_port": stats[index].port})
        # serve until drained (scale-down SIGTERM) or the test ends
        handle.extra["stop"].wait(60)
        rpc.call("register_execution_result", task_id=task_id,
                 exit_code=137)
        rpc.close()
        return 137

    driver = _driver(
        tmp_job_dirs, tmp_path, script, name="updown",
        **{"tony.replica.instances": 2,
           "tony.replica.command": "stub",
           "tony.replica.max-restarts": 1,
           "tony.application.framework": "serving",
           "tony.autoscale.enabled": True,
           "tony.autoscale.role": "replica",
           "tony.autoscale.min": 1,
           "tony.autoscale.queue-depth-slo": 4,
           "tony.quota.pool-slots": 2})
    t = threading.Thread(target=driver.run, daemon=True)
    t.start()
    try:
        # the parked slot never launched; only replica:0 runs
        _wait(lambda: driver.serving_endpoints("replica"),
              msg="replica:0 serving")
        assert driver.provisioner.launches == ["replica:0"]
        assert "replica:1" in driver._parked

        clock = {"t": 1000.0}
        ctl = AutoscaleController(
            queue_slo=4, min_replicas=1, max_replicas=2,
            cooldown_s=5.0, breach_ticks=2,
            now_fn=lambda: clock["t"])
        watcher = FleetWatcher()

        stats[0].queued = 10                    # breach
        assert driver.autoscale_tick(ctl, watcher) == "idle"  # streak 1
        clock["t"] += 1
        assert driver.autoscale_tick(ctl, watcher) == "scaled_up"
        _wait(lambda: len(driver.serving_endpoints("replica")) == 2,
              msg="replica:1 serving")
        assert "replica:1" not in driver._parked
        assert driver.arbiter.held("replica") == 2

        # sustained clear -> scale down past the cooldown; replica:1 is
        # least-loaded (its stats show queue 0 vs replica:0's 10...
        # flip the load so the victim is deterministic)
        stats[0].queued = 0
        clock["t"] += 6
        assert driver.autoscale_tick(ctl, watcher) == "idle"  # clear t0
        clock["t"] += 6
        assert driver.autoscale_tick(ctl, watcher) == "scaled_down"
        _wait(lambda: "replica:1" in driver._parked,
              msg="replica:1 parked")
        assert len(driver.serving_endpoints("replica")) == 1
        assert driver.arbiter.held("replica") == 1

        text = driver.render_metrics()
        assert "driver_autoscale_scale_ups_total 1" in text
        assert "driver_autoscale_scale_downs_total 1" in text
        assert "driver_task_restarts_total 0" in text
        assert 'driver_quota_slots{role="replica",stat="held"} 1' in text
        state = load_state(Path(driver.job_dir) / c.DRIVER_JOURNAL_FILE)
        dirs = [op["dir"] for op in state.scale_ops]
        assert dirs == ["up", "down"], dirs
        assert state.parked == {"replica:1"}
    finally:
        driver._stop_requested.set()
        for h in list(driver._handles.values()):
            h.extra["stop"].set()
        t.join(timeout=20)
        for s in stats:
            s.close()


def test_donation_cycle_e2e(tmp_job_dirs, tmp_path):
    """The arbiter's full batch<->interactive capacity cycle on one
    exhausted pool: a serving breach finds no free slot, preempt-drains
    trainer:1 (budget-free, 'donated' trace mark), launches replica:1
    on the freed capacity; when traffic ebbs, the replica drains back
    and the donated slot is RECLAIMED by the elastic rescale timer —
    relaunched with TONY_PRESTAGE_CKPT stamped (checkpoint-aware
    placement) and a 'reclaimed' trace mark."""
    stats = [_StatsServer() for _ in range(2)]
    trainer_events: dict = {"preempt": threading.Event()}

    def script(spec, index, env, handle, attempt):
        rpc = _rpc_for(env)
        task_id = f"{spec.name}:{index}"
        payload = rpc.call("register_worker", task_id=task_id,
                           host="127.0.0.1", port=23400 + 10 * (
                               spec.name == "trainer") + index,
                           attempt=int(env.get(c.ENV_TASK_ATTEMPT, -1)))
        while payload is None:
            time.sleep(0.03)
            payload = rpc.call("get_cluster_spec", task_id=task_id)
        if spec.name == "replica":
            rpc.call("publish_ports", task_id=task_id,
                     ports={"serve_port": stats[index].port})
            handle.extra["stop"].wait(60)
            rpc.call("register_execution_result", task_id=task_id,
                     exit_code=137)
            rpc.close()
            return 137
        # trainer: heartbeat, drain on a preempt command or a resize
        # SIGTERM (the stop event), exit EXIT_PREEMPTED either way
        deadline = time.time() + 60
        while time.time() < deadline:
            res = rpc.call("heartbeat", task_id=task_id)
            if isinstance(res, dict) and res.get("preempt"):
                trainer_events["preempt"].set()
                break
            if handle.extra["stop"].is_set():
                break
            time.sleep(0.05)
        rpc.call("register_execution_result", task_id=task_id,
                 exit_code=c.EXIT_PREEMPTED)
        rpc.close()
        return c.EXIT_PREEMPTED

    driver = _driver(
        tmp_job_dirs, tmp_path, script, name="donation",
        **{"tony.replica.instances": 2,
           "tony.replica.command": "stub",
           "tony.replica.max-restarts": 1,
           "tony.trainer.instances": 2,
           "tony.trainer.command": "stub",
           "tony.trainer.max-restarts": 1,
           "tony.trainer.priority-class": "batch",
           "tony.application.framework": "serving",
           "tony.task.heartbeat-interval-ms": 100,
           "tony.train.elastic-enabled": True,
           "tony.train.elastic-min-instances": 1,
           "tony.train.rescale-retry-ms": 200,
           "tony.train.checkpoint-dir": "/ckpt/run_$TONY_TASK_INDEX",
           "tony.autoscale.enabled": True,
           "tony.autoscale.role": "replica",
           "tony.autoscale.min": 1,
           "tony.autoscale.queue-depth-slo": 4,
           "tony.quota.pool-slots": 3})
    t = threading.Thread(target=driver.run, daemon=True)
    t.start()
    try:
        _wait(lambda: driver.serving_endpoints("replica")
              and driver.arbiter.held("trainer") == 2,
              msg="initial formation")
        assert driver.arbiter.free() == 0

        clock = {"t": 1000.0}
        ctl = AutoscaleController(
            queue_slo=4, min_replicas=1, max_replicas=2,
            cooldown_s=5.0, breach_ticks=1,
            now_fn=lambda: clock["t"])
        watcher = FleetWatcher()
        stats[0].queued = 10
        # no free slot: the tick initiates a donation instead
        assert driver.autoscale_tick(ctl, watcher) == "awaiting_donation"
        assert trainer_events["preempt"].wait(20), "no preempt command"
        _wait(lambda: "trainer:1" in driver._donated,
              msg="donation discharge")
        # the discharge hands the freed slot STRAIGHT to serving (a
        # tick-paced claim would race the faster rescale-retry timer,
        # which would reclaim the slot for batch — the donate->reclaim
        # livelock); replica:1 launches without another tick, and a
        # tick meanwhile reports the in-flight/at-max state, never a
        # duplicate donation
        _wait(lambda: len(driver.serving_endpoints("replica")) == 2,
              msg="replica:1 serving")
        clock["t"] += 1
        assert driver.autoscale_tick(ctl, watcher) in ("idle",
                                                       "at_max")
        assert driver.arbiter.donations == 1
        # donated slot must NOT be reclaimed while the pool is full
        time.sleep(0.6)
        assert "trainer:1" in driver._donated
        assert driver.arbiter.held("trainer") == 1

        # traffic ebbs: scale back down, then the rescale timer
        # reclaims the donated slot with the checkpoint prestaged
        stats[0].queued = 0
        clock["t"] += 6
        driver.autoscale_tick(ctl, watcher)             # clear t0
        clock["t"] += 6
        _wait(lambda: driver.autoscale_tick(ctl, watcher)
              == "scaled_down", timeout=10, msg="scale-down")
        _wait(lambda: "trainer:1" not in driver._donated
              and driver.arbiter.held("trainer") == 2,
              msg="reclaim")
        assert driver.arbiter.reclaims == 1
        envs = driver.provisioner.launch_envs["trainer:1"]
        assert envs[-1].get(c.ENV_PRESTAGE_CKPT) == \
            "/ckpt/run_$TONY_TASK_INDEX"
        assert c.ENV_PRESTAGE_CKPT not in envs[0]
        text = driver.render_metrics()
        assert "driver_quota_donations_total 1" in text
        assert "driver_quota_reclaims_total 1" in text
        assert "driver_task_restarts_total 0" in text
        # trace marks: donated + reclaimed on trainer:1
        with driver._tt_lock:
            tr = driver.task_traces.get("trainer:1")
            names = [n for n, _ in tr.spans]
        assert "donated" in names and "reclaimed" in names, names
    finally:
        driver._stop_requested.set()
        for h in list(driver._handles.values()):
            h.extra["stop"].set()
        t.join(timeout=20)
        for s in stats:
            s.close()


# --------------------------------------------------------------------------
# checkpoint prestage helper (train/checkpoint.py)
# --------------------------------------------------------------------------

def test_prestage_checkpoint_reads_newest_complete_step(tmp_path):
    from tony_tpu.train.checkpoint import prestage_checkpoint

    root = tmp_path / "ckpt"
    (root / "5").mkdir(parents=True)
    (root / "5" / "a.bin").write_bytes(b"x" * 100)
    (root / "10").mkdir()
    (root / "10" / "b.bin").write_bytes(b"y" * 300)
    (root / "10" / "sub").mkdir()
    (root / "10" / "sub" / "c.bin").write_bytes(b"z" * 50)
    # an in-progress orbax tmp dir must not be picked
    (root / "12.orbax-checkpoint-tmp-123").mkdir()
    got = prestage_checkpoint(str(root))
    assert got == {"step": 10, "files": 2, "bytes": 350}
    assert prestage_checkpoint(str(tmp_path / "missing")) is None
    empty = tmp_path / "empty"
    empty.mkdir()
    assert prestage_checkpoint(str(empty)) is None


def test_controller_router_view_is_max_not_sum():
    """The router's queue estimate OVERLAPS the replicas' own /stats
    (a router-posted request admitted server-side appears in both):
    the control law takes the max of the two views — summing would
    breach (and starve scale-downs) at half the configured SLO."""
    ctl = _ctl()                    # queue_slo 4
    both = FleetObservation(live=1, queued=3, router_queued=3)
    assert ctl.decide(both, 1, now=0.0) is None     # max 3 <= 4
    assert ctl.decide(both, 1, now=1.0) is None     # never breaches
    hot = FleetObservation(live=1, queued=0, router_queued=9)
    ctl2 = _ctl()
    ctl2.decide(hot, 1, now=0.0)
    d = ctl2.decide(hot, 1, now=1.0)
    assert d is not None and d.direction == "up"    # router-only breach


# --------------------------------------------------------------------------
# two-tier scaling (disaggregated fleets, PR 17)
# --------------------------------------------------------------------------


def test_controller_tiered_breach_attribution():
    """On a tiered fleet the breach SIGNAL names the tier: queue depth
    scales the prefill tier, TTFT/TPOT p99 the decode tier; the same
    signals on an untiered fleet leave tier empty (today's behavior)."""
    ctl = _ctl()
    hot_q = FleetObservation(live=2, queued=10, tiered=True)
    ctl.decide(hot_q, 2, now=0.0)
    d = ctl.decide(hot_q, 2, now=1.0)
    assert d is not None and d.direction == "up" and d.tier == "prefill"

    ctl = _ctl(queue_slo=0)
    slow = FleetObservation(live=2, ttft_p99_s=2.5, window_samples=20,
                            tiered=True)
    ctl.decide(slow, 2, now=0.0)
    d = ctl.decide(slow, 2, now=1.0)
    assert d is not None and d.tier == "decode" and "ttft" in d.reason
    # untiered: same breach, no tier
    ctl = _ctl()
    flat = FleetObservation(live=2, queued=10)
    ctl.decide(flat, 2, now=0.0)
    d = ctl.decide(flat, 2, now=1.0)
    assert d is not None and d.tier == ""


def test_controller_tpot_slo_breach_scales_decode():
    """TPOT p99 is the decode tier's own latency signal: a controller
    with tpot_slo_s set breaches on it (tier 'decode' when tiered) and
    a sub-half-SLO TPOT counts toward the scale-down clear window."""
    ctl = _ctl(queue_slo=0, ttft_slo_s=0.0, tpot_slo_s=0.05)
    slow = FleetObservation(live=2, tpot_p99_s=0.2, window_samples=20,
                            tiered=True)
    assert ctl.decide(slow, 2, now=0.0) is None
    d = ctl.decide(slow, 2, now=1.0)
    assert d is not None and d.direction == "up"
    assert d.tier == "decode" and "tpot" in d.reason
    # a TPOT still above half-SLO blocks the clear window
    ctl2 = _ctl(queue_slo=0, ttft_slo_s=0.0, tpot_slo_s=0.05,
                cooldown_s=5.0)
    warm = FleetObservation(live=2, tpot_p99_s=0.04, window_samples=5)
    for t in (0.0, 3.0, 6.0, 9.0):
        assert ctl2.decide(warm, 2, now=t) is None
    cool = FleetObservation(live=2, tpot_p99_s=0.01, window_samples=5)
    assert ctl2.decide(cool, 2, now=10.0) is None       # clear re-armed
    d = ctl2.decide(cool, 2, now=16.0)
    assert d is not None and d.direction == "down"


def test_watcher_parses_roles_and_tpot(monkeypatch):
    """FleetWatcher marks the observation tiered when any replica
    advertises a specialist role, splits prefill queue depth out, and
    windows TPOT buckets by delta exactly like TTFT."""
    import json as _json

    from tony_tpu.autoscale import FleetWatcher

    stats = {
        "p": {"role": "prefill", "queued": 6, "active": 0, "slots": 2},
        "d": {"role": "decode", "queued": 1, "active": 2, "slots": 2},
    }
    tpot = {"0.025": 0, "0.1": 40, "+Inf": 40}

    def metrics_text():
        return "\n".join(
            f'serving_tpot_seconds_bucket{{le="{le}"}} {v}'
            for le, v in tpot.items())

    watcher = FleetWatcher()

    def fake_get(url):
        for name in stats:
            if f"//{name}:" in url.replace("http://", "//h-"):
                pass
        if url.endswith("/stats"):
            name = url.split("//")[1].split(":")[0].split("-")[1]
            return _json.dumps(stats[name])
        return metrics_text()

    monkeypatch.setattr(watcher, "_get", fake_get)
    eps = [("p", "h-p", 1), ("d", "h-d", 2)]
    obs = watcher.observe(eps)
    assert obs.tiered
    assert obs.queued_prefill == 6
    assert obs.queued == 7 and obs.live == 2
    assert watcher.last_roles == {"p": "prefill", "d": "decode"}
    assert obs.tpot_p99_s is None, "first poll is the baseline"
    # a delta-only second poll windows TPOT: 10 new samples under 0.1s
    tpot = {"0.025": 0, "0.1": 50, "+Inf": 50}
    obs2 = watcher.observe(eps)
    assert obs2.tpot_p99_s is not None
    assert 0.025 < obs2.tpot_p99_s <= 0.1
    # an untiered fleet never sets the flag
    stats["p"]["role"] = "both"
    del stats["d"]["role"]
    assert not watcher.observe(eps).tiered


# --------------------------------------------------------------------------
# router-tier scaling (docs/serving.md "Router tier HA")
# --------------------------------------------------------------------------


def test_controller_router_tier_law():
    """The router tier scales on ITS OWN saturation signal — mean
    in-flight relays per live front door — with the serving hysteresis
    shape (breach ticks up, clear-for-a-cooldown down, floor rule) and
    a SHARED cooldown; n_routers=None or router_slo=0 leaves the law
    inert (byte-identical to the two-tier controller)."""
    def rctl(**kw):
        kw.setdefault("queue_slo", 0)
        kw.setdefault("ttft_slo_s", 0.0)
        kw.setdefault("min_replicas", 1)
        kw.setdefault("max_replicas", 1)
        kw.setdefault("router_slo", 2.0)
        kw.setdefault("router_min", 1)
        kw.setdefault("router_max", 3)
        kw.setdefault("cooldown_s", 10.0)
        kw.setdefault("breach_ticks", 2)
        return AutoscaleController(**kw)

    hot = FleetObservation(live=1, routers_live=2,
                           router_relay_inflight=10)
    # inert without a router fleet size (or with the SLO unset)
    ctl = rctl()
    assert ctl.decide(hot, 1, now=0.0) is None
    assert ctl.decide(hot, 1, now=1.0, n_routers=None) is None
    off = rctl(router_slo=0.0)
    assert off.decide(hot, 1, now=0.0, n_routers=2) is None
    assert off.decide(hot, 1, now=1.0, n_routers=2) is None

    # breach ticks, then up with tier="router"; cooldown suppresses
    ctl = rctl()
    assert ctl.decide(hot, 1, now=0.0, n_routers=2) is None  # streak 1
    d = ctl.decide(hot, 1, now=1.0, n_routers=2)
    assert d is not None and d.direction == "up" and d.tier == "router"
    assert "router relay inflight" in d.reason
    ctl.note_scaled("up", now=1.0)
    assert ctl.decide(hot, 1, now=2.0, n_routers=3) is None  # cooldown
    # at router-max: no decision even past cooldown + streak
    assert ctl.decide(hot, 1, now=12.0, n_routers=3) is None
    assert ctl.decide(hot, 1, now=13.0, n_routers=3) is None

    # clear below half the SLO for a full cooldown -> down; a blip
    # above half re-arms the clock
    ctl = rctl(cooldown_s=5.0)
    idle = FleetObservation(live=1, routers_live=2,
                            router_relay_inflight=0)
    warm = FleetObservation(live=1, routers_live=2,
                            router_relay_inflight=3)  # mean 1.5 > 1
    assert ctl.decide(idle, 1, now=0.0, n_routers=2) is None  # clear t0
    assert ctl.decide(warm, 1, now=2.0, n_routers=2) is None  # re-arm
    assert ctl.decide(idle, 1, now=3.0, n_routers=2) is None  # clear t0
    assert ctl.decide(idle, 1, now=6.0, n_routers=2) is None  # 3s < 5s
    d = ctl.decide(idle, 1, now=8.5, n_routers=2)
    assert d is not None and d.direction == "down"
    assert d.tier == "router"
    ctl.note_scaled("down", now=8.5)
    # never below router-min
    assert ctl.decide(idle, 1, now=60.0, n_routers=1) is None

    # floor rule: a router fleet below min relaunches without a breach
    ctl = rctl(router_min=2)
    d = ctl.decide(idle, 1, now=0.0, n_routers=1)
    assert d is not None and d.direction == "up" and d.tier == "router"
    assert "min" in d.reason

    # the SERVING law wins when both tiers breach: capacity goes where
    # the tokens are made
    ctl = rctl(queue_slo=4, max_replicas=3, breach_ticks=1)
    both = FleetObservation(live=1, queued=10, routers_live=1,
                            router_relay_inflight=10)
    d = ctl.decide(both, 1, now=0.0, n_routers=1)
    assert d is not None and d.direction == "up" and d.tier == ""

    # no router answered /stats: the law never actuates blind (but the
    # floor rule above still fires off the driver's own count)
    ctl = rctl(breach_ticks=1)
    blind = FleetObservation(live=1, routers_live=0,
                             router_relay_inflight=0)
    assert ctl.decide(blind, 1, now=0.0, n_routers=2) is None


def test_watcher_scrapes_router_endpoints(monkeypatch):
    """FleetWatcher scrapes each front door's /stats for
    relay_inflight (summed into the observation, kept per-door for
    victim picking) and, absent an explicit router_stats_url, derives
    the router-side queue estimate from their fleet views: per-door
    inflight SUMS (shared-nothing — each door counts only its own
    relays), the polled active view takes the MAX (every door polls
    the same replicas)."""
    import json as _json

    door_stats = {
        "router:0": {"relay_inflight": 3,
                     "fleet": {"inflight": 3, "active": 2}},
        "router:1": {"relay_inflight": 1,
                     "fleet": {"inflight": 1, "active": 2}},
    }
    watcher = FleetWatcher()

    def fake_get(url):
        if url == "http://agg:9/stats":
            return _json.dumps({"fleet": {"inflight": 9, "active": 2}})
        for name, port in (("router:0", 1), ("router:1", 2)):
            if url == f"http://d{port}:{port}/stats":
                return _json.dumps(door_stats[name])
        return None

    monkeypatch.setattr(watcher, "_get", fake_get)
    doors = [("router:0", "d1", 1), ("router:1", "d2", 2)]
    obs = watcher.observe([], router_endpoints=doors)
    assert obs.routers_live == 2
    assert obs.router_relay_inflight == 4
    assert watcher.last_router_loads == {"router:0": 3, "router:1": 1}
    # queue estimate: sum(inflight) - max(active) = 4 - 2
    assert obs.router_queued == 2
    # an explicit router_stats_url wins over the derived view
    obs = watcher.observe([], router_stats_url="http://agg:9/stats",
                          router_endpoints=doors)
    assert obs.router_queued == 7
    # a dead door contributes nothing and drops from the load map
    doors.append(("router:2", "dead", 3))
    obs = watcher.observe([], router_endpoints=doors)
    assert obs.routers_live == 2
    assert "router:2" not in watcher.last_router_loads


def test_router_tier_autoscale_e2e(tmp_job_dirs, tmp_path):
    """The tentpole's closed loop, end to end with REAL ``tony-tpu
    route`` front doors under a scripted provisioner: the role named
    ``router`` (framework "router" — auto-detected, no explicit
    tony.autoscale.router-role) starts with door 1 PARKED; saturating
    door 0 with live relays breaches the router law and unparks door 1
    (a second real route process, serving requests); a sustained clear
    scales the tier back down with an in-flight relay on the victim —
    which completes through the SIGTERM drain (exit 0, zero dropped),
    the slot parks, and the {tier="router"} metric series count both
    actuations."""
    import re as _re
    import signal as _signal
    import subprocess
    import sys
    import urllib.request

    from tests.test_router import StubReplica

    rep = StubReplica("backend")
    rep.delay_s = 1.2       # keeps relays in flight across a tick

    def script(spec, index, env, handle, attempt):
        rpc = _rpc_for(env)
        task_id = f"{spec.name}:{index}"
        if spec.name == "replica":
            payload = rpc.call("register_worker", task_id=task_id,
                               host="127.0.0.1", port=23500 + index,
                               attempt=int(env.get(c.ENV_TASK_ATTEMPT,
                                                   -1)))
            while payload is None:
                time.sleep(0.03)
                payload = rpc.call("get_cluster_spec", task_id=task_id)
            rpc.call("publish_ports", task_id=task_id,
                     ports={"serve_port": rep.port})
            handle.extra["stop"].wait(120)
            rpc.call("register_execution_result", task_id=task_id,
                     exit_code=0)
            rpc.close()
            return 0
        # router door: a REAL route process on an ephemeral port
        proc = subprocess.Popen(
            [sys.executable, "-m", "tony_tpu.cli.main", "route",
             "--port", "0", "--replica", f"127.0.0.1:{rep.port}",
             "--prefill-chunk", "4", "--health-interval-s", "0.2",
             "--drain-timeout-s", "20"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env={"PATH": env.get("PATH", "/usr/bin:/bin"),
                            "JAX_PLATFORMS": "cpu",
                            "PYTHONPATH": ":".join(sys.path)})
        port = None
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            m = _re.search(r"routing on http://[^:]+:(\d+)", line)
            if m:
                port = int(m.group(1))
                break
        if port is None:
            proc.kill()
            return 1
        payload = rpc.call("register_worker", task_id=task_id,
                           host="127.0.0.1", port=23600 + index,
                           attempt=int(env.get(c.ENV_TASK_ATTEMPT, -1)))
        while payload is None:
            time.sleep(0.03)
            payload = rpc.call("get_cluster_spec", task_id=task_id)
        rpc.call("publish_ports", task_id=task_id,
                 ports={"serve_port": port, "metrics_port": port})
        handle.extra["stop"].wait(120)
        # the drain contract: SIGTERM, in-flight relays finish, exit 0
        proc.send_signal(_signal.SIGTERM)
        try:
            code = proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            code = proc.wait(timeout=10)
        rpc.call("register_execution_result", task_id=task_id,
                 exit_code=code)
        rpc.close()
        return code

    driver = _driver(
        tmp_job_dirs, tmp_path, script, name="routertier",
        **{"tony.replica.instances": 1,
           "tony.replica.command": "stub",
           "tony.replica.max-restarts": 1,
           "tony.router.instances": 2,
           "tony.router.command": "stub",
           "tony.router.framework": "router",
           "tony.router.max-restarts": 1,
           "tony.application.framework": "serving",
           "tony.autoscale.enabled": True,
           "tony.autoscale.role": "replica",
           "tony.autoscale.min": 1,
           "tony.autoscale.router-relay-slo": 2,
           "tony.autoscale.router-min": 1,
           "tony.quota.pool-slots": 3})
    t = threading.Thread(target=driver.run, daemon=True)
    t.start()
    posts: list[threading.Thread] = []
    try:
        # the router role was auto-detected from its framework, and
        # door 1 started parked under the router floor
        assert driver._router_role == "router"
        _wait(lambda: driver.serving_endpoints("router")
              and driver.serving_endpoints("replica"),
              timeout=40, msg="door 0 + replica up")
        assert "router:1" in driver._parked
        door0 = driver.serving_endpoints("router")[0]

        def relay(port, out):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps({"prompt": [1, 2, 3, 4],
                                 "max_new_tokens": 1}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                out.append(json.loads(r.read().decode()))

        def inflight(port):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/stats", timeout=5) as r:
                return json.loads(r.read().decode())["relay_inflight"]

        clock = {"t": 1000.0}
        ctl = AutoscaleController(
            min_replicas=1, max_replicas=1, router_slo=2.0,
            router_min=1, router_max=2, cooldown_s=5.0,
            breach_ticks=2, now_fn=lambda: clock["t"])
        watcher = FleetWatcher()

        # saturate door 0: three live relays > SLO 2 per door
        got_up: list = []
        for _ in range(3):
            th = threading.Thread(target=relay, args=(door0[2], got_up))
            th.start()
            posts.append(th)
        _wait(lambda: inflight(door0[2]) == 3, timeout=10,
              msg="relays in flight")
        assert driver.autoscale_tick(ctl, watcher) == "idle"  # streak 1
        clock["t"] += 1
        assert driver.autoscale_tick(ctl, watcher) == "scaled_up"
        _wait(lambda: len(driver.serving_endpoints("router")) == 2,
              timeout=40, msg="door 1 serving")
        assert "router:1" not in driver._parked
        for th in posts:
            th.join(timeout=30)
        assert len(got_up) == 3
        assert all(r["finish_reason"] == "length" for r in got_up)
        # the fresh door really serves
        door1 = [e for e in driver.serving_endpoints("router")
                 if e[0] == "router:1"][0]
        got_d1: list = []
        relay(door1[2], got_d1)
        assert got_d1[0]["finish_reason"] == "length"

        # traffic ebbs to one relay per door (mean == half the SLO ->
        # clear): past the cooldown the tier scales DOWN, picking the
        # highest-index door on the load tie; its in-flight relay
        # finishes through the SIGTERM drain — zero dropped
        clock["t"] += 6
        got_down: list = []
        for _, _, port in driver.serving_endpoints("router"):
            th = threading.Thread(target=relay, args=(port, got_down))
            th.start()
            posts.append(th)
        _wait(lambda: inflight(door0[2]) == 1
              and inflight(door1[2]) == 1,
              timeout=10, msg="one relay per door")
        assert driver.autoscale_tick(ctl, watcher) == "idle"  # clear t0
        clock["t"] += 6
        assert driver.autoscale_tick(ctl, watcher) == "scaled_down"
        _wait(lambda: "router:1" in driver._parked, timeout=40,
              msg="door 1 drained + parked")
        for th in posts:
            th.join(timeout=30)
        assert len(got_down) == 2, "a relay was dropped on scale-down"
        assert all(r["finish_reason"] == "length" for r in got_down)
        assert len(driver.serving_endpoints("router")) == 1
        assert driver.arbiter.held("router") == 1

        text = driver.render_metrics()
        assert ('driver_autoscale_scale_ups_total{tier="router"} 1'
                in text)
        assert ('driver_autoscale_scale_downs_total{tier="router"} 1'
                in text)
        assert ('driver_autoscale_replicas{role="router",'
                'stat="current",tier="router"} 1' in text)
        assert "driver_task_restarts_total 0" in text
        state = load_state(Path(driver.job_dir) / c.DRIVER_JOURNAL_FILE)
        router_ops = [(op["dir"], op.get("tier"))
                      for op in state.scale_ops
                      if op["task"].startswith("router:")]
        assert router_ops == [("up", "router"), ("down", "router")]
        assert state.parked == {"router:1"}
    finally:
        driver._stop_requested.set()
        for h in list(driver._handles.values()):
            h.extra["stop"].set()
        t.join(timeout=30)
        rep.close()
