"""Parallelism library numerics on the 8-device CPU mesh: mesh building,
sharding rules, ring attention vs full attention, pipeline vs sequential,
MoE routing invariants."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tony_tpu.parallel import (
    MeshSpec,
    build_mesh,
    mesh_from_string,
    logical_to_spec,
    make_pipeline,
    make_ring_attention,
    moe_ffn,
    reference_attention,
    stack_stage_params,
    top_k_routing,
    load_balancing_loss,
    DP_RULES,
    FSDP_TP_RULES,
)


def test_mesh_spec_resolution():
    assert MeshSpec(fsdp=-1).resolve(8) == {
        "pipe": 1, "data": 1, "fsdp": 8, "seq": 1, "expert": 1, "tensor": 1}
    assert MeshSpec(data=2, fsdp=1, tensor=4).resolve(8)["tensor"] == 4
    with pytest.raises(ValueError, match="divisible"):
        MeshSpec(data=3, fsdp=-1).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(data=2, fsdp=2).resolve(8)  # product mismatch, no wildcard


def test_build_mesh_and_string():
    mesh = build_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
    assert dict(mesh.shape) == {
        "pipe": 1, "data": 2, "fsdp": 2, "seq": 1, "expert": 1, "tensor": 2}
    mesh2 = mesh_from_string("tensor=4")
    assert mesh2.shape["tensor"] == 4 and mesh2.shape["data"] == 2


def test_logical_to_spec():
    assert logical_to_spec(("batch", "seq", "embed"), DP_RULES) == P(("data", "fsdp"))
    spec = logical_to_spec(("embed", "mlp"), FSDP_TP_RULES)
    assert spec == P("fsdp", "tensor")


def test_sharded_matmul_end_to_end():
    """pjit a matmul with FSDP+TP rules; result must equal single-device."""
    mesh = build_mesh(MeshSpec(fsdp=2, tensor=4))
    x = jnp.arange(16 * 32, dtype=jnp.float32).reshape(16, 32) / 100
    w = jnp.ones((32, 64), jnp.float32) * 0.01
    from tony_tpu.parallel import sharding_for

    # activations: batch over (data, fsdp); embed stays unsharded (the
    # "embed" rule applies to params — re-using fsdp on an activation dim
    # would duplicate the axis)
    xs = jax.device_put(x, sharding_for(mesh, ("batch", None), FSDP_TP_RULES))
    ws = jax.device_put(w, sharding_for(mesh, ("embed", "mlp"), FSDP_TP_RULES))
    out = jax.jit(lambda a, b: a @ b)(xs, ws)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x @ w), rtol=1e-5)


# ----------------------------------------------------------- ring attention

@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_full(causal):
    mesh = build_mesh(MeshSpec(fsdp=1, seq=4, tensor=1, data=2))
    key = jax.random.PRNGKey(0)
    b, l, h, d = 2, 32, 4, 8  # l sharded 4-ways -> 8 per device
    q, k, v = (
        jax.random.normal(kk, (b, l, h, d), jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    ring = make_ring_attention(mesh, causal=causal)
    spec = P(None, "seq", None, None)
    qs, ks, vs = (
        jax.device_put(a, jax.sharding.NamedSharding(mesh, spec)) for a in (q, k, v)
    )
    out = jax.jit(ring)(qs, ks, vs)
    expected = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_ring_attention_gradients_flow():
    mesh = build_mesh(MeshSpec(fsdp=1, seq=8))
    ring = make_ring_attention(mesh, causal=True)
    q = jax.random.normal(jax.random.PRNGKey(1), (1, 16, 2, 4))

    def loss_ring(q):
        return jnp.sum(ring(q, q, q) ** 2)

    def loss_ref(q):
        return jnp.sum(reference_attention(q, q, q, causal=True) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring))(q)
    g_ref = jax.grad(loss_ref)(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref), atol=1e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_attention_matches_full(causal):
    """Flash-kernel ring path (Pallas interpret on CPU): per-step kernel
    results merged by lse must equal full attention."""
    mesh = build_mesh(MeshSpec(fsdp=1, seq=8))
    key = jax.random.PRNGKey(3)
    b, l, h, d = 1, 256, 2, 128  # 32 rows/device, padded to one kernel block
    q, k, v = (
        jax.random.normal(kk, (b, l, h, d), jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    ring = make_ring_attention(mesh, causal=causal, impl="flash")
    spec = P(None, "seq", None, None)
    qs, ks, vs = (
        jax.device_put(a, jax.sharding.NamedSharding(mesh, spec)) for a in (q, k, v)
    )
    out = jax.jit(ring)(qs, ks, vs)
    expected = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-4)


def test_ring_flash_attention_gradients_flow():
    """Gradients through scan + ppermute + lse-merged flash partials must
    match full-attention gradients (exercises the lse cotangent path of
    flash_attention_with_lse)."""
    mesh = build_mesh(MeshSpec(fsdp=1, seq=8))
    ring = make_ring_attention(mesh, causal=True, impl="flash")
    q = jax.random.normal(jax.random.PRNGKey(5), (1, 128, 1, 128))

    def loss_ring(q):
        return jnp.sum(ring(q, q, q) ** 2)

    def loss_ref(q):
        return jnp.sum(reference_attention(q, q, q, causal=True) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring))(q)
    g_ref = jax.grad(loss_ref)(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref), atol=1e-3)


def test_ring_auto_impl_dispatch(monkeypatch):
    """impl=None: off-TPU auto keeps the XLA path (the flash kernel would run
    in the slow Pallas interpreter) yet stays numerically correct; bogus impl
    strings are rejected instead of silently falling back."""
    import sys
    import tony_tpu.parallel.ring_attention  # noqa: F401 (function shadows module attr)
    ra = sys.modules["tony_tpu.parallel.ring_attention"]

    mesh = build_mesh(MeshSpec(fsdp=1, seq=8))
    key = jax.random.PRNGKey(9)
    q, k, v = (
        jax.random.normal(kk, (1, 128, 1, 128), jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    # prove auto off-TPU never enters the flash ring (numerics alone can't
    # distinguish the two paths)
    def _boom(*a, **kw):
        raise AssertionError("auto dispatch chose flash off-TPU")

    monkeypatch.setattr(ra, "ring_flash_attention", _boom)
    auto = jax.jit(make_ring_attention(mesh, causal=True))(q, k, v)
    expected = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(auto), np.asarray(expected), atol=2e-4)
    with pytest.raises(ValueError, match="impl"):
        make_ring_attention(mesh, impl="Flash")


# ------------------------------------------------------------- hybrid mesh

def test_hybrid_mesh_slice_locality():
    """2 virtual slices of 4: data spans DCN (slices), fsdp/tensor stay ICI
    (within one slice) — every ICI group must draw from a single slice."""
    from tony_tpu.parallel import build_hybrid_mesh

    devs = jax.devices()
    mesh = build_hybrid_mesh(
        ici=MeshSpec(fsdp=2, tensor=2), dcn=MeshSpec(data=2, fsdp=1),
        devices=devs, num_slices=2,
    )
    assert dict(mesh.shape)["data"] == 2
    assert dict(mesh.shape)["fsdp"] == 2 and dict(mesh.shape)["tensor"] == 2
    arr = mesh.devices  # [pipe, data, fsdp, seq, expert, tensor]
    slice_of = {d.id: (0 if d.id < 4 else 1) for d in devs}
    for data_idx in range(2):
        ids = {slice_of[d.id] for d in arr[0, data_idx].flat}
        assert len(ids) == 1, f"ICI group for data={data_idx} spans slices"


def test_hybrid_mesh_single_slice_degenerates_and_validates():
    from tony_tpu.parallel import build_hybrid_mesh

    mesh = build_hybrid_mesh(ici=MeshSpec(fsdp=2, tensor=4), num_slices=1)
    assert dict(mesh.shape)["fsdp"] == 2
    with pytest.raises(ValueError, match="both DCN and ICI"):
        build_hybrid_mesh(
            ici=MeshSpec(data=2, fsdp=2, tensor=1),
            dcn=MeshSpec(data=2, fsdp=1), num_slices=2,
        )


def test_hybrid_mesh_trains():
    """A real sharded train step over the hybrid mesh: dp over DCN axis,
    fsdp+tp within slices."""
    from tony_tpu.models import transformer
    from tony_tpu.parallel import build_hybrid_mesh
    from tony_tpu.train import create_train_step, synthetic_lm_batch

    mesh = build_hybrid_mesh(
        ici=MeshSpec(fsdp=2, tensor=2), dcn=MeshSpec(data=2, fsdp=1),
        num_slices=2,
    )
    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=64, max_seq_len=16, dtype=jnp.float32, attn_impl="ref",
    )
    bundle = create_train_step(cfg, mesh, rules=FSDP_TP_RULES)
    tokens, targets = synthetic_lm_batch(jax.random.PRNGKey(0), 8, 16, 64)
    _, _, metrics = bundle.step_fn(bundle.params, bundle.opt_state, tokens, targets)
    assert jnp.isfinite(metrics["loss"])


# -------------------------------------------------------- ulysses attention

@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_full(causal):
    from tony_tpu.parallel import make_ulysses_attention

    mesh = build_mesh(MeshSpec(fsdp=1, seq=4, tensor=1, data=2))
    key = jax.random.PRNGKey(0)
    b, l, h, d = 2, 32, 4, 8  # l and h both divisible by seq=4
    q, k, v = (
        jax.random.normal(kk, (b, l, h, d), jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    uly = make_ulysses_attention(mesh, causal=causal)
    spec = P(None, "seq", None, None)
    qs, ks, vs = (
        jax.device_put(a, jax.sharding.NamedSharding(mesh, spec)) for a in (q, k, v)
    )
    out = jax.jit(uly)(qs, ks, vs)
    expected = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_ulysses_with_flash_local_kernel_matches_full():
    """Ulysses with the Pallas flash kernel as the local attention
    (interpret mode here; what TPU jobs run via attn_fn auto-dispatch or
    TransformerConfig.sp_kernel='flash') must match full attention."""
    import functools

    from tony_tpu.ops.attention import attention_blhd
    from tony_tpu.parallel import make_ulysses_attention

    mesh = build_mesh(MeshSpec(fsdp=1, seq=4, tensor=1, data=2))
    key = jax.random.PRNGKey(1)
    b, l, h, d = 2, 64, 4, 16
    q, k, v = (
        jax.random.normal(kk, (b, l, h, d), jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    uly = make_ulysses_attention(
        mesh, causal=True,
        attn_fn=functools.partial(attention_blhd, causal=True),
    )
    spec = P(None, "seq", None, None)
    qs, ks, vs = (
        jax.device_put(a, jax.sharding.NamedSharding(mesh, spec))
        for a in (q, k, v)
    )
    out = jax.jit(uly)(qs, ks, vs)
    expected = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=2e-5)


@pytest.mark.slow
def test_ring_flash_remat_attn_composition_trains():
    """ring SP x forced flash kernel x remat_policy='attn' (the named
    residuals now live inside a scanned shard_map) must compile and
    produce a finite training step — the combination a long-context
    multi-host job actually runs."""
    from tony_tpu.models import transformer
    from tony_tpu.parallel import DP_RULES
    from tony_tpu.train import create_train_step, synthetic_lm_batch

    cfg = transformer.TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=64, max_seq_len=32, dtype=jnp.float32, remat=True,
        remat_policy="attn", sp_kernel="flash",
    )
    mesh = build_mesh(MeshSpec(data=2, fsdp=1, seq=4))
    bundle = create_train_step(cfg, mesh, rules=dict(DP_RULES),
                               sp_impl="ring")
    tokens, targets = synthetic_lm_batch(jax.random.PRNGKey(0), 4, 32, 128)
    tokens = jax.device_put(tokens, bundle.tok_sharding)
    targets = jax.device_put(targets, bundle.tok_sharding)
    _, _, m = bundle.step_fn(bundle.params, bundle.opt_state, tokens,
                             targets)
    assert np.isfinite(float(m["loss"]))


def test_ulysses_attention_gradients_flow():
    from tony_tpu.parallel import make_ulysses_attention

    mesh = build_mesh(MeshSpec(data=4, fsdp=1, seq=2))
    uly = make_ulysses_attention(mesh, causal=True)
    q = jax.random.normal(jax.random.PRNGKey(1), (1, 16, 2, 4))

    def loss_uly(q):
        return jnp.sum(uly(q, q, q) ** 2)

    def loss_ref(q):
        return jnp.sum(reference_attention(q, q, q, causal=True) ** 2)

    g_uly = jax.jit(jax.grad(loss_uly))(q)
    g_ref = jax.grad(loss_ref)(q)
    np.testing.assert_allclose(np.asarray(g_uly), np.asarray(g_ref), atol=1e-4)


def test_ulysses_rejects_indivisible_heads():
    from tony_tpu.parallel import make_ulysses_attention

    mesh = build_mesh(MeshSpec(fsdp=1, seq=8))
    uly = make_ulysses_attention(mesh, causal=True)
    q = jnp.zeros((1, 16, 2, 4))  # 2 heads, seq axis 8
    with pytest.raises(ValueError, match="divisible"):
        jax.jit(uly)(q, q, q)


# ---------------------------------------------------------------- pipeline

def test_pipeline_matches_sequential():
    mesh = build_mesh(MeshSpec(pipe=4, fsdp=2))
    n_stages, d = 4, 16

    def stage_fn(params, x):
        return jnp.tanh(x @ params["w"] + params["b"])

    key = jax.random.PRNGKey(0)
    per_stage = []
    for i in range(n_stages):
        k1, k2, key = jax.random.split(key, 3)
        per_stage.append({
            "w": jax.random.normal(k1, (d, d)) * 0.3,
            "b": jax.random.normal(k2, (d,)) * 0.1,
        })
    stacked = stack_stage_params(per_stage)
    batch = jax.random.normal(key, (8, d))

    pipeline = make_pipeline(mesh, stage_fn, num_microbatches=4)
    out = jax.jit(pipeline)(stacked, batch)

    expected = batch
    for p in per_stage:
        expected = stage_fn(p, expected)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=1e-5)


def test_pipeline_single_stage_degenerates():
    mesh = build_mesh(MeshSpec(pipe=1, fsdp=8))
    stage_fn = lambda p, x: x * p["s"]
    stacked = {"s": jnp.full((1,), 3.0)}
    pipeline = make_pipeline(mesh, stage_fn, num_microbatches=2)
    out = pipeline(stacked, jnp.ones((4, 2)))
    np.testing.assert_allclose(np.asarray(out), 3.0 * np.ones((4, 2)))


@pytest.mark.parametrize("M", [4, 8])
def test_pipeline_circular_matches_sequential(M):
    """Circular/interleaved schedule (V chunks per device): forward equals
    the sequential stack, and gradients flow (autodiff through the
    interleaved routing). M=8 > S=4 pins the dense-injection regime where
    deferred wrap-priority injections interleave with wrap arrivals —
    exactly what M == S never exercises (round-2 advisor finding)."""
    from tony_tpu.parallel.pipeline import make_pipeline_circular

    mesh = build_mesh(MeshSpec(pipe=4, fsdp=2))
    S, V, per_chunk, d = 4, 2, 1, 16
    n_layers = S * V * per_chunk

    def stage_fn(chunk_stack, x):
        def body(c, lp):
            return jnp.tanh(c @ lp["w"] + lp["b"]), None

        y, _ = jax.lax.scan(body, x, chunk_stack)
        return y

    key = jax.random.PRNGKey(3)
    ks = jax.random.split(key, 2 * n_layers + 1)
    stacked = {
        "w": jnp.stack([jax.random.normal(ks[i], (d, d)) * 0.3
                        for i in range(n_layers)]),
        "b": jnp.stack([jax.random.normal(ks[n_layers + i], (d,)) * 0.1
                        for i in range(n_layers)]),
    }
    batch = jax.random.normal(ks[-1], (3 * M, d))  # mb size 3 over M

    pipeline = make_pipeline_circular(
        mesh, stage_fn, num_microbatches=M, num_chunks=V
    )
    out = jax.jit(pipeline)(stacked, batch)

    expected = batch
    for i in range(n_layers):
        expected = jnp.tanh(expected @ stacked["w"][i] + stacked["b"][i])
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=1e-5)

    # gradients flow to every layer through the interleaved routing
    g = jax.grad(lambda p: jnp.sum(jax.jit(pipeline)(p, batch) ** 2))(stacked)
    for leaf in jax.tree.leaves(g):
        per_layer = np.abs(np.asarray(leaf)).reshape(n_layers, -1).max(axis=1)
        assert (per_layer > 0).all(), per_layer


@pytest.mark.slow
def test_pipeline_1f1b_loss_and_grads_match_autodiff():
    """The manually scheduled 1F1B backward must produce the same loss and
    gradients (stage params, head params, batch input) as autodiff of the
    equivalent sequential model."""
    from tony_tpu.parallel import make_pipeline_1f1b

    mesh = build_mesh(MeshSpec(pipe=4, fsdp=2))
    n_stages, d, M = 4, 16, 8

    def stage_fn(local_stack, x):
        # local_stack leaves keep the (sharded) layer dim, like the
        # transformer's stacked layers — scan this stage's run
        def body(carry, lp):
            y = jnp.tanh(carry @ lp["w"] + lp["b"])
            return y, jnp.sum(y * y)  # nontrivial aux path

        y, auxes = jax.lax.scan(body, x, local_stack)
        return y, jnp.sum(auxes).astype(jnp.float32)

    def head_fn(hp, y, tgt):
        return jnp.mean((y @ hp["wo"] - tgt) ** 2)

    key = jax.random.PRNGKey(1)
    ks = jax.random.split(key, 2 * n_stages + 3)
    stacked = {
        "w": jnp.stack([jax.random.normal(ks[i], (d, d)) * 0.3
                        for i in range(n_stages)]),
        "b": jnp.stack([jax.random.normal(ks[n_stages + i], (d,)) * 0.1
                        for i in range(n_stages)]),
    }
    hp = {"wo": jax.random.normal(ks[-3], (d, d)) * 0.2}
    batch = jax.random.normal(ks[-2], (16, d))
    targets = jax.random.normal(ks[-1], (16, d))
    aux_w = 0.01

    pipeline = make_pipeline_1f1b(
        mesh, stage_fn, head_fn, num_microbatches=M, aux_weight=aux_w
    )
    loss, dstacked, dhead, dx = jax.jit(pipeline)(stacked, hp, batch, targets)

    def ref_loss(stacked, hp, batch, targets):
        micro = batch.reshape(M, -1, d)
        micro_t = targets.reshape(M, -1, d)
        total = 0.0
        for m in range(M):
            x = micro[m]
            aux_sum = 0.0
            for s in range(n_stages):
                p = {"w": stacked["w"][s:s + 1], "b": stacked["b"][s:s + 1]}
                x, aux = stage_fn(p, x)
                aux_sum = aux_sum + aux
            total = total + head_fn(hp, x, micro_t[m]) + aux_w * aux_sum
        return total / M

    ref = jax.value_and_grad(ref_loss, argnums=(0, 1, 2))
    ref_l, (ref_ds, ref_dh, ref_dx) = ref(stacked, hp, batch, targets)

    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5
        ),
        dstacked, ref_ds,
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5
        ),
        dhead, ref_dh,
    )
    np.testing.assert_allclose(np.asarray(dx), np.asarray(ref_dx), atol=2e-5)


# --------------------------------------------------------------------- moe

def test_top_k_routing_invariants():
    t, e, cap, k = 16, 4, 8, 2
    logits = jax.random.normal(jax.random.PRNGKey(0), (t, e))
    dispatch, combine = top_k_routing(logits, k=k, capacity=cap)
    d = np.asarray(dispatch)
    # each (expert, capacity) slot used at most once
    assert d.sum(axis=0).max() <= 1.0 + 1e-6
    # each token dispatched at most k times
    assert d.sum(axis=(1, 2)).max() <= k + 1e-6
    # combine weights only where dispatched, and per-token total <= 1
    c = np.asarray(combine)
    assert ((c > 0) <= (d > 0)).all()
    assert c.sum(axis=(1, 2)).max() <= 1.0 + 1e-5


def test_moe_ffn_runs_and_large_capacity_keeps_all_tokens():
    t, d_model, d_ff, e = 32, 8, 16, 4
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (t, d_model))
    router_w = jax.random.normal(jax.random.PRNGKey(1), (d_model, e)) * 0.1
    w_in = jax.random.normal(jax.random.PRNGKey(2), (e, d_model, d_ff)) * 0.1
    w_out = jax.random.normal(jax.random.PRNGKey(3), (e, d_ff, d_model)) * 0.1
    out = moe_ffn(x, router_w, w_in, w_out, k=2, capacity_factor=4.0)
    assert out.shape == (t, d_model)
    assert not np.isnan(np.asarray(out)).any()
    # with huge capacity, every token keeps full combine weight ~1
    logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)
    dispatch, combine = top_k_routing(logits, k=2, capacity=t * 2)
    np.testing.assert_allclose(np.asarray(combine).sum(axis=(1, 2)), 1.0, atol=1e-5)


def test_load_balancing_loss_uniform_is_one():
    t, e = 64, 8
    logits = jnp.zeros((t, e))
    # uniform router: loss == 1 by construction... top_k ties break by index,
    # so token fraction is concentrated; just check finiteness and scale
    loss = load_balancing_loss(logits, k=2)
    assert np.isfinite(float(loss))


def test_moe_expert_sharded_matches_unsharded():
    mesh = build_mesh(MeshSpec(fsdp=2, expert=4))
    t, d_model, d_ff, e = 32, 8, 16, 4
    x = jax.random.normal(jax.random.PRNGKey(0), (t, d_model))
    router_w = jax.random.normal(jax.random.PRNGKey(1), (d_model, e)) * 0.1
    w_in = jax.random.normal(jax.random.PRNGKey(2), (e, d_model, d_ff)) * 0.1
    w_out = jax.random.normal(jax.random.PRNGKey(3), (e, d_ff, d_model)) * 0.1
    expected = moe_ffn(x, router_w, w_in, w_out, k=2, capacity_factor=4.0)

    exp_sharding = jax.sharding.NamedSharding(mesh, P("expert"))
    w_in_s = jax.device_put(w_in, exp_sharding)
    w_out_s = jax.device_put(w_out, exp_sharding)
    out = jax.jit(
        lambda *a: moe_ffn(*a, k=2, capacity_factor=4.0)
    )(x, router_w, w_in_s, w_out_s)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=1e-5)


def test_moe_capacity_no_float_truncation():
    """capacity_factor = e/k must guarantee capacity >= tokens (drop-free
    decode contract): (4/3)*21/4 floats to 6.999..., int() must not drop."""
    from tony_tpu.parallel.expert import top_k_routing

    t, k, e = 7, 3, 4
    cf = e / k
    cap = max(1, int(cf * t * k / e + 1e-6))
    assert cap >= t
    # end-to-end: no token loses all its routing weight at that capacity
    logits = jnp.zeros((t, e))  # ties: all tokens pick the same experts
    dispatch, combine = top_k_routing(logits, k, cap)
    kept = np.asarray(combine.sum(axis=(1, 2)))
    assert (kept > 0).all()
