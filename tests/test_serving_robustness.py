"""Fault tolerance of the serving path (models/serving.py + cli/serve.py).

The contract under test is the failure model (docs/serving.md "Failure
model"): every submitted request terminates with a completion, a shed
(QueueFullError / HTTP 429), or an explicit error — never a hang — through
deadlines, cancellation, bounded admission, loop recovery (SlotServer.
reset() + the ServeApp restart budget), graceful drain, and seeded chaos
injection. This is the serving-side analogue of the driver's liveness
discipline (heartbeat expiry, per-task restarts, whole-job retry — the
reference's core value proposition, SURVEY §5): the slot pool gets the
same "failure is an input, not an exception" treatment.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.cli.serve import ServeApp, ServingLoopError
from tony_tpu.models import transformer
from tony_tpu.models.generate import generate
from tony_tpu.models.serving import (
    Completion, QueueFullError, Request, SlotServer,
)

TINY = transformer.TransformerConfig(
    vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=128, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return transformer.init(jax.random.PRNGKey(0), TINY)


def _prompts(n, key=3, lo=2, hi=14):
    k = jax.random.PRNGKey(key)
    out = []
    for i in range(n):
        k, a, b = jax.random.split(k, 3)
        lp = int(jax.random.randint(a, (), lo, hi))
        out.append(np.asarray(
            jax.random.randint(b, (lp,), 0, TINY.vocab_size), np.int32))
    return out


def _solo(params, prompt, max_new, **kw):
    out = generate(params, TINY, jnp.asarray(prompt)[None], max_new, **kw)
    return [int(t) for t in np.asarray(out)[0]]


def _srv(params, **kw):
    """Same shapes as tests/test_serving.py, so the tier-1 run reuses the
    already-compiled programs."""
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_chunk", 8)
    return SlotServer(params, TINY, **kw)


# --------------------------------------------------------------------------
# deadlines + cancellation (SlotServer level)
# --------------------------------------------------------------------------

def test_cancel_queued_and_unknown(params):
    """A queued request cancels without ever taking a slot; an unknown id
    reports False instead of guessing."""
    pa, pb = _prompts(2, key=211)
    srv = _srv(params)
    a = Request(prompt=pa, max_new_tokens=5)
    b = Request(prompt=pb, max_new_tokens=5)
    srv.submit(a)
    srv.submit(b)
    assert srv.cancel(b.id) is True
    assert srv.cancel(987654321) is False
    done = srv.run_until_drained()
    assert done[b.id].finish_reason == "cancelled"
    assert done[b.id].tokens == []
    assert done[a.id].tokens == _solo(params, pa, 5)
    assert srv.stats()["cancelled"] == 1


@pytest.mark.slow
def test_cancel_mid_decode_frees_slot_token_identical(params):
    """THE cancellation contract: cancelling a mid-decode request frees
    its slot mid-flight, its partial tokens are an exact PREFIX of its
    solo greedy stream (the blocks already dispatched were real work),
    and the next request admitted into the freed slot is token-identical
    to a fresh server — cancellation is scheduling, never numerics.
    Slow-marked (~11s: budget-30 decodes + their solo references); the
    tier-1 gate keeps the cheaper cancellation-parity guards
    (test_cancel_releases_prefix_cache_refs, the queued/EOS variants and
    the replay regression)."""
    pa, pc, pb = _prompts(3, key=223)
    srv = _srv(params)
    a = Request(prompt=pa, max_new_tokens=30)
    c = Request(prompt=pc, max_new_tokens=30)   # keeps the OTHER slot busy
    srv.submit(a)
    srv.submit(c)
    for _ in range(3):
        srv.step()                              # both mid-decode
    assert srv.n_active == 2
    assert srv.cancel(a.id) is True
    b = Request(prompt=pb, max_new_tokens=6)
    srv.submit(b)                               # must land in a's slot
    done = srv.run_until_drained()
    assert done[a.id].finish_reason == "cancelled"
    got = done[a.id].tokens
    assert 0 < len(got) < 30, "cancel must stop the decode early"
    assert got == _solo(params, pa, 30)[:len(got)], (
        "cancelled request's partial tokens diverged from its solo stream")
    assert done[b.id].tokens == _solo(params, pb, 6), (
        "request admitted into a cancelled slot diverged")
    assert done[c.id].tokens == _solo(params, pc, 30), (
        "cancellation disturbed an unrelated decoding slot")


def test_cancel_releases_prefix_cache_refs(params):
    """A cancelled request must unpin its matched prefix-cache path
    (otherwise its blocks are unevictable forever), and the freed slot's
    next templated request stays token-identical through the cache."""
    template = np.asarray(
        jax.random.randint(jax.random.PRNGKey(227), (16,), 0,
                           TINY.vocab_size), np.int32)      # 2 full chunks
    sfx = _prompts(3, key=229, lo=2, hi=6)
    srv = _srv(params, prefix_cache_blocks=8)
    warm = Request(prompt=np.concatenate([template, sfx[0]]),
                   max_new_tokens=4)
    srv.submit(warm)
    srv.run_until_drained()                     # trie now holds the template
    a = Request(prompt=np.concatenate([template, sfx[1]]),
                max_new_tokens=30)
    srv.submit(a)
    srv.step()
    assert a.id in srv._prefix_refs, "hit path should be ref-pinned"
    assert srv.cancel(a.id) is True
    srv.run_until_drained()
    assert not srv._prefix_refs, "cancel must release the pinned path"
    assert all(n.refs == 0 for n in srv._prefix_cache._owned)
    prompt_b = np.concatenate([template, sfx[2]])
    b = Request(prompt=prompt_b, max_new_tokens=5)
    srv.submit(b)
    done = srv.run_until_drained()
    assert done[b.id].tokens == _solo(params, prompt_b, 5)


def test_idle_accounts_for_undrained_completions(params):
    """A completion sitting undrained keeps the server non-idle — a
    serving loop that gates its drain on `not idle` must keep turning
    until waiters get their results (the hang window: a reset() that
    preserves finished work while emptying everything else)."""
    srv = _srv(params)
    a = Request(prompt=_prompts(1, key=271)[0], max_new_tokens=4)
    srv.submit(a)
    assert not srv.idle
    srv.cancel(a.id)    # completion lands straight in _done; queue empty
    assert not srv.idle, "undrained completion must keep the server busy"
    assert srv.drain_completed()[a.id].finish_reason == "cancelled"
    assert srv.idle


def test_readmitted_slot_stays_busy_through_late_replay(params):
    """Replay-order regression: a parked completion (here an expired
    sweep) lets drain_completed return WITHOUT syncing the pipeline, so
    a slot can be re-admitted while its predecessor's completion record
    is still unprocessed. When that record finally replays it clears
    _host_busy — _apply_admit must re-arm it at the replay position, or
    the server reads idle while the successor still decodes on device
    and its waiter hangs."""
    pa = np.arange(5, dtype=np.int32) + 3
    srv = _srv(params)
    a = Request(prompt=pa, max_new_tokens=4)        # finishes fast
    c = Request(prompt=pa + 1, max_new_tokens=40)   # keeps slot 1 busy
    e = Request(prompt=pa + 2, max_new_tokens=4,
                deadline=time.monotonic() - 1)      # parks in _done
    b = Request(prompt=pa + 3, max_new_tokens=20)   # re-admits a's slot
    srv.submit(a)
    srv.submit(c)
    for _ in range(3):
        srv.step()              # a's whole budget is dispatched
    srv.submit(e)
    srv.submit(b)
    got = {}
    for _ in range(60):
        srv.step()
        got.update(srv.drain_completed())
        if a.id in got and b.id not in got:
            # a's (late-replayed) completion has landed while b — re-
            # admitted into a's slot — is unfinished: b must still be
            # accounted busy
            assert srv._host_busy.any(), (
                "re-admitted slot lost its busy flag to the predecessor's"
                " late-processed completion")
        if srv.idle:
            break
    assert sorted(got) == sorted([a.id, b.id, c.id, e.id]), (
        "a request was stranded by the replay")
    assert got[e.id].finish_reason == "expired"
    assert got[b.id].tokens == _solo(params, pa + 3, 20)


@pytest.mark.slow
def test_cancel_mid_decode_eos_mode(params):
    """Cancellation composes with EOS mode, where the host's view lags
    the device by the pipeline depth: the cancel still replays at its
    event-log position, frees the slot, and the next occupant matches
    solo generate(). Slow-marked: the fresh stop-token value compiles
    new decode/generate variants (~6s) and the predictive-mode cancel
    contract is covered in the tier-1 gate."""
    prompts = _prompts(3, key=269)
    solo = [_solo(params, p, 10) for p in prompts]
    # a stop token that never fires naturally, so budgets are exact
    stop = next(t for t in range(TINY.vocab_size)
                if all(t not in s for s in solo))
    srv = _srv(params, stop_tokens=(stop,), pad_id=255)
    a = Request(prompt=prompts[0], max_new_tokens=10)
    c = Request(prompt=prompts[1], max_new_tokens=10)
    srv.submit(a)
    srv.submit(c)
    for _ in range(2):
        srv.step()
    assert srv.cancel(a.id) is True
    b = Request(prompt=prompts[2], max_new_tokens=10)
    srv.submit(b)
    done = srv.run_until_drained()
    assert done[a.id].finish_reason == "cancelled"
    assert done[a.id].tokens == solo[0][:len(done[a.id].tokens)]
    assert done[c.id].tokens == solo[1]
    assert done[b.id].tokens == solo[2]


def test_expired_queued_request_never_admitted(params):
    """A request whose deadline passed while queued completes as
    "expired" without ever taking a slot or burning prefill."""
    pa, pb = _prompts(2, key=233)
    srv = _srv(params)
    a = Request(prompt=pa, max_new_tokens=5)
    b = Request(prompt=pb, max_new_tokens=5,
                deadline=time.monotonic() - 1.0)
    srv.submit(a)
    srv.submit(b)
    done = srv.run_until_drained()
    assert done[b.id].finish_reason == "expired"
    assert done[b.id].tokens == []
    assert done[a.id].tokens == _solo(params, pa, 5)
    assert srv.expired_requests == 1 and srv.stats()["expired"] == 1


# --------------------------------------------------------------------------
# bounded admission + reset (SlotServer level)
# --------------------------------------------------------------------------

def test_submit_sheds_when_queue_full(params):
    prompts = _prompts(3, key=239)
    srv = _srv(params, max_queue=2)
    reqs = [Request(prompt=p, max_new_tokens=4) for p in prompts]
    srv.submit(reqs[0])
    srv.submit(reqs[1])
    with pytest.raises(QueueFullError):
        srv.submit(reqs[2])
    assert srv.shed_requests == 1 and srv.stats()["shed"] == 1
    done = srv.run_until_drained()
    assert set(done) == {reqs[0].id, reqs[1].id}
    for r, p in zip(reqs[:2], prompts[:2]):
        assert done[r.id].tokens == _solo(params, p, 4)


def test_reset_replays_inflight_keeps_queue(params):
    """reset() = loop recovery's engine half, journal ON (the default):
    admitted requests are REPLAYED (re-queued ahead of the never-started
    queue with their journaled prompt + emitted prefix), QUEUED requests
    survive untouched, and the re-armed ring serves everything
    token-identical to an uninterrupted server — without rebuilding the
    SlotServer or reloading weights. Zero lost requests."""
    pa, pc, pb = _prompts(3, key=241)
    srv = _srv(params)
    a = Request(prompt=pa, max_new_tokens=20)
    c = Request(prompt=pc, max_new_tokens=20)
    srv.submit(a)
    srv.submit(c)
    for _ in range(2):
        srv.step()                              # both slots mid-decode
    b = Request(prompt=pb, max_new_tokens=6)
    srv.submit(b)                               # still queued (slots full)
    tracker = srv.dispatch_tracker
    reaper = tracker._thread
    pre_reset_seqs = list(range(1, tracker.tracked_total + 1))
    lost = srv.reset()
    assert lost == [], "journaled in-flight requests must replay, not fail"
    # replays queue AHEAD of the never-started request
    assert srv.pending == 3 and srv.n_active == 0
    assert [r.id for r in srv._queue] == [a.id, c.id, b.id]
    assert srv.resets == 1
    # reset() drained + re-armed the dispatch reaper: SAME thread (no
    # leak per reset), nothing pending, and no stale ready-instant from
    # a pre-reset dispatch can be read against post-reset dispatches
    assert tracker._thread is reaper and tracker.alive
    assert all(tracker.ready_time(s) is None for s in pre_reset_seqs)
    done = srv.run_until_drained()
    assert set(done) == {a.id, b.id, c.id}
    for req, p, budget in ((a, pa, 20), (c, pc, 20), (b, pb, 6)):
        assert done[req.id].tokens == _solo(params, p, budget), (
            "post-reset replay diverged from an uninterrupted server")
    assert srv.replays == 2 and srv.stats()["replays"] == 2
    assert tracker.drain(timeout=10), "post-reset dispatches must reap"
    assert tracker.snapshot()["dispatch_ready"]["decode_block"]["count"] > 0
    srv.shutdown()                              # stops the reaper thread
    assert not tracker.alive
    reaper.join(timeout=5)
    assert not reaper.is_alive(), "shutdown() leaked the reaper thread"


def test_reset_replay_off_fails_inflight_keeps_queue(params):
    """replay=False preserves the pre-journal fail-fast contract:
    admitted requests are lost (returned so the caller can fail them),
    queued requests survive, and the re-armed ring serves them
    token-identical to a fresh server."""
    pa, pc, pb = _prompts(3, key=241)
    srv = _srv(params, replay=False)
    a = Request(prompt=pa, max_new_tokens=20)
    c = Request(prompt=pc, max_new_tokens=20)
    srv.submit(a)
    srv.submit(c)
    for _ in range(2):
        srv.step()                              # both slots mid-decode
    b = Request(prompt=pb, max_new_tokens=6)
    srv.submit(b)                               # still queued (slots full)
    lost = srv.reset()
    assert sorted(lost) == sorted([a.id, c.id])
    assert srv.pending == 1 and srv.n_active == 0
    assert srv.resets == 1 and srv.replays == 0
    done = srv.run_until_drained()
    assert set(done) == {b.id}
    assert done[b.id].tokens == _solo(params, pb, 6), (
        "post-reset ring diverged from a fresh server")
    srv.shutdown()


# --------------------------------------------------------------------------
# request durability + replay (docs/serving.md "Request durability & replay")
# --------------------------------------------------------------------------

def test_reset_replay_resumes_from_emitted_prefix(params):
    """THE replay contract: a request interrupted mid-decode with tokens
    already processed replays via teacher-forced re-prefill of
    prompt+emitted and resumes decoding — the delivered completion is
    byte-identical to an uninterrupted run, the trace carries the
    'replayed' mark, and the recompute is bounded: the known prefix
    re-PREFILLS (one admission), only the continuation re-decodes."""
    pa = _prompts(1, key=311)[0]
    srv = _srv(params)
    a = Request(prompt=pa, max_new_tokens=20)
    srv.submit(a)
    for _ in range(3):
        srv.step()
    srv.drain_completed()       # processes the pipeline: prefix is known
    prefix = list(srv._journal.get(a.id).emitted)
    assert 0 < len(prefix) < 20, "setup: need a partial emitted prefix"
    blocks_before = srv.blocks_dispatched
    assert srv.reset() == []
    done = srv.run_until_drained()
    ref = _solo(params, pa, 20)
    assert done[a.id].tokens == ref, "replay diverged from solo stream"
    assert done[a.id].tokens[:len(prefix)] == prefix
    assert srv.replays == 1 and srv.replayed_tokens == len(prefix)
    spans = [s for s, _ in
             [(n, t) for n, t in done[a.id].trace["spans"]]]
    assert "replayed" in spans and spans[-1] == "finished"
    assert done[a.id].trace["attrs"]["replayed_tokens"] == len(prefix)
    # replay recompute bound: the continuation re-decodes, the prefix
    # does NOT — post-reset decode blocks cover only the remaining
    # budget (+1 block of admission slack), not the whole stream
    replay_blocks = srv.blocks_dispatched - blocks_before
    remaining = 20 - len(prefix)
    assert replay_blocks <= -(-remaining // srv.block_size) + 1, (
        f"replay re-decoded the prefix: {replay_blocks} blocks for "
        f"{remaining} remaining tokens")
    # the crash's latency cost is measured: replayed -> finished
    assert srv.telemetry.hist["replay_catchup_s"].count == 1


def test_cancel_of_replayed_request_targets_new_slot(params):
    """Cancel composes with replay: after a reset, a replayed id is
    cancellable both while RE-QUEUED (completion carries the journaled
    prefix — delivered work, not queue residue) and while RE-ADMITTED
    into its new slot (the dead slot's mapping died with the reset;
    partial tokens stay an exact solo-stream prefix)."""
    pa, pc = _prompts(2, key=313)
    srv = _srv(params)
    a = Request(prompt=pa, max_new_tokens=30)
    c = Request(prompt=pc, max_new_tokens=30)
    srv.submit(a)
    srv.submit(c)
    for _ in range(2):
        srv.step()
    srv.drain_completed()
    pre_a = list(srv._journal.get(a.id).emitted)
    pre_c = list(srv._journal.get(c.id).emitted)
    assert pre_a and pre_c
    assert srv.reset() == []
    # both replays are queued, nothing admitted: the old slot mappings
    # are gone — cancel must find the QUEUED replay
    assert a.id not in srv._slot_of and c.id not in srv._slot_of
    assert srv.cancel(c.id) is True
    srv.step()                  # re-admits a into a fresh slot
    assert a.id in srv._slot_of
    assert srv.cancel(a.id) is True, "cancel must target the NEW slot"
    done = srv.run_until_drained()
    assert done[c.id].finish_reason == "cancelled"
    assert done[c.id].tokens == pre_c, (
        "a queued replay's cancel must return its journaled prefix")
    assert done[a.id].finish_reason == "cancelled"
    got = done[a.id].tokens
    assert got[:len(pre_a)] == pre_a
    assert got == _solo(params, pa, 30)[:len(got)], (
        "cancelled replay's tokens diverged from its solo stream")
    assert srv._journal.get(a.id) is None, "cancel must seal the journal"


def test_replay_byte_identical_prefix_cache_on_and_off(params):
    """Replay determinism is prefix-cache-invariant: the teacher-forced
    re-prefill rides the cache when enabled (the replayed context's
    chunks are ordinary trie blocks) and recomputes when not — both
    byte-identical to the uninterrupted stream."""
    pa, pc = _prompts(2, key=317, lo=10, hi=14)  # >= 1 full chunk each
    for blocks in (0, 8):
        srv = _srv(params, prefix_cache_blocks=blocks)
        a = Request(prompt=pa, max_new_tokens=24)
        c = Request(prompt=pc, max_new_tokens=24)
        srv.submit(a)
        srv.submit(c)
        for _ in range(3):
            srv.step()
        srv.drain_completed()
        assert srv.reset() == []
        done = srv.run_until_drained()
        assert done[a.id].tokens == _solo(params, pa, 24), f"cache={blocks}"
        assert done[c.id].tokens == _solo(params, pc, 24), f"cache={blocks}"
        assert srv.replays == 2
        srv.shutdown()


def test_replay_int8_kv_tolerance(params):
    """Replay across int8 KV (the ROADMAP int8 carve-out, extended to
    replay): the resume prefix is preserved VERBATIM (teacher-forced,
    never re-sampled), while the continuation agrees with an
    uninterrupted int8 serving run at quantization tolerance — replayed
    positions re-prefill through the quantized cache where the
    uninterrupted run decode-wrote them, so a near-tie can flip a
    greedy token. Majority agreement is the regression bar; exactness
    claims belong to the native-dtype tests above."""
    prompts = _prompts(4, key=331)
    kw = dict(kv_dtype="int8", weight_dtype="int8")
    ref_srv = _srv(params, **kw)
    ref_reqs = [Request(prompt=p, max_new_tokens=12) for p in prompts]
    for r in ref_reqs:
        ref_srv.submit(r)
    ref_done = ref_srv.run_until_drained()
    refs = [ref_done[r.id].tokens for r in ref_reqs]
    srv = _srv(params, **kw)
    reqs = [Request(prompt=p, max_new_tokens=12) for p in prompts]
    for r in reqs:
        srv.submit(r)
    for _ in range(2):
        srv.step()
    srv.drain_completed()
    prefixes = {r.id: list(e.emitted)
                for r in reqs
                if (e := srv._journal.get(r.id)) is not None}
    assert any(prefixes.values()), "setup: need partial prefixes"
    assert srv.reset() == []
    done = srv.run_until_drained()
    for r in reqs:
        pre = prefixes.get(r.id)
        if pre:
            assert done[r.id].tokens[:len(pre)] == pre, (
                "the resume prefix must be preserved verbatim")
    got = [done[r.id].tokens for r in reqs]
    agree = sum(t == s for t, s in zip(got, refs))
    assert agree * 2 >= len(refs), (got, refs)


def test_journal_recovery_across_server_instances(tmp_path, params):
    """Process-restart recovery (the serve CLI's startup path, without
    processes): a file-backed journal written by one SlotServer is
    recovered by a FRESH one, which finishes the dead server's
    unfinished requests byte-identical to solo — a replica SIGKILL +
    restart costs latency, not requests. The recovered file is
    compacted, lineage rides attrs.recovered_from."""
    from tony_tpu.events.journal import JOURNAL_FILE, RequestJournal

    path = tmp_path / JOURNAL_FILE
    pa, pb = _prompts(2, key=337)
    srv1 = _srv(params, journal=RequestJournal(path))
    a = Request(prompt=pa, max_new_tokens=20)
    b = Request(prompt=pb, max_new_tokens=18)
    srv1.submit(a)
    srv1.submit(b)
    for _ in range(2):
        srv1.step()
    srv1.drain_completed()      # prefixes are journaled to disk
    # simulated SIGKILL: srv1 is abandoned mid-flight, never drained
    j2, entries = RequestJournal.recover(path)
    assert sorted(e.id for e in entries) == sorted([a.id, b.id])
    assert all(e.emitted for e in entries)
    # max_queue=1 must NOT shed recovered entries: the dead process
    # already accepted them all, and a shed here would be compacted
    # out of the only durable copy — recovery is exempt from the bound
    srv2 = _srv(params, journal=j2, max_queue=1)
    assert srv2.recover_journal(entries) == 2
    assert srv2.max_queue == 1, "the bound must be restored after"
    done = srv2.run_until_drained()
    by_origin = {c.trace["attrs"]["recovered_from"]: c
                 for c in done.values()}
    assert by_origin[a.id].tokens == _solo(params, pa, 20)
    assert by_origin[b.id].tokens == _solo(params, pb, 18)
    assert srv2.replays == 2 and srv2.replayed_tokens > 0
    assert len(j2) == 0, "finished recoveries must seal their entries"
    srv1.shutdown()
    srv2.shutdown()


def test_checkpoint_progress_advances_journal_without_stall(params):
    """The durability checkpoint: a SOLO open-loop request normally
    processes nothing until completion — its journal prefix (and
    /progress answer) would stay empty for its entire decode.
    checkpoint_progress() processes the pipeline down to
    pipeline_depth: the journal advances mid-request, the dispatch
    runway survives, and the final stream is untouched."""
    pa = _prompts(1, key=347)[0]
    srv = _srv(params)
    a = Request(prompt=pa, max_new_tokens=24)
    srv.submit(a)
    for _ in range(5):
        srv.step()              # open-loop: blocks pile up unprocessed
    assert srv._journal.get(a.id).emitted == [], (
        "setup: solo predictive traffic must not have processed yet")
    assert len(srv._pipeline) > srv.pipeline_depth
    srv.checkpoint_progress()
    mid = list(srv._journal.get(a.id).emitted)
    assert mid, "checkpoint must advance the journaled prefix"
    assert len(srv._pipeline) == srv.pipeline_depth, (
        "checkpoint must keep pipeline_depth blocks of runway in flight")
    assert srv.progress(a.id)["tokens"] == mid
    done = srv.run_until_drained()
    ref = _solo(params, pa, 24)
    assert done[a.id].tokens == ref and mid == ref[:len(mid)]


def test_fail_pending_seals_journal_entries(params):
    """A terminal delivered upstream IS the terminal: when ServeApp
    fails its waiters (restart-budget exhaustion / drain timeout),
    their journal entries must be sealed — a later restart's recovery
    must not resurrect and decode requests whose clients were already
    told 'failed'."""
    srv = _srv(params)
    app = ServeApp(srv)                 # loop never started: direct unit
    a = Request(prompt=[3, 1, 4], max_new_tokens=6)
    app._events[a.id] = threading.Event()
    srv.submit(a)
    assert srv._journal.get(a.id) is not None
    app._fail_pending(RuntimeError("budget exhausted"))
    assert srv._journal.get(a.id) is None, (
        "_fail_pending must seal the failed request's journal entry")
    assert app._events == {} and a.id in app._results


def test_expired_queued_replay_keeps_emitted_prefix(params):
    """A queued REPLAY whose deadline passes before re-admission still
    owns its emitted prefix (same contract as the queued-cancel path):
    the expired completion carries the delivered decode work, not an
    empty token list."""
    srv = _srv(params)
    r = Request(prompt=[3, 1, 4], max_new_tokens=8,
                resume_tokens=[9, 2, 6],
                deadline=time.monotonic() - 1.0)
    srv.submit(r)
    done = srv.run_until_drained()
    assert done[r.id].finish_reason == "expired"
    assert done[r.id].tokens == [9, 2, 6], (
        "an expired replay must keep its journaled prefix")
    assert srv._journal.get(r.id) is None


def test_resume_already_satisfied_completes_without_slot(params):
    """A resume prefix that already satisfies the request — budget
    reached, or it ends in a stop token (a failover racing a finished
    stream) — completes immediately: no slot, no prefill, no decode."""
    srv = _srv(params, stop_tokens=(9,), pad_id=255)
    r1 = Request(prompt=[1, 2], max_new_tokens=3, resume_tokens=[4, 5, 6])
    r2 = Request(prompt=[1, 2], max_new_tokens=8, resume_tokens=[4, 9])
    srv.submit(r1)
    srv.submit(r2)
    done = srv.drain_completed()
    assert done[r1.id].tokens == [4, 5, 6]
    assert done[r1.id].finish_reason == "length"
    assert done[r2.id].tokens == [4, 9]
    assert done[r2.id].finish_reason == "stop"
    assert srv.blocks_dispatched == 0 and srv.admission_dispatches == 0
    assert srv.replays == 2
    assert srv.idle


def test_crash_at_blocks_chaos_zero_failed_requests(params, monkeypatch):
    """The deterministic mid-decode crash injection point
    (TONY_TEST_SERVING_CRASH_AT_BLOCKS) through the full ServeApp
    recovery path: two injected loop crashes, and every request still
    completes byte-identical to solo generate — zero failed waiters,
    recovery + replay visible in the counters."""
    monkeypatch.setenv("TONY_TEST_SERVING_CRASH_AT_BLOCKS", "2, 5")
    prompts = _prompts(4, key=341)
    srv = _srv(params, max_queue=8)
    app = ServeApp(srv, max_loop_restarts=10, loop_backoff_s=0.01)
    app.start()
    try:
        results = {}

        def call(i):
            try:
                results[i] = app.generate(prompts[i], 10, timeout=90)
            except Exception as e:      # pragma: no cover
                results[i] = e

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "hung waiters"
        for i, r in results.items():
            assert isinstance(r, Completion), f"request {i} failed: {r!r}"
            assert r.tokens == _solo(params, prompts[i], 10), (
                f"request {i} diverged through crash+replay")
        assert srv.chaos_faults_injected >= 1, "injection never fired"
        assert app.loop_restarts >= 1 and srv.replays >= 1
        assert app.status != "down"
    finally:
        app.shutdown()


# --------------------------------------------------------------------------
# loop recovery lifecycle (ServeApp level, scripted engine)
# --------------------------------------------------------------------------

class ScriptedServer:
    """SlotServer stand-in with a scriptable per-step failure pattern:
    admits one queued request per step, completes it the step after.
    Exercises the ServeApp recovery state machine without a model."""

    slots, max_len, block_size = 1, 32, 4

    def __init__(self, fail=()):
        self.fail = list(fail)          # step n raises iff fail[n]
        self.fail_always = False
        self.fail_once_when_active = False   # one-shot mid-decode failure
        self.queue: list = []
        self.active = None
        self.done: dict = {}
        self.pause_admission = False
        self.resets = 0
        self.shed_requests = 0
        self.cancelled_requests = 0
        self.expired_requests = 0
        self.prefill_tokens_computed = 0
        self.prefill_tokens_reused = 0

    @property
    def idle(self):
        return not (self.queue or self.active or self.done)

    @property
    def pending(self):
        return len(self.queue)

    @property
    def n_active(self):
        return 1 if self.active is not None else 0

    @property
    def completions_ready(self):
        return bool(self.done)

    def submit(self, req):
        self.queue.append(req)
        return req.id

    def step(self):
        if self.fail_always or (self.fail and self.fail.pop(0)):
            raise RuntimeError("scripted step failure")
        if self.fail_once_when_active and self.active is not None:
            self.fail_once_when_active = False
            raise RuntimeError("scripted step failure (mid-decode)")
        if self.active is None:
            if self.queue and not self.pause_admission:
                self.active = self.queue.pop(0)
            return
        self.done[self.active.id] = Completion(self.active.id, [1, 2],
                                               "length")
        self.active = None

    def drain_completed(self):
        d, self.done = self.done, {}
        return d

    def cancel(self, request_id):
        for req in self.queue:
            if req.id == request_id:
                self.queue.remove(req)
                self.cancelled_requests += 1
                return True
        if self.active is not None and self.active.id == request_id:
            self.active = None
            self.cancelled_requests += 1
            return True
        return False

    def fail_queued(self):
        out, self.queue = self.queue, []
        return out

    def reset(self):
        self.resets += 1
        lost = [self.active.id] if self.active is not None else []
        self.active = None
        self.done = {}
        return lost

    def stats(self):
        return {"slots": self.slots, "active": self.n_active,
                "queued": self.pending}


def test_loop_recovery_healthz_lifecycle():
    """healthy -> (step failure) degraded -> recovered: a queued request
    rides THROUGH the restart and completes; /healthz never 503s and the
    restart counter records the event."""
    srv = ScriptedServer(fail=[True])           # first step fails only
    app = ServeApp(srv, max_loop_restarts=3, loop_backoff_s=0.4)
    assert app.health()["status"] == "ok"
    app.start()
    try:
        res = {}

        def call():
            try:
                res["r"] = app.generate([1], 4, timeout=30)
            except Exception as e:              # pragma: no cover
                res["r"] = e

        t = threading.Thread(target=call)
        t.start()
        # the failure fires on the first busy tick; during the 0.4s
        # backoff the app must read degraded (200, still behind the LB)
        deadline = time.monotonic() + 5
        saw_degraded = False
        while time.monotonic() < deadline and not saw_degraded:
            h = app.health()
            assert h["healthy"] is True
            saw_degraded = h["status"] == "degraded"
            time.sleep(0.01)
        assert saw_degraded, "recovery window never reported degraded"
        t.join(timeout=30)
        assert not t.is_alive()
        assert isinstance(res["r"], Completion), (
            "queued request should survive a loop restart")
        h = app.health()
        assert h["status"] == "ok" and h["loop_restarts"] == 1
        assert app.stats()["loop"]["restarts"] == 1
        assert srv.resets == 1
    finally:
        app.shutdown()


def test_loop_failure_mid_decode_fails_only_inflight():
    """A step failure with a request IN FLIGHT fails exactly that waiter
    (ServingLoopError, immediately) while its neighbor — queued at the
    failure or submitted during recovery — survives and completes."""
    srv = ScriptedServer()
    # one-shot: the step AFTER r1 is admitted raises, with r1 in flight
    srv.fail_once_when_active = True
    app = ServeApp(srv, max_loop_restarts=3, loop_backoff_s=0.01)
    app.start()
    try:
        res = {}

        def call(name):
            try:
                res[name] = app.generate([1], 4, timeout=30)
            except Exception as e:
                res[name] = e

        t1 = threading.Thread(target=call, args=("r1",))
        t1.start()
        t2 = threading.Thread(target=call, args=("r2",))
        t2.start()
        t1.join(timeout=30)
        t2.join(timeout=30)
        assert not t1.is_alive() and not t2.is_alive()
        # exactly one request was in flight when the step died: it got a
        # prompt ServingLoopError; the other rode through the restart
        lost = [r for r in res.values() if isinstance(r, ServingLoopError)]
        ok = [r for r in res.values() if isinstance(r, Completion)]
        assert len(lost) == 1 and "lost" in str(lost[0]), res
        assert len(ok) == 1, res
        assert srv.resets == 1 and app.loop_restarts == 1
    finally:
        app.shutdown()


def test_loop_restart_budget_exhausted_503():
    """Persistent failure exhausts the consecutive-restart budget: the
    app flips terminally down (healthz 503 + the cause), every waiter is
    failed immediately, and new submissions are rejected."""
    srv = ScriptedServer()
    srv.fail_always = True
    app = ServeApp(srv, max_loop_restarts=2, loop_backoff_s=0.01)
    app.start()
    try:
        with pytest.raises(ServingLoopError):
            app.generate([1], 4, timeout=30)
        assert app.status == "down"
        h = app.health()
        assert h["healthy"] is False and "exhausted" in h["error"]
        assert srv.resets == 2                  # budget, fully spent
        with pytest.raises(ServingLoopError, match="down"):
            app.generate([1], 4, timeout=5)
    finally:
        app.shutdown()


def test_engine_without_reset_is_terminal():
    """An engine that cannot re-arm (no reset()) keeps the old contract:
    first failure is terminal, waiters fail fast, healthz 503s."""
    class NoResetServer(ScriptedServer):
        reset = None

    srv = NoResetServer()
    srv.fail_always = True
    app = ServeApp(srv, max_loop_restarts=5, loop_backoff_s=0.01)
    app.start()
    try:
        with pytest.raises(ServingLoopError):
            app.generate([1], 4, timeout=30)
        assert app.status == "down" and app.loop_restarts == 0
    finally:
        app.shutdown()


# --------------------------------------------------------------------------
# graceful drain + shedding (ServeApp level, real engine)
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_drain_shutdown_finishes_inflight_fails_queued(params):
    """shutdown(drain=True): admission stops, in-flight requests finish
    (token-identical — drain is scheduling, not numerics), queued-but-
    unstarted requests fail with a clear error, and new submissions are
    rejected while draining. The setup stages the exact state drain must
    handle — two slots mid-decode, one request queued — by admitting
    BEFORE the loop thread starts (open-loop dispatch outruns any
    wall-clock poll, so racing the live loop is not deterministic).
    Slow-marked (~15s: two budget-48 decodes + their solo references);
    the drain building blocks (pause_admission, fail_queued, healthz)
    are cheap-tested in the tier-1 gate via the scripted engine."""
    pa, pc, pb = _prompts(3, key=251)
    srv = _srv(params)
    app = ServeApp(srv)            # loop NOT started yet
    res = {}

    def call(name, prompt, budget):
        try:
            res[name] = app.generate(prompt, budget, timeout=60)
        except Exception as e:
            res[name] = e

    t_a = threading.Thread(target=call, args=("a", pa, 48))
    t_c = threading.Thread(target=call, args=("c", pc, 48))
    t_a.start()
    t_c.start()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and srv.pending < 2:
        time.sleep(0.002)
    assert srv.pending == 2
    srv.step()                     # admit both into the 2 slots, block 1
    assert srv.n_active == 2, "both slots should be decoding"
    srv.pause_admission = True     # the switch drain itself flips
    t_b = threading.Thread(target=call, args=("b", pb, 4))
    t_b.start()
    while time.monotonic() < deadline and srv.pending < 1:
        time.sleep(0.002)
    assert srv.pending == 1, "third request never queued"
    app.start()                    # now the loop serves the staged state
    app.shutdown(drain=True, drain_timeout_s=60)
    for t in (t_a, t_c, t_b):
        t.join(timeout=30)
        assert not t.is_alive(), "drain left a hung waiter"
    assert isinstance(res["a"], Completion)
    assert res["a"].tokens == _solo(params, pa, 48), (
        "drain changed an in-flight request's tokens")
    assert isinstance(res["c"], Completion)
    assert res["c"].tokens == _solo(params, pc, 48)
    assert isinstance(res["b"], ServingLoopError)
    assert "shutting down" in str(res["b"])
    with pytest.raises(ServingLoopError, match="draining"):
        app.generate(pb, 4, timeout=5)
    h = app.health()
    assert h["healthy"] is False and h["status"] == "draining", (
        "/healthz must take a draining instance out of rotation")


def test_http_overload_sheds_429_with_retry_after(params):
    """HTTP surface of bounded admission: with the wait queue at
    max_queue, the next POST /generate is shed with 429 + Retry-After —
    while the queued request itself is served to completion once a slot
    picks it up. Admission is parked while the probe fires so the queue
    seat is DETERMINISTICALLY occupied (shedding is queue-depth-based;
    slot business is irrelevant to it)."""
    import json
    import urllib.error
    import urllib.request
    from http.server import ThreadingHTTPServer

    from tony_tpu.cli.serve import make_handler

    prompts = _prompts(2, key=257)
    srv = _srv(params, max_queue=1)
    srv.pause_admission = True      # hold the queue seat for the probe
    app = ServeApp(srv)
    app.start()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(app))
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        results = {}

        def post(i, p, budget):
            body = json.dumps({"prompt": [int(x) for x in p],
                               "max_new_tokens": budget,
                               "progress_key": f"k{i}"}).encode()
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/generate", data=body,
                        timeout=120) as r:
                    results[i] = json.loads(r.read())
            except Exception as e:
                results[i] = e

        t1 = threading.Thread(target=post, args=(0, prompts[0], 5))
        t1.start()                              # fills the queue (max 1)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and srv.pending < 1:
            time.sleep(0.002)
        assert srv.pending == 1, "first request never queued"
        # the failover-resume progress endpoint: the queued request's
        # journal entry is readable under its caller-chosen key (no
        # tokens yet — it hasn't been admitted); unknown keys are absent
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/progress?keys=k0,nope",
                timeout=10) as r:
            prog = json.loads(r.read())
        assert prog["k0"]["tokens"] == []
        assert prog["k0"]["prompt_tokens"] == len(prompts[0])
        assert "nope" not in prog
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps({"prompt": [1], "max_new_tokens": 4}
                                ).encode(), timeout=10)
        assert ei.value.code == 429
        # rate-derived header (observability.ServiceRateEstimator): an
        # integer in [1, 60]; with nothing served yet the EWMA default
        # keeps it at the 1s floor
        ra = int(ei.value.headers.get("Retry-After"))
        assert 1 <= ra <= 60
        assert ra == 1, "no service history yet: the default floor"
        srv.pause_admission = False             # let the queued one run
        t1.join(timeout=60)
        assert not t1.is_alive()
        assert isinstance(results[0], dict), results[0]
        assert results[0]["finish_reason"] == "length"
        assert results[0]["tokens"] == _solo(params, prompts[0], 5), (
            "shedding must not perturb the admitted request")
        st = app.stats()
        assert st["shed"] == 1
        names = {m["name"] for m in st["metrics"]}
        assert "max_serving_shed_total" in names
    finally:
        httpd.shutdown()
        httpd.server_close()
        app.shutdown()


def test_stream_client_disconnect_cancels_and_frees_slot(
        params, monkeypatch):
    """Mid-STREAM client disconnect (ISSUE 14 satellite): a client that
    closes its socket while its SSE stream is live triggers cancel()
    through the PR 3 path — the partial stream it read is an exact solo
    prefix, the disconnect is counted, and the freed slot's next
    occupant is byte-identical to a fresh server (cancellation is
    scheduling, never numerics)."""
    import json as _json
    import socket as _socket
    from http.server import ThreadingHTTPServer

    from tony_tpu.cli.serve import make_handler

    # slow each scheduling turn so the stream is reliably mid-decode
    # when the client walks away (read at SlotServer construction),
    # and serve in EOS mode (an unreachable stop token — vocab is 256)
    # so blocks pace per turn instead of the predictive mode's
    # open-loop run-ahead finishing the whole budget before the close
    # is noticed
    monkeypatch.setenv("TONY_TEST_SERVING_STEP_DELAY_MS", "40")
    pa, pb = _prompts(2, key=311)
    srv = _srv(params, stop_tokens=(300,))
    app = ServeApp(srv)
    app.start()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(app))
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        body = _json.dumps({"prompt": [int(t) for t in pa],
                            "max_new_tokens": 40,
                            "stream": True}).encode()
        raw = (f"POST /generate HTTP/1.1\r\nHost: x\r\n"
               f"Content-Type: application/json\r\n"
               f"Content-Length: {len(body)}\r\n"
               f"Connection: close\r\n\r\n").encode() + body
        sock = _socket.create_connection(("127.0.0.1", port), timeout=60)
        sock.sendall(raw)
        # read until at least one token frame arrived, then vanish
        buf = b""
        partial: list[int] = []
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not partial:
            chunk = sock.recv(4096)
            assert chunk, "server closed before any token frame"
            buf += chunk
            for line in buf.split(b"\n"):
                line = line.strip()
                if line.startswith(b"data: "):
                    obj = _json.loads(line[len(b"data: "):])
                    if "tokens" in obj and "finish_reason" not in obj:
                        partial.extend(obj["tokens"])
        assert partial, "never saw a token frame"
        sock.close()                            # the client is gone
        # the handler's next wait beat notices and cancels
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and \
                srv.cancelled_requests < 1:
            time.sleep(0.02)
        assert srv.cancelled_requests == 1, (
            "mid-stream disconnect must cancel the request")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and app.stream_disconnects < 1:
            time.sleep(0.02)
        assert app.stream_disconnects == 1
        # the cancel is logged against the newest in-flight block; the
        # stream is released when that block's processing replays it
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and srv.streams_active:
            time.sleep(0.02)
        assert srv.streams_active == 0, "stream must be released"
        # the frames the client DID read are an exact solo prefix
        assert 0 < len(partial) < 40
        assert partial == _solo(params, pa, 40)[:len(partial)], (
            "partial stream diverged from the solo greedy stream")
        # the freed slot's next occupant: byte-identical to fresh
        comp = app.generate(pb, 6, timeout=120)
        assert comp.tokens == _solo(params, pb, 6), (
            "request admitted after a disconnect-cancel diverged")
    finally:
        httpd.shutdown()
        httpd.server_close()
        app.shutdown()


# --------------------------------------------------------------------------
# seeded chaos: every request terminates, the server outlives the faults
# --------------------------------------------------------------------------

def test_chaos_seeded_every_request_terminates(params, monkeypatch):
    """Seeded dispatch-failure injection at a heavy rate: the serving
    loop recovers every time (restart streak never exceeds the budget at
    this rate), every submitted request terminates with a completion, a
    shed, or an explicit error — ZERO hung waiters — and every completed
    request is still token-exact vs solo generate() (recovery never
    corrupts survivors)."""
    monkeypatch.setenv("TONY_TEST_SERVING_DISPATCH_FAIL_RATE", "0.3")
    monkeypatch.setenv("TONY_TEST_SERVING_CHAOS_SEED", "42")
    prompts = _prompts(10, key=263)
    srv = _srv(params, max_queue=8)
    app = ServeApp(srv, max_loop_restarts=50, loop_backoff_s=0.01)
    app.start()
    try:
        results = {}

        def call(i):
            try:
                results[i] = app.generate(prompts[i], 6, timeout=90)
            except Exception as e:
                results[i] = e

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
            time.sleep(0.01)                    # a small arrival spread
        for t in threads:
            t.join(timeout=120)
        hung = [t for t in threads if t.is_alive()]
        assert not hung, f"{len(hung)} waiters hung under chaos"
        assert len(results) == len(prompts)
        completions = errors = 0
        for i, r in results.items():
            if isinstance(r, Completion):
                completions += 1
                assert r.finish_reason == "length"
                assert r.tokens == _solo(params, prompts[i], 6), (
                    f"request {i} corrupted by recovery")
            else:
                errors += 1
                assert isinstance(
                    r, (ServingLoopError, QueueFullError, TimeoutError)), r
        assert completions > 0, "chaos starved every request"
        assert srv.chaos_faults_injected >= 1, "chaos never fired"
        assert app.loop_restarts >= 1, "no recovery was exercised"
        assert app.status != "down", (
            "the restart budget should absorb this fault rate")
        st = app.stats()
        assert st["resets"] == app.loop_restarts
        assert st["loop"]["failures"] == srv.chaos_faults_injected
    finally:
        app.shutdown()


# --------------------------------------------------------------------------
# serve CLI: SIGKILL + restart finishes the dead process's requests
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_serve_cli_sigkill_restart_recovers_journal(tmp_path):
    """The process-death arm of the durability contract, through the
    real CLI: a serve process with a file journal SIGKILLs itself
    mid-decode (TONY_TEST_SERVING_SIGKILL_AT_BLOCK), and a restarted
    process pointing at the same --trace-dir recovers the journal and
    FINISHES the orphaned request — visible in /stats (replays, empty
    journal) and as a finished attrs.recovered_from trace record.
    Slow-marked: two jax process startups; the in-process recovery
    contract stays in the tier-1 gate
    (test_journal_recovery_across_server_instances)."""
    import json as _json
    import os
    import re as _re
    import signal
    import subprocess
    import sys
    import urllib.request

    from tony_tpu.events.trace import read_traces

    args = [sys.executable, "-m", "tony_tpu.cli.main", "serve",
            "--port", "0", "--vocab", "256", "--d-model", "64",
            "--n-layers", "2", "--n-heads", "4", "--d-ff", "128",
            "--dtype", "float32", "--slots", "2", "--max-len", "64",
            "--block-size", "4", "--prefill-chunk", "8",
            "--trace-dir", str(tmp_path)]

    def spawn(extra_env):
        return subprocess.Popen(
            args, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu", **extra_env})

    def await_port(proc, timeout=240):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            m = _re.search(r"http://[\d.]+:(\d+)", line or "")
            if m:
                threading.Thread(target=proc.stdout.read,
                                 daemon=True).start()
                return int(m.group(1))
        raise AssertionError("serve never printed its port")

    proc = spawn({"TONY_TEST_SERVING_SIGKILL_AT_BLOCK": "2"})
    try:
        port = await_port(proc)
        body = _json.dumps({"prompt": [3, 1, 4, 1, 5],
                            "max_new_tokens": 20}).encode()
        # the process SIGKILLs itself at decode block 2: the POST dies
        # with the connection
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/generate", data=body,
                timeout=300).read()
        assert proc.wait(timeout=60) == -signal.SIGKILL
    finally:
        if proc.poll() is None:
            proc.kill()
    journal = tmp_path / "requests.journal.jsonl"
    assert journal.exists() and journal.read_text().strip(), (
        "the dead process left no journal to recover")
    proc2 = spawn({})
    try:
        port2 = await_port(proc2)
        deadline = time.monotonic() + 120
        st = None
        while time.monotonic() < deadline:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port2}/stats", timeout=10) as r:
                st = _json.loads(r.read())
            if (st["replays"] >= 1 and st["journal"]["entries"] == 0
                    and st["active"] == 0 and st["queued"] == 0):
                break
            time.sleep(0.25)
        assert st is not None and st["replays"] >= 1, st
        assert st["journal"]["entries"] == 0, (
            "recovery must drain and seal the journal")
        recovered = [
            r for r in read_traces(tmp_path / "requests.trace.jsonl")
            if r["attrs"].get("recovered_from") is not None
            and r["spans"] and r["spans"][-1][0] == "finished"]
        assert recovered, "no finished recovered_from trace record"
        assert recovered[0]["attrs"]["n_tokens"] == 20
    finally:
        proc2.send_signal(signal.SIGTERM)
        try:
            proc2.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc2.kill()


# --------------------------------------------------------------------------
# serve CLI: graceful drain on SIGTERM
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_serve_cli_sigterm_graceful_drain(tmp_path):
    """A supervisor's SIGTERM must reach app.shutdown(drain=True), not
    kill the process mid-decode: the CLI installs handlers, prints the
    drain notice, and exits 0."""
    import os
    import signal
    import subprocess
    import sys

    proc = subprocess.Popen(
        [sys.executable, "-m", "tony_tpu.cli.main", "serve",
         "--port", "0", "--vocab", "256", "--d-model", "64",
         "--n-layers", "2", "--n-heads", "4", "--d-ff", "128",
         "--dtype", "float32", "--slots", "2", "--max-len", "64",
         "--drain-timeout-s", "10"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        line = ""
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if "serving" in line:
                break
        assert "serving" in line, "server never came up"
        proc.send_signal(signal.SIGTERM)
        out = proc.stdout.read()
        assert proc.wait(timeout=60) == 0
        assert "draining" in out
    finally:
        if proc.poll() is None:
            proc.kill()
