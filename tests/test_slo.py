"""Fleet metrics pipeline: MetricsHub TSDB + SLO burn-rate alerting
(tony_tpu/metricshub.py, tony_tpu/slo.py — docs/observability.md
"Metrics pipeline & SLO alerting").

The contract under test: the hub retains every scraped sample in
bounded rings (max_points AND retention_s both bind) with restart-safe
counter-reset offsets (the generalization of bucket_delta's clamp);
windows are queryable as increases/bucket deltas; the TSDB file
round-trips through load() (torn lines skipped, offsets rebuilt in
order) and compacts to the retention horizon; the SLO engine's
multi-window pairs fire only when BOTH windows burn above threshold,
clear after CLEAR_TICKS clean evaluations, journal every transition,
and RESUME journal-seeded alerts across a simulated driver recovery
without a duplicate firing transition; and every exposition surface
(driver, router, portal, the SLO renderer itself) round-trips the
shared strict parser. All shapes are synthetic — no model, no JAX.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from tony_tpu import metrics as _metrics
from tony_tpu.conf import TonyConf
from tony_tpu.events.driver_journal import DriverJournal, load_state
from tony_tpu.metricshub import TSDB_FILE, MetricsHub
from tony_tpu.observability import PromRenderer, parse_prom_text
from tony_tpu.slo import (
    CLEAR_TICKS,
    SLObjective,
    SLOEngine,
    good_under_threshold,
    slo_objectives_from_conf,
)


def _hub(**kw):
    kw.setdefault("retention_s", 1e9)
    kw.setdefault("max_points", 720)
    return MetricsHub(**kw)


def _avail_text(req: float, failed: float, shed: float = 0.0) -> str:
    return (f"{_metrics.ROUTER_REQUESTS_TOTAL}{{replica=\"r0\"}} {req}\n"
            f"{_metrics.ROUTER_FAILED_TOTAL} {failed}\n"
            f"{_metrics.ROUTER_SHED_TOTAL}{{replica=\"r0\"}} {shed}\n")


# --------------------------------------------------------------------------
# ring retention: max_points and retention_s both bind
# --------------------------------------------------------------------------

def test_ring_retention_bounds():
    hub = _hub(retention_s=100.0, max_points=8)
    for i in range(50):
        hub.ingest("t", f"some_gauge {i}\n", now=1000.0 + i)
    (series,) = hub._series.values()
    assert len(series.ring) <= 8, "max_points must bound the ring"
    assert series.latest() == 49.0
    # retention_s prunes the old edge even under max_points
    hub2 = _hub(retention_s=5.0, max_points=1000)
    for i in range(50):
        hub2.ingest("t", f"some_gauge {i}\n", now=1000.0 + i)
    (s2,) = hub2._series.values()
    assert all(ts >= 1049.0 - 5.0 for ts, _ in s2.ring), (
        "points past the retention horizon must be pruned")
    assert s2.latest() == 49.0


def test_counter_reset_offset_at_hub_layer():
    """The per-series monotonic offset generalizes bucket_delta's clamp:
    a raw sample dropping below its predecessor (exporter restart) folds
    the predecessor into the offset, so window increases across the
    restart equal the fresh process's contribution — and the full-run
    increase equals the sum of both processes' lifetimes."""
    hub = _hub()
    hub.ingest("t", "reqs_total 100\n", now=10.0)
    hub.ingest("t", "reqs_total 150\n", now=20.0)
    hub.ingest("t", "reqs_total 30\n", now=30.0)     # restarted at 0
    hub.ingest("t", "reqs_total 70\n", now=40.0)
    # window starting after the last pre-restart sample: only the fresh
    # process's 70 (the clamp equivalence)
    assert hub.window_increase("reqs_total", 15.0, now=40.0) == \
        pytest.approx(70.0)
    # window spanning the restart: 100->150 (+50) plus 0->70 (+70)
    assert hub.window_increase("reqs_total", 25.0, now=40.0) == \
        pytest.approx(120.0)
    # full run: 150 from the first process + 70 from the second
    assert hub.window_increase("reqs_total", 1e6, now=40.0) == \
        pytest.approx(220.0)
    # gauges do NOT get the offset — a drop is a real drop
    hub.ingest("t", "depth_gauge 9\n", now=10.0)
    hub.ingest("t", "depth_gauge 2\n", now=20.0)
    assert hub.latest("depth_gauge") == 2.0


def test_window_buckets_sum_and_model_exclusion():
    """window_buckets merges a histogram family's cumulative buckets
    across targets as windowed increases, skipping the {model=...}
    partitions exactly like scrape_ttft_buckets does."""
    text0 = ('serving_ttft_seconds_bucket{le="0.1"} 0\n'
             'serving_ttft_seconds_bucket{le="+Inf"} 0\n')
    text1 = ('serving_ttft_seconds_bucket{le="0.1"} 3\n'
             'serving_ttft_seconds_bucket{le="+Inf"} 5\n'
             'serving_ttft_seconds_bucket{model="m",le="0.1"} 100\n')
    hub = _hub()
    for tg in ("a", "b"):
        hub.ingest(tg, text0, now=10.0)
        hub.ingest(tg, text1, now=20.0)
    got = hub.window_buckets("serving_ttft_seconds", 15.0, now=20.0)
    assert got == {"0.1": 6.0, "+Inf": 10.0}, (
        "summed across targets, model partition excluded")


# --------------------------------------------------------------------------
# TSDB persistence: round-trip, torn lines, compaction
# --------------------------------------------------------------------------

def test_tsdb_persist_load_roundtrip(tmp_path):
    hub = _hub(persist_dir=tmp_path)
    hub.ingest("router", _avail_text(100, 2), now=10.0)
    hub.ingest("router", _avail_text(40, 3), now=20.0)   # reset mid-run
    hub.stop()
    path = tmp_path / TSDB_FILE
    assert path.exists()
    # torn tail + garbage line: both skipped on load
    with open(path, "a") as f:
        f.write("not json\n")
        f.write('{"t": 30.0, "tg": "rout')
    hub2 = _hub()
    n = hub2.load(path)
    assert n == 2
    for name in (_metrics.ROUTER_REQUESTS_TOTAL,
                 _metrics.ROUTER_FAILED_TOTAL):
        assert hub2.window_increase(name, 1e6, now=20.0) == \
            hub.window_increase(name, 1e6, now=20.0), (
            f"replayed window must match the live hub for {name}")
    # the reset offset rebuilt in record order: 100 + 40
    assert hub2.window_increase(
        _metrics.ROUTER_REQUESTS_TOTAL, 1e6, now=20.0) == \
        pytest.approx(140.0)


def test_tsdb_compaction_to_retention_horizon(tmp_path):
    hub = MetricsHub(persist_dir=tmp_path, retention_s=50.0,
                     max_points=720, max_persist_lines=10)
    for i in range(30):
        hub.ingest("t", f"c_total {i}\n", now=1000.0 + 10 * i)
    hub.stop()
    recs = [json.loads(l) for l in
            (tmp_path / TSDB_FILE).read_text().splitlines()]
    assert len(recs) <= 12, "compaction must bound the file"
    # compaction lags appends by up to one fill cycle: every record is
    # inside the horizon AS OF the newest compaction, which is at most
    # max_persist_lines appends behind the final scrape
    last_compact_t = 1000.0 + 10 * 25      # lines crest max at i=25
    assert all(r["t"] >= last_compact_t - 50.0 for r in recs), (
        "compaction keeps only records inside the retention horizon")


# --------------------------------------------------------------------------
# objective parsing + the good-under-threshold interpolation
# --------------------------------------------------------------------------

def test_slo_objectives_from_conf():
    conf = TonyConf({
        "tony.slo.avail.objective": "availability",
        "tony.slo.avail.target": 0.999,
        "tony.slo.avail.window-s": 120,
        "tony.slo.ttft.objective": "ttft-p99",
        "tony.slo.ttft.target": 0.99,
        "tony.slo.ttft.threshold-s": 0.25,
        "tony.slo.bogus.objective": "nonsense",       # skipped
        "tony.slo.nothresh.objective": "tpot-p99",    # skipped: no
        #                                               threshold-s
        "tony.slo.badtarget.objective": "availability",
        "tony.slo.badtarget.target": 1.5,             # skipped
    })
    slos = {s.name: s for s in slo_objectives_from_conf(conf)}
    assert set(slos) == {"avail", "ttft"}
    avail = slos["avail"]
    assert avail.target == 0.999 and avail.window_s == 120.0
    assert avail.pairs() == {
        "fast": (20.0, 2.0, 14.4), "slow": (120.0, 20.0, 6.0)}
    assert avail.windows() == [2.0, 20.0, 120.0]
    assert slos["ttft"].threshold_s == 0.25


def test_good_under_threshold_interpolation():
    buckets = {"0.1": 10.0, "1.0": 20.0, "+Inf": 20.0}
    # inside the (0.1, 1.0] bucket: linear share of its 10 counts
    assert good_under_threshold(buckets, 0.55) == pytest.approx(
        10.0 + 10.0 * (0.55 - 0.1) / 0.9)
    assert good_under_threshold(buckets, 0.05) == pytest.approx(5.0)
    # threshold past every finite bound: the honest floor (the +Inf
    # bucket's width is unknowable)
    assert good_under_threshold(buckets, 2.0) == 20.0


# --------------------------------------------------------------------------
# burn-rate window math: real rings, hand-computed ratios
# --------------------------------------------------------------------------

def test_burn_rate_windows_from_rings():
    """Availability burn over real ingested counters: 3600 healthy
    requests over the hour, the last 60 s all-failing. The W/60 window
    burns at 100x, W/6 at 10x, W at ~1.7x — so NEITHER pair fires
    (each needs BOTH its windows above threshold), which is exactly
    the multi-window recipe's point: one hot minute does not page."""
    hub = _hub()
    slo = SLObjective(name="avail", objective="availability",
                      target=0.99, window_s=3600.0)
    for t, req, fail in ((0.0, 0, 0), (3000.0, 3000, 0),
                         (3540.0, 3540, 0), (3600.0, 3600, 60)):
        hub.ingest("router", _avail_text(req, fail), now=t)
    eng = SLOEngine(hub, [slo], now_fn=lambda: 3600.0)
    assert eng.burn_rate(slo, 60.0) == pytest.approx(100.0)
    assert eng.burn_rate(slo, 600.0) == pytest.approx(10.0)
    assert eng.burn_rate(slo, 3600.0) == pytest.approx(
        (60.0 / 3600.0) / 0.01)
    snap = eng.evaluate()
    (s,) = snap["slos"]
    assert s["alerts"] == {"fast": False, "slow": False}, (
        "a single hot short window must not fire either pair")
    assert s["error_budget_remaining"] == pytest.approx(
        1.0 - (60.0 / 3600.0) / 0.01)


class _ScriptedHub:
    """Engine-facing stub: scripted (bad, total) per window — exact
    control over each pair's two windows."""

    def __init__(self):
        self.rates: dict[float, tuple[float, float]] = {}

    def window_increase(self, name, window_s, labels=None, target=None,
                        now=None):
        bad, total = self.rates.get(window_s, (0.0, 0.0))
        if name == _metrics.ROUTER_REQUESTS_TOTAL:
            return total
        if name == _metrics.ROUTER_FAILED_TOTAL:
            return bad
        return 0.0

    def window_buckets(self, family, window_s, now=None,
                       exclude_labels=("model",), target=None):
        return {}


def _scripted_engine(**kw):
    slo = SLObjective(name="avail", objective="availability",
                      target=0.99, window_s=3600.0)
    hub = _ScriptedHub()
    eng = SLOEngine(hub, [slo], now_fn=lambda: 0.0, **kw)
    return eng, hub, slo


def _set_burn(hub, window_s, burn, total=1000.0):
    # burn = (bad/total) / (1 - target), target 0.99 => bad = burn*10
    hub.rates[window_s] = (burn * (1.0 - 0.99) * total, total)


def test_alert_pairs_need_both_windows_and_clear_ticks():
    eng, hub, slo = _scripted_engine()
    # fast pair = (600, 60) @ 14.4; slow pair = (3600, 600) @ 6
    _set_burn(hub, 60.0, 100.0)
    _set_burn(hub, 600.0, 2.0)
    _set_burn(hub, 3600.0, 0.5)
    snap = eng.evaluate()
    assert snap["slos"][0]["alerts"] == {"fast": False, "slow": False}

    _set_burn(hub, 600.0, 20.0)          # both fast windows now hot
    snap = eng.evaluate()
    assert snap["slos"][0]["alerts"]["fast"] is True
    assert snap["slos"][0]["alerts"]["slow"] is False, (
        "slow pair needs the FULL window hot too")

    _set_burn(hub, 3600.0, 7.0)
    snap = eng.evaluate()
    assert snap["slos"][0]["alerts"] == {"fast": True, "slow": True}

    # recovery: the short windows drain first; clearing takes
    # CLEAR_TICKS consecutive clean evaluations (anti-flap)
    for w in (60.0, 600.0, 3600.0):
        _set_burn(hub, w, 0.0)
    for i in range(CLEAR_TICKS - 1):
        assert eng.evaluate()["slos"][0]["alerts"]["fast"] is True, (
            f"must stay firing through clear tick {i + 1}")
    snap = eng.evaluate()
    assert snap["slos"][0]["alerts"] == {"fast": False, "slow": False}
    states = [(h["severity"], h["state"]) for h in eng.history]
    assert states == [("fast", "firing"), ("slow", "firing"),
                      ("fast", "clear"), ("slow", "clear")]


# --------------------------------------------------------------------------
# alert journal replay: a recovered driver resumes, never re-fires
# --------------------------------------------------------------------------

def test_alert_journal_replay_across_recovery(tmp_path):
    """Driver #1 journals a fast-burn firing; driver #2 replays the
    journal, seeds the engine, and — with the incident still hot —
    keeps the alert FIRING with zero new transitions. The clear, when
    it comes, is journaled exactly once."""
    jpath = tmp_path / "driver.journal.jsonl"
    j1 = DriverJournal(jpath)
    j1.record("meta", app_id="slo_test", token="", session_id=1,
              rpc_port=1, driver_generation=1)
    eng1, hub1, _ = _scripted_engine(
        record_fn=lambda slo, sev, state, t: j1.record(
            "slo_alert", slo=slo, severity=sev, state=state, t=t))
    _set_burn(hub1, 60.0, 100.0)
    _set_burn(hub1, 600.0, 20.0)
    eng1.evaluate()
    assert eng1.alerts[("avail", "fast")] is True
    j1.close()
    raw = jpath.read_text()
    assert raw.count('"slo_alert"') == 1

    # --- driver death; recovery replays the journal
    state = load_state(jpath)
    assert state.slo_alerts == {
        "avail:fast": {"state": "firing",
                       "t": state.slo_alerts["avail:fast"]["t"]}}
    initial = {}
    for key, entry in state.slo_alerts.items():
        name, _, sev = key.rpartition(":")
        initial[(name, sev)] = entry.get("state") == "firing"

    j2 = DriverJournal(jpath)
    eng2, hub2, _ = _scripted_engine(
        record_fn=lambda slo, sev, state, t: j2.record(
            "slo_alert", slo=slo, severity=sev, state=state, t=t),
        initial_alerts=initial)
    _set_burn(hub2, 60.0, 100.0)          # incident still hot
    _set_burn(hub2, 600.0, 20.0)
    snap = eng2.evaluate()
    assert snap["slos"][0]["alerts"]["fast"] is True
    assert not eng2.history, "resumed alert must not re-transition"
    assert jpath.read_text().count('"slo_alert"') == 1, (
        "a resumed firing alert must not journal a duplicate firing")

    # the incident ends: exactly one journaled clear
    for w in (60.0, 600.0, 3600.0):
        _set_burn(hub2, w, 0.0)
    for _ in range(CLEAR_TICKS):
        eng2.evaluate()
    j2.close()
    recs = [json.loads(l) for l in jpath.read_text().splitlines()
            if '"slo_alert"' in l]
    assert [(r["severity"], r["state"]) for r in recs] == [
        ("fast", "firing"), ("fast", "clear")]
    # a THIRD replay sees the cleared state
    assert load_state(jpath).slo_alerts["avail:fast"]["state"] == "clear"


# --------------------------------------------------------------------------
# exposition conformance: every renderer round-trips the strict parser
# --------------------------------------------------------------------------

def test_slo_renderer_strict_roundtrip():
    eng, hub, _ = _scripted_engine()
    _set_burn(hub, 60.0, 100.0)
    _set_burn(hub, 600.0, 20.0)
    eng.evaluate()
    r = PromRenderer()
    eng.render_into(r)
    fams = parse_prom_text(r.render(), strict=True)
    assert set(fams) == {_metrics.DRIVER_SLO_BURN_RATE,
                         _metrics.DRIVER_SLO_ERROR_BUDGET_REMAINING,
                         _metrics.DRIVER_SLO_ALERTS_FIRING}
    burn = fams[_metrics.DRIVER_SLO_BURN_RATE]
    assert burn.values(slo="avail", window_s="60") == [
        pytest.approx(100.0)]
    firing = fams[_metrics.DRIVER_SLO_ALERTS_FIRING]
    assert firing.values(slo="avail", severity="fast") == [1.0]
    assert firing.values(slo="avail", severity="slow") == [0.0]


def test_router_exposition_strict_roundtrip():
    from tony_tpu.router import FleetRouter

    router = FleetRouter([("r0", "127.0.0.1", 1)], seed=0)
    fams = parse_prom_text(router.prometheus_metrics(), strict=True)
    assert _metrics.ROUTER_REPLICAS_LIVE in fams


def test_portal_exposition_and_slo_route(tmp_path):
    """The portal round-trips its own /metrics through the strict
    parser, and /slo/<app_id> serves the offline dashboard (JSON and
    HTML) replayed from the job's persisted TSDB + journal."""
    from tony_tpu.portal.server import serve_portal

    app_id = "slo_app"
    staging = tmp_path / "staging" / app_id
    staging.mkdir(parents=True)
    (staging / "tony-final.json").write_text(json.dumps({
        "tony.slo.avail.objective": "availability",
        "tony.slo.avail.target": 0.99,
        "tony.slo.avail.window-s": 3600,
    }))
    hub = MetricsHub(persist_dir=staging, retention_s=1e9)
    for t, req, fail in ((0.0, 0, 0), (3000.0, 3000, 0),
                         (3600.0, 3600, 60)):
        hub.ingest("router", _avail_text(req, fail), now=t)
    hub.stop()
    j = DriverJournal(staging / "driver.journal.jsonl")
    j.record("meta", app_id=app_id, token="", session_id=1,
             rpc_port=1, driver_generation=1)
    j.record("slo_alert", slo="avail", severity="fast",
             state="firing", t=3590.0)
    j.close()

    conf = TonyConf({
        "tony.staging.dir": str(tmp_path / "staging"),
        "tony.history.intermediate": str(tmp_path / "hist" / "inter"),
        "tony.history.finished": str(tmp_path / "hist" / "fin"),
    })
    server = serve_portal(conf, port=0, block=False)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        def get(path, accept="application/json"):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}",
                headers={"Accept": accept})
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.status, resp.headers, resp.read().decode()

        # portal self-exposition is strictly conformant
        _, _, text = get("/metrics", accept="text/plain")
        fams = parse_prom_text(text, strict=True)
        assert "portal_http_requests_total" in fams

        # JSON dashboard: evaluated at the LAST tsdb timestamp, alert
        # state seeded from the journal
        status, headers, body = get(f"/slo/{app_id}")
        assert status == 200
        assert headers["Content-Type"].startswith("application/json")
        data = json.loads(body)
        assert data["t"] == 3600.0
        (s,) = data["eval"]["slos"]
        assert s["error_budget_remaining"] == pytest.approx(
            1.0 - (60.0 / 3600.0) / 0.01)
        assert len(s["spark_burn"]) == len(s["spark_budget"]) == 32
        assert {(a["slo"], a["severity"]): a["firing"]
                for a in data["alerts"]}[("avail", "fast")] is True, (
            "journal-seeded alert state must surface on the dashboard")

        # HTML render carries the dashboard elements
        _, _, html_body = get(f"/slo/{app_id}", accept="text/html")
        assert "error budget remaining" in html_body
        assert "avail" in html_body and "FIRING" in html_body

        # unknown job 404s as JSON null
        try:
            get("/slo/not_a_job")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        server.shutdown()
        server.server_close()


# --------------------------------------------------------------------------
# driver integration e2e: hub scrape loop -> engine -> /slo + /metrics
# --------------------------------------------------------------------------

class _MetricsStub:
    """A replica endpoint under test control: /stats + a slow-TTFT
    /metrics histogram the hub scrapes (the test_autoscale
    _StatsServer, minus the autoscaler knobs)."""

    def __init__(self):
        self.slow = 0           # cumulative observations in (1, +Inf]
        outer = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/stats":
                    body = json.dumps({"queued": 0, "active": 0}).encode()
                    ctype = "application/json"
                elif self.path == "/metrics":
                    s = outer.slow
                    body = (
                        f'serving_ttft_seconds_bucket{{le="0.1"}} 0\n'
                        f'serving_ttft_seconds_bucket{{le="1.0"}} 0\n'
                        f'serving_ttft_seconds_bucket{{le="+Inf"}} {s}\n'
                    ).encode()
                    ctype = "text/plain"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.port = self.httpd.server_address[1]

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _wait(pred, timeout=20, every=0.05, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(every)
    raise AssertionError(f"timed out waiting for {msg}")


def test_driver_hub_slo_e2e(tmp_job_dirs, tmp_path):
    """A driver with a declared TTFT SLO: the hub's jittered loop
    scrapes the replica's /metrics and the driver's own renderer,
    the engine evaluates each round, an all-slow burst fires the fast
    pair (journaled), the driver /slo HTTP route and the driver_slo_*/
    driver_metricshub_* exposition families surface it, the unified
    scrape-failure counter renders, the TSDB file persists — and the
    whole driver payload round-trips the strict parser."""
    import tony_tpu.constants as c
    from tony_tpu.cluster.provisioner import ContainerHandle, Provisioner
    from tony_tpu.driver import Driver
    from tony_tpu.rpc import RpcClient

    stub = _MetricsStub()

    class Prov(Provisioner):
        def launch(self, spec, index, env, log_dir):
            handle = ContainerHandle(
                container_id=f"stub_{index}", host="127.0.0.1",
                role=spec.name, index=index)
            handle.extra["stop"] = threading.Event()

            def run():
                rpc = RpcClient(env[c.ENV_DRIVER_HOST],
                                int(env[c.ENV_DRIVER_PORT]),
                                token=env.get(c.ENV_TOKEN, ""),
                                role="executor")
                rpc.call("register_worker", task_id="replica:0",
                         host="127.0.0.1", port=23900)
                while rpc.call("get_cluster_spec",
                               task_id="replica:0") is None:
                    time.sleep(0.03)
                rpc.call("publish_ports", task_id="replica:0",
                         ports={"serve_port": stub.port})
                handle.extra["stop"].wait(60)
                rpc.call("register_execution_result",
                         task_id="replica:0", exit_code=0)
                rpc.close()
                if self.on_completion:
                    self.on_completion(handle, 0)

            threading.Thread(target=run, daemon=True).start()
            return handle

        def stop_container(self, handle):
            handle.extra["stop"].set()

        def stop_all(self):
            pass

    conf = TonyConf({
        "tony.staging.dir": tmp_job_dirs["staging"],
        "tony.history.location": tmp_job_dirs["history"],
        "tony.history.intermediate": tmp_job_dirs["history"] + "/intermediate",
        "tony.history.finished": tmp_job_dirs["history"] + "/finished",
        "tony.am.monitor-interval-ms": 50,
        "tony.task.registration-poll-interval-ms": 50,
        "tony.replica.instances": 1,
        "tony.replica.command": "stub",
        "tony.application.framework": "serving",
        # W=60 -> fast pair (10s, 1s) @ 14.4x: an all-slow burst fires
        # within a couple of 0.2s scrape rounds
        "tony.slo.ttft.objective": "ttft-p99",
        "tony.slo.ttft.target": 0.99,
        "tony.slo.ttft.window-s": 60,
        "tony.slo.ttft.threshold-s": 0.25,
        "tony.slo.scrape-interval-s": 0.2,
    })
    job_dir = tmp_path / "job_slo"
    job_dir.mkdir(exist_ok=True)
    conf.write_final(job_dir)
    driver = Driver(conf, app_id="slo_e2e", job_dir=str(job_dir),
                    token="slo-secret", provisioner=Prov())
    driver.client_signal.set()
    t = threading.Thread(target=driver.run, daemon=True)
    t.start()
    try:
        _wait(lambda: driver._slo_engine is not None
              and driver._slo_engine.last_eval is not None,
              msg="first SLO evaluation")
        port = driver.metrics_port
        assert port, "driver metrics server must be up"

        def slo_snapshot():
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/slo", timeout=10) as r:
                return json.loads(r.read())

        snap = slo_snapshot()
        assert snap["evaluated"] and snap["eval"]["slos"], snap
        assert snap["eval"]["slos"][0]["alerts"] == {
            "fast": False, "slow": False}, (
            "a healthy warm-up must not fire")

        # an all-slow burst, fed over several scrape rounds so both
        # fast-pair windows see an increase
        def burst_then_firing():
            stub.slow += 50
            return any(a["severity"] == "fast" and a["firing"]
                       for a in slo_snapshot()["alerts"])
        _wait(burst_then_firing, timeout=30, every=0.2,
              msg="fast-burn alert")

        # the transition was journaled (recovery's seed data)
        state = load_state(job_dir / "driver.journal.jsonl")
        assert state.slo_alerts.get("ttft:fast", {}).get(
            "state") == "firing"

        # exposition: strict round-trip + every new family present
        hub = driver._metrics_hub
        hub.failures["ghost"] = 1       # a failed target must surface
        text = driver.render_metrics()
        fams = parse_prom_text(text, strict=True)
        firing = fams[_metrics.DRIVER_SLO_ALERTS_FIRING]
        assert firing.values(slo="ttft", severity="fast") == [1.0]
        assert _metrics.DRIVER_SLO_BURN_RATE in fams
        assert _metrics.DRIVER_SLO_ERROR_BUDGET_REMAINING in fams
        assert fams[_metrics.DRIVER_METRICSHUB_TARGETS].values()[0] >= 2, (
            "hub must scrape the replica AND self-collect the driver")
        assert fams[_metrics.DRIVER_METRICSHUB_SCRAPES_TOTAL].values()[0] > 0
        scrape_fail = fams[_metrics.DRIVER_AUTOSCALE_SCRAPE_FAILURES_TOTAL]
        assert scrape_fail.values(target="ghost") == [1.0]

        # the TSDB persisted under the job dir (recovery's replay data)
        assert (job_dir / TSDB_FILE).exists()
    finally:
        driver._stop_requested.set()
        for h in list(driver._handles.values()):
            h.extra["stop"].set()
        t.join(timeout=20)
        stub.close()
